"""cituslint engine: parse the package once, index it, run rules.

The engine is rule-agnostic.  It owns:

- ``ModuleIndex``   — one parsed module: AST (with parent links),
  import-alias resolution (``import time as _t`` → ``_t.time()``
  resolves to ``time.time``), and the suppression-pragma table.
- ``PackageIndex``  — every ``*.py`` under one package directory.
- ``Rule``          — the base class rules subclass; ``run_lint``
  instantiates each rule, collects diagnostics, applies suppressions,
  and reports unjustified/unknown pragmas as diagnostics themselves.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Iterable, Optional

#: ``# lint: disable=ID1,ID2 -- why this is safe``
_PRAGMA = re.compile(
    r"#\s*lint:\s*disable=([A-Za-z0-9_, ]+?)(?:\s*--\s*(.*\S))?\s*$")


@dataclass(frozen=True, order=True)
class Diagnostic:
    """One finding: ``path:line rule-id message``."""

    path: str       # display path (package dir name + in-package path)
    line: int
    rule_id: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line} {self.rule_id} {self.message}"


@dataclass(frozen=True)
class Suppression:
    line: int             # line the pragma comment sits on
    rule_ids: tuple       # ids it names
    justification: str    # "" when missing (→ SUP01)
    standalone: bool      # own-line pragma: also covers the next line


class ModuleIndex:
    """One module's AST plus the derived lookup structures every rule
    needs: parent links, import aliases, and suppression pragmas."""

    def __init__(self, pkg_root: str, path: str, display_prefix: str):
        self.path = path
        self.rel = os.path.relpath(path, pkg_root).replace(os.sep, "/")
        self.display = f"{display_prefix}/{self.rel}"
        with open(path, encoding="utf-8") as fh:
            self.source = fh.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=path)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._lint_parent = parent  # type: ignore[attr-defined]
        self._index_imports()
        self._index_pragmas()

    # ---- imports -------------------------------------------------------
    def _index_imports(self) -> None:
        #: local name -> imported dotted module ("_t" -> "time")
        self.aliases: dict[str, str] = {}
        #: local name -> "module.member" ("jit" -> "jax.jit")
        self.members: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        self.aliases[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.members[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of an expression like ``_t.time`` or
        ``jit`` with import aliases resolved; None when the chain is
        not a plain Name/Attribute path."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        root, rest = parts[0], parts[1:]
        if root in self.members:
            return ".".join([self.members[root]] + rest)
        if root in self.aliases:
            return ".".join([self.aliases[root]] + rest)
        return ".".join(parts)

    # ---- suppressions --------------------------------------------------
    def _index_pragmas(self) -> None:
        self.pragmas: list[Suppression] = []
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            comments = [t for t in tokens
                        if t.type == tokenize.COMMENT]
        except tokenize.TokenizeError:  # pragma: no cover - parse ok'd
            comments = []
        for tok in comments:
            m = _PRAGMA.search(tok.string)
            if not m:
                continue
            ids = tuple(s.strip() for s in m.group(1).split(",")
                        if s.strip())
            line = tok.start[0]
            prefix = self.lines[line - 1][:tok.start[1]]
            self.pragmas.append(Suppression(
                line=line, rule_ids=ids,
                justification=(m.group(2) or "").strip(),
                standalone=not prefix.strip()))

    def suppressed(self, line: int, rule_id: str) -> bool:
        """True when a JUSTIFIED pragma covers (line, rule_id): the
        pragma trails the line itself, or sits alone on the line above."""
        for p in self.pragmas:
            if rule_id not in p.rule_ids or not p.justification:
                continue
            if p.line == line or (p.standalone and p.line == line - 1):
                return True
        return False


class PackageIndex:
    """Every module of one package, parsed once and shared by rules."""

    def __init__(self, package_path: str):
        self.root = os.path.abspath(package_path)
        if not os.path.isdir(self.root):
            raise FileNotFoundError(f"not a package directory: "
                                    f"{package_path}")
        self.display_prefix = os.path.basename(self.root.rstrip("/"))
        self.modules: list[ModuleIndex] = []
        self.by_rel: dict[str, ModuleIndex] = {}
        self.errors: list[Diagnostic] = []
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                try:
                    mod = ModuleIndex(self.root, path, self.display_prefix)
                except SyntaxError as e:
                    rel = os.path.relpath(path, self.root)
                    self.errors.append(Diagnostic(
                        f"{self.display_prefix}/{rel}", e.lineno or 1,
                        "PARSE", f"syntax error: {e.msg}"))
                    continue
                self.modules.append(mod)
                self.by_rel[mod.rel] = mod
        self._cache: dict[str, object] = {}

    def cached(self, key: str, build):
        """Memo slot shared across rules (e.g. the parsed COUNTERS
        list) so each cross-module fact is derived once per run."""
        if key not in self._cache:
            self._cache[key] = build()
        return self._cache[key]


class Rule:
    """Base rule.  Subclasses set ``id``/``name``/``doc`` and override
    one or both hooks; ``diag`` builds a Diagnostic with the rule id
    filled in."""

    id = ""
    name = ""

    def check_module(self, mod: ModuleIndex,
                     pkg: PackageIndex) -> Iterable[Diagnostic]:
        return ()

    def check_package(self, pkg: PackageIndex) -> Iterable[Diagnostic]:
        return ()

    def diag(self, mod: ModuleIndex, line: int, message: str) -> Diagnostic:
        return Diagnostic(mod.display, line, self.id, message)


def _pragma_diags(pkg: PackageIndex, known_ids: set) -> list[Diagnostic]:
    """Pragmas are linted too: a suppression without a justification
    (SUP01) or naming an unknown rule id (SUP02) is a finding — silent
    or typo'd opt-outs must not pass review."""
    out = []
    for mod in pkg.modules:
        for p in mod.pragmas:
            if not p.justification:
                out.append(Diagnostic(
                    mod.display, p.line, "SUP01",
                    "lint suppression needs a justification: "
                    "'# lint: disable=ID -- why this is safe'"))
            for rid in p.rule_ids:
                if rid not in known_ids:
                    out.append(Diagnostic(
                        mod.display, p.line, "SUP02",
                        f"suppression names unknown rule id {rid!r}"))
    return out


def run_lint(package_path: str, select: Optional[set] = None,
             rules: Optional[list] = None) -> list[Diagnostic]:
    """Lint one package directory; returns surviving diagnostics
    sorted by (path, line).  ``select`` restricts to a set of rule
    ids; ``rules`` substitutes the rule-class registry (tests)."""
    from tools.cituslint.rules import ALL_RULES
    pkg = PackageIndex(package_path)
    rule_classes = list(rules if rules is not None else ALL_RULES)
    known_ids = {rc.id for rc in rule_classes} | {"SUP01", "SUP02", "PARSE"}
    diags: list[Diagnostic] = list(pkg.errors)
    for rc in rule_classes:
        if select is not None and rc.id not in select:
            continue
        rule = rc()
        for mod in pkg.modules:
            diags.extend(rule.check_module(mod, pkg))
        diags.extend(rule.check_package(pkg))
    kept = []
    for d in diags:
        mod = _module_for(pkg, d.path)
        if mod is not None and mod.suppressed(d.line, d.rule_id):
            continue
        kept.append(d)
    if select is None or select & {"SUP01", "SUP02"}:
        kept.extend(_pragma_diags(pkg, known_ids))
    return sorted(set(kept))


def _module_for(pkg: PackageIndex, display: str) -> Optional[ModuleIndex]:
    prefix = pkg.display_prefix + "/"
    if display.startswith(prefix):
        return pkg.by_rel.get(display[len(prefix):])
    return None
