"""citussan: whole-program concurrency rules (LOCK02/BLK01/JIT01).

The per-attribute discipline rule (LOCK01) answers "is this shared
attribute always written under its lock"; these three answer the next
questions up the stack:

========  ==============================================================
LOCK02    lock acquisition ORDER: build the static lock-order graph —
          an edge A→B for every ``with lockA:`` scope that acquires
          lockB, resolved through same-class method calls (including
          the ``*_locked`` helper convention) and same-module function
          calls — and flag every cycle as a potential deadlock, plus
          re-acquisition of a non-reentrant ``threading.Lock``.
BLK01     blocking operations (socket recv/sendall/connect/accept,
          RpcClient ``call_binary*``, ``time.sleep``, no-timeout
          ``Thread.join``/``Queue.get``/``Future.result``,
          ``subprocess.*``, ``open()`` file I/O) executed while a lock
          is held, or from any function reachable on the
          ``RpcEventLoop`` loop thread (seeded from ``_run`` and every
          ``done_cb=`` passed to ``submit``); a lock acquire on the
          loop thread is flagged too — a contended acquire there stalls
          every in-flight RPC behind one caller.
JIT01     jit purity: a function handed to ``jit_compile``/``jax.vmap``
          (the doors ``kernel_cache.get_kernel`` builds flow through)
          must not bump counters, read clocks, take locks, or do
          tracer-visible I/O — those run ONCE at trace time and
          silently vanish on every cache hit.
========  ==============================================================

All three are static over-approximations with the usual escape hatch:
a justified ``# lint: disable=ID -- why this is safe`` pragma.  The
runtime half of citussan (``citus_tpu/utils/sanitizer.py``,
``CITUS_SANITIZE=1``) checks the same properties on the schedules the
test suite actually executes.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.cituslint.engine import ModuleIndex, PackageIndex, Rule

_LOCK_FACTORIES = {"threading.Lock", "threading.RLock",
                   "threading.Condition"}


def _self_attr(node: ast.AST, self_name: str) -> Optional[str]:
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == self_name:
        return node.attr
    return None


def _class_locks(mod: ModuleIndex, cls: ast.ClassDef) -> dict:
    """``{attr: factory}`` for every ``self.<attr> = threading.Lock()``
    (or RLock/Condition) assignment in ``__init__``."""
    out: dict = {}
    for meth in cls.body:
        if not isinstance(meth, ast.FunctionDef) or meth.name != "__init__":
            continue
        args = meth.args.posonlyargs + meth.args.args
        self_name = args[0].arg if args else "self"
        for node in ast.walk(meth):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                factory = mod.dotted(node.value.func)
                if factory in _LOCK_FACTORIES:
                    for t in node.targets:
                        attr = _self_attr(t, self_name)
                        if attr is not None:
                            out[attr] = factory
    return out


def _module_locks(mod: ModuleIndex) -> dict:
    """``{name: factory}`` for module-level ``NAME = threading.Lock()``
    (or RLock/Condition) assignments."""
    out: dict = {}
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) \
                and isinstance(stmt.value, ast.Call):
            factory = mod.dotted(stmt.value.func)
            if factory in _LOCK_FACTORIES:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = factory
    return out


def _module_functions(mod: ModuleIndex) -> dict:
    return {stmt.name: stmt for stmt in mod.tree.body
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _class_methods(cls: ast.ClassDef) -> dict:
    return {stmt.name: stmt for stmt in cls.body
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _fn_self_name(fn) -> Optional[str]:
    args = fn.args.posonlyargs + fn.args.args
    return args[0].arg if args else None


def _iter_body_children(node: ast.AST):
    """Children of ``node`` EXCLUDING nested function/lambda bodies —
    code inside a nested def runs on its own schedule, not under the
    locks lexically around its definition."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child


class _FnScope:
    """One analyzable function: its AST plus how to resolve the calls
    it makes (same-class methods through ``self``, same-module
    top-level functions by name)."""

    __slots__ = ("mod", "fn", "cls", "methods", "funcs", "self_name")

    def __init__(self, mod: ModuleIndex, fn, cls: Optional[ast.ClassDef],
                 methods: dict, funcs: dict):
        self.mod = mod
        self.fn = fn
        self.cls = cls
        self.methods = methods
        self.funcs = funcs
        self.self_name = _fn_self_name(fn) if cls is not None else None

    def key(self):
        return (self.mod.rel, id(self.fn))

    def resolve_call(self, call: ast.Call) -> Optional["_FnScope"]:
        f = call.func
        if isinstance(f, ast.Attribute) and self.self_name \
                and isinstance(f.value, ast.Name) \
                and f.value.id == self.self_name \
                and f.attr in self.methods:
            return _FnScope(self.mod, self.methods[f.attr], self.cls,
                            self.methods, self.funcs)
        if isinstance(f, ast.Name) and f.id in self.funcs:
            return _FnScope(self.mod, self.funcs[f.id], None, {},
                            self.funcs)
        return None

    def lock_node(self, expr: ast.AST, class_locks: dict,
                  mod_locks: dict) -> Optional[str]:
        """Graph-node id a ``with <expr>:`` item acquires, or None."""
        if self.self_name and self.cls is not None:
            attr = _self_attr(expr, self.self_name)
            if attr in class_locks:
                return f"{self.mod.rel}:{self.cls.name}.{attr}"
        if isinstance(expr, ast.Name) and expr.id in mod_locks:
            return f"{self.mod.rel}:{expr.id}"
        return None


def _iter_scopes(mod: ModuleIndex) -> Iterable[tuple]:
    """Yield (scope, class_locks, mod_locks) for every top-level
    function and every method of every class in ``mod``."""
    mod_locks = _module_locks(mod)
    funcs = _module_functions(mod)
    for fn in funcs.values():
        yield (_FnScope(mod, fn, None, {}, funcs), {}, mod_locks)
    for cls in mod.tree.body:
        if not isinstance(cls, ast.ClassDef):
            continue
        class_locks = _class_locks(mod, cls)
        methods = _class_methods(cls)
        for meth in methods.values():
            yield (_FnScope(mod, meth, cls, methods, funcs),
                   class_locks, mod_locks)


# --------------------------------------------------------------- LOCK02


class LockOrderRule(Rule):
    """Static lock-acquisition-order graph: an edge A→B whenever code
    holding A acquires B (lexically nested ``with`` blocks, resolved
    through same-class and same-module calls, ``*_locked`` helpers
    included).  Any cycle in the graph is a potential deadlock — two
    threads entering it from different edges park on each other
    forever.  Re-acquiring a non-reentrant ``threading.Lock`` already
    held on the same path is a guaranteed self-deadlock and is flagged
    directly."""

    id = "LOCK02"
    name = "lock acquisition order"

    def check_package(self, pkg):
        edges: dict = {}   # (a, b) -> (mod, line)
        kinds: dict = {}   # node id -> factory dotted name
        for mod in pkg.modules:
            for scope, class_locks, mod_locks in _iter_scopes(mod):
                for node, factory in class_locks.items():
                    kinds.setdefault(
                        f"{mod.rel}:{scope.cls.name}.{node}", factory)
                for name, factory in mod_locks.items():
                    kinds.setdefault(f"{mod.rel}:{name}", factory)
                self._walk(scope, class_locks, mod_locks, (),
                           frozenset([scope.key()]), edges, 0)
        for (a, b), (mod, line) in sorted(
                edges.items(), key=lambda kv: (kv[1][0].rel, kv[1][1])):
            if a == b and kinds.get(a) == "threading.Lock":
                yield self.diag(
                    mod, line,
                    f"re-acquires non-reentrant lock {a} while already "
                    f"holding it on this path — guaranteed self-deadlock")
        graph: dict = {}
        for (a, b) in edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
        for cycle in _find_cycles(graph):
            sites = [(edges[(cycle[i], cycle[(i + 1) % len(cycle)])], i)
                     for i in range(len(cycle))]
            (mod, line), _i = min(
                sites, key=lambda s: (s[0][0].rel, s[0][1]))
            path = " -> ".join(cycle + [cycle[0]])
            where = "; ".join(
                f"{cycle[i]}->{cycle[(i + 1) % len(cycle)]} at "
                f"{m.rel}:{ln}" for (m, ln), i in sites)
            yield self.diag(
                mod, line,
                f"lock-order cycle {path} (potential deadlock): {where}")

    def _walk(self, scope: _FnScope, class_locks: dict, mod_locks: dict,
              held: tuple, stack: frozenset, edges: dict,
              depth: int) -> None:
        if depth > 12:
            return

        def visit(node, held):
            if isinstance(node, ast.With):
                acquired = []
                for item in node.items:
                    ln = scope.lock_node(item.context_expr, class_locks,
                                         mod_locks)
                    if ln is not None:
                        for h in held:
                            edges.setdefault(
                                (h, ln), (scope.mod, node.lineno))
                        acquired.append(ln)
                inner = held + tuple(acquired)
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "acquire":
                    ln = scope.lock_node(f.value, class_locks, mod_locks)
                    if ln is not None:
                        for h in held:
                            edges.setdefault(
                                (h, ln), (scope.mod, node.lineno))
                callee = scope.resolve_call(node)
                if callee is not None and callee.key() not in stack \
                        and held:
                    self._walk(callee, class_locks if callee.cls else {},
                               mod_locks, held,
                               stack | {callee.key()}, edges, depth + 1)
            for child in _iter_body_children(node):
                visit(child, held)

        for stmt in scope.fn.body:
            visit(stmt, held)


def _find_cycles(graph: dict) -> list:
    """Deterministic list of elementary cycles, one per strongly
    connected component that contains one (node lists, rotation-
    normalized to start at the smallest node)."""
    index: dict = {}
    low: dict = {}
    on_stack: dict = {}
    stack: list = []
    sccs: list = []
    counter = [0]
    nodes = sorted(set(graph) | {b for bs in graph.values() for b in bs})

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack[v] = True
        for w in sorted(graph.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif on_stack.get(w):
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack[w] = False
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(sorted(comp))

    for v in nodes:
        if v not in index:
            strongconnect(v)

    cycles = []
    for comp in sccs:
        comp_set = set(comp)
        start = comp[0]
        # one representative cycle: DFS inside the SCC back to start
        path = [start]
        seen = {start}

        def dfs(v):
            for w in sorted(graph.get(v, ())):
                if w == start and len(path) > 1:
                    return True
                if w in comp_set and w not in seen:
                    seen.add(w)
                    path.append(w)
                    if dfs(w):
                        return True
                    path.pop()
            return False

        if dfs(start):
            cycles.append(list(path))
    return cycles


# ---------------------------------------------------------------- BLK01

#: dotted calls that block the calling thread outright
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep()",
    "socket.create_connection": "socket connect",
}

#: attribute-call names that block (receiver-typed; the resolver cannot
#: see the receiver's type, so these are name-matched — precise enough
#: because the names are idiomatically unambiguous in this tree)
_BLOCKING_METHODS = {
    "sendall": "socket sendall",
    "recv": "socket recv",
    "connect": "socket connect",
    "accept": "socket accept",
    "call_binary": "a synchronous RPC round-trip",
    "call_binary_pooled": "a synchronous RPC round-trip",
}


#: locks whose PURPOSE is serializing a durable-log / on-disk append:
#: holding them across the guarded file I/O is the design (WAL
#: discipline — the write and the in-memory state it mirrors must
#: commit atomically), so ``open()`` under them is exempt from BLK01.
#: Sleeps, RPCs, joins, and subprocesses under them are still flagged.
#: Reviewed like CONF01's tables: adding a lock here is a design
#: decision, not a suppression.
IO_SERIALIZING_LOCKS = {
    # catalog document store: mutate-in-memory + rewrite-file is one
    # critical section; a torn pair would desync every session
    "catalog/catalog.py:Catalog._lock",
    # CDC stream appends: LSN assignment and the segment append commit
    # together (exactly-once replay depends on it)
    "cdc.py:ChangeDataCapture._mu",
    # 2PC outcome store and failover authority file: the decision and
    # its durable record must be indivisible
    "net/control_plane.py:ControlPlane._lock",
    "net/control_plane.py:ControlPlane._failover_mu",
    # flight-recorder segment writes: _io_mu exists solely to order
    # rotate-vs-append; samples are small JSON lines
    "observability/flight_recorder.py:FlightRecorder._io_mu",
    # background-job records: claim/finish state flips pair with their
    # on-disk store (crash adoption replays from it)
    "services/background_jobs.py:BackgroundJobRunner._lock",
    # the transaction WAL itself
    "transaction/manager.py:TransactionLog._lock",
    # causal-clock persistence: the tick and its floor file pair up
    "utils/clock.py:CausalClock._mu",
}


def _blocking_desc(mod: ModuleIndex, call: ast.Call) -> Optional[str]:
    """Human description when ``call`` is a blocking operation, else
    None.  ``join()``/``get()``/``result()`` only count with zero
    positional args and no timeout bound (``",".join(xs)`` and
    ``d.get(k)`` take args; a bounded wait is a decision already
    made)."""
    dotted = mod.dotted(call.func)
    if dotted in _BLOCKING_DOTTED:
        return _BLOCKING_DOTTED[dotted]
    if dotted is not None and dotted.split(".")[0] == "subprocess":
        return f"{dotted}() subprocess"
    if isinstance(call.func, ast.Name) and call.func.id == "open":
        return "file I/O (open)"
    if not isinstance(call.func, ast.Attribute):
        return None
    name = call.func.attr
    if name in _BLOCKING_METHODS:
        return _BLOCKING_METHODS[name]
    if name in ("join", "get", "result") and not call.args:
        kwargs = {kw.arg for kw in call.keywords}
        if "timeout" not in kwargs and "block" not in kwargs:
            return {"join": "unbounded Thread.join()",
                    "get": "unbounded Queue.get()",
                    "result": "unbounded Future.result()"}[name]
    return None


def _fn_blockers(scope: _FnScope, memo: dict, stack: frozenset) -> list:
    """Transitive blocking operations reachable from ``scope.fn``
    through resolvable calls: ``[(desc, line)]`` (lines are in the
    function that directly performs the operation)."""
    key = scope.key()
    if key in memo:
        return memo[key]
    out: list = []

    def visit(node):
        if isinstance(node, ast.Call):
            desc = _blocking_desc(scope.mod, node)
            if desc is not None:
                out.append((desc, node.lineno))
            callee = scope.resolve_call(node)
            if callee is not None and callee.key() not in stack:
                for desc, _line in _fn_blockers(
                        callee, memo, stack | {callee.key()}):
                    out.append((f"{desc} (via "
                                f"{callee.fn.name}())", node.lineno))
        for child in _iter_body_children(node):
            visit(child)

    for stmt in scope.fn.body:
        visit(stmt)
    memo[key] = out
    return out


class BlockingCallRule(Rule):
    """Blocking operations in the two places they can wedge the whole
    process: (a) while a ``threading`` lock is held — every other
    thread needing that lock now waits on a peer's network/disk/sleep,
    the classic convoy that turns one slow RPC into a stalled
    coordinator; (b) in any function that runs on the ``RpcEventLoop``
    loop thread (``_run`` and its callees, plus every ``done_cb``
    handed to ``submit``) — the loop multiplexes ALL in-flight RPCs,
    so one blocking call there stops the entire data-plane fan-out.
    Lock acquires on the loop thread are flagged for the same reason:
    a contended acquire blocks the loop behind whoever holds it."""

    id = "BLK01"
    name = "blocking call under lock / on event-loop thread"

    def check_module(self, mod, pkg):
        memo: dict = {}
        for scope, class_locks, mod_locks in _iter_scopes(mod):
            yield from self._check_under_lock(scope, class_locks,
                                              mod_locks, memo)

    def check_package(self, pkg):
        loop_fns = self._loop_thread_scopes(pkg)
        memo: dict = {}
        for scope, class_locks, mod_locks in loop_fns:
            for node in ast.walk(scope.fn):
                if isinstance(node, ast.Call):
                    desc = _blocking_desc(scope.mod, node)
                    if desc is not None:
                        yield self.diag(
                            scope.mod, node.lineno,
                            f"{scope.fn.name}() runs on the RpcEventLoop "
                            f"thread but performs {desc} — a block here "
                            f"stalls every in-flight RPC")
                    callee = scope.resolve_call(node)
                    if callee is not None \
                            and callee.key() != scope.key():
                        for desc, line in _fn_blockers(
                                callee, memo,
                                frozenset([callee.key()])):
                            yield self.diag(
                                scope.mod, node.lineno,
                                f"{scope.fn.name}() runs on the "
                                f"RpcEventLoop thread but calls "
                                f"{callee.fn.name}() which performs "
                                f"{desc} (line {line})")
                if isinstance(node, ast.With):
                    for item in node.items:
                        ln = scope.lock_node(item.context_expr,
                                             class_locks, mod_locks)
                        if ln is not None:
                            yield self.diag(
                                scope.mod, node.lineno,
                                f"{scope.fn.name}() acquires {ln} on the "
                                f"RpcEventLoop thread — a contended "
                                f"acquire stalls every in-flight RPC")

    # ---- (a) blocking while a lock is held -----------------------------

    def _check_under_lock(self, scope: _FnScope, class_locks: dict,
                          mod_locks: dict, memo: dict):
        base_held = scope.fn.name.endswith("_locked")
        diags = []
        # a *_locked helper's (unnamed) held lock is I/O-serializing
        # when every lock its class owns is in the table
        conv_io_ok = bool(class_locks) and scope.cls is not None and all(
            f"{scope.mod.rel}:{scope.cls.name}.{attr}"
            in IO_SERIALIZING_LOCKS for attr in class_locks)

        def io_exempt(lock_name, desc) -> bool:
            if not desc.startswith("file I/O"):
                return False
            if lock_name in IO_SERIALIZING_LOCKS:
                return True
            return lock_name is None and conv_io_ok

        def visit(node, held):
            if isinstance(node, ast.With):
                acquired = [scope.lock_node(item.context_expr,
                                            class_locks, mod_locks)
                            for item in node.items]
                acquired = [a for a in acquired if a is not None]
                if acquired:
                    inner = acquired[0]
                else:
                    inner = held
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.Call) and held:
                lock_name = held if isinstance(held, str) else None
                lock_desc = lock_name or "a lock (*_locked convention)"
                desc = _blocking_desc(scope.mod, node)
                if desc is not None and not io_exempt(lock_name, desc):
                    diags.append(self.diag(
                        scope.mod, node.lineno,
                        f"{scope.fn.name}() performs {desc} while "
                        f"holding {lock_desc}"))
                callee = scope.resolve_call(node)
                if callee is not None and callee.key() != scope.key():
                    for desc, line in _fn_blockers(
                            callee, memo, frozenset([callee.key()])):
                        if io_exempt(lock_name, desc):
                            continue
                        diags.append(self.diag(
                            scope.mod, node.lineno,
                            f"{scope.fn.name}() calls "
                            f"{callee.fn.name}() which performs {desc} "
                            f"(line {line}) while holding {lock_desc}"))
            for child in _iter_body_children(node):
                visit(child, held)

        for stmt in scope.fn.body:
            visit(stmt, True if base_held else False)
        return diags

    # ---- (b) the event-loop thread's reachable set ---------------------

    def _loop_thread_scopes(self, pkg: PackageIndex) -> list:
        """Scopes that execute on the RpcEventLoop thread: ``_run`` and
        its same-class callees (transitively), plus every function
        passed as ``done_cb=`` to a ``.submit(...)`` call anywhere in
        the package (lambdas resolve through the self-methods they
        invoke), plus THEIR same-class callees."""

        def build():
            seeds: list = []
            # seed 1: RpcEventLoop._run
            for mod in pkg.modules:
                for cls in mod.tree.body:
                    if isinstance(cls, ast.ClassDef) \
                            and cls.name == "RpcEventLoop":
                        methods = _class_methods(cls)
                        funcs = _module_functions(mod)
                        if "_run" in methods:
                            seeds.append(
                                (_FnScope(mod, methods["_run"], cls,
                                          methods, funcs),
                                 _class_locks(mod, cls),
                                 _module_locks(mod)))
            # seed 2: done_cb= arguments to .submit() calls
            for mod in pkg.modules:
                funcs = _module_functions(mod)
                mod_locks = _module_locks(mod)
                for cls in [None] + [c for c in mod.tree.body
                                     if isinstance(c, ast.ClassDef)]:
                    body = mod.tree.body if cls is None else cls.body
                    methods = _class_methods(cls) if cls else {}
                    class_locks = _class_locks(mod, cls) if cls else {}
                    for holder in body:
                        if not isinstance(holder, (ast.FunctionDef,
                                                   ast.AsyncFunctionDef)):
                            continue
                        self_name = _fn_self_name(holder) if cls else None
                        for call in ast.walk(holder):
                            if not (isinstance(call, ast.Call)
                                    and isinstance(call.func,
                                                   ast.Attribute)
                                    and call.func.attr == "submit"):
                                continue
                            for kw in call.keywords:
                                if kw.arg != "done_cb":
                                    continue
                                for target in self._cb_targets(
                                        kw.value, self_name, methods,
                                        funcs):
                                    seeds.append(
                                        (_FnScope(mod, target, cls,
                                                  methods, funcs),
                                         class_locks, mod_locks))
            # close over same-class / same-module resolvable calls
            out: list = []
            seen: set = set()
            queue = list(seeds)
            while queue:
                scope, class_locks, mod_locks = queue.pop()
                if scope.key() in seen:
                    continue
                seen.add(scope.key())
                out.append((scope, class_locks, mod_locks))
                for node in ast.walk(scope.fn):
                    if isinstance(node, ast.Call):
                        callee = scope.resolve_call(node)
                        if callee is not None \
                                and callee.key() not in seen:
                            queue.append(
                                (callee,
                                 class_locks if callee.cls else {},
                                 mod_locks))
            return out

        return pkg.cached("blk01_loop_scopes", build)

    def _cb_targets(self, expr: ast.AST, self_name: Optional[str],
                    methods: dict, funcs: dict) -> list:
        """Function nodes a ``done_cb=<expr>`` resolves to."""
        out: list = []
        if isinstance(expr, ast.Lambda):
            for node in ast.walk(expr.body):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and self_name \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == self_name \
                        and node.func.attr in methods:
                    out.append(methods[node.func.attr])
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id in funcs:
                    out.append(funcs[node.func.id])
        elif isinstance(expr, ast.Attribute) and self_name \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == self_name \
                and expr.attr in methods:
            out.append(methods[expr.attr])
        elif isinstance(expr, ast.Name) and expr.id in funcs:
            out.append(funcs[expr.id])
        return out


# ---------------------------------------------------------------- JIT01

#: dotted calls whose value changes between trace time and run time —
#: inside a traced body they freeze to one trace-time constant
_IMPURE_DOTTED = {
    "time.time": "wall-clock read",
    "time.perf_counter": "clock read",
    "time.monotonic": "clock read",
    "citus_tpu.utils.clock.now": "wall-clock read",
    "citus_tpu.observability.trace.clock": "clock read",
}

_IMPURE_METHODS = {
    "bump": "COUNTERS bump",
    "bump_max": "COUNTERS bump",
    "acquire": "lock acquire",
    "begin_wait": "wait-event bracket",
}


class JitPurityRule(Rule):
    """Purity of traced bodies: any function lifted through
    ``jit_compile(f)`` or ``jax.vmap(f)`` (the only doors into the
    kernel cache) executes ONCE under the tracer; counter bumps, clock
    reads, lock acquires, wait brackets, and I/O inside it are burned
    into the trace — they fire at compile time and silently never
    again on a cache hit, so the stats lie exactly when the cache
    works."""

    id = "JIT01"
    name = "jit-traced body purity"

    _LIFTERS = {"jax.vmap"}

    def check_module(self, mod, pkg):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            dotted = mod.dotted(node.func)
            is_lifter = dotted in self._LIFTERS or (
                dotted is not None
                and dotted.split(".")[-1] == "jit_compile")
            if not is_lifter:
                continue
            target = self._resolve_fn_arg(node.args[0], node)
            if target is None:
                continue
            fname, body = target
            yield from self._check_body(mod, fname, body)

    def _resolve_fn_arg(self, arg: ast.AST, call: ast.Call):
        """(name, body-stmts) when the lifted argument is a local
        ``def``/``lambda``; None for opaque builder-call results
        (``build_worker_fn(plan, jnp)`` — checked at their own
        ``jit_compile`` sites when they have one)."""
        if isinstance(arg, ast.Lambda):
            return ("<lambda>", [ast.Expr(value=arg.body)])
        if not isinstance(arg, ast.Name):
            return None
        # walk outward through enclosing scopes for a matching def
        cur = call
        while cur is not None:
            cur = getattr(cur, "_lint_parent", None)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Module)):
                for stmt in cur.body:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                            and stmt.name == arg.id:
                        return (stmt.name, stmt.body)
        return None

    def _check_body(self, mod: ModuleIndex, fname: str, body):
        for stmt in body:
            for node in ast.walk(stmt):
                what = None
                if isinstance(node, ast.Call):
                    dotted = mod.dotted(node.func)
                    if dotted in _IMPURE_DOTTED:
                        what = _IMPURE_DOTTED[dotted]
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr in _IMPURE_METHODS:
                        what = _IMPURE_METHODS[node.func.attr]
                    elif isinstance(node.func, ast.Name) \
                            and node.func.id in ("print", "open",
                                                 "begin_wait"):
                        what = ("wait-event bracket"
                                if node.func.id == "begin_wait"
                                else "tracer-visible I/O")
                elif isinstance(node, ast.With):
                    for item in node.items:
                        ctx = item.context_expr
                        if isinstance(ctx, ast.Attribute) \
                                and ctx.attr.startswith(("_mu", "_lock",
                                                         "_cv")):
                            what = "lock acquire"
                if what is not None:
                    yield self.diag(
                        mod, node.lineno,
                        f"{fname}() is jit-traced but performs {what} "
                        f"inside the traced body — it fires once at "
                        f"trace time and vanishes on every cache hit")
