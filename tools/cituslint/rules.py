"""cituslint rules.

Each rule is a small class over the shared ``PackageIndex``.  IDs are
stable (suppressions name them):

========  ==============================================================
LOCK01    lock discipline: attribute mutated under ``with self._mu:``
          somewhere must hold the lock everywhere it is mutated
CONF01    confined calls: the data-driven table below pins risky calls
          to their single blessed module (jax.jit, perf_counter,
          time.time, sync_placement, call_binary, …)
THR01     ``threading.Thread(...)`` must pass an explicit ``daemon=``
THR02     a created thread needs a reachable join()/cancel path
SWL01     silent swallow: ``except Exception: pass`` / bare ``except:``
          with an empty body (no bump, no log, no re-raise)
CNT01     ``bump("name")`` / span-fold strings must name a counter
          declared in ``StatCounters.COUNTERS``
CNT02     every declared counter must have a bump site (dead counters
          lie in every dashboard)
CNT03     ``begin_wait("event")`` names must be registered in
          ``stats.WAIT_COUNTERS`` and every registered wait event must
          have a begin_wait site (both directions)
CNT04     every health-event kind in ``HEALTH_EVENT_KINDS`` must have a
          Prometheus gauge export and a ``citus_health_events()`` row
          type; ``emit_event("kind")`` literals must be declared
GUC01     ``settings.<section>.<field>`` reads must resolve to a
          declared Settings field
GUC02     every settings field the code reads must be SET/SHOW-covered
          in ``commands/config_cmds.py``'s ``_GUCS`` table
TODO01    no TODO/FIXME/XXX markers in shipped modules
SUP01/02  (engine) unjustified / unknown-id suppressions
========  ==============================================================
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from tools.cituslint.engine import ModuleIndex, PackageIndex, Rule
from tools.cituslint.concurrency import (
    BlockingCallRule, JitPurityRule, LockOrderRule,
)

# --------------------------------------------------------------- LOCK01

#: method names that mutate their receiver in place
_MUTATORS = {
    "append", "extend", "insert", "add", "remove", "discard", "pop",
    "popitem", "clear", "update", "setdefault", "appendleft", "popleft",
}

_LOCK_FACTORIES = {"threading.Lock", "threading.RLock",
                   "threading.Condition"}


def _self_attr(node: ast.AST, self_name: str) -> Optional[str]:
    """``self.attr`` / ``self.attr[...]`` (arbitrarily nested
    subscripts) → ``attr``; None otherwise."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == self_name:
        return node.attr
    return None


class LockDisciplineRule(Rule):
    """For every class that creates a ``threading.Lock/RLock/Condition``
    in ``__init__``: any attribute that is mutated under
    ``with self.<lock>:`` in ONE method is shared state — every other
    mutation of it must hold the lock too.  ``__init__`` itself is
    exempt (the object is still thread-private while constructing)."""

    id = "LOCK01"
    name = "lock discipline"

    def check_module(self, mod, pkg):
        for cls in ast.walk(mod.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(mod, cls)

    def _check_class(self, mod: ModuleIndex, cls: ast.ClassDef):
        lock_attrs = self._lock_attrs(mod, cls)
        if not lock_attrs:
            return
        # (method, attr, line, guarded) for every self-attribute
        # mutation outside __init__.  A method named *_locked is BY
        # CONVENTION called with the lock held: its mutations count as
        # guarded, and calls to it from unguarded context are flagged
        # below instead.
        records = []
        guarded_attrs: dict[str, tuple] = {}  # attr -> (method, line)
        helper_calls = []  # (method, helper, line, guarded)
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            args = meth.args.posonlyargs + meth.args.args
            if not args:
                continue  # staticmethod: no shared self state
            self_name = args[0].arg
            held = meth.name.endswith("_locked")
            for attr, line, guarded in self._mutations(
                    mod, meth, self_name, lock_attrs, base=held):
                if meth.name == "__init__":
                    continue
                records.append((meth.name, attr, line, guarded))
                if guarded:
                    guarded_attrs.setdefault(attr, (meth.name, line))
            for helper, line, guarded in self._locked_helper_calls(
                    meth, self_name, lock_attrs, base=held):
                helper_calls.append((meth.name, helper, line, guarded))
        for meth_name, attr, line, guarded in records:
            if guarded or attr not in guarded_attrs:
                continue
            gm, gl = guarded_attrs[attr]
            yield self.diag(
                mod, line,
                f"{cls.name}.{meth_name} mutates 'self.{attr}' without "
                f"holding a lock, but {gm} (line {gl}) mutates it under "
                f"'with self.<lock>:' — unguarded shared-state write")
        for meth_name, helper, line, guarded in helper_calls:
            if not guarded:
                yield self.diag(
                    mod, line,
                    f"{cls.name}.{meth_name} calls lock-held helper "
                    f"self.{helper}() without holding the lock "
                    f"(*_locked methods assume the caller locked)")

    def _lock_attrs(self, mod: ModuleIndex, cls: ast.ClassDef) -> set:
        out = set()
        for meth in cls.body:
            if not isinstance(meth, ast.FunctionDef) \
                    or meth.name != "__init__":
                continue
            args = meth.args.posonlyargs + meth.args.args
            self_name = args[0].arg if args else "self"
            for node in ast.walk(meth):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and mod.dotted(node.value.func) in _LOCK_FACTORIES:
                    for t in node.targets:
                        attr = _self_attr(t, self_name)
                        if attr is not None:
                            out.add(attr)
        return out

    def _locked_helper_calls(self, meth, self_name: str,
                             lock_attrs: set, base: bool = False):
        """Yield (helper, line, guarded) for calls to
        ``self.<x>_locked(...)`` inside ``meth``."""

        def visit(node, guarded):
            if isinstance(node, ast.With):
                holds = guarded or any(
                    _self_attr(item.context_expr, self_name) in lock_attrs
                    for item in node.items)
                for child in node.body:
                    yield from visit(child, holds)
                return
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr.endswith("_locked") \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == self_name:
                yield (node.func.attr, node.lineno, guarded)
            for child in ast.iter_child_nodes(node):
                yield from visit(child, guarded)

        for stmt in meth.body:
            yield from visit(stmt, base)

    def _mutations(self, mod: ModuleIndex, meth: ast.AST,
                   self_name: str, lock_attrs: set, base: bool = False):
        """Yield (attr, line, guarded) for each write to a self
        attribute inside ``meth``; ``guarded`` means an enclosing
        ``with self.<lock>:`` (or an ``finally``-released
        ``self.<lock>.acquire()`` idiom is NOT recognized — use with)."""

        def visit(node, guarded):
            if isinstance(node, ast.With):
                holds = guarded or any(
                    _self_attr(item.context_expr, self_name) in lock_attrs
                    for item in node.items)
                for item in node.items:
                    yield from visit(item.context_expr, guarded)
                for child in node.body:
                    yield from visit(child, holds)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for el in (t.elts if isinstance(
                            t, (ast.Tuple, ast.List)) else [t]):
                        attr = _self_attr(el, self_name)
                        if attr is not None and attr not in lock_attrs:
                            yield (attr, el.lineno, guarded)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = _self_attr(t, self_name)
                    if attr is not None and attr not in lock_attrs:
                        yield (attr, t.lineno, guarded)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                attr = _self_attr(node.func.value, self_name)
                if attr is not None and attr not in lock_attrs:
                    yield (attr, node.lineno, guarded)
            for child in ast.iter_child_nodes(node):
                yield from visit(child, guarded)

        for stmt in meth.body if isinstance(
                meth, (ast.FunctionDef, ast.AsyncFunctionDef)) else []:
            yield from visit(stmt, base)


# --------------------------------------------------------------- CONF01

#: dotted call -> in-package files allowed to make it.  This is the
#: generalization of the old hand-written CI checks: one table, one
#: rule, one failure shape.
CONFINED_CALLS = {
    # jax.jit only inside the kernel cache's jit_compile wrapper, so
    # ad-hoc compiles can't dodge cache accounting
    "jax.jit": ("executor/kernel_cache.py",),
    # query-axis batching: vmap-lifted kernels exist only where they
    # flow through get_kernel's batched: slots (executor/megabatch.py)
    # or the jit door itself
    "jax.vmap": ("executor/megabatch.py", "executor/kernel_cache.py"),
    # one span-timing clock for the whole package
    "time.perf_counter": ("observability/trace.py",),
    # one wall clock, swappable in tests (utils/clock.py now())
    "time.time": ("utils/clock.py",),
    # raw pool slots flow through the tenant-aware fair-share
    # scheduler only (workload/scheduler.py); anything else acquiring
    # directly would barge the per-tenant admission queues
    "citus_tpu.executor.admission.GLOBAL_POOL.acquire":
        ("workload/scheduler.py",),
    "citus_tpu.executor.admission.GLOBAL_POOL.release":
        ("workload/scheduler.py",),
    # wire codecs live in the data plane: npz is the LEGACY wire
    # fallback (zip container), and anything else serializing arrays
    # for the network must go through the frame codec there
    "numpy.savez": ("net/data_plane.py",),
    "numpy.load": ("net/data_plane.py",),
    # exactly one selector-driven dispatcher per process — ad-hoc
    # selectors would re-grow thread-per-RPC shapes around it
    "selectors.DefaultSelector": ("net/event_loop.py",),
    # the fused decode→filter→partial-agg(+merge) kernel builder lives
    # in ops/ and is entered only through the executor's jit_fused /
    # batched:jit_fused kernel-cache slots — an ad-hoc fused build
    # elsewhere would dodge both the cache and the donated-accumulator
    # discipline (the dtype/shape contract with _empty_partials)
    "citus_tpu.ops.scan_agg.build_fused_worker_fn":
        ("executor/executor.py", "executor/megabatch.py"),
    # same discipline for the streaming fused hash-table builder: only
    # the executor's jit_hash_fused / batched:jit_hash_fused slots may
    # enter it (the slot count / donated-state contract with
    # empty_hash_state)
    "citus_tpu.ops.hash_agg.build_fused_hash_worker":
        ("executor/executor.py", "executor/megabatch.py"),
    # hash-partial frames are wire format: encoded only by the task
    # codec halves, never ad-hoc
    "citus_tpu.net.data_plane.encode_hash_partials":
        ("executor/worker_tasks.py", "net/data_plane.py"),
    # placement-mutating operations have exactly five doors: the
    # rebalancer, the autopilot's actuator, the SQL command surface,
    # tenant isolation's split+move composition, and the background-job
    # runner registration.  A bare move/split launched from query-path
    # code would race the group-write-lock + catalog-flip discipline
    # those doors ride (and dodge the operation registry the autopilot
    # uses for exactly-once).  Both dotted forms are pinned because the
    # package __init__ re-exports move_shard_placement.
    "citus_tpu.operations.shard_transfer.move_shard_placement": (
        "operations/rebalancer.py", "services/autopilot.py",
        "commands/utility.py", "workload/isolation.py", "cluster.py"),
    "citus_tpu.operations.move_shard_placement": (
        "operations/rebalancer.py", "services/autopilot.py",
        "commands/utility.py", "workload/isolation.py", "cluster.py"),
    "citus_tpu.operations.shard_split.split_shard": (
        "commands/utility.py", "workload/isolation.py"),
    "citus_tpu.operations.split_shard": (
        "commands/utility.py", "workload/isolation.py"),
    "citus_tpu.workload.isolation.isolate_tenant_to_node": (
        "commands/utility.py",),
}

#: method name -> in-package files allowed to CALL it (receiver-typed
#: calls the dotted resolver can't see; matched by attribute name)
CONFINED_METHODS = {
    # the O(placement-bytes) pull path has exactly one executor door
    "sync_placement": ("executor/batches.py",),
    # the catalog placement flip is the move's 2PC decision — it must
    # ride the non-blocking sequence (final catch-up under the group
    # write lock + commit_metadata_flip); a flip anywhere else loses
    # writes raced onto the source
    "flip_placement": ("operations/shard_transfer.py",),
    # flight-recorder segment writes are the recorder's only disk
    # side-effect — confining the write door keeps retention/rotation
    # accounting honest (no second writer aging the segments)
    "append_segment_line": ("observability/flight_recorder.py",),
    # rollup refresh is the ONE door that advances a rollup past its
    # watermark: delta fold + upsert + watermark write commit as a
    # single transaction there (exactly-once restart replay); a second
    # caller would double-apply deltas or tear the watermark
    "refresh_once": ("rollup/manager.py",),
    "_apply_batch": ("rollup/manager.py",),
    # the replicated tenant control plane has ONE write door
    # (metadata/quotas.py): every catalog quota/class write must ride
    # the 2PC commit_metadata_flip sequence and re-hydrate the local
    # registry — a bare put anywhere else forks this coordinator's
    # admission behavior from the rest of the cluster
    "put_tenant_quota": ("metadata/quotas.py",),
    "drop_tenant_quota": ("metadata/quotas.py",),
    "put_priority_class": ("metadata/quotas.py",),
}

#: method name -> files where calling it is banned outright
BANNED_METHODS = {
    # worker_tasks ships tasks through the parallel dispatcher; a
    # sequential per-task RPC loop here costs sum-of-hosts not max
    "call_binary": ("executor/worker_tasks.py",),
    "call_binary_pooled": ("executor/worker_tasks.py",),
}

#: file -> identifiers that must appear in it (the positive half of
#: the dispatch invariant)
REQUIRED_IDENTIFIERS = {
    "executor/worker_tasks.py": ("dispatch_remote_tasks",),
    # the fan-out must ride the single event-loop dispatcher
    # (cat.remote_data.event_loop()), not per-RPC threads
    "executor/pipeline.py": ("event_loop",),
}


class ConfinedCallRule(Rule):
    """Data-driven call confinement (tables above)."""

    id = "CONF01"
    name = "confined calls"

    def check_module(self, mod, pkg):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted(node.func)
            if dotted in CONFINED_CALLS \
                    and mod.rel not in CONFINED_CALLS[dotted]:
                yield self.diag(
                    mod, node.lineno,
                    f"call to {dotted}() is confined to "
                    f"{', '.join(CONFINED_CALLS[dotted])}")
            if isinstance(node.func, ast.Attribute):
                name = node.func.attr
                if name in CONFINED_METHODS \
                        and mod.rel not in CONFINED_METHODS[name]:
                    yield self.diag(
                        mod, node.lineno,
                        f"call to .{name}() is confined to "
                        f"{', '.join(CONFINED_METHODS[name])}")
                if name in BANNED_METHODS \
                        and mod.rel in BANNED_METHODS[name]:
                    yield self.diag(
                        mod, node.lineno,
                        f"call to .{name}() is banned in {mod.rel}")

    def check_package(self, pkg):
        for rel, idents in REQUIRED_IDENTIFIERS.items():
            mod = pkg.by_rel.get(rel)
            if mod is None:
                continue
            present = {n.id for n in ast.walk(mod.tree)
                       if isinstance(n, ast.Name)}
            present |= {n.attr for n in ast.walk(mod.tree)
                        if isinstance(n, ast.Attribute)}
            for ident in idents:
                if ident not in present:
                    yield self.diag(mod, 1,
                                    f"{rel} must reference {ident!r} "
                                    f"(architecture invariant)")


# --------------------------------------------------------------- THR01/02


def _thread_calls(mod: ModuleIndex):
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and mod.dotted(node.func) == "threading.Thread":
            yield node


def _thread_binding(node: ast.Call) -> Optional[str]:
    """Name or attribute a ``Thread(...)`` call is assigned to."""
    parent = getattr(node, "_lint_parent", None)
    if isinstance(parent, ast.Assign):
        for t in parent.targets:
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, ast.Attribute):
                return t.attr
    return None


class ThreadDaemonRule(Rule):
    """``threading.Thread(...)`` must pass an explicit ``daemon=`` —
    thread lifetime is a decision, not a default."""

    id = "THR01"
    name = "explicit thread daemon flag"

    def check_module(self, mod, pkg):
        for node in _thread_calls(mod):
            if not any(kw.arg == "daemon" for kw in node.keywords):
                yield self.diag(
                    mod, node.lineno,
                    "threading.Thread(...) must pass an explicit "
                    "daemon= keyword")


class ThreadJoinRule(Rule):
    """A created thread needs a reachable join()/cancel path: the name
    or attribute it is bound to must be ``.join()``-ed (or
    ``.cancel()``-ed) somewhere in the module; a fire-and-forget
    Thread needs a justified suppression."""

    id = "THR02"
    name = "thread join/cancel path"

    def check_module(self, mod, pkg):
        joined = self._joined_names(mod)
        for node in _thread_calls(mod):
            bound = _thread_binding(node)
            if bound is None or bound not in joined:
                tgt = f"'{bound}'" if bound else "an unbound Thread"
                yield self.diag(
                    mod, node.lineno,
                    f"thread bound to {tgt} has no reachable .join()/"
                    f".cancel() call in this module")

    def _joined_names(self, mod: ModuleIndex) -> set:
        out = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("join", "cancel"):
                v = node.func.value
                if isinstance(v, ast.Attribute):
                    out.add(v.attr)
                elif isinstance(v, ast.Name):
                    out.add(v.id)
        return out


# ---------------------------------------------------------------- SWL01


class SilentSwallowRule(Rule):
    """``except Exception:`` / bare ``except:`` whose body is only
    ``pass``/``continue`` swallows failures invisibly: bump a counter,
    log, re-raise — or justify the suppression."""

    id = "SWL01"
    name = "silent exception swallow"

    def check_module(self, mod, pkg):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._broad(mod, node.type):
                continue
            if all(isinstance(s, (ast.Pass, ast.Continue))
                   for s in node.body):
                what = ("bare except:" if node.type is None
                        else "except Exception: pass")
                yield self.diag(
                    mod, node.lineno,
                    f"{what} silently swallows the failure — bump a "
                    f"counter, log, or re-raise")

    def _broad(self, mod: ModuleIndex, t) -> bool:
        if t is None:
            return True
        if isinstance(t, ast.Tuple):
            return any(self._broad(mod, el) for el in t.elts)
        return mod.dotted(t) in ("Exception", "BaseException",
                                 "builtins.Exception",
                                 "builtins.BaseException")


# -------------------------------------------------------------- CNT01/02


def _counters_decl(pkg: PackageIndex):
    """(names, (lineno, end_lineno), module) of StatCounters.COUNTERS
    in <pkg>/stats.py; (set(), None, None) when absent."""

    def build():
        mod = pkg.by_rel.get("stats.py")
        if mod is None:
            return (set(), None, None)
        for cls in mod.tree.body:
            if not (isinstance(cls, ast.ClassDef)
                    and cls.name == "StatCounters"):
                continue
            for stmt in cls.body:
                if isinstance(stmt, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "COUNTERS"
                        for t in stmt.targets):
                    names = {n.value for n in ast.walk(stmt.value)
                             if isinstance(n, ast.Constant)
                             and isinstance(n.value, str)}
                    return (names, (stmt.lineno, stmt.end_lineno), mod)
        return (set(), None, None)

    return pkg.cached("counters_decl", build)


class CounterNameRule(Rule):
    """Every ``bump("name")``/``bump_max("name")`` literal and every
    value of a ``_SPAN_MS``-style span-fold dict must be declared in
    ``StatCounters.COUNTERS`` — a typo'd bump silently counts into the
    void."""

    id = "CNT01"
    name = "counter names declared"

    def check_module(self, mod, pkg):
        names, _span, _mod = _counters_decl(pkg)
        if _mod is None:
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("bump", "bump_max") \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str) \
                    and node.args[0].value not in names:
                yield self.diag(
                    mod, node.lineno,
                    f"bump of undeclared counter "
                    f"{node.args[0].value!r} (not in "
                    f"StatCounters.COUNTERS)")
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id.endswith("_SPAN_MS")
                            for t in node.targets) \
                    and isinstance(node.value, ast.Dict):
                for v in node.value.values:
                    if isinstance(v, ast.Constant) \
                            and isinstance(v.value, str) \
                            and v.value not in names:
                        yield self.diag(
                            mod, v.lineno,
                            f"span-fold target {v.value!r} is not a "
                            f"declared counter")


class DeadCounterRule(Rule):
    """Inverse of CNT01: every declared counter needs at least one
    bump site (a string-literal use outside the declaration)."""

    id = "CNT02"
    name = "no dead counters"

    def check_package(self, pkg):
        names, span, decl_mod = _counters_decl(pkg)
        if decl_mod is None or not names:
            return
        used = set()
        for mod in pkg.modules:
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)):
                    continue
                if mod is decl_mod and span \
                        and span[0] <= node.lineno <= span[1]:
                    continue  # the declaration itself is not a use
                used.add(node.value)
        for name in sorted(names - used):
            yield self.diag(
                decl_mod, span[0],
                f"counter {name!r} is declared but never bumped "
                f"anywhere in the package")


def _wait_events_decl(pkg: PackageIndex):
    """(event names, (lineno, end_lineno), module) of the module-level
    ``WAIT_COUNTERS`` dict in <pkg>/stats.py; (set(), None, None) when
    absent."""

    def build():
        mod = pkg.by_rel.get("stats.py")
        if mod is None:
            return (set(), None, None)
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "WAIT_COUNTERS"
                    for t in stmt.targets) \
                    and isinstance(stmt.value, ast.Dict):
                keys = {k.value for k in stmt.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
                return (keys, (stmt.lineno, stmt.end_lineno), mod)
        return (set(), None, None)

    return pkg.cached("wait_events_decl", build)


class WaitEventRule(Rule):
    """Cross-consistency for the wait-event seam: every literal
    ``begin_wait("event")`` in the package must name a key of
    ``stats.WAIT_COUNTERS`` (a typo'd event books blocked time into a
    KeyError at end_wait), and every registered event must have at
    least one begin_wait site (an unentered event lies in every
    wait-profile dashboard)."""

    id = "CNT03"
    name = "wait events registered"

    def check_package(self, pkg):
        events, span, decl_mod = _wait_events_decl(pkg)
        if decl_mod is None or not events:
            return
        entered = set()
        for mod in pkg.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                fname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if fname != "begin_wait" or not node.args:
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    continue
                if arg.value not in events:
                    yield self.diag(
                        mod, node.lineno,
                        f"begin_wait of unregistered wait event "
                        f"{arg.value!r} (not a stats.WAIT_COUNTERS key)")
                else:
                    entered.add(arg.value)
        for ev in sorted(events - entered):
            yield self.diag(
                decl_mod, span[0],
                f"wait event {ev!r} is registered but no begin_wait "
                f"site enters it")


def _health_kinds_decl(pkg: PackageIndex):
    """(kind names, (lineno, end_lineno), module) of the module-level
    ``HEALTH_EVENT_KINDS`` dict in <pkg>/observability/flight_recorder.py;
    (set(), None, None) when absent."""

    def build():
        mod = pkg.by_rel.get("observability/flight_recorder.py")
        if mod is None:
            return (set(), None, None)
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name)
                    and t.id == "HEALTH_EVENT_KINDS"
                    for t in stmt.targets) \
                    and isinstance(stmt.value, ast.Dict):
                keys = {k.value for k in stmt.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
                return (keys, (stmt.lineno, stmt.end_lineno), mod)
        return (set(), None, None)

    return pkg.cached("health_kinds_decl", build)


def _module_strings(mod: ModuleIndex) -> set:
    """All string constants appearing anywhere in a module."""
    return {n.value for n in ast.walk(mod.tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}


class HealthEventRule(Rule):
    """Cross-consistency for the health-event seam: every kind declared
    in ``flight_recorder.HEALTH_EVENT_KINDS`` must surface BOTH as a
    Prometheus gauge in ``observability/export.py`` (the ``health_<kind>``
    literal) and as a ``citus_health_events()`` row type in
    ``commands/utility.py`` (the severity table) — an alert kind that
    exists in only one surface is invisible to half the operators.  And
    every literal ``emit_event("kind")`` must name a declared kind (a
    typo'd kind raises at runtime on the sampler thread, where nobody
    is watching)."""

    id = "CNT04"
    name = "health-event kinds exported"

    #: kind must appear (bare or ``health_``-prefixed) in each of these
    SURFACES = (
        ("observability/export.py", "Prometheus gauge export"),
        ("commands/utility.py", "citus_health_events() row type"),
    )

    def check_package(self, pkg):
        kinds, span, decl_mod = _health_kinds_decl(pkg)
        if decl_mod is None or not kinds:
            return
        for rel, what in self.SURFACES:
            mod = pkg.by_rel.get(rel)
            if mod is None:
                continue
            strings = _module_strings(mod)
            for kind in sorted(kinds):
                if kind not in strings \
                        and f"health_{kind}" not in strings:
                    yield self.diag(
                        decl_mod, span[0],
                        f"health-event kind {kind!r} has no {what} "
                        f"in {rel}")
        for mod in pkg.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                fname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if fname not in ("emit_event", "_emit_locked") \
                        or not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str) \
                        and arg.value not in kinds:
                    yield self.diag(
                        mod, node.lineno,
                        f"emit of undeclared health-event kind "
                        f"{arg.value!r} (not a HEALTH_EVENT_KINDS key)")


# -------------------------------------------------------------- GUC01/02


def _settings_schema(pkg: PackageIndex):
    """Parse <pkg>/config.py: ({section: field-set}, direct-field-set,
    methods).  Empty when config.py is absent."""

    def build():
        mod = pkg.by_rel.get("config.py")
        if mod is None:
            return ({}, set(), set(), None)
        class_fields: dict[str, set] = {}
        class_methods: dict[str, set] = {}
        for cls in mod.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            fields, methods = set(), set()
            for stmt in cls.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    fields.add(stmt.target.id)
                elif isinstance(stmt, ast.FunctionDef):
                    methods.add(stmt.name)
            class_fields[cls.name] = fields
            class_methods[cls.name] = methods
        sections: dict[str, set] = {}
        direct: set = set()
        for cls in mod.tree.body:
            if not (isinstance(cls, ast.ClassDef)
                    and cls.name == "Settings"):
                continue
            for stmt in cls.body:
                if not (isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)):
                    continue
                ann = stmt.annotation
                ann_name = ann.id if isinstance(ann, ast.Name) else None
                if ann_name in class_fields and ann_name != "Settings":
                    sections[stmt.target.id] = class_fields[ann_name]
                else:
                    direct.add(stmt.target.id)
            methods = class_methods.get("Settings", set())
            return (sections, direct, methods, mod)
        return ({}, set(), set(), None)

    return pkg.cached("settings_schema", build)


def _guc_coverage(pkg: PackageIndex):
    """(section, field) pairs covered by _GUCS in
    <pkg>/commands/config_cmds.py."""

    def build():
        mod = pkg.by_rel.get("commands/config_cmds.py")
        if mod is None:
            return (set(), None)
        covered = set()
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "_GUCS"
                            for t in node.targets)
                    and isinstance(node.value, ast.Dict)):
                continue
            for v in node.value.values:
                if isinstance(v, ast.Tuple) and len(v.elts) >= 2 \
                        and isinstance(v.elts[0], ast.Constant) \
                        and isinstance(v.elts[1], ast.Constant):
                    covered.add((v.elts[0].value, v.elts[1].value))
        return (covered, mod)

    return pkg.cached("guc_coverage", build)


class SettingsFieldRule(Rule):
    """Every ``settings.<section>.<field>`` attribute read must resolve
    to a declared Settings field (GUC01) and that field must have
    SET/SHOW coverage in the ``_GUCS`` table (GUC02) — config a DBA
    cannot inspect or change at runtime is a support hazard."""

    id = "GUC01"
    name = "settings reads resolve + SET/SHOW covered"

    def check_module(self, mod, pkg):
        sections, direct, methods, cfg_mod = _settings_schema(pkg)
        if cfg_mod is None or mod is cfg_mod:
            return
        covered, gucs_mod = _guc_coverage(pkg)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Attribute):
                continue
            parent = getattr(node, "_lint_parent", None)
            if isinstance(parent, ast.Attribute):
                continue  # only the outermost link of a chain
            chain = self._chain_after_settings(mod, node)
            if not chain:
                continue
            head = chain[0]
            if head in sections:
                if len(chain) < 2:
                    continue
                f = chain[1]
                if f not in sections[head]:
                    yield self.diag(
                        mod, node.lineno,
                        f"settings.{head}.{f} does not resolve to a "
                        f"declared {head.capitalize()}Settings field")
                elif gucs_mod is not None \
                        and (head, f) not in covered:
                    yield self.diag(
                        mod, node.lineno,
                        f"settings.{head}.{f} is read here but has no "
                        f"SET/SHOW entry in commands/config_cmds.py "
                        f"_GUCS (GUC02)")
            elif head not in direct and head not in methods:
                yield self.diag(
                    mod, node.lineno,
                    f"settings.{head} does not resolve to a declared "
                    f"Settings field or section")
            elif head in direct and gucs_mod is not None \
                    and (None, head) not in covered:
                yield self.diag(
                    mod, node.lineno,
                    f"settings.{head} is read here but has no SET/SHOW "
                    f"entry in commands/config_cmds.py _GUCS (GUC02)")

    def _chain_after_settings(self, mod: ModuleIndex,
                              node: ast.Attribute) -> Optional[list]:
        parts: list[str] = []
        cur: ast.AST = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        parts.reverse()
        if "settings" in parts:
            return parts[parts.index("settings") + 1:]
        if isinstance(cur, ast.Name) and cur.id == "settings":
            return parts
        if isinstance(cur, ast.Call):
            fn = mod.dotted(cur.func)
            if fn and fn.split(".")[-1] == "current_settings":
                return parts
        return None


# --------------------------------------------------------------- TODO01

_TODO = re.compile(r"\b(TODO|FIXME|XXX)\b")


class TodoMarkerRule(Rule):
    """No TODO/FIXME/XXX stubs in shipped modules — the package ships
    complete components, not placeholders."""

    id = "TODO01"
    name = "no TODO markers"

    def check_module(self, mod, pkg):
        for i, line in enumerate(mod.lines, 1):
            if _TODO.search(line):
                yield self.diag(mod, i,
                                f"{_TODO.search(line).group(1)} marker "
                                f"in shipped module")


ALL_RULES = [
    LockDisciplineRule,
    LockOrderRule,
    BlockingCallRule,
    JitPurityRule,
    ConfinedCallRule,
    ThreadDaemonRule,
    ThreadJoinRule,
    SilentSwallowRule,
    CounterNameRule,
    DeadCounterRule,
    WaitEventRule,
    HealthEventRule,
    SettingsFieldRule,
    TodoMarkerRule,
]
