"""CLI: ``python -m tools.cituslint citus_tpu [--select ID,ID ...]``.

Exit status 0 when the tree is clean, 1 when any diagnostic survives
suppression filtering (2 on usage errors) — suitable for CI and
scripts/lint.sh.
"""

from __future__ import annotations

import argparse
import sys

from tools.cituslint.engine import run_lint
from tools.cituslint.rules import ALL_RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.cituslint",
        description="AST-based static analysis for citus_tpu "
                    "(lock discipline, call confinement, silent "
                    "swallows, metrics/GUC consistency)")
    ap.add_argument("package", nargs="?", default="citus_tpu",
                    help="package directory to lint (default: citus_tpu)")
    ap.add_argument("--select", metavar="IDS",
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rc in ALL_RULES:
            doc = (rc.__doc__ or "").strip().split("\n")[0]
            print(f"{rc.id:8s} {rc.name:40s} {doc}")
        return 0

    select = None
    if args.select:
        select = {s.strip() for s in args.select.split(",") if s.strip()}
    try:
        diags = run_lint(args.package, select=select)
    except FileNotFoundError as e:
        print(f"cituslint: {e}", file=sys.stderr)
        return 2
    for d in diags:
        print(d)
    if diags:
        print(f"cituslint: {len(diags)} diagnostic(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
