"""cituslint — AST-based static analysis for the citus_tpu package.

One engine replaces the grown pile of regex CI checks (reference:
the ci/ lint battery — banned.h.sh and friends — enforced there as
shell scripts over raw source).  The package is parsed ONCE into
per-module symbol/call/attribute indexes (engine.py); a registry of
rule classes (rules.py) walks those indexes and reports
``file:line rule-id message`` diagnostics.

Run it::

    python -m tools.cituslint citus_tpu          # CLI, exit 1 on findings
    from tools.cituslint import run_lint         # importable
    diags = run_lint("citus_tpu")

Suppress a finding on a specific line with a justified pragma::

    risky_write()  # lint: disable=LOCK01 -- single-threaded at startup

The justification (the text after ``--``) is REQUIRED: a bare
``# lint: disable=ID`` is itself a diagnostic (SUP01).
"""

from tools.cituslint.engine import (  # noqa: F401
    Diagnostic,
    PackageIndex,
    Rule,
    run_lint,
)
from tools.cituslint.rules import ALL_RULES  # noqa: F401
