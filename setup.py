"""Build hook: compile the native columnar IO library during wheel
builds (reference: the extension's PGXS Makefiles build citus.so; here
one C++ shared library built by make, loaded via ctypes with a pure-
Python fallback when unavailable)."""

import subprocess
from pathlib import Path

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        native = Path(__file__).parent / "citus_tpu" / "native"
        try:
            subprocess.run(["make", "-C", str(native)], check=True)
        except Exception as e:  # toolchain absent: ship pure-Python
            print(f"warning: native build skipped ({e}); "
                  "the engine falls back to Python IO")
        super().run()


setup(cmdclass={"build_py": BuildWithNative})
