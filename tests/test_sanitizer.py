"""Runtime concurrency sanitizer (citussan dynamic half): lock-order
inversion detection across threads, self-deadlock, wait-under-lock and
loop-thread findings, the off-mode zero-cost passthrough, and the two
regression fixes the static rules drove — RemoteTaskDispatch submitting
outside its bookkeeping lock, and rollup refresh/drop executing with no
lock held (subprocess, CITUS_SANITIZE=1)."""

import os
import subprocess
import sys
import threading

import pytest

from citus_tpu.utils import sanitizer


@pytest.fixture
def san():
    """Activate the sanitizer's record mode for this test only (no
    threading patch: wrapped locks are constructed explicitly)."""
    old_active, old_mode = sanitizer._ACTIVE, sanitizer._MODE
    sanitizer._ACTIVE, sanitizer._MODE = True, "record"
    sanitizer.reset()
    yield sanitizer
    sanitizer.reset()
    sanitizer._ACTIVE, sanitizer._MODE = old_active, old_mode


def mklock(site, reentrant=False):
    make = sanitizer._real_RLock if reentrant else sanitizer._real_Lock
    return sanitizer._SanLock(make(), site, reentrant)


def kinds(report):
    return [f["kind"] for f in report]


# ------------------------------------------------------ order tracking


def test_ab_ba_inversion_on_two_threads_reports_cycle(san):
    a = mklock("t.py:A")
    b = mklock("t.py:B")

    def order(first, second):
        with first:
            with second:
                pass

    t1 = threading.Thread(target=order, args=(a, b))
    t1.start()
    t1.join()
    assert san.report() == []  # one order alone is fine
    t2 = threading.Thread(target=order, args=(b, a))
    t2.start()
    t2.join()
    found = [f for f in san.report() if f["kind"] == "lock-order-cycle"]
    assert len(found) == 1
    assert "t.py:A" in found[0]["detail"]
    assert "t.py:B" in found[0]["detail"]


def test_consistent_order_across_threads_is_clean(san):
    a = mklock("t.py:A")
    b = mklock("t.py:B")

    def ab():
        with a:
            with b:
                pass

    threads = [threading.Thread(target=ab) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert san.report() == []


def test_three_lock_rotation_reports_cycle(san):
    locks = {s: mklock(f"t.py:{s}") for s in "ABC"}

    def nest(x, y):
        with locks[x]:
            with locks[y]:
                pass

    for pair in ("AB", "BC", "CA"):  # A->B, B->C, then C->A closes it
        t = threading.Thread(target=nest, args=tuple(pair))
        t.start()
        t.join()
    assert "lock-order-cycle" in kinds(san.report())


def test_blocking_reacquire_always_raises(san):
    a = mklock("t.py:A")
    with a:
        with pytest.raises(sanitizer.SanitizerError):
            a.acquire()
    assert kinds(san.report()) == ["self-deadlock"]  # recorded AND raised
    san.reset()
    # an RLock re-acquire is legal and clean
    r = mklock("t.py:R", reentrant=True)
    with r:
        with r:
            pass
    assert san.report() == []


# --------------------------------------------------- begin_wait seam


def test_begin_wait_under_lock_is_reported(san):
    a = mklock("t.py:A")
    with a:
        san.on_begin_wait("remote_rpc")
    rep = san.report()
    assert kinds(rep) == ["wait-under-lock"]
    assert "t.py:A" in rep[0]["detail"]
    assert "remote_rpc" in rep[0]["detail"]


def test_condition_backing_lock_is_exempt(san):
    mu = mklock("t.py:MU")
    cv = sanitizer._condition_factory(mu)  # marks mu cv-backed
    with cv:
        san.on_begin_wait("admission_wait")
    assert san.report() == []


def test_begin_wait_routed_from_stats_seam(san):
    from citus_tpu.stats import begin_wait, end_wait
    a = mklock("t.py:A")
    with a:
        end_wait(begin_wait("remote_rpc"))
    assert "wait-under-lock" in kinds(san.report())


# ------------------------------------------------------- loop thread


def test_contended_acquire_on_loop_thread_is_reported(san):
    a = mklock("t.py:A")
    ready = threading.Event()

    def loop_thread():
        san.register_loop_thread()
        ready.wait(5)
        with a:  # contended: main holds it
            pass
        san.unregister_loop_thread()

    t = threading.Thread(target=loop_thread)
    import time as _time
    deadline = _time.monotonic() + 10
    with a:
        t.start()
        ready.set()
        # the loop thread records BEFORE parking on the contended lock
        while not any(k == "loop-thread-block"
                      for k in kinds(san.report())):
            assert _time.monotonic() < deadline, san.report()
            _time.sleep(0.005)
    t.join(5)
    rep = [f for f in san.report() if f["kind"] == "loop-thread-block"]
    assert rep and "t.py:A" in rep[0]["detail"]


def test_begin_wait_on_loop_thread_is_reported(san):
    out = []

    def loop_thread():
        san.register_loop_thread()
        san.on_begin_wait("remote_rpc")
        san.unregister_loop_thread()
        out.append(True)

    t = threading.Thread(target=loop_thread)
    t.start()
    t.join(5)
    assert out == [True]
    assert "loop-thread-block" in kinds(san.report())


# -------------------------------------------------- off-mode passthrough


@pytest.mark.skipif(sanitizer.enabled(),
                    reason="suite running under CITUS_SANITIZE")
def test_off_mode_is_zero_cost_passthrough():
    # no patch installed: threading.Lock is the real C factory and the
    # stats seam's guard flag is a single False attribute read
    assert threading.Lock is sanitizer._real_Lock
    assert threading.RLock is sanitizer._real_RLock
    assert threading.Condition is sanitizer._real_Condition
    assert sanitizer._ACTIVE is False
    sanitizer.on_begin_wait("remote_rpc")  # no-op, records nothing
    assert sanitizer.report() == []


# ------------------------------------- regression: dispatch fan-out fix


class _StubLoop:
    """Records submits and whether the dispatch bookkeeping lock was
    held at submit time (the old shape held it across JSON encode)."""

    def __init__(self):
        self.calls = []
        self.dispatch = None
        self.locked_during_submit = []

    def submit(self, ep, method, task, done_cb=None):
        if self.dispatch is not None:
            self.locked_during_submit.append(
                self.dispatch._mu.locked())
        self.calls.append((ep, method, task, done_cb))


class _Fut:
    def __init__(self, meta, blob):
        self._v = (meta, blob)

    def result(self):
        return self._v


def test_remote_dispatch_never_submits_under_its_lock():
    from collections import deque

    from citus_tpu.config import Settings
    from citus_tpu.executor.pipeline import RemoteTaskDispatch, _NodePool

    class _NS:
        runtime_cache = {}

    class _Cat:
        class remote_data:
            @staticmethod
            def event_loop():
                return None

    d = RemoteTaskDispatch(_Cat(), _NS(), Settings(), [], False)
    loop = _StubLoop()
    loop.dispatch = d
    d._loop = loop
    pool = _NodePool()
    pool.window = 2
    pool.pending = deque(
        [(0, 0, ("h", 1), {"t": 0}), (1, 0, ("h", 1), {"t": 1})])
    d._nodes[0] = pool
    d._total = 2

    d._launch()
    assert len(loop.calls) == 2  # window 2: both planned and submitted
    assert loop.locked_during_submit == [False, False]
    assert d._inflight_total == 2  # accounting committed at plan time

    # completion path (this runs on the event-loop thread in prod):
    # bookkeeping under the lock, relaunch AFTER releasing it
    pool.pending = deque([(2, 0, ("h", 1), {"t": 2})])
    d._total = 3
    cb = loop.calls[0][3]
    cb(_Fut({}, b"frame"))
    assert len(loop.calls) == 3  # completion relaunched the pending task
    assert loop.locked_during_submit == [False, False, False]
    assert d._settled == 1 and 0 in d._raw


def test_remote_dispatch_abort_waits_out_planned_tasks():
    from collections import deque

    from citus_tpu.config import Settings
    from citus_tpu.executor.pipeline import RemoteTaskDispatch, _NodePool

    class _NS:
        runtime_cache = {}

    class _Cat:
        class remote_data:
            @staticmethod
            def event_loop():
                return None

    d = RemoteTaskDispatch(_Cat(), _NS(), Settings(), [], False)
    loop = _StubLoop()
    d._loop = loop
    pool = _NodePool()
    pool.pending = deque([(0, 0, ("h", 1), {"t": 0})])
    d._nodes[0] = pool
    d._total = 1
    d._launch()
    assert d._inflight_total == 1
    done = []

    def aborter():
        d.abort()
        done.append(True)

    t = threading.Thread(target=aborter)
    t.start()
    t.join(0.2)
    assert not done  # abort() blocks on the in-flight task...
    loop.calls[0][3](_Fut({}, b"x"))  # ...until its done_cb settles it
    t.join(5)
    assert done and d._inflight_total == 0


# ------------------------- regression: rollup refresh fix (subprocess)


_ROLLUP_CHILD = r"""
import sys
import citus_tpu as ct
from citus_tpu.config import Settings
from citus_tpu.utils import sanitizer
from citus_tpu import stats

assert sanitizer.enabled(), "CITUS_SANITIZE did not activate"

cl = ct.Cluster(sys.argv[1],
                settings=Settings(enable_change_data_capture=True,
                                  start_maintenance_daemon=False))
cl.execute("INSERT INTO ev VALUES (1, 'kx', 5.0, 3), (2, 'ky', 6.0, 4)")

orig = cl.execute
def parked_execute(sql, *a, **k):
    # simulate the admission controller parking this statement: under
    # the OLD refresh shape this bracket opens while _refresh_mu is
    # held and the sanitizer reports wait-under-lock
    tok = stats.begin_wait("admission_wait")
    try:
        return orig(sql, *a, **k)
    finally:
        stats.end_wait(tok)
cl.execute = parked_execute

folded = cl.rollup_manager.refresh_once("ev_r")
assert folded, "refresh folded nothing"
cl.execute = orig
cl.rollup_manager.drop_rollup("ev_r")

bad = [f for f in sanitizer.report()
       if f["kind"] in ("wait-under-lock", "lock-order-cycle")]
if bad:
    print("SANITIZER FINDINGS:", bad, file=sys.stderr)
    sys.exit(1)
cl.close()
print("OK")
"""


def test_rollup_refresh_holds_no_lock_across_execute(tmp_path):
    """Under CITUS_SANITIZE=1, a refresh whose execute() parks in
    admission must NOT be holding any rollup-manager lock (the old
    _refresh_mu-across-execute shape fails this)."""
    import numpy as np

    import citus_tpu as ct
    from citus_tpu.config import Settings

    db = str(tmp_path / "db")
    cl = ct.Cluster(db, n_nodes=1,
                    settings=Settings(enable_change_data_capture=True,
                                      start_maintenance_daemon=False))
    cl.execute("CREATE TABLE ev (tid bigint NOT NULL, kind text, "
               "v double, code bigint)")
    cl.execute("SELECT create_distributed_table('ev', 'tid', 4)")
    cl.copy_from("ev", columns={
        "tid": np.arange(40, dtype=np.int64) % 4,
        "kind": np.array(["k%d" % (i % 3) for i in range(40)], object),
        "v": np.linspace(1.0, 5.0, 40),
        "code": np.zeros(40, dtype=np.int64)})
    cl.execute("SELECT citus_create_rollup('ev_r', 'ev', 'tid', "
               "'count(*), sum(v)')")
    cl.close()

    env = dict(os.environ, JAX_PLATFORMS="cpu", CITUS_SANITIZE="1")
    r = subprocess.run([sys.executable, "-c", _ROLLUP_CHILD, db],
                       env=env, timeout=300, capture_output=True,
                       cwd=os.path.dirname(os.path.dirname(__file__)))
    assert r.returncode == 0, r.stderr.decode()[-3000:]
    assert b"OK" in r.stdout


# --------------------- representative stress run under CITUS_SANITIZE=1


_STRESS_CHILD = r"""
import sys, threading
import numpy as np
import citus_tpu as ct
from citus_tpu.config import Settings
from citus_tpu.utils import sanitizer

assert sanitizer.enabled()
cl = ct.Cluster(sys.argv[1], n_nodes=2,
                settings=Settings(start_maintenance_daemon=False))
cl.execute("CREATE TABLE t (k bigint NOT NULL, v double)")
cl.execute("SELECT create_distributed_table('t', 'k', 8)")
cl.copy_from("t", columns={
    "k": np.arange(400, dtype=np.int64) % 50,
    "v": np.linspace(0.0, 1.0, 400)})

errors = []
def worker(i):
    try:
        for q in range(4):
            res = cl.execute(
                "SELECT k, count(*), sum(v) FROM t "
                "WHERE k >= %d GROUP BY k" % (i % 5))
            assert res.rows
    except Exception as e:  # surfaced below; the thread must not die silently
        errors.append(repr(e))

threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
for t in threads: t.start()
for t in threads: t.join(120)
cl.close()
assert not errors, errors
findings = sanitizer.report()
if findings:
    print("SANITIZER FINDINGS:", findings, file=sys.stderr)
    sys.exit(1)
print("CLEAN")
"""


def test_multithreaded_stress_is_sanitizer_clean(tmp_path):
    """Six concurrent query threads over a 2-node cluster under
    CITUS_SANITIZE=1: the fan-out, scheduler, stats, and megabatch
    interplay must leave an empty citus_sanitizer_report()."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", CITUS_SANITIZE="1")
    r = subprocess.run(
        [sys.executable, "-c", _STRESS_CHILD, str(tmp_path / "db")],
        env=env, timeout=540, capture_output=True,
        cwd=os.path.dirname(os.path.dirname(__file__)))
    assert r.returncode == 0, (r.stdout.decode()[-1000:],
                               r.stderr.decode()[-3000:])
    assert b"CLEAN" in r.stdout
