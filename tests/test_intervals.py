"""Typed date/timestamp literals + INTERVAL arithmetic (the TPC-H
predicate surface: ``l_shipdate <= date '1998-12-01' - interval '90'
day``).  Reference: PostgreSQL datetime types; the reference pushes
these expressions down into shard queries unchanged."""

import datetime as dt

import pytest

import citus_tpu as ct
from citus_tpu.config import ExecutorSettings, settings_override
from citus_tpu.errors import UnsupportedFeatureError


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"))
    c.execute("CREATE TABLE t (k bigint NOT NULL, d date, ts timestamp)")
    c.execute("SELECT create_distributed_table('t','k',4)")
    c.copy_from("t", rows=[
        (1, "1998-09-01", "1998-09-01 10:30:00"),
        (2, "1998-12-01", "1998-12-01 00:00:00"),
        (3, "1995-01-31", "1995-01-31 23:59:59"),
        (4, None, None),
    ])
    return c


def test_typed_date_literal(cl):
    assert cl.execute(
        "SELECT count(*) FROM t WHERE d <= date '1998-12-01'").rows == [(3,)]
    assert cl.execute("SELECT count(*) FROM t WHERE ts < "
                      "timestamp '1998-09-01 10:30:01'").rows == [(2,)]


def test_tpch_style_predicates(cl):
    assert cl.execute("SELECT count(*) FROM t WHERE d <= "
                      "date '1998-12-01' - interval '90' day").rows == [(2,)]
    assert cl.execute("SELECT count(*) FROM t WHERE d < "
                      "date '1995-01-01' + interval '1' year").rows == [(1,)]


def test_column_plus_interval_months_clamped(cl):
    rows = cl.execute("SELECT d + interval '1' month FROM t "
                      "WHERE k < 4 ORDER BY k").rows
    assert rows == [(dt.date(1998, 10, 1),), (dt.date(1999, 1, 1),),
                    (dt.date(1995, 2, 28),)]  # Jan 31 clamps to Feb 28
    rows = cl.execute("SELECT d + interval '1 year 2 months' FROM t "
                      "WHERE k = 3").rows
    assert rows == [(dt.date(1996, 3, 31),)]


def test_timestamp_intervals(cl):
    rows = cl.execute("SELECT ts + interval '90' minute FROM t "
                      "WHERE k = 1").rows
    assert rows == [(dt.datetime(1998, 9, 1, 12, 0),)]
    rows = cl.execute("SELECT ts - interval '2 days' FROM t "
                      "WHERE k = 2").rows
    assert rows == [(dt.datetime(1998, 11, 29, 0, 0),)]


def test_constant_fold_and_null(cl):
    assert cl.execute("SELECT date '1998-12-01' - interval '90' day").rows \
        == [(dt.date(1998, 9, 2),)]
    # NULL date propagates
    assert cl.execute("SELECT d + interval '1' day FROM t WHERE k = 4").rows \
        == [(None,)]


def test_subday_interval_on_date_rejected(cl):
    with pytest.raises(UnsupportedFeatureError):
        cl.execute("SELECT d + interval '90' minute FROM t")


def test_current_date(cl):
    assert cl.execute("SELECT count(*) FROM t WHERE d < current_date").rows \
        == [(3,)]
    today = cl.execute("SELECT current_date").rows[0][0]
    assert today == dt.date.today()


def test_jax_vs_cpu(cl):
    sql = ("SELECT d + interval '3' month, count(*) FROM t "
           "WHERE d >= date '1995-01-01' GROUP BY d + interval '3' month "
           "ORDER BY 1")
    jr = cl.execute(sql).rows
    with settings_override(executor=ExecutorSettings(task_executor_backend="cpu")):
        cr = cl.execute(sql).rows
    assert jr == cr
