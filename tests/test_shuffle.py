"""all_to_all repartition shuffle tests on the 8-device mesh."""

import jax
import numpy as np
import pytest

from citus_tpu.parallel.mesh import SHARD_AXIS, default_mesh
from citus_tpu.parallel.shuffle import build_repartition, repartition_host


@pytest.fixture(scope="module")
def mesh():
    return default_mesh()


def test_repartition_roundtrip(mesh):
    n_dev = 8
    N = 256
    rng = np.random.default_rng(5)
    vals = rng.integers(0, 10**9, (n_dev, N)).astype(np.int64)
    aux = rng.integers(0, 100, (n_dev, N)).astype(np.int64)
    target = rng.integers(0, n_dev, (n_dev, N)).astype(np.int32)
    mask = rng.random((n_dev, N)) > 0.1

    run = build_repartition(mesh, n_cols=2, capacity=N)  # ample capacity
    (out_vals, out_aux), out_valid, overflow = run((vals, aux), target, mask)
    out_vals, out_aux = np.asarray(out_vals), np.asarray(out_aux)
    out_valid = np.asarray(out_valid)
    assert int(overflow) == 0

    # every row must land exactly once on its target device
    flat_vals = vals.reshape(-1)
    flat_aux = aux.reshape(-1)
    flat_target = target.reshape(-1)
    flat_mask = mask.reshape(-1)
    for d in range(n_dev):
        got = sorted(zip(out_vals[d][out_valid[d]].tolist(),
                         out_aux[d][out_valid[d]].tolist()))
        want_sel = flat_mask & (flat_target == d)
        want = sorted(zip(flat_vals[want_sel].tolist(), flat_aux[want_sel].tolist()))
        assert got == want


def test_repartition_overflow_detected(mesh):
    n_dev = 8
    N = 64
    vals = np.arange(n_dev * N, dtype=np.int64).reshape(n_dev, N)
    target = np.zeros((n_dev, N), np.int32)  # everything to device 0
    mask = np.ones((n_dev, N), bool)
    run = build_repartition(mesh, n_cols=1, capacity=8)  # way too small
    (_,), out_valid, overflow = run((vals,), target, mask)
    assert int(overflow) > 0


def test_repartition_matches_host_oracle(mesh):
    n_dev = 8
    N = 128
    rng = np.random.default_rng(9)
    vals = rng.integers(0, 1000, (n_dev, N)).astype(np.int64)
    target = (vals % n_dev).astype(np.int32)
    mask = np.ones((n_dev, N), bool)
    run = build_repartition(mesh, n_cols=1, capacity=N * 2)
    (out_vals,), out_valid, overflow = run((vals,), target, mask)
    assert int(overflow) == 0
    oracle = repartition_host((vals.reshape(-1),), target.reshape(-1),
                              mask.reshape(-1), n_dev)
    for d in range(n_dev):
        got = sorted(np.asarray(out_vals)[d][np.asarray(out_valid)[d]].tolist())
        assert got == sorted(oracle[d][0].tolist())
