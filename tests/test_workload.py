"""Tenant-aware workload scheduler: fairness invariants, shedding,
quota/isolation surface (citus_tpu/workload/).

The fairness tests drive a private SharedTaskPool + TenantScheduler pair
so global pool counters stay untouched; the SQL-surface tests go through
a real Cluster.
"""

import threading
import time

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import ExecutorSettings, Settings, WorkloadSettings
from citus_tpu.errors import AdmissionShedError, AnalysisError, ExecutionError
from citus_tpu.executor.admission import SharedTaskPool
from citus_tpu.utils.clock import set_wall_clock
from citus_tpu.workload import GLOBAL_TENANTS, TenantScheduler


def _settings(limit, **wl):
    return Settings(executor=ExecutorSettings(max_shared_pool_size=limit),
                    workload=WorkloadSettings(**wl))


@pytest.fixture(autouse=True)
def _clean_registry():
    GLOBAL_TENANTS.clear()
    yield
    GLOBAL_TENANTS.clear()
    set_wall_clock(None)


def _drive(sched, settings, tenant, stop, hold_s, latencies=None):
    while not stop.is_set():
        t0 = time.monotonic()
        sched.acquire(settings, tenant)
        try:
            time.sleep(hold_s)
        finally:
            sched.release(tenant)
        if latencies is not None:
            latencies.append(time.monotonic() - t0)


# ------------------------------------------------------------- fairness


def test_equal_weight_tenants_get_equal_share():
    """One tenant flooding 8 threads cannot monopolize: with equal
    weights every tenant's share of granted slots stays >= 1/N - 10%."""
    sched = TenantScheduler(pool=SharedTaskPool())
    st = _settings(1)
    stop = threading.Event()
    threads = []
    for i in range(8):  # the noisy tenant floods
        threads.append(threading.Thread(
            target=_drive, args=(sched, st, "noisy", stop, 0.001)))
    for t in ("a", "b", "c"):  # three polite single-thread tenants
        threads.append(threading.Thread(
            target=_drive, args=(sched, st, t, stop, 0.001)))
    for t in threads:
        t.start()
    time.sleep(0.6)
    stop.set()
    for t in threads:
        t.join()
    rows = {r[0]: r for r in sched.rows_view()}
    total = sum(r[3] for r in rows.values())
    assert total > 50
    for tenant in ("noisy", "a", "b", "c"):
        share = rows[tenant][3] / total
        assert share >= (1 / 4) - 0.10, (tenant, share, rows)


def test_weights_bias_share():
    """weight 3 vs 1 under equal demand converges toward a 3:1 split."""
    GLOBAL_TENANTS.set_quota("gold", weight=3.0)
    GLOBAL_TENANTS.set_quota("basic", weight=1.0)
    sched = TenantScheduler(pool=SharedTaskPool())
    st = _settings(1)
    stop = threading.Event()
    threads = [threading.Thread(target=_drive,
                                args=(sched, st, t, stop, 0.001))
               for t in ("gold", "basic") for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.6)
    stop.set()
    for t in threads:
        t.join()
    rows = {r[0]: r for r in sched.rows_view()}
    total = rows["gold"][3] + rows["basic"][3]
    assert total > 50
    assert rows["gold"][3] / total >= 0.60, rows
    assert rows["basic"][3] / total >= 0.10, rows


def test_noisy_neighbor_light_tenant_p99():
    """A light tenant's p99 under a flooding neighbor stays within 3x
    its isolated p99 (the headline fairness acceptance)."""
    work_s = 0.02

    def light_run(sched, st, n=15):
        lat = []
        for _ in range(n):
            t0 = time.monotonic()
            sched.acquire(st, "light")
            try:
                time.sleep(work_s)
            finally:
                sched.release("light")
            lat.append(time.monotonic() - t0)
        lat.sort()
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    # isolated baseline
    sched = TenantScheduler(pool=SharedTaskPool())
    st = _settings(1)
    p99_isolated = light_run(sched, st)

    # contended: 6 heavy threads flooding the same single slot
    sched = TenantScheduler(pool=SharedTaskPool())
    stop = threading.Event()
    heavy = [threading.Thread(target=_drive,
                              args=(sched, st, "heavy", stop, work_s))
             for _ in range(6)]
    for t in heavy:
        t.start()
    try:
        p99_contended = light_run(sched, st)
    finally:
        stop.set()
        for t in heavy:
            t.join()
    assert p99_contended <= 3 * p99_isolated + 0.01, \
        (p99_isolated, p99_contended)


def test_degenerate_single_tenant_is_fifo():
    """No quotas, default GUCs, one tenant class: grant order is strict
    arrival order, and timeout raises the pool's own error shape."""
    pool = SharedTaskPool()
    sched = TenantScheduler(pool=pool)
    st = _settings(1)
    sched.acquire(st, "*")
    order = []
    threads = []

    def waiter(i):
        sched.acquire(st, "*")
        order.append(i)
        time.sleep(0.005)
        sched.release("*")

    for i in range(3):
        t = threading.Thread(target=waiter, args=(i,))
        threads.append(t)
        t.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if any(r[0] == "*" and r[2] == i + 1 for r in sched.rows_view()):
                break
            time.sleep(0.001)
    sched.release("*")
    for t in threads:
        t.join()
    assert order == [0, 1, 2]
    assert pool.in_use == 0
    with sched.slot(st, "*"):
        with pytest.raises(ExecutionError, match="max_shared_pool_size"):
            sched.acquire(st, "other", timeout=0.05)


# ------------------------------------------------------------- shedding


def test_queue_depth_shed_is_fast_retryable_and_slotless():
    pool = SharedTaskPool()
    sched = TenantScheduler(pool=pool)
    st = _settings(1, tenant_queue_depth=2)
    sched.acquire(st, "a")  # slot holder
    threads = []
    for _ in range(2):
        t = threading.Thread(
            target=lambda: (sched.acquire(st, "a", timeout=10),
                            sched.release("a")))
        threads.append(t)
        t.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if any(r[0] == "a" and r[2] == 2 for r in sched.rows_view()):
            break
        time.sleep(0.001)
    t0 = time.monotonic()
    with pytest.raises(AdmissionShedError) as ei:
        sched.acquire(st, "a")
    assert time.monotonic() - t0 < 0.1  # fast fail, never queued
    assert ei.value.retryable is True
    assert isinstance(ei.value, ExecutionError)
    assert "tenant_queue_depth" in str(ei.value)
    assert pool.in_use == 1  # a shed query never held a slot
    sched.release("a")
    for t in threads:
        t.join()
    row = {r[0]: r for r in sched.rows_view()}["a"]
    assert row[4] == 1  # shed
    assert pool.in_use == 0


def test_rate_limit_token_bucket_shed_and_refill():
    fake = [1000.0]
    set_wall_clock(lambda: fake[0])
    sched = TenantScheduler(pool=SharedTaskPool())
    st = _settings(0, tenant_rate_limit_qps=2.0)  # burst capacity 2
    for _ in range(2):
        sched.acquire(st, "r")
        sched.release("r")
    with pytest.raises(AdmissionShedError, match="tenant_rate_limit_qps"):
        sched.acquire(st, "r")
    fake[0] += 1.0  # one second refills 2 tokens
    sched.acquire(st, "r")
    sched.release("r")
    row = {r[0]: r for r in sched.rows_view()}["r"]
    assert row[3] == 3 and row[4] == 1  # granted, shed


def test_tenant_shed_counter_bumps():
    from citus_tpu.executor.executor import GLOBAL_COUNTERS
    before = GLOBAL_COUNTERS.snapshot().get("tenant_shed", 0)
    sched = TenantScheduler(pool=SharedTaskPool())
    st = _settings(0, tenant_rate_limit_qps=1.0)
    sched.acquire(st, "x")
    sched.release("x")
    with pytest.raises(AdmissionShedError):
        sched.acquire(st, "x")
    assert GLOBAL_COUNTERS.snapshot()["tenant_shed"] == before + 1


# ------------------------------------------------------- SQL surface


def _make_cluster(tmp_path, nodes=2, **exec_kw):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=nodes,
                    settings=Settings(executor=ExecutorSettings(**exec_kw)))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={"k": np.arange(2000, dtype=np.int64) % 50,
                               "v": np.arange(2000, dtype=np.int64)})
    return cl


def test_quota_utilities_roundtrip(tmp_path):
    cl = _make_cluster(tmp_path, nodes=1)
    cl.execute("SELECT citus_add_tenant_quota('7', 2.5, 3, 10.0, 8)")
    rows = cl.execute("SELECT citus_tenant_quotas()").rows
    assert rows == [("7", 2.5, 3, 10.0, 8, None, "")]
    assert cl.execute("SELECT citus_remove_tenant_quota('7')").rows == [(True,)]
    assert cl.execute("SELECT citus_tenant_quotas()").rows == []
    cl.close()


def test_stat_tenants_live_view(tmp_path):
    cl = _make_cluster(tmp_path, nodes=1)
    cl.execute("SELECT count(*) FROM t WHERE k = 5")
    cl.execute("SELECT count(*) FROM t WHERE k = 5")
    cl.execute("SELECT sum(v) FROM t")
    view = cl.execute("SELECT citus_stat_tenants()")
    assert view.columns[:3] == ["tenant", "query_count", "total_time_ms"]
    rows = {r[0]: dict(zip(view.columns, r)) for r in view.rows}
    assert rows["5"]["query_count"] == 2
    assert rows["5"]["granted"] >= 2
    assert rows["5"]["p99_ms"] > 0
    # multi-shard analytics book under the shared "*" class
    assert rows["*"]["granted"] >= 1
    assert rows["*"]["running"] == 0 and rows["*"]["queued"] == 0
    cl.close()


def test_sql_set_tenant_gucs(tmp_path):
    cl = _make_cluster(tmp_path, nodes=1)
    cl.execute("SET citus.tenant_default_weight = 2.0")
    cl.execute("SET citus.tenant_queue_depth = 16")
    cl.execute("SET citus.tenant_rate_limit_qps = 100.0")
    assert cl.execute("SHOW citus.tenant_default_weight").rows == [("2.0",)]
    assert cl.execute("SHOW citus.tenant_queue_depth").rows == [("16",)]
    assert cl.execute("SHOW citus.tenant_rate_limit_qps").rows == [("100.0",)]
    cl.close()


def test_isolate_tenant_to_node(tmp_path):
    cl = _make_cluster(tmp_path, nodes=2)
    before = cl.execute("SELECT count(*), sum(v) FROM t WHERE k = 7").rows
    nodes = cl.catalog.active_node_ids()
    target = nodes[-1]
    r = cl.execute(f"SELECT citus_isolate_tenant_to_node('t', 7, {target})")
    shard_id = r.rows[0][0]
    t = cl.catalog.table("t")
    shard = next(s for s in t.shards if s.shard_id == shard_id)
    assert shard.placements == [target]
    # the isolated shard holds exactly the tenant's hash range
    assert cl.execute("SELECT count(*), sum(v) FROM t WHERE k = 7").rows \
        == before
    quotas = {r[0]: r for r in cl.execute("SELECT citus_tenant_quotas()").rows}
    assert quotas["7"][5] == target  # pinned_node recorded
    with pytest.raises(AnalysisError):
        cl.execute("SELECT citus_isolate_tenant_to_node('t', 7, 99)")
    cl.close()


def test_shed_error_surfaces_through_sql(tmp_path):
    cl = _make_cluster(tmp_path, nodes=1)
    cl.execute("SET citus.tenant_rate_limit_qps = 1.0")
    cl.execute("SELECT count(*) FROM t WHERE k = 3")
    with pytest.raises(AdmissionShedError, match="retry after backoff"):
        cl.execute("SELECT count(*) FROM t WHERE k = 3")
    cl.close()
