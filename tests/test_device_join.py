"""Device-side repartition hash join (round-2 gap #3).

The all_to_all exchange AND the per-bucket join both run on the mesh:
one fused jitted collective packs both relations by join-group bucket,
exchanges them, and sort-joins per device; the host sees a single fetch
of joined columns (parallel/shuffle.py build_repartition_join).
Reference: MapMergeJob map+merge (multi_physical_planner.h:160) executed
in dependency order (directed_acyclic_graph_execution.c:57)."""

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import PlannerSettings, Settings


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("""CREATE TABLE a (a_id bigint NOT NULL, a_k bigint,
        a_k2 bigint, a_v bigint)""")
    cl.execute("""CREATE TABLE b (b_id bigint NOT NULL, b_k bigint,
        b_k2 bigint, b_v bigint)""")
    cl.execute("SELECT create_distributed_table('a', 'a_id', 4)")
    cl.execute("SELECT create_distributed_table('b', 'b_id', 4)")
    rng = np.random.default_rng(11)
    na, nb = 3000, 5000
    cl.copy_from("a", columns={
        "a_id": np.arange(na),
        "a_k": rng.integers(0, 400, na),       # duplicates on both sides
        "a_k2": rng.integers(0, 3, na),
        "a_v": rng.integers(0, 100, na)})
    cl.copy_from("b", columns={
        "b_id": np.arange(nb),
        "b_k": rng.integers(0, 500, nb),       # some unmatched
        "b_k2": rng.integers(0, 3, nb),
        "b_v": rng.integers(0, 100, nb)})
    yield cl
    cl.close()


def assert_matches_pull(db, tmp_path, sql):
    r = db.execute(sql)
    assert r.explain["strategy"] == "join:repartition", r.explain
    assert "devjoin" in r.explain["shuffle"], r.explain
    pull = ct.Cluster(str(tmp_path / "db"), settings=Settings(
        planner=PlannerSettings(enable_repartition_joins=False)))
    try:
        r2 = pull.execute(sql)
        assert r2.explain["strategy"] == "join:pull"
        assert r.rows == r2.rows, (r.rows[:5], r2.rows[:5])
    finally:
        pull.close()
    return r


def test_many_to_many_inner(db, tmp_path):
    """Duplicate keys on both sides: every pair must appear exactly once."""
    assert_matches_pull(db, tmp_path, """
        SELECT count(*), sum(a.a_v + b.b_v)
        FROM a JOIN b ON a.a_k = b.b_k""")


def test_multi_key_join(db, tmp_path):
    """Two join keys — dense gid assignment covers key tuples exactly."""
    assert_matches_pull(db, tmp_path, """
        SELECT count(*), sum(a.a_v)
        FROM a JOIN b ON a.a_k = b.b_k AND a.a_k2 = b.b_k2""")


def test_residual_condition(db, tmp_path):
    """Non-equi residual applies after the device join."""
    assert_matches_pull(db, tmp_path, """
        SELECT count(*)
        FROM a JOIN b ON a.a_k = b.b_k AND a.a_v < b.b_v""")


def test_projection_order(db, tmp_path):
    assert_matches_pull(db, tmp_path, """
        SELECT a.a_id, b.b_id FROM a JOIN b ON a.a_k = b.b_k
        ORDER BY a.a_id, b.b_id LIMIT 50""")


def test_group_by_after_device_join(db, tmp_path):
    assert_matches_pull(db, tmp_path, """
        SELECT a.a_k2, count(*), sum(b.b_v)
        FROM a JOIN b ON a.a_k = b.b_k
        GROUP BY a.a_k2 ORDER BY a.a_k2""")


def test_empty_side(db, tmp_path):
    """Inner join against an always-false-filtered side is empty."""
    r = db.execute("""SELECT count(*) FROM a
        JOIN b ON a.a_k = b.b_k WHERE b.b_v < 0""")
    assert r.rows[0][0] == 0


def test_outer_falls_back_to_bucket_path(db, tmp_path):
    """LEFT JOIN is not device-joinable; the bucket path handles it and
    the result still matches pull."""
    r = db.execute("""SELECT count(*), sum(a.a_v)
        FROM a LEFT JOIN b ON a.a_k = b.b_k""")
    assert r.explain["strategy"] == "join:repartition"
    assert "devjoin" not in r.explain["shuffle"]
    pull = ct.Cluster(str(tmp_path / "db"), settings=Settings(
        planner=PlannerSettings(enable_repartition_joins=False)))
    try:
        assert r.rows == pull.execute("""SELECT count(*), sum(a.a_v)
            FROM a LEFT JOIN b ON a.a_k = b.b_k""").rows
    finally:
        pull.close()


def test_sorted_join_indexes_unit():
    """Direct unit test of the per-device sort join index math."""
    import jax.numpy as jnp
    from citus_tpu.parallel.shuffle import _sorted_join_indexes
    lgid = jnp.array([5, 2, 2, 9, 2, 7], dtype=jnp.int64)
    lvalid = jnp.array([True, True, True, False, True, True])
    rgid = jnp.array([2, 7, 7, 3, 9], dtype=jnp.int64)
    rvalid = jnp.array([True, True, True, True, True])
    li, ri, ov, total = _sorted_join_indexes(lgid, lvalid, rgid, rvalid, 8)
    li, ri, ov = np.asarray(li), np.asarray(ri), np.asarray(ov)
    got = sorted((int(l), int(r)) for l, r, v in zip(li, ri, ov) if v)
    # gid 2: left {1,2,4} x right {0}; gid 7: left {5} x right {1,2};
    # gid 9 right row 4 matches nothing (left row 3 invalid)
    assert got == [(1, 0), (2, 0), (4, 0), (5, 1), (5, 2)]
    assert int(total) == 5
