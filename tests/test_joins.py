"""Join tests vs sqlite oracle: colocated, broadcast (reference), and
pull (repartition fallback) strategies; inner/left/right/full/cross."""

import decimal
import sqlite3

import numpy as np
import pytest

import citus_tpu as ct


@pytest.fixture(scope="module")
def db(tmp_path_factory):
    cl = ct.Cluster(str(tmp_path_factory.mktemp("jdb")))
    cl.execute("CREATE TABLE orders (o_orderkey bigint NOT NULL, o_custkey bigint, o_total decimal(12,2), o_status text)")
    cl.execute("CREATE TABLE lineitem (l_orderkey bigint NOT NULL, l_qty bigint, l_price decimal(12,2))")
    cl.execute("CREATE TABLE nation (n_id bigint, n_name text)")
    cl.execute("SELECT create_distributed_table('orders', 'o_orderkey', 4)")
    cl.execute("SELECT create_distributed_table('lineitem', 'l_orderkey', 4)")
    cl.execute("SELECT create_reference_table('nation')")

    rng = np.random.default_rng(3)
    n_orders, n_lines = 400, 1500
    orders = [(i, int(rng.integers(0, 25)), round(float(rng.integers(100, 99999)) / 100, 2),
               ["O", "F", "P"][int(rng.integers(0, 3))]) for i in range(n_orders)]
    lines = [(int(rng.integers(0, n_orders + 50)), int(rng.integers(1, 50)),
              round(float(rng.integers(100, 9999)) / 100, 2)) for _ in range(n_lines)]
    nations = [(i, f"nation_{i}") for i in range(25)]
    cl.copy_from("orders", rows=orders)
    cl.copy_from("lineitem", rows=lines)
    cl.copy_from("nation", rows=nations)

    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE orders (o_orderkey INTEGER, o_custkey INTEGER, o_total REAL, o_status TEXT)")
    sq.execute("CREATE TABLE lineitem (l_orderkey INTEGER, l_qty INTEGER, l_price REAL)")
    sq.execute("CREATE TABLE nation (n_id INTEGER, n_name TEXT)")
    sq.executemany("INSERT INTO orders VALUES (?,?,?,?)", orders)
    sq.executemany("INSERT INTO lineitem VALUES (?,?,?)", lines)
    sq.executemany("INSERT INTO nation VALUES (?,?)", nations)
    return cl, sq


def canon(rows):
    out = []
    for r in rows:
        out.append(tuple(
            round(float(v), 4) if isinstance(v, (decimal.Decimal, float)) else v
            for v in r))
    return out


JOIN_QUERIES = [
    # colocated: dist = dist on their dist columns
    "SELECT count(*) FROM orders o JOIN lineitem l ON o.o_orderkey = l.l_orderkey",
    "SELECT o.o_status, count(*), sum(l.l_price) FROM orders o "
    "JOIN lineitem l ON o.o_orderkey = l.l_orderkey GROUP BY o.o_status ORDER BY o.o_status",
    "SELECT count(*) FROM orders o LEFT JOIN lineitem l ON o.o_orderkey = l.l_orderkey",
    # broadcast: dist joins reference
    "SELECT n.n_name, count(*) FROM orders o JOIN nation n ON o.o_custkey = n.n_id "
    "GROUP BY n.n_name ORDER BY n.n_name LIMIT 5",
    "SELECT count(*) FROM orders o LEFT JOIN nation n ON o.o_custkey = n.n_id WHERE n.n_id IS NULL",
    # pull: equi-join on non-distribution columns
    "SELECT count(*) FROM orders a JOIN orders b ON a.o_custkey = b.o_custkey",
    # filters pushed below the join
    "SELECT count(*) FROM orders o JOIN lineitem l ON o.o_orderkey = l.l_orderkey "
    "WHERE o.o_total > 500 AND l.l_qty < 10",
    # three-way
    "SELECT n.n_name, sum(l.l_price) FROM orders o "
    "JOIN lineitem l ON o.o_orderkey = l.l_orderkey "
    "JOIN nation n ON o.o_custkey = n.n_id "
    "GROUP BY n.n_name ORDER BY n.n_name LIMIT 6",
    # non-agg projection join with order/limit
    "SELECT o.o_orderkey, l.l_qty FROM orders o JOIN lineitem l "
    "ON o.o_orderkey = l.l_orderkey WHERE o.o_orderkey < 5 "
    "ORDER BY o.o_orderkey, l.l_qty LIMIT 20",
    # residual non-equi condition
    "SELECT count(*) FROM orders o JOIN lineitem l "
    "ON o.o_orderkey = l.l_orderkey AND l.l_qty > 25",
    # cross join (small)
    "SELECT count(*) FROM nation a CROSS JOIN nation b",
]


@pytest.mark.parametrize("sql", JOIN_QUERIES)
def test_join_vs_sqlite(db, sql):
    cl, sq = db
    ours = canon(cl.execute(sql).rows)
    theirs = canon(sq.execute(sql).fetchall())
    if "ORDER BY" not in sql:
        ours, theirs = sorted(ours, key=repr), sorted(theirs, key=repr)
    assert ours == theirs


def test_join_strategies_chosen(db):
    cl, _ = db
    from citus_tpu.planner import parse_sql
    from citus_tpu.planner.join_planner import bind_join_select
    colo = bind_join_select(cl.catalog, parse_sql(
        "SELECT count(*) FROM orders o JOIN lineitem l ON o.o_orderkey = l.l_orderkey")[0])
    assert colo.strategy == "colocated"
    bcast = bind_join_select(cl.catalog, parse_sql(
        "SELECT count(*) FROM orders o JOIN nation n ON o.o_custkey = n.n_id")[0])
    assert bcast.strategy == "colocated"  # reference side replicated
    repart = bind_join_select(cl.catalog, parse_sql(
        "SELECT count(*) FROM orders a JOIN orders b ON a.o_custkey = b.o_custkey")[0])
    assert repart.strategy == "repartition"  # non-dist-key equi self-join


def test_full_outer_join(db):
    cl, sq = db
    sql = ("SELECT count(*) FROM orders o FULL OUTER JOIN lineitem l "
           "ON o.o_orderkey = l.l_orderkey")
    ours = cl.execute(sql).rows
    import sqlite3 as _sq3
    if _sq3.sqlite_version_info >= (3, 39):
        theirs = sq.execute(sql).fetchall()
    else:  # old sqlite: FULL OUTER = left join + unmatched right rows
        left = sq.execute(
            "SELECT count(*) FROM orders o LEFT JOIN lineitem l "
            "ON o.o_orderkey = l.l_orderkey").fetchall()[0][0]
        anti = sq.execute(
            "SELECT count(*) FROM lineitem l WHERE NOT EXISTS "
            "(SELECT 1 FROM orders o WHERE o.o_orderkey = l.l_orderkey)"
        ).fetchall()[0][0]
        theirs = [(left + anti,)]
    assert canon(ours) == canon(theirs)


def test_right_join(db):
    cl, sq = db
    sql = ("SELECT count(*) FROM lineitem l RIGHT JOIN orders o "
           "ON o.o_orderkey = l.l_orderkey")
    import sqlite3 as _sq3
    oracle_sql = sql if _sq3.sqlite_version_info >= (3, 39) else (
        # old sqlite: a RIGHT JOIN is the swapped LEFT JOIN
        "SELECT count(*) FROM orders o LEFT JOIN lineitem l "
        "ON o.o_orderkey = l.l_orderkey")
    assert canon(cl.execute(sql).rows) == canon(sq.execute(oracle_sql).fetchall())


def test_qualified_star_and_ambiguity(db):
    cl, _ = db
    r = cl.execute("SELECT * FROM orders o JOIN nation n ON o.o_custkey = n.n_id LIMIT 1")
    assert len(r.columns) == 6
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError):
        cl.execute("SELECT o_orderkey FROM orders o JOIN lineitem l ON o.o_orderkey = l.l_orderkey "
                   "JOIN orders o2 ON o2.o_orderkey = l.l_orderkey")


def test_join_order_by_non_output(db):
    cl, sq = db
    sql = ("SELECT o.o_orderkey FROM orders o JOIN lineitem l "
           "ON o.o_orderkey = l.l_orderkey WHERE o.o_orderkey < 30 "
           "ORDER BY l.l_qty, o.o_orderkey LIMIT 10")
    ours = cl.execute(sql)
    theirs = sq.execute(sql).fetchall()
    assert ours.columns == ["o_orderkey"]
    assert ours.rows == [tuple(r) for r in theirs]
