"""Fused single-dispatch hot loop: decode→skip→filter→partial-agg in
one kernel round with device-resident donated accumulators.

Covers the PR's acceptance surface:
- exactly ONE fused dispatch per batch and ZERO merge/worker kernel
  slots on the single-device path, counter-asserted;
- the fused path is the default and byte-identical to the staged CPU
  worker (task_executor_backend = 'cpu') on the same data;
- chunk-skipping admits/refutes stripe chunks from footer min/max
  BEFORE their streams are read (fused_rows_skipped counts the rows);
- streaming (uncached) peak device window stays ≤ 2× batch bytes with
  double-buffering on, and nothing is pinned past the HBM cache cap;
- uuid dictionary bypass: high-cardinality uuid ingest keeps the
  dictionary side file flat while text grows linearly, and uuid
  filters/group-bys stay oracle-identical across backends.
"""

import os
import uuid as _uuid

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.executor.executor import GLOBAL_COUNTERS
from citus_tpu.executor.device_cache import GLOBAL_CACHE
from citus_tpu.executor.kernel_cache import GLOBAL_KERNELS


@pytest.fixture()
def cl(tmp_path):
    return ct.Cluster(str(tmp_path / "db"))


@pytest.fixture()
def one_device(monkeypatch):
    """Pin the executor to the single-device path: the harness forces 8
    virtual host devices (conftest), which routes multi-batch scans to
    the mesh; the fused donated-accumulator loop is the single-device
    hot path, so these tests narrow jax.devices() to one."""
    import jax
    real = jax.devices()
    monkeypatch.setattr(jax, "devices", lambda *a, **k: real[:1])
    return real[0]


def _fill(cl, n=4096, shards=4):
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, s text)")
    cl.execute(f"SELECT create_distributed_table('t', 'k', {shards})")
    cl.copy_from("t", columns={
        "k": np.arange(n),
        "v": np.arange(n) % 97,
        "s": [f"g{i % 7}" for i in range(n)]})


def _delta(c0, c1, name):
    return c1[name] - c0[name]


def test_one_fused_dispatch_per_batch_zero_merges(cl, one_device):
    _fill(cl)
    GLOBAL_KERNELS.clear()
    c0 = cl.counters.snapshot()
    r = cl.execute("SELECT count(*), sum(v), min(v), max(v) FROM t")
    c1 = cl.counters.snapshot()
    batches = len(r.explain["tasks"])
    assert batches >= 1
    # ONE kernel round per batch: the merge rides inside the dispatch
    assert _delta(c0, c1, "fused_dispatches") == batches
    assert r.explain["pipeline"]["fused_dispatches"] == batches
    slots = {k[1] for k in GLOBAL_KERNELS._e}
    assert "jit_fused" in slots
    assert "jit_merge" not in slots and "jit_worker" not in slots
    v = np.arange(4096) % 97
    assert r.rows == [(4096, int(v.sum()), 0, 96)]


def test_fused_default_matches_staged_cpu_backend(cl, one_device):
    _fill(cl)
    queries = [
        "SELECT count(*), sum(v), min(v), max(v), avg(v) FROM t",
        "SELECT s, count(*), sum(v) FROM t GROUP BY s ORDER BY s",
        "SELECT count(*) FROM t WHERE v < 13 AND k >= 100",
    ]
    c0 = cl.counters.snapshot()
    fused = [cl.execute(q).rows for q in queries]
    c1 = cl.counters.snapshot()
    assert _delta(c0, c1, "fused_dispatches") > 0
    # A/B against the staged host worker: byte-identical results, zero
    # fused dispatches, and no new pipeline host stalls on that path
    cl.execute("SET citus.task_executor_backend = 'cpu'")
    c2 = cl.counters.snapshot()
    staged = [cl.execute(q).rows for q in queries]
    c3 = cl.counters.snapshot()
    cl.execute("SET citus.task_executor_backend = 'tpu'")
    assert fused == staged
    assert _delta(c2, c3, "fused_dispatches") == 0
    assert _delta(c2, c3, "pipeline_host_stalls") == 0


def test_chunk_skip_refutes_rows_before_decode(cl):
    # k is the sort-friendly column: each chunk's footer min/max covers
    # a disjoint range, so a tight predicate refutes most chunks before
    # any of their streams are read or decompressed
    cl.execute("CREATE TABLE big (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('big', 'k', 1)")
    n = 40_000
    cl.copy_from("big", columns={"k": np.arange(n), "v": np.arange(n)})
    c0 = cl.counters.snapshot()
    r = cl.execute("SELECT count(*), sum(v) FROM big WHERE k < 100")
    c1 = cl.counters.snapshot()
    assert r.rows == [(100, sum(range(100)))]
    skipped = _delta(c0, c1, "fused_rows_skipped")
    assert skipped > 0
    assert skipped + 100 <= n
    assert _delta(c0, c1, "chunks_selected") < _delta(c0, c1, "chunks_total")


def test_streaming_peak_window_bounded_by_double_buffer(cl, one_device):
    _fill(cl, n=8192, shards=4)
    # force the streaming path: an HBM cache too small to pin the scan
    old_cap = GLOBAL_CACHE.capacity
    GLOBAL_CACHE.clear()
    GLOBAL_CACHE.capacity = 1
    cl.execute("SET citus.executor_prefetch_depth = 1")
    cl.execute("SET citus.max_tasks_in_flight = 1")
    try:
        r = cl.execute("EXPLAIN ANALYZE SELECT sum(v), count(*) FROM t")
        text = "\n".join(l for (l,) in r.rows)
        assert "fused dispatches" in text
        import re
        m = re.search(r"stream window peak (\d+) bytes", text)
        h = re.search(r"H2D (\d+) bytes", text)
        d = re.search(r"fused dispatches (\d+)", text)
        assert m and h and d
        peak, h2d, nd = int(m.group(1)), int(h.group(1)), int(d.group(1))
        assert nd >= 2
        # uniform shards -> uniform batches: with depth 1 the un-synced
        # device window never holds more than 2× one batch's bytes
        batch_bytes = h2d / nd
        assert peak <= 2 * batch_bytes
        # nothing was pinned past the cap
        mv = GLOBAL_CACHE.memory_view()
        assert mv["live_bytes"] == 0
    finally:
        GLOBAL_CACHE.capacity = old_cap


def test_device_memory_ledger_visible_through_udf(cl):
    _fill(cl)
    cl.execute("SELECT count(*) FROM t")
    rows = cl.execute("SELECT citus_device_memory()").rows
    assert rows  # ledger renders; live/high-water accounted
    r = cl.execute("EXPLAIN ANALYZE SELECT count(*) FROM t")
    text = "\n".join(l for (l,) in r.rows)
    assert "Memory:" in text and "HBM bytes touched" in text


# ------------------------------------------------- uuid dictionary bypass


def test_uuid_high_cardinality_keeps_dictionary_flat(cl):
    n = 5000
    cl.execute("CREATE TABLE ud (k bigint NOT NULL, u uuid, s text)")
    cl.execute("SELECT create_distributed_table('ud', 'k', 2)")
    uuids = [str(_uuid.UUID(int=i * 2654435761 % (1 << 128)))
             for i in range(n)]
    cl.copy_from("ud", columns={
        "k": np.arange(n), "u": uuids,
        "s": [f"w{i}" for i in range(n)]})
    cat = cl.catalog
    # text column: dictionary grows linearly with distinct words
    cat._ensure_dict("ud", "s")
    assert len(cat._dicts[("ud", "s")]) == n
    # uuid column: fixed-width lane encoding — NO dictionary at all,
    # neither in memory nor as a side file (size stays flat at zero no
    # matter how many distinct uuids are ingested)
    assert ("ud", "u") not in cat._dicts
    assert not os.path.exists(cat._dict_path("ud", "u"))


def test_uuid_filter_and_groupby_oracle_identical(cl):
    n = 600
    cl.execute("CREATE TABLE ug (k bigint NOT NULL, u uuid, v bigint)")
    cl.execute("SELECT create_distributed_table('ug', 'k', 2)")
    pool = [str(_uuid.UUID(int=(7919 * i) % (1 << 128))) for i in range(7)]
    us = [pool[i % 7] for i in range(n)]
    cl.copy_from("ug", columns={
        "k": np.arange(n), "u": us, "v": np.arange(n) % 11})
    target = pool[3]
    q_eq = f"SELECT count(*), sum(v) FROM ug WHERE u = '{target}'"
    q_gb = "SELECT u, count(*) FROM ug GROUP BY u ORDER BY u"
    a = (cl.execute(q_eq).rows, cl.execute(q_gb).rows)
    cl.execute("SET citus.task_executor_backend = 'cpu'")
    b = (cl.execute(q_eq).rows, cl.execute(q_gb).rows)
    cl.execute("SET citus.task_executor_backend = 'tpu'")
    assert a == b
    # and against the plain python oracle
    want = sum(1 for x in us if x == target)
    assert a[0][0][0] == want
    assert sorted(r[0] for r in a[1]) == sorted(set(us))
