"""Set-returning FROM functions + the run_command_on_* admin surface.

Reference: PostgreSQL SRFs in FROM (materialized here through the
recursive-planning temp-table seam); operations/citus_tools.c
run_command_on_workers/_shards/_placements;
operations/node_protocol.c master_get_table_ddl_events.
"""

import pytest

import citus_tpu as ct
from citus_tpu.errors import ExecutionError, UnsupportedFeatureError


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"))
    c.execute("CREATE TABLE t (k bigint NOT NULL, v decimal(10,2))")
    c.execute("SELECT create_distributed_table('t','k',4)")
    c.copy_from("t", rows=[(i, round(i / 3, 2)) for i in range(100)])
    return c


def test_generate_series_basic(cl):
    assert cl.execute("SELECT * FROM generate_series(1, 5) g").rows \
        == [(1,), (2,), (3,), (4,), (5,)]
    assert cl.execute(
        "SELECT g FROM generate_series(10, 2, -3) AS g").rows \
        == [(10,), (7,), (4,)]
    assert cl.execute("SELECT count(*) FROM generate_series(5, 1)").rows \
        == [(0,)]
    with pytest.raises(ExecutionError):
        cl.execute("SELECT * FROM generate_series(1, 5, 0)")


def test_generate_series_join_and_agg(cl):
    rows = cl.execute("SELECT g, v FROM generate_series(0, 3) g "
                      "JOIN t ON t.k = g ORDER BY g").rows
    assert [r[0] for r in rows] == [0, 1, 2, 3]
    assert cl.execute("SELECT sum(g) FROM generate_series(1, 100) g "
                      "WHERE g % 7 = 0").rows == [(735,)]


def test_run_command_on_workers(cl):
    rows = cl.execute(
        "SELECT run_command_on_workers('SELECT count(*) FROM t')").rows
    assert len(rows) == len(cl.catalog.active_node_ids())
    assert all(ok and res == "100" for _n, ok, res in rows)


def test_run_command_on_shards_partitions_the_count(cl):
    rows = cl.execute("SELECT run_command_on_shards('t', "
                      "'SELECT count(*) FROM %s')").rows
    assert len(rows) == 4
    assert all(ok for _s, ok, _r in rows)
    assert sum(int(r) for _s, _ok, r in rows) == 100


def test_run_command_on_placements(cl):
    rows = cl.execute("SELECT run_command_on_placements('t', "
                      "'SELECT count(*) FROM %s')").rows
    assert all(len(r) == 4 for r in rows)
    assert sum(int(r[3]) for r in rows) == 100


def test_run_command_on_shards_rejects_ddl(cl):
    with pytest.raises(UnsupportedFeatureError):
        cl.execute("SELECT run_command_on_shards('t', 'DROP TABLE %s')")


def test_master_get_table_ddl_events_round_trips(cl, tmp_path):
    ddl = [r[0] for r in
           cl.execute("SELECT master_get_table_ddl_events('t')").rows]
    c2 = ct.Cluster(str(tmp_path / "db2"))
    for stmt in ddl:
        c2.execute(stmt)
    t2 = c2.catalog.table("t")
    assert t2.is_distributed and t2.dist_column == "k"
    assert t2.schema.names == ["k", "v"]


def test_ddl_events_include_fks(cl):
    cl.execute("CREATE TABLE child (k bigint NOT NULL REFERENCES t (k) "
               "ON DELETE CASCADE)")
    ddl = [r[0] for r in
           cl.execute("SELECT master_get_table_ddl_events('child')").rows]
    assert any("FOREIGN KEY (k) REFERENCES t (k) ON DELETE CASCADE" in d
               for d in ddl)


def test_gpid_and_coordinator(cl):
    assert cl.execute("SELECT citus_backend_gpid()").rows[0][0] > 0
    assert cl.execute("SELECT citus_coordinator_nodeid()").rows[0][0] == 0


def test_review_regressions(cl):
    # exact integer mod past 2^53
    assert cl.execute("SELECT mod(100000000000000001, 3)").rows == [(2,)]
    # NULL generate_series bound -> zero rows (PostgreSQL)
    assert cl.execute("SELECT * FROM generate_series(1, NULL) g").rows == []
    # unknown zero-arg function -> clean error, not IndexError
    with pytest.raises(UnsupportedFeatureError):
        cl.execute("SELECT totally_unknown_fn()")
    # per-shard rows survive WHERE pruning; all 4 shards reported
    rows = cl.execute("SELECT run_command_on_shards('t', "
                      "'SELECT count(*) FROM %s WHERE k = 5')").rows
    assert len(rows) == 4 and sum(int(r[2]) for r in rows) == 1
    # command must target the named table
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError):
        cl.execute("SELECT run_command_on_shards('t', "
                   "'SELECT 1 FROM generate_series(1,2) g')")


def test_constant_math_without_from(cl):
    r = cl.execute("SELECT sqrt(-1), power(2, 10), mod(17, 5), "
                   "greatest(1, NULL, 3), round(2.675, 2)").rows[0]
    assert r[0] is None
    assert r[1] == 1024.0
    assert r[2] == 2
    assert r[3] == 3
    assert float(r[4]) == pytest.approx(2.68)


def test_create_table_as_select(tmp_path):
    """CTAS: schema inferred from the result; distributable after."""
    import citus_tpu as ct
    from citus_tpu.errors import CatalogError
    cl = ct.Cluster(str(tmp_path / "ctas"))
    cl.execute("CREATE TABLE src (k bigint, v decimal(10,2), s text)")
    cl.execute("SELECT create_distributed_table('src', 'k', 4)")
    cl.copy_from("src", rows=[(i, i / 4, ["a", "b"][i % 2])
                              for i in range(100)])
    r = cl.execute("CREATE TABLE agg AS SELECT s, count(*) AS n, "
                   "sum(v) AS total FROM src GROUP BY s")
    assert r.explain["selected"] == 2
    t = cl.catalog.table("agg")
    assert t.schema.names == ["s", "n", "total"]
    assert sorted(cl.execute("SELECT s, n FROM agg").rows) == \
        [("a", 50), ("b", 50)]
    # totals survived the round trip exactly
    assert cl.execute("SELECT sum(total) FROM agg").rows == \
        cl.execute("SELECT sum(v) FROM src").rows
    with pytest.raises(CatalogError):
        cl.execute("CREATE TABLE agg AS SELECT 1 AS one")
    cl.execute("CREATE TABLE IF NOT EXISTS agg AS SELECT 1 AS one")  # no-op
    # CTAS over a set operation / computed projection
    cl.execute("CREATE TABLE u AS SELECT k FROM src WHERE k < 3 "
               "UNION SELECT k + 100 FROM src WHERE k < 2")
    assert cl.execute("SELECT count(*) FROM u").rows == [(5,)]
    # the new table is an ordinary table: index it
    cl.execute("CREATE UNIQUE INDEX u_k ON u (k)")


def test_ctas_in_transaction_and_atomicity(tmp_path):
    import citus_tpu as ct
    from citus_tpu.errors import UnsupportedFeatureError
    cl = ct.Cluster(str(tmp_path / "ctas2"))
    cl.execute("CREATE TABLE src (k bigint, s text)")
    cl.copy_from("src", rows=[(1, "a"), (2, "b")])
    # CTAS inside a transaction block stages and rolls back cleanly
    s = cl.session()
    s.execute("BEGIN")
    s.execute("CREATE TABLE snap AS SELECT * FROM src")
    assert s.execute("SELECT count(*) FROM snap").rows == [(2,)]
    s.execute("ROLLBACK")
    assert not cl.catalog.has_table("snap")
    s.execute("BEGIN")
    s.execute("CREATE TABLE snap AS SELECT * FROM src")
    s.execute("COMMIT")
    assert cl.execute("SELECT count(*) FROM snap").rows == [(2,)]
    # empty untyped result (window output) refuses to guess a schema
    with pytest.raises(UnsupportedFeatureError, match="empty result"):
        cl.execute("CREATE TABLE w AS SELECT s, row_number() OVER "
                   "(ORDER BY k) AS rn FROM src WHERE k < 0")
    assert not cl.catalog.has_table("w")


def test_copy_query_to(tmp_path):
    import citus_tpu as ct
    cl = ct.Cluster(str(tmp_path / "cq"))
    cl.execute("CREATE TABLE t (k bigint, s text)")
    cl.copy_from("t", rows=[(1, "a"), (2, None), (3, "c")])
    out = str(tmp_path / "out.csv")
    r = cl.execute(f"COPY (SELECT k, s FROM t WHERE k > 1 ORDER BY k) "
                   f"TO '{out}' WITH (header 'true', null 'NULL')")
    assert r.explain["copied"] == 2
    lines = open(out).read().splitlines()
    assert lines == ["k,s", "2,NULL", "3,c"]


def test_create_or_replace_view_and_truncate_list(tmp_path):
    import citus_tpu as ct
    from citus_tpu.errors import CatalogError
    cl = ct.Cluster(str(tmp_path / "orv"))
    cl.execute("CREATE TABLE t (k bigint, v bigint)")
    cl.execute("CREATE TABLE u (k bigint)")
    cl.copy_from("t", rows=[(1, 10), (2, 20)])
    cl.copy_from("u", rows=[(9,)])
    cl.execute("CREATE VIEW big AS SELECT k FROM t WHERE v > 15")
    assert cl.execute("SELECT count(*) FROM big").rows == [(1,)]
    with pytest.raises(CatalogError):
        cl.execute("CREATE VIEW big AS SELECT k FROM t")
    cl.execute("CREATE OR REPLACE VIEW big AS SELECT k FROM t WHERE v > 5")
    assert cl.execute("SELECT count(*) FROM big").rows == [(2,)]
    # OR REPLACE cannot clobber a table
    with pytest.raises(CatalogError):
        cl.execute("CREATE OR REPLACE VIEW t AS SELECT 1")
    cl.execute("TRUNCATE t, u")
    assert cl.execute("SELECT count(*) FROM t").rows == [(0,)]
    assert cl.execute("SELECT count(*) FROM u").rows == [(0,)]


def test_replace_view_guards_and_truncate_atomicity(tmp_path):
    import citus_tpu as ct
    from citus_tpu.errors import AnalysisError, CatalogError
    cl = ct.Cluster(str(tmp_path / "rvg"))
    cl.execute("CREATE TABLE t (k bigint, v bigint)")
    cl.copy_from("t", rows=[(1, 10)])
    cl.execute("CREATE VIEW w AS SELECT k, v FROM t")
    # self-reference rejected (would recurse forever at use)
    with pytest.raises(AnalysisError, match="itself"):
        cl.execute("CREATE OR REPLACE VIEW w AS SELECT k FROM w")
    # dropping/renaming columns rejected (PG rule); appending allowed
    with pytest.raises(AnalysisError, match="drop, rename"):
        cl.execute("CREATE OR REPLACE VIEW w AS SELECT k FROM t")
    cl.execute("CREATE OR REPLACE VIEW w AS SELECT k, v, k + v AS s FROM t")
    assert cl.execute("SELECT s FROM w").rows == [(11,)]
    # multi-table TRUNCATE is validated up front: a bad name empties
    # nothing
    with pytest.raises(CatalogError):
        cl.execute("TRUNCATE t, no_such_table")
    assert cl.execute("SELECT count(*) FROM t").rows == [(1,)]
    # parent+child in one list is allowed (PG) while parent alone is not
    cl.execute("CREATE TABLE p (id bigint)")
    cl.execute("CREATE TABLE c (id bigint REFERENCES p (id))")
    cl.copy_from("p", rows=[(1,)])
    with pytest.raises(AnalysisError, match="referenced"):
        cl.execute("TRUNCATE p")
    cl.execute("TRUNCATE p, c")
    assert cl.execute("SELECT count(*) FROM p").rows == [(0,)]


def test_indirect_view_cycle_errors_cleanly(tmp_path):
    import citus_tpu as ct
    from citus_tpu.errors import AnalysisError
    cl = ct.Cluster(str(tmp_path / "cyc"))
    cl.execute("CREATE TABLE t (k bigint)")
    cl.copy_from("t", rows=[(1,)])
    cl.execute("CREATE VIEW w AS SELECT k FROM t")
    cl.execute("CREATE VIEW v2 AS SELECT k FROM w")
    # indirect cycle: w -> v2 -> w passes the FROM-level guard but must
    # fail with a clean error at use, not a RecursionError
    cl.execute("CREATE OR REPLACE VIEW w AS SELECT k FROM v2")
    with pytest.raises(AnalysisError, match="nesting too deep"):
        cl.execute("SELECT * FROM w")
    # CTE shadowing the view name is legal (PostgreSQL)
    cl.execute("CREATE VIEW shadow AS SELECT k FROM t")
    cl.execute("CREATE OR REPLACE VIEW shadow AS "
               "WITH shadow AS (SELECT 7 AS k) SELECT k FROM shadow")
    assert cl.execute("SELECT k FROM shadow").rows == [(7,)]
    # type changes on replace are rejected
    cl.execute("CREATE VIEW ty AS SELECT k FROM t")
    with pytest.raises(AnalysisError, match="data type"):
        cl.execute("CREATE OR REPLACE VIEW ty AS SELECT k / 2.0 AS k FROM t")
