"""Remote task-execution subsystem (executor/worker_tasks.py + the
execute_task RPC): the worker half of a SELECT plan ships to the shard's
owning host, runs through that host's own batch pipeline, and only
partial-aggregate / result rows come back — versus the sync_placement
pull path that mirrors whole placements over the wire.

Reference: worker_sql_task_protocol.c (worker-side task execution) and
the adaptive executor's task-push model (adaptive_executor.c).
"""

import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import (
    CatalogError, TransactionError, UnsupportedFeatureError,
)
from citus_tpu.executor.device_cache import GLOBAL_CACHE
from citus_tpu.executor.executor import GLOBAL_COUNTERS


@pytest.fixture()
def pair(tmp_path):
    """Two coordinators, two data dirs, one logical cluster: A is the
    metadata authority hosting node 0; B attaches and hosts node 1."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    na = a.register_node()
    b = ct.Cluster(str(tmp_path / "b"), data_port=0, hosted_nodes=set(),
                   coordinator=("127.0.0.1", a.control_port), n_nodes=0)
    nb = b.register_node()
    a._maybe_reload_catalog(force_sync=True)
    yield a, b, na, nb
    b.close()
    a.close()


def _load(a, n=20000):
    a.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, c text)")
    a.execute("SELECT create_distributed_table('t', 'k', 4)")
    a.copy_from("t", columns={
        "k": np.arange(n), "v": np.arange(n) * 3,
        "c": [f"w{i % 7}" for i in range(n)]})
    GLOBAL_CACHE.clear()
    GLOBAL_COUNTERS.reset()
    return n


def _remote_stripe_bytes(a, b, table="t"):
    total = 0
    t = a.catalog.table(table)
    for s in t.shards:
        nd = s.placements[0]
        if not a.catalog.is_remote_node(nd):
            continue
        d = b.catalog.shard_dir(table, s.shard_id, nd)
        if os.path.isdir(d):
            for f in os.listdir(d):
                total += os.path.getsize(os.path.join(d, f))
    return total


def test_push_aggregate_no_placement_sync(pair):
    """The acceptance criterion: a cross-host aggregate executes via
    execute_task push — zero sync_placement calls, result bytes an
    order of magnitude under the stripe bytes pull would mirror."""
    a, b, na, nb = pair
    n = _load(a)
    t = a.catalog.table("t")
    assert {s.placements[0] for s in t.shards} == {na, nb}
    r = a.execute("SELECT count(*), sum(v) FROM t")
    assert r.rows == [(n, 3 * n * (n - 1) // 2)]
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["remote_tasks_pushed"] >= 1
    assert snap["remote_task_fallbacks"] == 0
    assert snap["placement_sync_bytes"] == 0
    assert a.catalog.remote_data.stats["remote_syncs"] == 0
    stripe_bytes = _remote_stripe_bytes(a, b)
    assert stripe_bytes > 0
    assert snap["remote_task_result_bytes"] * 10 <= stripe_bytes, \
        (snap["remote_task_result_bytes"], stripe_bytes)


def test_push_group_by_text(pair):
    """GROUP BY over a text column pushes too: dictionary ids are
    table-global (authority-mirrored), so worker partials combine."""
    a, b, na, nb = pair
    n = _load(a)
    r = a.execute("SELECT c, count(*), sum(v) FROM t GROUP BY c ORDER BY c")
    exp = {}
    for i in range(n):
        key = f"w{i % 7}"
        cnt, sv = exp.get(key, (0, 0))
        exp[key] = (cnt + 1, sv + 3 * i)
    assert r.rows == [(k, c, s) for k, (c, s) in sorted(exp.items())]
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["remote_tasks_pushed"] >= 1
    assert snap["placement_sync_bytes"] == 0


def test_push_projection(pair):
    """Filtered projections push: the owning host scans, filters, and
    ships only surviving rows."""
    a, b, na, nb = pair
    _load(a)
    r = a.execute("SELECT k, v FROM t WHERE k < 10 ORDER BY k")
    assert r.rows == [(i, 3 * i) for i in range(10)]
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["remote_tasks_pushed"] >= 1
    assert snap["placement_sync_bytes"] == 0


def test_pushed_literal_variants_share_kernels(pair):
    """Acceptance criterion on the push path: re-running a literal
    variant of a cross-host aggregate performs zero new XLA compiles —
    the coordinator-side merge AND the worker-side execute_task kernels
    (decoded plans, same structural fingerprint) all land in the
    process-wide kernel LRU."""
    a, b, na, nb = pair
    n = _load(a)
    r1 = a.execute("SELECT count(*), sum(v) FROM t WHERE v < 60000")
    assert r1.rows == [(n, 3 * n * (n - 1) // 2)]
    snap0 = GLOBAL_COUNTERS.snapshot()
    r2 = a.execute("SELECT count(*), sum(v) FROM t WHERE v < 60003")
    snap1 = GLOBAL_COUNTERS.snapshot()
    assert r2.rows == r1.rows  # both predicates keep every row
    assert snap1["remote_tasks_pushed"] > snap0["remote_tasks_pushed"]
    assert snap1["kernel_cache_hits"] > snap0["kernel_cache_hits"]
    assert snap1["kernel_cache_misses"] == snap0["kernel_cache_misses"]
    assert snap1["kernel_compile_ms"] == snap0["kernel_compile_ms"]


def test_explain_analyze_shows_remote_tasks(pair):
    a, b, na, nb = pair
    _load(a)
    r = a.execute("EXPLAIN ANALYZE SELECT count(*) FROM t")
    txt = "\n".join(row[0] for row in r.rows)
    assert "Remote Tasks:" in txt, txt
    assert "pushed to node" in txt, txt


def test_pull_policy_uses_sync_placement(pair):
    """SET citus.remote_task_execution = pull disables push: the same
    query mirrors placement files and still answers correctly."""
    a, b, na, nb = pair
    n = _load(a)
    a.execute("SET citus.remote_task_execution = pull")
    r = a.execute("SELECT count(*), sum(v) FROM t")
    assert r.rows == [(n, 3 * n * (n - 1) // 2)]
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["remote_tasks_pushed"] == 0
    assert snap["placement_sync_bytes"] > 0
    assert a.catalog.remote_data.stats["remote_syncs"] >= 1


def test_inexpressible_shape_falls_back(pair):
    """count(DISTINCT ...) partials are not elementwise-combinable —
    the task codec refuses, the fallback counter records it, and the
    pull path answers correctly."""
    a, b, na, nb = pair
    _load(a)
    r = a.execute("SELECT count(DISTINCT c) FROM t")
    assert r.rows == [(7,)]
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["remote_task_fallbacks"] >= 1
    assert snap["remote_tasks_pushed"] == 0


def test_worker_sigkill_fails_over_cleanly(tmp_path):
    """SIGKILL of the owning worker process: pushes fail over to the
    pull path (fallback counter), the query surfaces a clean error for
    the unreachable placements instead of hanging, and the coordinator
    keeps answering queries that do not need the dead host."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    na = a.register_node()
    worker = textwrap.dedent(f"""
        import sys, time
        import jax
        jax.config.update("jax_platforms", "cpu")
        import citus_tpu as ct
        b = ct.Cluster({str(tmp_path / 'b')!r}, data_port=0,
                       hosted_nodes=set(), n_nodes=0,
                       coordinator=("127.0.0.1", {a.control_port}))
        nb = b.register_node()
        print("READY", nb, flush=True)
        sys.stdout.close()
        time.sleep(120)
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", worker],
                            stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = proc.stdout.readline().split()
        assert line and line[0] == "READY", f"worker failed: {line}"
        nb = int(line[1])
        a._maybe_reload_catalog(force_sync=True)
        a.execute("CREATE TABLE big (k bigint NOT NULL, v bigint)")
        a.execute("SELECT create_distributed_table('big', 'k', 4)")
        a.execute("CREATE TABLE solo (k bigint NOT NULL, v bigint)")
        a.copy_from("solo", columns={"k": np.arange(5),
                                     "v": np.arange(5)})
        n = 3000
        a.copy_from("big", columns={"k": np.arange(n),
                                    "v": np.arange(n)})
        GLOBAL_CACHE.clear()
        GLOBAL_COUNTERS.reset()
        assert a.execute("SELECT count(*), sum(v) FROM big").rows == \
            [(n, n * (n - 1) // 2)]
        assert GLOBAL_COUNTERS.snapshot()["remote_tasks_pushed"] >= 1
        proc.kill()
        proc.wait()
        GLOBAL_CACHE.clear()
        fb0 = GLOBAL_COUNTERS.snapshot()["remote_task_fallbacks"]
        with pytest.raises(Exception):
            a.execute("SELECT count(*), sum(v) FROM big")
        assert GLOBAL_COUNTERS.snapshot()["remote_task_fallbacks"] > fb0
        # the cluster is not wedged: local-only tables still answer
        assert a.execute("SELECT count(*), sum(v) FROM solo").rows == \
            [(5, 10)]
    finally:
        proc.kill()
        proc.wait()
        a.close()


# ---- satellite regressions ------------------------------------------------


def test_2pc_abort_in_doubt_leaves_prepared_branches(pair):
    """When the abort claim cannot reach the outcome register, already
    PREPARED branches must NOT receive abort decides — they stay
    prepared and resolve against the register; the statement surfaces
    an in-doubt error (not a silent partial abort)."""
    a, b, na, nb = pair
    _load(a, n=2000)
    orig = a._control.record_txn_outcome

    def boom(gxid, outcome):
        raise RuntimeError("authority unreachable")

    a._control.record_txn_outcome = boom
    try:
        with pytest.raises(TransactionError, match="in doubt"):
            a.execute("UPDATE t SET v = v + 1")
        with b._data_server._branches_mu:
            branches = {g: e["prepared"]
                        for g, e in b._data_server._branches.items()}
        assert branches, "remote branch must survive the in-doubt abort"
        assert all(branches.values()), branches
    finally:
        a._control.record_txn_outcome = orig


def test_interactive_txn_commit_in_doubt_leaves_prepared_branches(pair):
    """Same property for BEGIN..COMMIT (transaction/branches.py): a
    commit whose outcome record AND abort claim both fail leaves the
    prepared remote branch untouched and raises in-doubt."""
    a, b, na, nb = pair
    _load(a, n=2000)
    s = a.session()
    s.execute("BEGIN")
    s.execute("UPDATE t SET v = v + 1")
    orig = a._control.record_txn_outcome

    def boom(gxid, outcome):
        raise RuntimeError("authority unreachable")

    a._control.record_txn_outcome = boom
    try:
        with pytest.raises(TransactionError, match="in doubt"):
            s.execute("COMMIT")
        with b._data_server._branches_mu:
            branches = {g: e["prepared"]
                        for g, e in b._data_server._branches.items()}
        assert branches and all(branches.values()), branches
    finally:
        a._control.record_txn_outcome = orig


def test_replicated_cross_host_writes_fail_closed(pair):
    """shard_replication_factor > 1 with placements spanning hosts:
    ingest and modify statements refuse (only one placement would see
    the write, silently diverging its replica); reads still work."""
    a, b, na, nb = pair
    a.execute("SET citus.shard_replication_factor = 2")
    a.execute("CREATE TABLE r2 (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('r2', 'k', 4)")
    t = a.catalog.table("r2")
    assert any(len(s.placements) > 1 for s in t.shards)
    with pytest.raises(UnsupportedFeatureError, match="span hosts"):
        a.copy_from("r2", columns={"k": np.arange(10),
                                   "v": np.arange(10)})
    with pytest.raises(UnsupportedFeatureError, match="span hosts"):
        a.execute("UPDATE r2 SET v = 1")
    with pytest.raises(UnsupportedFeatureError, match="span hosts"):
        a.execute("DELETE FROM r2")
    assert a.execute("SELECT count(*) FROM r2").rows == [(0,)]


def test_txn_stmt_branch_creation_race(pair):
    """Concurrent first statements of the same gxid converge on ONE
    branch session; the loser's session rolls back instead of leaking
    an open transaction whose locks would wedge later writers."""
    a, b, na, nb = pair
    a.execute("CREATE TABLE rt (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('rt', 'k', 4)")
    ep = a.catalog.node_endpoint(nb)
    gxid = "race-gxid-1"
    errs = []
    barrier = threading.Barrier(4)

    def stmt(i):
        try:
            barrier.wait(5)
            a.catalog.remote_data.call(
                ep, "txn_stmt",
                {"gxid": gxid,
                 "sql": f"INSERT INTO rt VALUES ({i}, {i})"})
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=stmt, args=(i,)) for i in range(4)]
    for th in ts:
        th.start()
    for th in ts:
        th.join(30)
    assert not errs, errs
    with b._data_server._branches_mu:
        assert list(b._data_server._branches) == [gxid]
    a.catalog.remote_data.call(ep, "txn_branch_abort", {"gxid": gxid})
    with b._data_server._branches_mu:
        assert gxid not in b._data_server._branches
    # no leaked open transaction: a later writer acquires the group
    # lock immediately instead of waiting out a stranded session
    a.copy_from("rt", columns={"k": np.arange(4), "v": np.arange(4)})
    assert a.execute("SELECT count(*) FROM rt").rows == [(4,)]


def test_add_check_takes_exclusive_write_lock(tmp_cluster):
    """ALTER TABLE ADD CHECK holds the colocation group's EXCLUSIVE
    write lock across validation scan + catalog commit — a concurrent
    writer can no longer slip a violating row between the two."""
    from citus_tpu.transaction.locks import EXCLUSIVE
    cl = tmp_cluster
    cl.execute("CREATE TABLE ck (k bigint NOT NULL, v bigint)")
    cl.copy_from("ck", columns={"k": np.arange(10), "v": np.arange(10)})
    cl.execute("SET lock_timeout = '400ms'")
    t = cl.catalog.table("ck")
    held, release = threading.Event(), threading.Event()

    def holder():
        with cl._write_lock(t, EXCLUSIVE):
            held.set()
            release.wait(15)

    th = threading.Thread(target=holder)
    th.start()
    assert held.wait(10)
    try:
        with pytest.raises(Exception):
            cl.execute("ALTER TABLE ck ADD CHECK (v >= 0)")
        assert not cl.catalog.table("ck").check_constraints
    finally:
        release.set()
        th.join(15)
    cl.execute("ALTER TABLE ck ADD CHECK (v >= 0)")
    assert cl.catalog.table("ck").check_constraints
    # validation still rejects violated constraints
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError, match="violated"):
        cl.execute("ALTER TABLE ck ADD CHECK (v > 5)")


def test_serial_does_not_clobber_foreign_sequence(tmp_cluster):
    """CREATE TABLE with a serial column refuses to restart a
    pre-existing same-named sequence it does not own; a leftover from
    a dropped incarnation of the SAME table still restarts."""
    cl = tmp_cluster
    cl.execute("CREATE SEQUENCE st_id_seq")
    assert cl.catalog.nextval("st_id_seq") == 1
    with pytest.raises(CatalogError, match="already exists"):
        cl.execute("CREATE TABLE st (id bigserial, v bigint)")
    assert not cl.catalog.has_table("st")  # all-or-nothing
    assert cl.catalog.nextval("st_id_seq") == 2  # untouched
    cl.execute("DROP SEQUENCE st_id_seq")
    # normal serial lifecycle: create, draw, drop leaves nothing behind
    cl.execute("CREATE TABLE st (id bigserial, v bigint)")
    assert cl.catalog.sequences["st_id_seq"].get("owner") == "st"
    cl.execute("INSERT INTO st (v) VALUES (7)")
    assert cl.execute("SELECT id, v FROM st").rows == [(1, 7)]
    cl.execute("DROP TABLE st")
    # a same-owner leftover restarts (simulates a crashed DROP that
    # kept the sequence): re-creating the table must succeed
    cl.catalog.create_sequence("st_id_seq", 5, 1)
    cl.catalog.sequences["st_id_seq"]["owner"] = "st"
    cl.execute("CREATE TABLE st (id bigserial, v bigint)")
    cl.execute("INSERT INTO st (v) VALUES (8)")
    assert cl.execute("SELECT id FROM st").rows == [(1,)]  # restarted


def test_pull_sync_elision_skips_unchanged_placements(pair):
    """Second pull-mode query over unchanged remote placements skips
    the per-placement list_placement RTT entirely: the elision token
    (data epoch + live invalidation stream) proves the mirror current."""
    a, b, na, nb = pair
    n = _load(a)
    a.execute("SET citus.remote_task_execution = pull")
    assert a.execute("SELECT count(*) FROM t").rows == [(n,)]
    syncs1 = a.catalog.remote_data.stats["remote_syncs"]
    assert syncs1 >= 1
    GLOBAL_CACHE.clear()   # drop HBM so the scan re-consults the mirror
    assert a.execute("SELECT sum(v) FROM t").rows == [(3 * n * (n - 1) // 2,)]
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["placement_sync_elided"] >= 1
    # proof the RTT was saved: no new list_placement round trips
    assert a.catalog.remote_data.stats["remote_syncs"] == syncs1


def test_write_invalidates_elision_cluster_wide(pair):
    """A write through the OTHER coordinator expires the elision tokens
    via the control-plane data_changed push: the next pull pays the RTT
    again and reads the fresh rows (no stale mirror)."""
    a, b, na, nb = pair
    n = _load(a)
    a.execute("SET citus.remote_task_execution = pull")
    assert a.execute("SELECT count(*) FROM t").rows == [(n,)]
    GLOBAL_CACHE.clear()
    a.execute("SELECT count(*) FROM t")   # arm the elision fast path
    syncs = a.catalog.remote_data.stats["remote_syncs"]
    b.copy_from("t", columns={"k": np.array([10 * n]),
                              "v": np.array([7]), "c": ["w0"]})
    GLOBAL_CACHE.clear()
    assert a.execute("SELECT count(*) FROM t").rows == [(n + 1,)]
    # the tokens expired: the mirrors re-synced over the wire
    assert a.catalog.remote_data.stats["remote_syncs"] > syncs


def test_elision_distrusted_without_push_stream(tmp_path):
    """No control plane (file-mtime polling topology): every sync pays
    the RTT — elision only activates when the invalidation stream is
    provably attached."""
    import citus_tpu.net.data_plane as dp
    a = ct.Cluster(str(tmp_path / "solo"), n_nodes=2)
    rd = dp.DataPlaneClient(a.catalog)
    assert rd.invalidation_fresh is None   # never wired -> no elision
    a.close()
