-- round-2 analytics surface: named windows, RANGE frames,
-- approx_percentile, enum ordering, multi-key correlation
CREATE TABLE s (k bigint NOT NULL, g bigint, h bigint, v bigint);
SELECT create_distributed_table('s', 'k', 4);
INSERT INTO s VALUES (1, 0, 0, 10), (2, 0, 1, 40), (3, 0, 0, 20), (4, 1, 1, 5), (5, 1, 0, 25), (6, 1, 1, 15), (7, 1, 0, 35), (8, 0, 1, 30);
SELECT k, sum(v) OVER w AS run, count(*) OVER w AS cnt FROM s WINDOW w AS (PARTITION BY g ORDER BY k) ORDER BY k;
SELECT k, sum(v) OVER (w ORDER BY v) AS byval FROM s WINDOW w AS (PARTITION BY g) ORDER BY k;
SELECT k, sum(v) OVER (PARTITION BY g ORDER BY v RANGE BETWEEN 10 PRECEDING AND 10 FOLLOWING) AS near FROM s ORDER BY k;
SELECT approx_percentile(0.5) WITHIN GROUP (ORDER BY v) AS med FROM s;
CREATE TYPE sev AS ENUM ('low', 'high', 'critical');
CREATE TABLE ev (k bigint NOT NULL, s sev);
SELECT create_distributed_table('ev', 'k', 2);
INSERT INTO ev VALUES (1, 'high'), (2, 'low'), (3, 'critical'), (4, 'low');
SELECT k, s FROM ev WHERE s >= 'high' ORDER BY s, k;
SELECT s, count(*) FROM ev GROUP BY s ORDER BY s DESC;
SELECT count(*) FROM s a WHERE EXISTS (SELECT 1 FROM s b WHERE b.g = a.g AND b.h = a.h AND b.v > a.v);
SELECT k, (SELECT max(b.v) FROM s b WHERE b.g = a.g AND b.h = a.h) AS peer_max FROM s a ORDER BY k;
DROP TABLE ev;
DROP TYPE sev;
DROP TABLE s;
