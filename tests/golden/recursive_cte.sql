CREATE TABLE edges (src bigint NOT NULL, dst bigint);
SELECT create_distributed_table('edges', 'src', 4);
INSERT INTO edges VALUES (1, 2), (2, 3), (3, 4), (3, 1), (4, 5), (9, 10);
WITH RECURSIVE s(n) AS (SELECT 1 UNION ALL SELECT n + 1 FROM s WHERE n < 6) SELECT n, n * n FROM s ORDER BY n;
WITH RECURSIVE reach(node) AS (SELECT 1 UNION SELECT e.dst FROM edges e, reach r WHERE e.src = r.node) SELECT node FROM reach ORDER BY node;
WITH RECURSIVE hops(node, depth) AS (SELECT 1, 0 UNION ALL SELECT e.dst, h.depth + 1 FROM edges e, hops h WHERE e.src = h.node AND h.depth < 3) SELECT depth, count(*) FROM hops GROUP BY depth ORDER BY depth;
WITH RECURSIVE a(x) AS (SELECT 41), b(y) AS (SELECT x + 1 FROM a) SELECT y FROM b;
DROP TABLE edges;
