-- window frames, navigation functions, windows over GROUP BY
CREATE TABLE w (k bigint NOT NULL, g bigint, v bigint);
SELECT create_distributed_table('w', 'k', 4);
INSERT INTO w VALUES (1, 0, 10), (2, 0, 40), (3, 0, 20), (4, 1, 5), (5, 1, 25), (6, 1, 15);
SELECT k, sum(v) OVER (PARTITION BY g ORDER BY k) AS running FROM w ORDER BY k;
SELECT k, sum(v) OVER (PARTITION BY g ORDER BY k ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS frame2 FROM w ORDER BY k;
SELECT k, lag(v) OVER (PARTITION BY g ORDER BY k) AS prev, lead(v) OVER (PARTITION BY g ORDER BY k) AS nxt FROM w ORDER BY k;
SELECT k, first_value(v) OVER (PARTITION BY g ORDER BY v) AS fv, last_value(v) OVER (PARTITION BY g ORDER BY v ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) AS lv FROM w ORDER BY k;
SELECT k, ntile(3) OVER (ORDER BY v) AS bucket FROM w ORDER BY k;
SELECT g, sum(v) AS total, rank() OVER (ORDER BY sum(v) DESC) AS rnk FROM w GROUP BY g ORDER BY g;
DROP TABLE w;
