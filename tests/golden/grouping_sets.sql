-- ROLLUP / CUBE / GROUPING SETS over a distributed table
CREATE TABLE sales (id bigint NOT NULL, region text, product text, amount decimal(10,2));
SELECT create_distributed_table('sales', 'id', 4);
INSERT INTO sales VALUES (1, 'east', 'ax', 10.00), (2, 'east', 'bx', 20.00),
  (3, 'west', 'ax', 30.00), (4, 'west', 'bx', 40.00), (5, 'east', 'ax', 5.00);
SELECT region, product, sum(amount) FROM sales GROUP BY ROLLUP(region, product) ORDER BY region NULLS LAST, product NULLS LAST;
SELECT region, product, count(*) FROM sales GROUP BY CUBE(region, product) ORDER BY region NULLS LAST, product NULLS LAST;
SELECT region, product, sum(amount) FROM sales GROUP BY GROUPING SETS((region), (product)) ORDER BY region NULLS LAST, product NULLS LAST;
SELECT region, grouping(region) AS g, sum(amount) FROM sales GROUP BY ROLLUP(region) ORDER BY g, region NULLS LAST;
DROP TABLE sales;
