-- set operations, EXISTS, derived tables (recursive planning surface)
CREATE TABLE a (k bigint NOT NULL, v bigint);
CREATE TABLE b (k bigint NOT NULL, v bigint);
SELECT create_distributed_table('a', 'k', 4);
SELECT create_distributed_table('b', 'k', 4);
INSERT INTO a VALUES (1, 1), (2, 2), (3, 3), (4, 4), (5, 5), (6, NULL);
INSERT INTO b VALUES (1, 3), (2, 4), (3, 5), (4, 6), (5, NULL);
SELECT v FROM a UNION SELECT v FROM b ORDER BY v NULLS LAST;
SELECT v FROM a UNION ALL SELECT v FROM b ORDER BY v NULLS LAST;
SELECT v FROM a INTERSECT SELECT v FROM b ORDER BY v;
SELECT v FROM a EXCEPT SELECT v FROM b ORDER BY v;
SELECT v FROM a WHERE v < 3 UNION SELECT v FROM a WHERE v > 4 INTERSECT SELECT v FROM b ORDER BY v;
SELECT count(*) FROM a WHERE EXISTS (SELECT 1 FROM b WHERE b.v = 6);
SELECT count(*) FROM a WHERE NOT EXISTS (SELECT 1 FROM b WHERE b.v = 99);
SELECT z.v, count(*) FROM (SELECT v FROM a WHERE v IS NOT NULL UNION ALL SELECT v FROM b WHERE v IS NOT NULL) z GROUP BY z.v ORDER BY z.v;
SELECT count(*) FROM a JOIN (SELECT k FROM b WHERE v >= 4) big ON a.k = big.k;
SELECT v FROM a UNION SELECT k, v FROM b;
DROP TABLE a;
DROP TABLE b;
