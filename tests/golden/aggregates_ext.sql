-- extended aggregates: variance family, percentiles, string_agg, booleans
CREATE TABLE m (k bigint NOT NULL, g bigint, v bigint, f double, s text, b boolean);
SELECT create_distributed_table('m', 'k', 4);
INSERT INTO m VALUES (1, 0, 10, 1.5, 'x', true), (2, 0, 20, 2.5, 'y', true),
  (3, 0, 30, 3.5, 'x', false), (4, 1, 5, 0.5, 'z', true),
  (5, 1, 15, 1.0, 'z', true), (6, 1, NULL, 2.0, NULL, NULL);
SELECT stddev(v), stddev_pop(v), var_samp(v), var_pop(v) FROM m;
SELECT g, stddev(v), variance(f) FROM m GROUP BY g ORDER BY g;
SELECT percentile_cont(0.5) WITHIN GROUP (ORDER BY v) FROM m;
SELECT g, percentile_disc(0.5) WITHIN GROUP (ORDER BY v) FROM m GROUP BY g ORDER BY g;
SELECT bool_and(b), bool_or(b) FROM m;
SELECT g, string_agg(s, ',') FROM m GROUP BY g ORDER BY g;
SELECT count(DISTINCT s), count(s) FROM m;
SELECT stddev(v) FROM m WHERE k = 1;
DROP TABLE m;
