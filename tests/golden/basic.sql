-- smoke coverage of the SQL surface, pg_regress style
CREATE TABLE items (id bigint NOT NULL, name text, price decimal(8,2), added date);
SELECT create_distributed_table('items', 'id', 4);
INSERT INTO items VALUES (1, 'hammer', 9.99, '2024-01-05'), (2, 'nail', 0.05, '2024-01-06'),
  (3, 'saw', 19.50, '2024-02-01'), (4, NULL, 2.50, '2024-02-10'), (5, 'drill', 89.00, NULL);
SELECT count(*), count(name), min(price), max(price) FROM items;
SELECT name, price FROM items WHERE price > 5 ORDER BY price DESC;
SELECT extract(month FROM added) AS m, count(*) FROM items GROUP BY extract(month FROM added) ORDER BY m NULLS LAST;
SELECT sum(price) FROM items WHERE name LIKE '%a%';
UPDATE items SET price = price * 2 WHERE id = 2;
SELECT price FROM items WHERE id = 2;
DELETE FROM items WHERE price > 50;
SELECT count(*) FROM items;
SELECT id, row_number() OVER (ORDER BY price DESC) AS rn FROM items WHERE price IS NOT NULL ORDER BY rn LIMIT 3;
WITH expensive AS (SELECT id, price FROM items WHERE price > 1)
SELECT count(*) FROM expensive;
SELECT nope FROM items;
SELECT count(*) FROM missing_table;
DROP TABLE items;
