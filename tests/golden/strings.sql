-- string function family over dictionary encodings
CREATE TABLE s (k bigint NOT NULL, t text);
SELECT create_distributed_table('s', 'k', 2);
INSERT INTO s VALUES (1, '  Padded  '), (2, 'xxcorexx'), (3, 'citus data'), (4, NULL);
SELECT k, trim(t) FROM s ORDER BY k;
SELECT k, upper(trim(t)) FROM s ORDER BY k;
SELECT k, replace(t, 'x', '') FROM s ORDER BY k;
SELECT k, left(t, 3), right(t, 3) FROM s ORDER BY k;
SELECT k, initcap(t), reverse(t) FROM s ORDER BY k;
SELECT k, substring(t, 3, 4) FROM s ORDER BY k;
SELECT k, length(trim(t)) FROM s ORDER BY k;
SELECT lower(trim(t)) AS key, count(*) FROM s GROUP BY lower(trim(t)) ORDER BY key NULLS LAST;
SELECT count(*) FROM s WHERE upper(t) LIKE '%CORE%';
DROP TABLE s;
