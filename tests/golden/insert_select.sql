-- INSERT..SELECT strategy ladder + parameterless repartition behavior
CREATE TABLE src (k bigint NOT NULL, v bigint, s text);
SELECT create_distributed_table('src', 'k', 4);
INSERT INTO src VALUES (1, 10, 'a'), (2, 20, 'b'), (3, 30, 'a'), (4, 40, 'c');
CREATE TABLE colo (k bigint NOT NULL, v bigint, s text);
SELECT create_distributed_table('colo', 'k', 4, 'src');
INSERT INTO colo SELECT k, v, s FROM src WHERE v > 15;
SELECT count(*), sum(v) FROM colo;
CREATE TABLE byv (k bigint, v bigint NOT NULL);
SELECT create_distributed_table('byv', 'v', 4);
INSERT INTO byv SELECT k, v FROM src;
SELECT count(*), sum(k) FROM byv;
CREATE TABLE rollup (g text, n bigint);
INSERT INTO rollup SELECT s, count(*) FROM src GROUP BY s;
SELECT g, n FROM rollup ORDER BY g;
DROP TABLE src; DROP TABLE colo; DROP TABLE byv; DROP TABLE rollup;
