-- joins + DML + utilities coverage
CREATE TABLE orders (oid bigint NOT NULL, cid bigint, total decimal(10,2));
CREATE TABLE customers (cid bigint, name text);
SELECT create_distributed_table('orders', 'oid', 4);
SELECT create_reference_table('customers');
INSERT INTO orders VALUES (1, 10, 100.00), (2, 20, 250.50), (3, 10, 75.25), (4, 30, 10.00);
INSERT INTO customers VALUES (10, 'ann'), (20, 'bo'), (30, 'cy');
SELECT c.name, sum(o.total) FROM orders o JOIN customers c ON o.cid = c.cid GROUP BY c.name ORDER BY c.name;
SELECT count(*) FROM orders o LEFT JOIN customers c ON o.cid = c.cid;
UPDATE orders SET total = total + 1 WHERE oid = 4;
SELECT total FROM orders WHERE oid = 4;
MERGE INTO orders t USING orders s ON t.oid = s.oid WHEN MATCHED AND t.oid = 1 THEN UPDATE SET total = 999.99;
SELECT total FROM orders WHERE oid = 1;
DELETE FROM orders WHERE cid = 10;
SELECT count(*) FROM orders;
SELECT count(*) FROM customers WHERE name LIKE '%n%';
WITH big AS (SELECT oid FROM orders WHERE total > 100)
SELECT count(*) FROM big;
SELECT bool_check FROM orders;
DROP TABLE orders;
DROP TABLE customers;
