"""Device-side hash aggregation: exactness under collisions and spills."""

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import (
    ExecutorSettings, PlannerSettings, Settings, settings_override,
)


def test_high_cardinality_groupby_matches_cpu(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v decimal(10,2))")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rng = np.random.default_rng(17)
    n = 60_000
    # key domain far wider than direct_gid_limit -> hash mode
    g = rng.integers(0, 10**12, 20_000)[rng.integers(0, 20_000, n)]
    cl.copy_from("t", columns={"k": np.arange(n, dtype=np.int64),
                               "g": g, "v": rng.integers(0, 10000, n) / 100})
    sql = "SELECT g, count(*), sum(v), min(v), max(v) FROM t GROUP BY g"
    from citus_tpu.planner import parse_sql
    from citus_tpu.planner.bind import bind_select
    from citus_tpu.planner.physical import plan_select
    plan = plan_select(cl.catalog, bind_select(cl.catalog, parse_sql(sql)[0]))
    assert plan.group_mode.kind == "hash_host"
    jax_rows = sorted(cl.execute(sql).rows)
    with settings_override(executor=ExecutorSettings(task_executor_backend="cpu")):
        cpu_rows = sorted(cl.execute(sql).rows)
    assert jax_rows == cpu_rows
    assert len(jax_rows) == len(np.unique(g))


def test_hash_agg_with_tiny_slot_table_spills_exactly(tmp_path):
    """Force massive slot collisions (S=64 << groups) — spills must keep
    results exact."""
    st = Settings(planner=PlannerSettings(hash_agg_slots=64, direct_gid_limit=4))
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2, settings=st)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 2)")
    rng = np.random.default_rng(3)
    n = 20_000
    g = rng.integers(0, 2000, n)
    v = rng.integers(0, 100, n)
    cl.copy_from("t", columns={"k": np.arange(n, dtype=np.int64), "g": g, "v": v})
    sql = "SELECT g, count(*), sum(v) FROM t GROUP BY g"
    got = sorted(cl.execute(sql).rows)
    # numpy truth
    import collections
    truth = collections.defaultdict(lambda: [0, 0])
    for gi, vi in zip(g.tolist(), v.tolist()):
        truth[gi][0] += 1
        truth[gi][1] += vi
    want = sorted((gi, c, s) for gi, (c, s) in truth.items())
    assert got == want


def test_null_keys_in_hash_mode(tmp_path):
    st = Settings(planner=PlannerSettings(direct_gid_limit=2))
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=1, settings=st)
    cl.execute("CREATE TABLE t (g bigint, v bigint)")
    cl.execute("INSERT INTO t VALUES (1, 10), (NULL, 20), (1, 30), (NULL, 40), (2, 5)")
    rows = sorted(cl.execute("SELECT g, count(*), sum(v) FROM t GROUP BY g").rows,
                  key=repr)
    assert sorted(rows, key=repr) == sorted(
        [(1, 2, 40), (2, 1, 5), (None, 2, 60)], key=repr)


def test_group_by_float32_column(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, f real, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 2)")
    rng = np.random.default_rng(8)
    n = 5000
    f = (rng.integers(0, 50, n) / 4).astype(np.float32)
    cl.copy_from("t", columns={"k": np.arange(n, dtype=np.int64), "f": f,
                               "v": np.ones(n, dtype=np.int64)})
    rows = cl.execute("SELECT f, count(*) FROM t GROUP BY f").rows
    assert len(rows) == len(np.unique(f))
    assert sum(r[1] for r in rows) == n
    with settings_override(executor=ExecutorSettings(task_executor_backend="cpu")):
        cpu = cl.execute("SELECT f, count(*) FROM t GROUP BY f").rows
    assert sorted(rows) == sorted(cpu)


def test_count_distinct(tmp_path):
    import sqlite3
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g text, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rows = [(i, ["a", "b", None][i % 3], (i * 3) % 17 if i % 5 else None)
            for i in range(2000)]
    cl.copy_from("t", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, g TEXT, v INTEGER)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)", rows)
    for sql in [
        "SELECT count(DISTINCT v) FROM t",
        "SELECT g, count(DISTINCT v), count(*) FROM t GROUP BY g",
        "SELECT count(DISTINCT v) FROM t WHERE k < 100",
        "SELECT count(DISTINCT g) FROM t",
    ]:
        ours = sorted(cl.execute(sql).rows, key=repr)
        theirs = sorted(sq.execute(sql).fetchall(), key=repr)
        assert ours == [tuple(r) for r in theirs], sql
    # empty input still yields one scalar row
    assert cl.execute("SELECT count(DISTINCT v) FROM t WHERE k < 0").rows == [(0,)]


def test_device_table_combine_across_batches(tmp_path):
    """VERDICT #8: every batch inserts into ONE donated device hash
    table (build_fused_hash_worker); the host sees one fetched table +
    spill masks and re-aggregates only spills.  Verified exact vs the
    cpu oracle at cardinality far above the slot count."""
    import citus_tpu as ct
    from citus_tpu.config import ExecutorSettings, Settings, settings_override

    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE big (k bigint NOT NULL, g bigint, v bigint)")
    cl.execute("SELECT create_distributed_table('big', 'k', 8)")
    rng = np.random.default_rng(40)
    n = 60_000
    g = rng.integers(0, 300_000, n)
    v = rng.integers(0, 100, n)
    cl.copy_from("big", columns={"k": np.arange(n), "g": g, "v": v})

    from citus_tpu.planner import parse_sql
    from citus_tpu.planner.bind import bind_select
    from citus_tpu.planner.physical import plan_select
    bound = bind_select(cl.catalog, parse_sql(
        "SELECT g, count(*) FROM big GROUP BY g")[0])
    plan = plan_select(cl.catalog, bound)
    assert plan.group_mode.kind == "hash_host"

    sql = "SELECT g, count(*), sum(v), min(v), max(v) FROM big GROUP BY g ORDER BY g LIMIT 40"
    r = cl.execute(sql)
    with settings_override(executor=ExecutorSettings(task_executor_backend="cpu")):
        r2 = cl.execute(sql)
    assert r.rows == r2.rows
    # the merge kernel was actually engaged (multiple batch tables)
    pp = cl._plan_cache.get(sql)
    tot = cl.execute(
        "SELECT sum(c), count(*) FROM (SELECT g, count(*) AS c FROM big GROUP BY g) z")
    assert tot.rows == [(n, len(np.unique(g)))]
    cl.close()
