"""Window functions vs the sqlite oracle (sqlite's default frame matches
PostgreSQL's: RANGE UNBOUNDED PRECEDING .. CURRENT ROW)."""

import decimal
import sqlite3

import pytest

import citus_tpu as ct


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g text, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rows = [(i, ["a", "b", "c"][i % 3], (i * 7) % 23) for i in range(300)]
    cl.copy_from("t", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, g TEXT, v INTEGER)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)", rows)
    return cl, sq


def check(db, sql):
    cl, sq = db
    ours = sorted(
        [tuple(float(v) if isinstance(v, decimal.Decimal) else v for v in r)
         for r in cl.execute(sql).rows], key=repr)
    theirs = sorted(sq.execute(sql).fetchall(), key=repr)
    assert len(ours) == len(theirs)
    for a, b in zip(ours, theirs):
        assert a == pytest.approx(b, rel=1e-9, abs=1e-9), (sql, a, b)


WINDOW_QUERIES = [
    "SELECT k, row_number() OVER (PARTITION BY g ORDER BY k) FROM t",
    "SELECT k, rank() OVER (PARTITION BY g ORDER BY v) FROM t",
    "SELECT k, dense_rank() OVER (PARTITION BY g ORDER BY v) FROM t",
    "SELECT k, sum(v) OVER (PARTITION BY g) FROM t",
    "SELECT k, sum(v) OVER (PARTITION BY g ORDER BY k) FROM t",
    "SELECT k, count(*) OVER (PARTITION BY g ORDER BY v) FROM t",
    "SELECT k, min(v) OVER (PARTITION BY g ORDER BY k) FROM t",
    "SELECT k, row_number() OVER (ORDER BY k DESC) FROM t WHERE v > 10",
]


@pytest.mark.parametrize("sql", WINDOW_QUERIES)
def test_window_vs_sqlite(db, sql):
    check(db, sql)


def test_window_with_outer_order_limit(db):
    cl, sq = db
    sql = ("SELECT k, row_number() OVER (ORDER BY k) AS rn FROM t "
           "ORDER BY rn DESC LIMIT 5")
    ours = cl.execute(sql).rows
    theirs = sq.execute(sql).fetchall()
    assert ours == list(theirs)


def test_window_pushdown_on_dist_column(tmp_path):
    """PARTITION BY distribution column -> per-shard window computation
    (reference: pushdown safety when partitioned by the distcol)."""
    import sqlite3
    cl = ct.Cluster(str(tmp_path / "wp"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rows = [(i % 10, i % 3, (i * 7) % 20) for i in range(60)]
    cl.copy_from("t", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, g INTEGER, v INTEGER)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)", rows)
    sql = ("SELECT k, sum(v) OVER (PARTITION BY k ORDER BY v "
           "ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS s "
           "FROM t ORDER BY k, s")
    r = cl.execute(sql)
    assert r.explain["strategy"] == "window:pushdown"
    assert sorted(r.rows) == sorted(tuple(x) for x in sq.execute(sql).fetchall())
    # non-dist partition falls back to pull, same results
    sql2 = "SELECT k, sum(v) OVER (PARTITION BY g) AS s FROM t ORDER BY k, s"
    r2 = cl.execute(sql2)
    assert r2.explain["strategy"] == "window:pull"
    assert sorted(r2.rows) == sorted(tuple(x) for x in sq.execute(sql2).fetchall())
    cl.close()
