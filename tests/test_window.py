"""Window functions vs the sqlite oracle (sqlite's default frame matches
PostgreSQL's: RANGE UNBOUNDED PRECEDING .. CURRENT ROW)."""

import decimal
import sqlite3

import pytest

import citus_tpu as ct


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g text, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rows = [(i, ["a", "b", "c"][i % 3], (i * 7) % 23) for i in range(300)]
    cl.copy_from("t", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, g TEXT, v INTEGER)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)", rows)
    return cl, sq


def check(db, sql):
    cl, sq = db
    ours = sorted(
        [tuple(float(v) if isinstance(v, decimal.Decimal) else v for v in r)
         for r in cl.execute(sql).rows], key=repr)
    theirs = sorted(sq.execute(sql).fetchall(), key=repr)
    assert len(ours) == len(theirs)
    for a, b in zip(ours, theirs):
        assert a == pytest.approx(b, rel=1e-9, abs=1e-9), (sql, a, b)


WINDOW_QUERIES = [
    "SELECT k, row_number() OVER (PARTITION BY g ORDER BY k) FROM t",
    "SELECT k, rank() OVER (PARTITION BY g ORDER BY v) FROM t",
    "SELECT k, dense_rank() OVER (PARTITION BY g ORDER BY v) FROM t",
    "SELECT k, sum(v) OVER (PARTITION BY g) FROM t",
    "SELECT k, sum(v) OVER (PARTITION BY g ORDER BY k) FROM t",
    "SELECT k, count(*) OVER (PARTITION BY g ORDER BY v) FROM t",
    "SELECT k, min(v) OVER (PARTITION BY g ORDER BY k) FROM t",
    "SELECT k, row_number() OVER (ORDER BY k DESC) FROM t WHERE v > 10",
]


@pytest.mark.parametrize("sql", WINDOW_QUERIES)
def test_window_vs_sqlite(db, sql):
    check(db, sql)


def test_window_with_outer_order_limit(db):
    cl, sq = db
    sql = ("SELECT k, row_number() OVER (ORDER BY k) AS rn FROM t "
           "ORDER BY rn DESC LIMIT 5")
    ours = cl.execute(sql).rows
    theirs = sq.execute(sql).fetchall()
    assert ours == list(theirs)


def test_window_pushdown_on_dist_column(tmp_path):
    """PARTITION BY distribution column -> per-shard window computation
    (reference: pushdown safety when partitioned by the distcol)."""
    import sqlite3
    cl = ct.Cluster(str(tmp_path / "wp"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rows = [(i % 10, i % 3, (i * 7) % 20) for i in range(60)]
    cl.copy_from("t", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, g INTEGER, v INTEGER)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)", rows)
    sql = ("SELECT k, sum(v) OVER (PARTITION BY k ORDER BY v "
           "ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS s "
           "FROM t ORDER BY k, s")
    r = cl.execute(sql)
    assert r.explain["strategy"] == "window:pushdown"
    assert sorted(r.rows) == sorted(tuple(x) for x in sq.execute(sql).fetchall())
    # non-dist partition falls back to pull, same results
    sql2 = "SELECT k, sum(v) OVER (PARTITION BY g) AS s FROM t ORDER BY k, s"
    r2 = cl.execute(sql2)
    assert r2.explain["strategy"] == "window:pull"
    assert sorted(r2.rows) == sorted(tuple(x) for x in sq.execute(sql2).fetchall())
    cl.close()


# ---- named windows (WINDOW clause), round-2 gap #5 -------------------

NAMED_WINDOW_QUERIES = [
    # OVER w verbatim
    "SELECT k, sum(v) OVER w FROM t WINDOW w AS (PARTITION BY g ORDER BY k)",
    # two functions sharing one named window
    "SELECT k, rank() OVER w, count(*) OVER w FROM t "
    "WINDOW w AS (PARTITION BY g ORDER BY v)",
    # OVER (w ORDER BY ...): copy partition, add ordering
    "SELECT k, sum(v) OVER (w ORDER BY k) FROM t WINDOW w AS (PARTITION BY g)",
    # named window referencing another named window
    "SELECT k, row_number() OVER w2 FROM t "
    "WINDOW w1 AS (PARTITION BY g), w2 AS (w1 ORDER BY k)",
    # verbatim use keeps the named window's frame
    "SELECT k, sum(v) OVER w FROM t WINDOW w AS (PARTITION BY g ORDER BY k "
    "ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING)",
]


@pytest.mark.parametrize("sql", NAMED_WINDOW_QUERIES)
def test_named_windows_vs_sqlite(db, sql):
    check(db, sql)


def test_named_window_errors(db):
    cl, _ = db
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError, match="does not exist"):
        cl.execute("SELECT sum(v) OVER nope FROM t")
    with pytest.raises(AnalysisError, match="ORDER BY"):
        cl.execute("SELECT sum(v) OVER (w ORDER BY v) FROM t "
                   "WINDOW w AS (PARTITION BY g ORDER BY k)")


# ---- RANGE frames ----------------------------------------------------

RANGE_QUERIES = [
    # explicit spelling of the default frame
    "SELECT k, sum(v) OVER (PARTITION BY g ORDER BY v "
    "RANGE BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) FROM t",
    # CURRENT ROW .. UNBOUNDED: peers included on the leading edge
    "SELECT k, count(*) OVER (PARTITION BY g ORDER BY v "
    "RANGE BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING) FROM t",
    # value-offset frames (single numeric sort key)
    "SELECT k, sum(v) OVER (PARTITION BY g ORDER BY v "
    "RANGE BETWEEN 3 PRECEDING AND 3 FOLLOWING) FROM t",
    "SELECT k, count(*) OVER (ORDER BY v RANGE BETWEEN 5 PRECEDING "
    "AND CURRENT ROW) FROM t",
    # DESC ordering flips the value direction
    "SELECT k, sum(v) OVER (ORDER BY v DESC RANGE BETWEEN 2 PRECEDING "
    "AND 2 FOLLOWING) FROM t",
    # frame-start shorthand (end = CURRENT ROW)
    "SELECT k, sum(v) OVER (PARTITION BY g ORDER BY v RANGE UNBOUNDED "
    "PRECEDING) FROM t",
    "SELECT k, sum(v) OVER (PARTITION BY g ORDER BY k ROWS 2 PRECEDING) FROM t",
]


@pytest.mark.parametrize("sql", RANGE_QUERIES)
def test_range_frames_vs_sqlite(db, sql):
    check(db, sql)


def test_range_offset_requires_single_order_key(db):
    cl, _ = db
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError, match="exactly one ORDER BY"):
        cl.execute("SELECT sum(v) OVER (ORDER BY g, v RANGE BETWEEN 1 "
                   "PRECEDING AND CURRENT ROW) FROM t")


def test_pushdown_on_injective_distcol_expression(tmp_path):
    """PARTITION BY (k + 1) is injective in k: still pushdown-safe."""
    import sqlite3
    cl = ct.Cluster(str(tmp_path / "wi"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rows = [(i % 10, (i * 7) % 20) for i in range(60)]
    cl.copy_from("t", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, v INTEGER)")
    sq.executemany("INSERT INTO t VALUES (?,?)", rows)
    sql = ("SELECT k, sum(v) OVER (PARTITION BY k + 1 ORDER BY v) AS s "
           "FROM t ORDER BY k, s")
    r = cl.execute(sql)
    assert r.explain["strategy"] == "window:pushdown"
    assert sorted(r.rows) == sorted(tuple(x) for x in sq.execute(sql).fetchall())
    # k % 3 is NOT injective: must pull
    sql2 = ("SELECT k, sum(v) OVER (PARTITION BY k % 3) AS s FROM t "
            "ORDER BY k, s")
    r2 = cl.execute(sql2)
    assert r2.explain["strategy"] == "window:pull"
    assert sorted(r2.rows) == sorted(tuple(x) for x in sq.execute(sql2).fetchall())
    cl.close()


# ---- review-finding regressions --------------------------------------

def test_named_window_with_params(db):
    """$N binding keeps the WINDOW clause (rewrite_params threads it)."""
    cl, sq = db
    r = cl.execute("SELECT k, sum(v) OVER w FROM t WHERE k < $1 "
                   "WINDOW w AS (PARTITION BY g)", params=[50])
    want = sq.execute("SELECT k, sum(v) OVER (PARTITION BY g) FROM t "
                      "WHERE k < 50").fetchall()
    assert sorted(r.rows) == sorted(tuple(x) for x in want)


def test_named_window_inside_cte(db):
    cl, sq = db
    r = cl.execute("WITH c AS (SELECT k, v, g FROM t) "
                   "SELECT k, sum(v) OVER w FROM c WINDOW w AS (PARTITION BY g)")
    want = sq.execute("SELECT k, sum(v) OVER (PARTITION BY g) FROM t").fetchall()
    assert sorted(r.rows) == sorted(tuple(x) for x in want)


def test_circular_named_window_rejected(db):
    cl, _ = db
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError, match="circular"):
        cl.execute("SELECT sum(v) OVER w FROM t WINDOW w AS (w)")
    with pytest.raises(AnalysisError, match="circular"):
        cl.execute("SELECT sum(v) OVER w1 FROM t "
                   "WINDOW w1 AS (w2), w2 AS (w1)")


def test_float_partition_expr_not_pushed_down(tmp_path):
    """k + <huge float> collapses distinct bigints — not injective, so
    the planner must pull, matching the single-partition oracle."""
    cl = ct.Cluster(str(tmp_path / "wf"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", rows=[(i, 1) for i in range(8)])
    r = cl.execute("SELECT k, count(*) OVER (PARTITION BY k + 1e18) AS c "
                   "FROM t ORDER BY k")
    assert r.explain["strategy"] == "window:pull"
    assert all(row[1] == 8 for row in r.rows), r.rows
    cl.close()


def test_range_offset_text_key_rejected(db):
    cl, _ = db
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError, match="numeric"):
        cl.execute("SELECT count(*) OVER (ORDER BY g RANGE BETWEEN 1 "
                   "PRECEDING AND CURRENT ROW) FROM t")
