"""End-to-end correctness vs two oracles.

1. sqlite3 executes the same SQL over the same rows (SQL semantics
   oracle) — the analog of the reference's pg_regress golden outputs.
2. The numpy cpu backend must produce *identical* rows to the jax
   backend (mesh path included) — the bit-exactness invariant that makes
   the psum combine trustworthy.
"""

import decimal
import sqlite3

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import ExecutorSettings, settings_override

N = 5000


@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    cl = ct.Cluster(str(tmp_path_factory.mktemp("db")))
    cl.execute("""CREATE TABLE events (
        id bigint NOT NULL, device bigint, kind text, qty decimal(12,2),
        score double, d date)""")
    cl.execute("SELECT create_distributed_table('events', 'id', 4)")
    rng = np.random.default_rng(11)
    kinds = ["click", "view", "buy", None]
    rows = []
    for i in range(N):
        rows.append((
            i,
            int(rng.integers(0, 50)) if rng.random() > 0.05 else None,
            kinds[int(rng.integers(0, 4))],
            round(float(rng.integers(0, 10000)) / 100, 2) if rng.random() > 0.1 else None,
            float(np.round(rng.random() * 100, 6)),
            f"202{int(rng.integers(0,4))}-0{int(rng.integers(1,10))}-1{int(rng.integers(0,10))}",
        ))
    cl.copy_from("events", rows=rows)

    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE events (id INTEGER, device INTEGER, kind TEXT, qty REAL, score REAL, d TEXT)")
    sq.executemany("INSERT INTO events VALUES (?,?,?,?,?,?)", rows)
    return cl, sq


QUERIES = [
    "SELECT count(*) FROM events",
    "SELECT count(device), count(kind), count(qty) FROM events",
    "SELECT sum(qty), min(qty), max(qty) FROM events",
    "SELECT avg(score) FROM events",
    "SELECT kind, count(*) FROM events GROUP BY kind ORDER BY kind NULLS LAST",
    "SELECT kind, sum(qty), avg(qty), min(score), max(score) FROM events GROUP BY kind ORDER BY kind NULLS LAST",
    "SELECT device, count(*) FROM events WHERE device IS NOT NULL GROUP BY device ORDER BY device LIMIT 10",
    "SELECT count(*) FROM events WHERE qty > 50 AND score < 40",
    "SELECT count(*) FROM events WHERE kind = 'click' OR kind = 'buy'",
    "SELECT count(*) FROM events WHERE d >= '2021-01-01' AND d < '2023-01-01'",
    "SELECT kind, count(*) FROM events WHERE device BETWEEN 10 AND 20 GROUP BY kind ORDER BY kind NULLS LAST",
    "SELECT device, kind, count(*), sum(qty) FROM events GROUP BY device, kind "
    "HAVING count(*) > 10 ORDER BY device NULLS LAST, kind NULLS LAST LIMIT 25",
    "SELECT count(*) FROM events WHERE kind IN ('click', 'view')",
    "SELECT count(*) FROM events WHERE kind LIKE 'c%'",
    "SELECT id, qty FROM events WHERE id = 777",
    "SELECT sum(qty * 2 + 1) FROM events WHERE device = 7",
    "SELECT count(*) FROM events WHERE NOT (score > 50)",
    "SELECT min(d), max(d) FROM events",
    "SELECT device FROM events WHERE id < 20 ORDER BY device NULLS FIRST LIMIT 5",
    "SELECT DISTINCT kind FROM events ORDER BY kind NULLS LAST",
]


def canon(rows):
    out = []
    for r in rows:
        row = []
        for v in r:
            if isinstance(v, decimal.Decimal):
                row.append(round(float(v), 4))
            elif isinstance(v, float):
                row.append(round(v, 4))
            elif hasattr(v, "isoformat"):
                row.append(v.isoformat())
            else:
                row.append(v)
        out.append(tuple(row))
    return out


@pytest.mark.parametrize("sql", QUERIES)
def test_vs_sqlite(loaded, sql):
    cl, sq = loaded
    ours = canon(cl.execute(sql).rows)
    theirs = canon(sq.execute(sql).fetchall())
    if "ORDER BY" not in sql:
        ours, theirs = sorted(ours, key=repr), sorted(theirs, key=repr)
    assert ours == pytest.approx(theirs, rel=1e-6, abs=1e-4) if _all_numeric(ours) \
        else ours == theirs


def _all_numeric(rows):
    return all(isinstance(v, (int, float)) and not isinstance(v, bool)
               for r in rows for v in r if v is not None)


@pytest.mark.parametrize("sql", QUERIES)
def test_jax_vs_cpu_identical(loaded, sql):
    cl, sq = loaded
    jax_rows = cl.execute(sql).rows
    with settings_override(executor=ExecutorSettings(task_executor_backend="cpu")):
        cpu_rows = cl.execute(sql).rows
    assert jax_rows == cpu_rows


def test_mesh_path_is_used(loaded):
    """The 8-device CPU mesh must actually take the shard_map branch."""
    import jax
    assert len(jax.devices()) == 8
    cl, _ = loaded
    from citus_tpu.planner import parse_sql
    from citus_tpu.planner.bind import bind_select
    from citus_tpu.planner.physical import plan_select
    bound = bind_select(cl.catalog, parse_sql("SELECT kind, count(*) FROM events GROUP BY kind")[0])
    plan = plan_select(cl.catalog, bound)
    from citus_tpu.executor.executor import _load_all_batches
    batches = _load_all_batches(cl.catalog, plan, cl.settings)
    assert len(batches) > 1  # multi-batch -> shard_map + psum path


def test_order_by_non_output_column(loaded):
    cl, sq = loaded
    sql = "SELECT kind FROM events WHERE id < 30 ORDER BY score LIMIT 10"
    ours = cl.execute(sql)
    theirs = sq.execute(sql).fetchall()
    assert ours.columns == ["kind"]
    assert ours.rows == [tuple(r) for r in theirs]
    # grouped query ordering by an aggregate not in the output
    sql2 = "SELECT kind FROM events GROUP BY kind ORDER BY count(*) DESC, kind NULLS LAST"
    ours2 = cl.execute(sql2).rows
    theirs2 = sq.execute(
        "SELECT kind FROM events GROUP BY kind "
        "ORDER BY count(*) DESC, kind IS NULL, kind").fetchall()
    assert ours2 == [tuple(r) for r in theirs2]


def test_coalesce_nullif_group_ordinals(loaded):
    cl, sq = loaded
    for sql in [
        "SELECT count(*) FROM events WHERE coalesce(device, 99) = 99",
        "SELECT coalesce(kind, 'none'), count(*) FROM events GROUP BY 1",
        "SELECT count(*) FROM events WHERE nullif(device, 7) IS NULL",
        "SELECT device, count(*) FROM events GROUP BY 1 ORDER BY 2 DESC LIMIT 5",
    ]:
        ours = sorted(canon(cl.execute(sql).rows), key=repr)
        theirs = sorted(canon(sq.execute(sql).fetchall()), key=repr)
        assert ours == theirs, sql


def test_having_without_group_by(loaded):
    cl, sq = loaded
    import sqlite3 as _sq3
    for sql, thresh in [
        ("SELECT count(*) FROM events HAVING count(*) > 10", 10),
        ("SELECT count(*) FROM events HAVING count(*) > 1000000", 1000000),
    ]:
        ours = cl.execute(sql).rows
        if _sq3.sqlite_version_info >= (3, 39):
            theirs = sq.execute(sql).fetchall()
        else:  # old sqlite rejects bare HAVING: apply the filter by hand
            n = sq.execute("SELECT count(*) FROM events").fetchall()[0][0]
            theirs = [(n,)] if n > thresh else []
        assert ours == [tuple(r) for r in theirs], sql


def test_boolean_column_end_to_end(tmp_path_factory):
    import citus_tpu as ct
    cl = ct.Cluster(str(tmp_path_factory.mktemp("booldb")), n_nodes=2)
    cl.execute("CREATE TABLE b (k bigint NOT NULL, flag boolean, v bigint)")
    cl.execute("SELECT create_distributed_table('b', 'k', 2)")
    cl.execute("INSERT INTO b VALUES (1, true, 10), (2, false, 20), (3, true, 30), (4, NULL, 40)")
    assert cl.execute("SELECT count(*) FROM b WHERE flag").rows == [(2,)]
    assert cl.execute("SELECT count(*) FROM b WHERE NOT flag").rows == [(1,)]
    rows = sorted(cl.execute("SELECT flag, sum(v) FROM b GROUP BY flag").rows, key=repr)
    assert rows == sorted([(True, 40), (False, 20), (None, 40)], key=repr)
