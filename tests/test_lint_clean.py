"""The shipped tree is cituslint-clean — tier-1 enforcement of every
rule in tools/cituslint (lock discipline, call confinement, silent
swallows, counter/GUC consistency, thread hygiene, pragma discipline).

Alongside it: regression tests for concrete races the lock rule
uncovered, and the fake-wall-clock seam the confinement sweep added.
"""

import os
import threading

import pytest

from tools.cituslint import run_lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_citus_tpu_is_lint_clean():
    diags = run_lint(os.path.join(REPO_ROOT, "citus_tpu"))
    assert diags == [], "cituslint diagnostics:\n" + "\n".join(
        str(d) for d in diags)


# ---------------------------------------------------------- regressions
# Races found by LOCK01 and fixed in the same sweep.  Each test drives
# the pre-fix interleaving hard enough to fail (flakily but reliably
# across the thread count) on the unguarded code.


def test_alloc_shard_id_is_race_free(tmp_path):
    """Catalog._alloc_shard_id read-increment-write ran without the
    catalog lock: two DDL threads could mint the SAME shard id."""
    from citus_tpu.catalog.catalog import Catalog

    cat = Catalog(str(tmp_path))
    ids, per_thread, n_threads = [], 200, 8
    out = [[] for _ in range(n_threads)]

    def mint(slot):
        for _ in range(per_thread):
            out[slot].append(cat._alloc_shard_id())

    threads = [threading.Thread(target=mint, args=(i,), daemon=True)
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for chunk in out:
        ids.extend(chunk)
    assert len(set(ids)) == n_threads * per_thread, "duplicate shard ids"


def test_tombstone_concurrent_with_commit_consume(tmp_path):
    """Catalog.tombstone mutated _tombstones unguarded while commit
    swaps the dict under the lock; concurrent drops must never lose an
    entry within one round."""
    from citus_tpu.catalog.catalog import Catalog

    cat = Catalog(str(tmp_path))
    n = 64

    def drop(i):
        cat.tombstone("tables", f"t{i}")

    threads = [threading.Thread(target=drop, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(cat._tombstones["tables"]) == n


def test_control_plane_stats_bumps_are_locked():
    """ControlPlane RPC handlers bumped self.stats['...'] += 1 from
    handler threads without the lock (lost updates under concurrency).
    The fix routes every bump through self._lock — assert the source
    invariant directly so a regression cannot reintroduce the bare
    increment."""
    from tools.cituslint import run_lint as lint

    diags = lint(os.path.join(REPO_ROOT, "citus_tpu"), select={"LOCK01"})
    assert diags == [], "\n".join(str(d) for d in diags)


# ----------------------------------------------------------- wall clock


@pytest.fixture
def fake_clock():
    from citus_tpu.utils import clock

    state = {"t": 1_000_000.0}
    clock.set_wall_clock(lambda: state["t"])
    try:
        yield state
    finally:
        clock.set_wall_clock(None)


def test_wall_clock_seam(fake_clock):
    from citus_tpu.utils import clock

    assert clock.now() == 1_000_000.0
    fake_clock["t"] += 5.5
    assert clock.now() == 1_000_005.5


def test_wall_clock_restore():
    import time

    from citus_tpu.utils import clock

    clock.set_wall_clock(lambda: 42.0)
    assert clock.now() == 42.0
    clock.set_wall_clock(None)
    assert abs(clock.now() - time.time()) < 5.0


def test_session_started_reads_fake_clock(fake_clock):
    """OpenTransaction.started (the deadlock victim policy's age) goes
    through the shared clock, so tests can age transactions without
    sleeping."""
    from citus_tpu.transaction.session import OpenTransaction

    old = OpenTransaction(xid=1, lock_sid=1)
    fake_clock["t"] += 100.0
    young = OpenTransaction(xid=2, lock_sid=2)
    assert young.started - old.started == 100.0


def test_activity_tracker_reads_fake_clock(fake_clock):
    """stats.py timestamps (activity view, tenant windows) follow the
    seam: advancing the fake clock moves measured durations exactly."""
    from citus_tpu.stats import ActivityTracker

    tr = ActivityTracker()
    gpid = tr.enter("SELECT 1")
    fake_clock["t"] += 30.0
    row = next(r for r in tr.rows_view() if r[0] == gpid)
    # (gpid, state, elapsed_s, sql, phase): exactly the fake delta
    assert row[2] == 30.0
    tr.exit(gpid)


# -------------------------------------------------- citussan (PR 14)


def test_concurrency_rules_clean():
    """LOCK02/BLK01/JIT01 on the shipped tree: the lock-order graph is
    acyclic, nothing blocks under a lock or on the event-loop thread
    without a reviewed pragma, and every jit-traced body is pure."""
    diags = run_lint(os.path.join(REPO_ROOT, "citus_tpu"),
                     select={"LOCK02", "BLK01", "JIT01"})
    assert diags == [], "\n".join(str(d) for d in diags)


def test_background_loop_thread_hygiene():
    """THR01/THR02 audit of the background loops (flight recorder,
    rollup refresh, event-loop wake channel, maintenance, cleaner):
    every thread has an explicit daemon= and a reachable bounded
    join, statically enforced."""
    diags = run_lint(os.path.join(REPO_ROOT, "citus_tpu"),
                     select={"THR01", "THR02"})
    assert diags == [], "\n".join(str(d) for d in diags)


def test_event_loop_stop_is_bounded_and_daemon():
    """Runtime half of the audit for the newest loop: the RpcEventLoop
    thread is a daemon, close() returns promptly (bounded join), the
    wake-channel socketpair is closed, and close() is idempotent."""
    import time

    from citus_tpu.net.event_loop import RpcEventLoop

    loop = RpcEventLoop()
    assert loop._thread.daemon is True
    # the service thread starts lazily on first submit; an unreachable
    # endpoint is fine — the future fails on the loop thread, and what
    # we assert is that close() still joins within its 5s bound
    fut = loop.submit(("127.0.0.1", 1), "ping", timeout=0.5)
    t0 = time.perf_counter()
    loop.close()
    assert time.perf_counter() - t0 < 6.0
    assert not loop._thread.is_alive()
    assert fut.done()
    loop.close()  # second close must not raise or hang
