"""INSERT ... ON CONFLICT (upsert) and SELECT DISTINCT ON.

Reference: PostgreSQL ON CONFLICT through the router modify path —
the reference requires the conflict target to include the distribution
column (multi_router_planner.c) so conflicts resolve within one shard
group; DISTINCT ON plans as Unique over Sort.
"""

import pytest

import citus_tpu as ct
from citus_tpu.errors import (
    AnalysisError, ExecutionError, UnsupportedFeatureError,
)


@pytest.fixture()
def kv(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE kv (k bigint NOT NULL, v bigint, note text)")
    cl.execute("SELECT create_distributed_table('kv','k',4)")
    cl.execute("INSERT INTO kv (k, v, note) VALUES "
               "(1, 10, 'a'), (2, 20, 'b'), (3, 30, 'c')")
    return cl


def test_do_nothing(kv):
    r = kv.execute("INSERT INTO kv (k, v) VALUES (1, 99), (5, 50) "
                   "ON CONFLICT (k) DO NOTHING")
    assert r.explain["inserted"] == 1 and r.explain["skipped"] == 1
    assert kv.execute("SELECT v FROM kv WHERE k = 1").rows == [(10,)]
    assert kv.execute("SELECT v FROM kv WHERE k = 5").rows == [(50,)]


def test_do_update_with_excluded(kv):
    r = kv.execute("INSERT INTO kv (k, v) VALUES (2, 7), (6, 60) "
                   "ON CONFLICT (k) DO UPDATE SET v = excluded.v + kv.v")
    assert r.explain == {"inserted": 1, "updated": 1, "skipped": 0,
                         "strategy": "upsert"}
    assert kv.execute("SELECT v FROM kv WHERE k = 2").rows == [(27,)]


def test_do_update_where_clause(kv):
    kv.execute("INSERT INTO kv (k, v) VALUES (3, 1) "
               "ON CONFLICT (k) DO UPDATE SET v = excluded.v WHERE kv.v > 25")
    assert kv.execute("SELECT v FROM kv WHERE k = 3").rows == [(1,)]
    kv.execute("INSERT INTO kv (k, v) VALUES (3, 2) "
               "ON CONFLICT (k) DO UPDATE SET v = excluded.v WHERE kv.v > 25")
    assert kv.execute("SELECT v FROM kv WHERE k = 3").rows == [(1,)]


def test_intra_batch_conflict(kv):
    # a row inserted earlier in the same command conflicts with a later one
    r = kv.execute("INSERT INTO kv (k, v) VALUES (9, 1), (9, 2) "
                   "ON CONFLICT (k) DO NOTHING")
    assert r.explain["inserted"] == 1 and r.explain["skipped"] == 1
    with pytest.raises(ExecutionError):
        kv.execute("INSERT INTO kv (k, v) VALUES (8, 1), (8, 2) "
                   "ON CONFLICT (k) DO UPDATE SET v = excluded.v")
    # ... and twice against the same PRE-EXISTING row (PG error 21000)
    with pytest.raises(ExecutionError):
        kv.execute("INSERT INTO kv (k, v) VALUES (1, 5), (1, 6) "
                   "ON CONFLICT (k) DO UPDATE SET v = excluded.v")


def test_decimal_date_key_normalization(tmp_path):
    """Proposed key values must compare equal to stored rows after the
    physical round-trip (5.0 vs Decimal('5.00'), string vs date)."""
    cl = ct.Cluster(str(tmp_path / "dbn"))
    cl.execute("CREATE TABLE p (k bigint NOT NULL, amt decimal(8,2), d date, "
               "v bigint)")
    cl.execute("SELECT create_distributed_table('p','k',2)")
    cl.execute("INSERT INTO p VALUES (1, 5.00, '2020-01-01', 1)")
    r = cl.execute("INSERT INTO p VALUES (1, 5.0, '2020-01-01', 2) "
                   "ON CONFLICT (k, amt, d) DO UPDATE SET v = excluded.v")
    assert r.explain["updated"] == 1
    assert cl.execute("SELECT v FROM p WHERE k = 1").rows == [(2,)]


def test_filter_on_scalar_function_rejected(kv):
    with pytest.raises(AnalysisError):
        kv.execute("SELECT abs(v) FILTER (WHERE v > 0) FROM kv")


def test_filter_survives_param_plans(kv):
    r = kv.execute("SELECT count(*) FILTER (WHERE v > $1) FROM kv",
                   params=[15])
    total = kv.execute("SELECT count(*) FILTER (WHERE v > 15) FROM kv").rows
    assert r.rows == total
    assert r.rows[0][0] < kv.execute("SELECT count(*) FROM kv").rows[0][0]


def test_null_key_never_conflicts(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db2"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, u bigint, v bigint)")
    cl.execute("SELECT create_distributed_table('t','k',2)")
    cl.execute("INSERT INTO t VALUES (1, NULL, 1)")
    r = cl.execute("INSERT INTO t VALUES (1, NULL, 2) "
                   "ON CONFLICT (k, u) DO NOTHING")
    assert r.explain["inserted"] == 1
    assert cl.execute("SELECT count(*) FROM t").rows == [(2,)]


def test_validation_errors(kv):
    with pytest.raises(UnsupportedFeatureError):
        kv.execute("INSERT INTO kv (k, v) VALUES (1, 1) "
                   "ON CONFLICT (v) DO NOTHING")      # missing distcol
    with pytest.raises(UnsupportedFeatureError):
        kv.execute("INSERT INTO kv (k, v) VALUES (1, 1) "
                   "ON CONFLICT DO NOTHING")           # no explicit target
    with pytest.raises(UnsupportedFeatureError):
        kv.execute("INSERT INTO kv (k, v) VALUES (1, 1) "
                   "ON CONFLICT (k) DO UPDATE SET k = 5")  # distcol update
    with pytest.raises(AnalysisError):
        kv.execute("INSERT INTO kv (k, v) VALUES (1, 1) "
                   "ON CONFLICT (nope) DO NOTHING")


def test_upsert_text_and_multi_key(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db3"))
    cl.execute("CREATE TABLE s (k bigint NOT NULL, tag text, v bigint)")
    cl.execute("SELECT create_distributed_table('s','k',2)")
    cl.execute("INSERT INTO s VALUES (1, 'x', 1), (1, 'y', 2)")
    r = cl.execute("INSERT INTO s VALUES (1, 'x', 9), (1, 'z', 3) "
                   "ON CONFLICT (k, tag) DO UPDATE SET v = excluded.v")
    assert r.explain["inserted"] == 1 and r.explain["updated"] == 1
    assert cl.execute("SELECT tag, v FROM s WHERE k = 1 ORDER BY tag").rows \
        == [("x", 9), ("y", 2), ("z", 3)]


# ------------------------------------------------------------ DISTINCT ON

@pytest.fixture()
def events(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db4"))
    cl.execute("CREATE TABLE e (id bigint NOT NULL, dev bigint, ts bigint, "
               "v double)")
    cl.execute("SELECT create_distributed_table('e','id',4)")
    rows = [(i, i % 3, (i * 7) % 20, float(i)) for i in range(60)]
    cl.copy_from("e", rows=rows)
    return cl, rows


def test_distinct_on_latest_per_group(events):
    cl, rows = events
    got = cl.execute("SELECT DISTINCT ON (dev) dev, ts, v FROM e "
                     "ORDER BY dev, ts DESC, v DESC").rows
    best = {}
    for i, d, t, v in rows:
        if d not in best or (t, v) > best[d]:
            best[d] = (t, v)
    assert got == [(d,) + best[d] for d in sorted(best)]


def test_distinct_on_with_limit_and_outer_order(events):
    cl, _ = events
    got = cl.execute("SELECT DISTINCT ON (dev) dev, ts FROM e "
                     "ORDER BY dev DESC, ts LIMIT 2").rows
    assert [r[0] for r in got] == [2, 1]


def test_distinct_on_requires_matching_order(events):
    cl, _ = events
    with pytest.raises(AnalysisError):
        cl.execute("SELECT DISTINCT ON (dev) dev, ts FROM e ORDER BY ts")


def test_distinct_on_no_order_by(events):
    cl, _ = events
    got = cl.execute("SELECT DISTINCT ON (dev) dev FROM e").rows
    assert sorted(r[0] for r in got) == [0, 1, 2]


def test_upsert_requires_update_privilege(kv):
    kv.execute("CREATE ROLE bob")
    kv.execute("GRANT INSERT ON kv TO bob")
    from citus_tpu.errors import CatalogError
    with pytest.raises(CatalogError):
        kv.execute("INSERT INTO kv (k, v) VALUES (1, 5) "
                   "ON CONFLICT (k) DO UPDATE SET v = excluded.v", role="bob")
    kv.execute("GRANT UPDATE ON kv TO bob")
    kv.execute("INSERT INTO kv (k, v) VALUES (1, 5) "
               "ON CONFLICT (k) DO UPDATE SET v = excluded.v", role="bob")
    assert kv.execute("SELECT v FROM kv WHERE k = 1").rows == [(5,)]


def test_upsert_respects_update_rls(tmp_path):
    cl = ct.Cluster(str(tmp_path / "dbrls"))
    cl.execute("CREATE TABLE t (id bigint NOT NULL, tenant bigint, v bigint)")
    cl.execute("SELECT create_distributed_table('t','id',2)")
    cl.execute("INSERT INTO t VALUES (2, 2, 200)")
    cl.execute("CREATE ROLE bob")
    cl.execute("GRANT INSERT ON t TO bob")
    cl.execute("GRANT UPDATE ON t TO bob")
    cl.execute("GRANT SELECT ON t TO bob")
    cl.execute("ALTER TABLE t ENABLE ROW LEVEL SECURITY")
    cl.execute("CREATE POLICY p ON t USING (tenant = 1) "
               "WITH CHECK (tenant = 1)")
    # the conflicting row belongs to tenant 2: bob's UPDATE policy must
    # block the DO UPDATE (PostgreSQL raises an RLS violation)
    with pytest.raises(AnalysisError):
        cl.execute("INSERT INTO t VALUES (2, 1, 999) "
                   "ON CONFLICT (id) DO UPDATE SET v = excluded.v",
                   role="bob")
    assert cl.execute("SELECT v FROM t WHERE id = 2").rows == [(200,)]


def test_atomic_duplicate_update_rejection(kv):
    """Duplicate DO UPDATE keys abort BEFORE any update applies."""
    with pytest.raises(ExecutionError):
        kv.execute("INSERT INTO kv (k, v) VALUES (1, 200), (1, 300) "
                   "ON CONFLICT (k) DO UPDATE SET v = excluded.v")
    assert kv.execute("SELECT v FROM kv WHERE k = 1").rows == [(10,)]


def test_distinct_on_survives_function_catalog(events):
    """SQL-function expansion must not strip distinct_on."""
    cl, _ = events
    cl.execute("CREATE FUNCTION addone(x bigint) RETURNS bigint AS 'x + 1'")
    got = cl.execute("SELECT DISTINCT ON (dev) dev, ts FROM e "
                     "ORDER BY dev, ts DESC").rows
    assert len(got) == 3
    assert len({r[0] for r in got}) == 3


def test_distinct_on_parameterized(events):
    cl, _ = events
    got = cl.execute("SELECT DISTINCT ON (dev) dev, ts FROM e "
                     "WHERE id < $1 ORDER BY dev, ts DESC", params=[60]).rows
    assert len(got) == 3


def test_agg_order_survives_function_inlining(tmp_path):
    """Macro parameters inside an aggregate's ORDER BY substitute too."""
    cl = ct.Cluster(str(tmp_path / "dbfn"))
    cl.execute("CREATE TABLE w (id bigint NOT NULL, g bigint, s text)")
    cl.execute("SELECT create_distributed_table('w','id',2)")
    cl.execute("INSERT INTO w VALUES (1, 1, 'a'), (2, 1, 'b'), (3, 1, 'c')")
    cl.execute("CREATE FUNCTION cat(k bigint) RETURNS text AS "
               "'string_agg(s, '','' ORDER BY id * k)'")
    r = cl.execute("SELECT g, cat(-1) FROM w GROUP BY g").rows
    assert r == [(1, "c,b,a")]


def test_distinct_on_expression(events):
    cl, _ = events
    got = cl.execute("SELECT DISTINCT ON (dev % 2) dev % 2, ts FROM e "
                     "ORDER BY dev % 2, ts DESC").rows
    assert [r[0] for r in got] == [0, 1]
    assert all(r[1] == 19 for r in got)


def test_insert_select_on_conflict(tmp_path):
    """INSERT..SELECT ... ON CONFLICT (pull strategy + upsert
    machinery; reference: insert_select_executor.c's pull path handles
    ON CONFLICT via colocated intermediate results)."""
    import citus_tpu as ct
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE dst (k bigint NOT NULL PRIMARY KEY, "
               "v bigint)")
    cl.execute("SELECT create_distributed_table('dst', 'k', 4)")
    cl.execute("CREATE TABLE src (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('src', 'k', 4, 'dst')")
    cl.copy_from("dst", rows=[(1, 10), (2, 20)])
    cl.copy_from("src", rows=[(1, 111), (3, 333)])
    r = cl.execute("INSERT INTO dst SELECT k, v FROM src "
                   "ON CONFLICT (k) DO UPDATE SET v = excluded.v")
    assert r.explain["inserted"] == 1 and r.explain["updated"] == 1
    assert sorted(cl.execute("SELECT k, v FROM dst").rows) == \
        [(1, 111), (2, 20), (3, 333)]
    # DO NOTHING flavor
    r = cl.execute("INSERT INTO dst SELECT k, v FROM src "
                   "ON CONFLICT (k) DO NOTHING")
    assert r.explain["skipped"] == 2 and r.explain["inserted"] == 0
    cl.close()
