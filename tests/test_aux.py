"""Auxiliary subsystems: causal clock, CDC, restore points, MX
(query-from-any-node), CSV COPY, alter/undistribute."""

import dataclasses
import os

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import Settings


def test_causal_clock_monotone_and_persistent(tmp_path):
    from citus_tpu.utils.clock import CausalClock, unpack
    c = CausalClock(str(tmp_path))
    vals = [c.now() for _ in range(1000)]
    assert vals == sorted(vals)
    assert len(set(vals)) == 1000
    # adjust merges remote clocks
    future = vals[-1] + (1 << 30)
    after = c.adjust(future)
    assert after > future
    # restart never goes backwards
    c._persist_at = 0
    c.now()
    c2 = CausalClock(str(tmp_path))
    assert c2.now() > vals[-1]


def test_clock_udfs(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=1)
    a = cl.execute("SELECT citus_get_node_clock()").rows[0][0]
    b = cl.execute("SELECT citus_get_transaction_clock()").rows[0][0]
    assert b > a


def test_cdc_insert_stream(tmp_path):
    st = Settings(enable_change_data_capture=True)
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=1, settings=st)
    cl.execute("CREATE TABLE t (a bigint, s text)")
    cl.execute("INSERT INTO t VALUES (1, 'x'), (2, 'y')")
    cl.execute("INSERT INTO t VALUES (3, NULL)")
    events = list(cl.cdc.events("t"))
    assert len(events) == 2
    assert events[0]["op"] == "insert"
    assert events[0]["rows"] == [[1, "x"], [2, "y"]]
    assert events[1]["rows"] == [[3, None]]
    assert events[1]["lsn"] > events[0]["lsn"]
    # resume from lsn
    resumed = list(cl.cdc.events("t", from_lsn=events[0]["lsn"]))
    assert len(resumed) == 1


def test_cdc_disabled_by_default(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=1)
    cl.execute("CREATE TABLE t (a bigint)")
    cl.execute("INSERT INTO t VALUES (1)")
    assert list(cl.cdc.events("t")) == []


def test_restore_point_roundtrip(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={"k": np.arange(100, dtype=np.int64),
                               "v": np.arange(100, dtype=np.int64)})
    cl.execute("SELECT citus_create_restore_point('before_damage')")
    assert cl.execute("SELECT citus_list_restore_points()").rows[0][0] == "before_damage"
    # damage: more inserts + deletes
    cl.copy_from("t", columns={"k": np.arange(100, 200, dtype=np.int64),
                               "v": np.zeros(100, dtype=np.int64)})
    cl.execute("DELETE FROM t WHERE k < 50")
    assert cl.execute("SELECT count(*) FROM t").rows == [(150,)]
    from citus_tpu.operations.restore import restore_to_point
    restore_to_point(cl.catalog, "before_damage")
    cl2 = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    assert cl2.execute("SELECT count(*) FROM t").rows == [(100,)]
    assert cl2.execute("SELECT sum(v) FROM t").rows == [(4950,)]


def test_query_from_any_node(tmp_path):
    """Two coordinators over the same metadata (the MX model)."""
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    a.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('t', 'k', 4)")
    a.copy_from("t", columns={"k": np.arange(500, dtype=np.int64),
                              "v": np.ones(500, dtype=np.int64)})
    b = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    assert b.execute("SELECT count(*) FROM t").rows == [(500,)]
    # writes through B become visible to A (catalog mtime reload)
    b.copy_from("t", columns={"k": np.arange(500, 600, dtype=np.int64),
                              "v": np.ones(100, dtype=np.int64)})
    assert a.execute("SELECT count(*) FROM t").rows == [(600,)]
    # DDL through B visible to A
    b.execute("CREATE TABLE u (x bigint)")
    b.execute("INSERT INTO u VALUES (7)")
    assert a.execute("SELECT x FROM u").rows == [(7,)]


def test_copy_from_csv(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=1)
    cl.execute("CREATE TABLE t (k bigint, name text, price decimal(8,2), d date)")
    cl.execute("SELECT create_distributed_table('t', 'k', 2)")
    csv_path = tmp_path / "data.csv"
    csv_path.write_text(
        "k,name,price,d\n"
        "1,apple,1.50,2024-01-01\n"
        "2,banana,0.25,2024-01-02\n"
        "3,,NULL_VAL,2024-01-03\n")
    n = cl.execute(
        f"COPY t FROM '{csv_path}' WITH (header true, null 'NULL_VAL')").explain["copied"]
    assert n == 3
    rows = cl.execute("SELECT k, name, price FROM t ORDER BY k").rows
    assert rows[0][1] == "apple"
    assert rows[2][2] is None
    import decimal
    assert rows[1][2] == decimal.Decimal("0.25")


def test_alter_distributed_table_reshard(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, s text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 2)")
    cl.copy_from("t", rows=[(i, i % 7, ["a", "b"][i % 2]) for i in range(3000)])
    before = sorted(cl.execute("SELECT v, count(*) FROM t GROUP BY v").rows)
    cl.execute("SELECT alter_distributed_table('t', 8)")
    t = cl.catalog.table("t")
    assert t.shard_count == 8
    assert sorted(cl.execute("SELECT v, count(*) FROM t GROUP BY v").rows) == before
    assert cl.execute("SELECT count(*) FROM t WHERE k = 77").rows == [(1,)]
    # change distribution column too
    cl.execute("SELECT alter_distributed_table('t', 4, 'v')")
    assert cl.catalog.table("t").dist_column == "v"
    assert sorted(cl.execute("SELECT v, count(*) FROM t GROUP BY v").rows) == before


def test_undistribute_table(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", rows=[(i, i) for i in range(1000)])
    cl.execute("SELECT undistribute_table('t')")
    t = cl.catalog.table("t")
    assert not t.is_distributed
    assert t.shard_count == 1
    assert cl.execute("SELECT count(*), sum(v) FROM t").rows == [(1000, 499500)]


def test_copy_to_roundtrip(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=1)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, name text, price decimal(8,2))")
    cl.execute("SELECT create_distributed_table('t', 'k', 2)")
    cl.copy_from("t", rows=[(1, "a", 1.25), (2, None, 3.5), (3, "c", None)])
    out = tmp_path / "export.csv"
    r = cl.execute(f"COPY t TO '{out}' WITH (header true)")
    assert r.explain["copied"] == 3
    cl.execute("CREATE TABLE t2 (k bigint NOT NULL, name text, price decimal(8,2))")
    cl.execute("SELECT create_distributed_table('t2', 'k', 2)")
    cl.execute(f"COPY t2 FROM '{out}' WITH (header true, null '')")
    assert sorted(cl.execute("SELECT k, name, price FROM t2").rows) == \
        sorted(cl.execute("SELECT k, name, price FROM t").rows)


def test_copy_to_honors_null_option(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=1)
    cl.execute("CREATE TABLE t (k bigint, s text)")
    cl.copy_from("t", rows=[(1, ""), (2, None)])
    out = tmp_path / "e.csv"
    cl.execute(f"COPY t TO '{out}' WITH (null 'NULLVAL')")
    body = out.read_text()
    assert "NULLVAL" in body
    # roundtrip preserves the empty-string / NULL distinction
    cl.execute("CREATE TABLE t2 (k bigint, s text)")
    cl.execute(f"COPY t2 FROM '{out}' WITH (null 'NULLVAL')")
    rows = dict(cl.execute("SELECT k, s FROM t2").rows)
    assert rows[1] == "" and rows[2] is None


def test_cdc_captures_dml(tmp_path):
    """CDC covers UPDATE/DELETE/MERGE/TRUNCATE (statement-level with
    counts) and exposes the subscriber read API."""
    from citus_tpu.config import Settings
    cl = ct.Cluster(str(tmp_path / "cdcdml"),
                    settings=Settings(enable_change_data_capture=True))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.execute("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
    cl.execute("UPDATE t SET v = v + 1 WHERE k < 3")
    cl.execute("DELETE FROM t WHERE k = 1")
    cl.execute("TRUNCATE t")
    evs = [tuple(r[:3]) for r in cl.execute("SELECT citus_cdc_events('t')").rows]
    assert [e[1] for e in evs] == ["insert", "update", "delete", "truncate"]
    assert evs[1][2] == 2 and evs[2][2] == 1
    lsns = [e[0] for e in evs]
    assert lsns == sorted(lsns)  # HLC-ordered
    later = cl.execute(f"SELECT citus_cdc_events('t', {lsns[1]})").rows
    assert [r[1] for r in later] == ["delete", "truncate"]
    cl.close()
