"""Transactional DDL: CREATE/DROP TABLE, indexes, views, sequences &c.
staged inside BEGIN..COMMIT and discarded by ROLLBACK.

Reference: citus_ProcessUtility runs DDL inside transaction blocks with
2PC (src/backend/distributed/commands/utility_hook.c:148; the 6-step
sequence in distributed/README.md:1773-1799).  TPU-native shape: DDL
mutates the in-memory catalog, Catalog.commit() defers persistence into
the OpenTransaction, COMMIT persists once under the DDL lease,
ROLLBACK reloads the untouched on-disk document; irreversible file
actions (drops) defer to COMMIT, reversible artifacts (index segments)
register rollback cleanups.
"""

import os

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import CatalogError, UnsupportedFeatureError


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"))
    c.execute("CREATE TABLE base (k bigint, v bigint)")
    c.execute("SELECT create_distributed_table('base', 'k', 4)")
    c.copy_from("base", rows=[(i, i * 10) for i in range(100)])
    return c


def test_create_table_rollback_leaves_no_trace(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("CREATE TABLE t2 (a bigint, b text)")
    s.execute("INSERT INTO t2 VALUES (1, 'x'), (2, 'y')")
    assert s.execute("SELECT count(*) FROM t2").rows == [(2,)]
    s.execute("ROLLBACK")
    assert not cl.catalog.has_table("t2")
    with pytest.raises(Exception):
        cl.execute("SELECT count(*) FROM t2")


def test_create_distribute_ingest_commit_is_atomic(cl, tmp_path):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("CREATE TABLE ev (id bigint, amt decimal(8,2))")
    s.execute("SELECT create_distributed_table('ev', 'id', 4)")
    s.copy_from("ev", rows=[(i, i / 4) for i in range(1000)])
    # isolation: a second coordinator on the same data dir must not see
    # the staged table before COMMIT (reference: uncommitted DDL is
    # invisible to other backends)
    peer = ct.Cluster(str(tmp_path / "db"))
    assert not peer.catalog.has_table("ev")
    s.execute("COMMIT")
    assert cl.execute("SELECT count(*) FROM ev").rows == [(1000,)]
    t = cl.catalog.table("ev")
    assert t.is_distributed and t.shard_count == 4
    peer2 = ct.Cluster(str(tmp_path / "db"))
    assert peer2.execute("SELECT count(*) FROM ev").rows == [(1000,)]


def test_drop_table_rollback_keeps_table_and_files(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("DROP TABLE base")
    assert not cl.catalog.has_table("base")  # staged: invisible in-session
    s.execute("ROLLBACK")
    assert cl.catalog.has_table("base")
    assert cl.execute("SELECT count(*) FROM base").rows == [(100,)]


def test_drop_table_commit_removes_files(cl):
    data_root = os.path.join(cl.catalog.data_dir, "data", "base")
    assert os.path.isdir(data_root)
    s = cl.session()
    s.execute("BEGIN")
    s.execute("DROP TABLE base")
    assert os.path.isdir(data_root)  # file removal deferred to COMMIT
    s.execute("COMMIT")
    assert not cl.catalog.has_table("base")
    assert not os.path.isdir(data_root)


def _seg_files(cl, table, column):
    t = cl.catalog.table(table)
    out = []
    for shard in t.shards:
        for node in shard.placements:
            d = cl.catalog.shard_dir(table, shard.shard_id, node)
            if os.path.isdir(d):
                out += [f for f in os.listdir(d)
                        if f.endswith(f".idx.{column}.npz")]
    return out


def test_create_index_rollback_removes_segments(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("CREATE INDEX base_v ON base (v)")
    assert _seg_files(cl, "base", "v")  # backfilled (staged)
    s.execute("ROLLBACK")
    assert cl.catalog.table("base").indexes == []
    assert _seg_files(cl, "base", "v") == []


def test_drop_index_rollback_keeps_segments(cl):
    cl.execute("CREATE INDEX base_v ON base (v)")
    s = cl.session()
    s.execute("BEGIN")
    s.execute("DROP INDEX base_v")
    assert cl.catalog.table("base").indexes == []
    assert _seg_files(cl, "base", "v")  # physical drop deferred
    s.execute("ROLLBACK")
    assert cl.catalog.table("base").index_on("v") is not None
    assert _seg_files(cl, "base", "v")
    r = cl.execute("EXPLAIN SELECT count(*) FROM base WHERE v = 50")
    assert any("Index Lookup" in row[0] for row in r.rows)


def test_create_index_commit_enforces_unique(cl):
    from citus_tpu.integrity import UniqueViolation
    s = cl.session()
    s.execute("BEGIN")
    s.execute("CREATE UNIQUE INDEX base_k ON base (k)")
    s.execute("COMMIT")
    with pytest.raises(UniqueViolation):
        cl.copy_from("base", rows=[(5, 999)])


def test_savepoint_rolls_back_ddl(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("CREATE TABLE a1 (x bigint)")
    s.execute("SAVEPOINT sp")
    s.execute("CREATE TABLE b1 (y bigint)")
    s.execute("CREATE INDEX base_v ON base (v)")
    assert cl.catalog.has_table("b1")
    s.execute("ROLLBACK TO SAVEPOINT sp")
    assert cl.catalog.has_table("a1")
    assert not cl.catalog.has_table("b1")
    assert cl.catalog.table("base").indexes == []
    assert _seg_files(cl, "base", "v") == []
    s.execute("COMMIT")
    assert cl.catalog.has_table("a1")
    assert not cl.catalog.has_table("b1")


def test_catalog_objects_stage_and_rollback(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("CREATE VIEW v1 AS SELECT k FROM base WHERE v > 500")
    s.execute("CREATE SEQUENCE seq1 START 10")
    s.execute("CREATE TYPE mood AS ENUM ('sad', 'ok', 'happy')")
    s.execute("CREATE ROLE analyst")
    assert s.execute("SELECT count(*) FROM v1").rows == [(49,)]
    s.execute("ROLLBACK")
    assert "v1" not in cl.catalog.views
    assert "seq1" not in cl.catalog.sequences
    assert "mood" not in cl.catalog.types
    assert "analyst" not in cl.catalog.roles


def test_failed_statement_after_ddl_rolls_back_cleanly(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("CREATE TABLE t3 (a bigint NOT NULL)")
    with pytest.raises(Exception):
        s.execute("INSERT INTO t3 VALUES (NULL)")
    r = s.execute("COMMIT")  # aborted block: rolls back
    assert r.explain.get("transaction") == "rollback"
    assert not cl.catalog.has_table("t3")


def test_nextval_block_reservation_after_ddl_refused(cl):
    cl.execute("CREATE SEQUENCE s1")
    s = cl.session()
    s.execute("BEGIN")
    s.execute("CREATE TABLE t4 (a bigint)")
    with pytest.raises(UnsupportedFeatureError):
        s.execute("SELECT nextval('s1')")
    s.execute("ROLLBACK")
    # outside the block the sequence works
    assert cl.execute("SELECT nextval('s1')").rows == [(1,)]


def test_drop_recreate_table_in_txn_keeps_new_data(cl):
    """The deferred file removal of the dropped incarnation must not
    destroy the recreated table's committed data."""
    s = cl.session()
    s.execute("BEGIN")
    s.execute("DROP TABLE base")
    s.execute("CREATE TABLE base (k bigint, v bigint)")
    s.execute("INSERT INTO base VALUES (1, 111)")
    s.execute("COMMIT")
    assert cl.execute("SELECT count(*), sum(v) FROM base").rows == [(1, 111)]


def test_drop_recreate_index_same_column_in_txn(cl):
    cl.execute("CREATE INDEX base_v ON base (v)")
    s = cl.session()
    s.execute("BEGIN")
    s.execute("DROP INDEX base_v")
    s.execute("CREATE INDEX base_v2 ON base (v)")
    s.execute("COMMIT")
    # the recreated index's segments survived the deferred drop
    assert _seg_files(cl, "base", "v")
    r = cl.execute("EXPLAIN SELECT count(*) FROM base WHERE v = 50")
    assert any("base_v2" in row[0] for row in r.rows)
    assert cl.execute("SELECT count(*) FROM base WHERE v = 500").rows == [(1,)]


def test_concurrent_autocommit_ddl_blocked_while_staging(cl):
    """Another session's catalog persist must not leak staged DDL; it
    waits for the staging transaction (and times out, like a lock)."""
    from citus_tpu.utils.filelock import LockTimeout
    s = cl.session()
    s.execute("BEGIN")
    s.execute("CREATE TABLE staged_t (a bigint)")
    with pytest.raises(LockTimeout):
        cl.catalog._await_no_staging(timeout=0.2)
    s.execute("ROLLBACK")
    cl.catalog._await_no_staging(timeout=0.2)  # free again
    assert not cl.catalog.has_table("staged_t")
    cl.execute("CREATE TABLE other (b bigint)")  # proceeds normally
    assert cl.catalog.has_table("other")


def test_ddl_commit_is_crash_atomic(cl, tmp_path):
    """Kill between stage and COMMIT: a fresh coordinator sees nothing
    (the on-disk document was never touched)."""
    s = cl.session()
    s.execute("BEGIN")
    s.execute("CREATE TABLE ghost (a bigint)")
    s.copy_from("ghost", rows=[(1,)])
    # simulate a crash: abandon the session/process without COMMIT
    fresh = ct.Cluster(str(tmp_path / "db"))
    assert not fresh.catalog.has_table("ghost")
    fresh.maintenance.run_once()  # 2PC recovery sweeps the orphan xid
    assert not fresh.catalog.has_table("ghost")