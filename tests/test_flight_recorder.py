"""Cluster flight recorder (citus_tpu/observability/flight_recorder.py):
ring history + rates, disk segment rotation/retention, the health engine
(typed events, dedup, resolution, advisory shedding), counters-reset
coherence, HBM accounting invariants, and EXPLAIN ANALYZE's Memory line.
"""

import json
import threading
import time

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import ExecutorSettings, Settings, WorkloadSettings
from citus_tpu.errors import AdmissionShedError
from citus_tpu.executor.admission import SharedTaskPool
from citus_tpu.executor.device_cache import GLOBAL_CACHE
from citus_tpu.observability.flight_recorder import (
    ADVISORY, HEALTH_EVENT_KINDS, PAYLOAD_SAMPLES,
)
from citus_tpu.workload import TenantScheduler


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"))
    c.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    c.execute("SELECT create_distributed_table('t', 'k', 4)")
    c.copy_from("t", columns={"k": np.arange(2000),
                              "v": np.arange(2000) * 2})
    yield c
    c.close()
    ADVISORY.pool_saturated = False  # process-global advisory: reset


# ----------------------------------------------------- ring + history


def test_ring_history_and_rates(cl):
    rec = cl.flight_recorder
    rec.run_once()
    cl.execute("SELECT count(*) FROM t")
    rec.run_once()
    rows = rec.history_rows(metric="queries_executed")
    assert len(rows) == 2
    ts = [r[0] for r in rows]
    assert ts == sorted(ts) and ts[0] < ts[1]
    assert rows[0][3] is None          # first sample has no rate base
    assert rows[1][3] is not None and rows[1][3] >= 0
    # the executed query moved the counter between the ticks
    assert rows[1][2] > rows[0][2]


def test_history_filter_limit_and_payload_bound(cl):
    rec = cl.flight_recorder
    for _ in range(3):
        rec.run_once()
    all_rows = rec.history_rows(metric="queries_executed")
    assert len(all_rows) == 3
    limited = rec.history_rows(metric="queries_executed", limit=1)
    assert len(limited) == 1
    assert limited[0][0] == all_rows[-1][0]
    # the dropped preceding sample still serves as the rate base
    assert limited[0][3] is not None
    # a generous lookback keeps everything; metric filter holds
    recent = rec.history_rows(metric="queries_executed", since_s=3600)
    assert len(recent) == 3
    assert all(r[1] == "queries_executed" for r in recent)
    payload = rec.export_payload()
    assert set(payload) == {"history", "health"}
    samples = {r[0] for r in payload["history"]}
    assert len(samples) <= PAYLOAD_SAMPLES


def test_sql_stat_history_single_node(cl):
    rec = cl.flight_recorder
    rec.run_once()
    cl.execute("SELECT sum(v) FROM t")
    rec.run_once()
    res = cl.execute("SELECT citus_stat_history('queries_executed')")
    assert res.columns == ["ts", "node", "metric", "value", "rate"]
    assert len(res.rows) == 2
    assert all(r[2] == "queries_executed" for r in res.rows)
    ts = [r[0] for r in res.rows]
    assert ts == sorted(ts)
    # the since_s window form parses and filters
    res2 = cl.execute(
        "SELECT citus_stat_history('queries_executed', 3600)")
    assert len(res2.rows) == 2


def test_guc_starts_and_stops_sampler_thread(cl):
    rec = cl.flight_recorder
    assert rec._thread is None  # off by default (interval 0)
    cl.execute("SET citus.flight_recorder_interval_ms = 10")
    assert rec._thread is not None and rec._thread.is_alive()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if cl.counters.snapshot().get("flight_recorder_ticks", 0) >= 2:
            break
        time.sleep(0.01)
    assert cl.counters.snapshot()["flight_recorder_ticks"] >= 2
    assert rec.history_rows(metric="queries_executed")
    cl.execute("SET citus.flight_recorder_interval_ms = 0")
    assert rec._thread is None  # stop() joins before returning


# ------------------------------------------------------------ disk log


def test_segment_spill_rotation_and_retention(cl):
    rec = cl.flight_recorder
    # drive the spill path with synthetic timestamps: default retention
    # 3600s rotates every 900s and prunes segments older than 3600s
    rotations0 = cl.counters.snapshot()["flight_recorder_rotations"]
    rec._spill(1000.0, {"a": 1})
    rec._spill(1000.5, {"a": 2})
    segs = rec.segment_files()
    assert len(segs) == 1
    lines = open(segs[0]).read().splitlines()
    assert len(lines) == 2
    assert json.loads(lines[0]) == {"ts": 1000.0, "m": {"a": 1}}
    rec._spill(1000.0 + 901, {"a": 3})     # past retention/4: rotate
    assert len(rec.segment_files()) == 2
    rec._spill(1000.0 + 7200, {"a": 4})    # both old segments expired
    segs = rec.segment_files()
    assert len(segs) == 1
    assert segs[0].endswith(f"seg_{int((1000.0 + 7200) * 1000)}.jsonl")
    assert cl.counters.snapshot()["flight_recorder_rotations"] \
        - rotations0 == 3


# ------------------------------------------------------- health engine


def _feed(rec, metric_dicts, monkeypatch):
    """Run one tick per dict with _collect() stubbed to return it."""
    seq = iter(metric_dicts)
    monkeypatch.setattr(rec, "_collect", lambda: next(seq))
    for _ in metric_dicts:
        rec.run_once()


def test_forced_p99_regression_raises_exactly_one_event(cl, monkeypatch):
    rec = cl.flight_recorder
    # 6 warmup ticks at 1ms baseline, then a sustained 50ms spike
    _feed(rec, [{"query_p99_ms": 1.0}] * 6 + [{"query_p99_ms": 50.0}] * 3,
          monkeypatch)
    events = [e for e in rec.events_rows() if e[1] == "p99_regression"]
    assert len(events) == 1  # deduped while the condition is active
    assert events[0][6] is True
    assert rec.active_counts()["p99_regression"] == 1
    # recovery resolves the event; the log entry survives, inactive
    _feed(rec, [{"query_p99_ms": 1.0}], monkeypatch)
    assert rec.active_counts()["p99_regression"] == 0
    events = [e for e in rec.events_rows() if e[1] == "p99_regression"]
    assert len(events) == 1 and events[0][6] is False


def test_forced_pool_saturation_event_and_advisory(cl, monkeypatch):
    cl.execute("SET citus.max_shared_pool_size = 2")
    rec = cl.flight_recorder
    assert ADVISORY.pool_saturated is False
    _feed(rec, [{"pool_in_use": 2}] * 4, monkeypatch)
    events = [e for e in rec.events_rows() if e[1] == "pool_saturation"]
    assert len(events) == 1  # exactly one despite 4 pinned ticks
    assert ADVISORY.pool_saturated is True
    assert rec.active_counts()["pool_saturation"] == 1
    _feed(rec, [{"pool_in_use": 0}], monkeypatch)
    assert ADVISORY.pool_saturated is False
    assert rec.active_counts()["pool_saturation"] == 0


def test_shed_spike_and_catchup_stall_events(cl, monkeypatch):
    rec = cl.flight_recorder
    # sheds jump by 10 in one tick against a zero baseline
    _feed(rec, [{"tenant_shed": 0}, {"tenant_shed": 10}], monkeypatch)
    assert rec.active_counts()["shed_rate_spike"] == 1
    _feed(rec, [{"tenant_shed": 10}], monkeypatch)  # delta 0: resolved
    assert rec.active_counts()["shed_rate_spike"] == 0
    # catch-up rounds advancing 5 ticks in a row = a stalled move
    _feed(rec, [{"shard_move_catchup_rounds": n} for n in range(7)],
          monkeypatch)
    assert rec.active_counts()["catchup_stall"] == 1


def test_wedge_marker_raises_and_clears_event(cl, tmp_path, monkeypatch):
    marker = tmp_path / "wedge_marker"
    monkeypatch.setenv("CITUS_WEDGE_MARKER", str(marker))
    rec = cl.flight_recorder
    marker.write_text('{"event":"tunnel_wedged"}\n')
    rec.run_once()
    assert rec.active_counts()["device_probe_wedged"] == 1
    from citus_tpu.observability.export import prometheus_text
    assert "citus_health_device_probe_wedged 1" in prometheus_text(cl)
    marker.unlink()
    rec.run_once()
    assert rec.active_counts()["device_probe_wedged"] == 0
    assert "citus_health_device_probe_wedged 0" in prometheus_text(cl)


def test_emit_event_rejects_unknown_kind(cl):
    with pytest.raises(ValueError, match="unknown health-event kind"):
        cl.flight_recorder.emit_event("made_up", "x", 1, 0, "detail")


def test_health_events_sql_surface(cl, monkeypatch):
    cl.execute("SET citus.max_shared_pool_size = 1")
    _feed(cl.flight_recorder, [{"pool_in_use": 1}] * 3, monkeypatch)
    res = cl.execute("SELECT citus_health_events()")
    assert res.columns == ["ts", "node", "kind", "severity", "subject",
                           "value", "baseline", "active", "detail"]
    sat = [r for r in res.rows if r[2] == "pool_saturation"]
    assert len(sat) == 1
    assert sat[0][3] == "critical" and sat[0][7] is True


def test_advisory_saturation_halves_shed_depth():
    """While the pool_saturation advisory is raised the scheduler sheds
    at half the configured queue depth (4 -> 2)."""
    sched = TenantScheduler(pool=SharedTaskPool())
    st = Settings(executor=ExecutorSettings(max_shared_pool_size=1),
                  workload=WorkloadSettings(tenant_queue_depth=4))
    sched.acquire(st, "a")  # hold the only slot
    threads = []
    try:
        for _ in range(2):
            th = threading.Thread(
                target=lambda: (sched.acquire(st, "a", timeout=10),
                                sched.release("a")),
                daemon=True)
            th.start()
            threads.append(th)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if any(r[0] == "a" and r[2] == 2 for r in sched.rows_view()):
                break
            time.sleep(0.001)
        ADVISORY.pool_saturated = True
        # 2 queued at effective depth 2: shed, where depth 4 would queue
        with pytest.raises(AdmissionShedError, match="2 waiters"):
            sched.acquire(st, "a")
    finally:
        ADVISORY.pool_saturated = False
        sched.release("a")
        for th in threads:
            th.join()


# ------------------------------------------------------ reset coherence


def test_counters_reset_clears_ring_and_histograms(cl):
    rec = cl.flight_recorder
    rec.run_once()
    cl.execute("SELECT count(*) FROM t")
    rec.run_once()
    assert rec.history_rows(metric="queries_executed")
    assert cl.query_stats.histograms_view()
    cl.execute("SELECT citus_stat_counters_reset()")
    # the reset hook dropped the ring atomically with the counters —
    # no post-reset sample can difference against a pre-reset value
    assert rec.history_rows() == []
    assert cl.counters.snapshot()["queries_executed"] == 0
    # the pre-reset query families are gone (the reset statement itself
    # records its own latency after the wipe — that one may remain)
    families = [q for q, _h in cl.query_stats.histograms_view()]
    assert not any("from t" in q for q in families), families
    rec.run_once()
    cl.execute("SELECT count(*) FROM t")
    rec.run_once()
    rows = rec.history_rows(metric="queries_executed")
    assert len(rows) == 2
    assert all(r[3] is None or r[3] >= 0 for r in rows), rows


def test_reset_during_tick_drops_sample(cl, monkeypatch):
    rec = cl.flight_recorder

    def racing_collect():
        rec.reset_baselines()  # a reset lands mid-collection
        return {"queries_executed": 5}

    monkeypatch.setattr(rec, "_collect", racing_collect)
    rec.run_once()
    assert rec.history_rows() == []  # torn sample was discarded


# ------------------------------------------------------- HBM accounting


def test_device_memory_attribution_invariant(cl):
    old_cap = GLOBAL_CACHE.capacity
    GLOBAL_CACHE.clear()
    # per-query entries under this workload are ~655KB: two fit, the
    # third put forces LRU eviction
    GLOBAL_CACHE.capacity = 1_400_000
    try:
        for hi in (100, 500, 900, 1300, 1700, 2000):
            cl.execute(f"SELECT count(*), sum(v) FROM t WHERE v < {hi}")
        mv = GLOBAL_CACHE.memory_view()
        assert mv["live_bytes"] > 0
        assert mv["live_bytes"] <= mv["capacity_bytes"]
        assert mv["high_water_bytes"] >= mv["live_bytes"]
        # the per-(table, tenant) ledger sums exactly to live bytes
        assert sum(b for _t, _n, b in mv["by_owner"]) == mv["live_bytes"]
        res = cl.execute("SELECT citus_device_memory()")
        assert res.columns == ["scope", "table", "tenant", "bytes"]
        by_scope = {}
        for scope, _table, _tenant, b in res.rows:
            by_scope.setdefault(scope, []).append(b)
        assert sum(by_scope["entry"]) == by_scope["total"][0]
        assert by_scope["total"][0] <= by_scope["capacity"][0]
    finally:
        GLOBAL_CACHE.capacity = old_cap
        GLOBAL_CACHE.clear()


def test_explain_analyze_memory_line_cached_and_streaming(cl):
    cl.execute("SELECT sum(v) FROM t WHERE v < 999")  # warm the cache
    r = cl.execute("EXPLAIN ANALYZE SELECT sum(v) FROM t WHERE v < 999")
    txt = "\n".join(row[0] for row in r.rows)
    assert "Memory:" in txt, txt
    mem = [ln for ln in txt.splitlines() if "Memory:" in ln][0]
    touched = int(mem.split("Memory:")[1].split()[0])
    assert touched > 0  # the cache hit replays resident bytes
    old_cap = GLOBAL_CACHE.capacity
    GLOBAL_CACHE.clear()
    GLOBAL_CACHE.capacity = 1  # nothing fits: pure streaming path
    try:
        r2 = cl.execute(
            "EXPLAIN ANALYZE SELECT sum(v) FROM t WHERE v < 999")
        txt2 = "\n".join(row[0] for row in r2.rows)
        mem2 = [ln for ln in txt2.splitlines() if "Memory:" in ln]
        assert mem2, txt2
        touched2 = int(mem2[0].split("Memory:")[1].split()[0])
        assert touched2 > 0  # streamed bytes are accounted too
        assert "cache-resident 0 bytes" in mem2[0]
    finally:
        GLOBAL_CACHE.capacity = old_cap
        GLOBAL_CACHE.clear()


# -------------------------------------------------------------- gauges


def test_pool_and_health_gauges_in_metrics(cl):
    from citus_tpu.observability.export import prometheus_text
    txt = prometheus_text(cl)
    assert "citus_pool_in_use 0" in txt
    assert "citus_pool_high_water" in txt
    assert "citus_tenant_queued" in txt
    for kind in HEALTH_EVENT_KINDS:
        assert f"citus_health_{kind} " in txt
    # running a query through the scheduler materializes the labeled
    # per-tenant queue-depth series
    cl.execute("SELECT count(*) FROM t")
    txt = prometheus_text(cl)
    assert 'citus_tenant_queue_depth{tenant="' in txt
