"""Round-2 breadth: window frames + navigation functions + windows over
GROUP BY, composable string functions, views, and sequences.

References: window pushdown/pull (the reference delegates execution to
PostgreSQL's window executor), string funcs over dictionary encodings,
views as distributed objects (commands/view.c), distributed sequences
(commands/sequence.c)."""

import sqlite3

import numpy as np
import pytest

import citus_tpu as ct


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v bigint, s text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rows = [(i, i % 3, (i * 7) % 20, f" W{i % 4} ") for i in range(60)]
    cl.copy_from("t", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, g INTEGER, v INTEGER, s TEXT)")
    sq.executemany("INSERT INTO t VALUES (?,?,?,?)", rows)
    yield cl, sq
    cl.close()


WINDOW_QUERIES = [
    "SELECT k, sum(v) OVER (PARTITION BY g ORDER BY k ROWS BETWEEN 2 PRECEDING "
    "AND CURRENT ROW) FROM t ORDER BY k",
    "SELECT k, avg(v) OVER (ORDER BY k ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) "
    "FROM t ORDER BY k",
    "SELECT k, min(v) OVER (PARTITION BY g ORDER BY k ROWS BETWEEN UNBOUNDED "
    "PRECEDING AND UNBOUNDED FOLLOWING) FROM t ORDER BY k",
    "SELECT k, lag(v, 1) OVER (PARTITION BY g ORDER BY k) FROM t ORDER BY k",
    "SELECT k, lag(v, 2, 0) OVER (ORDER BY k) FROM t ORDER BY k",
    "SELECT k, lead(v, 3) OVER (PARTITION BY g ORDER BY k) FROM t ORDER BY k",
    "SELECT k, first_value(v) OVER (PARTITION BY g ORDER BY k) FROM t ORDER BY k",
    "SELECT k, last_value(v) OVER (PARTITION BY g ORDER BY k ROWS BETWEEN "
    "UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) FROM t ORDER BY k",
    "SELECT k, ntile(5) OVER (ORDER BY k) FROM t ORDER BY k",
    "SELECT k, count(*) OVER (ORDER BY k ROWS BETWEEN 3 PRECEDING AND "
    "1 PRECEDING) FROM t ORDER BY k",
]


@pytest.mark.parametrize("sql", WINDOW_QUERIES)
def test_window_frames_vs_sqlite(db, sql):
    cl, sq = db

    import decimal

    def canon(rows):
        return [tuple(round(float(v), 9)
                      if isinstance(v, (int, float, decimal.Decimal))
                      and not isinstance(v, bool) else v for v in r)
                for r in rows]
    ours = canon(cl.execute(sql).rows)
    theirs = canon(sq.execute(sql).fetchall())
    assert ours == theirs, (sql, ours[:5], theirs[:5])


def test_window_over_group_by(db):
    cl, sq = db
    sql = ("SELECT g, sum(v) AS s, rank() OVER (ORDER BY sum(v) DESC) "
           "FROM t GROUP BY g ORDER BY g")
    ours = [tuple(r) for r in cl.execute(sql).rows]
    theirs = [tuple(r) for r in sq.execute(sql).fetchall()]
    assert ours == theirs


def test_string_functions_compose(db):
    cl, sq = db
    for sql in [
        "SELECT k, trim(s) FROM t WHERE k < 8 ORDER BY k",
        "SELECT k, upper(trim(s)) FROM t WHERE k < 8 ORDER BY k",
        "SELECT k, replace(trim(s), 'W', 'x') FROM t WHERE k < 8 ORDER BY k",
        "SELECT k, substring(trim(s), 2, 1) FROM t WHERE k < 8 ORDER BY k",
        "SELECT lower(trim(s)), count(*) FROM t GROUP BY lower(trim(s)) ORDER BY 1",
        "SELECT k, length(trim(s)) FROM t WHERE k < 8 ORDER BY k",
    ]:
        ours = [tuple(r) for r in cl.execute(sql).rows]
        theirs = [tuple(r) for r in sq.execute(sql).fetchall()]
        assert ours == theirs, (sql, ours[:4], theirs[:4])
    # PostgreSQL-only spellings vs hand-checked values
    assert cl.execute("SELECT left(trim(s), 1) FROM t WHERE k = 1").rows == [("W",)]
    assert cl.execute("SELECT right(trim(s), 1) FROM t WHERE k = 1").rows == [("1",)]
    assert cl.execute("SELECT reverse(trim(s)) FROM t WHERE k = 1").rows == [("1W",)]
    # literal args constant-fold (usable in comparisons)
    assert cl.execute(
        "SELECT count(*) FROM t WHERE upper(trim(s)) = upper('w1')").rows \
        == [(15,)]


def test_views_basic_and_nested(db, tmp_path):
    cl, _ = db
    cl.execute("CREATE VIEW agg AS SELECT g, sum(v) AS total FROM t GROUP BY g")
    exp = cl.execute("SELECT g, sum(v) FROM t GROUP BY g ORDER BY g").rows
    assert cl.execute("SELECT g, total FROM agg ORDER BY g").rows == exp
    cl.execute("CREATE VIEW agg_big AS SELECT g FROM agg WHERE total > 300")
    got = cl.execute("SELECT count(*) FROM agg_big").rows
    assert got == [(len([x for x in exp if x[1] > 300]),)]
    # join against a view
    r = cl.execute("SELECT count(*) FROM t JOIN agg_big a ON t.g = a.g").rows
    assert r[0][0] == 20 * len([x for x in exp if x[1] > 300])
    # views survive reopen
    cl2 = ct.Cluster(str(tmp_path / "db"))
    assert cl2.execute("SELECT g, total FROM agg ORDER BY g").rows == exp
    cl2.close()
    # name collision + drop
    from citus_tpu.errors import CatalogError
    with pytest.raises(CatalogError):
        cl.execute("CREATE VIEW t AS SELECT k FROM t")
    cl.execute("DROP VIEW agg_big")
    with pytest.raises(CatalogError):
        cl.execute("SELECT * FROM agg_big")


def test_sequences(db, tmp_path):
    cl, _ = db
    cl.execute("CREATE SEQUENCE ids START 10 INCREMENT 5")
    assert [cl.execute("SELECT nextval('ids')").rows[0][0]
            for _ in range(3)] == [10, 15, 20]
    assert cl.execute("SELECT currval('ids')").rows == [(20,)]
    cl.execute("SELECT setval('ids', 100)")
    assert cl.execute("SELECT nextval('ids')").rows == [(105,)]
    cl.execute("CREATE TABLE st (id bigint, v bigint)")
    cl.execute("INSERT INTO st VALUES (nextval('ids'), 1), (nextval('ids'), 2)")
    assert cl.execute("SELECT id FROM st ORDER BY id").rows == [(110,), (115,)]
    # restart never repeats (block gap allowed)
    cl2 = ct.Cluster(str(tmp_path / "db"))
    assert cl2.execute("SELECT nextval('ids')").rows[0][0] > 115
    cl2.close()
    from citus_tpu.errors import CatalogError
    with pytest.raises(CatalogError):
        cl.execute("SELECT nextval('nope')")
    cl.execute("DROP SEQUENCE ids")
    with pytest.raises(CatalogError):
        cl.execute("SELECT nextval('ids')")


def test_roles_and_grants(tmp_path):
    """CREATE/DROP ROLE + GRANT/REVOKE with table-level enforcement
    (reference: commands/role.c + commands/grant.c propagation)."""
    from citus_tpu.errors import CatalogError
    cl = ct.Cluster(str(tmp_path / "roles"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
    cl.execute("CREATE ROLE analyst")
    cl.execute("GRANT SELECT ON t TO analyst")
    assert cl.execute("SELECT count(*) FROM t", role="analyst").rows == [(2,)]
    with pytest.raises(CatalogError):
        cl.execute("INSERT INTO t VALUES (3, 30)", role="analyst")
    cl.execute("GRANT INSERT, DELETE ON t TO analyst")
    cl.execute("INSERT INTO t VALUES (3, 30)", role="analyst")
    cl.execute("REVOKE ALL ON t FROM analyst")
    with pytest.raises(CatalogError):
        cl.execute("SELECT count(*) FROM t", role="analyst")
    with pytest.raises(CatalogError):
        cl.execute("CREATE TABLE x (a bigint)", role="analyst")  # DDL denied
    assert cl.execute("SELECT citus_roles()").rows == [("analyst",)]
    # grants persist across reopen
    cl.execute("GRANT SELECT ON t TO analyst")
    cl.close()
    cl2 = ct.Cluster(str(tmp_path / "roles"))
    assert cl2.execute("SELECT count(*) FROM t", role="analyst").rows == [(3,)]
    cl2.execute("DROP ROLE analyst")
    with pytest.raises(CatalogError):
        cl2.execute("SELECT 1 FROM t", role="analyst")
    cl2.close()


def test_sql_functions(tmp_path):
    """CREATE FUNCTION expression macros inline at planning time
    (reference: distributed functions + call delegation)."""
    cl = ct.Cluster(str(tmp_path / "fns"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, p decimal(8,2))")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.execute("INSERT INTO t VALUES (1, 10, 5.00), (2, 20, 7.50), (3, 30, 10.00)")
    cl.execute("CREATE FUNCTION double_it(x bigint) RETURNS bigint AS 'x * 2'")
    assert cl.execute("SELECT k, double_it(v) FROM t ORDER BY k").rows == \
        [(1, 20), (2, 40), (3, 60)]
    cl.execute("CREATE FUNCTION with_tax(amount decimal, rate decimal) "
               "RETURNS decimal AS 'amount * (1 + rate)'")
    assert float(cl.execute("SELECT sum(with_tax(p, 0.1)) FROM t").rows[0][0]) \
        == 24.75
    assert cl.execute("SELECT count(*) FROM t WHERE double_it(v) > 25").rows \
        == [(2,)]
    cl.execute("CREATE FUNCTION quad(x bigint) RETURNS bigint AS "
               "'double_it(double_it(x))'")
    assert cl.execute("SELECT quad(v) FROM t WHERE k = 1").rows == [(40,)]
    cl.execute("CREATE OR REPLACE FUNCTION double_it(x bigint) RETURNS bigint "
               "AS 'x * 3'")
    assert cl.execute("SELECT double_it(v) FROM t WHERE k = 1").rows == [(30,)]
    cl.execute("DROP FUNCTION quad")
    # survives reopen
    cl.close()
    cl2 = ct.Cluster(str(tmp_path / "fns"))
    assert cl2.execute("SELECT double_it(v) FROM t WHERE k = 1").rows == [(30,)]
    cl2.close()


def test_enum_types(tmp_path):
    """CREATE TYPE ... AS ENUM: dictionary-encoded text with ingest
    validation (reference: type propagation, commands/type.c)."""
    from citus_tpu.errors import AnalysisError, CatalogError
    cl = ct.Cluster(str(tmp_path / "enums"))
    cl.execute("CREATE TYPE mood AS ENUM ('sad', 'ok', 'happy')")
    cl.execute("CREATE TABLE p (k bigint NOT NULL, m mood)")
    cl.execute("SELECT create_distributed_table('p', 'k', 4)")
    cl.execute("INSERT INTO p VALUES (1, 'happy'), (2, 'sad'), (3, NULL)")
    assert cl.execute("SELECT count(*) FROM p WHERE m = 'happy'").rows == [(1,)]
    with pytest.raises(AnalysisError):
        cl.execute("INSERT INTO p VALUES (4, 'angry')")
    with pytest.raises(CatalogError):
        cl.execute("DROP TYPE mood")  # still referenced
    assert cl.execute("SELECT citus_types()").rows == [("mood", "sad,ok,happy")]
    # survives reopen with validation intact
    cl.close()
    cl2 = ct.Cluster(str(tmp_path / "enums"))
    with pytest.raises(AnalysisError):
        cl2.execute("INSERT INTO p VALUES (5, 'nope')")
    cl2.execute("DROP TABLE p")
    cl2.execute("DROP TYPE mood")
    cl2.close()


def test_like_over_string_transforms(tmp_path):
    cl = ct.Cluster(str(tmp_path / "liket"))
    cl.execute("CREATE TABLE t (k bigint, s text)")
    cl.copy_from("t", rows=[(1, " red "), (2, "green"), (3, "BLUE"), (4, None)])
    assert cl.execute("SELECT count(*) FROM t WHERE upper(s) LIKE '%RE%'").rows == [(2,)]
    assert cl.execute("SELECT count(*) FROM t WHERE trim(s) LIKE 'red'").rows == [(1,)]
    assert cl.execute("SELECT count(*) FROM t WHERE lower(trim(s)) LIKE 'b%'").rows == [(1,)]
    cl.close()


def test_select_without_from_and_rename_table(tmp_path):
    import decimal
    from citus_tpu.errors import CatalogError
    cl = ct.Cluster(str(tmp_path / "misc"))
    assert cl.execute("SELECT 1 + 2 AS three, 'hi', true, NULL").rows == \
        [(3, "hi", True, None)]
    assert cl.execute("SELECT 10 / 4.0").rows[0][0] == decimal.Decimal("2.5")
    assert cl.execute("SELECT 1 WHERE 1 = 2").rows == []
    assert cl.execute("SELECT 1 UNION SELECT 2 ORDER BY 1").rows == [(1,), (2,)]
    cl.execute("CREATE TABLE a (k bigint NOT NULL, s text)")
    cl.execute("SELECT create_distributed_table('a', 'k', 4)")
    cl.execute("INSERT INTO a VALUES (1, 'x'), (2, 'y')")
    assert cl.execute("SELECT (SELECT max(s) FROM a)").rows == [("y",)]
    cl.execute("ALTER TABLE a RENAME TO b")
    assert cl.execute("SELECT count(*), max(s) FROM b").rows == [(2, "y")]
    with pytest.raises(CatalogError):
        cl.execute("SELECT * FROM a")
    cl.execute("INSERT INTO b VALUES (3, 'z')")
    cl.close()
    cl2 = ct.Cluster(str(tmp_path / "misc"))
    assert cl2.execute("SELECT max(s) FROM b").rows == [("z",)]
    cl2.close()


def test_explain_setop_and_insert_select(tmp_path):
    cl = ct.Cluster(str(tmp_path / "expl"))
    import numpy as np
    cl.execute("CREATE TABLE s (k bigint NOT NULL, v bigint)")
    cl.execute("CREATE TABLE d (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('s', 'k', 4)")
    cl.execute("SELECT create_distributed_table('d', 'k', 4, 's')")
    cl.copy_from("s", columns={"k": np.arange(50), "v": np.arange(50)})
    out = "\n".join(r[0] for r in cl.execute(
        "EXPLAIN INSERT INTO d SELECT k, v FROM s WHERE v < 10").rows)
    assert "Strategy: colocated" in out and "Distributed Scan on s" in out
    out = "\n".join(r[0] for r in cl.execute(
        "EXPLAIN INSERT INTO d SELECT k, v FROM s ORDER BY k").rows)
    assert "Strategy: pull" in out
    out = "\n".join(r[0] for r in cl.execute(
        "EXPLAIN SELECT v FROM s UNION ALL SELECT v FROM d").rows)
    assert "Set Operation: UNION ALL" in out and "left" in out and "right" in out
    # EXPLAIN must not have executed the insert
    assert cl.execute("SELECT count(*) FROM d").rows == [(0,)]
    cl.close()


def test_ilike(tmp_path):
    cl = ct.Cluster(str(tmp_path / "ilike"))
    cl.execute("CREATE TABLE t (k bigint, s text)")
    cl.copy_from("t", rows=[(1, "Red"), (2, "GREEN"), (3, "blue"), (4, None)])
    assert cl.execute("SELECT count(*) FROM t WHERE s ILIKE 'red'").rows == [(1,)]
    assert cl.execute("SELECT count(*) FROM t WHERE s ILIKE '%E%'").rows == [(3,)]
    assert cl.execute("SELECT count(*) FROM t WHERE s LIKE '%E%'").rows == [(1,)]
    assert cl.execute("SELECT count(*) FROM t WHERE trim(s) ILIKE 'BLUE'").rows \
        == [(1,)]
    cl.close()


def test_is_distinct_from(tmp_path):
    """Null-safe equality: never yields NULL, NULLs compare equal."""
    cl = ct.Cluster(str(tmp_path / "isdist"))
    cl.execute("CREATE TABLE t (k bigint, a bigint, b bigint)")
    cl.copy_from("t", rows=[(1, 1, 1), (2, 1, 2), (3, None, 1), (4, None, None)])
    assert cl.execute("SELECT k FROM t WHERE a IS DISTINCT FROM b "
                      "ORDER BY k").rows == [(2,), (3,)]
    assert cl.execute("SELECT k FROM t WHERE a IS NOT DISTINCT FROM b "
                      "ORDER BY k").rows == [(1,), (4,)]
    assert cl.execute("SELECT count(*) FROM t WHERE a IS NOT DISTINCT "
                      "FROM NULL").rows == [(2,)]
    assert cl.execute("SELECT count(*) FROM t WHERE a IS DISTINCT FROM 1"
                      ).rows == [(2,)]
    cl.close()


def test_simple_case_expr(tmp_path):
    cl = ct.Cluster(str(tmp_path / "scase"))
    cl.execute("CREATE TABLE t (k bigint, g bigint, s text)")
    cl.copy_from("t", rows=[(1, 0, "a"), (2, 1, "b"), (3, 2, "a"), (4, None, "c")])
    assert cl.execute("SELECT k, CASE g WHEN 0 THEN 10 WHEN 1 THEN 20 "
                      "ELSE 99 END FROM t ORDER BY k").rows == \
        [(1, 10), (2, 20), (3, 99), (4, 99)]
    assert cl.execute("SELECT sum(CASE s WHEN 'a' THEN 1 ELSE 0 END) "
                      "FROM t").rows == [(2,)]
    cl.close()


def test_rollup_cube_grouping_sets(tmp_path):
    cl = ct.Cluster(str(tmp_path / "gsets"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, a text, b bigint, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", rows=[(1, "x", 1, 10), (2, "x", 2, 20),
                            (3, "y", 1, 30), (4, "y", 2, 40)])
    r = cl.execute("SELECT a, b, sum(v) FROM t GROUP BY ROLLUP(a, b) "
                   "ORDER BY a NULLS LAST, b NULLS LAST").rows
    assert r == [("x", 1, 10), ("x", 2, 20), ("x", None, 30),
                 ("y", 1, 30), ("y", 2, 40), ("y", None, 70),
                 (None, None, 100)]
    r = cl.execute("SELECT a, b, sum(v) FROM t GROUP BY CUBE(a, b) "
                   "ORDER BY a NULLS LAST, b NULLS LAST").rows
    assert (None, 1, 40) in r and (None, 2, 60) in r and len(r) == 9
    r = cl.execute("SELECT a, b, count(*) FROM t GROUP BY "
                   "GROUPING SETS((a), (b), ()) "
                   "ORDER BY a NULLS LAST, b NULLS LAST").rows
    assert len(r) == 5
    assert cl.execute("SELECT a, sum(v) FROM t GROUP BY ROLLUP(a) "
                      "ORDER BY a NULLS LAST").rows == \
        [("x", 30), ("y", 70), (None, 100)]
    cl.close()


def test_grouping_function(tmp_path):
    """GROUPING(col) distinguishes rollup totals from real NULL keys."""
    cl = ct.Cluster(str(tmp_path / "gfn"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, a text, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", rows=[(1, "x", 10), (2, None, 20), (3, "y", 30)])
    r = cl.execute("SELECT a, grouping(a), sum(v) FROM t GROUP BY ROLLUP(a) "
                   "ORDER BY 2, a NULLS LAST").rows
    assert r == [("x", 0, 10), ("y", 0, 30), (None, 0, 20), (None, 1, 60)]
    cl.close()


def test_grouping_sets_edge_semantics(tmp_path):
    """DISTINCT dedup across sets, HAVING over rolled-up columns (NULL
    in absent sets), keys-only select lists (grand-total row), EXPLAIN."""
    cl = ct.Cluster(str(tmp_path / "gedge"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, h bigint, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", rows=[(1, 1, 1, 10), (2, 1, 2, 20), (3, 2, 1, 30)])
    assert cl.execute("SELECT DISTINCT g, sum(v) FROM t GROUP BY "
                      "GROUPING SETS((g),(g)) ORDER BY g").rows == \
        [(1, 30), (2, 30)]
    r = cl.execute("SELECT g, h, sum(v) FROM t GROUP BY ROLLUP(g, h) "
                   "HAVING g > 0 ORDER BY g, h NULLS LAST").rows
    assert (None, None, 60) not in r and (1, None, 30) in r
    assert cl.execute("SELECT g FROM t GROUP BY ROLLUP(g) "
                      "ORDER BY g NULLS LAST").rows == [(1,), (2,), (None,)]
    ex = cl.execute("EXPLAIN SELECT g, count(*) FROM t GROUP BY ROLLUP(g)").rows
    assert any("Grouping Sets" in x[0] for x in ex)
    cl.close()


def test_dml_returning(tmp_path):
    """INSERT/UPDATE/DELETE ... RETURNING (reference: RETURNING tuples
    from worker DML, adaptive_executor.c)."""
    cl = ct.Cluster(str(tmp_path / "ret"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, c text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    r = cl.execute("INSERT INTO t (k, v, c) VALUES (1, 10, 'a'), "
                   "(2, 20, 'b') RETURNING k, v * 2 AS dbl, c")
    assert r.columns == ["k", "dbl", "c"]
    assert r.rows == [(1, 20, 'a'), (2, 40, 'b')]
    assert cl.execute("INSERT INTO t (k) VALUES (3) RETURNING *").rows == \
        [(3, None, None)]
    r = cl.execute("UPDATE t SET v = v + 5 WHERE k <= 2 RETURNING k, v")
    assert sorted(r.rows) == [(1, 15), (2, 25)]
    assert r.explain["updated"] == 2
    # constant-substituted item (text assignment folds on the host)
    assert cl.execute("UPDATE t SET c = 'z' WHERE k = 1 "
                      "RETURNING c, k").rows == [('z', 1)]
    # all-constant RETURNING list still yields one row per affected row
    assert sorted(cl.execute("UPDATE t SET v = 0 WHERE k <= 2 "
                             "RETURNING v").rows) == [(0,), (0,)]
    r = cl.execute("DELETE FROM t WHERE k = 2 RETURNING *")
    assert r.rows == [(2, 0, 'b')] and r.explain["deleted"] == 1
    assert cl.execute("SELECT count(*) FROM t").rows == [(2,)]
    cl.close()


def test_dml_returning_params_and_coercion(tmp_path):
    import datetime
    cl = ct.Cluster(str(tmp_path / "ret2"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, d date)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    # RETURNING values match what a subsequent SELECT reads back
    r = cl.execute("INSERT INTO t (k, d) VALUES (5, '2020-01-02') "
                   "RETURNING d, t.k")
    assert r.rows == [(datetime.date(2020, 1, 2), 5)]
    # parameterized DML keeps its RETURNING clause
    cl.execute("INSERT INTO t (k, v) VALUES (1, 10), (2, 20)")
    assert cl.execute("UPDATE t SET v = v + $1 WHERE k = $2 RETURNING k, v",
                      params=[5, 1]).rows == [(1, 15)]
    r = cl.execute("DELETE FROM t WHERE k = $1 RETURNING *", params=[2])
    assert r.rows == [(2, 20, None)] and r.explain["deleted"] == 1
    cl.close()


def test_enum_declaration_order(tmp_path):
    """Enum comparisons and ORDER BY follow declaration order, not label
    text (reference: enumsortorder; round-2 gap #9)."""
    from citus_tpu.errors import AnalysisError
    cl = ct.Cluster(str(tmp_path / "enumord"))
    cl.execute("CREATE TYPE sev AS ENUM ('low', 'medium', 'high', 'critical')")
    cl.execute("CREATE TABLE ev (k bigint NOT NULL, s sev)")
    cl.execute("SELECT create_distributed_table('ev', 'k', 4)")
    cl.execute("INSERT INTO ev VALUES (1, 'high'), (2, 'low'), (3, 'critical'),"
               " (4, 'medium'), (5, 'low'), (6, NULL)")
    # declaration order: low < medium < high < critical (alphabetical
    # order would put 'critical' < 'high' < 'low' < 'medium')
    assert cl.execute("SELECT count(*) FROM ev WHERE s > 'medium'").rows == [(2,)]
    assert cl.execute("SELECT count(*) FROM ev WHERE s <= 'low'").rows == [(2,)]
    assert cl.execute("SELECT count(*) FROM ev WHERE s >= 'high'").rows == [(2,)]
    r = cl.execute("SELECT k, s FROM ev WHERE s IS NOT NULL ORDER BY s, k")
    assert [row[1] for row in r.rows] == \
        ["low", "low", "medium", "high", "critical"]
    r2 = cl.execute("SELECT k FROM ev ORDER BY s DESC, k LIMIT 2")
    # DESC: NULLS FIRST by default, then critical
    assert r2.rows == [(6,), (3,)]
    # grouped: ORDER BY the enum key follows declaration order
    g = cl.execute("SELECT s, count(*) FROM ev WHERE s IS NOT NULL "
                   "GROUP BY s ORDER BY s")
    assert [row[0] for row in g.rows] == ["low", "medium", "high", "critical"]
    # enum-vs-enum column comparison
    cl.execute("CREATE TABLE ev2 (k bigint NOT NULL, s sev)")
    cl.execute("SELECT create_distributed_table('ev2', 'k', 4)")
    cl.execute("INSERT INTO ev2 VALUES (1, 'medium'), (2, 'critical'), (3, 'low')")
    j = cl.execute("SELECT count(*) FROM ev a JOIN ev2 b ON a.k = b.k "
                   "WHERE a.s > b.s")
    assert j.rows == [(2,)]  # k=1 high>medium, k=3 critical>low
    # invalid label in a comparison errors like PostgreSQL
    with pytest.raises(AnalysisError, match="invalid input value"):
        cl.execute("SELECT count(*) FROM ev WHERE s > 'bogus'")
    # join ORDER BY follows declaration order too
    jo = cl.execute("SELECT a.k FROM ev a JOIN ev2 b ON a.k = b.k "
                    "ORDER BY a.s, a.k")
    assert jo.rows == [(2,), (1,), (3,)]  # low, high, critical
    # aggregate-internal ORDER BY over the enum column
    ag = cl.execute("SELECT array_agg(k ORDER BY s) FROM ev "
                    "WHERE s IS NOT NULL")
    assert list(ag.rows[0][0]) == [2, 5, 4, 1, 3]  # low,low,medium,high,crit
    # a string function over an enum column yields TEXT, not enum:
    # ordered comparison on it must NOT silently use declaration ranks
    from citus_tpu.errors import UnsupportedFeatureError
    with pytest.raises(UnsupportedFeatureError):
        cl.execute("SELECT count(*) FROM ev WHERE upper(s) > 'MEDIUM'")
    cl.close()
