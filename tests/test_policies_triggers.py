"""Row-level security policies, statement triggers, and text search
configuration objects (round-2 gap #7).

Reference: commands/policy.c (policy propagation), commands/trigger.c
(trigger propagation), commands/text_search.c (configuration objects).
Enforcement here is engine-native: policies rewrite queries for
non-superuser roles; triggers run stored SQL-statement functions after
DML; text search configurations are propagated metadata objects, as in
the reference (FTS execution lives in the host database there)."""

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import (
    AnalysisError, CatalogError, ExecutionError, UnsupportedFeatureError,
)


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE docs (k bigint NOT NULL, owner_id bigint, v bigint)")
    cl.execute("SELECT create_distributed_table('docs', 'k', 4)")
    cl.copy_from("docs", columns={
        "k": np.arange(100), "owner_id": np.arange(100) % 4,
        "v": np.arange(100)})
    cl.execute("CREATE ROLE app")
    cl.execute("GRANT SELECT, INSERT, UPDATE, DELETE ON docs TO app")
    yield cl
    cl.close()


# ------------------------------------------------------------ policies

def test_rls_default_deny_and_policy_filter(db):
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    # RLS on, no policy: default deny for non-superusers
    assert db.execute("SELECT count(*) FROM docs", role="app").rows == [(0,)]
    # superuser bypasses
    assert db.execute("SELECT count(*) FROM docs").rows == [(100,)]
    db.execute("CREATE POLICY own ON docs USING (owner_id = 2)")
    assert db.execute("SELECT count(*) FROM docs", role="app").rows == [(25,)]
    r = db.execute("SELECT sum(v) FROM docs WHERE v < 50", role="app")
    want = sum(v for v in range(50) if v % 4 == 2)
    assert r.rows == [(want,)]


def test_rls_policies_are_permissive_or(db):
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY p1 ON docs USING (owner_id = 1)")
    db.execute("CREATE POLICY p2 ON docs USING (owner_id = 3)")
    assert db.execute("SELECT count(*) FROM docs", role="app").rows == [(50,)]


def test_rls_role_scoped_policy(db):
    db.execute("CREATE ROLE other")
    db.execute("GRANT SELECT ON docs TO other")
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY justapp ON docs TO app USING (owner_id = 0)")
    assert db.execute("SELECT count(*) FROM docs", role="app").rows == [(25,)]
    # 'other' has no applicable policy: default deny
    assert db.execute("SELECT count(*) FROM docs", role="other").rows == [(0,)]


def test_rls_update_delete(db):
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY own ON docs USING (owner_id = 2)")
    db.execute("UPDATE docs SET v = v + 1000 WHERE v < 10", role="app")
    # only owner_id=2 rows with v<10 updated: v in {2, 6}
    assert db.execute("SELECT count(*) FROM docs WHERE v >= 1000").rows == [(2,)]
    db.execute("DELETE FROM docs WHERE v >= 1000", role="app")
    assert db.execute("SELECT count(*) FROM docs").rows == [(98,)]
    # superuser delete is unfiltered
    db.execute("DELETE FROM docs WHERE v < 10")
    assert db.execute("SELECT count(*) FROM docs WHERE v < 10").rows == [(0,)]


def test_rls_insert_with_check(db):
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY own ON docs USING (owner_id = 2)")
    db.execute("INSERT INTO docs VALUES (200, 2, 7)", role="app")  # passes
    with pytest.raises(AnalysisError, match="violates row-level security"):
        db.execute("INSERT INTO docs VALUES (201, 3, 7)", role="app")
    # superuser inserts anything
    db.execute("INSERT INTO docs VALUES (202, 3, 7)")


def test_rls_in_joins(db, tmp_path):
    db.execute("CREATE TABLE tags (tk bigint NOT NULL, doc_k bigint, lbl text)")
    db.execute("SELECT create_distributed_table('tags', 'tk', 4)")
    db.copy_from("tags", columns={"tk": np.arange(20),
                                  "doc_k": np.arange(20),
                                  "lbl": ["x"] * 20})
    db.execute("GRANT SELECT ON tags TO app")
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY own ON docs USING (owner_id = 2)")
    r = db.execute("SELECT count(*) FROM docs d JOIN tags g "
                   "ON d.k = g.doc_k", role="app")
    # docs 0..19 with owner 2: k in {2, 6, 10, 14, 18}
    assert r.rows == [(5,)]


def test_drop_policy_and_disable(db):
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY own ON docs USING (owner_id = 2)")
    db.execute("DROP POLICY own ON docs")
    assert db.execute("SELECT count(*) FROM docs", role="app").rows == [(0,)]
    db.execute("ALTER TABLE docs DISABLE ROW LEVEL SECURITY")
    assert db.execute("SELECT count(*) FROM docs", role="app").rows == [(100,)]
    with pytest.raises(CatalogError):
        db.execute("DROP POLICY nope ON docs")
    db.execute("DROP POLICY IF EXISTS nope ON docs")


def test_policies_view_and_persistence(db, tmp_path):
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY own ON docs FOR SELECT TO app "
               "USING (owner_id = 1)")
    v = db.execute("SELECT citus_policies()")
    assert v.rows == [("docs", "own", "select", "app", "owner_id = 1", None)]
    db.close()
    cl2 = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    assert cl2.execute("SELECT count(*) FROM docs", role="app").rows == [(25,)]
    cl2.close()
    # reopen the fixture's handle state for teardown
    db._closed = True if hasattr(db, "_closed") else None


# ------------------------------------------------------------ triggers

def test_statement_trigger_fires(db):
    db.execute("CREATE TABLE audit (n bigint)")
    db.execute("CREATE FUNCTION log_ins() RETURNS trigger AS "
               "'INSERT INTO audit VALUES (1)'")
    db.execute("CREATE TRIGGER t_ins AFTER INSERT ON docs "
               "FOR EACH STATEMENT EXECUTE FUNCTION log_ins()")
    db.execute("INSERT INTO docs VALUES (300, 0, 0), (301, 0, 0)")
    # statement-level: one audit row per INSERT statement
    assert db.execute("SELECT count(*) FROM audit").rows == [(1,)]
    db.execute("UPDATE docs SET v = 1 WHERE k = 300")  # no update trigger
    assert db.execute("SELECT count(*) FROM audit").rows == [(1,)]
    db.execute("DROP TRIGGER t_ins ON docs")
    db.execute("INSERT INTO docs VALUES (302, 0, 0)")
    assert db.execute("SELECT count(*) FROM audit").rows == [(1,)]


def test_trigger_events_and_view(db):
    db.execute("CREATE TABLE audit (n bigint)")
    db.execute("CREATE FUNCTION log_any() RETURNS trigger AS "
               "'INSERT INTO audit VALUES (1)'")
    db.execute("CREATE TRIGGER t_u AFTER UPDATE ON docs "
               "EXECUTE FUNCTION log_any()")
    db.execute("CREATE TRIGGER t_d AFTER DELETE ON docs "
               "EXECUTE FUNCTION log_any()")
    v = db.execute("SELECT citus_triggers()")
    assert v.rows == [("t_d", "docs", "delete", "log_any"),
                      ("t_u", "docs", "update", "log_any")]
    db.execute("UPDATE docs SET v = 0 WHERE k = 1")
    db.execute("DELETE FROM docs WHERE k = 2")
    assert db.execute("SELECT count(*) FROM audit").rows == [(2,)]


def test_trigger_recursion_limited(db):
    db.execute("CREATE TABLE loopt (n bigint)")
    db.execute("CREATE FUNCTION loop_fn() RETURNS trigger AS "
               "'INSERT INTO loopt VALUES (1)'")
    db.execute("CREATE TRIGGER t_loop AFTER INSERT ON loopt "
               "EXECUTE FUNCTION loop_fn()")
    with pytest.raises(ExecutionError, match="recursion"):
        db.execute("INSERT INTO loopt VALUES (0)")


def test_trigger_requires_trigger_function(db):
    db.execute("CREATE FUNCTION notrig(x bigint) RETURNS bigint AS 'x + 1'")
    with pytest.raises(CatalogError, match="not a trigger function"):
        db.execute("CREATE TRIGGER bad AFTER INSERT ON docs "
                   "EXECUTE FUNCTION notrig()")
    # trigger functions cannot be called as expressions
    db.execute("CREATE FUNCTION trg() RETURNS trigger AS "
               "'INSERT INTO docs VALUES (1, 1, 1)'")
    with pytest.raises(AnalysisError, match="trigger function"):
        db.execute("SELECT trg() FROM docs")


# ------------------------------------------- text search configurations

def test_text_search_configurations(db, tmp_path):
    db.execute("CREATE TEXT SEARCH CONFIGURATION english_fast "
               "(PARSER = default)")
    db.execute("CREATE TEXT SEARCH CONFIGURATION english_copy "
               "(COPY = english_fast)")
    v = db.execute("SELECT citus_text_search_configs()")
    assert v.rows == [("english_copy", "default"),
                      ("english_fast", "default")]
    with pytest.raises(CatalogError, match="already exists"):
        db.execute("CREATE TEXT SEARCH CONFIGURATION english_fast "
                   "(PARSER = default)")
    with pytest.raises(CatalogError, match="does not exist"):
        db.execute("CREATE TEXT SEARCH CONFIGURATION bad (COPY = missing)")
    db.execute("DROP TEXT SEARCH CONFIGURATION english_copy")
    db.execute("DROP TEXT SEARCH CONFIGURATION IF EXISTS english_copy")
    with pytest.raises(CatalogError):
        db.execute("DROP TEXT SEARCH CONFIGURATION english_copy")
    # persists across reopen
    db.close()
    cl2 = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    assert cl2.execute("SELECT citus_text_search_configs()").rows == \
        [("english_fast", "default")]
    cl2.close()


# ------------------------------------- review-finding regressions (RLS)

def test_rls_no_bypass_via_setops_and_subqueries(db):
    db.execute("CREATE TABLE pub (k bigint)")
    db.execute("INSERT INTO pub VALUES (2)")
    db.execute("GRANT SELECT ON pub TO app")
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY own ON docs USING (owner_id = 2)")
    # set operation
    r = db.execute("SELECT count(*) FROM docs UNION ALL SELECT 0",
                   role="app")
    assert (25,) in r.rows and (100,) not in r.rows
    # scalar subquery in the select list
    r2 = db.execute("SELECT (SELECT count(*) FROM docs) FROM pub",
                    role="app")
    assert r2.rows == [(25,)]
    # IN subquery in WHERE reads only policy rows
    r3 = db.execute("SELECT count(*) FROM pub WHERE k IN "
                    "(SELECT owner_id FROM docs)", role="app")
    assert r3.rows == [(1,)]
    r4 = db.execute("SELECT count(*) FROM pub WHERE k IN "
                    "(SELECT v FROM docs WHERE owner_id = 3)", role="app")
    assert r4.rows == [(0,)]  # owner 3 rows are invisible
    # CTE body
    r5 = db.execute("WITH c AS (SELECT v FROM docs) SELECT count(*) FROM c",
                    role="app")
    assert r5.rows == [(25,)]


def test_rls_update_cannot_escape_policy(db):
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY own ON docs USING (owner_id = 2)")
    with pytest.raises(AnalysisError, match="violates row-level security"):
        db.execute("UPDATE docs SET owner_id = 99 WHERE k = 2", role="app")
    # rewriting INTO scope is fine
    db.execute("UPDATE docs SET owner_id = 2 WHERE k = 2", role="app")
    # untouched policy columns stay allowed
    db.execute("UPDATE docs SET v = v + 1 WHERE k = 2", role="app")
    # superuser unrestricted
    db.execute("UPDATE docs SET owner_id = 99 WHERE k = 3")


def test_rls_parameterized_insert(db):
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY own ON docs USING (owner_id = 2)")
    db.execute("INSERT INTO docs VALUES ($1, $2, $3)",
               params=[400, 2, 7], role="app")
    with pytest.raises(AnalysisError, match="violates row-level security"):
        db.execute("INSERT INTO docs VALUES ($1, $2, $3)",
                   params=[401, 3, 7], role="app")


def test_replace_or_drop_trigger_function_guarded(db):
    db.execute("CREATE TABLE audit (n bigint)")
    db.execute("CREATE FUNCTION tf() RETURNS trigger AS "
               "'INSERT INTO audit VALUES (1)'")
    db.execute("CREATE TRIGGER tr AFTER INSERT ON docs "
               "EXECUTE FUNCTION tf()")
    with pytest.raises(CatalogError, match="depend"):
        db.execute("CREATE OR REPLACE FUNCTION tf(x bigint) "
                   "RETURNS bigint AS 'x + 1'")
    with pytest.raises(CatalogError, match="depend"):
        db.execute("DROP FUNCTION tf")
    db.execute("DROP TRIGGER tr ON docs")
    db.execute("DROP FUNCTION tf")


def test_rls_applies_inside_dml_subqueries(db):
    """INSERT..SELECT sources and UPDATE/DELETE subqueries over RLS
    tables are policy-filtered even when the DML TARGET has no policy."""
    db.execute("CREATE TABLE sink (k bigint, v bigint)")
    db.execute("GRANT SELECT, INSERT, UPDATE, DELETE ON sink TO app")
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY own ON docs USING (owner_id = 2)")
    db.execute("INSERT INTO sink SELECT k, v FROM docs", role="app")
    assert db.execute("SELECT count(*) FROM sink").rows == [(25,)]
    db.execute("DELETE FROM sink")
    db.execute("INSERT INTO sink VALUES (1, 0), (3, 0)")
    # subquery in UPDATE's WHERE reads only policy-visible docs rows
    db.execute("UPDATE sink SET v = 99 WHERE k IN "
               "(SELECT owner_id FROM docs)", role="app")
    r = db.execute("SELECT k, v FROM sink ORDER BY k")
    assert r.rows == [(1, 0), (3, 0)]  # owner_id values visible: only 2
    db.execute("DELETE FROM sink WHERE k IN (SELECT owner_id FROM docs "
               "WHERE owner_id = 3)", role="app")
    assert db.execute("SELECT count(*) FROM sink").rows == [(2,)]


def test_cte_shadowing_cannot_bypass_privileges(db):
    """WITH secret AS (SELECT * FROM secret): inside the CTE body the
    name is the REAL table and needs a grant."""
    db.execute("CREATE TABLE secret (x bigint)")
    db.execute("INSERT INTO secret VALUES (42)")
    with pytest.raises(CatalogError, match="permission denied"):
        db.execute("WITH secret AS (SELECT x FROM secret) "
                   "SELECT count(*) FROM secret", role="app")


def test_cte_shadowing_rls_table_is_the_cte(db):
    """A CTE named like an RLS table shadows it: the body reference must
    NOT get the policy predicate injected."""
    db.execute("ALTER TABLE docs ENABLE ROW LEVEL SECURITY")
    db.execute("CREATE POLICY own ON docs USING (owner_id = 2)")
    r = db.execute("WITH docs AS (SELECT 1 AS x) SELECT x FROM docs",
                   role="app")
    assert r.rows == [(1,)]


def test_policy_merge_is_per_policy(tmp_path):
    """Two coordinators adding policies on the same table via the flock
    path: both survive the commit-time merge."""
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    a.execute("CREATE TABLE t (k bigint, owner bigint)")
    b = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    a.execute("CREATE POLICY p1 ON t USING (owner = 1)")
    # b commits p2 without having seen p1's commit in memory
    b.execute("CREATE POLICY p2 ON t USING (owner = 2)")
    # a's next commit merges: both policies survive
    a.execute("CREATE TABLE t2 (x bigint)")
    names_a = {p["name"] for p in a.catalog.policies.get("t", [])}
    assert names_a == {"p1", "p2"}, names_a
    # and a drop through one coordinator doesn't resurrect via the other
    a.execute("DROP POLICY p1 ON t")
    b.execute("CREATE TABLE t3 (x bigint)")  # b merges on commit
    a.execute("CREATE TABLE t4 (x bigint)")  # a re-merges disk
    assert {p["name"] for p in a.catalog.policies.get("t", [])} == {"p2"}
    b.close()
    a.close()
