"""pg_regress-style golden tests.

Reference: src/test/regress/sql/*.sql diffed against expected/*.out via
pg_regress_multi.pl.  Here: each tests/golden/NAME.sql runs statement by
statement against a fresh cluster; the formatted output must match
tests/golden/NAME.out exactly.  Regenerate with:
    python tests/test_golden.py --regenerate
"""

import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import pytest

import citus_tpu as ct
from citus_tpu.errors import CitusTpuError

GOLDEN_DIR = pathlib.Path(__file__).parent / "golden"


def run_script(cl, text: str) -> str:
    from citus_tpu.planner.parser import Parser
    out = []
    for raw in split_statements(text):
        out.append(f"-- {raw}")
        try:
            r = cl.execute(raw)
            if r.columns:
                out.append(" | ".join(r.columns))
            for row in r.rows:
                out.append(" | ".join("\\N" if v is None else str(v) for v in row))
            if r.columns:
                out.append(f"({r.rowcount} rows)")
        except CitusTpuError as e:
            out.append(f"ERROR: {type(e).__name__}")
        out.append("")
    return "\n".join(out)


def split_statements(text: str) -> list[str]:
    stmts = []
    for chunk in text.split(";"):
        s = "\n".join(l for l in chunk.splitlines()
                      if not l.strip().startswith("--")).strip()
        if s:
            stmts.append(s)
    return stmts


def sql_cases():
    return sorted(p.stem for p in GOLDEN_DIR.glob("*.sql"))


@pytest.mark.parametrize("name", sql_cases())
def test_golden(name, tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    got = run_script(cl, (GOLDEN_DIR / f"{name}.sql").read_text())
    expected_path = GOLDEN_DIR / f"{name}.out"
    assert expected_path.exists(), f"missing {expected_path}; regenerate"
    assert got == expected_path.read_text(), f"golden mismatch for {name}"


if __name__ == "__main__":
    import sys
    import tempfile
    if "--regenerate" in sys.argv:
        import jax
        jax.config.update("jax_platforms", "cpu")
        for name in sql_cases():
            cl = ct.Cluster(tempfile.mkdtemp(), n_nodes=2)
            out = run_script(cl, (GOLDEN_DIR / f"{name}.sql").read_text())
            (GOLDEN_DIR / f"{name}.out").write_text(out)
            print(f"regenerated {name}.out")
