"""2PC transaction log, crash recovery, lock manager + deadlock
detection, fault injection (reference: transaction/ + mitmproxy tests)."""

import os
import threading

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import ExecutionError
from citus_tpu.ingest import TableIngestor, encode_columns
from citus_tpu.storage.writer import _staged_path
from citus_tpu.testing.faults import FAULTS, FaultError
from citus_tpu.transaction import DeadlockDetected, LockManager, LockTimeout
from citus_tpu.transaction.manager import TxState


@pytest.fixture(autouse=True)
def clean_faults():
    yield
    FAULTS.disarm()


def make_cluster(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    return cl


def _staged_ingest(cl, n=1000, finish=False):
    t = cl.catalog.table("t")
    values, validity = encode_columns(cl.catalog, t, {
        "k": np.arange(n, dtype=np.int64), "v": np.ones(n, dtype=np.int64)})
    ing = TableIngestor(cl.catalog, t, txlog=cl.txlog)
    ing.append(values, validity)
    for w in ing._writers.values():
        w.flush()
    if finish:
        ing.finish()
    return ing


def test_commit_makes_rows_visible_atomically(tmp_path):
    cl = make_cluster(tmp_path)
    ing = _staged_ingest(cl)
    # staged but not committed: invisible
    assert cl.execute("SELECT count(*) FROM t").rows == [(0,)]
    ing.finish()
    assert cl.execute("SELECT count(*) FROM t").rows == [(1000,)]


def test_abort_drops_staged_stripes(tmp_path):
    cl = make_cluster(tmp_path)
    ing = _staged_ingest(cl)
    dirs = [w.directory for w in ing._writers.values()]
    assert any(os.path.exists(_staged_path(d, ing.xid)) for d in dirs)
    ing.abort()
    assert cl.execute("SELECT count(*) FROM t").rows == [(0,)]
    for d in dirs:
        assert not os.path.exists(_staged_path(d, ing.xid))
        assert all(not f.endswith(".cts") or "stripe-" not in f
                   for f in os.listdir(d)) or True


def test_recovery_rolls_back_prepared(tmp_path):
    """Coordinator 'dies' after PREPARED but before COMMITTED."""
    cl = make_cluster(tmp_path)
    ing = _staged_ingest(cl)
    dirs = [w.directory for w in ing._writers.values()]
    cl.txlog.log(ing.xid, TxState.PREPARED,
                 {"kind": "ingest", "table": "t", "placements": dirs})
    cl.close()  # release the owner marker, as a real crash would
    # reopen: recovery must roll the transaction back
    cl2 = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    assert cl2.execute("SELECT count(*) FROM t").rows == [(0,)]
    assert cl2.txlog.outstanding() == []


def test_recovery_rolls_forward_committed(tmp_path):
    """Coordinator dies after COMMITTED but before the visibility flip."""
    cl = make_cluster(tmp_path)
    ing = _staged_ingest(cl)
    dirs = [w.directory for w in ing._writers.values()]
    cl.txlog.log(ing.xid, TxState.PREPARED,
                 {"kind": "ingest", "table": "t", "placements": dirs})
    cl.txlog.log(ing.xid, TxState.COMMITTED,
                 {"kind": "ingest", "table": "t", "placements": dirs})
    cl.close()  # release the owner marker, as a real crash would
    cl2 = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    assert cl2.execute("SELECT count(*) FROM t").rows == [(1000,)]
    assert cl2.txlog.outstanding() == []


def test_recovery_sweeps_unprepared_staged_files(tmp_path):
    """Coordinator dies mid-write, before any log record."""
    cl = make_cluster(tmp_path)
    _staged_ingest(cl)  # staged, never prepared
    cl.close()  # release the owner marker, as a real crash would
    cl2 = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    assert cl2.execute("SELECT count(*) FROM t").rows == [(0,)]
    # staged files swept
    for root, _, files in os.walk(str(tmp_path / "db" / "data")):
        assert not any(".staged." in f for f in files)


def test_copy_from_fault_rolls_back(tmp_path):
    cl = make_cluster(tmp_path)
    cl.copy_from("t", columns={"k": np.arange(100, dtype=np.int64),
                               "v": np.zeros(100, dtype=np.int64)})
    FAULTS.arm("catalog_commit", error=FaultError("crash"), times=1)
    with pytest.raises(FaultError):
        cl.copy_from("t", columns={"k": np.arange(100, dtype=np.int64),
                                   "v": np.ones(100, dtype=np.int64)})
    FAULTS.disarm()
    # the fault hit during finish() after COMMITTED was logged -> the
    # transaction rolls FORWARD on recovery (2PC semantics)
    cl.execute("SELECT recover_prepared_transactions()")
    assert cl.execute("SELECT count(*) FROM t").rows[0][0] in (100, 200)


def test_read_placement_failover(tmp_path):
    cl = make_cluster(tmp_path)
    cl.copy_from("t", columns={"k": np.arange(1000, dtype=np.int64),
                               "v": np.ones(1000, dtype=np.int64)})
    # replicate shard 0 so a failed read has somewhere to go
    t = cl.catalog.table("t")
    s0 = t.shards[0]
    src = s0.placements[0]
    dst = 1 - src
    cl.execute(f"SELECT citus_copy_shard_placement({s0.shard_id}, {src}, {dst})")
    before = cl.counters.snapshot()["connection_failovers"]
    FAULTS.arm("read_placement", error=FaultError("dead node"),
               match=f"t:{s0.shard_id}:{src}")
    r = cl.execute("SELECT count(*) FROM t")
    FAULTS.disarm()
    assert r.rows == [(1000,)]
    assert cl.counters.snapshot()["connection_failovers"] > before


def test_lock_manager_basic():
    lm = LockManager()
    lm.acquire(1, "shard:1", timeout=1)
    lm.acquire(1, "shard:1", timeout=1)  # re-entrant
    with pytest.raises(LockTimeout):
        lm.acquire(2, "shard:1", timeout=0.2)
    lm.release(1, "shard:1")
    lm.acquire(2, "shard:1", timeout=1)
    lm.release_all(2)
    # shared locks coexist
    lm.acquire(3, "rel:t", mode="shared", timeout=1)
    lm.acquire(4, "rel:t", mode="shared", timeout=1)
    rows = lm.lock_rows()
    assert sum(1 for r in rows if r[0] == "rel:t" and r[3]) == 2


def test_deadlock_detection():
    lm = LockManager()
    lm.acquire(1, "A", timeout=5)
    lm.acquire(2, "B", timeout=5)
    results = {}

    def s1():
        try:
            lm.acquire(1, "B", timeout=5)
            results[1] = "ok"
        except DeadlockDetected:
            results[1] = "deadlock"
        finally:
            lm.release_all(1)

    def s2():
        try:
            lm.acquire(2, "A", timeout=5)
            results[2] = "ok"
        except DeadlockDetected:
            results[2] = "deadlock"
        finally:
            lm.release_all(2)

    t1 = threading.Thread(target=s1)
    t2 = threading.Thread(target=s2)
    t1.start(); t2.start()
    t1.join(10); t2.join(10)
    assert sorted(results.values()) == ["deadlock", "ok"]


def test_stat_views(tmp_path):
    cl = make_cluster(tmp_path)
    cl.copy_from("t", columns={"k": np.arange(10, dtype=np.int64),
                               "v": np.zeros(10, dtype=np.int64)})
    cl.execute("SELECT count(*) FROM t")
    cl.execute("SELECT count(*) FROM t WHERE k = 3")
    counters = dict(cl.execute("SELECT citus_stat_counters()").rows)
    assert counters["queries_executed"] >= 2
    assert counters["router_queries"] >= 1
    stmts = cl.execute("SELECT citus_stat_statements()").rows
    assert any("count(*) from t" in q for q, *_ in stmts)
    # normalized: both WHERE k = 3 runs share a bucket with any literal
    shards_view = cl.execute("SELECT citus_shards()").rows
    assert len(shards_view) == 4
    tables_view = cl.execute("SELECT citus_tables()").rows
    assert any(r[0] == "t" and r[6] == 10 for r in tables_view)


def test_tenant_stats_and_progress_views(tmp_path):
    cl = make_cluster(tmp_path)
    cl.copy_from("t", columns={"k": np.arange(100, dtype=np.int64),
                               "v": np.zeros(100, dtype=np.int64)})
    cl.execute("SELECT count(*) FROM t WHERE k = 5")
    cl.execute("SELECT count(*) FROM t WHERE k = 5")
    cl.execute("SELECT count(*) FROM t WHERE k = 9")
    tenants = dict((r[0], r[1]) for r in
                   cl.execute("SELECT citus_stat_tenants()").rows)
    assert tenants.get("5") == 2
    assert tenants.get("9") == 1
    # progress view is empty without jobs, then reflects tasks
    assert cl.execute("SELECT get_rebalance_progress()").rows == []
    r = cl.background_jobs
    r.register("noop", lambda: None)
    jid = r.create_job("x")
    r.add_task(jid, "noop", {})
    r.wait_for_job(jid)
    rows = cl.execute("SELECT get_rebalance_progress()").rows
    assert rows and rows[0][3] == "done"
    cl.close()
