"""Cluster-wide observability plane: the wait-event seam
(stats.begin_wait/end_wait), the get_node_stats fan-out behind
citus_dist_stat_activity / citus_cluster_metrics, background-task
progress records, and the metrics exporter's HTTP mode.

Reference analogs: citus_dist_stat_activity (global pids merged across
workers), WaitEventSet instrumentation, and
get_rebalance_progress's bytes/phase columns.
"""

import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu import stats
from citus_tpu.executor.executor import GLOBAL_COUNTERS
from citus_tpu.testing.faults import FAULTS


@pytest.fixture()
def pair(tmp_path):
    """Authority + one attached worker (two processes' worth of state in
    one test process; node 0 hosted by a, node 1 by b)."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    a.register_node()
    b = ct.Cluster(str(tmp_path / "b"), data_port=0, hosted_nodes=set(),
                   coordinator=("127.0.0.1", a.control_port), n_nodes=0)
    b.register_node()
    a._maybe_reload_catalog(force_sync=True)
    yield a, b
    FAULTS.disarm()
    b.close()
    a.close()


@pytest.fixture()
def trio(tmp_path):
    """Authority + two attached workers — three live nodes, so the stat
    fan-out probes two remote endpoints."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    a.register_node()
    workers = []
    try:
        for name in ("b", "c"):
            w = ct.Cluster(str(tmp_path / name), data_port=0,
                           hosted_nodes=set(), n_nodes=0,
                           coordinator=("127.0.0.1", a.control_port))
            w.register_node()
            workers.append(w)
        a._maybe_reload_catalog(force_sync=True)
        yield a, workers
    finally:
        FAULTS.disarm()
        for w in workers:
            w.close()
        a.close()


def _load(cl, n=20000, shards=2, table="t"):
    cl.execute(f"CREATE TABLE {table} (k bigint NOT NULL, v bigint)")
    cl.execute(f"SELECT create_distributed_table('{table}', 'k', {shards})")
    cl.copy_from(table, columns={"k": np.arange(n), "v": np.arange(n) * 3})
    return n


# ------------------------------------------------------ wait-event seam


def test_wait_bracket_folds_blocked_ms():
    before = GLOBAL_COUNTERS.snapshot()["wait_lock_ms"]
    tok = stats.begin_wait("lock")
    time.sleep(0.02)
    ms = stats.end_wait(tok)
    assert ms >= 15
    after = GLOBAL_COUNTERS.snapshot()["wait_lock_ms"]
    assert after - before >= 15


def test_wait_bracket_books_at_least_one_ms():
    """A sub-ms block still books 1 ms — a bracketed wait is never
    invisible in the cumulative counter."""
    before = GLOBAL_COUNTERS.snapshot()["wait_remote_rpc_ms"]
    stats.end_wait(stats.begin_wait("remote_rpc"))
    assert GLOBAL_COUNTERS.snapshot()["wait_remote_rpc_ms"] - before >= 1


def test_wait_sink_sees_enter_and_clear():
    seen = []
    stats.push_wait_sink(seen.append)
    try:
        tok = stats.begin_wait("prefetch_stall")
        stats.end_wait(tok)
    finally:
        stats.pop_wait_sink()
    assert seen == ["prefetch_stall", ""]
    # popped: further brackets don't reach the sink
    stats.end_wait(stats.begin_wait("prefetch_stall"))
    assert seen == ["prefetch_stall", ""]


def test_wait_events_registry_matches_counters():
    for ev, ctr in stats.WAIT_COUNTERS.items():
        assert ctr in stats.StatCounters.COUNTERS
    assert stats.WAIT_EVENTS == tuple(sorted(stats.WAIT_COUNTERS))


def test_activity_rows_carry_wait_event(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    try:
        gpid = cl.activity.enter("SELECT 1")
        cl.activity.set_wait(gpid, "lock")
        row = [r for r in cl.activity.rows_view() if r[0] == gpid][0]
        assert row[-1] == "lock"
        cl.activity.set_wait(gpid, "")
        row = [r for r in cl.activity.rows_view() if r[0] == gpid][0]
        assert row[-1] == ""
        cl.activity.exit(gpid)
    finally:
        cl.close()


def test_lock_contention_books_wait(tmp_path):
    """Two threads racing one advisory lock: the loser's blocked time
    lands in wait_lock_ms and its activity row shows wait_event=lock."""
    cl = ct.Cluster(str(tmp_path / "db"))
    try:
        before = GLOBAL_COUNTERS.snapshot()["wait_lock_ms"]
        cl.locks.acquire(1, "race")
        events = []
        stats.push_wait_sink(events.append)

        def _release_soon():
            time.sleep(0.05)
            cl.locks.release(1, "race")

        t = threading.Thread(target=_release_soon)
        t.start()
        try:
            cl.locks.acquire(2, "race", timeout=5.0)
        finally:
            t.join()
            stats.pop_wait_sink()
        cl.locks.release(2, "race")
        assert GLOBAL_COUNTERS.snapshot()["wait_lock_ms"] - before >= 40
        assert events and events[0] == "lock" and events[-1] == ""
    finally:
        cl.close()


# -------------------------------------------------- stat fan-out views


def test_dist_stat_activity_shows_remote_wait(pair):
    """A query blocked on a remote task shows up in
    citus_dist_stat_activity with wait_event=remote_rpc, and the view
    carries per-node rows from every live endpoint."""
    a, b = pair
    _load(a)
    a.execute("SELECT count(*) FROM t")  # warm plans/caches
    FAULTS.arm("execute_task", delay_s=1.0)
    done = threading.Event()

    def _run():
        try:
            a.execute("SELECT count(*) FROM t")
        finally:
            done.set()

    t = threading.Thread(target=_run)
    t.start()
    try:
        seen_wait = None
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and seen_wait is None:
            r = a.execute("SELECT citus_dist_stat_activity()")
            cols = r.columns
            for row in r.rows:
                d = dict(zip(cols, row))
                if d["wait_event"] == "remote_rpc":
                    seen_wait = d
                    break
            time.sleep(0.02)
        assert seen_wait is not None, "never observed remote_rpc wait"
        assert seen_wait["node"] is not None
        assert "count(*)" in seen_wait["query"]
    finally:
        FAULTS.disarm()
        done.wait(10)
        t.join()
    assert GLOBAL_COUNTERS.snapshot()["wait_remote_rpc_ms"] >= 900


def test_dist_stat_activity_merges_remote_rows(pair):
    """A statement live on the WORKER's handle is visible from the
    coordinator's merged view, attributed to the worker's node."""
    a, b = pair
    gpid = b.activity.enter("SELECT 'held open'")
    try:
        r = a.execute("SELECT citus_dist_stat_activity()")
        rows = [dict(zip(r.columns, row)) for row in r.rows]
        remote = [d for d in rows if d["global_pid"] == gpid
                  and "held open" in d["query"]]
        assert remote, rows
        assert remote[0]["node"] == 1
    finally:
        b.activity.exit(gpid)


def test_dead_node_degrades_to_unreachable_within_timeout(pair):
    """Kill node 1 (as seen from the coordinator: its endpoint stops
    answering) — the merged view degrades to a node_unreachable row
    within the per-node budget instead of raising or hanging."""
    a, b = pair
    a.execute("SET citus.stat_fanout_timeout_s = 0.5")
    # a wedged peer: accepts the probe but never answers get_node_stats
    # (a hard-killed process behaves the same through this sandbox's
    # loopback proxy — the connection opens, then blackholes)
    b._data_server.server.register(
        "get_node_stats", lambda p: time.sleep(30) or {})
    t0 = time.monotonic()
    r = a.execute("SELECT citus_dist_stat_activity()")
    elapsed = time.monotonic() - t0
    rows = [dict(zip(r.columns, row)) for row in r.rows]
    dead = [d for d in rows if d["state"] == "node_unreachable"]
    assert dead and dead[0]["node"] == 1
    # dead endpoint costs at most the per-node timeout (+ join slack)
    assert elapsed < 2.5, elapsed
    assert GLOBAL_COUNTERS.snapshot()["stat_fanout_unreachable"] >= 1


def test_cluster_metrics_node_labels(trio):
    a, workers = trio
    _load(a, shards=3)
    a.execute("SELECT count(*) FROM t")
    r = a.execute("SELECT citus_cluster_metrics()")
    txt = "\n".join(row[0] for row in r.rows)
    assert "# TYPE citus_queries_executed_total counter" in txt
    # every node's series is labeled; the coordinator's sees our queries
    assert 'citus_queries_executed_total{node="0"}' in txt
    assert 'citus_node_unreachable{node="1"} 0' in txt
    assert 'citus_node_unreachable{node="2"} 0' in txt
    # kill one worker (endpoint rewired to a hole): its series degrade
    # to the unreachable marker while the others keep reporting
    a.execute("SET citus.stat_fanout_timeout_s = 0.5")
    workers[0]._data_server.server.register(
        "get_node_stats", lambda p: time.sleep(30) or {})
    txt2 = "\n".join(
        row[0] for row in
        a.execute("SELECT citus_cluster_metrics()").rows)
    assert 'citus_node_unreachable{node="1"} 1' in txt2
    assert 'citus_node_unreachable{node="2"} 0' in txt2


def test_cluster_slow_queries_attributes_node(pair):
    a, b = pair
    from citus_tpu.observability.slowlog import GLOBAL_SLOW_LOG
    GLOBAL_SLOW_LOG.clear()
    a.execute("SET citus.log_min_duration_ms = 0")
    a.execute("SELECT 1")
    r = a.execute("SELECT citus_cluster_slow_queries()")
    assert r.columns[0] == "node"
    assert any("SELECT 1" in str(row[-1]) for row in r.rows), r.rows


def test_get_node_stats_rpc_payload(pair):
    """The RPC itself: one round trip returns counters + gauges +
    activity + progress in a single JSON-safe payload."""
    a, b = pair
    from citus_tpu.net.rpc import RpcClient
    host, port = a.catalog.node_endpoint(1)
    c = RpcClient(host, port, timeout=5.0, secret=a.catalog.remote_data.secret)
    try:
        p = c.call("get_node_stats", {})
    finally:
        c.close()
    assert p["node_ids"] == [1]
    assert "queries_executed" in p["counters"]
    assert "live_queries" in p["gauges"]
    assert isinstance(p["activity"], list)
    assert isinstance(p["progress"], list)


# ------------------------------------------------- progress monitoring


def test_rebalance_progress_phases_and_bytes(tmp_path):
    """Poll get_rebalance_progress during a slowed shard move: bytes
    climb monotonically, phases walk copy -> flip -> cleanup, and the
    running task surfaces as citus_task_bytes_* gauges in
    citus_cluster_metrics."""
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    try:
        _load(cl, n=30000, shards=4)
        t = cl.catalog.table("t")
        shard = t.shards[0]
        src = shard.placements[0]
        dst = 1 - src
        FAULTS.arm("shard_move_copy", delay_s=0.15)
        jid = cl.background_jobs.create_job("observability move")
        tid = cl.background_jobs.add_task(
            jid, "move_shard", {"shard_id": shard.shard_id,
                                "source": src, "target": dst})
        seen_phases, byte_trail, metrics_saw_task = [], [], False
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            r = cl.execute("SELECT get_rebalance_progress()")
            rows = [dict(zip(r.columns, row)) for row in r.rows
                    if row[0] == tid]
            if rows and rows[0]["status"] in ("done", "failed"):
                assert rows[0]["status"] == "done", rows[0]
                break
            if rows and rows[0]["status"] == "running":
                d = rows[0]
                if d["phase"] and (not seen_phases
                                   or seen_phases[-1] != d["phase"]):
                    seen_phases.append(d["phase"])
                byte_trail.append(d["bytes_done"])
                if not metrics_saw_task:
                    txt = "\n".join(
                        row[0] for row in
                        cl.execute("SELECT citus_cluster_metrics()").rows)
                    metrics_saw_task = "citus_task_bytes_done{" in txt
            time.sleep(0.02)
        else:
            pytest.fail("move never finished")
        assert "copy" in seen_phases, seen_phases
        assert byte_trail == sorted(byte_trail), byte_trail
        assert byte_trail and byte_trail[-1] > 0
        assert metrics_saw_task
        # phases recorded in order (any subset, but never out of order);
        # the non-blocking move added a catchup phase between copy and
        # flip (operations/shard_transfer.py)
        order = {"starting": 0, "copy": 1, "catchup": 2, "flip": 3,
                 "cleanup": 4}
        ranks = [order[p] for p in seen_phases]
        assert ranks == sorted(ranks), seen_phases
        # finished task reports its final odometer + schema'd columns
        r = cl.execute("SELECT get_rebalance_progress()")
        d = [dict(zip(r.columns, row)) for row in r.rows if row[0] == tid][0]
        assert d["bytes_total"] > 0 and d["bytes_done"] >= d["bytes_total"]
        assert d["started_at"] is not None
        assert r.columns == ["task_id", "op", "args", "status", "attempts",
                             "phase", "bytes_done", "bytes_total",
                             "started_at", "eta_s"]
    finally:
        FAULTS.disarm()
        cl.close()


def test_jobs_view_is_a_copy(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    try:
        runner = cl.background_jobs
        v = runner.jobs_view()
        assert v == {"jobs": [], "tasks": []}
        v["tasks"].append({"oops": True})
        assert runner.jobs_view()["tasks"] == []
    finally:
        cl.close()


def test_eta_derives_from_rate():
    from citus_tpu.services.background_jobs import BackgroundJobRunner
    t = {"status": "running", "started_at": 100.0,
         "bytes_done": 250, "bytes_total": 1000}
    # 250 bytes in 10 s -> 750 more at the same rate = 30 s
    assert BackgroundJobRunner._eta_s(t, 110.0) == pytest.approx(30.0)
    t["bytes_done"] = 0
    assert BackgroundJobRunner._eta_s(t, 110.0) is None
    t.update(bytes_done=1000)
    assert BackgroundJobRunner._eta_s(t, 110.0) is None
    t.update(bytes_done=250, status="done")
    assert BackgroundJobRunner._eta_s(t, 110.0) is None


# ------------------------------------------- flight-recorder fan-out


def test_stat_history_cluster_fanout_monotonic(trio):
    """citus_stat_history fans the recorder rings in from every node:
    three hosts' samples merge into one monotonic-ts, node-attributed
    time series."""
    a, workers = trio
    _load(a, shards=3)
    a.execute("SELECT count(*) FROM t")  # books remote RPC wait ms
    for node in [a] + workers:
        node.flight_recorder.run_once()
    a.execute("SELECT sum(v) FROM t")
    for node in [a] + workers:
        node.flight_recorder.run_once()
    r = a.execute("SELECT citus_stat_history('wait_remote_rpc_ms', 60)")
    assert r.columns == ["ts", "node", "metric", "value", "rate"]
    rows = [dict(zip(r.columns, row)) for row in r.rows]
    assert {d["node"] for d in rows} == {0, 1, 2}
    assert all(d["metric"] == "wait_remote_rpc_ms" for d in rows)
    ts = [d["ts"] for d in rows]
    assert ts == sorted(ts)
    # two ticks per node survived the lookback window
    assert len(rows) == 6
    # the coordinator actually blocked on remote RPCs between its ticks
    coord = [d for d in rows if d["node"] == 0]
    assert coord[-1]["value"] >= coord[0]["value"] >= 0


def test_stat_history_degrades_and_raises_dead_node_event(trio):
    """A dead worker degrades citus_stat_history to the live nodes'
    rows and raises exactly one dead_node health event on the
    coordinator's recorder (resolved when the node answers again)."""
    a, workers = trio
    a.execute("SET citus.stat_fanout_timeout_s = 0.5")
    for node in [a, workers[1]]:
        node.flight_recorder.run_once()
    workers[0]._data_server.server.register(
        "get_node_stats", lambda p: time.sleep(30) or {})
    r = a.execute("SELECT citus_stat_history('queries_executed')")
    nodes = {row[1] for row in r.rows}
    assert 1 not in nodes and 0 in nodes and 2 in nodes
    # repeat fan-outs dedupe into one active event
    a.execute("SELECT citus_stat_history('queries_executed')")
    assert a.flight_recorder.active_counts()["dead_node"] == 1
    ev = a.execute("SELECT citus_health_events()")
    dead = [dict(zip(ev.columns, row)) for row in ev.rows
            if row[2] == "dead_node"]
    assert len(dead) == 1
    assert dead[0]["severity"] == "critical" and dead[0]["active"] is True
    assert dead[0]["node"] == 0  # the coordinator's recorder observed it


# ------------------------------------------------------- HTTP exporter


def _scrape(port, path="/metrics"):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        return resp.status, resp.headers.get("Content-Type"), \
            resp.read().decode()


def test_metrics_exporter_http_scrape(tmp_path):
    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(__file__)
                           .resolve().parents[1] / "scripts"))
    try:
        import metrics_exporter
    finally:
        sys.path.pop(0)
    cl = ct.Cluster(str(tmp_path / "db"))
    srv = None
    try:
        cl.execute("SELECT 1")
        srv = metrics_exporter.make_server(cl, 0, host="127.0.0.1")
        port = srv.server_address[1]
        th = threading.Thread(target=srv.serve_forever, daemon=True)
        th.start()
        status, ctype, body = _scrape(port)
        assert status == 200
        assert ctype.startswith("text/plain")
        assert "# TYPE citus_queries_executed_total counter" in body
        # every sample line parses as <name>{labels}? <value>
        for line in body.splitlines():
            if not line or line.startswith("#"):
                continue
            name_part, val = line.rsplit(" ", 1)
            float(val)
            assert name_part.startswith("citus_"), line
        with pytest.raises(urllib.error.HTTPError) as exc:
            _scrape(port, "/nope")
        assert exc.value.code == 404
    finally:
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        cl.close()


def test_metrics_exporter_cluster_mode_labels(pair):
    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(__file__)
                           .resolve().parents[1] / "scripts"))
    try:
        import metrics_exporter
    finally:
        sys.path.pop(0)
    a, b = pair
    a.execute("SELECT 1")
    srv = metrics_exporter.make_server(a, 0, cluster_wide=True,
                                       host="127.0.0.1")
    try:
        th = threading.Thread(target=srv.serve_forever, daemon=True)
        th.start()
        status, _, body = _scrape(srv.server_address[1])
        assert status == 200
        assert 'node="0"' in body and 'node="1"' in body
        assert "citus_node_unreachable" in body
    finally:
        srv.shutdown()
        srv.server_close()


def test_metrics_exporter_cluster_scrape_with_dead_node(pair):
    """--cluster scrape with one dead worker is a DEGRADED success: the
    HTTP response is still 200, the live node's series are present, and
    the dead node surfaces as citus_node_unreachable=1 — never a scrape
    failure."""
    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(__file__)
                           .resolve().parents[1] / "scripts"))
    try:
        import metrics_exporter
    finally:
        sys.path.pop(0)
    a, b = pair
    a.execute("SELECT 1")
    a.execute("SET citus.stat_fanout_timeout_s = 0.5")
    b._data_server.server.register(
        "get_node_stats", lambda p: time.sleep(30) or {})
    srv = metrics_exporter.make_server(a, 0, cluster_wide=True,
                                       host="127.0.0.1")
    try:
        th = threading.Thread(target=srv.serve_forever, daemon=True)
        th.start()
        status, _, body = _scrape(srv.server_address[1])
        assert status == 200
        assert 'citus_node_unreachable{node="1"} 1' in body
        assert 'citus_node_unreachable{node="0"} 0' in body
        assert 'citus_queries_executed_total{node="0"}' in body
    finally:
        srv.shutdown()
        srv.server_close()


def test_metrics_exporter_main_exit_codes(tmp_path, monkeypatch, capsys):
    """main() exits 0 on a working one-shot dump and non-zero only on
    total failure (unopenable cluster / render exception)."""
    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(__file__)
                           .resolve().parents[1] / "scripts"))
    try:
        import metrics_exporter
    finally:
        sys.path.pop(0)
    assert metrics_exporter.main([str(tmp_path / "db")]) == 0
    out = capsys.readouterr().out
    assert "# TYPE citus_queries_executed_total counter" in out
    # a data dir that cannot open is a total failure -> rc 1
    bogus = tmp_path / "not_a_dir"
    bogus.write_text("plain file, not a data dir")
    assert metrics_exporter.main([str(bogus)]) == 1
    assert "cannot open cluster" in capsys.readouterr().err
    # a render-time exception in one-shot mode is a total failure too

    def _boom(cl, cluster_wide):
        raise RuntimeError("render exploded")

    monkeypatch.setattr(metrics_exporter, "render_metrics", _boom)
    assert metrics_exporter.main([str(tmp_path / "db2")]) == 1
    assert "render failed" in capsys.readouterr().err
