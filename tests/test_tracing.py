"""End-to-end distributed tracing (citus_tpu/observability/): span-tree
shape, cross-RPC trace_id propagation over a 2-host in-process cluster,
the allocation-free unsampled hot path, slow-query force-capture, the
Chrome-trace / Prometheus exporters, and the live-phase activity view.
"""

import json
import os

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.observability import trace as T
from citus_tpu.observability.slowlog import GLOBAL_SLOW_LOG


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"))
    c.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    c.execute("SELECT create_distributed_table('t', 'k', 4)")
    c.copy_from("t", columns={"k": np.arange(2000),
                              "v": np.arange(2000) * 2})
    yield c
    c.close()


@pytest.fixture()
def pair(tmp_path):
    """Two coordinators, one logical cluster (same shape as the
    worker-tasks fixture): A hosts node 0, B attaches and hosts 1."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    a.register_node()
    b = ct.Cluster(str(tmp_path / "b"), data_port=0, hosted_nodes=set(),
                   coordinator=("127.0.0.1", a.control_port), n_nodes=0)
    b.register_node()
    a._maybe_reload_catalog(force_sync=True)
    yield a, b
    b.close()
    a.close()


# ------------------------------------------------------ tree correctness


def test_span_tree_single_rooted_no_orphans(cl):
    cl.execute("SET citus.trace_sample_rate = 1.0")
    cl.execute("SELECT count(*), sum(v) FROM t WHERE v < 3000")
    tr = T.last_trace()
    assert tr is not None
    root = tr.root()
    assert root is not None and root.name == "query"
    ids = {s.span_id for s in tr.spans}
    roots = [s for s in tr.spans
             if s.parent_id is None or s.parent_id not in ids]
    assert roots == [root], [s.name for s in roots]
    # the canonical phases hang off the tree
    names = {s.name for s in tr.spans}
    assert {"parse", "plan", "execute", "finalize"} <= names, names
    # every span closed, durations folded into counters
    assert all(s.t1 is not None for s in tr.spans)
    snap = cl.counters.snapshot()
    assert snap["trace_queries_sampled"] >= 1
    assert snap["trace_spans_recorded"] >= len(tr.spans)


def test_plan_span_reports_cache_hit(cl):
    cl.execute("SET citus.trace_sample_rate = 1.0")
    cl.execute("SELECT sum(v) FROM t WHERE v < 100")
    cl.execute("SELECT sum(v) FROM t WHERE v < 100")
    tr = T.last_trace()
    ps = tr.find("plan")
    assert ps is not None and ps.attrs.get("cache_hit") is True


def test_unsampled_path_is_allocation_free(cl):
    cl.execute("SET citus.trace_sample_rate = 0")
    cl.execute("SELECT count(*) FROM t")  # settle caches/compiles
    before = T.span_allocations()
    cl.execute("SELECT count(*) FROM t WHERE k = 7")
    cl.execute("SELECT sum(v) FROM t")
    assert T.span_allocations() == before


def test_sample_rate_validation(cl):
    from citus_tpu.errors import CatalogError
    with pytest.raises(CatalogError):
        cl.execute("SET citus.trace_sample_rate = 1.5")


# ------------------------------------------------------------ slow log


def test_slow_log_force_captures_at_threshold(cl):
    GLOBAL_SLOW_LOG.clear()
    cl.execute("SET citus.trace_sample_rate = 0")
    cl.execute("SET citus.log_min_duration_ms = 0")
    cl.execute("SELECT count(*) FROM t")
    assert len(GLOBAL_SLOW_LOG) >= 1
    ts, dur_ms, trace_id, phases, sql = GLOBAL_SLOW_LOG.rows_view()[0]
    assert "count(*)" in sql and dur_ms >= 0
    assert "execute=" in phases  # per-phase breakdown from the tree
    # threshold off -> no further capture
    GLOBAL_SLOW_LOG.clear()
    cl.execute("SET citus.log_min_duration_ms = -1")
    cl.execute("SELECT count(*) FROM t")
    assert len(GLOBAL_SLOW_LOG) == 0
    # a high threshold watches but does not capture fast queries
    cl.execute("SET citus.log_min_duration_ms = 60000")
    cl.execute("SELECT count(*) FROM t")
    assert len(GLOBAL_SLOW_LOG) == 0
    r = cl.execute("SELECT citus_slow_queries()")
    assert r.columns[1] == "duration_ms"


# ------------------------------------------------------- cross-host RPC


def test_remote_spans_share_trace_id_and_nest(pair, tmp_path):
    """The acceptance criterion: a sampled multi-shard aggregate over a
    2-host cluster exports ONE Chrome trace whose remote execute_task
    spans nest under the coordinator's query span, sharing trace_id."""
    a, b = pair
    n = 8000
    a.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('t', 'k', 4)")
    a.copy_from("t", columns={"k": np.arange(n), "v": np.arange(n)})
    export = tmp_path / "traces"
    a.execute("SET citus.trace_sample_rate = 1.0")
    a.execute(f"SET citus.trace_export_dir = '{export}'")
    r = a.execute("SELECT count(*), sum(v) FROM t")
    assert r.rows == [(n, n * (n - 1) // 2)]
    tr = T.last_trace()
    root = tr.root()
    assert root.name == "query"
    rtasks = tr.find_all("remote_task")
    assert rtasks, [s.name for s in tr.spans]
    by_id = {s.span_id: s for s in tr.spans}
    # worker-recorded execute_task spans were grafted under remote_task
    # spans of the SAME trace (single tree, one trace_id)
    wspans = tr.find_all("execute_task")
    assert wspans, [s.name for s in tr.spans]
    for w in wspans:
        anchor = by_id[w.parent_id]
        assert anchor.name == "remote_task"
        # ancestry chains to the coordinator's query root
        cur = anchor
        while cur.parent_id is not None:
            cur = by_id[cur.parent_id]
        assert cur is root
        # grafted times are re-anchored inside the RPC window
        assert anchor.t0 <= w.t0 and w.t1 <= anchor.t1 + 1e-6
    # worker body spans came along too
    assert tr.find("worker_scan") is not None
    # exported Chrome trace: one file for this query, loadable JSON
    files = [f for f in os.listdir(export) if f.endswith(".json")]
    assert f"trace_{tr.trace_id}.json" in files
    doc = json.load(open(export / f"trace_{tr.trace_id}.json"))
    assert doc["otherData"]["trace_id"] == tr.trace_id
    evts = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in evts}
    assert {"query", "remote_task", "execute_task"} <= names
    # remote worker spans render on a different pid row than the
    # coordinator's
    pids = {e["pid"] for e in evts if e["name"] == "execute_task"}
    assert pids and 1 not in pids


def test_explain_analyze_renders_from_span_tree(pair):
    a, b = pair
    n = 4000
    a.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('t', 'k', 4)")
    a.copy_from("t", columns={"k": np.arange(n), "v": np.arange(n)})
    a.execute("SET citus.trace_sample_rate = 0")  # forced trace anyway
    r = a.execute("EXPLAIN ANALYZE SELECT count(*) FROM t")
    txt = "\n".join(row[0] for row in r.rows)
    assert "Plan Cache:" in txt and "Elapsed:" in txt
    assert "Remote Tasks:" in txt and "pushed to node" in txt, txt
    # the lines came from the forced trace's tree
    tr = T.last_trace()
    assert tr.find("remote_task") is not None
    assert "forced" in tr.reasons


# ----------------------------------------------------------- exporters


def test_prometheus_text_exposition(cl):
    cl.execute("SELECT count(*) FROM t")
    r = cl.execute("SHOW citus.metrics")
    txt = "\n".join(row[0] for row in r.rows)
    assert "# TYPE citus_queries_executed_total counter" in txt
    assert "# HELP citus_queries_executed_total" in txt
    assert "citus_plan_cache_entries" in txt
    assert "citus_query_latency_ms_bucket" in txt
    assert 'le="+Inf"' in txt
    assert "citus_query_latency_ms_count" in txt
    # SQL-function spelling returns the same payload
    r2 = cl.execute("SELECT citus_metrics()")
    assert "\n".join(row[0] for row in r2.rows).splitlines()[0] \
        == txt.splitlines()[0]


def test_activity_reports_phase(cl):
    """ActivityTracker rows carry the live phase (and a wait_event
    column after it); a finished query leaves no rows, so drive the
    tracker directly."""
    gpid = cl.activity.enter("SELECT 1")
    T.push_phase_sink(lambda ph, _g=gpid: cl.activity.set_phase(_g, ph))
    try:
        T.set_phase("remote-wait")
        r = cl.execute("SELECT citus_stat_activity()")
        mine = [dict(zip(r.columns, row)) for row in r.rows
                if row[0] == gpid]
        assert mine and mine[0]["phase"] == "remote-wait"
        assert mine[0]["wait_event"] == ""
    finally:
        T.pop_phase_sink()
        cl.activity.exit(gpid)


def test_two_pc_spans_on_cross_host_write(pair):
    a, b = pair
    n = 1000
    a.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('t', 'k', 4)")
    a.copy_from("t", columns={"k": np.arange(n), "v": np.arange(n)})
    a.execute("SET citus.trace_sample_rate = 1.0")
    a.execute("UPDATE t SET v = v + 1 WHERE v >= 0")
    # the multi-host modify recorded its 2PC phases in SOME sampled
    # trace this statement produced
    tr = T.last_trace()
    names = {s.name for s in tr.spans}
    assert "2pc_prepare" in names and "2pc_commit_point" in names, names
    assert "2pc_decide" in names, names
