"""Global (cross-process) deadlock detection.

Reference: BuildGlobalWaitGraph (transaction/lock_graph.c:142) +
CheckForDistributedDeadlocks (distributed_deadlock_detection.c:105) —
merged wait graph across nodes, DFS cycles, cancel the youngest.
Here: holder/waiter records beside the flock lockfiles, assembled by
the maintenance daemon; victims get a cancel marker their flock wait
loops poll.
"""

import os
import subprocess
import sys
import time

import pytest

import citus_tpu as ct
from citus_tpu.transaction import global_deadlock as gd
from citus_tpu.transaction.locks import EXCLUSIVE, SHARED


def test_graph_and_victim_selection(tmp_path):
    d = str(tmp_path)
    gd.publish_hold(d, "100:1", "coloc:1", EXCLUSIVE, started=10.0)
    gd.publish_wait(d, "100:1", "coloc:2", EXCLUSIVE, started=10.0)
    gd.publish_hold(d, "200:2", "coloc:2", EXCLUSIVE, started=20.0)
    gd.publish_wait(d, "200:2", "coloc:1", EXCLUSIVE, started=20.0)
    edges, started, _nonces = gd.build_global_graph(d)
    assert edges["100:1"] == {"200:2"}
    assert edges["200:2"] == {"100:1"}
    victim = gd.find_cycle_victim(edges, started)
    assert victim == "200:2"  # youngest dies


def test_shared_holders_do_not_conflict(tmp_path):
    d = str(tmp_path)
    gd.publish_hold(d, "100:1", "coloc:1", SHARED, started=1.0)
    gd.publish_wait(d, "200:2", "coloc:1", SHARED, started=2.0)
    edges, _, _ = gd.build_global_graph(d)
    assert edges == {}
    gd.publish_wait(d, "300:3", "coloc:1", EXCLUSIVE, started=3.0)
    edges, _, _ = gd.build_global_graph(d)
    assert edges["300:3"] == {"100:1"}


def test_manager_layer_cycle_across_processes(tmp_path, monkeypatch):
    """A cycle threading two processes' in-process manager layers is
    only visible once each process dumps its manager graph: P111.s1
    ->(mgr) P111.s2 ->(flock r2) P222.s3 ->(mgr) P222.s4 ->(flock r1)
    -> P111.s1."""
    d = str(tmp_path)
    monkeypatch.setattr(gd, "_pid_alive", lambda pid: True)
    gd._write_record(gd._graph_dump_path(d, 111),
                     {"pid": 111, "edges": {"1": ["2"]},
                      "started": {"1": 10.0, "2": 11.0}})
    gd._write_record(gd._record_path(d, "w", "111:2", "r2"),
                     {"gpid": "111:2", "resource": "r2", "mode": EXCLUSIVE,
                      "started": 11.0, "pid": 111, "nonce": "abc"})
    gd._write_record(gd._record_path(d, "h", "222:3", "r2"),
                     {"gpid": "222:3", "resource": "r2", "mode": EXCLUSIVE,
                      "started": 12.0, "pid": 222})
    gd._write_record(gd._graph_dump_path(d, 222),
                     {"pid": 222, "edges": {"3": ["4"]},
                      "started": {"3": 12.0, "4": 13.0}})
    gd._write_record(gd._record_path(d, "w", "222:4", "r1"),
                     {"gpid": "222:4", "resource": "r1", "mode": EXCLUSIVE,
                      "started": 13.0, "pid": 222, "nonce": "def"})
    gd._write_record(gd._record_path(d, "h", "111:1", "r1"),
                     {"gpid": "111:1", "resource": "r1", "mode": EXCLUSIVE,
                      "started": 10.0, "pid": 111})
    edges, started, nonces = gd.build_global_graph(d)
    victim = gd.find_cycle_victim(edges, started)
    assert victim == "222:4"          # youngest across all four layers
    assert nonces[victim] == "def"    # cancellable by targeted marker


def test_stale_cancel_marker_is_ignored(tmp_path):
    d = str(tmp_path)
    gd.request_cancel(d, "100:7", nonce="old-wait")
    # a NEW wait with a different nonce must not be aborted by it
    assert gd.check_cancelled(d, "100:7", nonce="new-wait") is False
    # and the stale marker was consumed
    assert gd.check_cancelled(d, "100:7", nonce="new-wait") is False
    gd.request_cancel(d, "100:7", nonce="new-wait")
    assert gd.check_cancelled(d, "100:7", nonce="new-wait") is True


def test_dead_process_records_are_swept(tmp_path):
    d = str(tmp_path)
    p = gd.publish_hold(d, "999999:1", "coloc:1", EXCLUSIVE, started=1.0)
    # overwrite with a guaranteed-dead pid
    import json
    rec = json.load(open(p))
    rec["pid"] = 2 ** 22 - 7  # beyond pid_max on this box
    json.dump(rec, open(p, "w"))
    holds, waits, started = gd._load_records(d)
    assert holds == {} and waits == []
    assert not os.path.exists(p)


CHILD = r"""
import os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import citus_tpu as ct
from citus_tpu.transaction.locks import DeadlockDetected

data_dir, sync_dir = sys.argv[1], sys.argv[2]
from citus_tpu.config import ExecutorSettings, Settings
# a generous lock timeout separates "victim-cancelled by detection"
# from "gave up by timeout" even on a heavily loaded test box
cl = ct.Cluster(data_dir, settings=Settings(
    executor=ExecutorSettings(lock_timeout_s=120.0)))
s = cl.session()
s.execute("BEGIN")
s.execute("UPDATE a SET v = v + 1 WHERE k = 1")   # lock group a
open(os.path.join(sync_dir, "child_locked_a"), "w").close()
deadline = time.time() + 30
while not os.path.exists(os.path.join(sync_dir, "parent_locked_b")):
    if time.time() > deadline:
        print("SYNC_TIMEOUT"); sys.exit(2)
    time.sleep(0.05)
try:
    s.execute("UPDATE b SET v = v + 1 WHERE k = 1")  # -> cycle
    print("CHILD_COMPLETED")
except DeadlockDetected:
    print("CHILD_DEADLOCK_VICTIM")
    s.execute("ROLLBACK")
except Exception as e:
    print("CHILD_OTHER:" + type(e).__name__)
cl.close()
"""


def test_two_process_opposite_order_resolves_by_victim(tmp_path):
    """The round-2 done-criterion: two processes taking group locks in
    opposite order resolve by victim cancellation within the detection
    interval — NOT by LockTimeout."""
    data_dir = str(tmp_path / "db")
    sync_dir = str(tmp_path / "sync")
    os.makedirs(sync_dir)
    from citus_tpu.config import ExecutorSettings, Settings
    cl = ct.Cluster(data_dir, settings=Settings(
        executor=ExecutorSettings(lock_timeout_s=120.0)))
    cl.execute("CREATE TABLE a (k bigint, v bigint)")
    cl.execute("CREATE TABLE b (k bigint, v bigint)")
    cl.create_distributed_table("a", "k", 2, colocate_with="none")
    cl.create_distributed_table("b", "k", 2, colocate_with="none")
    cl.copy_from("a", rows=[(1, 0)])
    cl.copy_from("b", rows=[(1, 0)])

    script = tmp_path / "child.py"
    script.write_text(CHILD)
    # parent transaction begins FIRST -> child is the younger victim
    s = cl.session()
    s.execute("BEGIN")
    s.execute("UPDATE b SET v = v + 1 WHERE k = 1")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo_root)
    child = subprocess.Popen(
        [sys.executable, str(script), data_dir, sync_dir],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
    deadline = time.time() + 60
    while not os.path.exists(os.path.join(sync_dir, "child_locked_a")):
        assert child.poll() is None, child.communicate()
        assert time.time() < deadline, "child never locked a"
        time.sleep(0.05)
    open(os.path.join(sync_dir, "parent_locked_b"), "w").close()
    time.sleep(0.3)  # let the child reach its blocking UPDATE b
    t0 = time.time()
    s.execute("UPDATE a SET v = v + 1 WHERE k = 1")  # blocks, then wins
    elapsed = time.time() - t0
    s.execute("COMMIT")
    out, err = child.communicate(timeout=180)
    assert "CHILD_DEADLOCK_VICTIM" in out, (out, err)
    # resolved by cancellation (detection interval ~2s, generous
    # load headroom), not by the 120s lock timeout
    assert elapsed < 90, f"took {elapsed:.1f}s — smells like LockTimeout"
    assert cl.execute("SELECT v FROM a WHERE k = 1").rows == [(1,)]
    assert cl.execute("SELECT v FROM b WHERE k = 1").rows == [(1,)]
    cl.close()


def test_daemon_registers_deadlock_duty(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    names = [d[0] for d in cl.maintenance.status()]
    assert "deadlock_detection" in names
    cl.close()


def test_daemon_starts_with_cluster(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    assert cl._maintenance is not None          # started at open
    assert cl.maintenance._thread is not None   # thread live
    cl.close()
    from citus_tpu.config import Settings
    st = Settings(start_maintenance_daemon=False)
    cl2 = ct.Cluster(str(tmp_path / "db2"), settings=st)
    assert cl2._maintenance is None             # opt-out honored
    cl2.close()
