"""Test harness configuration.

Multi-"node" behavior is tested the way the reference tests multi-node
clusters on one box (src/test/regress/pg_regress_multi.pl launches a
coordinator + workers on localhost): we force JAX onto the host platform
with 8 virtual devices so every sharding/collective path runs exactly as
it would on an 8-chip TPU slice.

Note: this environment may register an accelerator PJRT plugin from
sitecustomize that overrides JAX_PLATFORMS; jax.config.update is the
reliable way to pin the cpu platform, and XLA_FLAGS must be set before
the backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

assert len(jax.devices()) == 8, f"expected 8 cpu devices, got {jax.devices()}"


@pytest.fixture()
def tmp_cluster(tmp_path):
    import citus_tpu as ct

    cluster = ct.Cluster(str(tmp_path / "db"))
    yield cluster


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session", autouse=True)
def _sanitizer_teardown_gate():
    """When the suite runs under CITUS_SANITIZE, an empty
    citus_sanitizer_report() at teardown is part of the contract —
    findings any individual test missed still fail the run."""
    yield
    from citus_tpu.utils import sanitizer

    if sanitizer.enabled():
        findings = sanitizer.report()
        assert not findings, (
            "concurrency sanitizer findings at teardown: %r" % findings)
