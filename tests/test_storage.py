"""Columnar storage engine tests (writer/reader/pruning/compression).

Modeled on the reference's columnar regression coverage
(src/test/regress/sql/columnar_create.sql, columnar_chunk_filtering.sql).
"""

import numpy as np
import pytest

from citus_tpu.schema import Schema
from citus_tpu.storage import ShardReader, ShardWriter, Interval
from citus_tpu.storage import compression as comp


SCHEMA = Schema.of(("a", "bigint"), ("b", "double"), ("c", "decimal(12,2)"))


def make_writer(tmp_path, codec="zstd", chunk=64, stripe=256):
    return ShardWriter(str(tmp_path / "shard"), SCHEMA, chunk_row_limit=chunk,
                       stripe_row_limit=stripe, codec=codec)


def test_roundtrip_single_stripe(tmp_path):
    w = make_writer(tmp_path)
    n = 200
    a = np.arange(n, dtype=np.int64)
    b = np.linspace(0, 1, n)
    c = (np.arange(n, dtype=np.int64) * 100 + 7)
    w.append_batch({"a": a, "b": b, "c": c})
    w.flush()
    r = ShardReader(str(tmp_path / "shard"), SCHEMA)
    assert r.row_count == n
    got_a, got_b, got_c = [], [], []
    for batch in r.scan(["a", "b", "c"]):
        got_a.append(batch.values["a"])
        got_b.append(batch.values["b"])
        got_c.append(batch.values["c"])
        assert batch.validity["a"] is None
    np.testing.assert_array_equal(np.concatenate(got_a), a)
    np.testing.assert_allclose(np.concatenate(got_b), b)
    np.testing.assert_array_equal(np.concatenate(got_c), c)


def test_multi_stripe_and_chunk_boundaries(tmp_path):
    w = make_writer(tmp_path, chunk=64, stripe=256)
    total = 1000  # 3 full stripes of 256 + final 232
    a = np.arange(total, dtype=np.int64)
    # append in awkward batch sizes
    i = 0
    for size in [1, 63, 64, 65, 255, 256, 257, 39]:
        w.append_batch({"a": a[i:i+size], "b": np.zeros(size), "c": np.zeros(size, np.int64)})
        i += size
    w.append_batch({"a": a[i:], "b": np.zeros(total - i), "c": np.zeros(total - i, np.int64)})
    w.flush()
    r = ShardReader(str(tmp_path / "shard"), SCHEMA)
    assert r.row_count == total
    assert len(r.stripe_files) == 4
    got = np.concatenate([b.values["a"] for b in r.scan(["a"])])
    np.testing.assert_array_equal(got, a)


def test_nulls_roundtrip(tmp_path):
    w = make_writer(tmp_path)
    n = 100
    a = np.arange(n, dtype=np.int64)
    valid = (a % 3) != 0
    w.append_batch({"a": a, "b": np.ones(n), "c": a * 10},
                   validity={"a": valid})
    w.flush()
    r = ShardReader(str(tmp_path / "shard"), SCHEMA)
    got_valid, got_vals = [], []
    for batch in r.scan(["a", "b"]):
        assert batch.validity["b"] is None
        v = batch.validity["a"]
        assert v is not None
        got_valid.append(v)
        got_vals.append(batch.values["a"])
    gv = np.concatenate(got_valid)
    ga = np.concatenate(got_vals)
    np.testing.assert_array_equal(gv, valid)
    # null slots are zeroed
    np.testing.assert_array_equal(ga[~gv], 0)
    np.testing.assert_array_equal(ga[gv], a[valid])


def test_chunk_pruning_skips_chunks(tmp_path):
    w = make_writer(tmp_path, chunk=64, stripe=256)
    n = 1024
    a = np.arange(n, dtype=np.int64)
    w.append_batch({"a": a, "b": np.zeros(n), "c": np.zeros(n, np.int64)})
    w.flush()
    r = ShardReader(str(tmp_path / "shard"), SCHEMA)
    sel, tot = r.chunk_counts([Interval("a", lo=900, hi=950)])
    assert tot == 16
    assert sel == 1
    rows = np.concatenate([b.values["a"] for b in r.scan(["a"], [Interval("a", lo=900, hi=950)])])
    # pruning is conservative: returns the whole admitted chunk
    assert rows.min() >= 896 and rows.max() <= 959
    # exclusive bounds prune boundary-only chunks
    sel2, _ = r.chunk_counts([Interval("a", lo=63, hi=64, lo_inclusive=False, hi_inclusive=False)])
    assert sel2 == 0


def test_all_null_chunk_pruned_for_range(tmp_path):
    w = make_writer(tmp_path, chunk=64, stripe=64)
    n = 64
    w.append_batch({"a": np.zeros(n, np.int64), "b": np.zeros(n), "c": np.zeros(n, np.int64)},
                   validity={"a": np.zeros(n, bool)})
    w.flush()
    r = ShardReader(str(tmp_path / "shard"), SCHEMA)
    sel, tot = r.chunk_counts([Interval("a", lo=-10, hi=10)])
    assert (sel, tot) == (0, 1)


@pytest.mark.parametrize("codec", ["none", "zlib", "zstd", "lz4"])
def test_codecs(codec, tmp_path):
    data = np.arange(5000, dtype=np.int64).tobytes() * 3
    c = comp.compress(data, codec, 3)
    assert comp.decompress(c, codec, len(data)) == data
    if codec != "none":
        assert len(c) < len(data)
    w = make_writer(tmp_path, codec=codec)
    a = np.arange(500, dtype=np.int64)
    w.append_batch({"a": a, "b": np.zeros(500), "c": np.zeros(500, np.int64)})
    w.flush()
    r = ShardReader(str(tmp_path / "shard"), SCHEMA)
    np.testing.assert_array_equal(np.concatenate([b.values["a"] for b in r.scan(["a"])]), a)


def test_compression_actually_shrinks(tmp_path):
    import os
    w = make_writer(tmp_path, codec="zstd", chunk=1024, stripe=8192)
    n = 8192
    # low-entropy data compresses well
    w.append_batch({"a": np.repeat(np.arange(8, dtype=np.int64), n // 8),
                    "b": np.zeros(n), "c": np.ones(n, np.int64)})
    w.flush()
    shard = tmp_path / "shard"
    size = sum(os.path.getsize(shard / f) for f in os.listdir(shard))
    raw = n * (8 + 8 + 8)
    assert size < raw / 4
