"""PREPARE / EXECUTE / DEALLOCATE — SQL-spelling prepared statements
over the text-keyed generic-plan cache (reference: prepared statements
+ Job->deferredPruning)."""

import pytest

import citus_tpu as ct
from citus_tpu.errors import CatalogError


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"))
    c.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    c.execute("SELECT create_distributed_table('t', 'k', 4)")
    c.copy_from("t", rows=[(i, i * 10) for i in range(100)])
    return c


def test_prepare_execute_select_with_params(cl):
    s = cl.session()
    s.execute("PREPARE q (bigint) AS SELECT count(*), sum(v) FROM t "
              "WHERE k < $1")
    assert s.execute("EXECUTE q (10)").rows == [(10, sum(i * 10 for i in range(10)))]
    assert s.execute("EXECUTE q (50)").rows == [(50, sum(i * 10 for i in range(50)))]
    # router form reuses the generic plan with deferred pruning
    s.execute("PREPARE pt AS SELECT v FROM t WHERE k = $1")
    h0 = cl.counters.snapshot().get("plan_cache_hits", 0)
    for key in (3, 7, 11):
        assert s.execute(f"EXECUTE pt ({key})").rows == [(key * 10,)]
    assert cl.counters.snapshot().get("plan_cache_hits", 0) >= h0 + 2


def test_prepare_execute_dml_and_errors(cl):
    s = cl.session()
    s.execute("PREPARE ins AS INSERT INTO t VALUES (1000, 1)")
    s.execute("EXECUTE ins")
    assert cl.execute("SELECT count(*) FROM t WHERE k = 1000").rows == [(1,)]
    with pytest.raises(CatalogError, match="already exists"):
        s.execute("PREPARE ins AS SELECT 1")
    with pytest.raises(CatalogError, match="does not exist"):
        s.execute("EXECUTE nope")
    s.execute("DEALLOCATE ins")
    with pytest.raises(CatalogError, match="does not exist"):
        s.execute("EXECUTE ins")
    # prepared statements are per session
    s2 = cl.session()
    s2.execute("PREPARE q2 AS SELECT 1")
    with pytest.raises(CatalogError):
        s.execute("EXECUTE q2")


def test_prepared_survive_rollback_and_deallocate_all(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("PREPARE q AS SELECT count(*) FROM t")
    s.execute("ROLLBACK")
    assert s.execute("EXECUTE q").rows == [(100,)]  # PG: not transactional
    s.execute("PREPARE r AS SELECT 1")
    s.execute("DEALLOCATE ALL")
    for name in ("q", "r"):
        with pytest.raises(CatalogError):
            s.execute(f"EXECUTE {name}")


def test_prepare_works_for_roles_and_checks_inner_privileges(cl):
    from citus_tpu.errors import SqlSyntaxError
    cl.execute("CREATE ROLE alice")
    cl.execute("GRANT SELECT ON t TO alice")
    s = cl.session()
    s.execute("PREPARE pq AS SELECT count(*) FROM t", role="alice")
    assert s.execute("EXECUTE pq", role="alice").rows == [(100,)]
    # the underlying statement's privileges still apply
    s.execute("PREPARE pd AS DELETE FROM t", role="alice")
    with pytest.raises(CatalogError, match="permission denied"):
        s.execute("EXECUTE pd", role="alice")
    # recursive/unplannable bodies rejected at parse time
    with pytest.raises(SqlSyntaxError, match="plannable"):
        s.execute("PREPARE bad AS EXECUTE bad")
    with pytest.raises(SqlSyntaxError, match="plannable"):
        s.execute("PREPARE bad2 AS BEGIN")


def test_prepared_error_aborts_transaction_block(cl):
    from citus_tpu.transaction.session import InFailedTransaction
    s = cl.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO t VALUES (2000, 1)")
    with pytest.raises(CatalogError):
        s.execute("EXECUTE nope")
    with pytest.raises(InFailedTransaction):
        s.execute("SELECT 1")
    r = s.execute("COMMIT")
    assert r.explain.get("transaction") == "rollback"
    assert cl.execute("SELECT count(*) FROM t WHERE k = 2000").rows == [(0,)]


def test_execute_not_double_counted_in_stats(cl):
    s = cl.session()
    s.execute("PREPARE sq AS SELECT count(*) FROM t")
    s.execute("EXECUTE sq")
    r = cl.execute("SELECT citus_stat_statements()")
    texts = [row[0] for row in r.rows]
    assert not any(t.startswith("EXECUTE sq") for t in texts), texts
