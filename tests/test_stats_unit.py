"""Unit tests for citus_tpu/stats.py: query normalization (quoted
identifiers, $N markers, identifier-adjacent digits), the log-scale
latency histogram behind p50/p95/p99, the O(1) LFU eviction in
QueryStats, and TenantStats' expire-at-read window.
"""


from citus_tpu.stats import (LatencyHistogram, QueryStats, TenantStats,
                             normalize_query)


# ------------------------------------------------------- normalization


def test_normalize_replaces_literals():
    assert normalize_query("SELECT * FROM t WHERE v < 100") \
        == "select * from t where v < ?"
    assert normalize_query("SELECT 1.5, 'abc' FROM t") \
        == "select ?, ? from t"


def test_normalize_keeps_identifier_adjacent_digits():
    # regression: a bare \b\d+\b pass rewrote the 1 in t1 / k_2 / x2y
    assert normalize_query("SELECT k_2 FROM t1 WHERE x2y = 3") \
        == "select k_2 from t1 where x2y = ?"


def test_normalize_keeps_quoted_identifiers_verbatim():
    # regression: digits inside double-quoted identifiers were mangled
    # ('"2024"' -> '"?"'), merging stats buckets across relations
    assert normalize_query('SELECT v FROM "2024" WHERE v = 7') \
        == 'select v from "2024" where v = ?'
    assert normalize_query('SELECT "a""b 1" FROM t') \
        == 'select "a""b 1" from t'


def test_normalize_keeps_parameter_markers():
    # regression: '$1' became '$?', erasing which parameter slot
    assert normalize_query("SELECT v FROM t WHERE k = $1 AND v > $12") \
        == "select v from t where k = $1 and v > $12"


def test_normalize_string_with_digits():
    assert normalize_query("SELECT * FROM t WHERE s = 'v 100'") \
        == "select * from t where s = ?"


# ---------------------------------------------------- latency histogram


def test_histogram_percentiles_monotone():
    h = LatencyHistogram()
    for ms in range(1, 101):  # 1..100 ms, uniform
        h.record(float(ms))
    p50, p95, p99 = (h.percentile(p) for p in (0.50, 0.95, 0.99))
    assert h.count == 100
    assert 0 < p50 <= p95 <= p99
    # log-scale buckets: estimates land in the right decade
    assert 16 <= p50 <= 128
    assert p99 <= 256


def test_histogram_empty_and_overflow():
    h = LatencyHistogram()
    assert h.percentile(0.5) == 0.0
    h.record(10 ** 9)  # beyond the last bound -> overflow bucket
    assert h.counts[-1] == 1
    assert h.percentile(0.5) >= LatencyHistogram.BOUNDS_MS[-1]


# ------------------------------------------------------- LFU eviction


def test_query_stats_percentile_columns():
    qs = QueryStats()
    for _ in range(4):
        qs.record("SELECT sum(v) FROM t", 0.010, 1, "adaptive")
    rows = qs.rows_view()
    assert len(rows) == 1
    q, executor, pkey, calls, total_ms, rows_n, p50, p95, p99 = rows[0]
    assert calls == 4 and executor == "adaptive"
    assert 0 < p50 <= p95 <= p99


def test_lfu_evicts_coldest_family():
    qs = QueryStats(max_entries=3)
    qs.record("SELECT 1 FROM a", 0.001, 1, "e")   # calls=1 (victim)
    for _ in range(3):
        qs.record("SELECT 1 FROM b", 0.001, 1, "e")  # calls=3
    for _ in range(2):
        qs.record("SELECT 1 FROM c", 0.001, 1, "e")  # calls=2
    qs.record("SELECT 1 FROM d", 0.001, 1, "e")   # evicts the coldest
    keys = {r[0] for r in qs.rows_view()}
    assert "select ? from a" not in keys
    assert {"select ? from b", "select ? from c",
            "select ? from d"} == keys


def test_lfu_tie_breaks_by_insertion_order():
    qs = QueryStats(max_entries=2)
    qs.record("SELECT 1 FROM a", 0.001, 1, "e")  # calls=1, older
    qs.record("SELECT 1 FROM b", 0.001, 1, "e")  # calls=1, newer
    qs.record("SELECT 1 FROM c", 0.001, 1, "e")  # evicts a (stalest)
    keys = {r[0] for r in qs.rows_view()}
    assert keys == {"select ? from b", "select ? from c"}


def test_lfu_hot_key_survives_heavy_churn():
    qs = QueryStats(max_entries=10)
    for _ in range(50):
        qs.record("SELECT * FROM hot", 0.001, 1, "e")
    for i in range(100):  # one-call families churn through the table
        qs.record(f"SELECT * FROM cold_{i} WHERE x = 'u'", 0.001, 1, "e")
    keys = {r[0] for r in qs.rows_view()}
    assert "select * from hot" in keys
    # internal invariant: frequency buckets account for every key
    assert sum(len(b) for b in qs._freq.values()) == len(qs._stats)


def test_lfu_min_calls_cursor_resets_on_insert():
    qs = QueryStats(max_entries=100)
    for _ in range(5):
        qs.record("SELECT * FROM hot", 0.001, 1, "e")
    assert qs._min_calls <= 5
    qs.record("SELECT * FROM newcomer", 0.001, 1, "e")
    assert qs._min_calls == 1  # new family re-opens the coldest bucket


# ------------------------------------------------------- tenant window


def test_tenant_stats_expire_at_read(monkeypatch):
    from citus_tpu.utils import clock

    ts = TenantStats()
    now = [1000.0]
    monkeypatch.setattr(clock, "_wall_clock", lambda: now[0])
    ts.record("acme", 0.010)
    ts.record("acme", 0.010)
    ts.record("globex", 0.005)
    rows = dict((k, (c, ms)) for k, c, ms in ts.rows_view())
    assert rows["acme"][0] == 2 and rows["globex"][0] == 1
    # regression: past the window with NO new record, the stale counts
    # used to show forever; rows_view must expire them
    now[0] += TenantStats.WINDOW_S + 1
    assert ts.rows_view() == []
    # a fresh record after expiry starts a clean window
    ts.record("acme", 0.002)
    rows = ts.rows_view()
    assert rows == [("acme", 1, 2.0)]
