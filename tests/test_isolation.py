"""Concurrency tests — the analog of the reference's isolation specs
(src/test/regress/spec/): concurrent operations against one cluster
must never produce wrong results or corrupt state."""

import threading

import numpy as np
import pytest

import citus_tpu as ct


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={"k": np.arange(20_000, dtype=np.int64),
                               "v": np.ones(20_000, dtype=np.int64)})
    yield cl
    cl.close()


def _run_all(workers):
    errors = []

    def wrap(fn):
        def go():
            try:
                fn()
            except Exception as e:  # pragma: no cover
                errors.append(e)
        return go
    threads = [threading.Thread(target=wrap(fn)) for fn in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors


def test_concurrent_queries_during_shard_move(db):
    cl = db
    t = cl.catalog.table("t")
    shard = t.shards[1]
    src = shard.placements[0]
    dst = 1 - src
    results = []

    def reader():
        for _ in range(30):
            r = cl.execute("SELECT count(*), sum(v) FROM t").rows
            results.append(r)

    def mover():
        from citus_tpu.operations import move_shard_placement
        move_shard_placement(cl.catalog, shard.shard_id, src, dst)

    _run_all([reader, mover])
    # every concurrent read saw a complete, consistent table
    assert all(r == [(20_000, 20_000)] for r in results)
    assert cl.catalog.table("t").shards[1].placements == [dst]


def test_concurrent_ingest_and_read(db):
    cl = db
    counts = []

    def writer():
        for i in range(5):
            cl.copy_from("t", columns={
                "k": np.arange(i * 100, (i + 1) * 100, dtype=np.int64) + 10**6,
                "v": np.full(100, 2, dtype=np.int64)})

    def reader():
        for _ in range(25):
            n = cl.execute("SELECT count(*) FROM t").rows[0][0]
            counts.append(n)

    _run_all([writer, reader])
    # reads only ever observe committed batch boundaries
    assert all((n - 20_000) % 100 == 0 for n in counts)
    assert cl.execute("SELECT count(*) FROM t").rows == [(20_500,)]


def test_concurrent_rebalance_and_aggregate(db):
    cl = db
    cl.execute("SELECT citus_add_node('w', 1)")
    sums = []

    def reader():
        for _ in range(20):
            sums.append(cl.execute("SELECT sum(v) FROM t").rows[0][0])

    def rebalancer():
        cl.execute("SELECT rebalance_table_shards('t')")

    _run_all([reader, rebalancer])
    assert all(s == 20_000 for s in sums)


def test_concurrent_deletes_disjoint_predicates(db):
    cl = db

    def d1():
        cl.execute("DELETE FROM t WHERE k < 5000")

    def d2():
        cl.execute("DELETE FROM t WHERE k >= 15000")

    _run_all([d1, d2])
    assert cl.execute("SELECT count(*) FROM t").rows == [(10_000,)]
