"""Concurrency tests — the analog of the reference's isolation specs
(src/test/regress/spec/): concurrent operations against one cluster
must never produce wrong results or corrupt state."""

import threading

import numpy as np
import pytest

import citus_tpu as ct


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={"k": np.arange(20_000, dtype=np.int64),
                               "v": np.ones(20_000, dtype=np.int64)})
    yield cl
    cl.close()


def _run_all(workers):
    errors = []

    def wrap(fn):
        def go():
            try:
                fn()
            except Exception as e:  # pragma: no cover
                errors.append(e)
        return go
    threads = [threading.Thread(target=wrap(fn)) for fn in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors


def test_concurrent_queries_during_shard_move(db):
    cl = db
    t = cl.catalog.table("t")
    shard = t.shards[1]
    src = shard.placements[0]
    dst = 1 - src
    results = []

    def reader():
        for _ in range(30):
            r = cl.execute("SELECT count(*), sum(v) FROM t").rows
            results.append(r)

    def mover():
        from citus_tpu.operations import move_shard_placement
        move_shard_placement(cl.catalog, shard.shard_id, src, dst)

    _run_all([reader, mover])
    # every concurrent read saw a complete, consistent table
    assert all(r == [(20_000, 20_000)] for r in results)
    assert cl.catalog.table("t").shards[1].placements == [dst]


def test_concurrent_ingest_and_read(db):
    cl = db
    counts = []

    def writer():
        for i in range(5):
            cl.copy_from("t", columns={
                "k": np.arange(i * 100, (i + 1) * 100, dtype=np.int64) + 10**6,
                "v": np.full(100, 2, dtype=np.int64)})

    def reader():
        for _ in range(25):
            n = cl.execute("SELECT count(*) FROM t").rows[0][0]
            counts.append(n)

    _run_all([writer, reader])
    # reads only ever observe committed batch boundaries
    assert all((n - 20_000) % 100 == 0 for n in counts)
    assert cl.execute("SELECT count(*) FROM t").rows == [(20_500,)]


def test_concurrent_rebalance_and_aggregate(db):
    cl = db
    cl.execute("SELECT citus_add_node('w', 1)")
    sums = []

    def reader():
        for _ in range(20):
            sums.append(cl.execute("SELECT sum(v) FROM t").rows[0][0])

    def rebalancer():
        cl.execute("SELECT rebalance_table_shards('t')")

    _run_all([reader, rebalancer])
    assert all(s == 20_000 for s in sums)


def test_concurrent_deletes_disjoint_predicates(db):
    cl = db

    def d1():
        cl.execute("DELETE FROM t WHERE k < 5000")

    def d2():
        cl.execute("DELETE FROM t WHERE k >= 15000")

    _run_all([d1, d2])
    assert cl.execute("SELECT count(*) FROM t").rows == [(10_000,)]


def test_writer_vs_shard_move_no_lost_writes(db):
    """The round-1 race: a stripe committed after the mover's catch-up
    pass but before the catalog flip landed only on the source placement
    and was silently dropped.  With the colocation-group write lock the
    mover blocks writers across catch-up + flip, so every committed row
    must survive the move."""
    cl = db
    t = cl.catalog.table("t")
    shard = t.shards[1]
    src = shard.placements[0]
    dst = 1 - src
    stop = threading.Event()
    written = [0]

    def writer():
        i = 0
        while not stop.is_set() and i < 200:
            cl.copy_from("t", columns={
                "k": np.arange(i * 50, (i + 1) * 50, dtype=np.int64) + 10**7,
                "v": np.full(50, 3, dtype=np.int64)})
            written[0] += 50
            i += 1

    def mover():
        from citus_tpu.operations import move_shard_placement
        for _ in range(3):  # several windows to hit the race
            move_shard_placement(cl.catalog, shard.shard_id, src, dst,
                                 lock_manager=cl.locks)
            move_shard_placement(cl.catalog, shard.shard_id, dst, src,
                                 lock_manager=cl.locks)
        stop.set()

    _run_all([writer, mover])
    # every committed write survived all six moves
    assert cl.execute("SELECT count(*) FROM t").rows == [(20_000 + written[0],)]
    assert written[0] > 0


def test_writer_vs_shard_split_no_lost_writes(db):
    cl = db
    t = cl.catalog.table("t")
    shard = t.shards[0]
    mid = (shard.hash_min + shard.hash_max) // 2
    written = [0]
    done = threading.Event()

    def writer():
        i = 0
        while not done.is_set() and i < 100:
            cl.copy_from("t", columns={
                "k": np.arange(i * 50, (i + 1) * 50, dtype=np.int64) + 2 * 10**7,
                "v": np.full(50, 4, dtype=np.int64)})
            written[0] += 50
            i += 1

    def splitter():
        from citus_tpu.operations.shard_split import split_shard
        split_shard(cl.catalog, shard.shard_id, [mid], lock_manager=cl.locks)
        done.set()

    _run_all([writer, splitter])
    assert cl.execute("SELECT count(*) FROM t").rows == [(20_000 + written[0],)]
    assert cl.catalog.table("t").shard_count == 5


def test_move_during_update_serializes(db):
    """An UPDATE holding the exclusive group lock excludes the mover's
    flip window entirely; both complete and no rows duplicate or drop."""
    cl = db
    t = cl.catalog.table("t")
    shard = t.shards[2]
    src = shard.placements[0]
    dst = 1 - src

    def updater():
        for _ in range(5):
            cl.execute("UPDATE t SET v = v + 1 WHERE k % 7 = 0")

    def mover():
        from citus_tpu.operations import move_shard_placement
        move_shard_placement(cl.catalog, shard.shard_id, src, dst,
                             lock_manager=cl.locks)

    _run_all([updater, mover])
    expected_bumped = len([k for k in range(20_000) if k % 7 == 0])
    r = cl.execute("SELECT count(*), sum(v) FROM t").rows
    assert r == [(20_000, 20_000 + 5 * expected_bumped)]


def test_concurrent_vacuum_and_writer(db):
    cl = db
    done = threading.Event()
    wrote = [0]

    def writer():
        i = 0
        while not done.is_set() and i < 60:
            cl.copy_from("t", columns={
                "k": np.arange(i * 20, (i + 1) * 20, dtype=np.int64) + 3 * 10**7,
                "v": np.full(20, 9, dtype=np.int64)})
            wrote[0] += 20
            i += 1

    def vacuumer():
        cl.execute("DELETE FROM t WHERE k < 2000")
        for _ in range(3):
            cl.execute("VACUUM t")
        done.set()

    _run_all([writer, vacuumer])
    assert cl.execute("SELECT count(*) FROM t").rows == \
        [(20_000 - 2000 + wrote[0],)]


def test_concurrent_merges_serialize(db):
    cl = db
    cl.execute("CREATE TABLE delta (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('delta', 'k', 4)")
    cl.copy_from("delta", columns={"k": np.arange(1000, dtype=np.int64),
                                   "v": np.full(1000, 5, dtype=np.int64)})

    def merger():
        cl.execute("""MERGE INTO t USING delta d ON t.k = d.k
            WHEN MATCHED THEN UPDATE SET v = t.v + 1""")

    ts = [threading.Thread(target=merger) for _ in range(3)]
    for th in ts:
        th.start()
    for th in ts:
        th.join(60)
    # each merge bumped the 1000 matched rows exactly once
    assert cl.execute("SELECT sum(v) FROM t").rows == [(20_000 + 3 * 1000,)]
    assert cl.execute("SELECT count(*) FROM t").rows == [(20_000,)]


def test_concurrent_split_and_readers(db):
    cl = db
    t = cl.catalog.table("t")
    shard = t.shards[3]
    mid = (shard.hash_min + shard.hash_max) // 2
    results = []

    def reader():
        for _ in range(25):
            results.append(cl.execute("SELECT count(*), sum(v) FROM t").rows)

    def splitter():
        from citus_tpu.operations.shard_split import split_shard
        split_shard(cl.catalog, shard.shard_id, [mid], lock_manager=cl.locks)

    _run_all([reader, splitter])
    assert all(r == [(20_000, 20_000)] for r in results)
    assert cl.catalog.table("t").shard_count == 5


def test_concurrent_ddl_and_select_other_table(db):
    """DDL on one table never disturbs readers of another."""
    cl = db
    errs = []

    def ddl():
        for i in range(10):
            cl.execute(f"CREATE TABLE tmp_{i} (a bigint)")
            cl.execute(f"INSERT INTO tmp_{i} VALUES (1)")
            cl.execute(f"DROP TABLE tmp_{i}")

    def reader():
        for _ in range(30):
            if cl.execute("SELECT count(*) FROM t").rows != [(20_000,)]:
                errs.append("bad read")  # pragma: no cover

    _run_all([ddl, reader])
    assert not errs


def test_concurrent_truncate_and_read(db):
    cl = db
    counts = []

    def reader():
        for _ in range(20):
            counts.append(cl.execute("SELECT count(*) FROM t").rows[0][0])

    def truncator():
        cl.execute("TRUNCATE t")

    _run_all([reader, truncator])
    # reads see either the full table or the empty one, nothing between
    assert all(c in (0, 20_000) for c in counts), counts
    assert cl.execute("SELECT count(*) FROM t").rows == [(0,)]


def test_concurrent_update_vs_delete_overlap(db):
    """Overlapping UPDATE and DELETE serialize: every row is either
    bumped then deleted or deleted first — never half-applied."""
    cl = db

    def updater():
        cl.execute("UPDATE t SET v = v + 100 WHERE k < 10000")

    def deleter():
        cl.execute("DELETE FROM t WHERE k < 10000")

    _run_all([updater, deleter])
    assert cl.execute("SELECT count(*) FROM t").rows == [(10_000,)]
    assert cl.execute("SELECT sum(v) FROM t").rows == [(10_000,)]


def test_concurrent_truncate_and_join_read(db):
    """The flip latch also covers join scans (they load frames outside
    execute_select)."""
    cl = db
    cl.execute("CREATE TABLE dims_tr (k bigint, name text)")
    cl.copy_from("dims_tr", rows=[(i, f"d{i % 5}") for i in range(100)])
    counts = []

    def reader():
        for _ in range(10):
            r = cl.execute("SELECT count(*) FROM t JOIN dims_tr dm "
                           "ON t.k = dm.k")
            counts.append(r.rows[0][0])

    def truncator():
        cl.execute("TRUNCATE t")

    _run_all([reader, truncator])
    # t.k is 0..19999 (unique), dims 0..99: the join matches exactly 100
    # rows pre-truncate and 0 after — anything else is a torn read
    assert all(c in (0, 100) for c in counts), counts
    assert cl.execute("SELECT count(*) FROM t").rows == [(0,)]


def test_concurrent_reshard_and_readers(db):
    """alter_distributed_table's shard-map swap + re-ingest is one flip
    to readers (same guarantee as the split-vs-reader case)."""
    cl = db
    results = []

    def reader():
        for _ in range(25):
            results.append(cl.execute("SELECT count(*), sum(v) FROM t").rows)

    def reshard():
        from citus_tpu.operations.alter_table import alter_distributed_table
        alter_distributed_table(cl.catalog, "t", shard_count=7)

    _run_all([reader, reshard])
    assert all(r == [(20_000, 20_000)] for r in results)
    assert cl.catalog.table("t").shard_count == 7


def test_concurrent_move_and_readers(db):
    """Shard moves flip placements (same bytes, new node): readers must
    never tear regardless of which placement they resolve."""
    cl = db
    t = cl.catalog.table("t")
    results = []

    def reader():
        for _ in range(25):
            results.append(cl.execute("SELECT count(*), sum(v) FROM t").rows)

    def mover():
        from citus_tpu.operations import move_shard_placement
        for s in list(cl.catalog.table("t").shards)[:2]:
            src = s.placements[0]
            dst = 1 - src if src in (0, 1) else 0
            move_shard_placement(cl.catalog, s.shard_id, src, dst,
                                 lock_manager=cl.locks)

    _run_all([reader, mover])
    assert all(r == [(20_000, 20_000)] for r in results)


def test_concurrent_vacuum_and_update(db):
    """VACUUM's placement rewrite must serialize with UPDATE through
    the write lock; readers stay consistent throughout."""
    cl = db
    results, errs = [], []

    def reader():
        for _ in range(20):
            results.append(
                cl.execute("SELECT count(*) FROM t").rows[0][0])

    def updater():
        for i in range(4):
            cl.execute(f"UPDATE t SET v = {i + 2} WHERE k % 10 = 0")

    def vacuumer():
        for _ in range(3):
            cl.execute("VACUUM t")

    _run_all([reader, updater, vacuumer])
    assert all(c == 20_000 for c in results), results[:5]


def test_device_cache_key_includes_flip_generation(db):
    """The HBM cache must key on the snapshot flip generation, not just
    table.version: writers commit the version bump BEFORE flipping
    stripes live, so a scan in that window (or a torn scan whose put
    survives the seqlock retry) would otherwise poison the cache under
    the new version and serve stale counts forever after."""
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    from citus_tpu.transaction.snapshot import flip_generation

    cl = db
    GLOBAL_CACHE.clear()
    assert cl.execute("SELECT count(*) FROM t").rows == [(20_000,)]
    h0 = GLOBAL_CACHE.hits
    assert cl.execute("SELECT count(*) FROM t").rows == [(20_000,)]
    assert GLOBAL_CACHE.hits == h0 + 1  # quiescent repeat: same key
    # a completed flip bumps the generation; the old entry must be
    # unreachable even though table.version did not change
    with flip_generation(cl.catalog.data_dir, cl.catalog.table("t")):
        pass
    h1 = GLOBAL_CACHE.hits
    assert cl.execute("SELECT count(*) FROM t").rows == [(20_000,)]
    assert GLOBAL_CACHE.hits == h1  # new generation: fresh entry
