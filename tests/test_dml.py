"""DELETE / UPDATE / TRUNCATE / VACUUM vs sqlite oracle."""

import os
import sqlite3

import numpy as np
import pytest

import citus_tpu as ct


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, s text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rows = [(i, i % 10, ["a", "b", "c"][i % 3]) for i in range(2000)]
    cl.copy_from("t", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, v INTEGER, s TEXT)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)", rows)
    return cl, sq


def both(db, sql):
    cl, sq = db
    cl.execute(sql)
    sq.execute(sql)


def check(db, sql):
    cl, sq = db
    ours = sorted(cl.execute(sql).rows, key=repr)
    theirs = sorted(sq.execute(sql).fetchall(), key=repr)
    assert ours == theirs


def test_delete_where(db):
    cl, sq = db
    r = cl.execute("DELETE FROM t WHERE v < 3")
    sq.execute("DELETE FROM t WHERE v < 3")
    assert r.explain["deleted"] == 600
    check(db, "SELECT count(*), sum(v) FROM t")
    check(db, "SELECT v, count(*) FROM t GROUP BY v")
    # delete more from the already-deleted set: no-op
    r2 = cl.execute("DELETE FROM t WHERE v < 3")
    assert r2.explain["deleted"] == 0


def test_delete_router(db):
    cl, sq = db
    both(db, "DELETE FROM t WHERE k = 77")
    check(db, "SELECT count(*) FROM t")
    check(db, "SELECT count(*) FROM t WHERE k = 77")


def test_delete_on_text_predicate(db):
    both(db, "DELETE FROM t WHERE s = 'b'")
    check(db, "SELECT s, count(*) FROM t GROUP BY s")


def test_update_simple(db):
    cl, sq = db
    r = cl.execute("UPDATE t SET v = v + 100 WHERE v = 5")
    sq.execute("UPDATE t SET v = v + 100 WHERE v = 5")
    assert r.explain["updated"] == 200
    check(db, "SELECT v, count(*) FROM t GROUP BY v")
    check(db, "SELECT sum(v) FROM t")


def test_update_text_and_multiple_columns(db):
    both(db, "UPDATE t SET s = 'z', v = 0 WHERE s = 'a' AND v > 6")
    check(db, "SELECT s, v, count(*) FROM t GROUP BY s, v")


def test_update_distribution_column_moves_rows(db):
    cl, sq = db
    both(db, "UPDATE t SET k = k + 1000000 WHERE k < 10")
    check(db, "SELECT count(*) FROM t WHERE k >= 1000000")
    # the moved rows are findable via router queries on the new key
    assert cl.execute("SELECT count(*) FROM t WHERE k = 1000003").rows == [(1,)]


def test_truncate(db):
    cl, sq = db
    cl.execute("TRUNCATE t")
    sq.execute("DELETE FROM t")  # sqlite has no TRUNCATE
    check(db, "SELECT count(*) FROM t")
    # reinsert works after truncate
    cl.execute("INSERT INTO t VALUES (1, 2, 'x')")
    assert cl.execute("SELECT count(*) FROM t").rows == [(1,)]


def test_vacuum_reclaims_deleted_rows(db):
    cl, _ = db
    cl.execute("DELETE FROM t WHERE v < 5")
    before_size = cl.execute("SELECT citus_table_size('t')").rows[0][0]
    counts_before = sorted(cl.execute("SELECT v, count(*) FROM t GROUP BY v").rows)
    r = cl.execute("VACUUM t")
    assert r.explain["rows_reclaimed"] == 1000
    cl.execute("SELECT citus_cleanup_orphaned_resources()")
    after_size = cl.execute("SELECT citus_table_size('t')").rows[0][0]
    assert after_size < before_size
    assert sorted(cl.execute("SELECT v, count(*) FROM t GROUP BY v").rows) == counts_before
    # no deletion bitmaps remain
    from citus_tpu.storage.deletes import load_deletes
    for shard in cl.catalog.table("t").shards:
        for node in shard.placements:
            d = cl.catalog.shard_dir("t", shard.shard_id, node)
            if os.path.isdir(d):
                assert load_deletes(d) == {}


def test_delete_survives_restart(db, tmp_path):
    cl, _ = db
    cl.execute("DELETE FROM t WHERE v >= 5")
    expect = cl.execute("SELECT count(*) FROM t").rows
    cl2 = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    assert cl2.execute("SELECT count(*) FROM t").rows == expect


def test_aggregates_respect_deletes_on_all_paths(db):
    cl, sq = db
    both(db, "DELETE FROM t WHERE v = 7")
    # direct groupby, scalar agg, hash path, projection, join
    check(db, "SELECT v, count(*) FROM t GROUP BY v")
    check(db, "SELECT count(*) FROM t")
    check(db, "SELECT k, v FROM t WHERE k < 20")
    ours = cl.execute("SELECT count(*) FROM t a JOIN t b ON a.k = b.k").rows
    theirs = sq.execute("SELECT count(*) FROM t a JOIN t b ON a.k = b.k").fetchall()
    assert ours == list(theirs)


def test_insert_select_array_path(db, tmp_path):
    cl, sq = db
    cl.execute("CREATE TABLE t2 (k bigint NOT NULL, v bigint, s text)")
    cl.execute("SELECT create_distributed_table('t2', 'k', 4)")
    sq.execute("CREATE TABLE t2 (k INTEGER, v INTEGER, s TEXT)")
    r = cl.execute("INSERT INTO t2 SELECT k, v, s FROM t WHERE v > 4")
    sq.execute("INSERT INTO t2 SELECT k, v, s FROM t WHERE v > 4")
    assert r.explain["inserted"] == 1000
    check((cl, sq), "SELECT count(*), sum(v) FROM t2")
    check((cl, sq), "SELECT s, count(*) FROM t2 GROUP BY s")


def test_insert_select_with_expressions(db):
    cl, sq = db
    cl.execute("CREATE TABLE t3 (k bigint NOT NULL, v2 bigint)")
    cl.execute("SELECT create_distributed_table('t3', 'k', 4)")
    sq.execute("CREATE TABLE t3 (k INTEGER, v2 INTEGER)")
    cl.execute("INSERT INTO t3 SELECT k, v * 2 + 1 FROM t WHERE k < 500")
    sq.execute("INSERT INTO t3 SELECT k, v * 2 + 1 FROM t WHERE k < 500")
    check((cl, sq), "SELECT count(*), sum(v2) FROM t3")


def test_insert_select_aggregate_falls_back(db):
    cl, sq = db
    cl.execute("CREATE TABLE agg (v bigint, c bigint)")
    sq.execute("CREATE TABLE agg (v INTEGER, c INTEGER)")
    cl.execute("INSERT INTO agg SELECT v, count(*) FROM t GROUP BY v")
    sq.execute("INSERT INTO agg SELECT v, count(*) FROM t GROUP BY v")
    check((cl, sq), "SELECT count(*), sum(c) FROM agg")


def test_alter_table_add_drop_rename(db):
    cl, sq = db
    cl.execute("ALTER TABLE t ADD COLUMN extra decimal(8,2)")
    sq.execute("ALTER TABLE t ADD COLUMN extra REAL")
    # existing rows read NULL for the new column
    check(db, "SELECT count(extra) FROM t")
    cl.execute("INSERT INTO t VALUES (9999, 1, 'a', 3.50)")
    sq.execute("INSERT INTO t VALUES (9999, 1, 'a', 3.5)")
    check(db, "SELECT count(extra), sum(extra) FROM t")
    # aggregate over mixed old/new stripes
    check(db, "SELECT s, count(extra) FROM t GROUP BY s")
    # rename + query under the new name
    cl.execute("ALTER TABLE t RENAME COLUMN extra TO bonus")
    sq.execute("ALTER TABLE t RENAME COLUMN extra TO bonus")
    check(db, "SELECT count(bonus) FROM t")
    # drop
    cl.execute("ALTER TABLE t DROP COLUMN bonus")
    import sqlite3 as _sq3
    if _sq3.sqlite_version_info >= (3, 35):
        sq.execute("ALTER TABLE t DROP COLUMN bonus")
    else:  # old sqlite: emulate via rebuild
        sq.execute("CREATE TABLE t_new AS SELECT k, v, s FROM t")
        sq.execute("DROP TABLE t")
        sq.execute("ALTER TABLE t_new RENAME TO t")
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError):
        cl.execute("SELECT bonus FROM t")
    # guard: cannot drop distribution column
    from citus_tpu.errors import CatalogError
    with pytest.raises(CatalogError):
        cl.execute("ALTER TABLE t DROP COLUMN k")
