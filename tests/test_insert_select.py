"""INSERT..SELECT strategy ladder — VERDICT round-2 item #4.

Reference: insert_select_planner.c picks colocated-pushdown /
repartition / pull-to-coordinator (README:1187-1238, ~100M / ~10M / ~1M
rows/s).  Here: colocated writes source-shard batches straight to the
same-index target shard (no hash, no routing); repartition streams
arrays through the hash-routing ingest; pull materializes rows."""

import time

import numpy as np
import pytest

import citus_tpu as ct


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE src (k bigint NOT NULL, v bigint, s text)")
    cl.execute("SELECT create_distributed_table('src', 'k', 4)")
    cl.copy_from("src", columns={
        "k": np.arange(10_000, dtype=np.int64),
        "v": np.arange(10_000, dtype=np.int64) * 2,
        "s": [f"w{i % 11}" for i in range(10_000)]})
    yield cl
    cl.close()


def test_colocated_pushdown(db):
    db.execute("CREATE TABLE dst (k bigint NOT NULL, v bigint, s text)")
    db.execute("SELECT create_distributed_table('dst', 'k', 4, 'src')")
    r = db.execute("INSERT INTO dst SELECT k, v, s FROM src WHERE v < 10000")
    assert r.explain["strategy"] == "insert_select:colocated"
    assert r.explain["inserted"] == 5000
    assert db.execute("SELECT count(*), sum(v) FROM dst").rows == \
        db.execute("SELECT count(*), sum(v) FROM src WHERE v < 10000").rows
    # rows landed on the correct shards: per-shard counts match the source
    a = db.execute("SELECT k FROM dst ORDER BY k").rows
    b = db.execute("SELECT k FROM src WHERE v < 10000 ORDER BY k").rows
    assert a == b
    # and text round-trips through the shared-dictionary space
    assert db.execute("SELECT count(*) FROM dst WHERE s = 'w3'").rows == \
        db.execute("SELECT count(*) FROM src WHERE s = 'w3' AND v < 10000").rows


def test_repartition_when_dist_key_changes(db):
    """Target distributed on a different column -> re-hash required."""
    db.execute("CREATE TABLE byv (k bigint, v bigint NOT NULL)")
    db.execute("SELECT create_distributed_table('byv', 'v', 4)")
    r = db.execute("INSERT INTO byv SELECT k, v FROM src")
    assert r.explain["strategy"] == "insert_select:repartition"
    assert db.execute("SELECT count(*), sum(k) FROM byv").rows == \
        db.execute("SELECT count(*), sum(k) FROM src").rows


def test_repartition_when_expression_feeds_dist_col(db):
    db.execute("CREATE TABLE dst2 (k bigint NOT NULL, v bigint, s text)")
    db.execute("SELECT create_distributed_table('dst2', 'k', 4, 'src')")
    r = db.execute("INSERT INTO dst2 SELECT k + 1, v, s FROM src")
    assert r.explain["strategy"] == "insert_select:repartition"
    assert db.execute("SELECT sum(k) FROM dst2").rows[0][0] == \
        db.execute("SELECT sum(k) FROM src").rows[0][0] + 10_000


def test_pull_fallback_for_aggregate_select(db):
    db.execute("CREATE TABLE agg (k bigint NOT NULL, c bigint)")
    db.execute("SELECT create_distributed_table('agg', 'k', 4)")
    r = db.execute(
        "INSERT INTO agg SELECT v % 10, count(*) FROM src GROUP BY v % 10")
    assert r.explain["strategy"] == "insert_select:pull"
    assert db.execute("SELECT count(*) FROM agg").rows == [(5,)]  # v even: 5 residues


def test_colocated_beats_pull_wallclock(db):
    """The ladder exists for throughput: colocated must beat row
    materialization (best-of-3 timings to absorb CI noise; the measured
    gap on quiet hardware is >10x)."""
    def timed(sql, expect):
        best = float("inf")
        for i in range(3):
            t0 = time.perf_counter()
            r = db.execute(sql.format(i=i))
            best = min(best, time.perf_counter() - t0)
            assert r.explain["strategy"] == expect
        return best

    for i in range(3):
        db.execute(f"CREATE TABLE fast{i} (k bigint NOT NULL, v bigint, s text)")
        db.execute(f"SELECT create_distributed_table('fast{i}', 'k', 4, 'src')")
        db.execute(f"CREATE TABLE slow{i} (k bigint NOT NULL, v bigint, s text)")
        db.execute(f"SELECT create_distributed_table('slow{i}', 'k', 4, 'src')")
    dt_colo = timed("INSERT INTO fast{i} SELECT k, v, s FROM src",
                    "insert_select:colocated")
    # ORDER BY forces ineligibility for the arrays path -> pull
    dt_pull = timed("INSERT INTO slow{i} SELECT k, v, s FROM src ORDER BY k",
                    "insert_select:pull")
    assert db.execute("SELECT sum(v) FROM slow0").rows == \
        db.execute("SELECT sum(v) FROM fast0").rows
    assert dt_colo < dt_pull, (dt_colo, dt_pull)
