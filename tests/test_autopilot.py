"""Autopilot (services/autopilot.py): the flight-recorder→rebalancer
loop.  Hysteresis, observe/on modes, exactly-once across crashes via
the operation registry, and the decision log's evidence trail."""

import json
import subprocess

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import CatalogError
from citus_tpu.executor.executor import GLOBAL_COUNTERS
from citus_tpu.observability.load_attribution import GLOBAL_ATTRIBUTION
from citus_tpu.operations.cleaner import operations_view, register_operation


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    c.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    c.execute("SELECT create_distributed_table('t', 'k', 4)")
    n = 8000
    c.copy_from("t", columns={"k": np.arange(n, dtype=np.int64),
                              "v": np.arange(n, dtype=np.int64)})
    GLOBAL_COUNTERS.reset()
    yield c
    c.close()


def _heat_node0(cl, ms=5000.0):
    """Book a hot-shard storm: all device time lands on node 0's
    placements, so by_observed_load plans a move 0 -> 1."""
    for s in cl.catalog.table("t").shards:
        node = s.placements[0]
        GLOBAL_ATTRIBUTION.book("t", s.shard_id, node, "hot" if node == 0
                                else "*",
                                device_ms=ms if node == 0 else 1.0,
                                queries=1)


def _placements(cl):
    return [tuple(s.placements) for s in cl.catalog.table("t").shards]


def test_guc_round_trip_and_default_off(cl):
    assert cl.execute("SHOW citus.autopilot").rows == [("off",)]
    cl.execute("SET citus.autopilot = observe")
    assert cl.execute("SHOW citus.autopilot").rows == [("observe",)]
    cl.execute("SET citus.autopilot = on")
    assert cl.settings.autopilot.mode == "on"
    cl.execute("SET citus.autopilot = off")
    with pytest.raises(CatalogError):
        cl.execute("SET citus.autopilot = maybe")
    cl.execute("SET citus.autopilot_sustain_ticks = 5")
    assert cl.settings.autopilot.sustain_ticks == 5
    cl.execute("SET citus.autopilot_cooldown_s = 120")
    assert cl.settings.autopilot.cooldown_s == 120.0


def test_off_mode_is_inert(cl):
    _heat_node0(cl)
    cl.autopilot.duty()
    assert GLOBAL_COUNTERS.snapshot()["autopilot_ticks"] == 0
    assert cl.autopilot.log_rows() == []


def test_observe_mode_logs_but_never_moves(cl):
    """Observe mode: the decision (with evidence) lands in the log and
    counters; zero moves, counter- AND registry-asserted."""
    cl.execute("SET citus.autopilot = observe")
    cl.execute("SET citus.autopilot_sustain_ticks = 2")
    _heat_node0(cl)
    before = _placements(cl)
    cl.autopilot.duty()   # sustain 1/2 -> declined
    cl.autopilot.duty()   # sustained -> observed
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["autopilot_ticks"] == 2
    assert snap["autopilot_actions_observed"] == 1
    assert snap["autopilot_actions_declined"] == 1
    assert snap["autopilot_actions_executed"] == 0
    assert _placements(cl) == before
    assert operations_view(cl.catalog) == {}
    rows = cl.autopilot.log_rows()
    assert rows[0][2] == "observed" and rows[0][3] == "move"
    ev = json.loads(rows[0][10])
    assert ev["mode"] == "observe" and ev["sustain"] == 2
    assert "health" in ev and "step" in ev
    # SQL surface fans the ring in with node attribution
    r = cl.execute("SELECT citus_autopilot_log()")
    assert r.rowcount == 2
    assert r.columns[0] == "node" and "evidence" in r.columns


def test_hysteresis_requires_consecutive_recurrence(cl):
    cl.execute("SET citus.autopilot = observe")
    cl.execute("SET citus.autopilot_sustain_ticks = 3")
    _heat_node0(cl)
    cl.autopilot.duty()
    cl.autopilot.duty()
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["autopilot_actions_observed"] == 0
    assert snap["autopilot_actions_declined"] == 2
    reasons = [row[9] for row in cl.autopilot.log_rows()]
    assert any("sustaining" in r for r in reasons)


def test_on_mode_executes_exactly_one_move(cl):
    """The e2e loop: hot-shard storm -> sustained decision -> ONE
    registry-bracketed move; the cooldown then holds further actions,
    and queries keep answering through and after the move."""
    n = 8000
    expect = [(n, n * (n - 1) // 2)]
    cl.execute("SET citus.autopilot = on")
    cl.execute("SET citus.autopilot_sustain_ticks = 2")
    cl.execute("SET citus.autopilot_cooldown_s = 3600")
    _heat_node0(cl)
    before = _placements(cl)
    cl.autopilot.duty()
    assert _placements(cl) == before   # hysteresis: no first-tick move
    cl.autopilot.duty()
    after = _placements(cl)
    assert after != before
    moved = [i for i, (b, a) in enumerate(zip(before, after)) if b != a]
    assert len(moved) == 1             # exactly one placement moved
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["autopilot_actions_executed"] == 1
    assert operations_view(cl.catalog) == {}   # bracket retired
    assert cl.execute("SELECT count(*), sum(v) FROM t").rows == expect
    # the action is a typed health event while the cooldown holds
    assert cl.flight_recorder.active_counts().get("autopilot_action") == 1
    # further storms decline on cooldown: still exactly one move
    _heat_node0(cl)
    cl.autopilot.duty()
    cl.autopilot.duty()
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["autopilot_actions_executed"] == 1
    assert _placements(cl) == after
    rows = cl.autopilot.log_rows()
    assert rows[0][2] == "declined" and "cooldown" in rows[0][9]
    assert any(row[2] == "executed" for row in rows)


def test_crashed_autopilot_is_adopted_not_repeated(cl):
    """A dead coordinator's in-flight autopilot row (SIGKILL between
    decision and completion) is adopted: the row retires, its cooldown
    is inherited, and NO second move happens — exactly-once."""
    p = subprocess.Popen(["sleep", "0"])
    p.wait()   # reaped: the pid is genuinely dead
    register_operation(cl.catalog, 12345, kind="autopilot", pid=p.pid)
    cl.execute("SET citus.autopilot = on")
    cl.execute("SET citus.autopilot_sustain_ticks = 1")
    cl.execute("SET citus.autopilot_cooldown_s = 3600")
    _heat_node0(cl)
    before = _placements(cl)
    cl.autopilot.duty()
    assert _placements(cl) == before
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["autopilot_actions_executed"] == 0
    assert operations_view(cl.catalog) == {}   # adopted row retired
    rows = cl.autopilot.log_rows()
    assert rows[0][2] == "declined" and "adopted" in rows[0][9]
    # the inherited cooldown persists on disk across a restart
    from citus_tpu.services.autopilot import Autopilot
    reborn = Autopilot(cl)
    assert float(reborn._state["last_action_ts"]) > 0.0
    cl.autopilot.duty()
    assert snap["autopilot_actions_executed"] == 0
    assert _placements(cl) == before


def test_live_autopilot_row_blocks_concurrent_action(cl):
    """max-concurrent-ops = 1: a LIVE autopilot row (another
    coordinator mid-move) declines this tick without retiring it."""
    import os
    register_operation(cl.catalog, 777, kind="autopilot", pid=os.getpid())
    cl.execute("SET citus.autopilot = on")
    cl.execute("SET citus.autopilot_sustain_ticks = 1")
    _heat_node0(cl)
    before = _placements(cl)
    cl.autopilot.duty()
    assert _placements(cl) == before
    assert "777" in operations_view(cl.catalog)   # NOT adopted
    rows = cl.autopilot.log_rows()
    assert rows[0][2] == "declined" and "in flight" in rows[0][9]


def test_no_plan_with_health_event_logs_declined(cl):
    """A health trigger with nothing actionable is itself an audited
    decision (the 'we looked and held still' record)."""
    cl.execute("SET citus.autopilot = observe")
    cl.flight_recorder.emit_event("p99_regression", "query_p99_ms",
                                  100.0, 10.0, "test")
    cl.autopilot.duty()   # balanced cluster: no steps
    rows = cl.autopilot.log_rows()
    assert rows and rows[0][2] == "declined"
    assert "no actionable plan" in rows[0][9]
    ev = json.loads(rows[0][10])
    assert ev["health"].get("p99_regression") == 1


def test_deadlock_duty_outranks_autopilot_in_a_tick(cl):
    """The deadlock detector's scheduling priority: within one
    maintenance tick it runs before every priority-0 duty (autopilot,
    cleanup), so victim selection never waits out a slow housekeeping
    pass — the scheduling fix for the two-process deadlock flake."""
    d = cl.maintenance
    names = [duty.name for duty in d._ordered()]
    assert names[0] == "deadlock_detection"
    assert "autopilot" in names
    assert names.index("deadlock_detection") < names.index("autopilot")
    ran = []
    d.register("probe_low", lambda: ran.append("low"), 0.0)
    # priority is honored over registration order, not just sorted once
    assert [x.name for x in d._ordered()][0] == "deadlock_detection"
