"""Schema-based sharding (tenant schemas).

Reference: citus.enable_schema_based_sharding +
commands/schema_based_sharding.c — every distributed schema is one
tenant: its tables form a single colocated shard group on one node and
move together."""

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import CatalogError


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=3)
    yield cl
    cl.close()


def test_tenant_schema_lifecycle(db):
    cl = db
    cl.execute("CREATE SCHEMA tenant1")
    cl.execute("CREATE SCHEMA tenant2")
    cl.execute("CREATE TABLE tenant1.orders (id bigint, total decimal(10,2))")
    cl.execute("CREATE TABLE tenant1.items (id bigint, qty bigint)")
    cl.execute("CREATE TABLE tenant2.orders (id bigint, total decimal(10,2))")
    t1o = cl.catalog.table("tenant1.orders")
    t1i = cl.catalog.table("tenant1.items")
    t2o = cl.catalog.table("tenant2.orders")
    # one colocation group per schema; different schemas differ
    assert t1o.colocation_id == t1i.colocation_id
    assert t1o.colocation_id != t2o.colocation_id
    # all of a tenant's shards live on the schema's home node
    assert t1o.shards[0].placements == t1i.shards[0].placements
    cl.execute("INSERT INTO tenant1.orders VALUES (1, 9.99), (2, 19.99)")
    cl.execute("INSERT INTO tenant2.orders VALUES (7, 5.00)")
    assert cl.execute("SELECT count(*), sum(total) FROM tenant1.orders").rows[0][0] == 2
    assert cl.execute("SELECT count(*) FROM tenant2.orders").rows == [(1,)]
    schemas = {r[0]: r for r in cl.execute("SELECT citus_schemas()").rows}
    assert schemas["tenant1"][3] == 2  # table count


def test_tenant_join_within_schema(db):
    cl = db
    cl.execute("CREATE SCHEMA app")
    cl.execute("CREATE TABLE app.users (uid bigint, name text)")
    cl.execute("CREATE TABLE app.events (uid bigint, n bigint)")
    cl.execute("INSERT INTO app.users VALUES (1, 'ann'), (2, 'bo')")
    cl.execute("INSERT INTO app.events VALUES (1, 10), (1, 20), (2, 5)")
    r = cl.execute(
        "SELECT u.name, sum(e.n) FROM app.users u JOIN app.events e "
        "ON u.uid = e.uid GROUP BY u.name ORDER BY u.name")
    assert r.rows == [("ann", 30), ("bo", 5)]


def test_tenant_moves_as_unit(db):
    cl = db
    cl.execute("CREATE SCHEMA ten")
    cl.execute("CREATE TABLE ten.a (x bigint)")
    cl.execute("CREATE TABLE ten.b (y bigint)")
    cl.execute("INSERT INTO ten.a VALUES (1), (2)")
    cl.execute("INSERT INTO ten.b VALUES (9)")
    ta = cl.catalog.table("ten.a")
    src = ta.shards[0].placements[0]
    dst = (src + 1) % 3
    cl.execute(f"SELECT citus_move_shard_placement({ta.shards[0].shard_id}, {src}, {dst})")
    assert cl.catalog.table("ten.a").shards[0].placements == [dst]
    assert cl.catalog.table("ten.b").shards[0].placements == [dst]
    assert cl.execute("SELECT count(*) FROM ten.a").rows == [(2,)]
    assert cl.execute("SELECT count(*) FROM ten.b").rows == [(1,)]


def test_schema_errors(db):
    cl = db
    with pytest.raises(CatalogError):
        cl.execute("CREATE TABLE missing.t (x bigint)")
    cl.execute("CREATE SCHEMA s1")
    with pytest.raises(CatalogError):
        cl.execute("CREATE SCHEMA s1")
    cl.execute("CREATE TABLE s1.t (x bigint)")
    with pytest.raises(CatalogError):
        cl.execute("DROP SCHEMA s1")  # not empty
    cl.execute("DROP SCHEMA s1 CASCADE")
    assert not cl.catalog.has_table("s1.t")
    with pytest.raises(CatalogError):
        cl.execute("SELECT count(*) FROM s1.t")
