"""Regression tests for plan-cache/statistics invalidation and ingest
edge cases found in review."""

import datetime

import numpy as np

import citus_tpu as ct
from citus_tpu.errors import CatalogError
import pytest


def test_drop_recreate_invalidates_plan_cache(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (a bigint)")
    cl.execute("INSERT INTO t VALUES (1), (2)")
    sql = "SELECT count(*) FROM t"
    assert cl.execute(sql).rows == [(2,)]
    cl.execute("DROP TABLE t")
    with pytest.raises(CatalogError):
        cl.execute(sql)
    cl.execute("CREATE TABLE t (a bigint)")
    # recreated table must start empty (old shard files removed)
    assert cl.execute(sql).rows == [(0,)]
    cl.execute("INSERT INTO t VALUES (7)")
    assert cl.execute(sql).rows == [(1,)]


def test_drop_recreate_resets_text_dictionary(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (c text)")
    cl.execute("INSERT INTO t VALUES ('old1'), ('old2')")
    cl.execute("DROP TABLE t")
    cl.execute("CREATE TABLE t (c text)")
    assert cl.catalog.dictionary("t", "c") == []
    cl.execute("INSERT INTO t VALUES ('new')")
    assert cl.execute("SELECT c FROM t").rows == [("new",)]


def test_stats_cache_isolated_between_clusters(tmp_path):
    a = ct.Cluster(str(tmp_path / "a"))
    b = ct.Cluster(str(tmp_path / "b"))
    a.execute("CREATE TABLE t (g bigint)")
    b.execute("CREATE TABLE t (g bigint)")
    a.execute("INSERT INTO t VALUES (1), (2), (3)")
    b.execute("INSERT INTO t VALUES (100), (200), (300)")
    assert a.execute("SELECT g, count(*) FROM t GROUP BY g ORDER BY g").rows == \
        [(1, 1), (2, 1), (3, 1)]
    assert b.execute("SELECT g, count(*) FROM t GROUP BY g ORDER BY g").rows == \
        [(100, 1), (200, 1), (300, 1)]


def test_count_constant_arg_hash_mode(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (f double)")
    cl.copy_from("t", columns={"f": np.array([1.5, 1.5, 2.5])})
    # float group key -> hash_host mode; count(1) has a constant argument
    r = cl.execute("SELECT f, count(1) FROM t GROUP BY f ORDER BY f")
    assert r.rows == [(1.5, 2), (2.5, 1)]


def test_decimal_rounding_consistent_between_ingest_paths(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE a (d decimal(10,2))")
    cl.execute("CREATE TABLE b (d decimal(10,2))")
    vals = [0.125, -0.125, 2.675]
    cl.copy_from("a", columns={"d": np.array(vals)})          # ndarray fast path
    cl.copy_from("b", rows=[(v,) for v in vals])              # object path
    ra = cl.execute("SELECT d FROM a ORDER BY d").rows
    rb = cl.execute("SELECT d FROM b ORDER BY d").rows
    assert ra == rb


def test_timestamp_roundtrip_exact(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (ts timestamp)")
    ts = datetime.datetime(2026, 7, 28, 12, 0, 0, 1)
    cl.copy_from("t", rows=[(ts,)])
    assert cl.execute("SELECT ts FROM t").rows == [(ts,)]


def test_ingest_invalidates_cached_group_domains(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (g bigint)")
    cl.execute("INSERT INTO t VALUES (1), (2)")
    sql = "SELECT g, count(*) FROM t GROUP BY g ORDER BY g"
    assert cl.execute(sql).rows == [(1, 1), (2, 1)]
    # new values outside the old [1,2] domain must still group correctly
    cl.execute("INSERT INTO t VALUES (50), (50), (2)")
    assert cl.execute(sql).rows == [(1, 1), (2, 2), (50, 2)]
