"""Pallas segment-reduction kernels must agree exactly with numpy.

Runs in interpret mode on the CPU mesh; on real TPU hardware the same
kernels compile natively (ExecutorSettings.use_pallas gates the
integration)."""

import numpy as np
import pytest

from citus_tpu.ops.pallas_kernels import segment_minmax_pallas, segment_sum_pallas


@pytest.mark.parametrize("dtype", [np.int64, np.float64, np.int32])
def test_segment_sum_matches_numpy(dtype):
    rng = np.random.default_rng(1)
    n, G = 10_000, 37
    gid = rng.integers(0, G, n).astype(np.int32)
    vals = rng.integers(-1000, 1000, n).astype(dtype)
    mask = rng.random(n) > 0.2
    got = np.asarray(segment_sum_pallas(gid, vals, mask, G=G, block=2048,
                                        interpret=True))
    want = np.zeros(G, dtype)
    np.add.at(want, gid[mask], vals[mask])
    np.testing.assert_array_equal(got, want)


def test_segment_sum_unaligned_length():
    gid = np.array([0, 1, 0, 2, 1], np.int32)
    vals = np.array([1, 10, 100, 1000, 10000], np.int64)
    mask = np.array([True, True, False, True, True])
    got = np.asarray(segment_sum_pallas(gid, vals, mask, G=4, block=4,
                                        interpret=True))
    np.testing.assert_array_equal(got, [1, 10010, 1000, 0])


@pytest.mark.parametrize("kind", ["min", "max"])
def test_segment_minmax_matches_numpy(kind):
    rng = np.random.default_rng(2)
    n, G = 5_000, 11
    gid = rng.integers(0, G, n).astype(np.int32)
    vals = rng.integers(-10**9, 10**9, n).astype(np.int64)
    mask = rng.random(n) > 0.5
    got = np.asarray(segment_minmax_pallas(gid, vals, mask, G=G, kind=kind,
                                           block=1024, interpret=True))
    info = np.iinfo(np.int64)
    want = np.full(G, info.max if kind == "min" else info.min, np.int64)
    op = np.minimum if kind == "min" else np.maximum
    getattr(op, "at")(want, gid[mask], vals[mask])
    np.testing.assert_array_equal(got, want)


def test_end_to_end_with_pallas_backend(tmp_path):
    """A full GROUP BY query through the pallas segment reductions must
    equal the default XLA path exactly."""
    import citus_tpu as ct
    from citus_tpu.config import ExecutorSettings, settings_override
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v decimal(10,2))")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rng = np.random.default_rng(5)
    n = 20_000
    cl.copy_from("t", columns={"k": np.arange(n, dtype=np.int64),
                               "g": rng.integers(0, 40, n),
                               "v": rng.integers(0, 10000, n) / 100})
    sql = "SELECT g, count(*), sum(v), min(v), max(v) FROM t GROUP BY g ORDER BY g"
    default_rows = cl.execute(sql).rows
    with settings_override(executor=ExecutorSettings(use_pallas=True)):
        cl2 = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
        pallas_rows = cl2.execute(sql).rows
    assert pallas_rows == default_rows
