"""CREATE/DROP INDEX, unique enforcement, and index point lookups.

Reference semantics being matched: DDL-propagated indexes
(src/backend/distributed/commands/index.c), index builds over columnar
(columnar_tableam.c:1444 columnar_index_build_range_scan), and btree
uniqueness (duplicate key SQLSTATE 23505).  The TPU-native shape is a
per-stripe sorted value->offset segment beside each stripe file.
"""

import os

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import CatalogError
from citus_tpu.executor.executor import GLOBAL_COUNTERS
from citus_tpu.integrity import UniqueViolation


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("""CREATE TABLE items (
        id bigint NOT NULL, grp bigint, label text, price decimal(10,2))""")
    cl.execute("SELECT create_distributed_table('items', 'id', 4)")
    rows = [(i, i % 7, f"label-{i % 50}", i * 1.25) for i in range(5000)]
    cl.copy_from("items", rows=rows)
    return cl


def seg_files(cl, table, column):
    t = cl.catalog.table(table)
    out = []
    for shard in t.shards:
        for node in shard.placements:
            d = cl.catalog.shard_dir(table, shard.shard_id, node)
            if os.path.isdir(d):
                out += [f for f in os.listdir(d)
                        if f.endswith(f".idx.{column}.npz")]
    return out


# ------------------------------------------------------------ DDL wiring

def test_create_index_backfills_existing_stripes(db):
    db.execute("CREATE INDEX items_grp_idx ON items (grp)")
    t = db.catalog.table("items")
    assert t.indexes == [{"name": "items_grp_idx", "column": "grp",
                          "unique": False}]
    assert len(seg_files(db, "items", "grp")) > 0


def test_new_ingest_builds_segments_without_backfill(db):
    db.execute("CREATE INDEX items_grp_idx ON items (grp)")
    before = len(seg_files(db, "items", "grp"))
    db.copy_from("items", rows=[(9001, 3, "x", 1.0)])
    assert len(seg_files(db, "items", "grp")) > before


def test_drop_index_removes_segments_and_metadata(db):
    db.execute("CREATE INDEX items_grp_idx ON items (grp)")
    assert seg_files(db, "items", "grp")
    db.execute("DROP INDEX items_grp_idx")
    assert db.catalog.table("items").indexes == []
    assert seg_files(db, "items", "grp") == []
    # queries on the column still work (plain scan)
    r = db.execute("SELECT count(*) FROM items WHERE grp = 3")
    assert r.rows[0][0] == len([i for i in range(5000) if i % 7 == 3])


def test_index_name_collision_and_if_not_exists(db):
    db.execute("CREATE INDEX ix ON items (grp)")
    with pytest.raises(CatalogError):
        db.execute("CREATE INDEX ix ON items (price)")
    db.execute("CREATE INDEX IF NOT EXISTS ix ON items (grp)")  # no-op
    with pytest.raises(CatalogError):
        db.execute("CREATE INDEX ix2 ON items (grp)")  # column taken
    with pytest.raises(CatalogError):
        db.execute("DROP INDEX nope")
    db.execute("DROP INDEX IF EXISTS nope")  # no-op


# ------------------------------------------------------- point lookups

def test_point_query_uses_index_and_prunes_chunks(db):
    db.execute("CREATE INDEX items_grp_idx ON items (grp)")
    GLOBAL_COUNTERS.reset()
    r = db.execute("SELECT count(*) FROM items WHERE grp = 5")
    assert r.rows[0][0] == len([i for i in range(5000) if i % 7 == 5])
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap.get("index_lookups", 0) > 0


def test_explain_shows_index_path(db):
    db.execute("CREATE INDEX items_grp_idx ON items (grp)")
    r = db.execute("EXPLAIN SELECT sum(price) FROM items WHERE grp = 2")
    text = "\n".join(row[0] for row in r.rows)
    assert "Index Lookup: grp = 2 using items_grp_idx" in text


def test_index_lookup_matches_scan_results(db):
    # identical results with and without the index, incl. aggregates,
    # projections, and text-column indexes (dictionary-id equality)
    queries = [
        "SELECT count(*), sum(price), min(id), max(id) FROM items WHERE grp = 4",
        "SELECT id, price FROM items WHERE grp = 6 ORDER BY id LIMIT 20",
        "SELECT count(*) FROM items WHERE label = 'label-17'",
        "SELECT grp, count(*) FROM items WHERE grp = 1 GROUP BY grp",
    ]
    before = [db.execute(q).rows for q in queries]
    db.execute("CREATE INDEX ix_grp ON items (grp)")
    db.execute("CREATE INDEX ix_label ON items (label)")
    after = [db.execute(q).rows for q in queries]
    assert before == after


def test_index_lookup_respects_deletes(db):
    db.execute("CREATE INDEX ix_grp ON items (grp)")
    expected = len([i for i in range(5000) if i % 7 == 2])
    assert db.execute("SELECT count(*) FROM items WHERE grp = 2").rows[0][0] == expected
    db.execute("DELETE FROM items WHERE grp = 2 AND id < 1000")
    gone = len([i for i in range(1000) if i % 7 == 2])
    r = db.execute("SELECT count(*) FROM items WHERE grp = 2")
    assert r.rows[0][0] == expected - gone


def test_index_survives_vacuum_rewrite(db):
    db.execute("CREATE INDEX ix_grp ON items (grp)")
    db.execute("DELETE FROM items WHERE grp = 0")
    db.execute("VACUUM items")
    # rewritten stripes must carry fresh segments
    assert seg_files(db, "items", "grp")
    r = db.execute("SELECT count(*) FROM items WHERE grp = 3")
    assert r.rows[0][0] == len([i for i in range(5000) if i % 7 == 3])


def test_rename_column_carries_index(db):
    db.execute("CREATE INDEX ix_grp ON items (grp)")
    db.execute("ALTER TABLE items RENAME COLUMN grp TO bucket")
    t = db.catalog.table("items")
    assert t.index_on("bucket") is not None
    assert seg_files(db, "items", "bucket")
    r = db.execute("SELECT count(*) FROM items WHERE bucket = 3")
    assert r.rows[0][0] == len([i for i in range(5000) if i % 7 == 3])


def test_drop_column_drops_index(db):
    db.execute("CREATE INDEX ix_grp ON items (grp)")
    db.execute("ALTER TABLE items DROP COLUMN grp")
    assert db.catalog.table("items").indexes == []
    assert seg_files(db, "items", "grp") == []


# ------------------------------------------------------------ uniqueness

def test_unique_index_rejects_duplicate_ingest(db):
    db.execute("CREATE UNIQUE INDEX items_id_key ON items (id)")
    with pytest.raises(UniqueViolation, match="items_id_key"):
        db.copy_from("items", rows=[(17, 0, "dup", 1.0)])
    # batch-internal duplicate
    with pytest.raises(UniqueViolation):
        db.copy_from("items", rows=[(90001, 0, "a", 1.0),
                                    (90001, 1, "b", 2.0)])
    # non-duplicate still loads
    assert db.copy_from("items", rows=[(90002, 0, "ok", 1.0)]) == 1


def test_unique_on_non_distribution_column(db):
    # global uniqueness across shards even though the column is not the
    # distribution key (beyond the reference, which refuses this)
    db.execute("CREATE UNIQUE INDEX items_price_key ON items (price)")
    with pytest.raises(UniqueViolation):
        db.copy_from("items", rows=[(80001, 0, "x", 100 * 1.25)])


def test_unique_backfill_validates_existing_data(db):
    db.copy_from("items", rows=[(70001, 0, "dup-grp", 1.0),
                                (70002, 0, "dup-grp", 2.0)])
    with pytest.raises(UniqueViolation):
        db.execute("CREATE UNIQUE INDEX ix_label ON items (label)")
    assert db.catalog.table("items").indexes == []


def test_delete_frees_unique_value(db):
    db.execute("CREATE UNIQUE INDEX items_id_key ON items (id)")
    db.execute("DELETE FROM items WHERE id = 42")
    assert db.copy_from("items", rows=[(42, 0, "back", 9.0)]) == 1
    with pytest.raises(UniqueViolation):
        db.copy_from("items", rows=[(42, 0, "again", 9.0)])


def test_update_respects_unique(db):
    db.execute("CREATE UNIQUE INDEX items_id_key ON items (id)")
    with pytest.raises(UniqueViolation):
        db.execute("UPDATE items SET id = 100 WHERE id = 200")
    # no-conflict update passes; self-replacement is not a conflict
    db.execute("UPDATE items SET id = 990001 WHERE id = 200")
    assert db.execute("SELECT count(*) FROM items WHERE id = 990001").rows[0][0] == 1
    # updating a NON-unique column of a unique-indexed table is fine
    db.execute("UPDATE items SET grp = 99 WHERE id = 300")


def test_upsert_still_works_with_unique_index(db):
    db.execute("CREATE UNIQUE INDEX items_id_key ON items (id)")
    db.execute("INSERT INTO items (id, grp, label, price) VALUES "
               "(55, 0, 'x', 1.0) ON CONFLICT (id) DO UPDATE SET grp = 77")
    r = db.execute("SELECT grp FROM items WHERE id = 55")
    assert r.rows == [(77,)]
    db.execute("INSERT INTO items (id, grp, label, price) VALUES "
               "(600001, 5, 'new', 2.0) ON CONFLICT (id) DO NOTHING")
    assert db.execute("SELECT count(*) FROM items WHERE id = 600001").rows[0][0] == 1


def test_primary_key_column_constraint(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db2"))
    cl.execute("CREATE TABLE users (uid bigint PRIMARY KEY, name text UNIQUE)")
    t = cl.catalog.table("users")
    assert t.index_on("uid")["unique"] and t.index_on("uid")["name"] == "users_pkey"
    assert t.index_on("name")["unique"]
    assert t.schema.column("uid").not_null
    cl.execute("INSERT INTO users VALUES (1, 'ann'), (2, 'bo')")
    with pytest.raises(UniqueViolation, match="users_pkey"):
        cl.execute("INSERT INTO users VALUES (1, 'carl')")
    with pytest.raises(UniqueViolation, match="users_name_key"):
        cl.execute("INSERT INTO users VALUES (3, 'ann')")


def test_create_table_pk_validation_is_atomic(tmp_path):
    """A failing implicit index must not leave a half-created table
    (PostgreSQL: CREATE TABLE is all-or-nothing)."""
    from citus_tpu.errors import UnsupportedFeatureError
    cl = ct.Cluster(str(tmp_path / "db3"))
    with pytest.raises(UnsupportedFeatureError):
        cl.execute("CREATE TABLE bad (x double precision PRIMARY KEY)")
    assert not cl.catalog.has_table("bad")
    cl.execute("CREATE TABLE a (k bigint PRIMARY KEY)")
    with pytest.raises(CatalogError):
        # index name a_pkey is taken by table a
        cl.execute("CREATE UNIQUE INDEX a_pkey ON a (k)")


def test_unique_index_inside_transaction_overlay(db):
    """Staged (uncommitted) rows of the open transaction also conflict."""
    db.execute("CREATE UNIQUE INDEX items_id_key ON items (id)")
    with db.session() as s:
        db.execute("BEGIN", session=s)
        db.execute("INSERT INTO items (id, grp, label, price) VALUES "
                   "(770001, 1, 'a', 1.0)", session=s)
        with pytest.raises(UniqueViolation):
            db.execute("INSERT INTO items (id, grp, label, price) VALUES "
                       "(770001, 2, 'b', 2.0)", session=s)
        db.execute("ROLLBACK", session=s)
    # rolled back: the value is free again
    assert db.copy_from("items", rows=[(770001, 1, "c", 1.0)]) == 1


def test_table_level_pk_and_unique_constraints(tmp_path):
    """PRIMARY KEY (col) / UNIQUE (col) as table constraints fold onto
    the column (PostgreSQL's table-constraint spelling)."""
    import citus_tpu as ct
    from citus_tpu.integrity import UniqueViolation
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint,"
               " PRIMARY KEY (k), UNIQUE (v))")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    names = {ix["name"] for ix in cl.catalog.table("t").unique_indexes}
    assert names == {"t_pkey", "t_v_key"}
    cl.execute("INSERT INTO t VALUES (1, 10)")
    with pytest.raises(UniqueViolation):
        cl.execute("INSERT INTO t VALUES (1, 20)")
    with pytest.raises(UniqueViolation):
        cl.execute("INSERT INTO t VALUES (2, 10)")
    cl.close()
