"""EXTRACT + calendar date_trunc vs sqlite's strftime oracle."""

import datetime

import numpy as np
import pytest

import citus_tpu as ct


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE e (k bigint NOT NULL, d date, ts timestamp, v bigint)")
    cl.execute("SELECT create_distributed_table('e', 'k', 4)")
    rng = np.random.default_rng(4)
    base = datetime.date(1995, 1, 1)
    rows = []
    for i in range(2000):
        d = base + datetime.timedelta(days=int(rng.integers(0, 4000)))
        ts = datetime.datetime(d.year, d.month, d.day,
                               int(rng.integers(0, 24)), int(rng.integers(0, 60)),
                               int(rng.integers(0, 60)))
        rows.append((i, d, ts, int(rng.integers(0, 100))))
    cl.copy_from("e", rows=rows)
    return cl, rows


def test_extract_fields(db):
    cl, rows = db
    got = cl.execute(
        "SELECT k, extract(year FROM d), extract(month FROM d), "
        "extract(day FROM d), extract(dow FROM d), extract(doy FROM d) "
        "FROM e WHERE k < 200 ORDER BY k").rows
    for (k, y, m, d_, dow, doy) in got:
        dd = rows[k][1]
        assert (y, m, d_) == (dd.year, dd.month, dd.day), (k, dd)
        assert dow == (dd.weekday() + 1) % 7  # PG: 0 = Sunday
        assert doy == dd.timetuple().tm_yday


def test_extract_time_fields(db):
    cl, rows = db
    got = cl.execute(
        "SELECT k, extract(hour FROM ts), extract(minute FROM ts), "
        "extract(second FROM ts) FROM e WHERE k < 100 ORDER BY k").rows
    for (k, h, mi, s) in got:
        ts = rows[k][2]
        assert (h, mi, s) == (ts.hour, ts.minute, ts.second)


def test_group_by_extract_year(db):
    cl, rows = db
    got = dict((y, c) for y, c in
               cl.execute("SELECT extract(year FROM d), count(*) FROM e "
                          "GROUP BY extract(year FROM d)").rows)
    import collections
    want = collections.Counter(r[1].year for r in rows)
    assert got == dict(want)


def test_date_trunc_month_year(db):
    cl, rows = db
    got = cl.execute(
        "SELECT k, date_trunc('month', d), date_trunc('year', d), "
        "date_trunc('quarter', d) FROM e WHERE k < 150 ORDER BY k").rows
    for (k, mo, yr, q) in got:
        dd = rows[k][1]
        assert mo == dd.replace(day=1)
        assert yr == dd.replace(month=1, day=1)
        qm = (dd.month - 1) // 3 * 3 + 1
        assert q == dd.replace(month=qm, day=1)


def test_monthly_rollup(db):
    cl, rows = db
    got = dict((m, c) for m, c in cl.execute(
        "SELECT date_trunc('month', d), count(*) FROM e GROUP BY date_trunc('month', d)").rows)
    import collections
    want = collections.Counter(r[1].replace(day=1) for r in rows)
    assert got == dict(want)


def test_string_functions(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db2"), n_nodes=2)
    cl.execute("CREATE TABLE s (k bigint NOT NULL, w text)")
    cl.execute("SELECT create_distributed_table('s', 'k', 2)")
    cl.copy_from("s", rows=[(1, "Hello"), (2, "WORLD"), (3, "ok"), (4, None)])
    rows = dict(cl.execute("SELECT k, upper(w) FROM s").rows)
    assert rows == {1: "HELLO", 2: "WORLD", 3: "OK", 4: None}
    rows = dict(cl.execute("SELECT k, lower(w) FROM s").rows)
    assert rows == {1: "hello", 2: "world", 3: "ok", 4: None}
    rows = dict(cl.execute("SELECT k, length(w) FROM s").rows)
    assert rows == {1: 5, 2: 5, 3: 2, 4: None}
    # filter + group through the transforms
    assert cl.execute("SELECT count(*) FROM s WHERE length(w) = 5").rows == [(2,)]
    g = dict(cl.execute("SELECT upper(w), count(*) FROM s GROUP BY upper(w)").rows)
    assert g == {"HELLO": 1, "WORLD": 1, "OK": 1, None: 1}


def test_substring_concat(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db3"), n_nodes=1)
    cl.execute("CREATE TABLE s (k bigint, w text)")
    cl.copy_from("s", rows=[(1, "hello"), (2, "hi"), (3, None)])
    assert dict(cl.execute("SELECT k, substring(w, 2, 3) FROM s").rows) == \
        {1: "ell", 2: "i", 3: None}
    assert dict(cl.execute("SELECT k, concat('<', w, '>') FROM s").rows) == \
        {1: "<hello>", 2: "<hi>", 3: None}


def test_update_with_subquery(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db4"), n_nodes=1)
    cl.execute("CREATE TABLE t (k bigint, v bigint)")
    cl.execute("CREATE TABLE u (x bigint)")
    cl.copy_from("t", rows=[(i, i) for i in range(10)])
    cl.copy_from("u", rows=[(3,), (5,)])
    cl.execute("UPDATE t SET v = (SELECT max(x) FROM u) WHERE k IN (SELECT x FROM u)")
    rows = dict(cl.execute("SELECT k, v FROM t").rows)
    assert rows[3] == 5 and rows[5] == 5 and rows[4] == 4
