"""Arbitrary-configs matrix.

Reference: src/test/regress/citus_tests/arbitrary_configs/ — one common
SQL suite executed across cluster shapes (shard counts, executors,
metadata modes).  Here the battery runs over shard counts x executor
backends x compression codecs x chunk sizes x planner toggles and must
produce identical results everywhere.
"""

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import (
    ColumnarSettings, ExecutorSettings, PlannerSettings, Settings,
)

CONFIGS = [
    {"shards": 1, "codec": "zstd", "chunk": 8192, "backend": "tpu"},
    {"shards": 4, "codec": "zstd", "chunk": 8192, "backend": "tpu"},
    {"shards": 8, "codec": "lz4", "chunk": 1024, "backend": "tpu"},
    {"shards": 3, "codec": "zlib", "chunk": 512, "backend": "tpu"},
    {"shards": 4, "codec": "none", "chunk": 8192, "backend": "cpu"},
    {"shards": 16, "codec": "zstd", "chunk": 256, "backend": "cpu"},
    # repartition joins disabled: non-colocated joins take the pull path
    {"shards": 4, "codec": "zstd", "chunk": 8192, "backend": "tpu",
     "repartition": False},
    # tiny hash-agg table: heavy spill through the exact host path
    {"shards": 4, "codec": "zstd", "chunk": 2048, "backend": "tpu",
     "hash_slots": 16},
    # tiny direct-gid budget: GROUP BY forced onto the hash path
    {"shards": 4, "codec": "zstd", "chunk": 8192, "backend": "tpu",
     "direct_limit": 4},
]

BATTERY = [
    "SELECT count(*), sum(v), min(v), max(v) FROM t",
    "SELECT g, count(*), avg(v) FROM t GROUP BY g ORDER BY g",
    "SELECT count(*) FROM t WHERE v BETWEEN 100 AND 400",
    "SELECT s, sum(v) FROM t WHERE g < 5 GROUP BY s ORDER BY s",
    "SELECT k, v FROM t WHERE k = 37",
    "SELECT count(*) FROM t a JOIN t b ON a.k = b.k",
    # non-colocated equi-join (repartition or pull depending on config)
    "SELECT count(*), sum(a.v) FROM t a JOIN t b ON a.v = b.g",
    "SELECT g, stddev(v) FROM t GROUP BY g ORDER BY g",
    "SELECT v % 97 AS m, count(*) FROM t GROUP BY v % 97 ORDER BY m LIMIT 5",
]


def run_battery(tmp_path, cfg):
    st = Settings(
        columnar=ColumnarSettings(
            chunk_group_row_limit=cfg["chunk"],
            stripe_row_limit=cfg["chunk"] * 4,
            compression=cfg["codec"]),
        executor=ExecutorSettings(task_executor_backend=cfg["backend"]),
        planner=PlannerSettings(
            enable_repartition_joins=cfg.get("repartition", True),
            hash_agg_slots=cfg.get("hash_slots", 8192),
            direct_gid_limit=cfg.get("direct_limit", 65536)),
    )
    tag = "_".join(str(v) for v in cfg.values())
    cl = ct.Cluster(str(tmp_path / f"db_{tag}"), n_nodes=2, settings=st)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v bigint, s text)")
    cl.execute(f"SELECT create_distributed_table('t', 'k', {cfg['shards']})")
    rng = np.random.default_rng(99)
    n = 5000
    cl.copy_from("t", columns={
        "k": np.arange(n, dtype=np.int64),
        "g": rng.integers(0, 10, n),
        "v": rng.integers(0, 500, n),
        "s": np.array(["x", "y", "z"])[rng.integers(0, 3, n)].tolist()})
    out = []
    for sql in BATTERY:
        out.append(sorted(cl.execute(sql).rows, key=repr))
    cl.close()
    return out


def _canon(rows):
    import decimal
    out = []
    for r in rows:
        out.append(tuple(round(float(v), 6)
                         if isinstance(v, (float, decimal.Decimal)) else v
                         for v in r))
    return out


def test_configs_matrix(tmp_path):
    baseline = run_battery(tmp_path, CONFIGS[0])
    for cfg in CONFIGS[1:]:
        got = run_battery(tmp_path, cfg)
        for sql, want, have in zip(BATTERY, baseline, got):
            assert _canon(want) == _canon(have), (cfg, sql)
