"""Pipelined adaptive executor (executor/pipeline.py): remote
execute_task RPCs fan out on threads with per-node slow-start windows
(adaptive_executor.c's connection ramp-up analog) and overlap the local
shard scan; a background decode worker feeds a bounded read-ahead queue
so host stripe decode overlaps device compute.

Timing assertions use fault-injected delays (testing/faults.py), so
they measure scheduling structure, not machine speed: an injected
per-item delay makes "overlapped" vs "serial" differ by integer
multiples of the delay, far above scheduler noise.
"""

import threading
import time

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.executor.device_cache import GLOBAL_CACHE
from citus_tpu.executor.executor import GLOBAL_COUNTERS
from citus_tpu.testing.faults import FAULTS


@pytest.fixture()
def pair(tmp_path):
    """Authority + one attached worker (two data dirs, one logical
    cluster) — half of a table's shards land on the remote host."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    a.register_node()
    b = ct.Cluster(str(tmp_path / "b"), data_port=0, hosted_nodes=set(),
                   coordinator=("127.0.0.1", a.control_port), n_nodes=0)
    b.register_node()
    a._maybe_reload_catalog(force_sync=True)
    yield a
    FAULTS.disarm()
    b.close()
    a.close()


@pytest.fixture()
def quad(tmp_path):
    """Authority + three attached workers: a 4-shard table puts one
    shard on each host, so one scan issues three remote RPCs."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    a.register_node()
    workers = []
    try:
        for name in ("b", "c", "d"):
            w = ct.Cluster(str(tmp_path / name), data_port=0,
                           hosted_nodes=set(), n_nodes=0,
                           coordinator=("127.0.0.1", a.control_port))
            w.register_node()
            workers.append(w)
        a._maybe_reload_catalog(force_sync=True)
        yield a
    finally:
        FAULTS.disarm()
        for w in workers:
            w.close()
        a.close()


def _load(cl, n=20000, shards=4, table="t"):
    cl.execute(f"CREATE TABLE {table} (k bigint NOT NULL, v bigint)")
    cl.execute(f"SELECT create_distributed_table('{table}', 'k', {shards})")
    cl.copy_from(table, columns={"k": np.arange(n),
                                 "v": np.arange(n) * 3})
    GLOBAL_CACHE.clear()
    GLOBAL_COUNTERS.reset()
    return n


def test_parallel_dispatch_wall_is_max_not_sum(quad):
    """Three remote tasks, each delayed 0.5 s at the worker: parallel
    fan-out costs ~one delay, sequential dispatch would cost three."""
    a = quad
    n = _load(a)
    assert sum(1 for s in a.catalog.table("t").shards
               if a.catalog.is_remote_node(s.placements[0])) == 3
    FAULTS.arm("execute_task", delay_s=0.5)
    t0 = time.perf_counter()
    r = a.execute("SELECT count(*), sum(v) FROM t")
    wall = time.perf_counter() - t0
    FAULTS.disarm()
    assert r.rows == [(n, 3 * n * (n - 1) // 2)]
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["remote_tasks_pushed"] == 3
    assert snap["remote_task_fallbacks"] == 0
    assert snap["remote_tasks_inflight_peak"] == 3
    # serial dispatch would need >= 1.5 s of injected delay alone
    assert wall < 1.2, wall


def test_remote_wait_overlaps_local_scan(quad):
    """The local shard scan runs while remote RPCs are in flight: the
    overlapped-wait gauge reports nonzero hidden wait."""
    a = quad
    n = _load(a)
    FAULTS.arm("execute_task", delay_s=0.2)
    r = a.execute("SELECT count(*), sum(v) FROM t")
    FAULTS.disarm()
    assert r.rows == [(n, 3 * n * (n - 1) // 2)]
    pl = r.explain.get("pipeline") or {}
    assert pl.get("remote_inflight_peak") == 3, pl
    # blocked wait + wait hidden behind local work covers the 0.2 s
    # the RPCs were in flight, however the local scan happened to pace
    assert pl.get("remote_wait_ms", 0) + pl.get("remote_overlapped_ms", 0) \
        >= 150, pl


def test_inflight_peak_respects_pool_cap(pair):
    """citus.max_adaptive_executor_pool_size caps the per-node RPC
    window: with 4 remote tasks on one worker and a cap of 2, the
    in-flight high-water mark never exceeds 2."""
    a = pair
    n = _load(a, shards=8)
    a.execute("SET citus.max_adaptive_executor_pool_size = 2")
    assert a.execute(
        "SHOW citus.max_adaptive_executor_pool_size").rows == [("2",)]
    GLOBAL_CACHE.clear()
    FAULTS.arm("execute_task", delay_s=0.05)
    r = a.execute("SELECT count(*), sum(v) FROM t")
    FAULTS.disarm()
    assert r.rows == [(n, 3 * n * (n - 1) // 2)]
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["remote_tasks_pushed"] == 4
    assert 1 <= snap["remote_tasks_inflight_peak"] <= 2, snap


def test_per_task_failure_falls_back_mid_flight(quad):
    """One of three parallel RPCs dies: only that task falls back to
    the pull path; the other two pushes stand and the answer is
    exact."""
    a = quad
    n = _load(a)
    FAULTS.arm("execute_task", error=RuntimeError("mid-flight loss"),
               times=1)
    r = a.execute("SELECT count(*), sum(v) FROM t")
    FAULTS.disarm()
    assert r.rows == [(n, 3 * n * (n - 1) // 2)]
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["remote_tasks_pushed"] == 2
    assert snap["remote_task_fallbacks"] == 1


def test_collect_creates_o1_threads_under_wide_fanout(pair, monkeypatch):
    """64 remote tasks dispatch through ONE selector-driven event loop:
    the coordinator's collect path creates no per-RPC thread (the old
    citus-remote-task-* dispatch threads), and total thread creation
    during the query stays far below the fan-out width — O(1)
    dispatcher threads per coordinator, not O(tasks) per query."""
    a = pair
    n = _load(a, shards=128)
    started = []
    orig_start = threading.Thread.start

    def record(self):
        started.append(self.name)
        return orig_start(self)

    monkeypatch.setattr(threading.Thread, "start", record)
    try:
        r = a.execute("SELECT count(*), sum(v) FROM t")
    finally:
        monkeypatch.undo()
    assert r.rows == [(n, 3 * n * (n - 1) // 2)]
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["remote_tasks_pushed"] == 64, snap
    assert not [nm for nm in started if "citus-remote-task" in nm], started
    assert sum("citus-rpc-loop" in nm for nm in started) <= 1, started
    # the only other creations are the local scan's decode workers and
    # the WORKER-side per-connection server handlers (unnamed
    # "Thread-N (_serve_conn)" threads) — the latter bounded by the
    # pool cap, not the 64-task fan-out
    conns = [nm for nm in started
             if nm.startswith("Thread-") or "_serve_conn" in nm]
    others = [nm for nm in started
              if nm not in conns and "citus-host-decode" not in nm
              and "citus-rpc-loop" not in nm]
    assert not others, others
    assert len(conns) < 32, conns


def test_prefetch_overlaps_decode_with_device(tmp_cluster):
    """A/B on the mesh path with injected per-batch decode delay and
    per-round device delay: depth-2 read-ahead hides decode behind
    device rounds, so pipelined wall must land well under serial."""
    cl = tmp_cluster
    n = 20000
    cl.execute("CREATE TABLE ov (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('ov', 'k', 32)")
    cl.copy_from("ov", columns={"k": np.arange(n),
                                "v": np.arange(n) * 3})
    q = "SELECT count(*), sum(v) FROM ov"
    exp = [(n, 3 * n * (n - 1) // 2)]
    GLOBAL_CACHE.clear()
    assert cl.execute(q).rows == exp  # warmup: compile kernels uncached

    def measured(depth):
        cl.execute(f"SET citus.executor_prefetch_depth = {depth}")
        try:
            FAULTS.arm("decode_batch", delay_s=0.02, match="ov")
            FAULTS.arm("device_round", delay_s=0.16, match="ov")
            GLOBAL_CACHE.clear()
            t0 = time.perf_counter()
            r = cl.execute(q)
            wall = time.perf_counter() - t0
        finally:
            FAULTS.disarm()
        assert r.rows == exp  # depth changes timing, never results
        return wall

    serial = measured(0)
    piped = measured(2)
    assert piped < 0.75 * serial, (piped, serial)
    snap = GLOBAL_COUNTERS.snapshot()
    # with decode 8x faster than a device round, the host side stalls
    # (queue full / consumer busy), the device side does not starve
    assert snap["pipeline_host_stalls"] + snap["pipeline_device_stalls"] > 0


def test_prefetch_decode_error_propagates(tmp_cluster):
    """An exception on the background decode thread surfaces as the
    query's error (no hang, no partial answer) and the cluster keeps
    answering afterwards."""
    cl = tmp_cluster
    n = 20000
    cl.execute("CREATE TABLE pe (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('pe', 'k', 32)")
    cl.copy_from("pe", columns={"k": np.arange(n),
                                "v": np.arange(n) * 3})
    GLOBAL_CACHE.clear()
    FAULTS.arm("decode_batch", error=RuntimeError("stripe rot"),
               match="pe", after=2)
    try:
        with pytest.raises(Exception, match="stripe rot"):
            cl.execute("SELECT count(*), sum(v) FROM pe")
    finally:
        FAULTS.disarm()
    GLOBAL_CACHE.clear()
    assert cl.execute("SELECT count(*), sum(v) FROM pe").rows == \
        [(n, 3 * n * (n - 1) // 2)]


def test_depth_zero_matches_piped_results_all_paths(tmp_cluster):
    """Inline decode (depth 0) and pipelined decode produce identical
    rows for scalar agg, GROUP BY, and filtered projection — on both
    the mesh (32-shard) and single-device (1-shard) layouts."""
    cl = tmp_cluster
    n = 12000
    for table, shards in (("m1", 32), ("s1", 1)):
        cl.execute(f"CREATE TABLE {table} (k bigint NOT NULL, v bigint,"
                   f" c text)")
        cl.execute(
            f"SELECT create_distributed_table('{table}', 'k', {shards})")
        cl.copy_from(table, columns={
            "k": np.arange(n), "v": np.arange(n) * 3,
            "c": [f"w{i % 5}" for i in range(n)]})
    queries = [
        "SELECT count(*), sum(v), min(k), max(v) FROM {t}",
        "SELECT c, count(*), sum(v) FROM {t} GROUP BY c ORDER BY c",
        "SELECT k, v FROM {t} WHERE k < 40 ORDER BY k",
    ]
    for table in ("m1", "s1"):
        for q in queries:
            sql = q.format(t=table)
            rows = {}
            for depth in (0, 3):
                cl.execute(f"SET citus.executor_prefetch_depth = {depth}")
                GLOBAL_CACHE.clear()
                rows[depth] = cl.execute(sql).rows
            assert rows[0] == rows[3], sql


def test_explain_analyze_pipeline_lines(pair):
    """EXPLAIN ANALYZE renders the pipeline block (decode/device halves,
    stalls) and split rpc/decode timings per pushed task."""
    a = pair
    _load(a)
    GLOBAL_CACHE.clear()
    r = a.execute("EXPLAIN ANALYZE SELECT count(*), sum(v) FROM t")
    txt = "\n".join(row[0] for row in r.rows)
    assert "Pipeline: host decode" in txt, txt
    assert "ms rpc" in txt and "ms decode" in txt, txt
    assert "Remote Wait:" in txt and "peak in-flight" in txt, txt


def test_prefetch_depth_guc_roundtrip(tmp_cluster):
    cl = tmp_cluster
    assert cl.execute("SHOW citus.executor_prefetch_depth").rows == [("2",)]
    cl.execute("SET citus.executor_prefetch_depth = 0")
    assert cl.execute("SHOW citus.executor_prefetch_depth").rows == [("0",)]
    assert cl.execute(
        "SHOW citus.max_adaptive_executor_pool_size").rows == [("16",)]
    cl.execute("SET citus.max_tasks_in_flight = 4")
    assert cl.execute("SHOW citus.max_tasks_in_flight").rows == [("4",)]
