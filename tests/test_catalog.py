"""Catalog + hash sharding tests (pg_dist_* equivalents)."""

import numpy as np
import pytest

from citus_tpu.catalog import Catalog, DistributionMethod
from citus_tpu.catalog.hashing import (
    INT32_MAX, INT32_MIN, hash_int64, shard_hash_ranges,
    shard_index_for_hash, shard_index_for_values,
)
from citus_tpu.errors import CatalogError
from citus_tpu.schema import Schema


def test_hash_ranges_cover_int32_space():
    for count in [1, 2, 3, 7, 8, 32]:
        ranges = shard_hash_ranges(count)
        assert ranges[0][0] == INT32_MIN
        assert ranges[-1][1] == INT32_MAX
        for (lo1, hi1), (lo2, hi2) in zip(ranges, ranges[1:]):
            assert lo2 == hi1 + 1
            assert lo1 <= hi1


def test_hash_deterministic_and_spread():
    v = np.arange(100000, dtype=np.int64)
    h1, h2 = hash_int64(v), hash_int64(v)
    np.testing.assert_array_equal(h1, h2)
    idx = shard_index_for_hash(h1, 8)
    counts = np.bincount(idx, minlength=8)
    # roughly uniform: each shard within 20% of mean
    assert counts.min() > 100000 / 8 * 0.8
    assert counts.max() < 100000 / 8 * 1.2


def test_shard_index_matches_ranges():
    v = np.arange(5000, dtype=np.int64) * 7919
    h = hash_int64(v)
    for count in [2, 5, 8]:
        ranges = shard_hash_ranges(count)
        idx = shard_index_for_hash(h, count)
        for hv, i in zip(h.tolist(), idx.tolist()):
            lo, hi = ranges[i]
            assert lo <= hv <= hi


def test_catalog_create_distribute_persist(tmp_path):
    cat = Catalog(str(tmp_path))
    schema = Schema.of(("id", "bigint"), ("v", "double"))
    cat.create_table("t", schema)
    assert cat.table("t").method == DistributionMethod.LOCAL
    nodes = cat.ensure_nodes(4)
    cat.distribute_table("t", "id", 8, nodes)
    cat.commit()

    cat2 = Catalog(str(tmp_path))
    t = cat2.table("t")
    assert t.method == DistributionMethod.HASH
    assert t.dist_column == "id"
    assert t.shard_count == 8
    assert [s.hash_min for s in t.shards][0] == INT32_MIN
    # round-robin placements over 4 nodes
    assert [s.placements[0] for s in t.shards] == [0, 1, 2, 3, 0, 1, 2, 3]


def test_catalog_colocation(tmp_path):
    cat = Catalog(str(tmp_path))
    s = Schema.of(("id", "bigint"))
    cat.create_table("a", s)
    cat.create_table("b", s)
    nodes = cat.ensure_nodes(2)
    cat.distribute_table("a", "id", 4, nodes)
    cat.distribute_table("b", "id", 4, nodes, colocate_with="a")
    assert cat.table("a").colocation_id == cat.table("b").colocation_id
    cat.create_table("c", s)
    with pytest.raises(CatalogError):
        cat.distribute_table("c", "id", 8, nodes, colocate_with="a")


def test_catalog_errors(tmp_path):
    cat = Catalog(str(tmp_path))
    with pytest.raises(CatalogError):
        cat.table("missing")
    s = Schema.of(("id", "bigint"), ("f", "double"))
    cat.create_table("t", s)
    with pytest.raises(CatalogError):
        cat.create_table("t", s)
    with pytest.raises(CatalogError):
        cat.distribute_table("t", "f", 4, [0])  # float dist col


def test_text_dictionary_roundtrip(tmp_path):
    cat = Catalog(str(tmp_path))
    ids = cat.encode_strings("t", "c", ["x", "y", "x", "z"])
    assert list(ids) == [0, 1, 0, 2]
    assert cat.decode_strings("t", "c", ids) == ["x", "y", "x", "z"]
    cat.commit()
    cat2 = Catalog(str(tmp_path))
    assert list(cat2.encode_strings("t", "c", ["z", "w"])) == [2, 3]
    assert cat2.lookup_string_id("t", "c", "y") == 1
    assert cat2.lookup_string_id("t", "c", "nope") is None
