"""SET / SHOW runtime settings (the GUC surface).

Reference: the citus.* GUCs defined in shared_library_init.c:980+;
settings here apply to the Cluster handle."""

import pytest

import citus_tpu as ct
from citus_tpu.errors import CatalogError


@pytest.fixture()
def cl(tmp_path):
    return ct.Cluster(str(tmp_path / "db"))


def test_set_and_show_roundtrip(cl):
    assert cl.execute("SHOW citus.shard_count").rows == [("8",)]
    cl.execute("SET citus.shard_count = 16")
    assert cl.execute("SHOW citus.shard_count").rows == [("16",)]
    # the setting actually drives DDL
    cl.execute("CREATE TABLE t (k bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k')")
    assert cl.catalog.table("t").shard_count == 16
    # prefix optional, TO spelling works
    cl.execute("SET shard_count TO 4")
    assert cl.execute("SHOW shard_count").rows == [("4",)]


def test_set_backend_switches_executor(cl):
    cl.execute("CREATE TABLE t (k bigint, v bigint)")
    cl.copy_from("t", rows=[(i, i) for i in range(100)])
    want = cl.execute("SELECT sum(v) FROM t").rows
    cl.execute("SET citus.task_executor_backend = 'cpu'")
    assert cl.execute("SHOW citus.task_executor_backend").rows == [("cpu",)]
    assert cl.execute("SELECT sum(v) FROM t").rows == want  # bit-identical
    cl.execute("SET citus.task_executor_backend = 'tpu'")


def test_set_secondary_nodes_spelling(cl):
    assert cl.execute("SHOW citus.use_secondary_nodes").rows == [("never",)]
    cl.execute("SET citus.use_secondary_nodes = 'always'")
    assert cl.execute("SHOW citus.use_secondary_nodes").rows == [("always",)]
    assert cl.settings.executor.use_secondary_nodes is True


def test_set_cdc_flag_takes_effect(cl):
    cl.execute("CREATE TABLE ev (k bigint)")
    cl.copy_from("ev", rows=[(1,)])
    assert list(cl.cdc.events("ev")) == []
    cl.execute("SET citus.enable_change_data_capture = on")
    cl.copy_from("ev", rows=[(2,)])
    assert len(list(cl.cdc.events("ev"))) == 1


def test_lock_timeout_pg_units_and_boolean_rendering(cl):
    cl.execute("SET lock_timeout = 3000")      # bare number = ms (PG)
    assert cl.settings.executor.lock_timeout_s == 3.0
    cl.execute("SET lock_timeout = '2s'")
    assert cl.settings.executor.lock_timeout_s == 2.0
    cl.execute("SET lock_timeout = '500ms'")
    assert cl.settings.executor.lock_timeout_s == 0.5
    assert cl.execute("SHOW lock_timeout").rows == [("500ms",)]
    # booleans render as on/off (PG)
    assert cl.execute("SHOW citus.enable_repartition_joins").rows == [("on",)]
    with pytest.raises(CatalogError, match="Boolean"):
        cl.execute("SET citus.enable_repartition_joins = 'tru'")
    with pytest.raises(CatalogError, match="always or never"):
        cl.execute("SET citus.use_secondary_nodes = 'alway'")


def test_set_rolls_back_with_transaction(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("SET citus.shard_count = 64")
    assert cl.execute("SHOW citus.shard_count").rows == [("64",)]
    s.execute("ROLLBACK")
    assert cl.execute("SHOW citus.shard_count").rows == [("8",)]
    s.execute("BEGIN")
    s.execute("SET citus.shard_count = 32")
    s.execute("COMMIT")
    assert cl.execute("SHOW citus.shard_count").rows == [("32",)]


def test_deadlock_interval_is_live(cl):
    names = {d[0]: d[1] for d in cl.maintenance.status()}
    assert names["deadlock_detection"] == 2.0
    cl.execute("SET citus.distributed_deadlock_detection_interval = 0.5")
    names = {d[0]: d[1] for d in cl.maintenance.status()}
    assert names["deadlock_detection"] == 0.5


def test_show_all_and_unknown(cl):
    rows = cl.execute("SHOW ALL").rows
    names = [r[0] for r in rows]
    assert "citus.task_executor_backend" in names
    assert "citus.max_shared_pool_size" in names
    with pytest.raises(CatalogError, match="unrecognized"):
        cl.execute("SHOW citus.nope")
    with pytest.raises(CatalogError):
        cl.execute("SET citus.shard_count = 'many'")


def test_set_decode_threads_drives_native_pool(cl):
    from citus_tpu.storage import reader as R
    # default: 0 = auto (min(8, cpu_count)); SHOW renders the raw GUC
    assert cl.execute("SHOW citus.decode_threads").rows == [("0",)]
    try:
        cl.execute("SET citus.decode_threads = 3")
        assert cl.execute("SHOW citus.decode_threads").rows == [("3",)]
        assert R.decode_thread_count() == 3
        cl.execute("SET citus.decode_threads = 0")   # back to auto
        assert R.decode_thread_count() >= 1
    finally:
        R.set_decode_threads(0)
