"""Cross-host bulk data plane: coordinators with SEPARATE data dirs
exchange shard bytes over RPC — placement reads, distributed COPY
routing, shard moves, and dictionary sync.

Reference: executor/transmit.c (COPY-protocol file transfer),
operations/worker_shard_copy.c, commands/multi_copy.c per-shard stream
forwarding, pg_dist_node nodename/nodeport.
"""

import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import citus_tpu as ct


@pytest.fixture()
def pair(tmp_path):
    """Two coordinators, two data dirs, one logical cluster: A is the
    metadata authority hosting node 0; B attaches and hosts node 1."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    na = a.register_node()
    b = ct.Cluster(str(tmp_path / "b"), data_port=0, hosted_nodes=set(),
                   coordinator=("127.0.0.1", a.control_port), n_nodes=0)
    nb = b.register_node()
    a._maybe_reload_catalog(force_sync=True)
    yield a, b, na, nb
    b.close()
    a.close()


def test_distributed_query_spans_both_processes(pair):
    a, b, na, nb = pair
    a.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, c text)")
    a.execute("SELECT create_distributed_table('t', 'k', 4)")
    t = a.catalog.table("t")
    owners = {s.placements[0] for s in t.shards}
    assert owners == {na, nb}, "shards must land on both hosts"
    n = 2000
    a.copy_from("t", columns={
        "k": np.arange(n), "v": np.arange(n) * 3,
        "c": [f"w{i % 7}" for i in range(n)]})
    # rows physically split across the two data dirs
    local_rows = 0
    for s in t.shards:
        d = a.catalog.shard_dir("t", s.shard_id, s.placements[0])
        if os.path.isdir(d):
            from citus_tpu.storage.writer import _load_meta
            local_rows += _load_meta(d)["row_count"]
    assert 0 < local_rows < n, "some rows must live on the remote host"
    # the full answer needs shards from BOTH processes
    assert a.execute("SELECT count(*), sum(v) FROM t").rows == \
        [(n, 3 * n * (n - 1) // 2)]
    # text decode across hosts (dictionary authority = A)
    r = a.execute("SELECT c, count(*) FROM t GROUP BY c ORDER BY c")
    assert len(r.rows) == 7 and sum(x[1] for x in r.rows) == n
    # B answers the same query over the wire: under the (default) push
    # policy the worker half of the plan ships to the owning host and
    # only result rows come back (executor/worker_tasks.py); under pull
    # the placement files are fetched.  Either way cross-host transport
    # must have happened.
    b._maybe_reload_catalog(force_sync=True)
    assert b.execute("SELECT count(*), sum(v) FROM t").rows == \
        [(n, 3 * n * (n - 1) // 2)]
    from citus_tpu.executor.executor import GLOBAL_COUNTERS
    fetched = a.catalog.remote_data.stats["files_fetched"] \
        + b.catalog.remote_data.stats["files_fetched"]
    assert GLOBAL_COUNTERS.snapshot()["remote_tasks_pushed"] + fetched > 0


def test_move_shard_placement_over_the_wire(pair):
    a, b, na, nb = pair
    a.execute("CREATE TABLE m (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('m', 'k', 4)")
    n = 1000
    a.copy_from("m", columns={"k": np.arange(n), "v": np.ones(n, np.int64)})
    before = a.execute("SELECT count(*), sum(v) FROM m").rows
    t = a.catalog.table("m")
    moved = next(s for s in t.shards if s.placements[0] == nb)
    # B -> A: pull over the data plane, flip, drop on B via RPC
    a.execute(f"SELECT citus_move_shard_placement({moved.shard_id}, "
              f"{nb}, {na})")
    t = a.catalog.table("m")
    s2 = next(s for s in t.shards if s.shard_id == moved.shard_id)
    assert s2.placements == [na]
    assert os.path.isdir(a.catalog.shard_dir("m", moved.shard_id, na))
    assert a.execute("SELECT count(*), sum(v) FROM m").rows == before
    # A -> B: push over the data plane
    back = next(s for s in t.shards if s.placements[0] == na)
    a.execute(f"SELECT citus_move_shard_placement({back.shard_id}, "
              f"{na}, {nb})")
    assert os.path.isdir(b.catalog.shard_dir("m", back.shard_id, nb))
    assert a.execute("SELECT count(*), sum(v) FROM m").rows == before
    # B sees the flipped placement map and still answers
    b._maybe_reload_catalog(force_sync=True)
    assert b.execute("SELECT count(*), sum(v) FROM m").rows == before


def test_remote_write_restrictions(pair):
    a, b, na, nb = pair
    a.execute("CREATE TABLE r (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('r', 'k', 4)")
    from citus_tpu.errors import UnsupportedFeatureError
    s = a.session()
    s.execute("BEGIN")
    with pytest.raises(UnsupportedFeatureError, match="cross-host 2PC"):
        s.execute("INSERT INTO r VALUES (1, 2)")
    s.execute("ROLLBACK")


def test_update_delete_on_remote_shards_visible(pair):
    """DML executed on the coordinator hosting the shard is visible to
    the peer's next read (mutable files re-sync)."""
    a, b, na, nb = pair
    a.execute("CREATE TABLE d (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('d', 'k', 4)")
    n = 400
    a.copy_from("d", columns={"k": np.arange(n), "v": np.zeros(n, np.int64)})
    b._maybe_reload_catalog(force_sync=True)
    assert b.execute("SELECT count(*) FROM d").rows == [(n,)]
    # B deletes rows routed to a shard IT hosts (distribution-column
    # filter -> local execution on B); A must observe the deletion
    # bitmaps through the re-synced mutable files
    from citus_tpu.catalog.hashing import shard_index_for_values
    t = b.catalog.table("d")
    idx = shard_index_for_values(np.arange(n, dtype=np.int64),
                                 t.shard_count)
    local_keys = [int(k) for k in range(n)
                  if t.shards[idx[k]].placements[0] == nb][:5]
    assert local_keys
    deleted = 0
    for k in local_keys:
        r = b.execute(f"DELETE FROM d WHERE k = {k}")
        deleted += r.explain["deleted"]
    assert deleted == len(local_keys)
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    GLOBAL_CACHE.clear()
    assert a.execute("SELECT count(*) FROM d").rows == [(n - deleted,)]


def test_two_os_processes_two_data_dirs(tmp_path):
    """The VERDICT criterion: coordinator processes that do NOT share a
    data directory answer a distributed query whose shards live on both,
    and complete citus_move_shard_placement over the wire."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    na = a.register_node()
    worker = textwrap.dedent(f"""
        import sys, time
        import jax
        jax.config.update("jax_platforms", "cpu")
        import citus_tpu as ct
        b = ct.Cluster({str(tmp_path / 'b')!r}, data_port=0,
                       hosted_nodes=set(), n_nodes=0,
                       coordinator=("127.0.0.1", {a.control_port}))
        nb = b.register_node()
        print("READY", nb, flush=True)
        sys.stdout.close()
        time.sleep(120)
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", worker],
                            stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = proc.stdout.readline().split()
        assert line and line[0] == "READY", f"worker failed: {line}"
        nb = int(line[1])
        a._maybe_reload_catalog(force_sync=True)
        assert a.catalog.nodes[nb].endpoint is not None
        a.execute("CREATE TABLE big (k bigint NOT NULL, v bigint)")
        a.execute("SELECT create_distributed_table('big', 'k', 4)")
        n = 3000
        a.copy_from("big", columns={"k": np.arange(n),
                                    "v": np.arange(n)})
        t = a.catalog.table("big")
        assert {s.placements[0] for s in t.shards} == {na, nb}
        # (a) distributed query across both OS processes
        assert a.execute("SELECT count(*), sum(v) FROM big").rows == \
            [(n, n * (n - 1) // 2)]
        # (b) move a remote-hosted shard into this process over the wire
        moved = next(s for s in t.shards if s.placements[0] == nb)
        a.execute(f"SELECT citus_move_shard_placement({moved.shard_id}, "
                  f"{nb}, {na})")
        assert a.execute("SELECT count(*), sum(v) FROM big").rows == \
            [(n, n * (n - 1) // 2)]
        t = a.catalog.table("big")
        assert next(s for s in t.shards
                    if s.shard_id == moved.shard_id).placements == [na]
    finally:
        proc.kill()
        proc.wait()
        a.close()


def test_rpc_auth_rejects_unauthenticated_peer(tmp_path):
    """VERDICT #8: an unauthenticated client is refused registration and
    catalog fetch; a wrong secret is refused too; the right secret
    works."""
    from citus_tpu.net.rpc import RpcClient, RpcError
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0, secret=b"s3cret")
    a.register_node()
    port = a.control_port
    # no secret: server rejects the frame
    c = RpcClient("127.0.0.1", port)
    with pytest.raises(RpcError):
        c.call("fetch_catalog")
    c.close()
    # wrong secret
    c = RpcClient("127.0.0.1", port, secret=b"wrong")
    with pytest.raises(RpcError):
        c.call("fetch_catalog")
    c.close()
    # right secret: full attach works end-to-end
    b = ct.Cluster(str(tmp_path / "b"), data_port=0, hosted_nodes=set(),
                   n_nodes=0, coordinator=("127.0.0.1", port),
                   secret=b"s3cret")
    nb = b.register_node()
    assert nb in b.catalog.nodes
    # and the DATA plane refuses unauthenticated reads of shard bytes
    dc = RpcClient("127.0.0.1", a.data_port)
    with pytest.raises(RpcError):
        dc.call("list_placement", {"table": "x", "shard_id": 1, "node": 0})
    dc.close()
    b.close()
    a.close()


def test_blob_tamper_detection():
    """A substituted same-length binary frame fails the digest check."""
    import socket
    import struct
    import threading

    from citus_tpu.net.rpc import AuthError, RpcServer, _recv, _send
    srv = RpcServer(port=0, secret=b"k")
    received = []
    srv.register("put", lambda p, blob: received.append(blob) or {"ok": 1})
    srv.start()
    # craft a frame with a valid HMAC but swapped blob bytes
    s = socket.create_connection(("127.0.0.1", srv.port))
    import hashlib
    import hmac as hm
    import json
    blob = b"A" * 16
    obj = {"id": 1, "method": "put", "payload": {}, "bin": 16,
           "bin_sha256": hashlib.sha256(blob).hexdigest()}
    body = json.dumps(obj, sort_keys=True).encode()
    obj["hmac"] = hm.new(b"k", body, hashlib.sha256).hexdigest()
    data = json.dumps(obj).encode()
    s.sendall(struct.pack(">I", len(data)) + data)
    s.sendall(struct.pack(">I", 16) + b"B" * 16)  # tampered bytes
    resp = _recv(s, b"k")
    assert resp is not None and "authentication" in resp[0].get("error", "")
    assert received == []
    s.close()
    srv.stop()


def test_remote_dml_forwarding_and_guard(pair):
    """A router modify whose shard lives on the peer forwards the
    statement text (the deparse-and-ship analog); a multi-host modify
    raises instead of silently skipping remote shards."""
    a, b, na, nb = pair
    a.execute("CREATE TABLE w (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('w', 'k', 4)")
    n = 500
    a.copy_from("w", columns={"k": np.arange(n), "v": np.zeros(n, np.int64)})
    t = a.catalog.table("w")
    # find a key that routes to a B-hosted shard
    from citus_tpu.catalog.hashing import shard_index_for_values
    ks = np.arange(n)
    idx = shard_index_for_values(ks.astype(np.int64), t.shard_count)
    remote_keys = [int(k) for k, si in zip(ks, idx)
                   if t.shards[si].placements[0] == nb]
    assert remote_keys
    k0 = remote_keys[0]
    r = a.execute(f"UPDATE w SET v = 7 WHERE k = {k0}")
    assert r.explain.get("updated") == 1
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    GLOBAL_CACHE.clear()
    assert a.execute(f"SELECT v FROM w WHERE k = {k0}").rows == [(7,)]
    r = a.execute(f"DELETE FROM w WHERE k = {k0}")
    assert r.explain.get("deleted") == 1
    GLOBAL_CACHE.clear()
    assert a.execute("SELECT count(*) FROM w").rows == [(n - 1,)]
    # a modify spanning both hosts runs as a cross-host 2PC
    r = a.execute("UPDATE w SET v = 9")
    assert r.explain.get("updated") == n - 1
    GLOBAL_CACHE.clear()
    assert a.execute("SELECT sum(v) FROM w").rows == [(9 * (n - 1),)]


def test_reference_table_replicates_to_remote_host(pair):
    """Reference-table COPY replicates the full batch to every host
    with a placement; both coordinators answer joins against it from
    their local replica; cross-host modifies are refused (divergence)."""
    a, b, na, nb = pair
    a.execute("CREATE TABLE ref (id bigint NOT NULL, name text)")
    a.execute("SELECT create_reference_table('ref')")
    a.copy_from("ref", rows=[(1, "one"), (2, "two"), (3, "three")])
    assert a.execute("SELECT count(*) FROM ref").rows == [(3,)]
    b._maybe_reload_catalog(force_sync=True)
    assert b.execute("SELECT count(*) FROM ref").rows == [(3,)]
    # B's replica is LOCAL bytes in B's data dir (not a remote fetch)
    t = b.catalog.table("ref")
    import os
    assert any(os.path.isdir(b.catalog.shard_dir("ref", s.shard_id, nb))
               for s in t.shards)
    from citus_tpu.errors import UnsupportedFeatureError
    with pytest.raises(UnsupportedFeatureError, match="reference table"):
        a.execute("DELETE FROM ref WHERE id = 1")


def test_insert_select_routes_to_remote_host(pair):
    """Review finding: the INSERT..SELECT array path must not drop rows
    hashing to remote-hosted shards — it falls back to the routed pull
    path in multi-host mode."""
    a, b, na, nb = pair
    a.execute("CREATE TABLE src (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('src', 'k', 4)")
    a.execute("CREATE TABLE dst (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('dst', 'k', 4, 'src')")
    n = 800
    a.copy_from("src", columns={"k": np.arange(n),
                                "v": np.arange(n) * 2})
    r = a.execute("INSERT INTO dst SELECT k, v FROM src")
    assert r.explain["inserted"] == n
    assert r.explain["strategy"] == "insert_select:pull"
    assert a.execute("SELECT count(*), sum(v) FROM dst").rows == \
        [(n, n * (n - 1))]
    # no divergent local directory was created for the foreign node
    t = a.catalog.table("dst")
    for s in t.shards:
        if s.placements[0] == nb:
            assert not os.path.isdir(
                a.catalog.shard_dir("dst", s.shard_id, nb))


def test_truncate_forwards_to_remote_host(pair):
    """Review finding: TRUNCATE must reach remote-hosted placements or
    their rows resurrect through the remote read path."""
    a, b, na, nb = pair
    a.execute("CREATE TABLE tr (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('tr', 'k', 4)")
    a.copy_from("tr", columns={"k": np.arange(300),
                               "v": np.ones(300, np.int64)})
    assert a.execute("SELECT count(*) FROM tr").rows == [(300,)]
    a.execute("TRUNCATE tr")
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    GLOBAL_CACHE.clear()
    assert a.execute("SELECT count(*) FROM tr").rows == [(0,)]
    b._maybe_reload_catalog(force_sync=True)
    assert b.execute("SELECT count(*) FROM tr").rows == [(0,)]


def test_merge_into_remote_shards_fails_closed(pair):
    a, b, na, nb = pair
    a.execute("CREATE TABLE mt (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('mt', 'k', 4)")
    a.execute("CREATE TABLE ms (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('ms', 'k', 4, 'mt')")
    from citus_tpu.errors import UnsupportedFeatureError
    with pytest.raises(UnsupportedFeatureError, match="remote-hosted"):
        a.execute("MERGE INTO mt USING ms ON mt.k = ms.k "
                  "WHEN MATCHED THEN UPDATE SET v = ms.v "
                  "WHEN NOT MATCHED THEN INSERT VALUES (ms.k, ms.v)")


def test_multi_host_update_two_phase_commit(pair):
    """A modify spanning shards on BOTH hosts commits atomically via
    cross-host 2PC: prepare everywhere, durable outcome at the
    authority, decide everywhere."""
    a, b, na, nb = pair
    a.execute("CREATE TABLE tp (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('tp', 'k', 4)")
    n = 600
    a.copy_from("tp", columns={"k": np.arange(n), "v": np.zeros(n, np.int64)})
    r = a.execute("UPDATE tp SET v = 5 WHERE k % 2 = 0")
    assert r.explain.get("updated") == n // 2
    assert "gxid" in r.explain
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    GLOBAL_CACHE.clear()
    assert a.execute("SELECT sum(v) FROM tp").rows == [(5 * n // 2,)]
    b._maybe_reload_catalog(force_sync=True)
    assert b.execute("SELECT sum(v) FROM tp").rows == [(5 * n // 2,)]
    # and a multi-host DELETE
    r = a.execute("DELETE FROM tp WHERE v = 5")
    assert r.explain.get("deleted") == n // 2
    GLOBAL_CACHE.clear()
    assert a.execute("SELECT count(*) FROM tp").rows == [(n // 2,)]


def test_multi_host_update_aborts_atomically_on_branch_failure(pair):
    """One branch failing to prepare aborts the WHOLE statement: no
    host applies anything (presumed abort + explicit decides)."""
    import threading

    a, b, na, nb = pair
    a.execute("CREATE TABLE ab (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('ab', 'k', 4)")
    n = 400
    a.copy_from("ab", columns={"k": np.arange(n), "v": np.zeros(n, np.int64)})
    # wedge B's branch: a foreign holder keeps B's colocation-group
    # flock EXCLUSIVE so B's dml_prepare times out
    from citus_tpu.transaction.write_locks import group_resource, lockfile_path
    import fcntl
    res = group_resource(b.catalog.table("ab"))
    lockpath = lockfile_path(b.catalog.data_dir, res)
    fd = open(lockpath, "w")
    fcntl.flock(fd, fcntl.LOCK_EX)
    b.settings.executor.lock_timeout_s = 1.0
    try:
        with pytest.raises(Exception):
            a.execute("UPDATE ab SET v = 9")
    finally:
        fcntl.flock(fd, fcntl.LOCK_UN)
        fd.close()
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    GLOBAL_CACHE.clear()
    # nothing applied anywhere — A's branch rolled back too
    assert a.execute("SELECT sum(v) FROM ab").rows == [(0,)]
    b._maybe_reload_catalog(force_sync=True)
    assert b.execute("SELECT sum(v) FROM ab").rows == [(0,)]


def test_branch_resolves_from_outcome_store_when_decide_lost(pair):
    """A prepared branch whose phase-2 decide never arrives resolves
    from the authority's durable outcome store (commit case) — the
    pg_dist_transaction reconciliation."""
    a, b, na, nb = pair
    a.execute("CREATE TABLE rb (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('rb', 'k', 4)")
    n = 200
    a.copy_from("rb", columns={"k": np.arange(n), "v": np.zeros(n, np.int64)})
    import uuid as uuid_mod2
    gxid = uuid_mod2.uuid4().hex
    # phase 1 directly against B's data server; then "lose" the decide
    ep = ("127.0.0.1", b.data_port)
    r = a.catalog.remote_data.call(
        ep, "dml_prepare", {"gxid": gxid, "sql": "UPDATE rb SET v = 3"})
    assert r["explain"]["updated"] > 0
    # durable commit decision at the authority, decide never sent
    a._control.record_txn_outcome(gxid, "commit")
    # branch expiry consults the store and COMMITS
    b._data_server.BRANCH_EXPIRE_S = 0.0
    b._data_server._expire_stale_branches()
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    GLOBAL_CACHE.clear()
    updated = [row for row in b.execute("SELECT k, v FROM rb").rows
               if row[1] == 3]
    assert updated, "B's branch must have committed from the store"
    # abort case: no outcome recorded -> presumed abort
    gxid2 = uuid_mod2.uuid4().hex
    a.catalog.remote_data.call(
        ep, "dml_prepare", {"gxid": gxid2, "sql": "UPDATE rb SET v = 8"})
    b._data_server._expire_stale_branches()
    GLOBAL_CACHE.clear()
    assert not [row for row in b.execute("SELECT v FROM rb").rows
                if row[0] == 8]


def test_interactive_cross_host_transaction_commit(pair):
    """BEGIN..COMMIT spanning hosts: statements accumulate in persistent
    remote branch sessions; COMMIT drives the branch 2PC."""
    a, b, na, nb = pair
    a.execute("CREATE TABLE it (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('it', 'k', 4)")
    n = 400
    a.copy_from("it", columns={"k": np.arange(n), "v": np.zeros(n, np.int64)})
    s = a.session()
    s.execute("BEGIN")
    r1 = s.execute("UPDATE it SET v = 1 WHERE k % 2 = 0")
    assert r1.explain.get("updated") == n // 2
    r2 = s.execute("UPDATE it SET v = v + 10 WHERE k % 2 = 0")
    assert r2.explain.get("updated") == n // 2  # branch sees its own write
    # other sessions see NOTHING until commit
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    GLOBAL_CACHE.clear()
    assert a.execute("SELECT sum(v) FROM it").rows == [(0,)]
    s.execute("COMMIT")
    GLOBAL_CACHE.clear()
    assert a.execute("SELECT sum(v) FROM it").rows == [(11 * n // 2,)]
    b._maybe_reload_catalog(force_sync=True)
    assert b.execute("SELECT sum(v) FROM it").rows == [(11 * n // 2,)]


def test_interactive_cross_host_transaction_rollback(pair):
    a, b, na, nb = pair
    a.execute("CREATE TABLE ir (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('ir', 'k', 4)")
    n = 200
    a.copy_from("ir", columns={"k": np.arange(n), "v": np.zeros(n, np.int64)})
    s = a.session()
    s.execute("BEGIN")
    s.execute("UPDATE ir SET v = 7")
    s.execute("ROLLBACK")
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    GLOBAL_CACHE.clear()
    assert a.execute("SELECT sum(v) FROM ir").rows == [(0,)]
    b._maybe_reload_catalog(force_sync=True)
    assert b.execute("SELECT sum(v) FROM ir").rows == [(0,)]


def test_interactive_cross_host_restrictions(pair):
    """Read-after-remote-write and savepoints are refused with clear
    errors inside a cross-host transaction."""
    a, b, na, nb = pair
    a.execute("CREATE TABLE rr (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('rr', 'k', 4)")
    a.copy_from("rr", columns={"k": np.arange(100),
                               "v": np.zeros(100, np.int64)})
    from citus_tpu.errors import UnsupportedFeatureError
    s = a.session()
    s.execute("BEGIN")
    s.execute("UPDATE rr SET v = 1")
    with pytest.raises(UnsupportedFeatureError, match="remote-hosted"):
        s.execute("SELECT count(*) FROM rr")
    s.execute("ROLLBACK")
    s = a.session()
    s.execute("BEGIN")
    s.execute("UPDATE rr SET v = 2")
    with pytest.raises(UnsupportedFeatureError, match="savepoint"):
        s.execute("SAVEPOINT sp")
    s.execute("ROLLBACK")
