"""Zero-copy columnar wire frames (net/data_plane.py: CTFR codec).

Covers the frame codec contract from every side: round trips across the
full dtype allowlist (with validity masks), degenerate frames (zero
rows, zero columns), malformed-input rejection (FrameError, never
pickle), the zero-copy guarantee (decoded arrays are frombuffer views
into the wire blob), the ≥30 % host-decode win over the legacy npz
container, the citus.wire_format GUC, and an end-to-end A/B on a real
two-host cluster showing both codecs produce identical rows while
bumping their own byte counters.
"""

import struct
import time

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import CatalogError
from citus_tpu.executor.device_cache import GLOBAL_CACHE
from citus_tpu.executor.executor import GLOBAL_COUNTERS
from citus_tpu.net.data_plane import (
    _FRAME_DTYPES, _npz_bytes, _npz_load, FRAME_MAGIC, FRAME_VERSION,
    FrameError, decode_batch, decode_frame, decode_partials, encode_batch,
    encode_frame, encode_partials,
)
from citus_tpu.testing.faults import FAULTS


@pytest.fixture()
def pair(tmp_path):
    """Authority + one attached worker — half of a table's shards land
    on the remote host (same harness as test_pipeline.py)."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    a.register_node()
    b = ct.Cluster(str(tmp_path / "b"), data_port=0, hosted_nodes=set(),
                   coordinator=("127.0.0.1", a.control_port), n_nodes=0)
    b.register_node()
    a._maybe_reload_catalog(force_sync=True)
    yield a
    FAULTS.disarm()
    b.close()
    a.close()


# ------------------------------------------------------- codec round trips

def test_frame_roundtrip_all_dtypes():
    """Every dtype in the allowlist survives encode/decode bit-exact,
    keeping dtype and shape — fuzzed values, not hand-picked ones."""
    rng = np.random.default_rng(7)
    arrays = {}
    for code, dt in _FRAME_DTYPES.items():
        n = int(rng.integers(1, 2000))
        if dt == np.dtype(np.bool_):
            a = rng.integers(0, 2, n).astype(bool)
        elif dt.kind == "f":
            a = rng.standard_normal(n).astype(dt)
        else:
            info = np.iinfo(dt)
            a = rng.integers(info.min, info.max, n,
                             dtype=np.int64 if dt.kind == "i"
                             else np.uint64).astype(dt)
        arrays[f"col_{code}"] = a
    out = decode_frame(encode_frame(arrays))
    assert set(out) == set(arrays)
    for k, a in arrays.items():
        assert out[k].dtype == a.dtype.newbyteorder("<"), k
        np.testing.assert_array_equal(out[k], a)


def test_frame_roundtrip_multidim_zero_row_zero_col():
    """2-D buffers, zero-row columns, and the empty (zero-column) frame
    all round trip; buffer alignment never corrupts neighbors."""
    arrays = {
        "mat": np.arange(12, dtype=np.float32).reshape(3, 4),
        "empty": np.empty(0, dtype=np.int64),
        "one": np.array([True]),
        "wide": np.zeros((0, 5), dtype=np.uint16),
        "scalar": np.array(2.5, dtype=np.float64),  # 0-d agg partial
        "strided": np.arange(10, dtype=np.int64)[::2],
    }
    out = decode_frame(encode_frame(arrays))
    for k, a in arrays.items():
        assert out[k].shape == a.shape, k
        np.testing.assert_array_equal(out[k], a)
    assert decode_frame(encode_frame({})) == {}


def test_batch_roundtrip_with_validity_masks():
    """encode_batch keeps the v__/m__ column naming, so validity
    bitmaps survive the wire as ordinary bool columns."""
    values = {"k": np.arange(100, dtype=np.int64),
              "v": np.linspace(0, 1, 100, dtype=np.float64)}
    validity = {"v": np.arange(100) % 3 != 0}
    v2, m2 = decode_batch(encode_batch(values, validity))
    assert set(v2) == {"k", "v"} and set(m2) == {"v"}
    np.testing.assert_array_equal(v2["k"], values["k"])
    np.testing.assert_array_equal(v2["v"], values["v"])
    np.testing.assert_array_equal(m2["v"], validity["v"])


def test_partials_roundtrip_positional():
    parts = (np.arange(5, dtype=np.int64),
             np.array([1.5, 2.5]),
             np.zeros(0, dtype=np.uint32))
    out = decode_partials(encode_partials(parts))
    assert len(out) == 3
    for a, b in zip(parts, out):
        assert b.dtype == a.dtype.newbyteorder("<")
        np.testing.assert_array_equal(a, b)


def test_npz_blob_still_decodes():
    """Magic-sniffing decode accepts the legacy npz container, so a
    frame-default coordinator interoperates with an npz peer."""
    values = {"k": np.arange(10, dtype=np.int64)}
    blob = encode_batch(values, {}, wire="npz")
    assert blob[:4] != FRAME_MAGIC
    v2, _m = decode_batch(blob)
    np.testing.assert_array_equal(v2["k"], values["k"])


# ------------------------------------------------------ malformed inputs

def test_frame_rejects_malformed_inputs():
    """Bad magic, bad version, truncation, out-of-bounds buffers, and
    unknown dtype codes all raise FrameError — a clean parse error, not
    a crash and never a pickle path."""
    good = encode_frame({"a": np.arange(64, dtype=np.int64)})
    with pytest.raises(FrameError, match="magic"):
        decode_frame(b"XXXX" + good[4:])
    with pytest.raises(FrameError, match="version"):
        decode_frame(FRAME_MAGIC
                     + struct.pack("<BxxxI", FRAME_VERSION + 9, 0))
    with pytest.raises(FrameError, match="truncated"):
        decode_frame(good[:7])  # header cut mid-preamble
    with pytest.raises(FrameError, match="bounds"):
        decode_frame(good[:-8])  # buffer shorter than the directory says
    bad_dtype = (FRAME_MAGIC + struct.pack("<BxxxI", FRAME_VERSION, 1)
                 + struct.pack("<H", 1) + b"a"
                 + struct.pack("<BB", 200, 1) + struct.pack("<Q", 0)
                 + struct.pack("<QQ", 28, 0))
    with pytest.raises(FrameError, match="dtype code"):
        decode_frame(bad_dtype)


def test_object_dtype_never_crosses_the_wire():
    """Non-physical (object dtype) columns are refused at encode time,
    and a pickled npz payload is refused at decode time — neither
    codec ever deserializes arbitrary objects."""
    with pytest.raises(TypeError, match="physical"):
        encode_batch({"c": np.array(["raw", "text"], dtype=object)}, {})
    with pytest.raises(FrameError):
        encode_frame({"c": np.array(["raw", "text"], dtype=object)})
    pickled = _npz_bytes({"v__c": np.arange(3)})  # valid container...
    import io
    import zipfile
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:  # ...vs an object payload
        with zipfile.ZipFile(io.BytesIO(pickled)) as src:
            for n in src.namelist():
                z.writestr(n, src.read(n))
        obj = io.BytesIO()
        np.save(obj, np.array([{"x": 1}], dtype=object),
                allow_pickle=True)
        z.writestr("v__evil.npy", obj.getvalue())
    with pytest.raises(ValueError):
        decode_batch(buf.getvalue())


# ----------------------------------------------------------- zero copy

def test_decode_frame_is_zero_copy():
    """Decoded arrays are READ-ONLY frombuffer views into the one wire
    blob — no per-column host copy."""
    arrays = {"a": np.arange(4096, dtype=np.int64),
              "b": np.ones(1000, dtype=np.float32)}
    blob = encode_frame(arrays)
    raw = np.frombuffer(blob, dtype=np.uint8)
    out = decode_frame(blob)
    for k in arrays:
        assert not out[k].flags.writeable, k
        assert np.shares_memory(out[k], raw), k


def test_frame_decode_cuts_host_decode_time_vs_npz():
    """The acceptance A/B: frame decode of a ~32 MB batch beats npz by
    >= 30 % (it is typically >10x — frombuffer views vs a zip-container
    copy). Best-of-3 each to shave scheduler noise."""
    arrays = {f"v__c{i}": np.arange(1_000_000, dtype=np.int64)
              for i in range(4)}
    frame = encode_frame(arrays)
    npz = _npz_bytes(arrays)

    def best_of(fn, blob):
        t = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(blob)
            t.append(time.perf_counter() - t0)
            assert len(out) == 4
        return min(t)

    t_frame = best_of(decode_frame, frame)
    t_npz = best_of(_npz_load, npz)
    assert t_frame <= 0.7 * t_npz, (t_frame, t_npz)


# ------------------------------------------------------------- GUC + e2e

def test_wire_format_guc_roundtrip(tmp_cluster):
    cl = tmp_cluster
    assert cl.execute("SHOW citus.wire_format").rows == [("frame",)]
    cl.execute("SET citus.wire_format = npz")
    assert cl.execute("SHOW citus.wire_format").rows == [("npz",)]
    cl.execute("SET citus.wire_format = FRAME")  # case-insensitive
    assert cl.execute("SHOW citus.wire_format").rows == [("frame",)]
    with pytest.raises(CatalogError):
        cl.execute("SET citus.wire_format = arrow2")


def test_end_to_end_frame_vs_npz_identical_rows(pair):
    """Same query pushed to a real remote worker under both wire
    formats: identical rows, and each run bumps its own byte counter —
    proof the chosen codec actually carried the task results."""
    a = pair
    n = 20000
    a.execute("CREATE TABLE wt (k bigint NOT NULL, v bigint)")
    a.execute("SELECT create_distributed_table('wt', 'k', 4)")
    a.copy_from("wt", columns={"k": np.arange(n), "v": np.arange(n) * 3})
    q = "SELECT count(*), sum(v) FROM wt"
    expected = [(n, 3 * n * (n - 1) // 2)]
    rows = {}
    for fmt in ("frame", "npz"):
        a.execute(f"SET citus.wire_format = {fmt}")
        GLOBAL_CACHE.clear()
        GLOBAL_COUNTERS.reset()
        rows[fmt] = a.execute(q).rows
        snap = GLOBAL_COUNTERS.snapshot()
        assert snap["remote_tasks_pushed"] > 0, (fmt, snap)
        assert snap[f"wire_{fmt}_bytes"] > 0, (fmt, snap)
        other = "npz" if fmt == "frame" else "frame"
        assert snap[f"wire_{other}_bytes"] == 0, (fmt, snap)
    assert rows["frame"] == rows["npz"] == expected
