"""SQL parser tests."""

import decimal

import pytest

from citus_tpu.errors import SqlSyntaxError
from citus_tpu.planner import ast as A
from citus_tpu.planner import parse_statement


def test_create_table():
    s = parse_statement(
        "CREATE TABLE lineitem (l_orderkey bigint NOT NULL, l_quantity decimal(12,2), "
        "l_shipdate date, l_comment varchar(44)) USING columnar WITH (compression = 'zstd')")
    assert isinstance(s, A.CreateTable)
    assert s.name == "lineitem"
    assert [c.name for c in s.columns] == ["l_orderkey", "l_quantity", "l_shipdate", "l_comment"]
    assert s.columns[0].not_null
    assert s.columns[1].type_args == [12, 2]
    assert s.options == {"access_method": "columnar", "compression": "zstd"}


def test_insert_values():
    s = parse_statement("INSERT INTO t (a, b) VALUES (1, 'x'), (2, NULL)")
    assert isinstance(s, A.Insert)
    assert s.columns == ["a", "b"]
    assert len(s.rows) == 2
    assert s.rows[0][0] == A.Literal(1, "int")
    assert s.rows[1][1] == A.Literal(None, "null")


def test_select_q6_shape():
    s = parse_statement(
        "SELECT sum(l_extendedprice * l_discount) AS revenue FROM lineitem "
        "WHERE l_shipdate >= '1994-01-01' AND l_shipdate < '1995-01-01' "
        "AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24")
    assert isinstance(s, A.Select)
    assert s.items[0].alias == "revenue"
    f = s.items[0].expr
    assert isinstance(f, A.FuncCall) and f.name == "sum"
    # where is a conjunction tree
    assert isinstance(s.where, A.BinOp) and s.where.op == "and"


def test_select_group_order_limit():
    s = parse_statement(
        "SELECT l_returnflag, l_linestatus, count(*) AS c FROM lineitem "
        "GROUP BY l_returnflag, l_linestatus "
        "ORDER BY l_returnflag, l_linestatus DESC NULLS FIRST LIMIT 10 OFFSET 2")
    assert len(s.group_by) == 2
    assert s.order_by[1].ascending is False
    assert s.order_by[1].nulls_first is True
    assert s.limit == 10 and s.offset == 2
    c = s.items[2].expr
    assert isinstance(c.args[0], A.Star)


def test_operator_precedence():
    s = parse_statement("SELECT a + b * c - d FROM t")
    e = s.items[0].expr
    # ((a + (b*c)) - d)
    assert e.op == "-"
    assert e.left.op == "+"
    assert e.left.right.op == "*"
    s2 = parse_statement("SELECT 1 FROM t WHERE a = 1 OR b = 2 AND c = 3")
    assert s2.where.op == "or"
    assert s2.where.right.op == "and"


def test_joins():
    s = parse_statement(
        "SELECT o.o_orderkey FROM orders o JOIN lineitem l ON o.o_orderkey = l.l_orderkey "
        "LEFT JOIN customer c ON c.c_custkey = o.o_custkey")
    j = s.from_
    assert isinstance(j, A.Join) and j.kind == "left"
    assert j.left.kind == "inner"
    assert j.left.left.alias == "o"


def test_utility_call():
    s = parse_statement("SELECT create_distributed_table('lineitem', 'l_orderkey')")
    assert isinstance(s, A.UtilityCall)
    assert s.args == ["lineitem", "l_orderkey"]


def test_case_cast_in_isnull():
    s = parse_statement(
        "SELECT CASE WHEN a > 0 THEN 1 ELSE 0 END, CAST(b AS bigint), c::decimal(10,2) "
        "FROM t WHERE d IS NOT NULL AND e IN (1, 2, 3)")
    assert isinstance(s.items[0].expr, A.CaseExpr)
    assert isinstance(s.items[1].expr, A.Cast)
    assert isinstance(s.items[2].expr, A.Cast)
    assert isinstance(s.where.left, A.IsNull) and s.where.left.negated
    assert isinstance(s.where.right, A.InList)


def test_literals():
    s = parse_statement("SELECT 1, 1.5, 1e3, 'it''s', true, NULL FROM t")
    vals = [i.expr for i in s.items]
    assert vals[0] == A.Literal(1, "int")
    assert vals[1] == A.Literal(decimal.Decimal("1.5"), "decimal")
    assert vals[2] == A.Literal(1000.0, "float")
    assert vals[3] == A.Literal("it's", "string")
    assert vals[4] == A.Literal(True, "bool")
    assert vals[5] == A.Literal(None, "null")


def test_syntax_errors():
    for bad in ["SELECT", "SELECT FROM t", "CREATE TABLE", "INSERT INTO", "SELECT * FROM"]:
        with pytest.raises(SqlSyntaxError):
            parse_statement(bad)


def test_explain():
    s = parse_statement("EXPLAIN ANALYZE SELECT count(*) FROM t")
    assert isinstance(s, A.Explain) and s.analyze
    assert isinstance(s.statement, A.Select)
