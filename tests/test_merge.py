"""MERGE statement vs sqlite oracle (sqlite supports UPSERT, so the
oracle is hand-computed or expressed with equivalent statements)."""

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import ExecutionError


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE tgt (id bigint NOT NULL, qty bigint, s text)")
    cl.execute("SELECT create_distributed_table('tgt', 'id', 4)")
    cl.execute("CREATE TABLE src (id bigint NOT NULL, qty bigint)")
    cl.execute("SELECT create_distributed_table('src', 'id', 4)")
    cl.copy_from("tgt", rows=[(i, i * 10, "old") for i in range(10)])
    cl.copy_from("src", rows=[(i, 1000 + i) for i in range(5, 15)])
    return cl


def test_merge_update_and_insert(db):
    cl = db
    r = cl.execute("""
        MERGE INTO tgt t USING src s ON t.id = s.id
        WHEN MATCHED THEN UPDATE SET qty = s.qty, s = 'upd'
        WHEN NOT MATCHED THEN INSERT (id, qty, s) VALUES (s.id, s.qty, 'new')""")
    assert r.explain == {"updated": 5, "deleted": 0, "inserted": 5}
    rows = dict((k, (q, s_)) for k, q, s_ in
                cl.execute("SELECT id, qty, s FROM tgt ORDER BY id").rows)
    assert rows[4] == (40, "old")        # untouched
    assert rows[5] == (1005, "upd")      # updated
    assert rows[14] == (1014, "new")     # inserted
    assert len(rows) == 15


def test_merge_delete(db):
    cl = db
    r = cl.execute("""
        MERGE INTO tgt t USING src s ON t.id = s.id
        WHEN MATCHED AND s.qty > 1007 THEN DELETE""")
    assert r.explain["deleted"] == 2  # ids 8, 9 matched with qty 1008/1009
    assert cl.execute("SELECT count(*) FROM tgt").rows == [(8,)]


def test_merge_duplicate_source_match_errors(db):
    cl = db
    cl.execute("CREATE TABLE dup (id bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('dup', 'id', 2)")
    cl.copy_from("dup", rows=[(5, 1), (5, 2)])  # two source rows match id 5
    with pytest.raises(ExecutionError):
        cl.execute("MERGE INTO tgt t USING dup d ON t.id = d.id "
                   "WHEN MATCHED THEN UPDATE SET qty = d.v")


def test_merge_do_nothing_and_condition(db):
    cl = db
    r = cl.execute("""
        MERGE INTO tgt t USING src s ON t.id = s.id
        WHEN MATCHED AND s.qty < 1007 THEN UPDATE SET qty = 0
        WHEN NOT MATCHED THEN DO NOTHING""")
    assert r.explain["updated"] == 2  # ids 5, 6
    # + id 0 whose original qty was already 0
    assert cl.execute("SELECT count(*) FROM tgt WHERE qty = 0").rows == [(3,)]
    assert cl.execute("SELECT count(*) FROM tgt").rows == [(10,)]
