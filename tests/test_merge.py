"""MERGE statement vs sqlite oracle (sqlite supports UPSERT, so the
oracle is hand-computed or expressed with equivalent statements)."""

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import ExecutionError


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE tgt (id bigint NOT NULL, qty bigint, s text)")
    cl.execute("SELECT create_distributed_table('tgt', 'id', 4)")
    cl.execute("CREATE TABLE src (id bigint NOT NULL, qty bigint)")
    cl.execute("SELECT create_distributed_table('src', 'id', 4)")
    cl.copy_from("tgt", rows=[(i, i * 10, "old") for i in range(10)])
    cl.copy_from("src", rows=[(i, 1000 + i) for i in range(5, 15)])
    return cl


def test_merge_update_and_insert(db):
    cl = db
    r = cl.execute("""
        MERGE INTO tgt t USING src s ON t.id = s.id
        WHEN MATCHED THEN UPDATE SET qty = s.qty, s = 'upd'
        WHEN NOT MATCHED THEN INSERT (id, qty, s) VALUES (s.id, s.qty, 'new')""")
    assert r.explain == {"updated": 5, "deleted": 0, "inserted": 5}
    rows = dict((k, (q, s_)) for k, q, s_ in
                cl.execute("SELECT id, qty, s FROM tgt ORDER BY id").rows)
    assert rows[4] == (40, "old")        # untouched
    assert rows[5] == (1005, "upd")      # updated
    assert rows[14] == (1014, "new")     # inserted
    assert len(rows) == 15


def test_merge_delete(db):
    cl = db
    r = cl.execute("""
        MERGE INTO tgt t USING src s ON t.id = s.id
        WHEN MATCHED AND s.qty > 1007 THEN DELETE""")
    assert r.explain["deleted"] == 2  # ids 8, 9 matched with qty 1008/1009
    assert cl.execute("SELECT count(*) FROM tgt").rows == [(8,)]


def test_merge_duplicate_source_match_errors(db):
    cl = db
    cl.execute("CREATE TABLE dup (id bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('dup', 'id', 2)")
    cl.copy_from("dup", rows=[(5, 1), (5, 2)])  # two source rows match id 5
    with pytest.raises(ExecutionError):
        cl.execute("MERGE INTO tgt t USING dup d ON t.id = d.id "
                   "WHEN MATCHED THEN UPDATE SET qty = d.v")


def test_merge_do_nothing_and_condition(db):
    cl = db
    r = cl.execute("""
        MERGE INTO tgt t USING src s ON t.id = s.id
        WHEN MATCHED AND s.qty < 1007 THEN UPDATE SET qty = 0
        WHEN NOT MATCHED THEN DO NOTHING""")
    assert r.explain["updated"] == 2  # ids 5, 6
    # + id 0 whose original qty was already 0
    assert cl.execute("SELECT count(*) FROM tgt WHERE qty = 0").rows == [(3,)]
    assert cl.execute("SELECT count(*) FROM tgt").rows == [(10,)]


def test_merge_text_values_and_conditions(db):
    cl = db
    cl.execute("CREATE TABLE ev (id bigint NOT NULL, tag text, n bigint)")
    cl.execute("SELECT create_distributed_table('ev', 'id', 4)")
    cl.copy_from("ev", rows=[(1, "hot", 5), (2, "cold", 3), (3, "hot", 1)])
    cl.execute("CREATE TABLE delta (id bigint NOT NULL, n bigint)")
    cl.execute("SELECT create_distributed_table('delta', 'id', 4)")
    cl.copy_from("delta", rows=[(1, 10), (3, 30), (9, 90)])
    r = cl.execute("""
        MERGE INTO ev e USING delta d ON e.id = d.id
        WHEN MATCHED AND e.tag = 'hot' THEN UPDATE SET n = d.n, tag = 'warm'
        WHEN NOT MATCHED THEN INSERT (id, tag, n) VALUES (d.id, 'fresh', d.n)""")
    assert r.explain == {"updated": 2, "deleted": 0, "inserted": 1}
    rows = {k: (t, n) for k, t, n in
            cl.execute("SELECT id, tag, n FROM ev ORDER BY id").rows}
    assert rows[1] == ("warm", 10)
    assert rows[2] == ("cold", 3)   # condition excluded it
    assert rows[3] == ("warm", 30)
    assert rows[9] == ("fresh", 90)


def test_merge_is_one_transaction(db):
    """A fault during the merge commit leaves the target untouched or
    fully merged — never half."""
    from citus_tpu.testing.faults import FAULTS, FaultError
    cl = db
    before = cl.execute("SELECT count(*), sum(qty) FROM tgt").rows
    FAULTS.arm("catalog_commit", error=FaultError("boom"), times=1)
    import pytest as _pt
    with _pt.raises(FaultError):
        cl.execute("""
            MERGE INTO tgt t USING src s ON t.id = s.id
            WHEN MATCHED THEN UPDATE SET qty = 0
            WHEN NOT MATCHED THEN INSERT (id, qty, s) VALUES (s.id, 0, 'x')""")
    FAULTS.disarm()
    cl.execute("SELECT recover_prepared_transactions()")
    after = cl.execute("SELECT count(*), sum(qty) FROM tgt").rows
    merged = [(15, sum(0 for _ in range(15)))]
    assert after == before or (after[0][0] == 15
                               and after[0][1] == 0), (before, after)


def test_merge_insert_only(db):
    cl = db
    cl.execute("DELETE FROM tgt WHERE id >= 5")
    r = cl.execute("""
        MERGE INTO tgt t USING src s ON t.id = s.id
        WHEN NOT MATCHED THEN INSERT (id, qty, s) VALUES (s.id, s.qty, 'ins')""")
    assert r.explain == {"updated": 0, "deleted": 0, "inserted": 10}
    assert cl.execute("SELECT count(*) FROM tgt").rows == [(15,)]


def test_merge_respects_unique_index(tmp_path):
    """Round 4: MERGE on unique-indexed targets is allowed and enforced
    (pre-commit delete-aware probe; replaced rows don't self-conflict)."""
    import citus_tpu as ct
    from citus_tpu.integrity import UniqueViolation
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE tgt (k bigint PRIMARY KEY, v bigint)")
    cl.execute("CREATE TABLE src (k bigint, v bigint)")
    cl.copy_from("tgt", rows=[(1, 10), (2, 20)])
    cl.copy_from("src", rows=[(1, 11), (3, 30)])
    # matched update (self-replacement of k=1) + unmatched insert (k=3)
    r = cl.execute(
        "MERGE INTO tgt t USING src s ON t.k = s.k "
        "WHEN MATCHED THEN UPDATE SET v = s.v "
        "WHEN NOT MATCHED THEN INSERT (k, v) VALUES (s.k, s.v)")
    assert r.explain == {"updated": 1, "deleted": 0, "inserted": 1}
    assert sorted(cl.execute("SELECT k, v FROM tgt").rows) == \
        [(1, 11), (2, 20), (3, 30)]
    # an insert arm that would duplicate an existing key aborts atomically
    cl.execute("CREATE TABLE src2 (k bigint, v bigint)")
    cl.copy_from("src2", rows=[(9, 90)])
    with pytest.raises(UniqueViolation):
        cl.execute(
            "MERGE INTO tgt t USING src2 s ON t.k = s.k + 1000 "
            "WHEN NOT MATCHED THEN INSERT (k, v) VALUES (2, s.v)")
    assert sorted(cl.execute("SELECT k, v FROM tgt").rows) == \
        [(1, 11), (2, 20), (3, 30)]


def test_merge_text_insert_remaps_dictionaries(tmp_path):
    """Source text codes live in the source table's dictionary; MERGE
    must re-encode them into the target's (the reviewer's repro: 'bob'
    silently became NULL before the remap)."""
    import citus_tpu as ct
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE tgt (k bigint, name text)")
    cl.execute("CREATE TABLE src (k bigint, name text)")
    cl.copy_from("tgt", rows=[(1, "alice")])
    cl.copy_from("src", rows=[(2, "bob"), (3, "alice")])
    cl.execute("MERGE INTO tgt t USING src s ON t.k = s.k "
               "WHEN NOT MATCHED THEN INSERT (k, name) VALUES (s.k, s.name)")
    assert sorted(cl.execute("SELECT k, name FROM tgt").rows) == \
        [(1, "alice"), (2, "bob"), (3, "alice")]
    # matched text assignment remaps too
    cl.execute("MERGE INTO tgt t USING src s ON t.k = s.k "
               "WHEN MATCHED THEN UPDATE SET name = s.name")
    assert sorted(cl.execute("SELECT k, name FROM tgt").rows) == \
        [(1, "alice"), (2, "bob"), (3, "alice")]


def test_merge_text_unique_and_on_keys_fail_closed(tmp_path):
    import citus_tpu as ct
    from citus_tpu.errors import UnsupportedFeatureError
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE tgt (name text PRIMARY KEY, v bigint)")
    cl.execute("CREATE TABLE src (name text, v bigint)")
    cl.copy_from("tgt", rows=[("alice", 1)])
    cl.copy_from("src", rows=[("zed", 2), ("alice", 3)])
    # text ON keys: codes are incomparable across dictionaries
    with pytest.raises(UnsupportedFeatureError, match="text join keys"):
        cl.execute("MERGE INTO tgt t USING src s ON t.name = s.name "
                   "WHEN MATCHED THEN UPDATE SET v = s.v")
    # but a remapped text INSERT through a non-text key enforces UNIQUE
    from citus_tpu.integrity import UniqueViolation
    with pytest.raises(UniqueViolation):
        cl.execute("MERGE INTO tgt t USING src s ON t.v = s.v "
                   "WHEN NOT MATCHED THEN INSERT (name, v) "
                   "VALUES (s.name, s.v)")  # src has 'alice' -> duplicate
    assert cl.execute("SELECT count(*) FROM tgt").rows == [(1,)]
