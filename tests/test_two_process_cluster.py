"""Two-OS-process cluster fixture (VERDICT #10): authority failover and
2PC recovery across REAL process kills — not same-process threads.

Reference: the reference tests multi-node behavior with real postgres
processes (pg_regress_multi.pl) and exercises 2PC recovery by killing
connections mid-commit (mitmproxy harness); node promotion is
operations/node_promotion.c.
"""

import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import citus_tpu as ct

ENV = dict(os.environ, JAX_PLATFORMS="cpu")


def _spawn(code: str) -> subprocess.Popen:
    body = "import jax\njax.config.update('jax_platforms','cpu')\n" + code
    return subprocess.Popen([sys.executable, "-c", body],
                            stdout=subprocess.PIPE, text=True, env=ENV)


def test_authority_failover_after_process_kill(tmp_path):
    """The metadata authority dies (SIGKILL); an attached coordinator's
    maintenance duty promotes itself under the shared-FS arbiter and
    DDL keeps working."""
    d = str(tmp_path / "db")
    auth = _spawn(textwrap.dedent(f"""
        import sys, time
        import citus_tpu as ct
        a = ct.Cluster({d!r}, serve_port=0)
        print("PORT", a.control_port, flush=True)
        sys.stdout.close()
        time.sleep(120)
    """))
    try:
        line = auth.stdout.readline().split()
        assert line and line[0] == "PORT"
        port = int(line[1])
        b = ct.Cluster(d, coordinator=("127.0.0.1", port))
        b.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
        b.execute("SELECT create_distributed_table('t', 'k', 4)")
        b.copy_from("t", columns={"k": np.arange(100),
                                  "v": np.arange(100)})
        assert b._control.ensure_authority() == "ok"
        # kill the authority outright — no clean shutdown
        auth.kill()
        auth.wait()
        deadline = time.monotonic() + 15
        status = None
        while time.monotonic() < deadline:
            status = b._control.ensure_authority()
            if status in ("promoted", "repointed"):
                break
            time.sleep(0.2)
        assert status == "promoted", f"failover did not happen: {status}"
        # we are the authority now: DDL + queries keep working
        b.execute("CREATE TABLE t2 (k bigint NOT NULL)")
        assert b.catalog.has_table("t2")
        assert b.execute("SELECT count(*) FROM t").rows == [(100,)]
        assert b._control.server is not None
        b.close()
    finally:
        if auth.poll() is None:
            auth.kill()
            auth.wait()


def test_2pc_rolls_forward_after_committed_process_killed(tmp_path):
    """A coordinator process is SIGKILLed after writing PREPARED +
    COMMITTED but before flipping staged stripes live; a second process
    sharing the data dir recovers the transaction FORWARD."""
    d = str(tmp_path / "db")
    # set up the table from the main process first
    setup = ct.Cluster(d, n_nodes=2)
    setup.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    setup.execute("SELECT create_distributed_table('t', 'k', 4)")
    setup.close()
    child = _spawn(textwrap.dedent(f"""
        import os, sys
        import numpy as np
        import citus_tpu as ct
        from citus_tpu.ingest import TableIngestor, encode_columns
        from citus_tpu.transaction.manager import TxState
        cl = ct.Cluster({d!r})
        t = cl.catalog.table("t")
        values, validity = encode_columns(cl.catalog, t, {{
            "k": np.arange(500, dtype=np.int64),
            "v": np.ones(500, dtype=np.int64)}})
        ing = TableIngestor(cl.catalog, t, txlog=cl.txlog)
        ing.append(values, validity)
        for w in ing._writers.values():
            w.flush()
        t.version += 1
        cl.catalog.commit()
        dirs = [w.directory for w in ing._writers.values()]
        cl.txlog.log(ing.xid, TxState.PREPARED,
                     {{"kind": "ingest", "table": "t",
                       "placements": dirs}})
        cl.txlog.log(ing.xid, TxState.COMMITTED, {{"table": "t"}})
        print("STAGED", os.getpid(), flush=True)
        sys.stdout.flush()
        import time
        time.sleep(120)  # killed here: before flipping stripes live
    """))
    try:
        line = child.stdout.readline().split()
        assert line and line[0] == "STAGED"
        child.kill()
        child.wait()
        # a surviving coordinator recovers on open (recovery runs at
        # Cluster construction, the maintenance-daemon-startup analog)
        cl = ct.Cluster(d)
        assert cl.execute("SELECT count(*), sum(v) FROM t").rows == \
            [(500, 500)]
        cl.close()
    finally:
        if child.poll() is None:
            child.kill()
            child.wait()


def test_2pc_rolls_back_after_prepared_only_process_killed(tmp_path):
    """SIGKILL after PREPARED with no COMMITTED record: the survivor
    rolls the transaction BACK (reference: RecoverTwoPhaseCommits
    aborts prepared transactions without a commit record)."""
    d = str(tmp_path / "db")
    setup = ct.Cluster(d, n_nodes=2)
    setup.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    setup.execute("SELECT create_distributed_table('t', 'k', 4)")
    setup.copy_from("t", columns={"k": np.arange(50),
                                  "v": np.zeros(50, np.int64)})
    setup.close()
    child = _spawn(textwrap.dedent(f"""
        import os, sys, time
        import numpy as np
        import citus_tpu as ct
        from citus_tpu.ingest import TableIngestor, encode_columns
        from citus_tpu.transaction.manager import TxState
        cl = ct.Cluster({d!r})
        t = cl.catalog.table("t")
        values, validity = encode_columns(cl.catalog, t, {{
            "k": np.arange(100, 200, dtype=np.int64),
            "v": np.ones(100, dtype=np.int64)}})
        ing = TableIngestor(cl.catalog, t, txlog=cl.txlog)
        ing.append(values, validity)
        for w in ing._writers.values():
            w.flush()
        dirs = [w.directory for w in ing._writers.values()]
        cl.txlog.log(ing.xid, TxState.PREPARED,
                     {{"kind": "ingest", "table": "t",
                       "placements": dirs}})
        print("PREPARED", os.getpid(), flush=True)
        sys.stdout.flush()
        time.sleep(120)  # killed here: prepared, never committed
    """))
    try:
        line = child.stdout.readline().split()
        assert line and line[0] == "PREPARED"
        child.kill()
        child.wait()
        cl = ct.Cluster(d)
        from citus_tpu.transaction.recovery import recover_transactions
        st = recover_transactions(cl.catalog, cl.txlog)
        # either the open-time recovery or this explicit pass rolled it
        # back; the staged rows must never become visible
        assert cl.execute("SELECT count(*) FROM t").rows == [(50,)]
        assert cl.execute("SELECT sum(v) FROM t").rows == [(0,)]
        cl.close()
    finally:
        if child.poll() is None:
            child.kill()
            child.wait()


def test_concurrent_writes_from_two_processes(tmp_path):
    """Two coordinator processes ingest into one table concurrently;
    cross-process flocks serialize correctly and nothing is lost."""
    d = str(tmp_path / "db")
    setup = ct.Cluster(d, n_nodes=2)
    setup.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    setup.execute("SELECT create_distributed_table('t', 'k', 4)")
    setup.close()
    workers = []
    for w in range(2):
        workers.append(_spawn(textwrap.dedent(f"""
            import numpy as np
            import citus_tpu as ct
            cl = ct.Cluster({d!r})
            for i in range(5):
                base = {w} * 50_000 + i * 10_000
                cl.copy_from("t", columns={{
                    "k": np.arange(base, base + 10_000, dtype=np.int64),
                    "v": np.ones(10_000, dtype=np.int64)}})
            cl.close()
            print("DONE", flush=True)
        """)))
    try:
        for p in workers:
            out = p.stdout.readline().strip()
            assert out == "DONE", f"worker failed: {out!r}"
            p.wait(timeout=30)
    finally:
        for p in workers:
            if p.poll() is None:
                p.kill()
                p.wait()
    cl = ct.Cluster(d)
    assert cl.execute("SELECT count(*), sum(v) FROM t").rows == \
        [(100_000, 100_000)]
    cl.close()
