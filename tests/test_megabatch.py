"""Query megabatching — same-family coalescing into one device dispatch
(executor/megabatch.py).

Covers the ISSUE-7 acceptance matrix: K threads coalesce (occupancy >
1 with per-query stat attribution), divergent shard pruning
sub-batches, window=0 is row-identical to the batched path across the
oracle suite, a mid-batch per-query error isolates to its caller, and
an injected per-dispatch delay proves batched throughput >= 2x serial.
"""

import threading
import time

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.testing.faults import FAULTS, FaultError


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, s text, d decimal(8,2))")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={
        "k": np.arange(2000), "v": np.arange(2000) % 50,
        "s": [f"n{i % 5}" for i in range(2000)],
        "d": np.arange(2000) / 4})
    yield cl
    FAULTS.disarm()
    cl.close()


def _delta(c0, c1, key):
    return c1.get(key, 0) - c0.get(key, 0)


def _fanout(cl, sqls, n_threads=None):
    """Run one SQL per thread (or the same SQL K times), barrier-synced
    so they land inside one coalescing window.  -> (results, errors)."""
    if isinstance(sqls, str):
        sqls = [sqls] * n_threads
    results, errors = {}, {}
    bar = threading.Barrier(len(sqls))

    def run(i, sql):
        bar.wait()
        try:
            results[i] = cl.execute(sql).rows
        except Exception as e:  # noqa: BLE001 - recorded for assertions
            errors[i] = e
    ts = [threading.Thread(target=run, args=(i, s))
          for i, s in enumerate(sqls)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return results, errors


def test_same_family_queries_coalesce(db):
    cl = db
    sql = "SELECT sum(v), count(*) FROM t WHERE k = 42"
    base = cl.execute(sql).rows           # serial baseline (window=0)
    cl.execute("SET citus.megabatch_window_ms = 1000")
    cl.execute("SET citus.megabatch_max_size = 6")
    cl.execute("SELECT citus_stat_statements_reset()")
    c0 = cl.counters.snapshot()
    results, errors = _fanout(cl, sql, 6)
    c1 = cl.counters.snapshot()
    assert errors == {}
    assert all(results[i] == base for i in range(6))
    # 6 queries rode strictly fewer dispatches (a full batch of 6 cuts
    # the window short, so normally exactly one)
    assert _delta(c0, c1, "megabatch_queries") == 6
    assert 1 <= _delta(c0, c1, "megabatch_batches") < 6
    assert _delta(c0, c1, "megabatch_fallbacks") == 0
    # coalescing waits book under megabatch_wait, never device_round
    assert _delta(c0, c1, "wait_megabatch_ms") > 0
    # per-QUERY stat attribution survives batching: the family books
    # one citus_stat_statements entry per issuing statement
    ss = {row[0]: row for row in cl.execute(
        "SELECT citus_stat_statements()").rows}
    fam = [row for q, row in ss.items() if "k = ?" in q or "k = 42" in q]
    assert fam and fam[0][3] == 6, fam       # calls column
    # occupancy accounting: the dispatcher saw one batch of 6 and the
    # admission pool served 5 of the 6 without a slot of their own
    mb = cl.execute("SELECT citus_megabatch_stats()").rows[0]
    assert mb[3] >= 6                        # queries
    pool = cl.execute("SELECT citus_stat_pool()").rows[0]
    assert pool[6] >= 5                      # coalesced column


def test_divergent_shards_sub_batch(db):
    cl = db
    # k=7, 13, 42 hash to distinct shards of 4 (deterministic); the
    # family coalesces into ONE queue but dispatches per shard set
    keys = (7, 13, 42)
    base = {k: cl.execute(
        f"SELECT sum(v), count(*) FROM t WHERE k = {k}").rows for k in keys}
    cl.execute("SET citus.megabatch_window_ms = 1000")
    cl.execute("SET citus.megabatch_max_size = 3")
    c0 = cl.counters.snapshot()
    results, errors = _fanout(
        cl, [f"SELECT sum(v), count(*) FROM t WHERE k = {k}" for k in keys])
    c1 = cl.counters.snapshot()
    assert errors == {}
    assert all(results[i] == base[k] for i, k in enumerate(keys))
    assert _delta(c0, c1, "megabatch_queries") == 3
    # sub-batched by placement: more than one dispatch, zero fallbacks,
    # and every query still returned ITS OWN shard's rows
    assert _delta(c0, c1, "megabatch_batches") >= 2
    assert _delta(c0, c1, "megabatch_fallbacks") == 0


ORACLE_SUITE = [
    "SELECT sum(v), count(*) FROM t WHERE k = 42",
    "SELECT v, s FROM t WHERE k = 13",
    "SELECT count(*) FROM t WHERE s = 'n3'",
    "SELECT sum(d), min(v) FROM t WHERE k BETWEEN 10 AND 20",
    "SELECT min(v), max(v) FROM t WHERE k >= 1990",
    "SELECT v, count(*) FROM t WHERE v < 5 AND k < 100 GROUP BY v ORDER BY v",
    "SELECT k, v FROM t WHERE k > 1995 ORDER BY k",
]


def test_window_zero_identical_to_batched_path(db):
    cl = db
    # window=0 (default): serial path, byte-identical to pre-megabatch
    serial = [cl.execute(q).rows for q in ORACLE_SUITE]
    # window>0 solo: every query rides the batched runners (occupancy
    # 1), including the interval-free shared scan — rows must match the
    # serial path row-for-row
    cl.execute("SET citus.megabatch_window_ms = 30")
    c0 = cl.counters.snapshot()
    batched = [cl.execute(q).rows for q in ORACLE_SUITE]
    c1 = cl.counters.snapshot()
    assert batched == serial
    # the suite really exercised the batched path
    assert _delta(c0, c1, "megabatch_queries") >= len(ORACLE_SUITE) - 1


def test_mid_batch_error_isolates_to_its_caller(db):
    cl = db
    keys = (7, 13, 42)
    base = {k: cl.execute(
        f"SELECT sum(v) FROM t WHERE k = {k}").rows for k in keys}
    cl.execute("SET citus.megabatch_window_ms = 1000")
    cl.execute("SET citus.megabatch_max_size = 3")
    # per-query failure injected at the caller-side scatter, keyed by
    # router key: only k=42's caller may see it
    FAULTS.arm("megabatch_finalize", error=FaultError("scatter boom"),
               match=":42", times=1)
    try:
        results, errors = _fanout(
            cl, [f"SELECT sum(v) FROM t WHERE k = {k}" for k in keys])
    finally:
        FAULTS.disarm("megabatch_finalize")
    assert list(errors) == [2], (errors, results)
    assert isinstance(errors[2], FaultError)
    for i, k in enumerate(keys[:2]):
        assert results[i] == base[k]


def test_batched_throughput_beats_serial_2x(db):
    cl = db
    sql = "SELECT sum(v), count(*) FROM t WHERE k = 42"
    K, R = 6, 3

    def storm():
        bar = threading.Barrier(K)

        def run():
            bar.wait()
            for _ in range(R):
                cl.execute(sql)
        ts = [threading.Thread(target=run) for _ in range(K)]
        t0 = time.monotonic()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return time.monotonic() - t0

    # warm both paths (compile + device cache) before arming the delay
    cl.execute(sql)
    cl.execute("SET citus.megabatch_window_ms = 300")
    cl.execute("SET citus.megabatch_max_size = 6")
    cl.execute(sql)
    cl.execute("SET citus.megabatch_window_ms = 0")
    # a fixed per-dispatch cost: hit under the kernel lock, so serial
    # same-family queries pay it K*R times end to end while coalesced
    # rounds pay it once per batch
    FAULTS.arm("kernel_dispatch", delay_s=0.03)
    try:
        serial_wall = storm()
        cl.execute("SET citus.megabatch_window_ms = 300")
        batched_wall = storm()
    finally:
        FAULTS.disarm("kernel_dispatch")
    assert batched_wall * 2 <= serial_wall, (batched_wall, serial_wall)


def test_explain_analyze_shows_batch_line(db):
    cl = db
    cl.execute("SET citus.megabatch_window_ms = 30")
    r = cl.execute("EXPLAIN ANALYZE SELECT sum(v) FROM t WHERE k = 7")
    lines = [row[0] for row in r.rows]
    batch = [ln for ln in lines if ln.strip().startswith("Batch:")]
    assert batch, lines
    assert "occupancy 1" in batch[0] and "window 30" in batch[0]


def test_megabatch_gucs_round_trip(db):
    cl = db
    cl.execute("SET citus.megabatch_window_ms = 12.5")
    cl.execute("SET citus.megabatch_max_size = 9")
    assert float(cl.execute("SHOW citus.megabatch_window_ms").rows[0][0]) \
        == 12.5
    assert int(cl.execute("SHOW citus.megabatch_max_size").rows[0][0]) == 9
    assert cl.settings.executor.megabatch_window_ms == 12.5
    assert cl.settings.executor.megabatch_max_size == 9
    r = cl.execute("SELECT citus_megabatch_stats()")
    assert r.columns[:5] == ["window_ms", "max_size", "batches", "queries",
                             "fallbacks"]
    cl.execute("SET citus.megabatch_window_ms = 0")
    assert cl.settings.executor.megabatch_window_ms == 0.0


# ------------------------------------------------- adaptive auto window


def _mb_stats(cl):
    r = cl.execute("SELECT citus_megabatch_stats()")
    return dict(zip(r.columns, r.rows[0]))


def test_auto_window_beats_fixed_under_bursty_arrivals(db):
    """SET citus.megabatch_window_ms = auto sizes the wait from the plan
    family's inter-arrival EWMA: under a bursty storm it still
    coalesces (occupancy > 1) but never parks queries for a whole
    oversized fixed window, so wall time is <= the fixed configuration
    on the same workload."""
    cl = db
    sql = "SELECT sum(v), count(*) FROM t WHERE k = 42"
    K, R = 6, 4
    cl.execute(sql)  # warm compile + device caches
    cl.execute("SET citus.megabatch_max_size = 32")

    def storm():
        bar = threading.Barrier(K)

        def run():
            bar.wait()
            for _ in range(R):
                cl.execute(sql)
        ts = [threading.Thread(target=run) for _ in range(K)]
        t0 = time.monotonic()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return time.monotonic() - t0

    cl.execute("SET citus.megabatch_window_ms = auto")
    s0 = _mb_stats(cl)
    auto_wall = storm()
    s1 = _mb_stats(cl)
    # the bursty family coalesced under auto: batched queries
    # outnumber batches (occupancy > 1 on average)
    assert s1["queries"] - s0["queries"] > s1["batches"] - s0["batches"], \
        (s0, s1)
    # fixed oversized window: every round parks for the full window
    # (max_size 32 means the batch never fills early)
    cl.execute("SET citus.megabatch_window_ms = 40")
    fixed_wall = storm()
    assert auto_wall <= fixed_wall, (auto_wall, fixed_wall)


def test_auto_window_sparse_family_stays_serial(db):
    """A family arriving slower than the sparseness threshold pays no
    window at all under auto: maybe_megabatch bows out pre-queue, so
    megabatch counters do not move."""
    cl = db
    sql = "SELECT sum(v) FROM t WHERE k = 7"
    expected = cl.execute(sql).rows
    cl.execute("SET citus.megabatch_window_ms = auto")
    s0 = _mb_stats(cl)
    for _ in range(5):
        assert cl.execute(sql).rows == expected
        time.sleep(0.03)  # above _AUTO_SPARSE_S: the family is sparse
    s1 = _mb_stats(cl)
    assert s1["queries"] == s0["queries"], (s0, s1)
    assert s1["batches"] == s0["batches"], (s0, s1)
