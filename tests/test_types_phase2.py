"""Type breadth phase 1 (round-5 VERDICT #5): timestamptz, interval,
uuid, bytea, 1-D arrays + unnest, and the SUM overflow guard.

Reference: the columnar AM stores arbitrary PG datums
(columnar/columnar_tableam.c:718) and commands/type.c propagates type
DDL; here every variable-width type is dictionary-encoded with
kind-specific canonicalization (types.py normalize_word/render_word).
"""

import datetime
import uuid as uuid_mod

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import AnalysisError, ExecutionError

UTC = datetime.timezone.utc


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"))
    yield c
    c.close()


class TestTimestamptz:
    def test_round_trip_and_utc_normalization(self, cl):
        cl.execute("CREATE TABLE e (k bigint NOT NULL, at timestamptz)")
        cl.execute("SELECT create_distributed_table('e', 'k', 4)")
        cl.copy_from("e", rows=[
            (1, "2024-06-01T12:00:00+02:00"),   # 10:00 UTC
            (2, "2024-06-01 10:00:00+00:00"),   # same instant
            (3, datetime.datetime(2024, 6, 1, 5, 0, tzinfo=datetime.timezone(
                datetime.timedelta(hours=-5)))),  # also 10:00 UTC
            (4, None)])
        rows = cl.execute("SELECT k, at FROM e ORDER BY k").rows
        want = datetime.datetime(2024, 6, 1, 10, 0, tzinfo=UTC)
        assert rows[0][1] == rows[1][1] == rows[2][1] == want
        assert rows[3][1] is None
        # identical instants compare equal regardless of written offset
        assert cl.execute(
            "SELECT count(*) FROM e WHERE at = timestamptz "
            "'2024-06-01 12:00:00+02:00'").rows == [(3,)]

    def test_sql_type_spelling_with_time_zone(self, cl):
        cl.execute("CREATE TABLE w (k bigint, at timestamp with time zone,"
                   " plain timestamp without time zone)")
        t = cl.catalog.table("w")
        assert t.schema.column("at").type.kind == "timestamptz"
        assert t.schema.column("plain").type.kind == "timestamp"

    def test_range_filter_and_extract(self, cl):
        cl.execute("CREATE TABLE r (k bigint NOT NULL, at timestamptz)")
        cl.execute("SELECT create_distributed_table('r', 'k', 4)")
        base = datetime.datetime(2024, 1, 1, tzinfo=UTC)
        cl.copy_from("r", rows=[
            (i, base + datetime.timedelta(hours=i)) for i in range(48)])
        assert cl.execute(
            "SELECT count(*) FROM r WHERE at >= '2024-01-02 00:00:00+00'"
        ).rows == [(24,)]
        r = cl.execute("SELECT extract(day FROM at), count(*) FROM r "
                       "GROUP BY 1 ORDER BY 1").rows
        assert r == [(1, 24), (2, 24)]


class TestInterval:
    def test_column_round_trip_and_comparison(self, cl):
        cl.execute("CREATE TABLE iv (k bigint NOT NULL, d interval)")
        cl.execute("SELECT create_distributed_table('iv', 'k', 4)")
        cl.copy_from("iv", rows=[
            (1, "90 minutes"), (2, datetime.timedelta(days=1)),
            (3, "1 day 02:30:00"), (4, "-3 hours"), (5, None)])
        rows = dict(cl.execute("SELECT k, d FROM iv").rows)
        assert rows[1] == datetime.timedelta(minutes=90)
        assert rows[2] == datetime.timedelta(days=1)
        assert rows[3] == datetime.timedelta(days=1, hours=2, minutes=30)
        assert rows[4] == datetime.timedelta(hours=-3)
        assert rows[5] is None
        assert cl.execute(
            "SELECT count(*) FROM iv WHERE d > interval '1 hour'"
        ).rows == [(3,)]
        assert cl.execute(
            "SELECT count(*) FROM iv WHERE d = interval '90' minute"
        ).rows == [(1,)]

    def test_timestamptz_plus_interval(self, cl):
        cl.execute("CREATE TABLE tz (k bigint NOT NULL, at timestamptz)")
        cl.execute("SELECT create_distributed_table('tz', 'k', 4)")
        cl.copy_from("tz", rows=[(1, "2024-06-01 10:00:00+00")])
        assert cl.execute(
            "SELECT count(*) FROM tz WHERE at + interval '2 hours' = "
            "timestamptz '2024-06-01 12:00:00+00'").rows == [(1,)]

    def test_month_components_rejected_for_columns(self, cl):
        cl.execute("CREATE TABLE mi (k bigint, d interval)")
        with pytest.raises(AnalysisError, match="month"):
            cl.copy_from("mi", rows=[(1, "3 months")])


class TestUuid:
    def test_round_trip_and_case_insensitive_equality(self, cl):
        cl.execute("CREATE TABLE u (k bigint NOT NULL, id uuid)")
        cl.execute("SELECT create_distributed_table('u', 'k', 4)")
        a = "a0eebc99-9c0b-4ef8-bb6d-6bb9bd380a11"
        cl.copy_from("u", rows=[
            (1, a), (2, a.upper()), (3, uuid_mod.UUID(a)),
            (4, "b1ffcd00-0000-4000-8000-000000000001"), (5, None)])
        # all three spellings share one canonical dictionary word
        assert cl.execute(
            f"SELECT count(*) FROM u WHERE id = '{a.upper()}'"
        ).rows == [(3,)]
        assert cl.execute(
            f"SELECT count(*) FROM u WHERE id = uuid '{a}'").rows == [(3,)]
        rows = dict(cl.execute("SELECT k, id FROM u").rows)
        assert rows[1] == rows[2] == rows[3] == a
        assert rows[5] is None
        r = cl.execute("SELECT id, count(*) FROM u WHERE id IS NOT NULL "
                       "GROUP BY id ORDER BY count(*) DESC").rows
        assert r[0] == (a, 3)

    def test_invalid_uuid_rejected(self, cl):
        cl.execute("CREATE TABLE v (k bigint, id uuid)")
        with pytest.raises(AnalysisError, match="uuid"):
            cl.copy_from("v", rows=[(1, "not-a-uuid")])


class TestBytea:
    def test_round_trip_bytes_and_hex(self, cl):
        cl.execute("CREATE TABLE b (k bigint NOT NULL, payload bytea)")
        cl.execute("SELECT create_distributed_table('b', 'k', 4)")
        cl.copy_from("b", rows=[
            (1, b"\x00\x01\xff"), (2, "\\x0001ff"), (3, b"hello"),
            (4, None)])
        rows = dict(cl.execute("SELECT k, payload FROM b").rows)
        assert rows[1] == b"\x00\x01\xff"
        assert rows[2] == b"\x00\x01\xff"  # hex spelling, same value
        assert rows[3] == b"hello"
        assert rows[4] is None
        assert cl.execute(
            "SELECT count(*) FROM b WHERE payload = bytea '\\x0001ff'"
        ).rows == [(2,)]


class TestArrays:
    def test_array_column_round_trip(self, cl):
        cl.execute("CREATE TABLE a (k bigint NOT NULL, tags text[],"
                   " nums bigint[])")
        cl.execute("SELECT create_distributed_table('a', 'k', 4)")
        cl.copy_from("a", rows=[
            (1, ["red", "blue"], [1, 2, 3]),
            (2, ["red", "blue"], [4]),
            (3, [], None)])
        rows = dict((r[0], (r[1], r[2])) for r in
                    cl.execute("SELECT k, tags, nums FROM a").rows)
        assert rows[1] == (["red", "blue"], [1, 2, 3])
        assert rows[2] == (["red", "blue"], [4])
        assert rows[3] == ([], None)
        # equal arrays share one dictionary word -> groupable/comparable
        assert cl.execute(
            "SELECT count(*) FROM a WHERE tags = ARRAY['red', 'blue']"
        ).rows == [(2,)]

    def test_unnest_in_from(self, cl):
        r = cl.execute("SELECT * FROM unnest(ARRAY[3, 1, 2]) AS x")
        assert [row[0] for row in r.rows] == [3, 1, 2]

    def test_unnest_of_column_in_target_list(self, cl):
        cl.execute("CREATE TABLE t (k bigint NOT NULL, tags text[])")
        cl.execute("SELECT create_distributed_table('t', 'k', 4)")
        cl.copy_from("t", rows=[
            (1, ["a", "b"]), (2, ["b", "c"]), (3, None), (4, [])])
        r = cl.execute("SELECT k, unnest(tags) AS tag FROM t ORDER BY k, tag")
        assert r.columns == ["k", "tag"]
        assert r.rows == [(1, "a"), (1, "b"), (2, "b"), (2, "c")]

    def test_unnest_then_requery(self, cl):
        """The reference idiom: unnest + re-aggregate via a derived
        table."""
        cl.execute("CREATE TABLE t (k bigint NOT NULL, tags text[])")
        cl.execute("SELECT create_distributed_table('t', 'k', 4)")
        cl.copy_from("t", rows=[(i, ["x", "y"] if i % 2 else ["x"])
                                for i in range(10)])
        r = cl.execute("SELECT tag, count(*) FROM (SELECT unnest(tags) "
                       "AS tag FROM t) s GROUP BY tag ORDER BY tag")
        assert r.rows == [("x", 10), ("y", 5)]


class TestSumOverflowGuard:
    def test_decimal_sum_overflow_errors(self, cl):
        cl.execute("CREATE TABLE d (k bigint NOT NULL, v decimal(18,4))")
        cl.execute("SELECT create_distributed_table('d', 'k', 4)")
        big = 10 ** 13  # scaled by 1e4 -> 1e17 physical each
        cl.copy_from("d", columns={
            "k": np.arange(200, dtype=np.int64),
            "v": np.full(200, big, np.int64) * 1.0})
        with pytest.raises(ExecutionError, match="out of range"):
            cl.execute("SELECT sum(v) FROM d")

    def test_bigint_sum_overflow_errors(self, cl):
        cl.execute("CREATE TABLE i (k bigint NOT NULL, v bigint)")
        cl.execute("SELECT create_distributed_table('i', 'k', 4)")
        cl.copy_from("i", columns={
            "k": np.arange(100, dtype=np.int64),
            "v": np.full(100, 2 ** 61, np.int64)})
        with pytest.raises(ExecutionError, match="out of range"):
            cl.execute("SELECT sum(v) FROM i")

    def test_sane_sums_unaffected(self, cl):
        cl.execute("CREATE TABLE s (k bigint NOT NULL, v decimal(12,2))")
        cl.execute("SELECT create_distributed_table('s', 'k', 4)")
        cl.copy_from("s", columns={
            "k": np.arange(10_000, dtype=np.int64),
            "v": np.arange(10_000) / 4})
        import decimal
        assert cl.execute("SELECT sum(v) FROM s").rows == \
            [(decimal.Decimal("12498750.00"),)]
        # group-by path carries the shadow slot too
        r = cl.execute("SELECT k % 3, sum(v) FROM s GROUP BY 1 ORDER BY 1")
        assert sum(x[1] for x in r.rows) == decimal.Decimal("12498750.00")


def test_new_types_survive_storage_cdc_and_csv(tmp_path):
    """Round-trip through storage, CDC capture, and COPY TO CSV."""
    import os

    from citus_tpu.config import Settings
    cl = ct.Cluster(str(tmp_path / "db"),
                    settings=Settings(enable_change_data_capture=True))
    cl.execute("CREATE TABLE m (k bigint NOT NULL, id uuid, at timestamptz,"
               " d interval, payload bytea, tags text[])")
    cl.execute("SELECT create_distributed_table('m', 'k', 4)")
    u = "a0eebc99-9c0b-4ef8-bb6d-6bb9bd380a11"
    cl.copy_from("m", rows=[
        (1, u, "2024-06-01 10:00:00+00", "2 hours", b"\x01\x02", ["a"])])
    # survives a cluster reopen (storage round-trip)
    cl.close()
    cl = ct.Cluster(str(tmp_path / "db"),
                    settings=Settings(enable_change_data_capture=True))
    row = cl.execute("SELECT * FROM m").rows[0]
    assert row == (1, u, datetime.datetime(2024, 6, 1, 10, 0, tzinfo=UTC),
                   datetime.timedelta(hours=2), b"\x01\x02", ["a"])
    # CDC captured canonical words
    evs = list(cl.cdc.events("m"))
    assert evs and evs[0]["rows"][0][1] == u
    # CSV export round-trips the canonical spellings
    p = str(tmp_path / "out.csv")
    cl.execute(f"COPY m TO '{p}' WITH (header true)")
    text = open(p).read()
    assert u in text and "\\x0102" in text
    cl.close()


def test_fuzz_new_types_vs_sqlite(tmp_path):
    """Differential coverage for the new types: random filters over
    uuid/timestamptz/interval columns against a sqlite mirror (the
    query-generator oracle pattern of tests/test_fuzz.py)."""
    import random
    import sqlite3

    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE f (k bigint NOT NULL, u uuid,"
               " at timestamptz, d interval)")
    cl.execute("SELECT create_distributed_table('f', 'k', 4)")
    rng = random.Random(42)
    pool = [str(uuid_mod.UUID(int=rng.getrandbits(128), version=4))
            for _ in range(8)]
    base = datetime.datetime(2024, 1, 1, tzinfo=UTC)
    rows, mirror = [], []
    for i in range(2000):
        u = rng.choice(pool + [None])
        at_us = rng.randrange(0, 90 * 86_400_000_000) \
            if rng.random() > 0.05 else None
        d_us = rng.randrange(-10 ** 12, 10 ** 12) \
            if rng.random() > 0.05 else None
        rows.append((
            i, u,
            None if at_us is None else base
            + datetime.timedelta(microseconds=at_us),
            None if d_us is None else datetime.timedelta(microseconds=d_us)))
        mirror.append((i, u, at_us, d_us))
    cl.copy_from("f", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE f (k INTEGER, u TEXT, at INTEGER, d INTEGER)")
    sq.executemany("INSERT INTO f VALUES (?,?,?,?)", mirror)
    base_us = int(base.timestamp() * 1_000_000)
    for trial in range(60):
        r2 = random.Random(1000 + trial)
        kind = r2.randrange(4)
        if kind == 0:
            u = r2.choice(pool)
            spelled = u.upper() if r2.random() < 0.5 else u
            ours = cl.execute(
                f"SELECT count(*) FROM f WHERE u = '{spelled}'").rows[0][0]
            theirs = sq.execute(
                "SELECT count(*) FROM f WHERE u = ?", (u,)).fetchone()[0]
        elif kind == 1:
            cut_us = r2.randrange(0, 90 * 86_400_000_000)
            cut = base + datetime.timedelta(microseconds=cut_us)
            op = r2.choice(["<", ">=", ">"])
            ours = cl.execute(
                f"SELECT count(*) FROM f WHERE at {op} "
                f"'{cut.isoformat()}'").rows[0][0]
            theirs = sq.execute(
                f"SELECT count(*) FROM f WHERE at {op} ?",
                (cut_us,)).fetchone()[0]
        elif kind == 2:
            hrs = r2.randrange(-200, 200)
            op = r2.choice(["<", ">", "<=", ">="])
            ours = cl.execute(
                f"SELECT count(*) FROM f WHERE d {op} interval "
                f"'{hrs} hours'").rows[0][0]
            theirs = sq.execute(
                f"SELECT count(*) FROM f WHERE d {op} ?",
                (hrs * 3_600_000_000,)).fetchone()[0]
        else:
            u = r2.choice(pool)
            ours_rows = cl.execute(
                f"SELECT min(at), max(at) FROM f WHERE u = '{u}' "
                "AND at IS NOT NULL").rows
            got = ours_rows[0]
            t0, t1 = sq.execute(
                "SELECT min(at), max(at) FROM f WHERE u = ? "
                "AND at IS NOT NULL", (u,)).fetchone()
            want = tuple(
                None if x is None else base + datetime.timedelta(
                    microseconds=x) for x in (t0, t1))
            assert got == want, f"trial {trial}: {got} != {want}"
            continue
        assert ours == theirs, f"trial {trial} kind {kind}: {ours} != {theirs}"
    cl.close()


def test_copy_binary_round_trip(tmp_path):
    """COPY WITH (format binary): columnar frames, lossless for every
    type incl. nulls, portable across clusters (words, not dict ids)."""
    cl = ct.Cluster(str(tmp_path / "a"))
    cl.execute("CREATE TABLE src (k bigint NOT NULL, v decimal(12,2),"
               " s text, at timestamptz, id uuid, tags bigint[])")
    cl.execute("SELECT create_distributed_table('src', 'k', 4)")
    u = "a0eebc99-9c0b-4ef8-bb6d-6bb9bd380a11"
    rows = [(i, None if i % 7 == 0 else i / 4,
             None if i % 5 == 0 else f"w{i % 3}",
             datetime.datetime(2024, 1, 1, tzinfo=UTC)
             + datetime.timedelta(minutes=i),
             u if i % 2 else None,
             [i, i + 1]) for i in range(5000)]
    cl.copy_from("src", rows=rows)
    p = str(tmp_path / "dump.bin")
    r = cl.execute(f"COPY src TO '{p}' WITH (format binary)")
    assert r.explain["copied"] == 5000
    # import into a DIFFERENT cluster (fresh dictionaries: id spaces
    # differ, words must carry the data)
    cl2 = ct.Cluster(str(tmp_path / "b"))
    cl2.execute("CREATE TABLE dst (k bigint NOT NULL, v decimal(12,2),"
                " s text, at timestamptz, id uuid, tags bigint[])")
    cl2.execute("SELECT create_distributed_table('dst', 'k', 8)")
    cl2.copy_from("dst", rows=[(99999, 1.0, "seed", None, None, None)])
    r = cl2.execute(f"COPY dst FROM '{p}' WITH (format binary)")
    assert r.explain["copied"] == 5000
    for q in ("SELECT count(*), sum(v) FROM {} WHERE s = 'w1'",
              "SELECT count(*) FROM {} WHERE id = '" + u + "'",
              "SELECT min(at), max(at) FROM {} WHERE k < 5000"):
        assert cl.execute(q.format("src")).rows == \
            cl2.execute(q.format("dst") + " AND k < 99999"
                        if "WHERE" in q else q.format("dst")).rows
    assert cl2.execute("SELECT tags FROM dst WHERE k = 3").rows == \
        [([3, 4],)]
    cl.close()
    cl2.close()


def test_catalog_migration_framework(tmp_path):
    """Versioned document migrations (the 69-SQL-migration analog):
    v0 documents lift through every migration; newer-than-build
    documents are refused."""
    import json
    import os

    from citus_tpu.catalog.migrations import (
        CATALOG_FORMAT_VERSION, migrate_document,
    )
    from citus_tpu.errors import CatalogError
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (k bigint, v bigint)")
    cl.copy_from("t", rows=[(1, 2)])
    doc = cl.catalog.export_document()
    assert doc["format_version"] == CATALOG_FORMAT_VERSION
    cl.close()
    # strip to a v0 (round-3) shape
    doc.pop("format_version")
    for sec in ("extensions", "domains", "collations", "publications",
                "statistics", "domain_columns"):
        doc.pop(sec, None)
    for td in doc["tables"]:
        td.pop("indexes", None)
    path = os.path.join(str(tmp_path / "db"), "catalog.json")
    with open(path, "w") as fh:
        json.dump(doc, fh)
    cl = ct.Cluster(str(tmp_path / "db"))
    assert cl.catalog.table("t").indexes == []
    assert cl.execute("SELECT v FROM t WHERE k = 1").rows == [(2,)]
    # the next commit re-stamps the current version
    cl.execute("CREATE TABLE t2 (x bigint)")
    with open(path) as fh:
        assert json.load(fh)["format_version"] == CATALOG_FORMAT_VERSION
    cl.close()
    # refuse documents from the future
    with pytest.raises(CatalogError, match="newer than this build"):
        migrate_document({"format_version": CATALOG_FORMAT_VERSION + 1})


def test_avg_overflow_guard_not_overstrict(cl):
    """Review finding: avg()'s overflow limit must use the ARGUMENT
    scale, not the +6-digit output scale — legitimate averages of large
    values must not raise."""
    cl.execute("CREATE TABLE big (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('big', 'k', 4)")
    cl.copy_from("big", columns={
        "k": np.arange(5000, dtype=np.int64),
        "v": np.full(5000, 1_000_000_000, np.int64)})
    import decimal
    assert cl.execute("SELECT avg(v) FROM big").rows == \
        [(decimal.Decimal("1000000000.000000"),)]
    # and the guard still fires when the SUM truly leaves int64
    cl.execute("UPDATE big SET v = 4611686018427387904")  # 2^62
    with pytest.raises(ExecutionError, match="out of range"):
        cl.execute("SELECT avg(v) FROM big")


def test_upsert_canonicalizes_uuid_conflict_key(cl):
    """Review finding: a non-canonical uuid spelling must CONFLICT with
    the stored canonical row, not insert a duplicate."""
    cl.execute("CREATE TABLE uc (id uuid NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('uc', 'v', 4)")
    a = "a0eebc99-9c0b-4ef8-bb6d-6bb9bd380a11"
    cl.execute(f"INSERT INTO uc VALUES ('{a}', 1)")
    r = cl.execute(f"INSERT INTO uc VALUES ('{a.upper()}', 1) "
                   "ON CONFLICT (id, v) DO NOTHING")
    assert r.explain.get("skipped") == 1
    assert cl.execute("SELECT count(*) FROM uc").rows == [(1,)]


class TestTimeType:
    def test_time_round_trip_and_filters(self, cl):
        cl.execute("CREATE TABLE sh (k bigint NOT NULL, at time)")
        cl.execute("SELECT create_distributed_table('sh', 'k', 4)")
        cl.copy_from("sh", rows=[
            (1, "09:15:00"), (2, "18:40:11.25"),
            (3, datetime.time(23, 59, 59)), (4, None)])
        rows = dict(cl.execute("SELECT k, at FROM sh").rows)
        assert rows[1] == datetime.time(9, 15)
        assert rows[2] == datetime.time(18, 40, 11, 250000)
        assert rows[3] == datetime.time(23, 59, 59)
        assert rows[4] is None
        assert cl.execute(
            "SELECT count(*) FROM sh WHERE at > '12:00:00'").rows == [(2,)]
        assert cl.execute(
            "SELECT count(*) FROM sh WHERE at = time '09:15:00'"
        ).rows == [(1,)]
        assert cl.execute(
            "SELECT min(at), max(at) FROM sh").rows == \
            [(datetime.time(9, 15), datetime.time(23, 59, 59))]


def test_explain_update_delete(cl):
    cl.execute("CREATE TABLE ex (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('ex', 'k', 8)")
    cl.copy_from("ex", rows=[(i, i) for i in range(50)])
    out = "\n".join(r[0] for r in cl.execute(
        "EXPLAIN UPDATE ex SET v = 1 WHERE k = 5").rows)
    assert "Update on ex (shards: 1/8)" in out
    assert "Strategy: local" in out
    out = "\n".join(r[0] for r in cl.execute(
        "EXPLAIN DELETE FROM ex").rows)
    assert "Delete on ex (shards: 8/8)" in out
