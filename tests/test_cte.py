"""WITH (CTE) queries via materialized intermediate results."""

import sqlite3

import pytest

import citus_tpu as ct


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, s text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rows = [(i, i % 15, ["x", "y", "z"][i % 3]) for i in range(1000)]
    cl.copy_from("t", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, v INTEGER, s TEXT)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)", rows)
    return cl, sq


def check(db, sql):
    cl, sq = db
    ours = sorted(cl.execute(sql).rows, key=repr)
    theirs = sorted(
        [tuple(float(x) if isinstance(x, float) else x for x in r)
         for r in sq.execute(sql).fetchall()], key=repr)
    ours = sorted(
        [tuple(float(x) if hasattr(x, "as_tuple") else x for x in r)
         for r in ours], key=repr)
    assert ours == pytest.approx(theirs)


CTE_QUERIES = [
    "WITH top AS (SELECT v, count(*) AS c FROM t GROUP BY v) "
    "SELECT count(*), sum(c) FROM top",
    "WITH f AS (SELECT k, v FROM t WHERE v > 10) "
    "SELECT v, count(*) FROM f GROUP BY v",
    "WITH a AS (SELECT v, count(*) AS c FROM t GROUP BY v), "
    "b AS (SELECT c FROM a WHERE c > 60) SELECT count(*) FROM b",
    "WITH agg AS (SELECT s, sum(v) AS sv FROM t GROUP BY s) "
    "SELECT t2.s, t2.sv FROM agg t2 ORDER BY t2.s",
]


@pytest.mark.parametrize("sql", CTE_QUERIES)
def test_cte_vs_sqlite(db, sql):
    check(db, sql)


def test_cte_join_with_base_table(db):
    cl, sq = db
    sql = ("WITH sums AS (SELECT v, sum(k) AS sk FROM t GROUP BY v) "
           "SELECT count(*) FROM t JOIN sums ON t.v = sums.v WHERE sums.sk > 30000")
    check(db, sql)


def test_cte_temp_tables_are_dropped(db):
    cl, _ = db
    cl.execute("WITH x AS (SELECT count(*) AS c FROM t) SELECT c FROM x")
    leftovers = [n for n in cl.catalog.tables if n.startswith("__cte_")]
    assert leftovers == []


def test_large_intermediate_results_distribute(tmp_path):
    """CTE/derived results above the threshold hash-distribute back out
    (reference: RedistributeTaskListResults) so downstream joins run
    sharded; small ones stay local."""
    import numpy as np
    cl = ct.Cluster(str(tmp_path / "dint"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={"k": np.arange(20000),
                               "v": np.arange(20000) % 100})
    seen_dist = []
    orig = cl.catalog.distribute_table

    def spy(*a, **kw):
        seen_dist.append(a[0])
        return orig(*a, **kw)
    cl.catalog.distribute_table = spy
    r = cl.execute("WITH big AS (SELECT k, v * 2 AS w FROM t WHERE v < 90) "
                   "SELECT count(*), sum(w) FROM big").rows
    v = np.arange(20000) % 100
    assert r == [(int((v < 90).sum()), int(v[v < 90].sum() * 2))]
    assert any(n.startswith("__cte_") for n in seen_dist)  # distributed out
    seen_dist.clear()
    assert cl.execute("WITH s AS (SELECT k FROM t WHERE k < 10) "
                      "SELECT count(*) FROM s").rows == [(10,)]
    assert not seen_dist  # small: stays local
    cl.close()
