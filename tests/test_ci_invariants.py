"""Repo invariants, run as part of the suite (reference: ci/*.sh —
check_gucs_are_alphabetically_sorted.sh, check_migration_files.sh,
banned.h.sh — enforced there as CI scripts; here as always-on tests).

The source-shape checks that used to live here as hand-rolled regex
scans are now thin wrappers over tools/cituslint — one AST framework,
one suppression mechanism, one failure shape (see test_lint_clean.py
for the full-tree run).  Runtime invariants (registry completeness,
document round-trips, golden pairing) stay as plain tests: they need
imports, not parsing.
"""

import pathlib
import re

from tools.cituslint import run_lint

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "citus_tpu"


def _lint(*rule_ids: str):
    return run_lint(str(PKG), select=set(rule_ids))


# ------------------------------------------------- cituslint wrappers

def test_no_todo_markers():
    """No TODO/FIXME stubs in the package (TODO01: the framework ships
    complete components, not placeholders)."""
    assert _lint("TODO01") == []


def test_executor_pull_path_has_single_call_site():
    """The executor reaches sync_placement (the O(placement-bytes) pull
    path) through exactly ONE door — executor/batches.py (CONF01's
    confined-method table).  The aggregate/projection paths and the
    push subsystem must ship tasks, never placement files."""
    assert _lint("CONF01") == []
    # the confinement table itself must keep pinning the method
    from tools.cituslint.rules import CONFINED_METHODS
    assert CONFINED_METHODS["sync_placement"] == ("executor/batches.py",)


def test_remote_dispatch_is_parallel_only():
    """Remote execute_task RPCs go through the parallel fan-out
    (pipeline.RemoteTaskDispatch over the single event-loop dispatcher,
    net/event_loop.py) — never a sequential per-task call_binary loop
    in worker_tasks.py (CONF01's banned-method + required-identifier
    tables)."""
    assert _lint("CONF01") == []
    from tools.cituslint.rules import BANNED_METHODS, REQUIRED_IDENTIFIERS
    assert "executor/worker_tasks.py" in BANNED_METHODS["call_binary"]
    assert "dispatch_remote_tasks" in \
        REQUIRED_IDENTIFIERS["executor/worker_tasks.py"]
    assert "event_loop" in \
        REQUIRED_IDENTIFIERS["executor/pipeline.py"]


def test_wire_codecs_confined_to_data_plane():
    """np.savez/np.load (the legacy npz wire fallback) and selector use
    (the event-loop dispatcher) stay confined to net/ — array
    serialization anywhere else must route through the data plane's
    frame codec (CONF01's confined-call table)."""
    assert _lint("CONF01") == []
    from tools.cituslint.rules import CONFINED_CALLS
    assert CONFINED_CALLS["numpy.savez"] == ("net/data_plane.py",)
    assert CONFINED_CALLS["numpy.load"] == ("net/data_plane.py",)
    assert CONFINED_CALLS["selectors.DefaultSelector"] == \
        ("net/event_loop.py",)


def test_jit_confined_to_kernel_cache():
    """``jax.jit`` is invoked only inside executor/kernel_cache.py
    (through its jit_compile wrapper), so per-plan ad-hoc compiles —
    invisible to the kernel cache and its compile-time accounting —
    cannot silently regrow anywhere in the package."""
    assert _lint("CONF01") == []
    from tools.cituslint.rules import CONFINED_CALLS
    assert CONFINED_CALLS["jax.jit"] == ("executor/kernel_cache.py",)


def test_vmap_confined_to_megabatch():
    """``jax.vmap`` (query-axis batching) lives only in
    executor/megabatch.py and executor/kernel_cache.py, so every
    batched kernel flows through get_kernel's ``batched:`` slots and
    the single jit door — a vmap call anywhere else would dodge both
    the kernel cache and megabatch's occupancy accounting."""
    assert _lint("CONF01") == []
    from tools.cituslint.rules import CONFINED_CALLS
    assert CONFINED_CALLS["jax.vmap"] == \
        ("executor/megabatch.py", "executor/kernel_cache.py")


def test_perf_counter_confined_to_trace():
    """time.perf_counter is called only in observability/trace.py (the
    package-wide ``clock``), so every subsystem's timings share one
    clock and fold consistently into spans and counters."""
    assert _lint("CONF01") == []
    from tools.cituslint.rules import CONFINED_CALLS
    assert CONFINED_CALLS["time.perf_counter"] == \
        ("observability/trace.py",)


def test_wall_clock_confined_to_utils_clock():
    """time.time() goes through utils/clock.py now() (the swappable
    wall-clock seam) everywhere — TTLs and activity timestamps are
    fake-clock-testable package-wide."""
    assert _lint("CONF01") == []
    from tools.cituslint.rules import CONFINED_CALLS
    assert CONFINED_CALLS["time.time"] == ("utils/clock.py",)


def test_no_dead_counters():
    """Every name in StatCounters.COUNTERS has at least one bump site
    (CNT02) and every bump names a declared counter (CNT01) — a counter
    nothing increments is a lie in every metrics dashboard, and a typo'd
    bump counts into the void."""
    assert _lint("CNT01", "CNT02") == []


# --------------------------------------------------- runtime invariants

def test_golden_files_paired():
    """Every golden .sql has an .out and vice versa (the reference's
    regress sql/ <-> expected/ pairing)."""
    golden = REPO / "tests" / "golden"
    sqls = {p.stem for p in golden.glob("*.sql")}
    outs = {p.stem for p in golden.glob("*.out")}
    assert sqls == outs, (sqls - outs, outs - sqls)


def test_agg_registry_complete():
    """Every registered aggregate declares lower+finalize (bind may be
    None only for internal kinds the binder dispatches itself)."""
    from citus_tpu.planner.aggregates import AGG_REGISTRY
    internal = {"min_text", "max_text", "sum_distinct", "avg_distinct"}
    for name, d in AGG_REGISTRY.items():
        assert callable(d.lower), name
        assert callable(d.finalize), name
        if name not in internal:
            assert callable(d.bind), name


def test_udf_whitelist_unique():
    from citus_tpu.planner import parser as P
    src = (PKG / "planner" / "parser.py").read_text()
    m = re.search(r"_UTILITY_FUNCS = \{(.*?)\}", src, re.S) \
        or re.search(r'"citus_version".*?\}', src, re.S)
    names = re.findall(r'"([a-z_0-9]+)"', m.group(0))
    assert len(names) == len(set(names)), \
        [n for n in names if names.count(n) > 1]


def test_catalog_document_round_trip():
    """export_document/load_document cover the same sections — a field
    added to one but not the other would silently drop metadata in the
    control plane's document transport."""
    import tempfile
    from citus_tpu.catalog.catalog import Catalog
    cat = Catalog(tempfile.mkdtemp())
    doc = cat.export_document()
    cat2 = Catalog(tempfile.mkdtemp())
    cat2.load_document(doc)
    doc2 = cat2.export_document()
    assert doc == doc2
    # every mutable dict section in __init__ is exported
    sections = {"schemas", "views", "sequences", "roles", "grants",
                "functions", "types", "enum_columns", "policies", "rls",
                "triggers", "ts_configs"}
    assert sections <= set(doc.keys()), sections - set(doc.keys())


def test_config_fields_are_commented():
    """Every Settings field carries an explanatory comment (the
    reference documents each GUC; ci enforces ordering/description)."""
    src = (PKG / "config.py").read_text()
    lines = src.splitlines()
    missing = []
    in_class = False
    prev_comment = False
    for i, line in enumerate(lines):
        s = line.strip()
        if s.startswith("@dataclass"):
            in_class = True
            prev_comment = False
            continue
        if in_class and re.match(r"^[a-z_]+: [A-Za-z]", s) \
                and not s.startswith("_") \
                and "Settings" not in s.split("=")[0]:
            # nested settings groups are self-describing
            if not prev_comment:
                missing.append(f"config.py:{i + 1} {s.split(':')[0]}")
        prev_comment = s.startswith("#")
        if s.startswith("def ") or (s and not line.startswith((" ", "@"))
                                    and not s.startswith("class")):
            in_class = in_class and s.startswith("class")
    assert not missing, missing
