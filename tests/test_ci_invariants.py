"""Repo invariants, run as part of the suite (reference: ci/*.sh —
check_gucs_are_alphabetically_sorted.sh, check_migration_files.sh,
banned.h.sh — enforced there as CI scripts; here as always-on tests)."""

import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "citus_tpu"


def test_golden_files_paired():
    """Every golden .sql has an .out and vice versa (the reference's
    regress sql/ <-> expected/ pairing)."""
    golden = REPO / "tests" / "golden"
    sqls = {p.stem for p in golden.glob("*.sql")}
    outs = {p.stem for p in golden.glob("*.out")}
    assert sqls == outs, (sqls - outs, outs - sqls)


def test_no_todo_markers():
    """No TODO/FIXME stubs in the package (the framework ships complete
    components, not placeholders)."""
    hits = []
    for p in PKG.rglob("*.py"):
        for i, line in enumerate(p.read_text().splitlines(), 1):
            if re.search(r"\b(TODO|FIXME|XXX)\b", line):
                hits.append(f"{p.relative_to(REPO)}:{i}")
    assert not hits, hits


def test_executor_pull_path_has_single_call_site():
    """The executor reaches sync_placement (the O(placement-bytes) pull
    path) through exactly ONE helper — batches._pull_placement_fallback.
    The aggregate/projection paths (executor.py) and the push subsystem
    (worker_tasks.py) must ship tasks, never placement files."""
    hits = {}
    for p in (PKG / "executor").glob("*.py"):
        n = p.read_text().count("sync_placement(")
        if n:
            hits[p.name] = n
    assert hits == {"batches.py": 1}, hits


def test_remote_dispatch_is_parallel_only():
    """Remote execute_task RPCs go through the parallel fan-out
    (pipeline.RemoteTaskDispatch over pooled connections) — never a
    sequential per-task call_binary loop in worker_tasks.py, which
    would cost the SUM of per-host times instead of the max."""
    wt = (PKG / "executor" / "worker_tasks.py").read_text()
    assert "call_binary" not in wt, \
        "worker_tasks.py must not dispatch RPCs itself"
    assert "dispatch_remote_tasks" in wt
    pl = (PKG / "executor" / "pipeline.py").read_text()
    assert "call_binary_pooled" in pl


def test_jit_confined_to_kernel_cache():
    """``jax.jit`` is invoked only inside executor/kernel_cache.py
    (through its jit_compile wrapper), so per-plan ad-hoc compiles —
    invisible to the kernel cache and its compile-time accounting —
    cannot silently regrow anywhere in the package."""
    hits = []
    for p in PKG.rglob("*.py"):
        if "jax.jit" in p.read_text():
            hits.append(str(p.relative_to(PKG)))
    assert hits == ["executor/kernel_cache.py"], hits


def test_agg_registry_complete():
    """Every registered aggregate declares lower+finalize (bind may be
    None only for internal kinds the binder dispatches itself)."""
    from citus_tpu.planner.aggregates import AGG_REGISTRY
    internal = {"min_text", "max_text", "sum_distinct", "avg_distinct"}
    for name, d in AGG_REGISTRY.items():
        assert callable(d.lower), name
        assert callable(d.finalize), name
        if name not in internal:
            assert callable(d.bind), name


def test_udf_whitelist_unique():
    from citus_tpu.planner import parser as P
    src = (PKG / "planner" / "parser.py").read_text()
    m = re.search(r"_UTILITY_FUNCS = \{(.*?)\}", src, re.S) \
        or re.search(r'"citus_version".*?\}', src, re.S)
    names = re.findall(r'"([a-z_0-9]+)"', m.group(0))
    assert len(names) == len(set(names)), \
        [n for n in names if names.count(n) > 1]


def test_catalog_document_round_trip():
    """export_document/load_document cover the same sections — a field
    added to one but not the other would silently drop metadata in the
    control plane's document transport."""
    import tempfile
    from citus_tpu.catalog.catalog import Catalog
    cat = Catalog(tempfile.mkdtemp())
    doc = cat.export_document()
    cat2 = Catalog(tempfile.mkdtemp())
    cat2.load_document(doc)
    doc2 = cat2.export_document()
    assert doc == doc2
    # every mutable dict section in __init__ is exported
    sections = {"schemas", "views", "sequences", "roles", "grants",
                "functions", "types", "enum_columns", "policies", "rls",
                "triggers", "ts_configs"}
    assert sections <= set(doc.keys()), sections - set(doc.keys())


def test_config_fields_are_commented():
    """Every Settings field carries an explanatory comment (the
    reference documents each GUC; ci enforces ordering/description)."""
    src = (PKG / "config.py").read_text()
    lines = src.splitlines()
    missing = []
    in_class = False
    prev_comment = False
    for i, line in enumerate(lines):
        s = line.strip()
        if s.startswith("@dataclass"):
            in_class = True
            prev_comment = False
            continue
        if in_class and re.match(r"^[a-z_]+: [A-Za-z]", s) \
                and not s.startswith("_") \
                and "Settings" not in s.split("=")[0]:
            # nested settings groups are self-describing
            if not prev_comment:
                missing.append(f"config.py:{i + 1} {s.split(':')[0]}")
        prev_comment = s.startswith("#")
        if s.startswith("def ") or (s and not line.startswith((" ", "@"))
                                    and not s.startswith("class")):
            in_class = in_class and s.startswith("class")
    assert not missing, missing


def test_no_dead_counters():
    """Every name in StatCounters.COUNTERS has at least one bump site
    (or span-fold mapping) somewhere under citus_tpu/ — a counter that
    nothing increments is a lie in every metrics dashboard.  The check
    looks for the name as a string literal outside its declaration in
    stats.py, which covers direct bump("name") calls and indirect
    routes like trace._SPAN_MS."""
    from citus_tpu.stats import StatCounters
    dead = []
    srcs = []
    for p in PKG.rglob("*.py"):
        text = p.read_text()
        if p.name == "stats.py":
            # strip the COUNTERS declaration itself: appearing there is
            # the definition, not a use
            text = re.sub(r"COUNTERS\s*=\s*\([^)]*\)", "", text, flags=re.S)
        srcs.append(text)
    blob = "\n".join(srcs)
    for name in StatCounters.COUNTERS:
        if f'"{name}"' not in blob and f"'{name}'" not in blob:
            dead.append(name)
    assert not dead, f"counters never bumped anywhere: {dead}"


def test_perf_counter_confined_to_trace():
    """time.perf_counter is called only in observability/trace.py (the
    package-wide ``clock``), so every subsystem's timings share one
    clock and fold consistently into spans and counters."""
    hits = []
    for p in PKG.rglob("*.py"):
        if "perf_counter" in p.read_text():
            hits.append(str(p.relative_to(PKG)))
    assert hits == ["observability/trace.py"], hits
