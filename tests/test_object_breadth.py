"""Object-surface breadth: extensions, domains, collations,
publications, statistics objects, secondary-node routing.

Reference: commands/extension.c, domain.c, collation.c, publication.c,
statistics.c propagation + citus.use_secondary_nodes."""

import pytest

import citus_tpu as ct
from citus_tpu.errors import CatalogError, ExecutionError


@pytest.fixture()
def cl(tmp_path):
    return ct.Cluster(str(tmp_path / "db"))


def test_extensions(cl):
    cl.execute("CREATE EXTENSION citus")
    cl.execute("CREATE EXTENSION IF NOT EXISTS citus")
    with pytest.raises(CatalogError):
        cl.execute("CREATE EXTENSION citus")
    assert cl.execute("SELECT citus_extensions()").rows == [("citus", "1.0")]
    cl.execute("DROP EXTENSION citus")
    assert cl.execute("SELECT citus_extensions()").rows == []
    cl.execute("DROP EXTENSION IF EXISTS citus")


def test_domain_enforced_on_ingest(cl):
    cl.execute("CREATE DOMAIN posint AS bigint CHECK (value > 0)")
    cl.execute("CREATE TABLE t (k bigint, qty posint)")
    assert cl.catalog.table("t").schema.column("qty").type.kind == "int64"
    cl.execute("INSERT INTO t VALUES (1, 5)")
    with pytest.raises(ExecutionError, match="posint"):
        cl.execute("INSERT INTO t VALUES (2, -3)")
    cl.execute("INSERT INTO t VALUES (3, NULL)")  # NULL passes CHECK
    assert cl.execute("SELECT count(*) FROM t").rows == [(2,)]
    with pytest.raises(CatalogError, match="depends on it"):
        cl.execute("DROP DOMAIN posint")
    rows = cl.execute("SELECT citus_domains()").rows
    assert rows == [("posint", "bigint", False, "value > 0")]


def test_domain_not_null(cl):
    cl.execute("CREATE DOMAIN req_text AS text NOT NULL")
    cl.execute("CREATE TABLE u (k bigint, name req_text)")
    with pytest.raises(Exception):
        cl.execute("INSERT INTO u VALUES (1, NULL)")
    cl.execute("INSERT INTO u VALUES (1, 'ok')")


def test_collations_registry(cl):
    cl.execute("CREATE COLLATION german (locale = 'de_DE', provider = 'icu')")
    assert cl.execute("SELECT citus_collations()").rows == \
        [("german", "de_DE", "icu")]
    cl.execute("DROP COLLATION german")
    with pytest.raises(CatalogError):
        cl.execute("DROP COLLATION german")


def test_publication_gates_cdc(cl):
    """CDC is globally off, but a publication covering the table turns
    its change stream on (reference: publications gate logical
    decoding per table)."""
    assert not cl.cdc.enabled
    cl.execute("CREATE TABLE ev (k bigint, v bigint)")
    cl.execute("CREATE TABLE quiet (k bigint)")
    cl.copy_from("ev", rows=[(1, 10)])
    assert list(cl.cdc.events("ev")) == []  # not yet published
    cl.execute("CREATE PUBLICATION pub_ev FOR TABLE ev")
    cl.copy_from("ev", rows=[(2, 20)])
    cl.copy_from("quiet", rows=[(1,)])
    evs = list(cl.cdc.events("ev"))
    assert len(evs) == 1 and evs[0]["op"] == "insert"
    assert list(cl.cdc.events("quiet")) == []  # uncovered table stays quiet
    cl.execute("DROP PUBLICATION pub_ev")
    cl.copy_from("ev", rows=[(3, 30)])
    assert len(list(cl.cdc.events("ev"))) == 1  # stream stopped


def test_publication_for_all_tables(cl):
    cl.execute("CREATE TABLE a (k bigint)")
    cl.execute("CREATE PUBLICATION everything FOR ALL TABLES")
    cl.copy_from("a", rows=[(1,)])
    assert len(list(cl.cdc.events("a"))) == 1
    assert cl.execute("SELECT citus_publications()").rows == \
        [("everything", "ALL TABLES")]


def test_statistics_objects(cl):
    cl.execute("CREATE TABLE s (a bigint, b bigint)")
    cl.copy_from("s", rows=[(i % 3, i % 4) for i in range(120)])
    cl.execute("CREATE STATISTICS s_ab ON a, b FROM s")
    rows = cl.execute("SELECT citus_statistics_objects()").rows
    assert rows == [("s_ab", "s", "a, b", 12)]  # 3x4 combinations
    cl.execute("DROP STATISTICS s_ab")
    assert cl.execute("SELECT citus_statistics_objects()").rows == []


def test_domain_enforced_on_update_and_insert_select(cl):
    cl.execute("CREATE DOMAIN posint AS bigint CHECK (value > 0)")
    cl.execute("CREATE TABLE t (k bigint, qty posint)")
    cl.execute("INSERT INTO t VALUES (1, 5)")
    with pytest.raises(ExecutionError, match="posint"):
        cl.execute("UPDATE t SET qty = -5 WHERE k = 1")
    assert cl.execute("SELECT qty FROM t").rows == [(5,)]
    cl.execute("CREATE TABLE src (k bigint, qty bigint)")
    cl.execute("INSERT INTO src VALUES (2, -7)")
    with pytest.raises(ExecutionError, match="posint"):
        cl.execute("INSERT INTO t SELECT * FROM src")
    assert cl.execute("SELECT count(*) FROM t").rows == [(1,)]


def test_drop_table_cleans_domain_and_publication_refs(cl):
    cl.execute("CREATE DOMAIN posint AS bigint CHECK (value > 0)")
    cl.execute("CREATE TABLE t (k bigint, qty posint)")
    cl.execute("CREATE PUBLICATION p FOR TABLE t")
    cl.execute("DROP TABLE t")
    cl.execute("DROP DOMAIN posint")  # no stale dependency
    assert cl.catalog.publications["p"]["tables"] == []
    # re-created same-name table is NOT domain-bound or published
    cl.execute("CREATE TABLE t (k bigint, qty bigint)")
    cl.execute("INSERT INTO t VALUES (1, -5)")  # plain bigint: fine
    assert list(cl.cdc.events("t")) == []


def test_empty_publication_captures_nothing(cl):
    cl.execute("CREATE TABLE q (k bigint)")
    cl.execute("CREATE PUBLICATION empty_pub")
    cl.copy_from("q", rows=[(1,)])
    assert list(cl.cdc.events("q")) == []


def test_publication_on_partitioned_parent(cl):
    cl.execute("CREATE TABLE pe (k bigint, d date) PARTITION BY RANGE (d)")
    cl.execute("CREATE TABLE pe_a PARTITION OF pe "
               "FOR VALUES FROM ('2024-01-01') TO ('2025-01-01')")
    cl.execute("CREATE PUBLICATION ppub FOR TABLE pe")
    cl.copy_from("pe", rows=[(1, "2024-05-05")])
    # captured under the leaf partition's stream (pubviaroot=false style)
    assert len(list(cl.cdc.events("pe_a"))) == 1


def test_add_column_with_domain_and_enum(cl):
    cl.execute("CREATE DOMAIN posint AS bigint CHECK (value > 0)")
    cl.execute("CREATE TYPE mood AS ENUM ('sad', 'happy')")
    cl.execute("CREATE TABLE t (k bigint)")
    cl.execute("ALTER TABLE t ADD COLUMN qty posint")
    cl.execute("ALTER TABLE t ADD COLUMN m mood")
    with pytest.raises(ExecutionError, match="posint"):
        cl.execute("INSERT INTO t VALUES (1, -2, 'sad')")
    cl.execute("INSERT INTO t VALUES (1, 2, 'happy')")
    assert cl.execute("SELECT m FROM t WHERE qty = 2").rows == [("happy",)]


def test_secondary_node_routing(tmp_path):
    """use_secondary_nodes prefers replica placements for reads."""
    import numpy as np
    from citus_tpu.config import ExecutorSettings, Settings, ShardingSettings
    st = Settings(sharding=ShardingSettings(shard_replication_factor=2),
                  executor=ExecutorSettings(use_secondary_nodes=True))
    cl = ct.Cluster(str(tmp_path / "db2"), n_nodes=2, settings=st)
    cl.execute("CREATE TABLE r (k bigint, v bigint)")
    cl.execute("SELECT create_distributed_table('r', 'k', 4)")
    cl.copy_from("r", rows=[(i, i) for i in range(1000)])
    # destroy every PRIMARY placement: reads must come from replicas
    import shutil
    t = cl.catalog.table("r")
    for s in t.shards:
        shutil.rmtree(cl.catalog.shard_dir("r", s.shard_id, s.placements[0]),
                      ignore_errors=True)
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    GLOBAL_CACHE.clear()
    fo_before = cl.counters.snapshot().get("connection_failovers", 0)
    assert cl.execute("SELECT count(*), sum(v) FROM r").rows == \
        [(1000, sum(range(1000)))]
    # replicas served directly — no failover was needed
    assert cl.counters.snapshot().get("connection_failovers", 0) == fo_before
    cl.close()

def test_analyze_refreshes_statistics(cl):
    cl.execute("CREATE TABLE s2 (a bigint, b bigint)")
    cl.copy_from("s2", rows=[(i % 2, i % 2) for i in range(40)])
    cl.execute("CREATE STATISTICS s2_ab ON a, b FROM s2")
    assert cl.execute("SELECT citus_statistics_objects()").rows[0][3] == 2
    cl.copy_from("s2", rows=[(i % 10, i % 7) for i in range(200)])
    r = cl.execute("ANALYZE s2")
    assert r.explain["statistics_refreshed"] == 1
    nd = cl.execute("SELECT citus_statistics_objects()").rows[0][3]
    assert nd > 2
    # bare ANALYZE refreshes everything
    assert cl.execute("ANALYZE").explain["statistics_refreshed"] == 1


def test_reindex_rebuilds_segments(cl):
    import os
    cl.execute("CREATE TABLE ri (k bigint, v bigint)")
    cl.copy_from("ri", rows=[(i, i % 50) for i in range(5000)])
    cl.execute("CREATE INDEX ri_v ON ri (v)")

    def segs():
        t = cl.catalog.table("ri")
        out = []
        for shard in t.shards:
            for node in shard.placements:
                d = cl.catalog.shard_dir("ri", shard.shard_id, node)
                if os.path.isdir(d):
                    out += [os.path.join(d, f) for f in os.listdir(d)
                            if f.endswith(".idx.v.npz")]
        return out

    before = segs()
    assert before
    for p in before:  # simulate lost/corrupted segments
        os.remove(p)
    r = cl.execute("REINDEX INDEX ri_v")
    assert r.explain["segments_rebuilt"] >= len(before)
    assert segs()
    assert cl.execute("SELECT count(*) FROM ri WHERE v = 7").rows == [(100,)]
    r2 = cl.execute("REINDEX TABLE ri")
    assert r2.explain["segments_rebuilt"] >= len(before)
    # VACUUM ANALYZE spelling parses and runs
    cl.execute("VACUUM ANALYZE ri")


def test_analyze_edge_cases(cl):
    with pytest.raises(CatalogError):
        cl.execute("ANALYZE no_such_table")
    cl.execute("CREATE TABLE dc (a bigint, b bigint, c bigint)")
    cl.copy_from("dc", rows=[(1, 2, 3)])
    cl.execute("CREATE STATISTICS dc_ab ON a, b FROM dc")
    # dropping a member column auto-drops the statistics object (PG)
    cl.execute("ALTER TABLE dc DROP COLUMN b")
    assert cl.execute("SELECT citus_statistics_objects()").rows == []
    cl.execute("ANALYZE")  # no stale entry to trip over
    # VACUUM FULL spelling parses
    cl.execute("VACUUM FULL dc")


def test_column_defaults_and_serial(tmp_path):
    """DEFAULT expressions (pg_attrdef analog) and serial columns
    (integer + owned sequence + nextval default)."""
    import citus_tpu as ct
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (id bigserial NOT NULL,"
               " v bigint DEFAULT 7, s text DEFAULT 'none', k bigint)")
    cl.execute("SELECT create_distributed_table('t', 'id', 4)")
    cl.execute("INSERT INTO t (k) VALUES (100)")
    cl.execute("INSERT INTO t (k, v) VALUES (300, 99)")
    rows = sorted(cl.execute("SELECT id, v, s, k FROM t").rows)
    assert rows == [(1, 7, "none", 100), (2, 99, "none", 300)]
    # explicit NULL on a defaulted column stays NULL (the column was
    # listed); omitted columns without defaults stay NULL too
    cl.execute("INSERT INTO t (k, v) VALUES (400, NULL)")
    assert (3, None, "none", 400) in \
        cl.execute("SELECT id, v, s, k FROM t").rows
    # defaults survive a catalog round-trip (reopen)
    cl.close()
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("INSERT INTO t (k) VALUES (500)")
    got = [r for r in cl.execute("SELECT v, s, k FROM t").rows
           if r[2] == 500]
    assert got == [(7, "none", 500)]
    # serial ids are unique across the reopen
    ids = [r[0] for r in cl.execute("SELECT id FROM t").rows]
    assert len(ids) == len(set(ids))
    cl.close()


def test_check_constraints(tmp_path):
    """Column- and table-level CHECK constraints enforced on INSERT,
    COPY, and UPDATE (pg_constraint CHECK analog; NULL passes)."""
    import citus_tpu as ct
    from citus_tpu.integrity import CheckViolation
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE acc (id bigint NOT NULL,"
               " balance decimal(12,2) CHECK (balance >= 0),"
               " status text, CHECK (id > 0))")
    cl.execute("SELECT create_distributed_table('acc', 'id', 4)")
    assert len(cl.catalog.table("acc").check_constraints) == 2
    cl.execute("INSERT INTO acc VALUES (1, 100.50, 'open')")
    with pytest.raises(CheckViolation):
        cl.execute("INSERT INTO acc VALUES (2, -5, 'open')")
    with pytest.raises(CheckViolation):
        cl.copy_from("acc", rows=[(3, 10, "a"), (-4, 10, "b")])
    with pytest.raises(CheckViolation):
        cl.execute("UPDATE acc SET balance = balance - 200 WHERE id = 1")
    # NULL passes a CHECK (SQL three-valued logic)
    cl.execute("INSERT INTO acc VALUES (5, NULL, 'open')")
    # survives reopen
    cl.close()
    cl = ct.Cluster(str(tmp_path / "db"))
    with pytest.raises(CheckViolation):
        cl.execute("INSERT INTO acc VALUES (6, -1, 'x')")
    assert cl.execute("SELECT count(*) FROM acc").rows == [(2,)]
    cl.close()


def test_alter_add_check_and_default_values(tmp_path):
    import citus_tpu as ct
    from citus_tpu.errors import AnalysisError
    from citus_tpu.integrity import CheckViolation
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (id bigserial NOT NULL,"
               " v bigint DEFAULT 42)")
    cl.execute("SELECT create_distributed_table('t', 'id', 4)")
    cl.execute("INSERT INTO t DEFAULT VALUES")
    cl.execute("INSERT INTO t DEFAULT VALUES")
    assert sorted(cl.execute("SELECT id, v FROM t").rows) == \
        [(1, 42), (2, 42)]
    cl.execute("INSERT INTO t (v) VALUES (-7)")
    # ADD CHECK validates existing rows (NULL passes, FALSE rejects)
    with pytest.raises(AnalysisError, match="violated by"):
        cl.execute("ALTER TABLE t ADD CONSTRAINT pos CHECK (v >= 0)")
    cl.execute("DELETE FROM t WHERE v < 0")
    cl.execute("ALTER TABLE t ADD CONSTRAINT pos CHECK (v >= 0)")
    with pytest.raises(CheckViolation):
        cl.execute("INSERT INTO t (v) VALUES (-1)")
    cl.close()


def test_check_constraints_inherited_by_partitions(tmp_path):
    """Review finding: parent CHECK constraints must bind to every
    partition (PostgreSQL propagates them); writes through the parent
    or directly into a leaf are both enforced."""
    import citus_tpu as ct
    from citus_tpu.integrity import CheckViolation
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE m (id bigint NOT NULL, v bigint,"
               " CHECK (v > 0)) PARTITION BY RANGE (id)")
    cl.execute("CREATE TABLE m1 PARTITION OF m "
               "FOR VALUES FROM (0) TO (100)")
    cl.execute("SELECT create_distributed_table('m', 'id', 4)")
    with pytest.raises(CheckViolation):
        cl.execute("INSERT INTO m VALUES (1, -5)")
    with pytest.raises(CheckViolation):
        cl.copy_from("m1", rows=[(2, -1)])
    cl.execute("INSERT INTO m VALUES (3, 5)")
    assert cl.execute("SELECT count(*) FROM m").rows == [(1,)]
    cl.close()


def test_create_table_atomic_with_bad_check_and_serial_lifecycle(tmp_path):
    """Review findings: a failing CHECK leaves NO half-created table,
    and serial sequences die with their table (a recreated table
    restarts at 1)."""
    import citus_tpu as ct
    cl = ct.Cluster(str(tmp_path / "db"))
    with pytest.raises(Exception):
        cl.execute("CREATE TABLE bad (x bigint, CHECK (nosuch > 0))")
    assert not cl.catalog.has_table("bad")
    cl.execute("CREATE TABLE s2 (id serial NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('s2', 'id', 4)")
    cl.execute("INSERT INTO s2 (v) VALUES (1)")
    cl.execute("DROP TABLE s2")
    assert "s2_id_seq" not in cl.catalog.sequences
    cl.execute("CREATE TABLE s2 (id serial NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('s2', 'id', 4)")
    r = cl.execute("INSERT INTO s2 (v) VALUES (2) RETURNING id")
    assert r.rows == [(1,)]  # fresh sequence, not the old counter
    cl.close()
