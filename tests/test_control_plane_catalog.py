"""Catalog document over RPC + authority-serialized DDL (round-2 gap #4).

The catalog document itself travels over the control plane: peers fetch
it from the metadata authority (fetch_catalog) and commit by pushing the
merged document back (push_catalog) while holding the cluster-wide DDL
lease the authority grants.  The shared-FS flock path remains the
degenerate fallback.  Reference: metadata changes travel inside the
coordinator's transaction (metadata/metadata_sync.c), serialized by the
metadata locks."""

import threading
import time

import numpy as np
import pytest

import citus_tpu as ct


def wait_until(fn, timeout=20.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if fn():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture()
def pair(tmp_path):
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2, serve_port=0)
    b = ct.Cluster(str(tmp_path / "db"), n_nodes=2,
                   coordinator=("127.0.0.1", a.control_port))
    yield a, b
    b.close()
    a.close()


def test_commit_pushes_document_over_rpc(pair):
    """A client coordinator's DDL travels as a pushed document, not a
    local file write."""
    a, b = pair
    b.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    b.execute("SELECT create_distributed_table('t', 'k', 4)")
    assert a._control.stats["push_catalog"] >= 2
    assert a._control.stats["lease_acquired"] >= 2
    # the authority applied the push synchronously — no dirty-flag wait
    assert a.catalog.has_table("t")
    b.copy_from("t", columns={"k": np.arange(10), "v": np.ones(10, np.int64)})
    assert a.execute("SELECT count(*) FROM t").rows == [(10,)]


def test_reload_fetches_document_over_rpc(pair):
    """The invalidated peer reloads over RPC — either the incremental
    pull-on-mismatch path (metadata/sync.py: version vector + object
    pull) or the full-document fetch fallback — never the file."""
    a, b = pair
    a.execute("CREATE TABLE r (x bigint)")
    a.execute("INSERT INTO r VALUES (1), (2)")
    assert wait_until(lambda: b._catalog_dirty)
    before = dict(a._control.stats)
    assert b.execute("SELECT sum(x) FROM r").rows == [(3,)]
    stats = a._control.stats
    assert (stats["metadata_versions"] > before["metadata_versions"]
            or stats["fetch_catalog"] > before["fetch_catalog"])


def test_concurrent_ddl_serializes_through_lease(tmp_path):
    """Two client coordinators commit DDL concurrently: the lease
    serializes them and no table is lost (the failure mode of plain
    last-writer-wins)."""
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2, serve_port=0)
    clients = [ct.Cluster(str(tmp_path / "db"), n_nodes=2,
                          coordinator=("127.0.0.1", a.control_port))
               for _ in range(2)]
    try:
        errs = []

        def mk(cl, lo, hi):
            try:
                for i in range(lo, hi):
                    cl.execute(f"CREATE TABLE c{i} (x bigint)")
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=mk, args=(clients[0], 0, 8)),
              threading.Thread(target=mk, args=(clients[1], 8, 16))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs
        # every table from both committers survives on the authority
        for i in range(16):
            assert a.catalog.has_table(f"c{i}"), f"lost c{i}"
    finally:
        for c in clients:
            c.close()
        a.close()


def test_push_without_lease_rejected(pair):
    a, b = pair
    from citus_tpu.net.rpc import RpcError
    doc = b.catalog.export_document()
    with pytest.raises(RpcError, match="lease"):
        b._control.client.call("push_catalog",
                               {"doc": doc, "origin": "rogue"})


def test_lease_expires_after_crash(pair):
    """A holder that vanishes cannot wedge DDL: the lease TTL expires."""
    import citus_tpu.net.control_plane as cp
    a, b = pair
    assert a._control._lease_try("ghost")
    # simulate expiry instead of waiting out the real TTL
    a._control._lease_expires = time.monotonic() - 1.0
    b.execute("CREATE TABLE after_crash (x bigint)")
    assert a.catalog.has_table("after_crash")


def test_drop_survives_transport(pair):
    """Tombstones ride the pushed document: a drop through a client
    doesn't resurrect via the authority's merge."""
    a, b = pair
    a.execute("CREATE TABLE dropme (x bigint)")
    assert wait_until(lambda: b._catalog_dirty)
    b.execute("SELECT count(*) FROM dropme")  # sync b
    b.execute("DROP TABLE dropme")
    assert not a.catalog.has_table("dropme")
    assert not b.catalog.has_table("dropme")


def test_authority_death_falls_back_to_flock(tmp_path):
    """Client commits keep working through the shared-FS flock path when
    the authority disappears mid-flight (server.stop() also severs the
    request connection, so the remote path genuinely fails).  The
    maintenance daemon is disabled so auto-promotion doesn't heal the
    outage before the fallback is exercised."""
    from citus_tpu.config import Settings
    st = Settings(start_maintenance_daemon=False)
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2, serve_port=0, settings=st)
    b = ct.Cluster(str(tmp_path / "db"), n_nodes=2, settings=st,
                   coordinator=("127.0.0.1", a.control_port))
    try:
        a._control.server.stop()
        assert wait_until(lambda: not b._control.connected)
        b.execute("CREATE TABLE orphan_ok (x bigint)")
        b.execute("INSERT INTO orphan_ok VALUES (9)")
        assert b.execute("SELECT x FROM orphan_ok").rows == [(9,)]
    finally:
        b.close()
        a.close()


def test_flock_commit_between_fetch_and_push_survives(pair, tmp_path):
    """A NON-attached coordinator flock-commits while a client holds the
    lease between fetch and push: the authority's store merges the disk
    file once more, so the flock commit is not overwritten."""
    a, b = pair
    with b._control.catalog_lease():
        doc = b._control.fetch_catalog_doc()
        # c commits through the flock path while b holds the lease
        c = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
        c.execute("CREATE TABLE from_flock (x bigint)")
        c.close()
        b.catalog._merge_doc_locked(doc)
        b.catalog.views["v_from_push"] = "SELECT 1"
        b._control.push_catalog_doc(b.catalog.export_document())
    assert a.catalog.has_table("from_flock"), "flock commit overwritten"
    assert "v_from_push" in a.catalog.views


def test_authority_failover_peer_promotes(tmp_path):
    """Round-4 gap: kill the authority mid-workload — a peer promotes
    itself under the shared-FS promotion lock, the other peer re-points
    its subscription, and DDL+DML proceed over the NEW authority.
    Reference: operations/node_promotion.c."""
    from citus_tpu.config import Settings
    st = Settings(start_maintenance_daemon=False)  # deterministic: no
    # concurrent authority_watch racing the explicit calls below
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2, serve_port=0, settings=st)
    b = ct.Cluster(str(tmp_path / "db"), n_nodes=2, settings=st,
                   coordinator=("127.0.0.1", a.control_port))
    c = ct.Cluster(str(tmp_path / "db"), n_nodes=2, settings=st,
                   coordinator=("127.0.0.1", a.control_port))
    try:
        b.execute("CREATE TABLE pre (x bigint)")
        b.execute("INSERT INTO pre VALUES (1)")
        # authority dies mid-workload
        a._control.server.stop()
        assert wait_until(lambda: not b._control.connected)
        assert wait_until(lambda: not c._control.connected)
        # writes continue immediately (flock fallback)
        b.execute("INSERT INTO pre VALUES (2)")
        # a peer promotes (the maintenance duty drives this; call it
        # directly to keep the test deterministic)
        outcome_b = b._control.ensure_authority()
        assert outcome_b == "promoted"
        assert b._control.server is not None
        outcome_c = c._control.ensure_authority()
        assert outcome_c == "repointed"
        assert c._control.connected
        # DDL through the re-pointed peer rides the NEW authority
        pushes_before = b._control.stats["push_catalog"]
        c.execute("CREATE TABLE post (y bigint)")
        assert b._control.stats["push_catalog"] > pushes_before
        assert b.catalog.has_table("post")
        c.execute("INSERT INTO post VALUES (42)")
        assert b.execute("SELECT y FROM post").rows == [(42,)]
        # invalidation flows from the new authority to the client
        b.execute("CREATE TABLE after_promo (z bigint)")
        assert wait_until(lambda: c._catalog_dirty)
        assert c.execute("SELECT count(*) FROM after_promo").rows == [(0,)]
        # idempotent: a healthy pair reports ok
        assert b._control.ensure_authority() == "ok"
        assert c._control.ensure_authority() == "ok"
    finally:
        c.close()
        b.close()
        a.close()


def test_failover_via_maintenance_daemon(tmp_path):
    """The daemon's authority_watch duty performs the promotion without
    any explicit call."""
    from citus_tpu.config import Settings
    st = Settings(authority_watch_interval_s=0.3)
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2, serve_port=0, settings=st)
    b = ct.Cluster(str(tmp_path / "db"), n_nodes=2, settings=st,
                   coordinator=("127.0.0.1", a.control_port))
    try:
        names = [d[0] for d in b.maintenance.status()]
        assert "authority_watch" in names
        a._control.server.stop()
        assert wait_until(lambda: b._control.server is not None, timeout=15)
        assert b._control.ensure_authority() == "ok"
    finally:
        b.close()
        a.close()


def test_recovered_old_authority_steps_down(tmp_path):
    """Split-brain guard: an authority that was wedged while a peer
    promoted must step down when it sees the authority file advertising
    a live different authority — exactly one metadata writer remains."""
    from citus_tpu.config import Settings
    st = Settings(start_maintenance_daemon=False)
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2, serve_port=0, settings=st)
    b = ct.Cluster(str(tmp_path / "db"), n_nodes=2, settings=st,
                   coordinator=("127.0.0.1", a.control_port))
    try:
        # a wedges (unreachable), b promotes under the promotion lock
        from citus_tpu.net.rpc import RpcServer
        a._control.server.stop()
        assert wait_until(lambda: not b._control.connected)
        assert b._control.ensure_authority() == "promoted"
        # a recovers still believing it is the authority (serving again,
        # file not rewritten — the wedge outlasted the promotion)
        a._control.server = RpcServer(port=0)
        a._control._register_handlers()
        a._control.server.start()
        # a notices the file advertising live b, and steps down
        assert a._control.ensure_authority() == "stepped_down"
        assert a._control.server is None
        assert a._control.connected  # now subscribed to b
        # the demoted coordinator's DDL rides the new authority
        pushes = b._control.stats["push_catalog"]
        a.execute("CREATE TABLE via_new (x bigint)")
        assert b._control.stats["push_catalog"] > pushes
        assert b.catalog.has_table("via_new")
        assert a._control.ensure_authority() == "ok"
    finally:
        b.close()
        a.close()
