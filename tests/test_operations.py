"""Cluster operations: shard moves, rebalancer, background jobs,
maintenance cleanup (reference: operations/ + utils/background_jobs.c)."""

import os

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import CatalogError


def make_cluster(tmp_path, nodes=2):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=nodes)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={"k": np.arange(10000, dtype=np.int64),
                               "v": np.arange(10000, dtype=np.int64) % 97})
    return cl


def test_move_shard_placement(tmp_path):
    cl = make_cluster(tmp_path)
    before = cl.execute("SELECT count(*), sum(v) FROM t").rows
    t = cl.catalog.table("t")
    shard = t.shards[0]
    src = shard.placements[0]
    dst = 1 - src if src in (0, 1) else 0
    cl.execute(f"SELECT citus_move_shard_placement({shard.shard_id}, {src}, {dst})")
    assert cl.catalog.table("t").shards[0].placements == [dst]
    # data still correct after the move
    assert cl.execute("SELECT count(*), sum(v) FROM t").rows == before
    # source dir is recorded for deferred cleanup, then dropped
    from citus_tpu.operations import pending_cleanup, try_drop_orphaned_resources
    assert len(pending_cleanup(cl.catalog)) >= 1
    n = try_drop_orphaned_resources(cl.catalog)
    assert n >= 1
    assert not os.path.isdir(cl.catalog.shard_dir("t", shard.shard_id, src))
    cl.close()


def test_move_errors(tmp_path):
    cl = make_cluster(tmp_path)
    t = cl.catalog.table("t")
    shard = t.shards[0]
    src = shard.placements[0]
    with pytest.raises(CatalogError):
        cl.execute(f"SELECT citus_move_shard_placement({shard.shard_id}, {src}, {src})")
    with pytest.raises(CatalogError):
        cl.execute(f"SELECT citus_move_shard_placement({shard.shard_id}, {1-src}, {src})")
    with pytest.raises(CatalogError):
        cl.execute(f"SELECT citus_move_shard_placement(999999, 0, 1)")
    cl.close()


def test_add_node_and_rebalance(tmp_path):
    cl = make_cluster(tmp_path, nodes=2)
    before = sorted(cl.execute("SELECT v, count(*) FROM t GROUP BY v").rows)
    r = cl.execute("SELECT citus_add_node('worker-2', 5432)")
    new_node = r.rows[0][0]
    assert new_node == 2
    plan = cl.execute("SELECT get_rebalance_table_shards_plan('t')")
    assert plan.rowcount >= 1  # new empty node attracts moves
    moved = cl.execute("SELECT rebalance_table_shards('t')").rows[0][0]
    assert moved >= 1
    # placements now cover the new node
    nodes_used = {p for s in cl.catalog.table("t").shards for p in s.placements}
    assert new_node in nodes_used
    assert sorted(cl.execute("SELECT v, count(*) FROM t GROUP BY v").rows) == before
    # idempotent: already balanced
    again = cl.execute("SELECT get_rebalance_table_shards_plan('t')")
    assert again.rowcount == 0
    cl.close()


def test_colocated_shards_move_together(tmp_path):
    cl = make_cluster(tmp_path)
    cl.execute("CREATE TABLE t2 (k bigint NOT NULL, w bigint)")
    cl.execute("SELECT create_distributed_table('t2', 'k', 4)")
    cl.copy_from("t2", columns={"k": np.arange(5000, dtype=np.int64),
                                "w": np.arange(5000, dtype=np.int64)})
    t, t2 = cl.catalog.table("t"), cl.catalog.table("t2")
    assert t.colocation_id == t2.colocation_id
    shard = t.shards[2]
    src = shard.placements[0]
    dst = 1 - src
    join_before = cl.execute(
        "SELECT count(*) FROM t JOIN t2 ON t.k = t2.k").rows
    cl.execute(f"SELECT citus_move_shard_placement({shard.shard_id}, {src}, {dst})")
    assert cl.catalog.table("t").shards[2].placements == [dst]
    assert cl.catalog.table("t2").shards[2].placements == [dst]
    assert cl.execute("SELECT count(*) FROM t JOIN t2 ON t.k = t2.k").rows == join_before
    cl.close()


def test_background_rebalance_job(tmp_path):
    cl = make_cluster(tmp_path, nodes=2)
    cl.execute("SELECT citus_add_node('w', 1)")
    jid = cl.execute("SELECT citus_rebalance_start()").rows[0][0]
    status = cl.execute(f"SELECT citus_job_wait({jid})").rows[0][0]
    assert status == "done"
    nodes_used = {p for s in cl.catalog.table("t").shards for p in s.placements}
    assert 2 in nodes_used
    assert cl.execute("SELECT count(*) FROM t").rows == [(10000,)]
    cl.close()


def test_background_job_retry_and_failure(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=1)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")

    def always_fails():
        raise RuntimeError("permanent")

    r = cl.background_jobs
    r.register("flaky", flaky)
    r.register("boom", always_fails)
    jid = r.create_job("test")
    r.add_task(jid, "flaky", {}, max_attempts=5)
    assert r.wait_for_job(jid) == "done"
    assert calls["n"] == 3
    jid2 = r.create_job("failing")
    r.add_task(jid2, "boom", {}, max_attempts=2)
    assert r.wait_for_job(jid2) == "failed"
    cl.close()


def test_background_job_dependencies(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=1)
    order = []
    r = cl.background_jobs
    r.register("step", lambda name: order.append(name))
    jid = r.create_job("ordered")
    t1 = r.add_task(jid, "step", {"name": "a"})
    t2 = r.add_task(jid, "step", {"name": "b"}, depends_on=[t1])
    r.add_task(jid, "step", {"name": "c"}, depends_on=[t2])
    assert r.wait_for_job(jid) == "done"
    assert order == ["a", "b", "c"]
    cl.close()


def test_maintenance_daemon_runs_cleanup(tmp_path):
    cl = make_cluster(tmp_path)
    from citus_tpu.operations import record_cleanup, pending_cleanup
    victim = str(tmp_path / "orphan")
    os.makedirs(victim)
    record_cleanup(cl.catalog, victim)
    cl.maintenance.run_once()
    assert not os.path.exists(victim)
    assert pending_cleanup(cl.catalog) == []
    cl.close()


def test_remove_node_guard(tmp_path):
    cl = make_cluster(tmp_path)
    with pytest.raises(CatalogError):
        cl.execute("SELECT citus_remove_node(0)")  # still has placements
    cl.execute("SELECT citus_add_node('x', 1)")
    cl.execute("SELECT citus_remove_node(2)")  # fresh empty node: ok
    assert 2 not in cl.catalog.nodes
    cl.close()


def test_split_shard(tmp_path):
    cl = make_cluster(tmp_path)
    before = cl.execute("SELECT count(*), sum(v) FROM t").rows
    t = cl.catalog.table("t")
    shard = t.shards[0]
    mid = (shard.hash_min + shard.hash_max) // 2
    r = cl.execute(f"SELECT citus_split_shard_by_split_points({shard.shard_id}, {mid})")
    assert r.rowcount == 2
    t = cl.catalog.table("t")
    assert t.shard_count == 5
    # ranges still tile the int32 space contiguously
    for a, b in zip(t.shards, t.shards[1:]):
        assert b.hash_min == a.hash_max + 1
    assert cl.execute("SELECT count(*), sum(v) FROM t").rows == before
    # router queries still find their rows
    assert cl.execute("SELECT count(*) FROM t WHERE k = 123").rows == [(1,)]
    from citus_tpu.operations import try_drop_orphaned_resources
    assert try_drop_orphaned_resources(cl.catalog) >= 1
    assert cl.execute("SELECT count(*), sum(v) FROM t").rows == before
    cl.close()


def test_split_colocated_group(tmp_path):
    cl = make_cluster(tmp_path)
    cl.execute("CREATE TABLE t2 (k bigint NOT NULL, w bigint)")
    cl.execute("SELECT create_distributed_table('t2', 'k', 4)")
    cl.copy_from("t2", columns={"k": np.arange(3000, dtype=np.int64),
                                "w": np.ones(3000, dtype=np.int64)})
    join_before = cl.execute("SELECT count(*) FROM t JOIN t2 ON t.k = t2.k").rows
    t = cl.catalog.table("t")
    shard = t.shards[2]
    mid = (shard.hash_min + shard.hash_max) // 2
    cl.execute(f"SELECT citus_split_shard_by_split_points({shard.shard_id}, {mid})")
    assert cl.catalog.table("t").shard_count == 5
    assert cl.catalog.table("t2").shard_count == 5
    # colocated joins still align shard-by-shard
    assert cl.execute("SELECT count(*) FROM t JOIN t2 ON t.k = t2.k").rows == join_before
    cl.close()


def test_isolate_tenant(tmp_path):
    cl = make_cluster(tmp_path)
    r = cl.execute("SELECT isolate_tenant_to_new_shard('t', 42)")
    new_shard = r.rows[0][0]
    t = cl.catalog.table("t")
    iso = [s for s in t.shards if s.shard_id == new_shard][0]
    from citus_tpu.catalog.hashing import hash_int64_scalar
    h = hash_int64_scalar(42)
    assert iso.hash_min <= h <= iso.hash_max
    assert iso.hash_max - iso.hash_min <= 1
    assert cl.execute("SELECT count(*) FROM t WHERE k = 42").rows == [(1,)]
    assert cl.execute("SELECT count(*) FROM t").rows == [(10000,)]
    cl.close()


def test_shard_replication_factor(tmp_path):
    """shard_replication_factor places replicas; reads fail over when a
    placement directory is lost; writes hit every placement."""
    import shutil

    import numpy as np
    from citus_tpu.config import Settings, ShardingSettings
    cl = ct.Cluster(str(tmp_path / "rf"), n_nodes=3, settings=Settings(
        sharding=ShardingSettings(shard_count=6, shard_replication_factor=2)))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k')")
    t = cl.catalog.table("t")
    assert all(len(s.placements) == 2 for s in t.shards)
    cl.copy_from("t", columns={"k": np.arange(5000), "v": np.arange(5000)})
    cl.execute("UPDATE t SET v = 0 WHERE k < 100")
    expected = 12497500 - 4950
    assert cl.execute("SELECT sum(v) FROM t").rows == [(expected,)]
    # lose one replica of every shard: reads fail over, results unchanged.
    # Drop the HBM batch cache first — it would (validly) serve the query
    # without touching the lost placement, and this test is about the
    # disk-read failover path.
    for s in t.shards:
        shutil.rmtree(cl.catalog.shard_dir("t", s.shard_id, s.placements[0]),
                      ignore_errors=True)
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    GLOBAL_CACHE.clear()
    assert cl.execute("SELECT count(*), sum(v) FROM t").rows == \
        [(5000, expected)]
    assert cl.counters.snapshot().get("connection_failovers", 0) > 0
    cl.close()


def test_rebalance_by_shard_count(tmp_path):
    """pg_dist_rebalance_strategy built-ins: by_shard_count weighs every
    colocation group equally (by_disk_size remains the default)."""
    import numpy as np
    cl = ct.Cluster(str(tmp_path / "rbs"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 8)")
    cl.copy_from("t", columns={"k": np.arange(10000), "v": np.arange(10000)})
    cl.execute("SELECT citus_add_node('w', 1)")
    cl.execute("SELECT rebalance_table_shards('t', 'by_shard_count')")
    t = cl.catalog.table("t")
    per_node = {}
    for s in t.shards:
        per_node[s.placements[0]] = per_node.get(s.placements[0], 0) + 1
    assert max(per_node.values()) - min(per_node.values()) <= 1
    assert cl.execute("SELECT count(*), sum(v) FROM t").rows == \
        [(10000, 49995000)]
    from citus_tpu.errors import CatalogError
    with pytest.raises(CatalogError):
        cl.execute("SELECT rebalance_table_shards('t', 'bogus')")
    cl.close()


def test_node_disable_activate_and_admin_udfs(tmp_path):
    import numpy as np
    from citus_tpu.config import Settings, ShardingSettings
    cl = ct.Cluster(str(tmp_path / "adm"), n_nodes=3, settings=Settings(
        sharding=ShardingSettings(shard_count=6, shard_replication_factor=2)))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k')")
    cl.copy_from("t", columns={"k": np.arange(3000), "v": np.arange(3000)})
    sid = cl.execute(
        "SELECT get_shard_id_for_distribution_column('t', 42)").rows[0][0]
    assert any(s.shard_id == sid for s in cl.catalog.table("t").shards)
    assert cl.execute("SELECT citus_relation_size('t')").rows[0][0] > 0
    cl.execute("SELECT citus_disable_node(0)")
    assert cl.execute("SELECT citus_get_active_worker_nodes()").rows == \
        [(1,), (2,)]
    # reads route around the disabled node; results identical
    assert cl.execute("SELECT count(*), sum(v) FROM t").rows == \
        [(3000, 4498500)]
    cl.execute("SELECT citus_activate_node(0)")
    assert len(cl.execute("SELECT citus_get_active_worker_nodes()").rows) == 3
    cl.close()
