"""Streaming fused device hash aggregation with pushable partials.

Covers the PR's acceptance surface:
- fused-vs-cpu oracle across cardinalities below / at / above the slot
  count plus a spill-heavy adversarial keyset (slots << groups);
- float group-key equality: -0.0 and 0.0 group together, every NaN
  payload is ONE group, on both the device and host-spill halves
  (sqlite oracle for mixed-sign zeros; NaN maps to sqlite's NULL);
- exactly ONE fused dispatch per batch (`hash_fused_dispatches`) and
  ZERO `jit_table_merge_*` / `jit_hash_worker` kernel slots;
- streaming peak device window stays ≤ 2× batch bytes with the HBM
  cache capped out of the way and depth 1;
- `citus.hash_agg_slots = auto` sizes from catalog row stats and the
  EXPLAIN ANALYZE `Hash:` line reports slots / occupancy / spill;
- 2-host push: hash-table partials ship as TASK_VERSION 3 "hash"
  tasks (`hash_partials_pushed` rises, zero fallbacks, zero placement
  sync) byte-identical to the pull path, and a TASK_VERSION-2 peer
  falls back to pull cleanly.
"""

import math
import re

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.executor.device_cache import GLOBAL_CACHE
from citus_tpu.executor.executor import GLOBAL_COUNTERS
from citus_tpu.executor.kernel_cache import GLOBAL_KERNELS


@pytest.fixture()
def cl(tmp_path):
    return ct.Cluster(str(tmp_path / "db"))


@pytest.fixture()
def one_device(monkeypatch):
    """Pin the executor to the single-device path (conftest forces 8
    virtual host devices)."""
    import jax
    real = jax.devices()
    monkeypatch.setattr(jax, "devices", lambda *a, **k: real[:1])
    return real[0]


@pytest.fixture()
def pair(tmp_path):
    """Two coordinators, two data dirs, one logical cluster: A is the
    metadata authority hosting node 0; B attaches and hosts node 1."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    na = a.register_node()
    b = ct.Cluster(str(tmp_path / "b"), data_port=0, hosted_nodes=set(),
                   coordinator=("127.0.0.1", a.control_port), n_nodes=0)
    nb = b.register_node()
    a._maybe_reload_catalog(force_sync=True)
    yield a, b, na, nb
    b.close()
    a.close()


def _delta(c0, c1, name):
    return c1[name] - c0[name]


def _fill_groups(cl, n, groups, shards=4, table="t"):
    cl.execute(f"CREATE TABLE {table} "
               "(k bigint NOT NULL, g bigint, v bigint)")
    cl.execute(f"SELECT create_distributed_table('{table}', 'k', {shards})")
    rng = np.random.default_rng(groups)
    # key domain far wider than direct_gid_limit -> hash_host mode
    g = rng.integers(0, 10**12, groups)[rng.integers(0, groups, n)]
    v = rng.integers(0, 1000, n)
    cl.copy_from(table, columns={"k": np.arange(n, dtype=np.int64),
                                 "g": g, "v": v})
    return g, v


def _assert_hash_mode(cl, sql):
    from citus_tpu.planner import parse_sql
    from citus_tpu.planner.bind import bind_select
    from citus_tpu.planner.physical import plan_select
    plan = plan_select(cl.catalog, bind_select(cl.catalog, parse_sql(sql)[0]))
    assert plan.group_mode.kind == "hash_host"


SQL = "SELECT g, count(*), sum(v), min(v), max(v) FROM t GROUP BY g"


@pytest.mark.parametrize("slots,groups", [
    (4096, 700),      # cardinality below the slot count
    (1024, 1024),     # at the slot count
    (1024, 3000),     # above: second-chance probes + spills engaged
])
def test_fused_matches_cpu_oracle_across_cardinalities(
        cl, one_device, slots, groups):
    _fill_groups(cl, 30_000, groups)
    cl.execute(f"SET citus.hash_agg_slots = {slots}")
    _assert_hash_mode(cl, SQL)
    fused = sorted(cl.execute(SQL).rows)
    cl.execute("SET citus.task_executor_backend = 'cpu'")
    cpu = sorted(cl.execute(SQL).rows)
    cl.execute("SET citus.task_executor_backend = 'tpu'")
    assert fused == cpu
    assert len(fused) == groups


def test_spill_heavy_adversarial_keyset_stays_exact(cl, one_device):
    """slots=64 against ~20000 groups: nearly every row loses both
    probes — the exact host spill path carries the query."""
    import collections
    g, v = _fill_groups(cl, 40_000, 20_000)
    cl.execute("SET citus.hash_agg_slots = 64")
    c0 = cl.counters.snapshot()
    got = sorted(cl.execute("SELECT g, count(*), sum(v) FROM t GROUP BY g").rows)
    c1 = cl.counters.snapshot()
    assert _delta(c0, c1, "hash_spill_rows") > 0
    truth = collections.defaultdict(lambda: [0, 0])
    for gi, vi in zip(g.tolist(), v.tolist()):
        truth[gi][0] += 1
        truth[gi][1] += vi
    assert got == sorted((gi, c, s) for gi, (c, s) in truth.items())


def test_one_dispatch_per_batch_zero_merge_slots(cl, one_device):
    _fill_groups(cl, 20_000, 2000)
    GLOBAL_KERNELS.clear()
    GLOBAL_CACHE.clear()
    c0 = cl.counters.snapshot()
    r = cl.execute(SQL)
    c1 = cl.counters.snapshot()
    batches = len(r.explain["tasks"])
    assert batches >= 1
    # ONE fused dispatch per batch: insert AND merge ride together
    assert _delta(c0, c1, "hash_fused_dispatches") == batches
    assert r.explain["pipeline"]["fused_dispatches"] == batches
    slots = {k[1] for k in GLOBAL_KERNELS._e}
    assert "jit_hash_fused" in slots
    assert not any(s == "jit_hash_worker" or s.startswith("jit_table_merge")
                   for s in slots)
    assert len(r.rows) == 2000


def test_streaming_peak_window_bounded(cl, one_device):
    _fill_groups(cl, 30_000, 500, shards=4)
    old_cap = GLOBAL_CACHE.capacity
    GLOBAL_CACHE.clear()
    GLOBAL_CACHE.capacity = 1  # force the streaming path
    cl.execute("SET citus.executor_prefetch_depth = 1")
    cl.execute("SET citus.max_tasks_in_flight = 1")
    try:
        r = cl.execute(f"EXPLAIN ANALYZE {SQL}")
        text = "\n".join(l for (l,) in r.rows)
        m = re.search(r"stream window peak (\d+) bytes", text)
        h = re.search(r"H2D (\d+) bytes", text)
        d = re.search(r"fused dispatches (\d+)", text)
        assert m and h and d, text
        peak, h2d, nd = int(m.group(1)), int(h.group(1)), int(d.group(1))
        assert nd >= 2
        # with depth 1 the un-synced device window never holds more
        # than 2× one batch's bytes (table slots are accounted apart)
        assert peak <= 2 * (h2d / nd)
        assert GLOBAL_CACHE.memory_view()["live_bytes"] == 0
    finally:
        GLOBAL_CACHE.capacity = old_cap


def test_auto_slots_and_explain_hash_line(cl, one_device):
    _fill_groups(cl, 25_000, 900)
    cl.execute("SET citus.hash_agg_slots = auto")
    assert cl.execute("SHOW citus.hash_agg_slots").rows == [("0",)]
    r = cl.execute(f"EXPLAIN ANALYZE {SQL}")
    text = "\n".join(l for (l,) in r.rows)
    m = re.search(r"hash slots (\d+), occupancy ([\d.]+)%, "
                  r"spilled (\d+) rows", text)
    assert m, text
    S = int(m.group(1))
    # auto: next pow2 of the catalog row count, clamped [1024, 1<<20]
    assert 1024 <= S <= 1 << 20 and S & (S - 1) == 0
    assert S >= 25_000 or S == 1 << 20
    assert 0.0 <= float(m.group(2)) <= 100.0
    cl.execute("SET citus.hash_agg_slots = 2048")
    assert cl.execute("SHOW citus.hash_agg_slots").rows == [("2048",)]
    cl.execute("SET citus.hash_agg_slots = 8192")


def test_float_keys_negative_zero_and_nan_group_once(cl, one_device):
    """-0.0 groups with 0.0 and every NaN is ONE group, exact vs the
    sqlite oracle (sqlite stores NaN as NULL: our NaN group maps to its
    NULL group) and byte-identical across backends."""
    import sqlite3
    cl.execute("CREATE TABLE f (k bigint NOT NULL, f double, v bigint)")
    cl.execute("SELECT create_distributed_table('f', 'k', 2)")
    base = [0.0, -0.0, float("nan"), 1.5, -1.5, float("nan"), 0.0, -0.0,
            2.5, float("-inf")]
    n = 4000
    fs = np.array([base[i % len(base)] for i in range(n)])
    vs = np.arange(n, dtype=np.int64) % 13
    cl.copy_from("f", columns={"k": np.arange(n, dtype=np.int64),
                               "f": fs, "v": vs})
    sql = "SELECT f, count(*), sum(v) FROM f GROUP BY f"
    # small slot table forces some rows through the host spill half too
    cl.execute("SET citus.hash_agg_slots = 1024")
    ours = cl.execute(sql).rows
    cl.execute("SET citus.task_executor_backend = 'cpu'")
    cpu = cl.execute(sql).rows
    cl.execute("SET citus.task_executor_backend = 'tpu'")
    assert sorted(map(repr, ours)) == sorted(map(repr, cpu))

    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE f (f REAL, v INTEGER)")
    sq.executemany("INSERT INTO f VALUES (?,?)",
                   list(zip(fs.tolist(), vs.tolist())))

    def norm(rows):
        out = []
        for key, c, s in rows:
            if key is not None and isinstance(key, float):
                if math.isnan(key):
                    key = None      # sqlite renders NaN as NULL
                elif key == 0.0:
                    key = 0.0       # fold -0.0 representatives
            out.append((key, c, s))
        return sorted(out, key=repr)

    theirs = [tuple(r) for r in sq.execute(sql).fetchall()]
    assert norm(ours) == norm(theirs)
    # one row per distinct canonical key: 0.0/-0.0 merged, NaNs merged
    assert len(ours) == 6


def test_hash_groupby_rides_megabatch(cl, one_device):
    """hash_host families coalesce under `batched:jit_hash_fused`:
    concurrent literal variants return exactly their serial rows."""
    import threading
    _fill_groups(cl, 12_000, 800)
    queries = [f"SELECT g, count(*), sum(v) FROM t WHERE v < {900 + i} "
               "GROUP BY g ORDER BY g" for i in range(4)]
    serial = [cl.execute(q).rows for q in queries]
    cl.execute("SET citus.megabatch_window_ms = 50")
    cl.execute("SET citus.megabatch_max_size = 4")
    try:
        c0 = cl.counters.snapshot()
        got = [None] * len(queries)
        bar = threading.Barrier(len(queries))

        def run(i):
            bar.wait()
            got[i] = cl.execute(queries[i]).rows
        ts = [threading.Thread(target=run, args=(i,))
              for i in range(len(queries))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        c1 = cl.counters.snapshot()
        assert got == serial
        assert _delta(c0, c1, "megabatch_queries") >= 2
        assert "batched:jit_hash_fused" in {k[1] for k in GLOBAL_KERNELS._e}
    finally:
        cl.execute("SET citus.megabatch_window_ms = 0")


# ------------------------------------------------------- 2-host push


def _load_pair(a, n=20_000, groups=3000):
    a.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v bigint)")
    a.execute("SELECT create_distributed_table('t', 'k', 4)")
    rng = np.random.default_rng(5)
    g = rng.integers(0, 10**12, groups)[rng.integers(0, groups, n)]
    v = rng.integers(0, 1000, n)
    a.copy_from("t", columns={"k": np.arange(n, dtype=np.int64),
                              "g": g, "v": v})
    GLOBAL_CACHE.clear()
    GLOBAL_COUNTERS.reset()
    return g, v


def test_push_hash_partials_byte_identical_to_pull(pair):
    """A cross-host hash_host GROUP BY ships hash-table partials
    (TASK_VERSION 3 "hash" tasks): remote_tasks_pushed rises, zero
    fallbacks, zero placement sync — and the rows are byte-identical
    to the pull path's."""
    a, b, na, nb = pair
    _load_pair(a)
    sql = ("SELECT g, count(*), sum(v), min(v), max(v) FROM t "
           "GROUP BY g ORDER BY g")
    _assert_hash_mode(a, sql)
    pushed = a.execute(sql).rows
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["remote_tasks_pushed"] >= 1
    assert snap["remote_task_fallbacks"] == 0
    assert snap["hash_partials_pushed"] >= 1
    assert snap["placement_sync_bytes"] == 0
    a.execute("SET citus.remote_task_execution = pull")
    GLOBAL_CACHE.clear()
    c0 = GLOBAL_COUNTERS.snapshot()
    pulled = a.execute(sql).rows
    c1 = GLOBAL_COUNTERS.snapshot()
    a.execute("SET citus.remote_task_execution = auto")
    assert _delta(c0, c1, "remote_tasks_pushed") == 0
    assert pushed == pulled


def test_task_version_2_peer_falls_back_to_pull(pair, monkeypatch):
    """A peer that still speaks TASK_VERSION 2 rejects the "hash" task
    server-side; the coordinator counts the fallback and rescans the
    shard through the pull path — rows stay correct."""
    import collections
    from citus_tpu.executor import worker_tasks
    a, b, na, nb = pair
    g, v = _load_pair(a)
    real = worker_tasks.encode_task

    def stale(plan, params=((), ())):
        t = real(plan, params)
        return dict(t, v=2) if t is not None else None
    monkeypatch.setattr(worker_tasks, "encode_task", stale)
    got = sorted(a.execute("SELECT g, count(*), sum(v) FROM t GROUP BY g").rows)
    snap = GLOBAL_COUNTERS.snapshot()
    assert snap["remote_task_fallbacks"] >= 1
    assert snap["hash_partials_pushed"] == 0
    truth = collections.defaultdict(lambda: [0, 0])
    for gi, vi in zip(g.tolist(), v.tolist()):
        truth[gi][0] += 1
        truth[gi][1] += vi
    assert got == sorted((gi, c, s) for gi, (c, s) in truth.items())
