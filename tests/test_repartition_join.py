"""Repartition (all_to_all) hash joins — VERDICT round-2 item #2.

Non-colocated equi-joins shuffle both sides by join-key hash (device
all_to_all on a multi-device mesh; host bucketing on the cpu oracle) and
join per bucket, instead of pulling everything to the coordinator.
Reference: MapMergeJob (multi_physical_planner.h:160), DAG execution
(directed_acyclic_graph_execution.c:57)."""

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import ExecutorSettings, PlannerSettings, Settings


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("""CREATE TABLE orders (o_orderkey bigint NOT NULL,
        o_custkey bigint, o_flag bigint, o_tag text)""")
    cl.execute("""CREATE TABLE lineitem (l_linenumber bigint NOT NULL,
        l_orderkey bigint, l_qty bigint)""")
    cl.execute("CREATE TABLE nation (n_id bigint NOT NULL, n_name text)")
    cl.execute("SELECT create_distributed_table('orders', 'o_orderkey', 4)")
    cl.execute("SELECT create_distributed_table('lineitem', 'l_linenumber', 4)")
    cl.execute("SELECT create_reference_table('nation')")
    rng = np.random.default_rng(3)
    n_o, n_l = 2000, 8000
    cl.copy_from("orders", columns={
        "o_orderkey": np.arange(n_o),
        "o_custkey": rng.integers(0, 200, n_o),
        "o_flag": rng.integers(0, 3, n_o),
        "o_tag": [f"t{i%5}" for i in range(n_o)]})
    cl.copy_from("lineitem", columns={
        "l_linenumber": np.arange(n_l),
        "l_orderkey": rng.integers(0, n_o + 200, n_l),  # some unmatched
        "l_qty": rng.integers(1, 50, n_l)})
    cl.copy_from("nation", columns={"n_id": np.arange(3),
                                    "n_name": ["aa", "bb", "cc"]})
    yield cl
    cl.close()


def pull_cluster(tmp_path):
    return ct.Cluster(str(tmp_path / "db"), settings=Settings(
        planner=PlannerSettings(enable_repartition_joins=False)))


def assert_matches_pull(db, tmp_path, sql, expect_strategy="join:repartition"):
    r = db.execute(sql)
    assert r.explain["strategy"] == expect_strategy, r.explain
    pull = pull_cluster(tmp_path)
    try:
        r2 = pull.execute(sql)
        assert r2.explain["strategy"] == "join:pull"
        assert r.rows == r2.rows, (r.rows[:5], r2.rows[:5])
    finally:
        pull.close()
    return r


def test_q12_shape_agg(db, tmp_path):
    """TPC-H Q12 shape: join on a non-distribution key + GROUP BY."""
    r = assert_matches_pull(db, tmp_path, """
        SELECT o.o_flag, count(*), sum(l.l_qty)
        FROM lineitem l JOIN orders o ON l.l_orderkey = o.o_orderkey
        WHERE l.l_qty < 40
        GROUP BY o.o_flag ORDER BY o.o_flag""")
    assert len(r.rows) == 3
    # on the mesh the inner equi step joins ON DEVICE (all_to_all
    # exchange + per-device sort join); host mode buckets on the host
    assert r.explain["shuffle"].startswith(("all_to_all+1-devjoin", "host")), \
        r.explain


def test_projection_rows(db, tmp_path):
    assert_matches_pull(db, tmp_path, """
        SELECT l.l_linenumber, o.o_custkey
        FROM lineitem l JOIN orders o ON l.l_orderkey = o.o_orderkey
        ORDER BY l.l_linenumber LIMIT 100""")


def test_left_outer_preserves_unmatched(db, tmp_path):
    r = assert_matches_pull(db, tmp_path, """
        SELECT count(*), sum(l.l_qty)
        FROM lineitem l LEFT JOIN orders o ON l.l_orderkey = o.o_orderkey""")
    assert r.rows[0][0] == 8000  # every lineitem row preserved


def test_with_reference_table(db, tmp_path):
    """Replicated relation joins bucket-locally after the shuffle."""
    assert_matches_pull(db, tmp_path, """
        SELECT n.n_name, count(*)
        FROM lineitem l
        JOIN orders o ON l.l_orderkey = o.o_orderkey
        JOIN nation n ON o.o_flag = n.n_id
        GROUP BY n.n_name ORDER BY n.n_name""")


def test_text_key_join(db, tmp_path):
    """Join on a text column (dictionary-remapped ids)."""
    assert_matches_pull(db, tmp_path, """
        SELECT count(*)
        FROM lineitem l JOIN orders o ON l.l_orderkey = o.o_orderkey
        WHERE o.o_tag = 't1'""")


def test_cpu_backend_uses_host_shuffle(db, tmp_path):
    cpu = ct.Cluster(str(tmp_path / "db"), settings=Settings(
        executor=ExecutorSettings(task_executor_backend="cpu")))
    try:
        r = cpu.execute("""SELECT count(*) FROM lineitem l
            JOIN orders o ON l.l_orderkey = o.o_orderkey""")
        assert r.explain["strategy"] == "join:repartition"
        assert r.explain["shuffle"] == "host"
        r2 = db.execute("""SELECT count(*) FROM lineitem l
            JOIN orders o ON l.l_orderkey = o.o_orderkey""")
        assert r.rows == r2.rows
    finally:
        cpu.close()


def test_colocated_still_colocated(db):
    """Same-key joins keep the colocated strategy (no shuffle)."""
    db.execute("""CREATE TABLE payments (p_orderkey bigint NOT NULL,
        p_amt bigint)""")
    db.execute("SELECT create_distributed_table('payments', 'p_orderkey', 4)")
    db.copy_from("payments", columns={
        "p_orderkey": np.arange(500), "p_amt": np.ones(500, np.int64)})
    r = db.execute("""SELECT count(*) FROM orders o
        JOIN payments p ON o.o_orderkey = p.p_orderkey""")
    assert r.explain["strategy"] == "join:colocated"


def test_three_distributed_rels_stepwise_dag(db, tmp_path):
    """Three distributed relations on two different join keys: the
    step-wise shuffle DAG repartitions per step (2 shuffles), matching
    the pull result exactly."""
    db.execute("CREATE TABLE extra (e_id bigint NOT NULL, e_k bigint)")
    db.execute("SELECT create_distributed_table('extra', 'e_id', 4)")
    db.copy_from("extra", columns={"e_id": np.arange(100),
                                   "e_k": np.arange(100)})
    sql = """SELECT count(*) FROM lineitem l
        JOIN orders o ON l.l_orderkey = o.o_orderkey
        JOIN extra e ON e.e_k = l.l_qty"""
    r = db.execute(sql)
    assert r.explain["strategy"] == "join:repartition"
    assert r.explain["shuffle"].endswith("2-step"), r.explain
    pull = pull_cluster(tmp_path)
    try:
        r2 = pull.execute(sql)
        assert r2.explain["strategy"] == "join:pull"
        assert r.rows == r2.rows
    finally:
        pull.close()
