"""Streaming scan regression tests: the executor must never materialize
a working set larger than the HBM batch cache, on either the
single-device or the multi-device mesh path.

Round-3 VERDICT gaps closed here: the flagship streaming pipeline had no
dedicated test (weak #2/#9), the mesh path loaded every batch up front
(weak #3), and the mesh path never populated the HBM cache (weak #8).
"""

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import ExecutorSettings, settings_override
from citus_tpu.executor.device_cache import GLOBAL_CACHE
from citus_tpu.executor import executor as ex

SQL = "SELECT s, count(*), sum(v), min(v), max(v) FROM t GROUP BY s ORDER BY s"


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (k bigint, v bigint, s bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 16)")
    rows = [(i, i % 1000, i % 3) for i in range(20000)]
    cl.copy_from("t", rows=rows)
    GLOBAL_CACHE.clear()
    yield cl
    GLOBAL_CACHE.clear()


def oracle(cl, sql):
    """Numpy-path reference result.  Cluster.settings is captured at
    construction, so swap it in place (settings_override alone only
    affects clusters constructed under it)."""
    import dataclasses
    old = cl.settings
    cl.settings = dataclasses.replace(
        old, executor=ExecutorSettings(task_executor_backend="cpu"))
    try:
        return cl.execute(sql).rows
    finally:
        cl.settings = old
        GLOBAL_CACHE.clear()


def test_mesh_streams_past_cache_capacity(db, monkeypatch):
    """Working set > capacity: the mesh path must stream round by round
    (never _load_all_batches) and pin nothing."""
    expect = oracle(db, SQL)
    monkeypatch.setattr(GLOBAL_CACHE, "capacity", 1)  # force streaming

    def boom(*a, **k):
        raise AssertionError("mesh agg path materialized all batches")
    monkeypatch.setattr(ex, "_load_all_batches", boom)
    got = db.execute(SQL).rows
    assert got == expect
    assert GLOBAL_CACHE._entries == {}, "pinned past capacity"


def test_mesh_populates_hbm_cache_and_rehits(db):
    """Weak #8: the mesh path now puts its device-sharded rounds into
    the cache; a repeat query serves from HBM."""
    expect = oracle(db, SQL)
    assert db.execute(SQL).rows == expect
    assert len(GLOBAL_CACHE._entries) == 1
    (key, _entry), = GLOBAL_CACHE._entries.items()
    assert "mesh" in key, key
    h0 = GLOBAL_CACHE.hits
    assert db.execute(SQL).rows == expect
    assert GLOBAL_CACHE.hits == h0 + 1


def test_single_device_streams_past_capacity(db, monkeypatch):
    """The single-device streaming pipeline (round 3's flagship) —
    pinned behind a 1-device view of the platform."""
    import jax
    expect = oracle(db, SQL)
    real = jax.devices()
    monkeypatch.setattr(jax, "devices", lambda *a: real[:1])
    monkeypatch.setattr(GLOBAL_CACHE, "capacity", 1)
    got = db.execute(SQL).rows
    assert got == expect
    assert GLOBAL_CACHE._entries == {}, "pinned past capacity"


def test_single_device_pins_when_it_fits(db, monkeypatch):
    import jax
    expect = oracle(db, SQL)
    real = jax.devices()
    monkeypatch.setattr(jax, "devices", lambda *a: real[:1])
    assert db.execute(SQL).rows == expect
    assert len(GLOBAL_CACHE._entries) == 1
    h0 = GLOBAL_CACHE.hits
    assert db.execute(SQL).rows == expect
    assert GLOBAL_CACHE.hits == h0 + 1


def test_transaction_overlay_bypasses_cache(db):
    """Staged writes change what a scan sees without a version bump —
    the overlayed table must not hit or pollute the cache."""
    expect = oracle(db, SQL)
    assert db.execute(SQL).rows == expect  # populates the cache
    s = db.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO t VALUES (999999, 5, 0)")
    in_txn = s.execute("SELECT count(*) FROM t").rows
    assert in_txn == [(20001,)]
    s.execute("ROLLBACK")
    assert db.execute("SELECT count(*) FROM t").rows == [(20000,)]