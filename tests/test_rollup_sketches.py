"""Sketch serialization round-trips (rollup/sketches.py): every kind
encodes→decodes losslessly, word-level merge equals state-level merge,
words survive both wire codecs (frame + npz) and a storage write/read
cycle, and finalized estimates stay inside each sketch's documented
error bound."""

import glob
import os

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import Settings
from citus_tpu.errors import AnalysisError
from citus_tpu.net.data_plane import (
    _decode_arrays, _encode_arrays, arrays_to_sketch_words, decode_frame,
    encode_frame, sketch_words_to_arrays,
)
from citus_tpu.rollup import sketches as sk

KINDS = ("hll", "ddsk", "topk", "tdg")


def _random_state(kind: str, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    if kind == "hll":
        return rng.integers(0, 30, sk.HLL_M).astype(np.int32)
    if kind == "ddsk":
        s = np.zeros(sk.DDSK_M, np.int64)
        idx = rng.choice(sk.DDSK_M, 40, replace=False)
        s[idx] = rng.integers(1, 1000, idx.size)
        return s
    if kind == "topk":
        s = sk.empty_state("topk")
        idx = rng.choice(sk.TOPK_M, 25, replace=False)
        s[idx] = rng.integers(1, 500, idx.size)
        s[sk.TOPK_M + idx] = rng.integers(-10**9, 10**9, idx.size)
        return s
    return sk.tdg_from_values(rng.normal(50.0, 10.0, 500))


# ------------------------------------------------------ codec laws

@pytest.mark.parametrize("kind", KINDS)
def test_encode_decode_roundtrip(kind):
    state = _random_state(kind, 1)
    word = sk.encode_sketch(kind, state)
    # the word passes the SKETCH column type's envelope check
    assert word.split(":", 2)[0] == kind
    k2, s2 = sk.decode_sketch(word)
    assert k2 == kind
    assert np.array_equal(np.asarray(state), np.asarray(s2))


@pytest.mark.parametrize("kind", KINDS)
def test_word_merge_equals_state_merge(kind):
    a, b = _random_state(kind, 2), _random_state(kind, 3)
    direct = sk.merge_states(kind, a, b)
    via_words = sk.merge_sketch_words(
        sk.encode_sketch(kind, a), sk.encode_sketch(kind, b))
    _, merged = sk.decode_sketch(via_words)
    assert np.array_equal(np.asarray(direct), np.asarray(merged))


@pytest.mark.parametrize("kind", ("hll", "ddsk", "topk"))
def test_merge_commutative_associative(kind):
    a, b, c = (_random_state(kind, s) for s in (4, 5, 6))
    ab = sk.merge_states(kind, a, b)
    ba = sk.merge_states(kind, b, a)
    assert np.array_equal(ab, ba)
    assert np.array_equal(sk.merge_states(kind, ab, c),
                          sk.merge_states(kind, a, sk.merge_states(kind, b, c)))


def test_empty_state_is_merge_identity():
    for kind in ("hll", "ddsk", "topk"):
        s = _random_state(kind, 7)
        merged = sk.merge_states(kind, s, sk.empty_state(kind))
        assert np.array_equal(s, merged)


def test_cross_kind_merge_rejected():
    with pytest.raises(AnalysisError):
        sk.merge_sketch_words(sk.encode_sketch("hll", sk.empty_state("hll")),
                              sk.encode_sketch("ddsk", sk.empty_state("ddsk")))


@pytest.mark.parametrize("word", [
    "notakind:1:AAAA",
    "hll:9:AAAA",                       # unsupported version
    "hll:1:!!notbase64!!",
    "hll:1:" + "QQ==",                  # wrong payload size
    "plainstring",
])
def test_malformed_words_rejected(word):
    with pytest.raises(AnalysisError):
        sk.decode_sketch(word)


def test_sparse_decode_rejects_out_of_range_bucket():
    bad_idx = np.asarray([sk.DDSK_M + 5], "<i4").tobytes()
    payload = bad_idx + np.asarray([3], "<i8").tobytes()
    import base64
    word = "ddsk:1:" + base64.b64encode(payload).decode()
    with pytest.raises(AnalysisError):
        sk.decode_sketch(word)


# ----------------------------------------------------- error bounds

def test_hll_estimate_within_documented_bound():
    n = 5000
    from citus_tpu.rollup import kernels
    bits = kernels.value_bits(np.arange(n, dtype=np.int64) * 7919 + 13)
    gidx = np.zeros(n, np.int64)
    part = kernels.delta_partials("hll", gidx, np.ones(n, bool), 1, bits)
    word = sk.encode_sketch("hll", part[0])
    est, ok = sk.finalize_sketch("hll", sk.decode_sketch(word)[1])
    assert ok
    # documented 1-sigma error is ±9% (1.04/sqrt(128)); allow 3 sigma
    assert abs(est - n) / n < 0.27, est


def test_ddsk_percentile_within_relative_bound():
    rng = np.random.default_rng(12)
    vals = rng.lognormal(3.0, 1.0, 4000)
    from citus_tpu.rollup import kernels
    gidx = np.zeros(vals.size, np.int64)
    part = kernels.delta_partials("ddsk", gidx, np.ones(vals.size, bool),
                                  1, vals)
    _, state = sk.decode_sketch(sk.encode_sketch("ddsk", part[0]))
    for frac in (0.1, 0.5, 0.95):
        est, ok = sk.finalize_sketch("ddsk", state, frac)
        assert ok
        true = float(np.quantile(vals, frac))
        assert abs(est - true) / true < 0.06, (frac, est, true)


def test_tdg_percentile_within_rank_bound():
    rng = np.random.default_rng(13)
    vals = rng.uniform(0.0, 100.0, 4000)
    halves = [sk.tdg_from_values(vals[:2000]), sk.tdg_from_values(vals[2000:])]
    word = sk.merge_sketch_words(sk.encode_sketch("tdg", halves[0]),
                                 sk.encode_sketch("tdg", halves[1]))
    _, state = sk.decode_sketch(word)
    for frac in (0.1, 0.5, 0.9):
        est, ok = sk.finalize_sketch("tdg", state, frac)
        assert ok
        # uniform[0,100]: value error == 100 * rank error; ~2% documented
        assert abs(est - 100.0 * frac) < 5.0, (frac, est)


def test_topk_exact_on_skewed_input():
    from citus_tpu.rollup import kernels
    values = np.array([7] * 50 + [11] * 30 + [13] * 5, np.int64)
    gidx = np.zeros(values.size, np.int64)
    counts, vals = kernels.delta_partials(
        "topk", gidx, np.ones(values.size, bool), 1,
        kernels.value_bits(values))
    state = sk.empty_state("topk")
    state[:sk.TOPK_M] = counts[0]
    state[sk.TOPK_M:] = vals[0]
    word = sk.encode_sketch("topk", state)
    import json
    top, ok = sk.finalize_sketch("topk", sk.decode_sketch(word)[1], 2)
    assert ok
    got = json.loads(top)
    assert got[0] == {"value": 7, "count": 50}
    assert got[1] == {"value": 11, "count": 30}


# ----------------------------------------------------- wire formats

def _words_fixture():
    return [sk.encode_sketch(k, _random_state(k, i))
            for i, k in enumerate(KINDS)] + [None, "hll:1:" + "A" * 172]


@pytest.mark.parametrize("wire", ("frame", "npz"))
def test_sketch_words_wire_roundtrip(wire):
    words = _words_fixture()
    arrays = sketch_words_to_arrays("apct_v", words)
    blob = _encode_arrays(arrays, wire)
    back = _decode_arrays(blob)
    assert arrays_to_sketch_words(back, "apct_v") == words
    # merged-through-the-wire equals merged-locally
    a, b = words[0], sk.encode_sketch("hll", _random_state("hll", 42))
    wired = arrays_to_sketch_words(
        _decode_arrays(_encode_arrays(
            sketch_words_to_arrays("c", [a]), wire)), "c")[0]
    assert sk.merge_sketch_words(wired, b) == sk.merge_sketch_words(a, b)


def test_sketch_words_empty_and_all_null():
    for words in ([], [None, None]):
        arrays = sketch_words_to_arrays("x", words)
        blob = encode_frame(arrays)
        assert arrays_to_sketch_words(decode_frame(blob), "x") == words


# ---------------------------------------------------- storage cycle

def test_sketch_column_storage_roundtrip(tmp_path):
    """Words survive a real stripe write + reopen, merge correctly from
    storage, and the skip list never records min/max for them."""
    words = [sk.encode_sketch(k, _random_state(k, i + 20))
             for i, k in enumerate(("hll", "ddsk", "topk"))]
    db = str(tmp_path / "db")
    cl = ct.Cluster(db, n_nodes=1)
    cl.execute("CREATE TABLE st (k bigint, w sketch)")
    cl.execute("SELECT create_distributed_table('st', 'k', 2)")
    for i, w in enumerate(words):
        cl.execute(f"INSERT INTO st VALUES ({i}, '{w}')")
    cl.execute("INSERT INTO st VALUES (99, NULL)")
    cl.close()

    cl2 = ct.Cluster(db, n_nodes=1)
    try:
        got = dict(cl2.execute("SELECT k, w FROM st").rows)
        assert [got[i] for i in range(3)] == words
        assert got[99] is None
        # stored word is still mergeable state, not an opaque string
        merged = sk.merge_sketch_words(got[0], got[0])
        assert sk.decode_sketch(merged)[0] == "hll"
        # no min/max skip stats on the sketch stream (dictionary ids
        # carry no value order, so any stat would invite bogus pruning)
        from citus_tpu.storage.format import read_stripe_footer
        shard_dirs = {
            os.path.dirname(p) for p in glob.glob(
                os.path.join(db, "**", "stripe-*.cts"), recursive=True)}
        checked = 0
        for sd in shard_dirs:
            for stripe in glob.glob(os.path.join(sd, "stripe-*.cts")):
                footer = read_stripe_footer(stripe)
                if "w" not in footer.columns:
                    continue
                for cs in footer.columns["w"]:
                    assert cs.minimum is None and cs.maximum is None
                    checked += 1
                for cs in footer.columns["k"]:
                    assert cs.minimum is not None  # stats still on others
        assert checked, "no sketch column chunks found on disk"
    finally:
        cl2.close()


def test_invalid_word_rejected_at_insert(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=1,
                    settings=Settings())
    try:
        cl.execute("CREATE TABLE st (k bigint, w sketch)")
        with pytest.raises(AnalysisError):
            cl.execute("INSERT INTO st VALUES (1, 'not-a-sketch')")
    finally:
        cl.close()
