"""Regression tests for the round-4 advisor findings: partition-bound
enforcement on direct leaf writes, flip-latch crash cleanup, unique-probe
placement failover, CDC resume under HLC skew, and all-or-nothing
multi-table TRUNCATE locking."""

import datetime
import os
import shutil

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import AnalysisError


@pytest.fixture()
def pdb(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("""CREATE TABLE events (
        tenant bigint NOT NULL, ts date, amount bigint)
        PARTITION BY RANGE (ts)""")
    cl.execute("CREATE TABLE events_h1 PARTITION OF events "
               "FOR VALUES FROM ('2024-01-01') TO ('2024-07-01')")
    cl.execute("CREATE TABLE events_h2 PARTITION OF events "
               "FOR VALUES FROM ('2024-07-01') TO ('2025-01-01')")
    cl.execute("SELECT create_distributed_table('events', 'tenant', 4)")
    return cl


class TestPartitionBoundEnforcement:
    """High finding: a direct write into a leaf partition must respect
    the leaf's [lo, hi) bounds (PostgreSQL's implicit partition CHECK);
    otherwise parent queries that prune partitions silently lose rows."""

    def test_direct_leaf_copy_out_of_range_rejected(self, pdb):
        with pytest.raises(AnalysisError, match="partition constraint"):
            pdb.copy_from("events_h1",
                          rows=[(1, "2024-09-15", 10)])  # belongs in h2
        assert pdb.execute("SELECT count(*) FROM events_h1").rows == [(0,)]

    def test_direct_leaf_insert_null_partition_col_rejected(self, pdb):
        with pytest.raises(AnalysisError, match="partition constraint"):
            pdb.execute("INSERT INTO events_h1 VALUES (1, NULL, 10)")

    def test_direct_leaf_copy_in_range_ok(self, pdb):
        pdb.copy_from("events_h1", rows=[(1, "2024-03-01", 10)])
        assert pdb.execute("SELECT count(*) FROM events").rows == [(1,)]

    def test_leaf_update_moving_row_out_of_range_rejected(self, pdb):
        pdb.copy_from("events", rows=[(1, "2024-03-01", 10)])
        with pytest.raises(AnalysisError, match="partition constraint"):
            pdb.execute("UPDATE events_h1 SET ts = date '2024-12-01' "
                        "WHERE tenant = 1")
        # row unchanged, still visible through the pruned parent query
        assert pdb.execute(
            "SELECT count(*) FROM events WHERE ts < '2024-07-01'"
        ).rows == [(1,)]

    def test_parent_query_with_pruning_never_loses_rows(self, pdb):
        """The exact advisor scenario: an out-of-range leaf row would be
        invisible to a pruned parent query; the write must fail instead."""
        with pytest.raises(AnalysisError):
            pdb.execute("INSERT INTO events_h1 VALUES (7, '2024-10-01', 5)")
        total = pdb.execute("SELECT count(*) FROM events").rows[0][0]
        pruned = pdb.execute(
            "SELECT count(*) FROM events WHERE ts >= '2024-01-01'"
        ).rows[0][0]
        assert total == pruned == 0


def test_snapshot_dead_writer_generation_reaped(tmp_path):
    """Medium finding (round 4, carried into the snapshot design): a
    writer killed mid-flip must not lock readers out forever — readers
    reap a flip registration whose owner pid is dead."""
    import json

    from citus_tpu.config import ExecutorSettings, Settings
    st = Settings(executor=ExecutorSettings(lock_timeout_s=2.0))
    cl = ct.Cluster(str(tmp_path / "db"), settings=st)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={"k": np.arange(100), "v": np.arange(100)})
    from citus_tpu.transaction.snapshot import _snap_paths
    from citus_tpu.transaction.write_locks import group_resource
    res = group_resource(cl.catalog.table("t"))
    path, _lock = _snap_paths(cl.catalog.data_dir, res)
    # forge a crash: flip registered by a pid that no longer exists
    with open(path, "w") as f:
        json.dump({"gen": 7, "writers": {"999999999": 1}}, f)
    assert cl.execute("SELECT count(*) FROM t").rows == [(100,)]
    with open(path) as f:
        assert json.load(f)["writers"] == {}  # reader reaped it
    cl.close()


def test_snapshot_live_writer_mid_flip_times_out(tmp_path):
    """A flip registration owned by a LIVE process keeps holding
    readers off (they cannot observe a consistent generation)."""
    import json

    from citus_tpu.config import ExecutorSettings, Settings
    from citus_tpu.utils.filelock import LockTimeout
    st = Settings(executor=ExecutorSettings(lock_timeout_s=0.3))
    cl = ct.Cluster(str(tmp_path / "db"), settings=st)
    cl.execute("CREATE TABLE t (k bigint NOT NULL)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={"k": np.arange(10)})
    from citus_tpu.transaction.snapshot import _snap_paths
    from citus_tpu.transaction.write_locks import group_resource
    res = group_resource(cl.catalog.table("t"))
    path, _lock = _snap_paths(cl.catalog.data_dir, res)
    with open(path, "w") as f:
        json.dump({"gen": 7, "writers": {str(os.getpid()): 1}}, f)
    try:
        with pytest.raises(LockTimeout):
            cl.execute("SELECT count(*) FROM t")
    finally:
        os.remove(path)
    cl.close()


def test_unique_probe_fails_over_to_replica(tmp_path):
    """Medium finding: with the primary placement directory gone, the
    uniqueness probe must read the replica (like normal reads do) rather
    than silently admitting duplicates."""
    from citus_tpu.config import Settings, ShardingSettings
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2, settings=Settings(
        sharding=ShardingSettings(shard_count=4,
                                  shard_replication_factor=2)))
    cl.execute("CREATE TABLE u (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('u', 'k')")
    cl.execute("CREATE UNIQUE INDEX u_k_key ON u (k)")
    cl.copy_from("u", columns={"k": np.arange(200), "v": np.arange(200)})
    t = cl.catalog.table("u")
    for s in t.shards:
        shutil.rmtree(cl.catalog.shard_dir("u", s.shard_id, s.placements[0]),
                      ignore_errors=True)
    from citus_tpu.integrity import UniqueViolation
    with pytest.raises(UniqueViolation):
        cl.copy_from("u", rows=[(5, 99)])  # k=5 exists (on the replica)
    cl.close()


def test_cdc_resume_survives_multi_stride_hlc_skew(tmp_path):
    """Low finding: events(from_lsn) must not seek past records whose
    lsn exceeds from_lsn merely because emitter skew spans more than one
    index stride.  A skewed emitter writes an old lsn thousands of
    records (many strides) after its timestamp's position."""
    from citus_tpu.cdc import ChangeDataCapture
    cs = ChangeDataCapture(str(tmp_path / "db"), enabled=True)
    for i in range(3000):
        cs.emit("t", "insert", lsn=10_000 + i,
                rows=[[i, f"value-{i}"]], columns=["k", "v"])
        if i == 2500:  # skew far beyond one 16KiB index stride
            cs.emit("t", "insert", lsn=10_200,
                    rows=[[-1, "late"]], columns=["k", "v"])
    got = [r["lsn"] for r in cs.events("t", from_lsn=10_199)]
    # the late-written skewed record (lsn 10200, duplicated) AND every
    # larger lsn must all survive the seek
    assert got.count(10_200) == 2
    assert sorted(got) == sorted([10_200] + list(range(10_200, 13_000)))


def test_cdc_resume_cross_instance_prefix_max(tmp_path):
    """A second ChangeDataCapture over the same stream (another
    coordinator process) must fold the first's records into its index
    prefix-max rather than trusting its own (empty) history."""
    from citus_tpu.cdc import ChangeDataCapture
    d = str(tmp_path / "db")
    a = ChangeDataCapture(d, enabled=True)
    for i in range(1200):
        a.emit("t", "insert", lsn=5_000 + i, count=1)
    b = ChangeDataCapture(d, enabled=True)  # cold start, foreign bytes
    for i in range(1200):
        b.emit("t", "insert", lsn=6_200 + i, count=1)
    got = [r["lsn"] for r in b.events("t", from_lsn=6_150)]
    assert got == list(range(6_151, 7_400))


def test_multi_table_truncate_all_or_nothing(tmp_path):
    """Low finding: TRUNCATE a, b is all-or-nothing — a lock failure on
    b must surface BEFORE a is irreversibly emptied."""
    import subprocess
    import sys

    from citus_tpu.config import ExecutorSettings, Settings
    from citus_tpu.transaction.write_locks import group_resource, lockfile_path
    st = Settings(executor=ExecutorSettings(lock_timeout_s=1.0))
    cl = ct.Cluster(str(tmp_path / "db"), settings=st)
    cl.execute("CREATE TABLE a (x bigint)")
    cl.execute("CREATE TABLE b (x bigint)")
    cl.copy_from("a", rows=[(1,), (2,)])
    cl.copy_from("b", rows=[(3,)])
    res = group_resource(cl.catalog.table("b"))
    lockfile = lockfile_path(cl.catalog.data_dir, res)
    hold = subprocess.Popen(  # foreign process holds EXCLUSIVE on b
        [sys.executable, "-c", (
            "import fcntl, sys, time\n"
            "fd = open(sys.argv[1], 'w')\n"
            "fcntl.flock(fd, fcntl.LOCK_EX)\n"
            "print('held', flush=True)\n"
            "time.sleep(30)\n"), lockfile],
        stdout=subprocess.PIPE, text=True)
    try:
        assert hold.stdout.readline().strip() == "held"
        with pytest.raises(Exception):
            cl.execute("TRUNCATE a, b")
        # a must still hold its rows: no partial truncate happened
        assert cl.execute("SELECT count(*) FROM a").rows == [(2,)]
        assert cl.execute("SELECT count(*) FROM b").rows == [(1,)]
    finally:
        hold.kill()
        hold.wait()
    cl.close()
