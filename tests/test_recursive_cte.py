"""WITH RECURSIVE: coordinator-materialized iteration, diffed against
sqlite3's recursive CTEs.

Reference: recursive_planning.c:1175-1181 — the reference supports
recursive CTEs through materialization; iteration semantics (working
table = previous round's rows) are PostgreSQL's.
"""

import sqlite3

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import ExecutionError, UnsupportedFeatureError


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"))
    yield c
    c.close()


def test_counting_series(cl):
    r = cl.execute(
        "WITH RECURSIVE s(n) AS ("
        "  SELECT 1 UNION ALL SELECT n + 1 FROM s WHERE n < 10"
        ") SELECT n FROM s ORDER BY n")
    assert [row[0] for row in r.rows] == list(range(1, 11))
    assert r.columns == ["n"]


def test_union_distinct_terminates_on_cycle(cl):
    """UNION (distinct) terminates even when the recursive term
    revisits rows — the graph-walk termination property."""
    cl.execute("CREATE TABLE edges (src bigint NOT NULL, dst bigint)")
    cl.execute("SELECT create_distributed_table('edges', 'src', 4)")
    # a cycle: 1 -> 2 -> 3 -> 1, plus a tail 3 -> 4
    cl.copy_from("edges", rows=[(1, 2), (2, 3), (3, 1), (3, 4)])
    r = cl.execute(
        "WITH RECURSIVE reach(node) AS ("
        "  SELECT 1 UNION "
        "  SELECT e.dst FROM edges e, reach r WHERE e.src = r.node"
        ") SELECT node FROM reach ORDER BY node")
    assert [row[0] for row in r.rows] == [1, 2, 3, 4]


def test_hierarchy_walk_vs_sqlite(cl):
    """The VERDICT golden test: an org-hierarchy walk diffed against
    sqlite3's recursive CTEs."""
    cl.execute("CREATE TABLE emp (id bigint NOT NULL, boss bigint,"
               " salary bigint)")
    cl.execute("SELECT create_distributed_table('emp', 'id', 4)")
    rng = np.random.default_rng(7)
    rows = [(0, None, 100)]
    for i in range(1, 300):
        rows.append((i, int(rng.integers(0, i)), int(rng.integers(50, 150))))
    cl.copy_from("emp", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE emp (id INTEGER, boss INTEGER, salary INTEGER)")
    sq.executemany("INSERT INTO emp VALUES (?,?,?)", rows)
    query = ("WITH RECURSIVE chain(id, depth) AS ("
             "  SELECT id, 0 FROM emp WHERE boss IS NULL"
             "  UNION ALL"
             "  SELECT e.id, c.depth + 1 FROM emp e, chain c"
             "  WHERE e.boss = c.id"
             ") SELECT depth, count(*) FROM chain GROUP BY depth "
             "ORDER BY depth")
    ours = cl.execute(query).rows
    theirs = [tuple(r) for r in sq.execute(query).fetchall()]
    assert ours == theirs
    # and a filtered subtree (the router-ish case: walk from one root)
    q2 = ("WITH RECURSIVE sub(id) AS ("
          "  SELECT id FROM emp WHERE id = 5"
          "  UNION ALL"
          "  SELECT e.id FROM emp e, sub s WHERE e.boss = s.id"
          ") SELECT count(*) FROM sub")
    assert cl.execute(q2).rows == [tuple(sq.execute(q2).fetchone())]


def test_recursive_cte_feeding_body_join(cl):
    cl.execute("CREATE TABLE fact (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('fact', 'k', 4)")
    cl.copy_from("fact", columns={"k": np.arange(20),
                                  "v": np.arange(20) * 10})
    r = cl.execute(
        "WITH RECURSIVE keys(n) AS ("
        "  SELECT 0 UNION ALL SELECT n + 2 FROM keys WHERE n < 8"
        ") SELECT sum(f.v) FROM fact f, keys WHERE f.k = keys.n")
    assert r.rows == [(0 + 20 + 40 + 60 + 80,)]


def test_plain_with_still_works_with_recursive_keyword(cl):
    """WITH RECURSIVE where a CTE is NOT self-referencing behaves like a
    plain CTE (PostgreSQL allows the mix)."""
    r = cl.execute(
        "WITH RECURSIVE a(x) AS (SELECT 41), "
        "b(y) AS (SELECT x + 1 FROM a) SELECT y FROM b")
    assert r.rows == [(42,)]


def test_iteration_cap_raises(cl):
    with pytest.raises(ExecutionError, match="iterations"):
        cl.execute(
            "WITH RECURSIVE s(n) AS ("
            "  SELECT 1 UNION ALL SELECT n + 1 FROM s"
            ") SELECT count(*) FROM s")


def test_recursive_ref_in_first_arm_rejected(cl):
    with pytest.raises(UnsupportedFeatureError, match="second UNION arm"):
        cl.execute(
            "WITH RECURSIVE s(n) AS ("
            "  SELECT n FROM s UNION ALL SELECT 1"
            ") SELECT * FROM s")
