"""Multi-coordinator metadata sync + catalog-persisted tenant control
plane (citus_tpu/metadata/): version-vector convergence, replicated
quota writes, kill-matrix exactly-once apply, and plan/admission
equivalence across coordinators (the "query from any node" invariants).

Reference: Citus MX metadata sync (metadata_sync.c) is tested with real
multi-node clusters; here the cross-coordinator tests use real OS
processes sharing a data dir through the metadata authority.
"""

import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.metadata.sync import version_vector
from citus_tpu.workload import GLOBAL_TENANTS

ENV = dict(os.environ, JAX_PLATFORMS="cpu")


def _spawn(code: str) -> subprocess.Popen:
    body = "import jax\njax.config.update('jax_platforms','cpu')\n" + code
    return subprocess.Popen([sys.executable, "-c", body],
                            stdout=subprocess.PIPE, text=True, env=ENV)


@pytest.fixture(autouse=True)
def _clean_registry():
    GLOBAL_TENANTS.clear()
    yield
    GLOBAL_TENANTS.clear()


# ------------------------------------------------------- version vector


def test_version_vector_names_exactly_the_divergent_objects():
    base = {
        "format_version": 3,
        "tables": [{"name": "t", "version": 1}],
        "nodes": [{"node_id": 0, "is_active": True}],
        "next_shard_id": 102008, "next_colocation_id": 1,
        "schemas": {"public": {}},
        "tenant_quotas": {"7": {"weight": 1.0}},
    }
    v1 = version_vector(base)
    assert set(v1) == {"tables/t", "nodes/0", "allocators/next_shard_id",
                       "allocators/next_colocation_id", "schemas/public",
                       "tenant_quotas/7"}
    # touch one object: exactly one entry changes
    changed = dict(base, tables=[{"name": "t", "version": 2}])
    v2 = version_vector(changed)
    assert {k for k in v1 if v1[k] != v2.get(k)} == {"tables/t"}
    # add one object: exactly one new key
    grown = dict(base, tenant_quotas={"7": {"weight": 1.0},
                                      "8": {"weight": 2.0}})
    v3 = version_vector(grown)
    assert set(v3) - set(v1) == {"tenant_quotas/8"}
    assert all(v3[k] == v1[k] for k in v1)


# ------------------------------------- replicated tenant control plane


def test_replicated_quota_persists_across_reopen(tmp_path):
    d = str(tmp_path / "db")
    cl = ct.Cluster(d)
    cl.execute("SELECT citus_add_tenant_quota('7', 2.5, 3, 10.0, 8, 'gold')")
    cl.execute("SELECT citus_add_priority_class('gold', 4.0)")
    assert cl.catalog.tenant_quotas["7"]["priority_class"] == "gold"
    assert cl.catalog.priority_classes["gold"] == {"weight": 4.0}
    cl.close()
    GLOBAL_TENANTS.clear()
    # a fresh process-equivalent open hydrates the registry from the doc
    cl2 = ct.Cluster(d)
    assert cl2.execute("SELECT citus_tenant_quotas()").rows == \
        [("7", 2.5, 3, 10.0, 8, None, "gold")]
    assert cl2.execute("SELECT citus_priority_classes()").rows == \
        [("gold", 4.0)]
    # removal tombstones the catalog entry and retires the mirror
    assert cl2.execute("SELECT citus_remove_tenant_quota('7')").rows == \
        [(True,)]
    assert "7" not in cl2.catalog.tenant_quotas
    assert GLOBAL_TENANTS.get("7") is None
    cl2.close()


def test_hydration_leaves_locally_registered_quotas_alone(tmp_path):
    """Internal tenants registered straight against the registry (the
    rollup refresh worker pattern) survive catalog re-hydration."""
    from citus_tpu.metadata import hydrate_tenant_registry
    cl = ct.Cluster(str(tmp_path / "db"))
    GLOBAL_TENANTS.set_quota("_internal", weight=9.0)
    cl.execute("SELECT citus_add_tenant_quota('7', 2.5)")
    hydrate_tenant_registry(cl.catalog)
    assert GLOBAL_TENANTS.get("_internal").weight == 9.0
    assert GLOBAL_TENANTS.get("7").weight == 2.5
    cl.close()


def test_quota_write_on_a_is_pulled_by_b(tmp_path):
    """The acceptance shape: quotas written through coordinator A are
    queryable through coordinator B — here via the incremental
    pull-on-mismatch engine, asserting B's own catalog content."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0)
    b = ct.Cluster(str(tmp_path / "b"),
                   coordinator=("127.0.0.1", a.control_port))
    before = a.counters.snapshot().get("metadata_sync_bytes", 0)
    a.execute("SELECT citus_add_tenant_quota('42', 5.0, 2, 0.0, 4, 'gold')")
    a.execute("SELECT citus_add_priority_class('gold', 4.0)")
    # drive rounds until B's own catalog holds the replicated sections
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        b.metadata_sync.sync_once()
        if "42" in b.catalog.tenant_quotas:
            break
        time.sleep(0.05)
    assert b.catalog.tenant_quotas["42"]["priority_class"] == "gold"
    assert b.catalog.priority_classes["gold"] == {"weight": 4.0}
    # converged: the next round applies nothing (exactly-once)
    assert b.metadata_sync.sync_once() == 0
    assert b.execute("SELECT citus_tenant_quotas()").rows[0][0] == "42"
    snap = a.counters.snapshot()
    assert snap.get("metadata_sync_rounds", 0) >= 2
    assert snap.get("metadata_sync_bytes", 0) > before
    b.close()
    a.close()


def test_sync_retires_objects_dropped_on_the_authority(tmp_path):
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0)
    b = ct.Cluster(str(tmp_path / "b"),
                   coordinator=("127.0.0.1", a.control_port))
    a.execute("SELECT citus_add_tenant_quota('9', 1.0)")
    b.metadata_sync.sync_once()
    assert "9" in b.catalog.tenant_quotas
    a.execute("SELECT citus_remove_tenant_quota('9')")
    deadline = time.monotonic() + 10
    while "9" in b.catalog.tenant_quotas and time.monotonic() < deadline:
        b.metadata_sync.sync_once()
        time.sleep(0.05)
    assert "9" not in b.catalog.tenant_quotas
    b.close()
    a.close()


# ------------------------------------------------- lag health event


def test_metadata_sync_lag_event_emits_and_resolves(tmp_path):
    from citus_tpu.metadata.sync import SYNC_LAG_ROUNDS
    cl = ct.Cluster(str(tmp_path / "db"))
    ms = cl.metadata_sync
    for _ in range(SYNC_LAG_ROUNDS):
        ms._note_diverged(3)
    health = cl.flight_recorder.events_rows()
    lag = [e for e in health if e[1] == "metadata_sync_lag"]
    assert lag and lag[-1][6]  # active
    rows = cl.execute("SELECT citus_health_events()").rows
    mine = [r for r in rows if r[2] == "metadata_sync_lag"]
    assert mine and mine[-1][3] == "warning"
    ms._note_converged()
    health = cl.flight_recorder.events_rows()
    lag = [e for e in health if e[1] == "metadata_sync_lag"]
    assert lag and not lag[-1][6]  # resolved
    cl.close()


# ------------------------------------------------- two-level scheduler


def test_priority_classes_split_share_by_class_not_tenant():
    """One gold tenant vs three basic tenants, class weights 3:1.  A
    flat ring would hand gold ~25%; the two-level tree gives the gold
    CLASS ~75% regardless of tenant population."""
    import threading
    from citus_tpu.config import ExecutorSettings, Settings, WorkloadSettings
    from citus_tpu.executor.admission import SharedTaskPool
    from citus_tpu.workload import TenantScheduler
    GLOBAL_TENANTS.set_class("gold", 3.0)
    GLOBAL_TENANTS.set_class("basic", 1.0)
    GLOBAL_TENANTS.set_quota("g1", priority_class="gold")
    for t in ("b1", "b2", "b3"):
        GLOBAL_TENANTS.set_quota(t, priority_class="basic")
    sched = TenantScheduler(pool=SharedTaskPool())
    st = Settings(executor=ExecutorSettings(max_shared_pool_size=1),
                  workload=WorkloadSettings())
    stop = threading.Event()

    def drive(tenant):
        while not stop.is_set():
            sched.acquire(st, tenant)
            try:
                time.sleep(0.001)
            finally:
                sched.release(tenant)

    threads = [threading.Thread(target=drive, args=(t,))
               for t in ("g1", "b1", "b2", "b3") for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.6)
    stop.set()
    for t in threads:
        t.join()
    rows = {r[0]: r for r in sched.rows_view()}
    total = sum(rows[t][3] for t in ("g1", "b1", "b2", "b3"))
    assert total > 50
    gold_share = rows["g1"][3] / total
    assert gold_share >= 0.60, (gold_share, rows)
    # within a class the flat stride still applies: basics stay close
    basics = sorted(rows[t][3] for t in ("b1", "b2", "b3"))
    assert basics[0] >= basics[-1] * 0.3, rows


# ---------------------------------------------- kill matrix (real procs)


def test_kill_mid_sync_apply_restarts_and_converges(tmp_path):
    """A coordinator SIGKILLed at the metadata_sync_apply fault point —
    after pulling, before applying — restarts, re-diffs, and lands on
    the authority's document; the re-run applies the same objects
    exactly once (the follow-up round applies 0)."""
    d = str(tmp_path / "db")
    auth = ct.Cluster(d, serve_port=0)
    auth.execute("SELECT citus_add_tenant_quota('13', 7.0, 0, 0.0, 0, 'gold')")
    auth.execute("SELECT citus_add_priority_class('gold', 2.0)")
    port = auth.control_port
    attach_dir = str(tmp_path / "attach")
    victim = _spawn(textwrap.dedent(f"""
        import citus_tpu as ct
        from citus_tpu.testing.faults import FAULTS
        FAULTS.arm("metadata_sync_apply", kill=True)
        b = ct.Cluster({attach_dir!r}, coordinator=("127.0.0.1", {port}))
        print("SYNCING", flush=True)
        b.metadata_sync.sync_once()   # os._exit(1) at the fault point
        print("UNREACHABLE", flush=True)
    """))
    try:
        assert victim.stdout.readline().split() == ["SYNCING"]
        victim.wait(timeout=30)
        assert victim.returncode == 1  # died AT the fault point
    finally:
        if victim.poll() is None:
            victim.kill()
            victim.wait()
    # same data dir, no fault: the restarted coordinator converges
    survivor = _spawn(textwrap.dedent(f"""
        import citus_tpu as ct
        b = ct.Cluster({attach_dir!r}, coordinator=("127.0.0.1", {port}))
        n1 = b.metadata_sync.sync_once()
        n2 = b.metadata_sync.sync_once()
        q = b.catalog.tenant_quotas.get("13", {{}})
        print("RESULT", n1, n2, q.get("priority_class"), flush=True)
        b.close()
    """))
    try:
        out = survivor.stdout.readline().split()
        assert out[0] == "RESULT", out
        n1, n2, pclass = int(out[1]), int(out[2]), out[3]
        assert n1 > 0          # the interrupted batch applied on restart
        assert n2 == 0         # exactly once: nothing left to re-apply
        assert pclass == "gold"
        survivor.wait(timeout=30)
    finally:
        if survivor.poll() is None:
            survivor.kill()
            survivor.wait()
    auth.close()


def test_plan_and_admission_equivalence_across_coordinators(tmp_path):
    """Two attached coordinators plan the same query to the same
    fingerprint and resolve the same tenants to the same admission
    inputs — the zero-divergence half of query-from-any-node."""
    d = str(tmp_path / "db")
    auth = ct.Cluster(d, serve_port=0)
    auth.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    auth.execute("SELECT create_distributed_table('t', 'k', 8)")
    auth.copy_from("t", columns={"k": np.arange(400, dtype=np.int64) % 20,
                                 "v": np.arange(400, dtype=np.int64)})
    auth.execute("SELECT citus_add_priority_class('gold', 3.0)")
    auth.execute("SELECT citus_add_tenant_quota('5', 4.0, 2, 50.0, 8, 'gold')")
    auth.execute("SELECT citus_add_tenant_quota('11', 1.0, 1, 5.0, 2, '')")
    port = auth.control_port
    child = textwrap.dedent("""
        import json, sys
        import citus_tpu as ct
        from citus_tpu.executor.kernel_cache import plan_fingerprint
        from citus_tpu.planner import parse_sql
        from citus_tpu.planner.bind import bind_select
        from citus_tpu.planner.physical import plan_select
        from citus_tpu.workload import GLOBAL_TENANTS
        b = ct.Cluster(sys.argv[1], coordinator=("127.0.0.1", int(sys.argv[2])))
        b.metadata_sync.sync_once()
        fps = []
        for sql in ("SELECT count(*), sum(v) FROM t WHERE k = 5",
                    "SELECT k, sum(v) FROM t GROUP BY k"):
            bound = bind_select(b.catalog, parse_sql(sql)[0])
            fps.append(plan_fingerprint(plan_select(b.catalog, bound)))
        admission = []
        for tenant in ("5", "11", "999"):
            q = GLOBAL_TENANTS.get(tenant)
            wl = b.settings.workload
            pclass = (q.priority_class if q and q.priority_class
                      else wl.tenant_default_priority_class)
            admission.append((
                tenant,
                q.weight if q else wl.tenant_default_weight,
                q.max_concurrency if q else 0,
                q.rate_limit_qps if q else wl.tenant_rate_limit_qps,
                q.queue_depth if q else wl.tenant_queue_depth,
                pclass, GLOBAL_TENANTS.class_weight(pclass)))
        print("JSON " + json.dumps({"fps": fps, "admission": admission}),
              flush=True)
        b.close()
    """)

    def run(sub: str) -> dict:
        body = ("import jax\njax.config.update('jax_platforms','cpu')\n"
                + child)
        p = subprocess.run(
            [sys.executable, "-c", body, str(tmp_path / sub), str(port)],
            stdout=subprocess.PIPE, text=True, env=ENV, timeout=120)
        for line in p.stdout.splitlines():
            if line.startswith("JSON "):
                import json
                return json.loads(line[5:])
        raise AssertionError(f"no JSON line in child output: {p.stdout!r}")

    r1 = run("c1")
    r2 = run("c2")
    assert r1["fps"] == r2["fps"]
    assert r1["admission"] == r2["admission"]
    auth.close()
