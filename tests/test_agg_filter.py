"""FILTER (WHERE ...) on aggregates — plain, grouped, window.

Reference: PostgreSQL FILTER clause (evaluated before the transition
function); the reference pushes it down inside shard queries unchanged.
Here it desugars at bind time to CASE WHEN f THEN arg END, which is
exact because every supported aggregate ignores NULL inputs
(planner/bind.py rewrite_agg_filter).
"""

import sqlite3

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import ExecutorSettings, settings_override

N = 3000


@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    cl = ct.Cluster(str(tmp_path_factory.mktemp("db")))
    cl.execute("""CREATE TABLE f (
        id bigint NOT NULL, g bigint, kind text, q decimal(10,2), x double)""")
    cl.execute("SELECT create_distributed_table('f', 'id', 4)")
    rng = np.random.default_rng(3)
    kinds = ["a", "b", "c", None]
    rows = []
    for i in range(N):
        rows.append((
            i, int(rng.integers(0, 12)),
            kinds[int(rng.integers(0, 4))],
            round(float(rng.integers(-5000, 5000)) / 100, 2)
            if rng.random() > 0.1 else None,
            float(np.round(rng.random() * 10, 6)),
        ))
    cl.copy_from("f", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE f (id INTEGER, g INTEGER, kind TEXT, q REAL, x REAL)")
    sq.executemany("INSERT INTO f VALUES (?,?,?,?,?)", rows)
    return cl, sq


QUERIES = [
    "SELECT count(*) FILTER (WHERE q > 0), count(*) FROM f",
    "SELECT sum(q) FILTER (WHERE kind = 'a'), sum(q) FILTER (WHERE kind = 'b') FROM f",
    "SELECT g, count(*) FILTER (WHERE x > 5), sum(q) FILTER (WHERE q < 0) "
    "FROM f GROUP BY g ORDER BY g",
    "SELECT g, avg(x) FILTER (WHERE kind IS NOT NULL), min(q) FILTER (WHERE q > 10) "
    "FROM f GROUP BY g ORDER BY g",
    "SELECT kind, count(q) FILTER (WHERE q BETWEEN -10 AND 10) "
    "FROM f GROUP BY kind ORDER BY kind NULLS LAST",
    "SELECT count(DISTINCT g) FILTER (WHERE x > 5) FROM f",
]


def canon(rows):
    out = []
    for r in rows:
        out.append(tuple(
            round(float(v), 4) if isinstance(v, float)
            or str(type(v).__name__) == "Decimal" else v for v in r))
    return out


@pytest.mark.parametrize("sql", QUERIES)
def test_vs_sqlite(loaded, sql):
    cl, sq = loaded
    ours = canon(cl.execute(sql).rows)
    theirs = canon(sq.execute(sql).fetchall())
    assert len(ours) == len(theirs)
    for ro, rt in zip(ours, theirs):
        for vo, vt in zip(ro, rt):
            if isinstance(vo, float) or isinstance(vt, float):
                assert vo == pytest.approx(vt, rel=1e-6, abs=1e-4), sql
            else:
                assert vo == vt, sql


@pytest.mark.parametrize("sql", QUERIES)
def test_jax_vs_cpu(loaded, sql):
    cl, _ = loaded
    jax_rows = cl.execute(sql).rows
    with settings_override(executor=ExecutorSettings(task_executor_backend="cpu")):
        cpu_rows = cl.execute(sql).rows
    assert jax_rows == cpu_rows


def test_filter_on_extended_aggs(loaded):
    cl, sq = loaded
    ours = cl.execute(
        "SELECT g, stddev_samp(x) FILTER (WHERE x > 2) FROM f "
        "GROUP BY g ORDER BY g").rows
    # oracle: two-pass via sqlite sums
    import math
    for g, got in ours:
        n, s, ss = sq.execute(
            "SELECT count(x), sum(x), sum(x*x) FROM f WHERE x > 2 AND g = ?",
            (g,)).fetchone()
        if n < 2:
            assert got is None
        else:
            want = math.sqrt(max((ss - s * s / n) / (n - 1), 0.0))
            assert got == pytest.approx(want, rel=1e-9)


def test_filter_window(loaded):
    cl, sq = loaded
    sql = ("SELECT id, count(*) FILTER (WHERE q > 0) OVER "
           "(PARTITION BY g) FROM f WHERE id < 200 ORDER BY id")
    ours = cl.execute(sql).rows
    theirs = sq.execute(sql).fetchall()
    assert ours == [tuple(r) for r in theirs]


def test_filter_rejected_on_ranking_window(loaded):
    cl, _ = loaded
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError):
        cl.execute("SELECT row_number() FILTER (WHERE q > 0) OVER "
                   "(ORDER BY id) FROM f")


def test_filter_in_having(loaded):
    cl, sq = loaded
    sql = ("SELECT g FROM f GROUP BY g "
           "HAVING count(*) FILTER (WHERE x > 5) > 100 ORDER BY g")
    assert cl.execute(sql).rows == [tuple(r) for r in sq.execute(sql).fetchall()]
