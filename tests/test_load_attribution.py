"""Per-placement load attribution (observability/load_attribution.py).

The ledger's balance invariant: summed over every (table, shard, node,
tenant) entry, queries / rows_returned / bytes_scanned equal the
whole-query StatCounters deltas — attribution never invents or loses
work, on the local path AND the pushed remote-task path.
"""

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.executor.device_cache import GLOBAL_CACHE
from citus_tpu.executor.executor import GLOBAL_COUNTERS
from citus_tpu.observability.load_attribution import (
    GLOBAL_ATTRIBUTION, LoadAttribution,
)


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    c.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    c.execute("SELECT create_distributed_table('t', 'k', 4)")
    n = 20000
    c.copy_from("t", columns={"k": np.arange(n, dtype=np.int64),
                              "v": np.arange(n, dtype=np.int64) % 97})
    GLOBAL_CACHE.clear()
    GLOBAL_COUNTERS.reset()
    yield c
    c.close()


def _totals():
    return GLOBAL_ATTRIBUTION.totals()


def test_ledger_balances_whole_query_counters(cl):
    """queries / rows / bytes booked in the ledger == the counters'
    deltas across a mix of aggregate, grouped and projection queries."""
    cl.execute("SELECT count(*), sum(v) FROM t")
    cl.execute("SELECT v, count(*) FROM t GROUP BY v")
    cl.execute("SELECT k, v FROM t WHERE k < 5 ORDER BY k")
    snap = GLOBAL_COUNTERS.snapshot()
    tot = _totals()
    assert tot["queries"] == snap["queries_executed"]
    assert tot["rows_returned"] == snap["rows_returned"]
    assert tot["bytes_scanned"] == snap["bytes_scanned"]
    assert tot["device_ms"] > 0.0


def test_cached_replay_books_no_stale_work(cl):
    """Re-running a query that now serves from the device cache books
    the query itself but no stale bytes: the booking seam consumes the
    per-execution task logs exactly once (pop, not get)."""
    cl.execute("SELECT count(*), sum(v) FROM t")
    first = _totals()
    cl.execute("SELECT count(*), sum(v) FROM t")
    snap = GLOBAL_COUNTERS.snapshot()
    tot = _totals()
    assert tot["queries"] == snap["queries_executed"] == 2
    assert tot["rows_returned"] == snap["rows_returned"]
    # the cache-hit replay scanned nothing new — counter and ledger agree
    assert tot["bytes_scanned"] == snap["bytes_scanned"] == \
        first["bytes_scanned"]


def test_rows_view_attributes_placements(cl):
    cl.execute("SELECT count(*) FROM t")
    rows = GLOBAL_ATTRIBUTION.rows_view()
    assert rows, "at least one placement booked"
    t = cl.catalog.table("t")
    placements = {(s.shard_id, s.placements[0]) for s in t.shards}
    for r in rows:
        assert r[0] == "t"
        assert (r[1], r[2]) in placements
    # deterministic order: device_ms descending
    ms = [r[5] for r in rows]
    assert ms == sorted(ms, reverse=True)


def test_shard_load_sql_surface(cl):
    cl.execute("SELECT count(*), sum(v) FROM t")
    r = cl.execute("SELECT citus_shard_load()")
    assert r.rowcount >= 1
    cols = r.columns
    assert "device_ms" in cols and "tenant" in cols
    by_name = {c: i for i, c in enumerate(cols)}
    for row in r.rows:
        assert row[by_name["table_name"]] == "t"
    # filtered form matches, unknown table is empty
    assert cl.execute("SELECT citus_shard_load('t')").rowcount == r.rowcount
    assert cl.execute("SELECT citus_shard_load('zzz')").rowcount == 0


def test_reset_hook_rezeros_ledger(cl):
    cl.execute("SELECT count(*) FROM t")
    assert _totals()["queries"] > 0
    cl.execute("SELECT citus_stat_counters_reset()")
    tot = _totals()
    assert all(v == 0 for v in tot.values())
    # and the invariant holds again immediately after the reset
    cl.execute("SELECT count(*) FROM t")
    snap = GLOBAL_COUNTERS.snapshot()
    assert _totals()["queries"] == snap["queries_executed"]


def test_ewma_rates_need_explicit_ticks():
    """Reading scores never advances the EWMA; ticks do, and the
    cold-start fallback serves cumulative ms before rates exist."""
    led = LoadAttribution()
    led.book("t", 7, 0, "*", device_ms=500.0)
    # no ticks yet: cumulative fallback
    assert led.load_scores() == {("t", 7, 0): 500.0}
    led.tick(now=100.0)   # baseline only
    led.tick(now=101.0)   # zero delta -> rate decays toward 0 (stays 0)
    led.book("t", 7, 0, "*", device_ms=300.0)
    led.tick(now=102.0)   # 300 ms over 1 s
    s = led.load_scores()[("t", 7, 0)]
    assert 0.0 < s <= 300.0
    before = led.load_scores()
    assert led.load_scores() == before  # reads are side-effect free


def test_ring_metrics_bounded_and_sampled(cl):
    cl.execute("SELECT count(*) FROM t")
    m = GLOBAL_ATTRIBUTION.ring_metrics()
    assert m and len(m) <= 32
    assert all(k.startswith("shard_load:t.") for k in m)
    # the flight recorder's sampler carries these into its ring
    cl.execute("SET citus.flight_recorder_interval_ms = 50")
    try:
        cl.flight_recorder.run_once()
        hist = cl.execute(
            f"SELECT citus_stat_history('{sorted(m)[0]}')")
        assert hist.rowcount >= 1
    finally:
        cl.execute("SET citus.flight_recorder_interval_ms = 0")


def test_pushed_tasks_book_on_worker_placements(tmp_path):
    """Push path: the worker books device ms + bytes against its own
    placements, and cluster-wide the ledger still balances the (shared
    in-process) whole-query counters."""
    a = ct.Cluster(str(tmp_path / "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    na = a.register_node()
    b = ct.Cluster(str(tmp_path / "b"), data_port=0, hosted_nodes=set(),
                   coordinator=("127.0.0.1", a.control_port), n_nodes=0)
    nb = b.register_node()
    a._maybe_reload_catalog(force_sync=True)
    try:
        a.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
        a.execute("SELECT create_distributed_table('t', 'k', 4)")
        n = 20000
        a.copy_from("t", columns={"k": np.arange(n, dtype=np.int64),
                                  "v": np.arange(n, dtype=np.int64) * 3})
        GLOBAL_CACHE.clear()
        GLOBAL_COUNTERS.reset()
        r = a.execute("SELECT count(*), sum(v) FROM t")
        assert r.rows == [(n, 3 * n * (n - 1) // 2)]
        snap = GLOBAL_COUNTERS.snapshot()
        assert snap["remote_tasks_pushed"] >= 1
        tot = _totals()
        assert tot["queries"] == snap["queries_executed"]
        assert tot["rows_returned"] == snap["rows_returned"]
        assert tot["bytes_scanned"] == snap["bytes_scanned"]
        # remote placements carried their own load: entries exist on
        # node nb's shards with device ms booked by the worker
        remote = [r2 for r2 in GLOBAL_ATTRIBUTION.rows_view()
                  if r2[2] == nb]
        assert remote and any(r2[5] > 0 for r2 in remote)
        local = [r2 for r2 in GLOBAL_ATTRIBUTION.rows_view()
                 if r2[2] == na]
        assert local
    finally:
        b.close()
        a.close()
