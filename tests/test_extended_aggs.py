"""Extended aggregates — VERDICT round-2 item #6.

Reference: arbitrary aggregates via worker_partial_agg/coord_combine_agg
(utils/aggregate_utils.c:502,847) and t-digest percentile pushdown.
Here: a declared partial/combine registry (planner/aggregates.py).
Variance-family aggs lower to sum/sumsq/count partials and combine with
the same psum as plain sums (device path); percentile/string_agg/
array_agg use exact collect partials on the host grouping path.
Float results are tolerance-checked against numpy (documented: float64
accumulators, like PostgreSQL's float8 variance)."""

import math

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import ExecutorSettings, settings_override

N = 3000


@pytest.fixture(scope="module")
def db(tmp_path_factory):
    cl = ct.Cluster(str(tmp_path_factory.mktemp("agg")))
    cl.execute("""CREATE TABLE t (k bigint NOT NULL, g bigint, v bigint,
        f double, d decimal(10,2), s text, b boolean)""")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rng = np.random.default_rng(21)
    data = {
        "g": rng.integers(0, 6, N),
        "v": rng.integers(-50, 150, N),
        "f": rng.random(N) * 100,
        "d": np.round(rng.random(N) * 50, 2),
        "b": rng.integers(0, 2, N).astype(bool),
    }
    cl.copy_from("t", columns={
        "k": np.arange(N), **data,
        "s": [f"tag{i % 7}" for i in range(N)]})
    yield cl, data
    cl.close()


def test_variance_family_scalar(db):
    cl, d = db
    r = cl.execute("""SELECT stddev(v), stddev_samp(v), stddev_pop(v),
        variance(f), var_samp(f), var_pop(f) FROM t""").rows[0]
    v, f = d["v"], d["f"]
    exp = (np.std(v, ddof=1), np.std(v, ddof=1), np.std(v),
           np.var(f, ddof=1), np.var(f, ddof=1), np.var(f))
    for got, want in zip(r, exp):
        assert got == pytest.approx(want, rel=1e-9)


def test_variance_grouped_device_path(db):
    """Grouped stddev rides the direct (psum) device path — no collect."""
    cl, d = db
    from citus_tpu.planner import parse_sql
    from citus_tpu.planner.bind import bind_select
    from citus_tpu.planner.physical import plan_select
    bound = bind_select(cl.catalog, parse_sql(
        "SELECT g, stddev(v) FROM t GROUP BY g")[0])
    plan = plan_select(cl.catalog, bound)
    assert plan.group_mode.kind == "direct"
    assert all(op.kind in ("sum", "count") for op in plan.partial_ops)
    rows = cl.execute("SELECT g, stddev(v) FROM t GROUP BY g ORDER BY g").rows
    for gi, sd in rows:
        want = np.std(d["v"][d["g"] == gi], ddof=1)
        assert sd == pytest.approx(want, rel=1e-9)


def test_variance_jax_matches_cpu(db):
    cl, _ = db
    sql = "SELECT g, var_samp(f), stddev_pop(d) FROM t GROUP BY g ORDER BY g"
    jax_rows = cl.execute(sql).rows
    with settings_override(executor=ExecutorSettings(task_executor_backend="cpu")):
        cpu_rows = cl.execute(sql).rows
    for a, b in zip(jax_rows, cpu_rows):
        assert a[0] == b[0]
        assert a[1] == pytest.approx(b[1], rel=1e-9)
        assert a[2] == pytest.approx(b[2], rel=1e-9)


def test_variance_of_single_row_and_empty(db):
    cl, _ = db
    r = cl.execute("SELECT stddev(v), var_pop(v) FROM t WHERE k = 5").rows[0]
    assert r[0] is None          # n < 2 -> NULL (sample)
    assert r[1] == 0.0           # population variance of one value
    r = cl.execute("SELECT stddev(v) FROM t WHERE k < 0").rows[0]
    assert r[0] is None


def test_bool_and_or(db):
    cl, d = db
    r = cl.execute("SELECT bool_and(b), bool_or(b) FROM t").rows[0]
    assert r == (bool(d["b"].all()), bool(d["b"].any()))
    rows = cl.execute("SELECT g, bool_and(b) FROM t GROUP BY g ORDER BY g").rows
    for gi, ba in rows:
        assert ba == bool(d["b"][d["g"] == gi].all())


def test_percentiles(db):
    cl, d = db
    v, f, g = d["v"], d["f"], d["g"]
    r = cl.execute("SELECT percentile_cont(0.5) WITHIN GROUP (ORDER BY v) "
                   "FROM t").rows[0][0]
    assert r == pytest.approx(np.percentile(v, 50), abs=1e-9)
    r = cl.execute("SELECT percentile_cont(0.95) WITHIN GROUP (ORDER BY f) "
                   "FROM t").rows[0][0]
    assert r == pytest.approx(np.percentile(f, 95), rel=1e-12)
    r = cl.execute("SELECT percentile_disc(0.25) WITHIN GROUP (ORDER BY v) "
                   "FROM t").rows[0][0]
    sv = np.sort(v)
    assert r == sv[math.ceil(0.25 * N) - 1]
    rows = cl.execute("SELECT g, percentile_cont(0.9) WITHIN GROUP "
                      "(ORDER BY f) FROM t GROUP BY g ORDER BY g").rows
    for gi, p in rows:
        assert p == pytest.approx(np.percentile(f[g == gi], 90), rel=1e-12)


def test_string_agg_and_array_agg(db):
    cl, d = db
    r = cl.execute("SELECT string_agg(s, '|') FROM t WHERE k < 7").rows[0][0]
    assert sorted(r.split("|")) == sorted(f"tag{i}" for i in range(7))
    rows = cl.execute("SELECT g, array_agg(v) FROM t WHERE k < 50 "
                      "GROUP BY g ORDER BY g").rows
    got = sorted(x for _, vals in rows for x in vals)
    assert got == sorted(d["v"][:50].tolist())
    # empty input -> NULL, not empty string
    r = cl.execute("SELECT string_agg(s, ',') FROM t WHERE k < 0").rows[0][0]
    assert r is None


def test_mixing_with_builtin_aggs_and_having(db):
    cl, d = db
    rows = cl.execute("""SELECT g, count(*), avg(v), stddev(v),
        percentile_cont(0.5) WITHIN GROUP (ORDER BY v)
        FROM t GROUP BY g HAVING count(*) > 10 ORDER BY g""").rows
    v, g = d["v"], d["g"]
    for gi, cnt, _avg, sd, med in rows:
        sel = v[g == gi]
        assert cnt == sel.size
        assert sd == pytest.approx(np.std(sel, ddof=1), rel=1e-9)
        assert med == pytest.approx(np.percentile(sel, 50), abs=1e-9)


def test_decimal_stddev(db):
    cl, d = db
    r = cl.execute("SELECT stddev(d) FROM t").rows[0][0]
    assert r == pytest.approx(np.std(d["d"], ddof=1), rel=1e-9)


def test_registry_rejects_bad_usage(db):
    cl, _ = db
    from citus_tpu.errors import AnalysisError, SqlSyntaxError
    with pytest.raises((AnalysisError, SqlSyntaxError)):
        cl.execute("SELECT percentile_cont(1.5) WITHIN GROUP (ORDER BY v) FROM t")
    with pytest.raises(AnalysisError):
        cl.execute("SELECT bool_and(v) FROM t")
    with pytest.raises(AnalysisError):
        cl.execute("SELECT string_agg(v, ',') FROM t")


def test_ordered_string_and_array_agg(tmp_path):
    """string_agg/array_agg(... ORDER BY ...) collect (value, sortkey)
    tuples; text sort keys order by lexicographic rank."""
    cl = ct.Cluster(str(tmp_path / "ordagg"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v bigint, s text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", rows=[(1, 0, 30, "c"), (2, 0, 10, "a"), (3, 0, 20, "b"),
                            (4, 1, 5, "z"), (5, 1, 9, "y"), (6, 1, 7, None)])
    assert cl.execute("SELECT g, string_agg(s, ',' ORDER BY v) FROM t "
                      "GROUP BY g ORDER BY g").rows == \
        [(0, "a,b,c"), (1, "z,y")]
    assert cl.execute("SELECT g, string_agg(s, '-' ORDER BY v DESC) FROM t "
                      "GROUP BY g ORDER BY g").rows == \
        [(0, "c-b-a"), (1, "y-z")]
    assert cl.execute("SELECT string_agg(s, ',' ORDER BY s) FROM t").rows == \
        [("a,b,c,y,z",)]
    assert cl.execute("SELECT g, array_agg(v ORDER BY v DESC) FROM t "
                      "GROUP BY g ORDER BY g").rows == \
        [(0, [30, 20, 10]), (1, [9, 7, 5])]
    cl.close()


def test_distinct_sum_avg_minmax(tmp_path):
    """sum/avg(DISTINCT) via exact value-set partials; DISTINCT is a
    no-op for min/max (including text)."""
    import decimal
    import sqlite3
    cl = ct.Cluster(str(tmp_path / "dagg"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v bigint, "
               "d decimal(8,2), f double, s text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rows = [(i, i % 3, (i * 7) % 5, round((i % 4) * 1.25, 2),
             float(i % 6), f"w{i % 4}") for i in range(100)]
    cl.copy_from("t", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, g INTEGER, v INTEGER, d REAL, "
               "f REAL, s TEXT)")
    sq.executemany("INSERT INTO t VALUES (?,?,?,?,?,?)", rows)
    for sql in [
        "SELECT sum(DISTINCT v), avg(DISTINCT f) FROM t",
        "SELECT g, sum(DISTINCT v), count(DISTINCT v) FROM t GROUP BY g ORDER BY g",
        "SELECT min(DISTINCT v), max(DISTINCT v) FROM t",
    ]:
        ours = [tuple(round(float(v), 6)
                      if isinstance(v, (float, decimal.Decimal)) else v
                      for v in r) for r in cl.execute(sql).rows]
        theirs = [tuple(round(float(v), 6) if isinstance(v, float) else v
                        for v in r) for r in sq.execute(sql).fetchall()]
        assert ours == theirs, (sql, ours, theirs)
    assert cl.execute("SELECT sum(DISTINCT d) FROM t").rows[0][0] == \
        decimal.Decimal("7.50")
    assert cl.execute("SELECT max(DISTINCT s) FROM t").rows == [("w3",)]
    cl.close()


def test_approx_count_distinct(tmp_path):
    """HyperLogLog sketch: registers are max-combinable partials (the
    same collective as plain max — a true device-side sketch aggregate,
    the distinct-counting analog of t-digest pushdown)."""
    from citus_tpu.config import ExecutorSettings, Settings
    cl = ct.Cluster(str(tmp_path / "hll"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v bigint, s text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 8)")
    rng = np.random.default_rng(1)
    n = 60_000
    v = rng.integers(0, 4000, n)
    g = rng.integers(0, 4, n)
    cl.copy_from("t", columns={"k": np.arange(n), "g": g, "v": v,
                               "s": [f"u{i % 500}" for i in range(n)]})
    est = cl.execute("SELECT approx_count_distinct(v) FROM t").rows[0][0]
    true = len(np.unique(v))
    assert abs(est - true) / true < 0.25, (est, true)
    est_s = cl.execute("SELECT approx_count_distinct(s) FROM t").rows[0][0]
    assert abs(est_s - 500) / 500 < 0.25
    for gi, e in cl.execute("SELECT g, approx_count_distinct(v) FROM t "
                            "GROUP BY g ORDER BY g").rows:
        tru = len(np.unique(v[g == gi]))
        assert abs(e - tru) / tru < 0.25, (gi, e, tru)
    # registers are deterministic: cpu backend produces the same estimate
    cl2 = ct.Cluster(str(tmp_path / "hll"), settings=Settings(
        executor=ExecutorSettings(task_executor_backend="cpu")))
    assert cl2.execute("SELECT approx_count_distinct(v) FROM t").rows[0][0] == est
    cl2.close()
    cl.close()


# ------------- approx_percentile (DDSketch device sketch, gap #8) -----

def test_approx_percentile_scalar(db):
    """Sketch percentile within the bucket's relative-error bound of the
    exact percentile (reference: t-digest pushdown,
    tdigest_extension.c:250)."""
    cl, data = db
    for frac in (0.1, 0.5, 0.9, 0.99):
        r = cl.execute(f"SELECT approx_percentile({frac}) WITHIN GROUP "
                       "(ORDER BY f) FROM t")
        exact = float(np.percentile(data["f"], frac * 100))
        got = float(r.rows[0][0])
        assert math.isclose(got, exact, rel_tol=0.06, abs_tol=0.5), \
            (frac, got, exact)


def test_approx_percentile_grouped(db):
    cl, data = db
    r = cl.execute("SELECT g, approx_percentile(0.5) WITHIN GROUP "
                   "(ORDER BY v) FROM t GROUP BY g ORDER BY g")
    for g, got in r.rows:
        vals = data["v"][data["g"] == g]
        exact = float(np.percentile(vals, 50))
        assert math.isclose(float(got), exact, rel_tol=0.06, abs_tol=1.0), \
            (g, got, exact)


def test_approx_percentile_negative_and_int(db):
    """Negative values route through the mirrored bucket half."""
    cl, data = db
    r = cl.execute("SELECT approx_percentile(0.05) WITHIN GROUP "
                   "(ORDER BY v) FROM t")
    exact = float(np.percentile(data["v"], 5))
    got = float(r.rows[0][0])
    assert math.isclose(got, exact, rel_tol=0.06, abs_tol=1.0), (got, exact)


def test_approx_percentile_matches_cpu_oracle(db):
    """Device combine of bucket vectors == numpy host path.  The SCALAR
    shape is the one that rides the device worker + 'ddsk'->'sum'
    combine (grouped queries route host via host_grouped), so that is
    the shape the oracle comparison must use."""
    cl, _ = db
    sql = ("SELECT approx_percentile(0.9) WITHIN GROUP (ORDER BY f) "
           "FROM t")
    got = cl.execute(sql)
    with settings_override(executor=ExecutorSettings(
            task_executor_backend="cpu")):
        oracle = cl.execute(sql)
    assert got.rows == oracle.rows


def test_approx_percentile_empty_and_nulls(tmp_path):
    cl = ct.Cluster(str(tmp_path / "ap"))
    cl.execute("CREATE TABLE e (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('e', 'k', 2)")
    r = cl.execute("SELECT approx_percentile(0.5) WITHIN GROUP "
                   "(ORDER BY v) FROM e")
    assert r.rows == [(None,)]
    cl.execute("INSERT INTO e VALUES (1, NULL), (2, 42)")
    r2 = cl.execute("SELECT approx_percentile(0.5) WITHIN GROUP "
                    "(ORDER BY v) FROM e")
    assert math.isclose(float(r2.rows[0][0]), 42.0, rel_tol=0.06)
    cl.close()


# ------------- approx_top_k (count-array heavy hitters, ROADMAP #4) ---


def _topk_expected(values, k):
    """Replicate the sketch on the host: splitmix64 bucket counts plus
    a max-value register per bucket, exactly the arrays the device
    psum/max-combines (planner/aggregates.py: topk_buckets)."""
    import json

    from citus_tpu.planner.aggregates import (TOPK_M, TOPK_SENTINEL,
                                              topk_buckets)
    b = topk_buckets(np, np.asarray(values, np.int64))
    counts = np.bincount(b, minlength=TOPK_M).astype(np.int64)
    regs = np.full(TOPK_M, TOPK_SENTINEL, np.int64)
    np.maximum.at(regs, b, np.asarray(values, np.int64))
    hot = np.nonzero(counts > 0)[0]
    order = sorted(hot, key=lambda i: (-int(counts[i]), int(regs[i])))
    return json.dumps([{"value": int(regs[i]), "count": int(counts[i])}
                       for i in order[:k]])


def test_approx_top_k_exact_small_domain(tmp_path):
    """With a small collision-free domain the sketch IS the exact
    frequency table: top-k must match numpy counts bit-for-bit across
    an 8-shard psum merge."""
    import json

    from citus_tpu.planner.aggregates import topk_buckets
    cl = ct.Cluster(str(tmp_path / "topk"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 8)")
    # zipf-ish skew over 20 distinct values; verify the domain really
    # is collision-free so exact equality is a fair demand
    dom = np.arange(20, dtype=np.int64) * 7 - 31
    assert len(np.unique(topk_buckets(np, dom))) == len(dom)
    rng = np.random.default_rng(9)
    v = dom[np.minimum(rng.geometric(0.25, 20_000) - 1, 19)]
    cl.copy_from("t", columns={"k": np.arange(len(v)), "v": v})
    got = cl.execute("SELECT approx_top_k(v, 5) FROM t").rows[0][0]
    uniq, cnt = np.unique(v, return_counts=True)
    order = sorted(range(len(uniq)), key=lambda i: (-int(cnt[i]),
                                                    int(uniq[i])))
    want = [{"value": int(uniq[i]), "count": int(cnt[i])}
            for i in order[:5]]
    assert json.loads(got) == want
    cl.close()


def test_approx_top_k_matches_host_sketch(db):
    """200-distinct column: collisions are expected and deterministic —
    the merged device sketch must equal the host replication exactly,
    scalar and per-group."""
    cl, d = db
    got = cl.execute("SELECT approx_top_k(v, 8) FROM t").rows[0][0]
    assert got == _topk_expected(d["v"], 8)
    for gi, s in cl.execute("SELECT g, approx_top_k(v, 3) FROM t "
                            "GROUP BY g ORDER BY g").rows:
        assert s == _topk_expected(d["v"][d["g"] == gi], 3), gi
    # backend-deterministic: cpu task executor produces the same text
    with settings_override(executor=ExecutorSettings(
            task_executor_backend="cpu")):
        assert cl.execute("SELECT approx_top_k(v, 8) FROM t"
                          ).rows[0][0] == got


def test_approx_top_k_empty_and_validation(tmp_path):
    from citus_tpu.errors import AnalysisError, UnsupportedFeatureError
    cl = ct.Cluster(str(tmp_path / "topkv"))
    cl.execute("CREATE TABLE e (k bigint NOT NULL, v bigint, s text, "
               "f double)")
    cl.execute("SELECT create_distributed_table('e', 'k', 2)")
    assert cl.execute("SELECT approx_top_k(v, 4) FROM e").rows == [(None,)]
    for bad in ("approx_top_k(v)", "approx_top_k(v, k)",
                "approx_top_k(v, 0)", "approx_top_k(v, 65)",
                "approx_top_k(s, 4)", "approx_top_k(f, 4)"):
        with pytest.raises(AnalysisError):
            cl.execute(f"SELECT {bad} FROM e")
    with pytest.raises(UnsupportedFeatureError):
        cl.execute("SELECT approx_top_k(DISTINCT v, 4) FROM e")
    cl.close()
