"""Dry-run rebalance planning (operations/rebalance_plan.py +
SELECT citus_rebalance_plan(strategy)): deterministic, side-effect
free, and strategy-aware (shard count / bytes / observed load)."""

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import CatalogError
from citus_tpu.operations.rebalance_plan import (
    ISOLATE_TENANT_SHARE, build_rebalance_plan, plan_rows,
)


def make_cluster(tmp_path, nodes=2, shards=4, n=8000):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=nodes)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute(f"SELECT create_distributed_table('t', 'k', {shards})")
    cl.copy_from("t", columns={"k": np.arange(n, dtype=np.int64),
                               "v": np.arange(n, dtype=np.int64)})
    return cl


def _placements(cl, table="t"):
    return [tuple(s.placements) for s in cl.catalog.table(table).shards]


def test_plan_deterministic_and_side_effect_free(tmp_path):
    cl = make_cluster(tmp_path)
    cl.execute("SELECT citus_add_node('w2', 5432)")
    before = _placements(cl)
    r1 = cl.execute("SELECT citus_rebalance_plan('by_shard_count')")
    r2 = cl.execute("SELECT citus_rebalance_plan('by_shard_count')")
    assert r1.rows == r2.rows
    assert r1.rowcount >= 1  # empty new node attracts moves
    # pure observability: nothing moved, nothing registered
    assert _placements(cl) == before
    from citus_tpu.operations.cleaner import operations_view
    assert operations_view(cl.catalog) == {}
    cols = r1.columns
    assert list(cols) == ["step", "action", "table_name", "shard_id",
                          "source_node", "target_node", "cost", "score",
                          "reason"]
    by = {c: i for i, c in enumerate(cols)}
    for row in r1.rows:
        assert row[by["action"]] == "move"
        assert row[by["target_node"]] == 2   # the empty node
        assert 0.0 < row[by["score"]] <= 1.0
    # steps are ordered 1..N
    assert [row[by["step"]] for row in r1.rows] == \
        list(range(1, r1.rowcount + 1))
    cl.close()


def test_balanced_cluster_plans_nothing(tmp_path):
    cl = make_cluster(tmp_path)
    assert cl.execute("SELECT citus_rebalance_plan('by_shard_count')").rows == []
    assert cl.execute("SELECT citus_rebalance_plan('by_bytes')").rows == []
    cl.close()


def test_unknown_strategy_raises(tmp_path):
    cl = make_cluster(tmp_path)
    with pytest.raises(CatalogError):
        cl.execute("SELECT citus_rebalance_plan('by_vibes')")
    cl.close()


def test_by_bytes_strategy_moves_toward_empty_node(tmp_path):
    cl = make_cluster(tmp_path)
    cl.execute("SELECT citus_add_node('w2', 5432)")
    r = cl.execute("SELECT citus_rebalance_plan('by_bytes', 0.2)")
    assert r.rowcount >= 1
    by = {c: i for i, c in enumerate(r.columns)}
    for row in r.rows:
        assert row[by["cost"]] > 0.0      # real stripe bytes moved
    # the empty node attracts the first (highest-benefit) move
    assert r.rows[0][by["target_node"]] == 2
    cl.close()


def test_by_observed_load_follows_attribution(tmp_path):
    """Load booked against node 0's placements produces a plan moving a
    hot slot off node 0 — and an explicit snapshot makes the plan a
    pure function of its inputs."""
    cl = make_cluster(tmp_path)
    t = cl.catalog.table("t")
    scores = {}
    for s in t.shards:
        node = s.placements[0]
        scores[("t", s.shard_id, node)] = 500.0 if node == 0 else 1.0
    p1 = build_rebalance_plan(cl.catalog, "by_observed_load",
                              load_scores=scores, attribution_rows=[])
    p2 = build_rebalance_plan(cl.catalog, "by_observed_load",
                              load_scores=scores, attribution_rows=[])
    assert p1 == p2
    assert p1 and p1[0].action == "move"
    assert p1[0].source_node == 0 and p1[0].target_node == 1
    assert plan_rows(p1)[0][0] == 1
    cl.close()


def test_unsplittable_hot_slot_plans_split(tmp_path):
    """A single group slot heavier than the whole gap cannot be fixed
    by a move: the plan recognizes the shape and proposes a split."""
    cl = make_cluster(tmp_path, shards=1, n=2000)
    sid = cl.catalog.table("t").shards[0].shard_id
    src = cl.catalog.table("t").shards[0].placements[0]
    steps = build_rebalance_plan(cl.catalog, "by_shard_count")
    assert len(steps) == 1
    assert steps[0].action == "split"
    assert steps[0].shard_id == sid and steps[0].source_node == src
    cl.close()


def test_dominant_tenant_plans_isolation(tmp_path):
    """Under by_observed_load, one tenant carrying >= 60% of the
    hottest unmovable placement yields an isolate step, not a split."""
    cl = make_cluster(tmp_path, shards=1, n=2000)
    s = cl.catalog.table("t").shards[0]
    node = s.placements[0]
    scores = {("t", s.shard_id, node): 1000.0}
    rows = [["t", s.shard_id, node, "42", 10, 800.0, 0, 0, 0.0, 0.0],
            ["t", s.shard_id, node, "7", 3, 200.0, 0, 0, 0.0, 0.0]]
    steps = build_rebalance_plan(cl.catalog, "by_observed_load",
                                 load_scores=scores, attribution_rows=rows)
    assert len(steps) == 1
    st = steps[0]
    assert st.action == "isolate"
    assert "42" in st.reason
    assert st.score >= ISOLATE_TENANT_SHARE
    # a diffuse tenant mix on the same shape degrades to a split
    diffuse = [["t", s.shard_id, node, str(i), 1, 100.0, 0, 0, 0.0, 0.0]
               for i in range(10)]
    steps2 = build_rebalance_plan(cl.catalog, "by_observed_load",
                                  load_scores=scores,
                                  attribution_rows=diffuse)
    assert steps2 and steps2[0].action == "split"
    cl.close()


def test_single_node_plans_nothing(tmp_path):
    cl = make_cluster(tmp_path, nodes=1)
    assert build_rebalance_plan(cl.catalog, "by_shard_count") == []
    cl.close()
