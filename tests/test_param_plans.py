"""Parameterized-plan cache with deferred pruning — VERDICT item #7.

Reference: prepared statements keep a generic plan with shard pruning
deferred to bind time (Job->deferredPruning, fast_path_router_planner.c
README:307-311).  Here: one bind+plan per SQL text, $N values arrive as
0-d traced arrays, so the jitted kernel compiles once and every later
execution is zero replan / zero recompile."""

import numpy as np
import pytest

import citus_tpu as ct


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, s text, d decimal(8,2))")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={
        "k": np.arange(2000), "v": np.arange(2000) % 50,
        "s": [f"n{i % 5}" for i in range(2000)],
        "d": np.arange(2000) / 4})
    yield cl
    cl.close()


def _delta(c0, c1, key):
    return c1.get(key, 0) - c0.get(key, 0)


def test_router_query_zero_replan(db):
    cl = db
    c0 = cl.counters.snapshot()
    for kv in (5, 77, 400, 913, 1999):
        r = cl.execute("SELECT v, s FROM t WHERE k = $1", params=[kv])
        assert r.rows == [(kv % 50, f"n{kv % 5}")]
        assert r.explain["router"] is True
        assert r.explain["shards"] == 1
    c1 = cl.counters.snapshot()
    assert _delta(c0, c1, "plan_cache_misses") == 1
    assert _delta(c0, c1, "plan_cache_hits") == 4
    assert _delta(c0, c1, "router_queries") == 5


def test_jit_kernel_reused_across_values(db):
    cl = db
    sql = "SELECT count(*), sum(v) FROM t WHERE v < $1"
    for lim in (10, 25, 40, 49):
        cl.execute(sql, params=[lim])
    plan = cl._plan_cache[("$param", sql)][1]
    # one plan object; its jitted worker was traced exactly once even
    # though four different parameter values executed
    assert "mesh_run" in plan.runtime_cache or "jit_worker" in plan.runtime_cache
    jitted = plan.runtime_cache.get("jit_worker")
    if jitted is not None and hasattr(jitted, "_cache_size"):
        assert jitted._cache_size() == 1


def test_param_results_match_literal_path(db):
    cl = db
    v = np.arange(2000) % 50
    for lim in (7, 33):
        a = cl.execute("SELECT s, count(*), sum(v) FROM t WHERE v < $1 "
                       "GROUP BY s ORDER BY s", params=[lim])
        b = cl.execute(f"SELECT s, count(*), sum(v) FROM t WHERE v < {lim} "
                       "GROUP BY s ORDER BY s")
        assert a.rows == b.rows


def test_text_and_null_and_decimal_params(db):
    cl = db
    assert cl.execute("SELECT count(*) FROM t WHERE s = $1",
                      params=["n3"]).rows == [(400,)]
    assert cl.execute("SELECT count(*) FROM t WHERE s = $1",
                      params=["missing"]).rows == [(0,)]
    assert cl.execute("SELECT count(*) FROM t WHERE k = $1",
                      params=[None]).rows == [(0,)]
    assert cl.execute("SELECT count(*) FROM t WHERE d <= $1",
                      params=[2.5]).rows == [(11,)]


def test_params_in_select_list_and_between(db):
    cl = db
    r = cl.execute("SELECT v + $2 FROM t WHERE k = $1", params=[3, 100])
    assert r.rows == [(103,)]
    r = cl.execute("SELECT count(*) FROM t WHERE v BETWEEN $1 AND $2",
                   params=[10, 19])
    assert r.rows == [(400,)]
    r = cl.execute("SELECT count(*) FROM t WHERE v IN ($1, $2, $3)",
                   params=[1, 2, 3])
    assert r.rows == [(120,)]


def test_plan_invalidated_on_ddl(db):
    cl = db
    sql = "SELECT count(*) FROM t WHERE v < $1"
    cl.execute(sql, params=[5])
    cl.execute("ALTER TABLE t ADD COLUMN extra bigint")
    c0 = cl.counters.snapshot()
    r = cl.execute(sql, params=[5])
    c1 = cl.counters.snapshot()
    assert _delta(c0, c1, "plan_cache_misses") == 1  # replanned after DDL
    assert r.rows == [(200,)]


def test_fallback_for_subquery_params(db):
    """Shapes outside the generic-plan subset still execute correctly
    through literal substitution."""
    cl = db
    r = cl.execute(
        "SELECT count(*) FROM t WHERE v < (SELECT max(v) FROM t WHERE k < $1)",
        params=[100])
    lit = cl.execute(
        "SELECT count(*) FROM t WHERE v < (SELECT max(v) FROM t WHERE k < 100)")
    assert r.rows == lit.rows


def test_missing_params_error(db):
    cl = db
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError):
        cl.execute("SELECT count(*) FROM t WHERE v < $2", params=[1])
