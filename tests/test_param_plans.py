"""Parameterized-plan cache with deferred pruning — VERDICT item #7.

Reference: prepared statements keep a generic plan with shard pruning
deferred to bind time (Job->deferredPruning, fast_path_router_planner.c
README:307-311).  Here: one bind+plan per SQL text, $N values arrive as
0-d traced arrays, so the jitted kernel compiles once and every later
execution is zero replan / zero recompile."""

import numpy as np
import pytest

import citus_tpu as ct


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, s text, d decimal(8,2))")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={
        "k": np.arange(2000), "v": np.arange(2000) % 50,
        "s": [f"n{i % 5}" for i in range(2000)],
        "d": np.arange(2000) / 4})
    yield cl
    cl.close()


def _delta(c0, c1, key):
    return c1.get(key, 0) - c0.get(key, 0)


def test_router_query_zero_replan(db):
    cl = db
    c0 = cl.counters.snapshot()
    for kv in (5, 77, 400, 913, 1999):
        r = cl.execute("SELECT v, s FROM t WHERE k = $1", params=[kv])
        assert r.rows == [(kv % 50, f"n{kv % 5}")]
        assert r.explain["router"] is True
        assert r.explain["shards"] == 1
    c1 = cl.counters.snapshot()
    assert _delta(c0, c1, "plan_cache_misses") == 1
    assert _delta(c0, c1, "plan_cache_hits") == 4
    assert _delta(c0, c1, "router_queries") == 5


def test_jit_kernel_reused_across_values(db):
    cl = db
    sql = "SELECT count(*), sum(v) FROM t WHERE v < $1"
    for lim in (10, 25, 40, 49):
        cl.execute(sql, params=[lim])
    plan = cl._plan_cache[("$param", sql)][1]
    # one plan object; its jitted worker was traced exactly once even
    # though four different parameter values executed
    assert "mesh_run" in plan.runtime_cache or "jit_fused" in plan.runtime_cache
    jitted = plan.runtime_cache.get("jit_fused")
    if jitted is not None and hasattr(jitted, "_cache_size"):
        assert jitted._cache_size() == 1


def test_param_results_match_literal_path(db):
    cl = db
    v = np.arange(2000) % 50
    for lim in (7, 33):
        a = cl.execute("SELECT s, count(*), sum(v) FROM t WHERE v < $1 "
                       "GROUP BY s ORDER BY s", params=[lim])
        b = cl.execute(f"SELECT s, count(*), sum(v) FROM t WHERE v < {lim} "
                       "GROUP BY s ORDER BY s")
        assert a.rows == b.rows


def test_text_and_null_and_decimal_params(db):
    cl = db
    assert cl.execute("SELECT count(*) FROM t WHERE s = $1",
                      params=["n3"]).rows == [(400,)]
    assert cl.execute("SELECT count(*) FROM t WHERE s = $1",
                      params=["missing"]).rows == [(0,)]
    assert cl.execute("SELECT count(*) FROM t WHERE k = $1",
                      params=[None]).rows == [(0,)]
    assert cl.execute("SELECT count(*) FROM t WHERE d <= $1",
                      params=[2.5]).rows == [(11,)]


def test_params_in_select_list_and_between(db):
    cl = db
    r = cl.execute("SELECT v + $2 FROM t WHERE k = $1", params=[3, 100])
    assert r.rows == [(103,)]
    r = cl.execute("SELECT count(*) FROM t WHERE v BETWEEN $1 AND $2",
                   params=[10, 19])
    assert r.rows == [(400,)]
    r = cl.execute("SELECT count(*) FROM t WHERE v IN ($1, $2, $3)",
                   params=[1, 2, 3])
    assert r.rows == [(120,)]


def test_plan_invalidated_on_ddl(db):
    cl = db
    sql = "SELECT count(*) FROM t WHERE v < $1"
    cl.execute(sql, params=[5])
    cl.execute("ALTER TABLE t ADD COLUMN extra bigint")
    c0 = cl.counters.snapshot()
    r = cl.execute(sql, params=[5])
    c1 = cl.counters.snapshot()
    assert _delta(c0, c1, "plan_cache_misses") == 1  # replanned after DDL
    assert r.rows == [(200,)]


def test_fallback_for_subquery_params(db):
    """Shapes outside the generic-plan subset still execute correctly
    through literal substitution."""
    cl = db
    r = cl.execute(
        "SELECT count(*) FROM t WHERE v < (SELECT max(v) FROM t WHERE k < $1)",
        params=[100])
    lit = cl.execute(
        "SELECT count(*) FROM t WHERE v < (SELECT max(v) FROM t WHERE k < 100)")
    assert r.rows == lit.rows


def test_missing_params_error(db):
    cl = db
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError):
        cl.execute("SELECT count(*) FROM t WHERE v < $2", params=[1])


# ---- query-family kernel cache (auto-parameterization) --------------------


def test_literal_variants_share_kernels(db):
    """Two textually different ad-hoc queries that differ only in their
    comparison literals hoist to one structural fingerprint: the second
    variant reuses the first's compiled kernels — zero new XLA compiles
    — and still answers correctly (sqlite oracle)."""
    import sqlite3
    cl = db
    cl.execute("SELECT s, count(*), sum(v) FROM t WHERE v < 100 "
               "GROUP BY s ORDER BY s")
    c0 = cl.counters.snapshot()
    r = cl.execute("SELECT s, count(*), sum(v) FROM t WHERE v < 200 "
                   "GROUP BY s ORDER BY s")
    c1 = cl.counters.snapshot()
    assert _delta(c0, c1, "kernel_cache_hits") >= 1
    assert _delta(c0, c1, "kernel_cache_misses") == 0
    assert _delta(c0, c1, "kernel_compile_ms") == 0  # compile amortized
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, v INTEGER, s TEXT)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)",
                   [(i, i % 50, f"n{i % 5}") for i in range(2000)])
    exp = sq.execute("SELECT s, count(*), sum(v) FROM t WHERE v < 200 "
                     "GROUP BY s ORDER BY s").fetchall()
    assert [tuple(row) for row in r.rows] == [tuple(e) for e in exp]


def test_kernels_shared_between_adhoc_and_param_paths(db):
    """The $N prepared path and the auto-parameterized literal path
    produce the same generic structure, so they share kernels too."""
    cl = db
    cl.execute("SELECT count(*), sum(v) FROM t WHERE v < $1", params=[10])
    c0 = cl.counters.snapshot()
    r = cl.execute("SELECT count(*), sum(v) FROM t WHERE v < 25")
    c1 = cl.counters.snapshot()
    assert _delta(c0, c1, "kernel_cache_misses") == 0
    assert _delta(c0, c1, "kernel_compile_ms") == 0
    assert r.rows == [(1000, sum(i % 50 for i in range(2000)
                                 if i % 50 < 25))]


def test_plan_cache_mode_guc(db):
    """citus.plan_cache_mode: force_custom bypasses the plan cache for
    ad-hoc SQL (replan every execution, no counter traffic); auto
    caches by text."""
    cl = db
    cl.execute("SET citus.plan_cache_mode = force_custom")
    r = cl.execute("SHOW citus.plan_cache_mode")
    assert r.rows == [("force_custom",)]
    c0 = cl.counters.snapshot()
    a = cl.execute("SELECT count(*) FROM t WHERE v < 10")
    b = cl.execute("SELECT count(*) FROM t WHERE v < 10")
    c1 = cl.counters.snapshot()
    assert a.rows == b.rows == [(400,)]
    assert _delta(c0, c1, "plan_cache_hits") == 0
    assert _delta(c0, c1, "plan_cache_misses") == 0
    cl.execute("SET citus.plan_cache_mode = auto")
    c0 = cl.counters.snapshot()
    cl.execute("SELECT count(*) FROM t WHERE v < 11")
    cl.execute("SELECT count(*) FROM t WHERE v < 11")
    c1 = cl.counters.snapshot()
    assert _delta(c0, c1, "plan_cache_misses") == 1
    assert _delta(c0, c1, "plan_cache_hits") == 1
    from citus_tpu.errors import CatalogError
    with pytest.raises(CatalogError):
        cl.execute("SET citus.plan_cache_mode = bogus")


def test_kernel_cache_gucs(db):
    cl = db
    assert cl.execute("SHOW citus.kernel_cache_size").rows == [("512",)]
    cl.execute("SET citus.kernel_cache_size = 256")
    assert cl.execute("SHOW citus.kernel_cache_size").rows == [("256",)]
    from citus_tpu.executor.kernel_cache import GLOBAL_KERNELS
    assert GLOBAL_KERNELS.capacity == 256
    cl.execute("SET citus.kernel_cache_size = 512")
    assert cl.execute("SHOW citus.jit_cache_dir").rows == [("",)]


def test_explain_analyze_shows_cache_lines(db):
    cl = db
    r1 = cl.execute("EXPLAIN ANALYZE SELECT count(*) FROM t WHERE v < 30")
    txt1 = "\n".join(row[0] for row in r1.rows)
    assert "Plan Cache: miss" in txt1, txt1
    assert "Device Cache:" in txt1, txt1
    r2 = cl.execute("EXPLAIN ANALYZE SELECT count(*) FROM t WHERE v < 30")
    txt2 = "\n".join(row[0] for row in r2.rows)
    assert "Plan Cache: hit" in txt2, txt2


# ---- surgical invalidation ------------------------------------------------


def test_ddl_on_other_table_keeps_plan(db):
    """DDL against table B must not evict A's cached plans: the DDL
    epoch bump is disarmed by the object-state token compare and the
    entry re-arms in place."""
    cl = db
    cl.execute("CREATE TABLE other (x bigint, y bigint)")
    sql = "SELECT count(*) FROM t WHERE v < $1"
    assert cl.execute(sql, params=[5]).rows == [(200,)]
    cl.execute("ALTER TABLE other ADD COLUMN z bigint")
    cl.execute("CREATE INDEX other_x ON other (x)")
    c0 = cl.counters.snapshot()
    r = cl.execute(sql, params=[7])
    c1 = cl.counters.snapshot()
    assert r.rows == [(280,)]
    assert _delta(c0, c1, "plan_cache_hits") == 1
    assert _delta(c0, c1, "plan_cache_misses") == 0
    assert _delta(c0, c1, "plan_cache_invalidations") == 0


def test_ddl_on_own_table_still_invalidates(db):
    """ALTER / TRUNCATE against the referenced table itself must keep
    invalidating — surgical, not absent."""
    cl = db
    sql = "SELECT count(*) FROM t WHERE v < $1"
    cl.execute(sql, params=[5])
    cl.execute("ALTER TABLE t ADD COLUMN e1 bigint")
    c0 = cl.counters.snapshot()
    cl.execute(sql, params=[5])
    c1 = cl.counters.snapshot()
    assert _delta(c0, c1, "plan_cache_misses") == 1
    cl.execute("TRUNCATE t")
    c0 = cl.counters.snapshot()
    r = cl.execute(sql, params=[5])
    c1 = cl.counters.snapshot()
    assert r.rows == [(0,)]
    assert _delta(c0, c1, "plan_cache_misses") == 1


def test_ingest_flip_invalidates_cached_plan(db):
    """The ingest-flip window: an INSERT bumps the table version, so a
    plan cached before the flip is detected stale at its next lookup
    and replanned — results include the new row."""
    cl = db
    sql = "SELECT count(*) FROM t WHERE v < $1"
    assert cl.execute(sql, params=[1]).rows == [(40,)]
    cl.execute("INSERT INTO t VALUES (5000, 0, 'n0', 1.0)")
    c0 = cl.counters.snapshot()
    r = cl.execute(sql, params=[1])
    c1 = cl.counters.snapshot()
    assert r.rows == [(41,)]
    assert _delta(c0, c1, "plan_cache_misses") == 1
    assert _delta(c0, c1, "plan_cache_hits") == 0
