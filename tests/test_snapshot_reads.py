"""Snapshot (MVCC-style) reads: SELECT never blocks behind writers and
never observes a torn multi-file metadata flip.

Reference: the MVCC read semantics the reference inherits from
PostgreSQL — readers never block writers, writers never block readers,
every statement sees a consistent snapshot.  Round-4 VERDICT weak #3 /
next #4: drop the shared flip latch from the read path.
"""

import threading
import time

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import ExecutorSettings, Settings
from citus_tpu.testing.faults import FAULTS


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    n = 4000
    cl.copy_from("t", columns={"k": np.arange(n), "v": np.ones(n, np.int64)})
    yield cl
    FAULTS.disarm()
    cl.close()


def test_slow_select_overlapping_truncate_update_move(db):
    """The VERDICT scenario: a slow multi-shard SELECT overlaps
    TRUNCATE + UPDATE + a shard move; it must return a consistent image
    (all-or-nothing per statement), and the writers must never wait for
    the reader."""
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    GLOBAL_CACHE.clear()
    # ~0.08 s per placement read -> the scan spans all writer activity
    FAULTS.arm("read_placement", delay_s=0.08)
    results, writer_times = [], {}

    def reader():
        r = db.execute("SELECT count(*), sum(v) FROM t")
        results.append(r.rows[0])

    th = threading.Thread(target=reader)
    th.start()
    time.sleep(0.05)  # reader is mid-scan now
    t0 = time.perf_counter()
    db.execute("UPDATE t SET v = 2 WHERE k < 1000")
    writer_times["update"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    shard = db.catalog.table("t").shards[0]
    other = 1 - shard.placements[0]
    db.execute(f"SELECT citus_move_shard_placement({shard.shard_id}, "
               f"{shard.placements[0]}, {other})")
    writer_times["move"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    db.execute("TRUNCATE t")
    writer_times["truncate"] = time.perf_counter() - t0
    th.join(timeout=30)
    assert not th.is_alive()
    count, total = results[0]
    # consistent images only: pre-everything, post-update, or
    # post-truncate; never a torn mixture
    valid = {(4000, 4000), (4000, 5000), (0, None)}
    assert (count, total) in valid, f"torn read: {(count, total)}"
    # writers never waited out the reader's multi-second scan
    for op, dt in writer_times.items():
        assert dt < 2.0, f"{op} blocked behind the reader for {dt:.2f}s"


def test_update_commit_atomic_to_readers(db):
    """An UPDATE's commit flips deletion bitmaps AND re-insert stripes;
    a reader must never see the deletes without the replacements (the
    pre-snapshot read path could undercount here)."""
    stop = threading.Event()
    errors = []

    def hammer_reads():
        while not stop.is_set():
            r = db.execute("SELECT count(*) FROM t")
            if r.rows[0][0] != 4000:
                errors.append(r.rows[0][0])
                return

    threads = [threading.Thread(target=hammer_reads) for _ in range(2)]
    for th in threads:
        th.start()
    try:
        for i in range(8):
            db.execute(f"UPDATE t SET v = {i + 10} WHERE k % 3 = 0")
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=10)
    assert not errors, f"reader saw torn UPDATE commit: count={errors[0]}"


def test_copy_visibility_all_or_nothing(db):
    """A multi-shard COPY flip is atomic to readers: counts move in one
    jump, never through intermediate per-shard states."""
    stop = threading.Event()
    seen = set()
    errors = []

    def watch():
        while not stop.is_set():
            c = db.execute("SELECT count(*) FROM t").rows[0][0]
            seen.add(c)
            if c not in (4000, 6000):
                errors.append(c)
                return

    th = threading.Thread(target=watch)
    th.start()
    try:
        db.copy_from("t", columns={"k": np.arange(4000, 6000),
                                   "v": np.ones(2000, np.int64)})
        time.sleep(0.05)
    finally:
        stop.set()
        th.join(timeout=10)
    assert not errors, f"torn COPY visibility: {errors[0]}"
    assert db.execute("SELECT count(*) FROM t").rows == [(6000,)]


def test_vacuum_swap_invisible_to_readers(db):
    """VACUUM's placement directory swap (old -> .old, new -> live) must
    never surface as a missing placement or torn data to a concurrent
    reader."""
    db.execute("DELETE FROM t WHERE k % 2 = 1")
    stop = threading.Event()
    errors = []

    def hammer():
        while not stop.is_set():
            c = db.execute("SELECT count(*) FROM t").rows[0][0]
            if c != 2000:
                errors.append(c)
                return

    th = threading.Thread(target=hammer)
    th.start()
    try:
        for _ in range(3):
            db.execute("VACUUM t")
    finally:
        stop.set()
        th.join(timeout=10)
    assert not errors, f"reader observed VACUUM swap: {errors[0]}"


def test_snapshot_costs_no_reader_lock(tmp_path):
    """Reads take no shared lock: a reader runs to completion while an
    EXCLUSIVE write lock is held by someone else (only the tiny flip
    window excludes readers, not whole statements)."""
    cl = ct.Cluster(str(tmp_path / "db"), settings=Settings(
        executor=ExecutorSettings(lock_timeout_s=1.0)))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={"k": np.arange(100), "v": np.arange(100)})
    from citus_tpu.transaction.locks import EXCLUSIVE
    t = cl.catalog.table("t")
    done = threading.Event()

    def hold_lock():
        with cl._write_lock(t, EXCLUSIVE):
            done.wait(5.0)

    th = threading.Thread(target=hold_lock)
    th.start()
    time.sleep(0.1)
    try:
        # pre-snapshot design: this would block behind the 2PL lock via
        # the latch; now it completes immediately
        t0 = time.perf_counter()
        assert cl.execute("SELECT count(*) FROM t").rows == [(100,)]
        assert time.perf_counter() - t0 < 0.9
    finally:
        done.set()
        th.join()
        cl.close()
