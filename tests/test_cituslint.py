"""cituslint framework tests: every rule group fires on a bad fixture,
stays quiet on the equivalent good one, and the suppression pragma
behaves (honored when justified, itself a diagnostic when not)."""

import textwrap

import pytest

from tools.cituslint import run_lint


def make_pkg(tmp_path, files: dict) -> str:
    """Write a synthetic package and return its path."""
    pkg = tmp_path / "fixturepkg"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(pkg)


def ids(diags):
    return [d.rule_id for d in diags]


# ------------------------------------------------------------- LOCK01

LOCKY_BAD = """
    import threading

    class Box:
        def __init__(self):
            self._mu = threading.Lock()
            self.items = []

        def add(self, x):
            with self._mu:
                self.items.append(x)

        def drop(self):
            self.items = []
"""

LOCKY_GOOD = """
    import threading

    class Box:
        def __init__(self):
            self._mu = threading.Lock()
            self.items = []

        def add(self, x):
            with self._mu:
                self.items.append(x)

        def drop(self):
            with self._mu:
                self.items = []
"""


def test_lock_rule_fires_on_unguarded_write(tmp_path):
    diags = run_lint(make_pkg(tmp_path, {"box.py": LOCKY_BAD}),
                     select={"LOCK01"})
    assert ids(diags) == ["LOCK01"]
    assert "drop" in diags[0].message and "items" in diags[0].message


def test_lock_rule_quiet_when_guarded(tmp_path):
    assert run_lint(make_pkg(tmp_path, {"box.py": LOCKY_GOOD}),
                    select={"LOCK01"}) == []


def test_lock_rule_locked_suffix_convention(tmp_path):
    src = """
        import threading

        class Box:
            def __init__(self):
                self._mu = threading.Lock()
                self.items = []

            def add(self, x):
                with self._mu:
                    self._add_locked(x)

            def _add_locked(self, x):
                self.items.append(x)

            def sneak(self, x):
                self._add_locked(x)
    """
    diags = run_lint(make_pkg(tmp_path, {"box.py": src}),
                     select={"LOCK01"})
    # _add_locked's own mutation is fine (caller holds the lock); the
    # unguarded CALL from sneak() is the finding
    assert len(diags) == 1
    assert "sneak" in diags[0].message and "_add_locked" in diags[0].message


def test_lock_rule_ignores_init(tmp_path):
    src = """
        import threading

        class Box:
            def __init__(self):
                self._mu = threading.Lock()
                self.items = []
                self.items = ["seed"]

            def add(self, x):
                with self._mu:
                    self.items.append(x)
    """
    assert run_lint(make_pkg(tmp_path, {"box.py": src}),
                    select={"LOCK01"}) == []


# ------------------------------------------------------------- CONF01

def test_confinement_fires_outside_blessed_module(tmp_path):
    pkg = make_pkg(tmp_path, {
        "utils/__init__.py": "",
        "utils/clock.py": "import time\n\ndef now():\n    return time.time()\n",
        "stray.py": "import time\n\ndef f():\n    return time.time()\n",
    })
    diags = run_lint(pkg, select={"CONF01"})
    assert len(diags) == 1
    assert diags[0].path.endswith("stray.py")
    assert "time.time" in diags[0].message


def test_confinement_resolves_import_aliases(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stray.py": "import time as _t\n\ndef f():\n    return _t.time()\n",
    })
    diags = run_lint(pkg, select={"CONF01"})
    assert len(diags) == 1 and "time.time" in diags[0].message


def test_confinement_quiet_in_blessed_module(tmp_path):
    pkg = make_pkg(tmp_path, {
        "utils/__init__.py": "",
        "utils/clock.py": "import time\n\ndef now():\n    return time.time()\n",
    })
    assert run_lint(pkg, select={"CONF01"}) == []


def test_thread_rules(tmp_path):
    src = """
        import threading

        def bad():
            t = threading.Thread(target=print)
            t.start()

        def good():
            t = threading.Thread(target=print, daemon=False)
            t.start()
            t.join()
    """
    diags = run_lint(make_pkg(tmp_path, {"threads.py": src}),
                     select={"THR01", "THR02"})
    # bad(): missing daemon= (THR01).  THR02 is module-scoped on the
    # bound name: 't' IS joined (in good), so only THR01 fires here.
    assert ids(diags) == ["THR01"]

    src2 = """
        import threading

        def fire_and_forget():
            threading.Thread(target=print, daemon=True).start()
    """
    diags2 = run_lint(make_pkg(tmp_path / "p2", {"threads2.py": src2}),
                      select={"THR01", "THR02"})
    assert ids(diags2) == ["THR02"]


# ------------------------------------------------------------- SWL01

def test_silent_swallow_fires(tmp_path):
    src = """
        def f():
            try:
                risky()
            except Exception:
                pass
    """
    diags = run_lint(make_pkg(tmp_path, {"m.py": src}), select={"SWL01"})
    assert ids(diags) == ["SWL01"]


def test_bare_except_fires(tmp_path):
    src = """
        def f():
            for _ in range(3):
                try:
                    risky()
                except:
                    continue
    """
    diags = run_lint(make_pkg(tmp_path, {"m.py": src}), select={"SWL01"})
    assert ids(diags) == ["SWL01"]
    assert "bare except" in diags[0].message


def test_swallow_with_handling_is_quiet(tmp_path):
    src = """
        def f(counters):
            try:
                risky()
            except Exception:
                counters.bump("errors")
            try:
                risky()
            except ValueError:
                pass  # narrow catch: not SWL01's business
    """
    assert run_lint(make_pkg(tmp_path, {"m.py": src}),
                    select={"SWL01"}) == []


# ----------------------------------------------------------- CNT01/02

STATS_FIXTURE = """
    class StatCounters:
        COUNTERS = [
            "queries_executed",
            "errors_seen",
        ]
"""


def test_undeclared_counter_bump_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": STATS_FIXTURE,
        "m.py": ("def f(c):\n    c.bump('queries_executed')\n"
                 "    c.bump('made_up_name')\n"),
    })
    diags = run_lint(pkg, select={"CNT01"})
    assert len(diags) == 1 and "made_up_name" in diags[0].message


def test_dead_counter_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": STATS_FIXTURE,
        "m.py": "def f(c):\n    c.bump('queries_executed')\n",
    })
    diags = run_lint(pkg, select={"CNT02"})
    assert len(diags) == 1 and "errors_seen" in diags[0].message


def test_declared_and_used_counters_quiet(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": STATS_FIXTURE,
        "m.py": ("def f(c):\n    c.bump('queries_executed')\n"
                 "    c.bump_max('errors_seen', 2)\n"),
    })
    assert run_lint(pkg, select={"CNT01", "CNT02"}) == []


# --------------------------------------------------------------- CNT03

WAITS_FIXTURE = """
    class StatCounters:
        COUNTERS = ["wait_lock_ms", "wait_remote_rpc_ms"]

    WAIT_COUNTERS = {
        "lock": "wait_lock_ms",
        "remote_rpc": "wait_remote_rpc_ms",
    }
"""


def test_unregistered_wait_event_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": WAITS_FIXTURE,
        "m.py": ("from stats import begin_wait\n"
                 "def f():\n"
                 "    begin_wait('lock')\n"
                 "    begin_wait('remote_rpc')\n"
                 "    begin_wait('made_up_stall')\n"),
    })
    diags = run_lint(pkg, select={"CNT03"})
    assert len(diags) == 1 and "made_up_stall" in diags[0].message


def test_unentered_wait_event_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": WAITS_FIXTURE,
        "m.py": ("def f(stats):\n"
                 "    stats.begin_wait('lock')\n"),
    })
    diags = run_lint(pkg, select={"CNT03"})
    assert len(diags) == 1 and "remote_rpc" in diags[0].message


def test_registered_and_entered_wait_events_quiet(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": WAITS_FIXTURE,
        "m.py": ("from stats import begin_wait\n"
                 "def f(stats):\n"
                 "    begin_wait('lock')\n"
                 "    stats.begin_wait('remote_rpc')\n"),
    })
    assert run_lint(pkg, select={"CNT03"}) == []


# --------------------------------------------------------------- CNT04

RECORDER_FIXTURE = """
    HEALTH_EVENT_KINDS = {
        "p99_regression": "p99 above baseline",
        "dead_node": "endpoint unreachable",
    }
"""

# both kinds surfaced: export uses the health_<kind> gauge spelling,
# utility uses the bare kind in its severity row table
EXPORT_FIXTURE = ('def g(d, active):\n'
                  '    d["health_p99_regression"] = active\n'
                  '    d["health_dead_node"] = active\n')
UTILITY_FIXTURE = ('SEV = {"p99_regression": "warning",\n'
                   '       "dead_node": "critical"}\n')

CNT04_BASE = {
    "observability/__init__.py": "",
    "commands/__init__.py": "",
    "observability/flight_recorder.py": RECORDER_FIXTURE,
}


def test_health_kind_missing_gauge_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        **CNT04_BASE,
        "observability/export.py":
            'def g(d, active):\n    d["health_p99_regression"] = active\n',
        "commands/utility.py": UTILITY_FIXTURE,
    })
    diags = run_lint(pkg, select={"CNT04"})
    assert len(diags) == 1
    assert "dead_node" in diags[0].message
    assert "Prometheus" in diags[0].message


def test_health_kind_missing_row_type_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        **CNT04_BASE,
        "observability/export.py": EXPORT_FIXTURE,
        "commands/utility.py": 'SEV = {"p99_regression": "warning"}\n',
    })
    diags = run_lint(pkg, select={"CNT04"})
    assert len(diags) == 1
    assert "dead_node" in diags[0].message
    assert "citus_health_events" in diags[0].message


def test_undeclared_emit_kind_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        **CNT04_BASE,
        "observability/export.py": EXPORT_FIXTURE,
        "commands/utility.py": UTILITY_FIXTURE,
        "m.py": ("def f(rec):\n"
                 "    rec.emit_event('p99_regression', 'x', 1, 0, 'd')\n"
                 "    rec.emit_event('made_up_alarm', 'x', 1, 0, 'd')\n"),
    })
    diags = run_lint(pkg, select={"CNT04"})
    assert len(diags) == 1 and "made_up_alarm" in diags[0].message


def test_health_kinds_fully_surfaced_quiet(tmp_path):
    pkg = make_pkg(tmp_path, {
        **CNT04_BASE,
        "observability/export.py": EXPORT_FIXTURE,
        "commands/utility.py": UTILITY_FIXTURE,
        "m.py": ("def f(rec):\n"
                 "    rec.emit_event('dead_node', 'h:1', 1, 0, 'down')\n"),
    })
    assert run_lint(pkg, select={"CNT04"}) == []


# ------------------------------------------------------------- GUC01

CONFIG_FIXTURE = """
    from dataclasses import dataclass, field

    @dataclass
    class PlannerSettings:
        shard_cap: int = 8

    @dataclass
    class Settings:
        planner: PlannerSettings = field(default_factory=PlannerSettings)
        verbose: bool = False

        def replace(self, **kw):
            return self
"""

GUCS_FIXTURE = """
    _GUCS = {
        "citus.shard_cap": ("planner", "shard_cap", int),
        "citus.verbose": (None, "verbose", "bool"),
    }
"""


def test_settings_typo_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "config.py": CONFIG_FIXTURE,
        "commands/__init__.py": "",
        "commands/config_cmds.py": GUCS_FIXTURE,
        "m.py": "def f(settings):\n    return settings.planner.shardcap\n",
    })
    diags = run_lint(pkg, select={"GUC01"})
    assert len(diags) == 1 and "shardcap" in diags[0].message


def test_settings_uncovered_field_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "config.py": CONFIG_FIXTURE,
        "commands/__init__.py": "",
        "commands/config_cmds.py": "_GUCS = {}\n",
        "m.py": "def f(settings):\n    return settings.planner.shard_cap\n",
    })
    diags = run_lint(pkg, select={"GUC01"})
    assert len(diags) == 1 and "SET/SHOW" in diags[0].message


def test_settings_covered_reads_quiet(tmp_path):
    pkg = make_pkg(tmp_path, {
        "config.py": CONFIG_FIXTURE,
        "commands/__init__.py": "",
        "commands/config_cmds.py": GUCS_FIXTURE,
        "m.py": ("def f(settings):\n"
                 "    return settings.planner.shard_cap, settings.verbose\n"),
    })
    assert run_lint(pkg, select={"GUC01"}) == []


# -------------------------------------------------------- suppressions

def test_justified_suppression_honored(tmp_path):
    src = """
        def f():
            try:
                risky()
            # lint: disable=SWL01 -- probe only; failure falls back
            except Exception:
                pass
    """
    assert run_lint(make_pkg(tmp_path, {"m.py": src})) == []


def test_trailing_suppression_honored(tmp_path):
    src = """
        import time

        def f():
            return time.time()  # lint: disable=CONF01 -- wall-clock display only
    """
    assert run_lint(make_pkg(tmp_path, {"m.py": src}),
                    select={"CONF01"}) == []


def test_unjustified_suppression_rejected(tmp_path):
    src = """
        def f():
            try:
                risky()
            # lint: disable=SWL01
            except Exception:
                pass
    """
    diags = run_lint(make_pkg(tmp_path, {"m.py": src}))
    got = set(ids(diags))
    # the swallow STILL fires (no justification => no suppression) and
    # the bare pragma is its own finding
    assert got == {"SWL01", "SUP01"}


def test_unknown_rule_id_in_pragma_rejected(tmp_path):
    src = """
        X = 1  # lint: disable=NOPE99 -- misremembered id
    """
    diags = run_lint(make_pkg(tmp_path, {"m.py": src}))
    assert ids(diags) == ["SUP02"]
    assert "NOPE99" in diags[0].message


def test_suppression_only_covers_named_rule(tmp_path):
    src = """
        import time

        def f():
            try:
                return time.time()
            # lint: disable=CONF01 -- wrong id for the swallow below
            except Exception:
                pass
    """
    diags = run_lint(make_pkg(tmp_path, {"m.py": src}),
                     select={"SWL01", "CONF01"})
    assert "SWL01" in ids(diags)


# ------------------------------------------------------------ engine

def test_parse_error_is_a_diagnostic(tmp_path):
    diags = run_lint(make_pkg(tmp_path, {"broken.py": "def f(:\n"}))
    assert ids(diags) == ["PARSE"]


def test_missing_package_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        run_lint(str(tmp_path / "no_such_pkg"))


def test_diagnostics_sorted_and_unique(tmp_path):
    src = """
        def f():
            try:
                risky()
            except Exception:
                pass

        def g():
            try:
                risky()
            except Exception:
                pass
    """
    diags = run_lint(make_pkg(tmp_path, {"b.py": src, "a.py": src}),
                     select={"SWL01"})
    assert len(diags) == 4
    assert diags == sorted(diags)


def test_cli_main_exit_codes(tmp_path, capsys):
    from tools.cituslint.__main__ import main
    pkg = make_pkg(tmp_path, {"m.py": "def f():\n    try:\n        x()\n"
                                      "    except Exception:\n        pass\n"})
    assert main([pkg]) == 1
    out = capsys.readouterr().out
    assert "SWL01" in out
    clean = make_pkg(tmp_path / "c", {"m.py": "X = 1\n"})
    assert main([clean]) == 0
    assert main(["--list-rules"]) == 0


# ------------------------------------------------------------- LOCK02

ORDER_CYCLE_2 = """
    import threading

    class Pair:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def ab(self):
            with self._a:
                with self._b:
                    pass

        def ba(self):
            with self._b:
                with self._a:
                    pass
"""

ORDER_CONSISTENT = """
    import threading

    class Pair:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._a:
                with self._b:
                    pass
"""


def test_lock_order_two_lock_cycle(tmp_path):
    diags = run_lint(make_pkg(tmp_path, {"pair.py": ORDER_CYCLE_2}),
                     select={"LOCK02"})
    assert ids(diags) == ["LOCK02"]
    msg = diags[0].message
    assert "cycle" in msg and "Pair._a" in msg and "Pair._b" in msg


def test_lock_order_consistent_is_quiet(tmp_path):
    assert run_lint(make_pkg(tmp_path, {"pair.py": ORDER_CONSISTENT}),
                    select={"LOCK02"}) == []


def test_lock_order_three_lock_rotation(tmp_path):
    src = """
        import threading

        class Trio:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._c = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def bc(self):
                with self._b:
                    with self._c:
                        pass

            def ca(self):
                with self._c:
                    with self._a:
                        pass
    """
    diags = run_lint(make_pkg(tmp_path, {"trio.py": src}),
                     select={"LOCK02"})
    assert ids(diags) == ["LOCK02"]
    for node in ("Trio._a", "Trio._b", "Trio._c"):
        assert node in diags[0].message


def test_lock_order_resolves_through_locked_helper(tmp_path):
    src = """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def _grab_b_locked(self):
                with self._b:
                    pass

            def ab(self):
                with self._a:
                    self._grab_b_locked()

            def ba(self):
                with self._b:
                    with self._a:
                        pass
    """
    diags = run_lint(make_pkg(tmp_path, {"pair.py": src}),
                     select={"LOCK02"})
    assert ids(diags) == ["LOCK02"]
    assert "cycle" in diags[0].message


def test_lock_order_self_reacquire_nonreentrant(tmp_path):
    src = """
        import threading

        class Box:
            def __init__(self):
                self._mu = threading.Lock()

            def outer(self):
                with self._mu:
                    self._inner()

            def _inner(self):
                with self._mu:
                    pass
    """
    diags = run_lint(make_pkg(tmp_path, {"box.py": src}),
                     select={"LOCK02"})
    assert ids(diags) == ["LOCK02"]
    assert "self-deadlock" in diags[0].message


def test_lock_order_rlock_reacquire_is_quiet(tmp_path):
    src = """
        import threading

        class Box:
            def __init__(self):
                self._mu = threading.RLock()

            def outer(self):
                with self._mu:
                    self._inner()

            def _inner(self):
                with self._mu:
                    pass
    """
    assert run_lint(make_pkg(tmp_path, {"box.py": src}),
                    select={"LOCK02"}) == []


# -------------------------------------------------------------- BLK01

BLK_RPC_UNDER_LOCK = """
    import threading

    class Client:
        def __init__(self, rpc):
            self._mu = threading.Lock()
            self.rpc = rpc

        def fetch(self):
            with self._mu:
                return self.rpc.call_binary("get", {})
"""

BLK_RPC_OUTSIDE_LOCK = """
    import threading

    class Client:
        def __init__(self, rpc):
            self._mu = threading.Lock()
            self.rpc = rpc
            self.last = None

        def fetch(self):
            got = self.rpc.call_binary("get", {})
            with self._mu:
                self.last = got
            return got
"""


def test_blocking_rpc_under_lock_fires(tmp_path):
    diags = run_lint(make_pkg(tmp_path, {"c.py": BLK_RPC_UNDER_LOCK}),
                     select={"BLK01"})
    assert ids(diags) == ["BLK01"]
    assert "RPC" in diags[0].message and "Client._mu" in diags[0].message


def test_blocking_rpc_outside_lock_is_quiet(tmp_path):
    assert run_lint(make_pkg(tmp_path, {"c.py": BLK_RPC_OUTSIDE_LOCK}),
                    select={"BLK01"}) == []


def test_blocking_sleep_on_loop_thread_fires(tmp_path):
    src = """
        import time

        class RpcEventLoop:
            def _run(self):
                while True:
                    time.sleep(0.1)
    """
    diags = run_lint(make_pkg(tmp_path, {"loop.py": src}),
                     select={"BLK01"})
    assert ids(diags) == ["BLK01"]
    assert "time.sleep" in diags[0].message
    assert "RpcEventLoop" in diags[0].message


def test_blocking_done_cb_body_is_loop_reachable(tmp_path):
    src = """
        import time

        class Dispatch:
            def __init__(self, loop):
                self._loop = loop

            def go(self):
                self._loop.submit(
                    "ep", done_cb=lambda fut: self._settle(fut))

            def _settle(self, fut):
                time.sleep(1.0)
    """
    diags = run_lint(make_pkg(tmp_path, {"d.py": src}),
                     select={"BLK01"})
    assert ids(diags) == ["BLK01"]
    assert "time.sleep" in diags[0].message


def test_bounded_waits_under_lock_are_quiet(tmp_path):
    src = """
        import threading

        class Box:
            def __init__(self, q, t):
                self._mu = threading.Lock()
                self.q = q
                self.t = t

            def drain(self):
                with self._mu:
                    item = self.q.get(timeout=1.0)
                    self.t.join(5.0)
                    return item
    """
    assert run_lint(make_pkg(tmp_path, {"b.py": src}),
                    select={"BLK01"}) == []


def test_unbounded_join_under_lock_fires(tmp_path):
    src = """
        import threading

        class Box:
            def __init__(self, t):
                self._mu = threading.Lock()
                self.t = t

            def stop(self):
                with self._mu:
                    self.t.join()
    """
    diags = run_lint(make_pkg(tmp_path, {"b.py": src}),
                     select={"BLK01"})
    assert ids(diags) == ["BLK01"]
    assert "join" in diags[0].message


# -------------------------------------------------------------- JIT01

JIT_IMPURE = """
    import jax

    COUNTERS = None

    def build():
        def kern(x):
            COUNTERS.bump("kernel_calls")
            return x + 1
        return jax.vmap(kern)
"""

JIT_PURE = """
    import jax

    def build():
        def kern(x):
            return x + 1
        return jax.vmap(kern)
"""


def test_jit_purity_counter_bump_fires(tmp_path):
    diags = run_lint(make_pkg(tmp_path, {"k.py": JIT_IMPURE}),
                     select={"JIT01"})
    assert ids(diags) == ["JIT01"]
    assert "COUNTERS bump" in diags[0].message
    assert "trace time" in diags[0].message


def test_jit_purity_pure_kernel_is_quiet(tmp_path):
    assert run_lint(make_pkg(tmp_path, {"k.py": JIT_PURE}),
                    select={"JIT01"}) == []


def test_jit_purity_clock_read_via_jit_compile(tmp_path):
    src = """
        import time

        def build(cache):
            def kern(x):
                t0 = time.perf_counter()
                return x * t0
            return cache.jit_compile(kern)
    """
    diags = run_lint(make_pkg(tmp_path, {"k.py": src}),
                     select={"JIT01"})
    assert ids(diags) == ["JIT01"]
    assert "clock read" in diags[0].message


def test_jit_purity_covers_donated_accumulator_body(tmp_path):
    # the fused hot-loop shape: the accumulator-threading body handed
    # to jit_compile WITH donate_argnums is purity-checked exactly like
    # a plain traced body — donation kwargs must not hide it
    src = """
        COUNTERS = None

        def build(cache):
            def fused(acc, cols, valids, row_mask):
                COUNTERS.bump("fused_dispatches")
                return tuple(a + c for a, c in zip(acc, cols))
            return cache.jit_compile(fused, donate_argnums=0)
    """
    diags = run_lint(make_pkg(tmp_path, {"k.py": src}),
                     select={"JIT01"})
    assert ids(diags) == ["JIT01"]
    assert "COUNTERS bump" in diags[0].message

    pure = """
        def build(cache, xp):
            def fused(acc, cols, valids, row_mask):
                return tuple(xp.minimum(a, c) for a, c in zip(acc, cols))
            return cache.jit_compile(fused, donate_argnums=0)
    """
    assert run_lint(make_pkg(tmp_path, {"k.py": pure}),
                    select={"JIT01"}) == []


def test_new_rules_suppressible_with_pragma(tmp_path):
    src = """
        import threading

        class Client:
            def __init__(self, rpc):
                self._mu = threading.Lock()
                self.rpc = rpc

            def fetch(self):
                with self._mu:
                    # lint: disable=BLK01 -- single-writer socket, lock IS the wire serializer
                    return self.rpc.call_binary("get", {})
    """
    assert run_lint(make_pkg(tmp_path, {"c.py": src}),
                    select={"BLK01", "SUP01", "SUP02"}) == []
