"""cituslint framework tests: every rule group fires on a bad fixture,
stays quiet on the equivalent good one, and the suppression pragma
behaves (honored when justified, itself a diagnostic when not)."""

import textwrap

import pytest

from tools.cituslint import run_lint


def make_pkg(tmp_path, files: dict) -> str:
    """Write a synthetic package and return its path."""
    pkg = tmp_path / "fixturepkg"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(pkg)


def ids(diags):
    return [d.rule_id for d in diags]


# ------------------------------------------------------------- LOCK01

LOCKY_BAD = """
    import threading

    class Box:
        def __init__(self):
            self._mu = threading.Lock()
            self.items = []

        def add(self, x):
            with self._mu:
                self.items.append(x)

        def drop(self):
            self.items = []
"""

LOCKY_GOOD = """
    import threading

    class Box:
        def __init__(self):
            self._mu = threading.Lock()
            self.items = []

        def add(self, x):
            with self._mu:
                self.items.append(x)

        def drop(self):
            with self._mu:
                self.items = []
"""


def test_lock_rule_fires_on_unguarded_write(tmp_path):
    diags = run_lint(make_pkg(tmp_path, {"box.py": LOCKY_BAD}),
                     select={"LOCK01"})
    assert ids(diags) == ["LOCK01"]
    assert "drop" in diags[0].message and "items" in diags[0].message


def test_lock_rule_quiet_when_guarded(tmp_path):
    assert run_lint(make_pkg(tmp_path, {"box.py": LOCKY_GOOD}),
                    select={"LOCK01"}) == []


def test_lock_rule_locked_suffix_convention(tmp_path):
    src = """
        import threading

        class Box:
            def __init__(self):
                self._mu = threading.Lock()
                self.items = []

            def add(self, x):
                with self._mu:
                    self._add_locked(x)

            def _add_locked(self, x):
                self.items.append(x)

            def sneak(self, x):
                self._add_locked(x)
    """
    diags = run_lint(make_pkg(tmp_path, {"box.py": src}),
                     select={"LOCK01"})
    # _add_locked's own mutation is fine (caller holds the lock); the
    # unguarded CALL from sneak() is the finding
    assert len(diags) == 1
    assert "sneak" in diags[0].message and "_add_locked" in diags[0].message


def test_lock_rule_ignores_init(tmp_path):
    src = """
        import threading

        class Box:
            def __init__(self):
                self._mu = threading.Lock()
                self.items = []
                self.items = ["seed"]

            def add(self, x):
                with self._mu:
                    self.items.append(x)
    """
    assert run_lint(make_pkg(tmp_path, {"box.py": src}),
                    select={"LOCK01"}) == []


# ------------------------------------------------------------- CONF01

def test_confinement_fires_outside_blessed_module(tmp_path):
    pkg = make_pkg(tmp_path, {
        "utils/__init__.py": "",
        "utils/clock.py": "import time\n\ndef now():\n    return time.time()\n",
        "stray.py": "import time\n\ndef f():\n    return time.time()\n",
    })
    diags = run_lint(pkg, select={"CONF01"})
    assert len(diags) == 1
    assert diags[0].path.endswith("stray.py")
    assert "time.time" in diags[0].message


def test_confinement_resolves_import_aliases(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stray.py": "import time as _t\n\ndef f():\n    return _t.time()\n",
    })
    diags = run_lint(pkg, select={"CONF01"})
    assert len(diags) == 1 and "time.time" in diags[0].message


def test_confinement_quiet_in_blessed_module(tmp_path):
    pkg = make_pkg(tmp_path, {
        "utils/__init__.py": "",
        "utils/clock.py": "import time\n\ndef now():\n    return time.time()\n",
    })
    assert run_lint(pkg, select={"CONF01"}) == []


def test_thread_rules(tmp_path):
    src = """
        import threading

        def bad():
            t = threading.Thread(target=print)
            t.start()

        def good():
            t = threading.Thread(target=print, daemon=False)
            t.start()
            t.join()
    """
    diags = run_lint(make_pkg(tmp_path, {"threads.py": src}),
                     select={"THR01", "THR02"})
    # bad(): missing daemon= (THR01).  THR02 is module-scoped on the
    # bound name: 't' IS joined (in good), so only THR01 fires here.
    assert ids(diags) == ["THR01"]

    src2 = """
        import threading

        def fire_and_forget():
            threading.Thread(target=print, daemon=True).start()
    """
    diags2 = run_lint(make_pkg(tmp_path / "p2", {"threads2.py": src2}),
                      select={"THR01", "THR02"})
    assert ids(diags2) == ["THR02"]


# ------------------------------------------------------------- SWL01

def test_silent_swallow_fires(tmp_path):
    src = """
        def f():
            try:
                risky()
            except Exception:
                pass
    """
    diags = run_lint(make_pkg(tmp_path, {"m.py": src}), select={"SWL01"})
    assert ids(diags) == ["SWL01"]


def test_bare_except_fires(tmp_path):
    src = """
        def f():
            for _ in range(3):
                try:
                    risky()
                except:
                    continue
    """
    diags = run_lint(make_pkg(tmp_path, {"m.py": src}), select={"SWL01"})
    assert ids(diags) == ["SWL01"]
    assert "bare except" in diags[0].message


def test_swallow_with_handling_is_quiet(tmp_path):
    src = """
        def f(counters):
            try:
                risky()
            except Exception:
                counters.bump("errors")
            try:
                risky()
            except ValueError:
                pass  # narrow catch: not SWL01's business
    """
    assert run_lint(make_pkg(tmp_path, {"m.py": src}),
                    select={"SWL01"}) == []


# ----------------------------------------------------------- CNT01/02

STATS_FIXTURE = """
    class StatCounters:
        COUNTERS = [
            "queries_executed",
            "errors_seen",
        ]
"""


def test_undeclared_counter_bump_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": STATS_FIXTURE,
        "m.py": ("def f(c):\n    c.bump('queries_executed')\n"
                 "    c.bump('made_up_name')\n"),
    })
    diags = run_lint(pkg, select={"CNT01"})
    assert len(diags) == 1 and "made_up_name" in diags[0].message


def test_dead_counter_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": STATS_FIXTURE,
        "m.py": "def f(c):\n    c.bump('queries_executed')\n",
    })
    diags = run_lint(pkg, select={"CNT02"})
    assert len(diags) == 1 and "errors_seen" in diags[0].message


def test_declared_and_used_counters_quiet(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": STATS_FIXTURE,
        "m.py": ("def f(c):\n    c.bump('queries_executed')\n"
                 "    c.bump_max('errors_seen', 2)\n"),
    })
    assert run_lint(pkg, select={"CNT01", "CNT02"}) == []


# --------------------------------------------------------------- CNT03

WAITS_FIXTURE = """
    class StatCounters:
        COUNTERS = ["wait_lock_ms", "wait_remote_rpc_ms"]

    WAIT_COUNTERS = {
        "lock": "wait_lock_ms",
        "remote_rpc": "wait_remote_rpc_ms",
    }
"""


def test_unregistered_wait_event_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": WAITS_FIXTURE,
        "m.py": ("from stats import begin_wait\n"
                 "def f():\n"
                 "    begin_wait('lock')\n"
                 "    begin_wait('remote_rpc')\n"
                 "    begin_wait('made_up_stall')\n"),
    })
    diags = run_lint(pkg, select={"CNT03"})
    assert len(diags) == 1 and "made_up_stall" in diags[0].message


def test_unentered_wait_event_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": WAITS_FIXTURE,
        "m.py": ("def f(stats):\n"
                 "    stats.begin_wait('lock')\n"),
    })
    diags = run_lint(pkg, select={"CNT03"})
    assert len(diags) == 1 and "remote_rpc" in diags[0].message


def test_registered_and_entered_wait_events_quiet(tmp_path):
    pkg = make_pkg(tmp_path, {
        "stats.py": WAITS_FIXTURE,
        "m.py": ("from stats import begin_wait\n"
                 "def f(stats):\n"
                 "    begin_wait('lock')\n"
                 "    stats.begin_wait('remote_rpc')\n"),
    })
    assert run_lint(pkg, select={"CNT03"}) == []


# --------------------------------------------------------------- CNT04

RECORDER_FIXTURE = """
    HEALTH_EVENT_KINDS = {
        "p99_regression": "p99 above baseline",
        "dead_node": "endpoint unreachable",
    }
"""

# both kinds surfaced: export uses the health_<kind> gauge spelling,
# utility uses the bare kind in its severity row table
EXPORT_FIXTURE = ('def g(d, active):\n'
                  '    d["health_p99_regression"] = active\n'
                  '    d["health_dead_node"] = active\n')
UTILITY_FIXTURE = ('SEV = {"p99_regression": "warning",\n'
                   '       "dead_node": "critical"}\n')

CNT04_BASE = {
    "observability/__init__.py": "",
    "commands/__init__.py": "",
    "observability/flight_recorder.py": RECORDER_FIXTURE,
}


def test_health_kind_missing_gauge_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        **CNT04_BASE,
        "observability/export.py":
            'def g(d, active):\n    d["health_p99_regression"] = active\n',
        "commands/utility.py": UTILITY_FIXTURE,
    })
    diags = run_lint(pkg, select={"CNT04"})
    assert len(diags) == 1
    assert "dead_node" in diags[0].message
    assert "Prometheus" in diags[0].message


def test_health_kind_missing_row_type_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        **CNT04_BASE,
        "observability/export.py": EXPORT_FIXTURE,
        "commands/utility.py": 'SEV = {"p99_regression": "warning"}\n',
    })
    diags = run_lint(pkg, select={"CNT04"})
    assert len(diags) == 1
    assert "dead_node" in diags[0].message
    assert "citus_health_events" in diags[0].message


def test_undeclared_emit_kind_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        **CNT04_BASE,
        "observability/export.py": EXPORT_FIXTURE,
        "commands/utility.py": UTILITY_FIXTURE,
        "m.py": ("def f(rec):\n"
                 "    rec.emit_event('p99_regression', 'x', 1, 0, 'd')\n"
                 "    rec.emit_event('made_up_alarm', 'x', 1, 0, 'd')\n"),
    })
    diags = run_lint(pkg, select={"CNT04"})
    assert len(diags) == 1 and "made_up_alarm" in diags[0].message


def test_health_kinds_fully_surfaced_quiet(tmp_path):
    pkg = make_pkg(tmp_path, {
        **CNT04_BASE,
        "observability/export.py": EXPORT_FIXTURE,
        "commands/utility.py": UTILITY_FIXTURE,
        "m.py": ("def f(rec):\n"
                 "    rec.emit_event('dead_node', 'h:1', 1, 0, 'down')\n"),
    })
    assert run_lint(pkg, select={"CNT04"}) == []


# ------------------------------------------------------------- GUC01

CONFIG_FIXTURE = """
    from dataclasses import dataclass, field

    @dataclass
    class PlannerSettings:
        shard_cap: int = 8

    @dataclass
    class Settings:
        planner: PlannerSettings = field(default_factory=PlannerSettings)
        verbose: bool = False

        def replace(self, **kw):
            return self
"""

GUCS_FIXTURE = """
    _GUCS = {
        "citus.shard_cap": ("planner", "shard_cap", int),
        "citus.verbose": (None, "verbose", "bool"),
    }
"""


def test_settings_typo_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "config.py": CONFIG_FIXTURE,
        "commands/__init__.py": "",
        "commands/config_cmds.py": GUCS_FIXTURE,
        "m.py": "def f(settings):\n    return settings.planner.shardcap\n",
    })
    diags = run_lint(pkg, select={"GUC01"})
    assert len(diags) == 1 and "shardcap" in diags[0].message


def test_settings_uncovered_field_fires(tmp_path):
    pkg = make_pkg(tmp_path, {
        "config.py": CONFIG_FIXTURE,
        "commands/__init__.py": "",
        "commands/config_cmds.py": "_GUCS = {}\n",
        "m.py": "def f(settings):\n    return settings.planner.shard_cap\n",
    })
    diags = run_lint(pkg, select={"GUC01"})
    assert len(diags) == 1 and "SET/SHOW" in diags[0].message


def test_settings_covered_reads_quiet(tmp_path):
    pkg = make_pkg(tmp_path, {
        "config.py": CONFIG_FIXTURE,
        "commands/__init__.py": "",
        "commands/config_cmds.py": GUCS_FIXTURE,
        "m.py": ("def f(settings):\n"
                 "    return settings.planner.shard_cap, settings.verbose\n"),
    })
    assert run_lint(pkg, select={"GUC01"}) == []


# -------------------------------------------------------- suppressions

def test_justified_suppression_honored(tmp_path):
    src = """
        def f():
            try:
                risky()
            # lint: disable=SWL01 -- probe only; failure falls back
            except Exception:
                pass
    """
    assert run_lint(make_pkg(tmp_path, {"m.py": src})) == []


def test_trailing_suppression_honored(tmp_path):
    src = """
        import time

        def f():
            return time.time()  # lint: disable=CONF01 -- wall-clock display only
    """
    assert run_lint(make_pkg(tmp_path, {"m.py": src}),
                    select={"CONF01"}) == []


def test_unjustified_suppression_rejected(tmp_path):
    src = """
        def f():
            try:
                risky()
            # lint: disable=SWL01
            except Exception:
                pass
    """
    diags = run_lint(make_pkg(tmp_path, {"m.py": src}))
    got = set(ids(diags))
    # the swallow STILL fires (no justification => no suppression) and
    # the bare pragma is its own finding
    assert got == {"SWL01", "SUP01"}


def test_unknown_rule_id_in_pragma_rejected(tmp_path):
    src = """
        X = 1  # lint: disable=NOPE99 -- misremembered id
    """
    diags = run_lint(make_pkg(tmp_path, {"m.py": src}))
    assert ids(diags) == ["SUP02"]
    assert "NOPE99" in diags[0].message


def test_suppression_only_covers_named_rule(tmp_path):
    src = """
        import time

        def f():
            try:
                return time.time()
            # lint: disable=CONF01 -- wrong id for the swallow below
            except Exception:
                pass
    """
    diags = run_lint(make_pkg(tmp_path, {"m.py": src}),
                     select={"SWL01", "CONF01"})
    assert "SWL01" in ids(diags)


# ------------------------------------------------------------ engine

def test_parse_error_is_a_diagnostic(tmp_path):
    diags = run_lint(make_pkg(tmp_path, {"broken.py": "def f(:\n"}))
    assert ids(diags) == ["PARSE"]


def test_missing_package_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        run_lint(str(tmp_path / "no_such_pkg"))


def test_diagnostics_sorted_and_unique(tmp_path):
    src = """
        def f():
            try:
                risky()
            except Exception:
                pass

        def g():
            try:
                risky()
            except Exception:
                pass
    """
    diags = run_lint(make_pkg(tmp_path, {"b.py": src, "a.py": src}),
                     select={"SWL01"})
    assert len(diags) == 4
    assert diags == sorted(diags)


def test_cli_main_exit_codes(tmp_path, capsys):
    from tools.cituslint.__main__ import main
    pkg = make_pkg(tmp_path, {"m.py": "def f():\n    try:\n        x()\n"
                                      "    except Exception:\n        pass\n"})
    assert main([pkg]) == 1
    out = capsys.readouterr().out
    assert "SWL01" in out
    clean = make_pkg(tmp_path / "c", {"m.py": "X = 1\n"})
    assert main([clean]) == 0
    assert main(["--list-rules"]) == 0
