"""Random query generation vs the sqlite oracle.

Reference: src/test/regress/citus_tests/query_generator/ — random
queries executed both distributed and locally, results diffed.  Here the
generator emits queries from the supported grammar over a fixed schema
and every result is compared (order-insensitively unless ORDER BY fully
determines it) against sqlite3.
"""

import decimal
import random
import sqlite3

import numpy as np
import pytest

import citus_tpu as ct

COLS = ["k", "a", "b", "f", "s"]
N = 3000


@pytest.fixture(scope="module")
def db(tmp_path_factory):
    cl = ct.Cluster(str(tmp_path_factory.mktemp("fuzz")))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, a bigint, b decimal(10,2), f double, s text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rng = np.random.default_rng(123)
    rows = []
    for i in range(N):
        rows.append((
            i,
            int(rng.integers(-50, 50)) if rng.random() > 0.08 else None,
            round(float(rng.integers(0, 20000)) / 100, 2) if rng.random() > 0.08 else None,
            round(float(rng.random() * 1000), 6),
            random.Random(i).choice(["red", "green", "blue", "teal", None]),
        ))
    cl.copy_from("t", rows=rows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, a INTEGER, b REAL, f REAL, s TEXT)")
    sq.executemany("INSERT INTO t VALUES (?,?,?,?,?)", rows)
    return cl, sq


class Gen:
    NUMERIC = ["k", "a", "b", "f"]

    def __init__(self, seed):
        self.r = random.Random(seed)

    def scalar(self, depth=0):
        r = self.r
        choice = r.random()
        if choice < 0.45 or depth >= 2:
            c = r.choice(self.NUMERIC)
            return c
        if choice < 0.6:
            return str(r.randint(-40, 60))
        op = r.choice(["+", "-", "*"])
        return f"({self.scalar(depth + 1)} {op} {self.scalar(depth + 1)})"

    def predicate(self, depth=0):
        r = self.r
        c = r.random()
        if c < 0.5 or depth >= 2:
            lhs = self.scalar(1)
            op = r.choice(["=", "<>", "<", "<=", ">", ">="])
            return f"{lhs} {op} {r.randint(-40, 60)}"
        if c < 0.6:
            return f"s = '{r.choice(['red', 'green', 'blue', 'nope'])}'"
        if c < 0.68:
            return f"a IS {'NOT ' if r.random() < 0.5 else ''}NULL"
        if c < 0.76:
            return f"a IN ({', '.join(str(r.randint(-50, 50)) for _ in range(3))})"
        if c < 0.84:
            return f"a BETWEEN {r.randint(-50, 0)} AND {r.randint(1, 50)}"
        glue = r.choice(["AND", "OR"])
        return f"({self.predicate(depth + 1)} {glue} {self.predicate(depth + 1)})"

    def aggregate(self):
        r = self.r
        fn = r.choice(["count", "sum", "min", "max", "avg"])
        if fn == "count" and r.random() < 0.4:
            return "count(*)"
        return f"{fn}({r.choice(self.NUMERIC)})"

    def query(self):
        r = self.r
        kind = r.random()
        where = f" WHERE {self.predicate()}" if r.random() < 0.7 else ""
        if kind < 0.35:  # global aggregates
            aggs = ", ".join(self.aggregate() for _ in range(r.randint(1, 3)))
            return f"SELECT {aggs} FROM t{where}"
        if kind < 0.65:  # group by
            key = r.choice(["a", "s", "a, s"])
            aggs = ", ".join(self.aggregate() for _ in range(r.randint(1, 2)))
            having = f" HAVING count(*) > {r.randint(0, 3)}" if r.random() < 0.3 else ""
            return (f"SELECT {key}, {aggs} FROM t{where} GROUP BY {key}{having}")
        if kind < 0.75:  # set operations over single columns
            col = r.choice(["a", "s"])
            op = r.choice(["UNION", "UNION ALL", "INTERSECT", "EXCEPT"])
            p1 = self.predicate()
            p2 = self.predicate()
            return (f"SELECT {col} FROM t WHERE {p1} {op} "
                    f"SELECT {col} FROM t WHERE {p2}")
        if kind < 0.82:  # EXISTS / NOT EXISTS (uncorrelated)
            neg = "NOT " if r.random() < 0.5 else ""
            return (f"SELECT count(*) FROM t{where or ' WHERE k >= 0'} "
                    f"AND {neg}EXISTS (SELECT 1 FROM t WHERE {self.predicate()})")
        if kind < 0.9:  # derived table with aggregation
            key = r.choice(["a", "s"])
            return (f"SELECT count(*) FROM (SELECT {key}, count(*) AS n "
                    f"FROM t{where} GROUP BY {key}) z WHERE n > {r.randint(0, 5)}")
        # projection
        cols = ", ".join(r.sample(COLS, r.randint(1, 3)))
        return f"SELECT {cols} FROM t{where} AND k < 200" if where \
            else f"SELECT {cols} FROM t WHERE k < 200"


def canon(rows):
    out = []
    for row in rows:
        vals = []
        for v in row:
            if isinstance(v, decimal.Decimal):
                vals.append(round(float(v), 3))
            elif isinstance(v, float):
                vals.append(round(v, 3))
            else:
                vals.append(v)
        out.append(tuple(vals))
    return sorted(out, key=repr)


@pytest.mark.parametrize("seed", range(60))
def test_fuzz_query(db, seed):
    cl, sq = db
    sql = Gen(seed).query()
    ours = canon(cl.execute(sql).rows)
    theirs = canon(sq.execute(sql).fetchall())
    assert len(ours) == len(theirs), sql
    for a, b in zip(ours, theirs):
        assert a == pytest.approx(b, rel=1e-6, abs=2e-3), (sql, a, b)


@pytest.fixture(scope="module")
def jdb(tmp_path_factory):
    cl = ct.Cluster(str(tmp_path_factory.mktemp("jfuzz")))
    cl.execute("CREATE TABLE big (k bigint NOT NULL, g bigint, v decimal(10,2))")
    cl.execute("SELECT create_distributed_table('big', 'k', 4)")
    cl.execute("CREATE TABLE small (g bigint, label text)")
    rng = np.random.default_rng(77)
    big = [(i, int(rng.integers(0, 30)),
            round(float(rng.integers(0, 5000)) / 100, 2)) for i in range(2000)]
    small = [(i, f"lab{i % 7}") for i in range(25)]
    cl.copy_from("big", rows=big)
    cl.copy_from("small", rows=small)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE big (k INTEGER, g INTEGER, v REAL)")
    sq.execute("CREATE TABLE small (g INTEGER, label TEXT)")
    sq.executemany("INSERT INTO big VALUES (?,?,?)", big)
    sq.executemany("INSERT INTO small VALUES (?,?)", small)
    return cl, sq


class JoinGen:
    def __init__(self, seed):
        self.r = random.Random(seed)

    def query(self):
        r = self.r
        kind = r.choice(["inner", "left", "inner", "inner"])
        join = "JOIN" if kind == "inner" else "LEFT JOIN"
        where = ""
        if r.random() < 0.6:
            where = f" WHERE b.v {r.choice(['<', '>', '<='])} {r.randint(0, 50)}"
            if r.random() < 0.4:
                where += f" AND s.g {r.choice(['<', '>='])} {r.randint(0, 30)}"
        shape = r.random()
        if shape < 0.5:
            agg = r.choice(["count(*)", "sum(b.v)", "min(b.v)", "count(s.label)"])
            return (f"SELECT s.label, {agg} FROM big b {join} small s "
                    f"ON b.g = s.g{where} GROUP BY s.label")
        if shape < 0.75:
            return (f"SELECT count(*), sum(b.v) FROM big b {join} small s "
                    f"ON b.g = s.g{where}")
        return (f"SELECT b.k, s.label FROM big b {join} small s "
                f"ON b.g = s.g{where} AND b.k < 50")


@pytest.mark.parametrize("seed", range(25))
def test_fuzz_join_query(jdb, seed):
    cl, sq = jdb
    sql = JoinGen(seed).query()
    ours = canon(cl.execute(sql).rows)
    theirs = canon(sq.execute(sql).fetchall())
    assert len(ours) == len(theirs), sql
    for a, b in zip(ours, theirs):
        assert a == pytest.approx(b, rel=1e-6, abs=2e-3), (sql, a, b)
