"""Pallas scan->filter->partial-agg kernel vs the fused-XLA worker.

Runs in interpreter mode on the CPU mesh (same program as on a chip,
no Mosaic); results must be BIT-IDENTICAL to the default path, which is
itself bit-identical to the numpy oracle."""

import dataclasses

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import ExecutorSettings

QUERIES = [
    "SELECT count(*) FROM t",
    "SELECT sum(v), min(v), max(v), count(v) FROM t",
    "SELECT sum(q * (1 - dd)) FROM t WHERE d <= 9500",
    "SELECT rf, count(*), sum(q) FROM t GROUP BY rf ORDER BY rf",
    "SELECT avg(v) FROM t WHERE v > 0",
]


@pytest.fixture(scope="module")
def db(tmp_path_factory):
    cl = ct.Cluster(str(tmp_path_factory.mktemp("pls")))
    cl.execute("""CREATE TABLE t (k bigint NOT NULL, v bigint,
        q decimal(12,2), dd decimal(12,2), rf text, d date)""")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    rng = np.random.default_rng(3)
    n = 60_000
    cl.copy_from("t", columns={
        "k": rng.integers(0, n, n),
        "v": rng.integers(-1000, 1000, n),
        "q": rng.integers(100, 5100, n) / 100.0,
        "dd": rng.integers(0, 11, n) / 100.0,
        "rf": np.array(["A", "N", "R"])[rng.integers(0, 3, n)].tolist(),
        "d": (rng.integers(0, 2500, n) + 8036).astype(np.int32)})
    return cl


def run_with(cl, sql, **exec_kw):
    old = cl.settings
    cl.settings = dataclasses.replace(
        old, executor=dataclasses.replace(old.executor, **exec_kw))
    try:
        cl._plan_cache.clear()
        from citus_tpu.executor.device_cache import GLOBAL_CACHE
        GLOBAL_CACHE.clear()
        return cl.execute(sql).rows
    finally:
        cl.settings = old


@pytest.mark.parametrize("sql", QUERIES)
def test_pallas_matches_default_and_oracle(db, sql):
    default = run_with(db, sql)
    pallas = run_with(db, sql, use_pallas_scan=True)
    oracle = run_with(db, sql, task_executor_backend="cpu")
    assert pallas == default == oracle


def test_pallas_multi_block_accumulation(db):
    """Force several grid steps per batch so cross-step accumulation
    (init-then-merge) is exercised."""
    import citus_tpu.ops.pallas_scan as ps
    old = ps.BLOCK_ROWS
    ps.BLOCK_ROWS = 4096
    try:
        sql = "SELECT sum(v), min(v), max(v), count(*) FROM t WHERE v != 17"
        assert run_with(db, sql, use_pallas_scan=True) == run_with(db, sql)
    finally:
        ps.BLOCK_ROWS = old


def test_pallas_with_parameters(db):
    sql = "SELECT count(*), sum(v) FROM t WHERE v > $1"
    old = db.settings
    db.settings = dataclasses.replace(
        old, executor=dataclasses.replace(old.executor, use_pallas_scan=True))
    try:
        db._plan_cache.clear()
        got = db.execute(sql, params=[250]).rows
    finally:
        db.settings = old
    db._plan_cache.clear()
    want = db.execute(sql, params=[250]).rows
    assert got == want


def test_unsupported_plans_fall_back(db):
    """hll/ddsk partials (VMEM-hostile one-hots) fall back to the fused
    path; results stay correct either way."""
    from citus_tpu.ops import pallas_scan as ps
    sql = "SELECT approx_count_distinct(v) FROM t"
    assert run_with(db, sql, use_pallas_scan=True) == run_with(db, sql)
    from citus_tpu.planner.bind import bind_select
    from citus_tpu.planner.parser import parse_statement
    from citus_tpu.planner.physical import plan_select
    bound = bind_select(db.catalog, parse_statement(sql))
    plan = plan_select(db.catalog, bound)
    assert not ps.supports_plan(plan)


def test_direct_group_block_shrinks_to_vmem_budget():
    """A wide direct-group domain shrinks the row block to keep the
    one-hot intermediate inside the VMEM budget."""
    from citus_tpu.ops import pallas_scan as ps

    def plan_with_groups(g):
        class _GM:
            kind = "direct"
            n_groups = g

        class _Plan:
            group_mode = _GM()
        return _Plan()

    block = ps._block_rows_for(plan_with_groups(256), ps.BLOCK_ROWS)
    assert block * 256 * 8 <= ps._DIRECT_VMEM_BUDGET
    assert block >= ps._MIN_BLOCK
    # a domain too wide for even the minimum block is unsupported
    assert ps._block_rows_for(plan_with_groups(4096),
                              ps.BLOCK_ROWS) < ps._MIN_BLOCK
