"""Regression tests for the round-1 advisor findings: recovery must not
sweep live transactions, durability ordering of dictionaries vs commit
records, DML serialization through the lock manager, cross-process
dictionary growth, and cleanup policy handling."""

import os
import threading

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.catalog.catalog import Catalog
from citus_tpu.ingest import TableIngestor, encode_columns
from citus_tpu.transaction.manager import TransactionLog, TxState
from citus_tpu.transaction.recovery import recover_transactions


def make_cluster(tmp_path, name="db"):
    cl = ct.Cluster(str(tmp_path / name), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    return cl


def test_recover_spares_inflight_transaction(tmp_path):
    """A concurrent recovery pass (the maintenance daemon's duty) must
    not sweep staged files of a transaction still being written."""
    cl = make_cluster(tmp_path)
    t = cl.catalog.table("t")
    values, validity = encode_columns(cl.catalog, t, {
        "k": np.arange(500, dtype=np.int64), "v": np.ones(500, dtype=np.int64)})
    ing = TableIngestor(cl.catalog, t, txlog=cl.txlog)
    ing.append(values, validity)
    for w in ing._writers.values():
        w.flush()
    # staged, not yet prepared — exactly the window the advisor flagged
    st = recover_transactions(cl.catalog, cl.txlog)
    assert st["swept"] == 0
    ing.finish()  # must still commit successfully
    assert cl.execute("SELECT count(*) FROM t").rows == [(500,)]


def test_recover_spares_foreign_live_transaction(tmp_path):
    """Transactions owned by another live coordinator (same data dir)
    are not recovered out from under it."""
    cl = make_cluster(tmp_path)
    other = TransactionLog(cl.catalog.data_dir)  # a second "process"
    xid = other.begin()
    other.log(xid, TxState.PREPARED, {"kind": "ingest", "table": "t",
                                      "placements": []})
    st = recover_transactions(cl.catalog, cl.txlog)
    assert st["rolled_back"] == 0 and st["rolled_forward"] == 0
    # once the owner releases its marker (crash/exit), recovery applies
    other.close()
    st = recover_transactions(cl.catalog, cl.txlog)
    assert st["rolled_back"] == 1


def test_xid_blocks_never_collide(tmp_path):
    d = str(tmp_path / "x")
    os.makedirs(d)
    a = TransactionLog(d)
    b = TransactionLog(d)
    xa = {a.begin() for _ in range(50)}
    xb = {b.begin() for _ in range(50)}
    assert not (xa & xb)
    a.close(), b.close()


def test_truncate_done_keeps_concurrent_record(tmp_path):
    d = str(tmp_path / "x")
    os.makedirs(d)
    log = TransactionLog(d)
    xid = log.begin()
    log.log(xid, TxState.PREPARED, {})
    stop = threading.Event()

    def churn():
        while not stop.is_set():
            log.truncate_done()

    th = threading.Thread(target=churn)
    th.start()
    try:
        xids = []
        for _ in range(50):
            x = log.begin()
            log.log(x, TxState.PREPARED, {"n": x})
            xids.append(x)
    finally:
        stop.set()
        th.join()
    recorded = {x for x, s, _ in log.outstanding()}
    assert set(xids) <= recorded  # no record lost to a concurrent rewrite
    log.close()


def test_concurrent_updates_serialize(tmp_path):
    """Two overlapping UPDATEs must not duplicate doubly-matched rows
    (advisor: LockManager had zero callers)."""
    cl = make_cluster(tmp_path)
    cl.copy_from("t", columns={"k": np.arange(200, dtype=np.int64),
                               "v": np.zeros(200, dtype=np.int64)})
    errs = []

    def bump():
        try:
            cl.execute("UPDATE t SET v = v + 1 WHERE k < 200")
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=bump) for _ in range(4)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    assert not errs
    # serialized updates: every row updated exactly 4 times, row count flat
    assert cl.execute("SELECT count(*) FROM t").rows == [(200,)]
    assert cl.execute("SELECT min(v), max(v) FROM t").rows == [(4, 4)]


def test_dictionary_growth_merges_across_catalogs(tmp_path):
    """Two coordinators growing one text dictionary must never assign
    the same id to different words."""
    d = str(tmp_path / "db")
    cl = ct.Cluster(d, n_nodes=2)
    cl.execute("CREATE TABLE s (k bigint NOT NULL, name text)")
    cl.execute("SELECT create_distributed_table('s', 'k', 4)")
    cat2 = Catalog(d)  # second coordinator's catalog view
    ids1 = cl.catalog.encode_strings("s", "name", ["alpha", "beta"])
    ids2 = cat2.encode_strings("s", "name", ["gamma", "beta", "delta"])
    # beta resolves to the same id in both processes
    assert ids1[1] == ids2[1]
    # and no two distinct words share an id
    w1 = cl.catalog.encode_strings("s", "name", ["alpha", "beta", "gamma", "delta"])
    w2 = cat2.encode_strings("s", "name", ["alpha", "beta", "gamma", "delta"])
    assert w1.tolist() == w2.tolist()
    assert len(set(w1.tolist())) == 4


def test_cleanup_on_failure_policy(tmp_path):
    from citus_tpu.operations.cleaner import (
        ON_FAILURE, complete_operation, pending_cleanup, record_cleanup,
        try_drop_orphaned_resources,
    )
    cl = make_cluster(tmp_path)
    target = tmp_path / "victim"
    target.mkdir()
    record_cleanup(cl.catalog, str(target), ON_FAILURE, operation_id=7)
    # operation still running: nothing dropped
    assert try_drop_orphaned_resources(cl.catalog) == 0
    assert target.exists()
    # operation succeeded: record discarded, resource kept
    complete_operation(cl.catalog, 7, success=True)
    assert try_drop_orphaned_resources(cl.catalog) == 0
    assert target.exists()
    # a failed operation's entries are dropped
    record_cleanup(cl.catalog, str(target), ON_FAILURE, operation_id=8)
    complete_operation(cl.catalog, 8, success=False)
    assert try_drop_orphaned_resources(cl.catalog) == 1
    assert not target.exists()


def test_concurrent_catalog_commits_merge(tmp_path):
    """Commit does read-merge-store: concurrent coordinators' objects
    survive each other's commits; tombstoned drops stay dropped."""
    from citus_tpu.catalog.catalog import Catalog
    d = str(tmp_path / "merge")
    a = ct.Cluster(d, n_nodes=2)
    b = ct.Cluster(d, n_nodes=2)
    a.execute("CREATE TABLE ta (x bigint)")
    b.catalog.create_table("tb", a.catalog.table("ta").schema)
    b.catalog.commit()  # b commits from a stale snapshot
    a.execute("CREATE TABLE tc (y bigint)")  # a too
    fresh = Catalog(d)
    assert set(fresh.tables) >= {"ta", "tb", "tc"}
    a.execute("DROP TABLE tc")
    assert "tc" not in Catalog(d).tables  # tombstone survives merge
    a.close(), b.close()


def test_sequence_blocks_disjoint_across_coordinators(tmp_path):
    d = str(tmp_path / "seqx")
    a = ct.Cluster(d, n_nodes=2)
    b = ct.Cluster(d, n_nodes=2)
    a.execute("CREATE SEQUENCE sq START 1")
    va = [a.execute("SELECT nextval('sq')").rows[0][0] for _ in range(40)]
    vb = [b.execute("SELECT nextval('sq')").rows[0][0] for _ in range(40)]
    assert not (set(va) & set(vb))
    a.close(), b.close()


def test_privileges_cover_expression_subqueries(tmp_path):
    """A role without SELECT on t2 cannot read it through a subquery in
    WHERE, the select list, EXISTS, or DML predicates."""
    from citus_tpu.errors import CatalogError
    cl = ct.Cluster(str(tmp_path / "privsub"))
    cl.execute("CREATE TABLE t1 (x bigint)")
    cl.execute("CREATE TABLE t2 (secret bigint)")
    cl.execute("INSERT INTO t1 VALUES (1)")
    cl.execute("INSERT INTO t2 VALUES (42)")
    cl.execute("CREATE ROLE r")
    cl.execute("GRANT SELECT ON t1 TO r")
    cl.execute("GRANT DELETE ON t1 TO r")
    import pytest as _pt
    for sql in [
        "SELECT * FROM t1 WHERE x IN (SELECT secret FROM t2)",
        "SELECT (SELECT max(secret) FROM t2) FROM t1",
        "SELECT * FROM t1 WHERE EXISTS (SELECT 1 FROM t2)",
    ]:
        with _pt.raises(CatalogError):
            cl.execute(sql, role="r")
    with _pt.raises(CatalogError):
        cl.execute("DELETE FROM t1 WHERE x = (SELECT max(secret) FROM t2)",
                   role="r")
    cl.execute("GRANT SELECT ON t2 TO r")
    assert cl.execute("SELECT (SELECT max(secret) FROM t2) FROM t1",
                      role="r").rows == [(42,)]
    cl.close()


# ------------------------------------------------ round-2 advisor fixes


def test_drop_column_drops_own_fk(tmp_path):
    """ALTER TABLE DROP COLUMN removes the table's own FK constraints
    that include the column (PostgreSQL drops dependent constraints),
    so parent DELETEs keep working afterwards."""
    cl = ct.Cluster(str(tmp_path / "dfk"))
    cl.execute("CREATE TABLE parent (pid bigint NOT NULL, v bigint)")
    cl.execute("CREATE TABLE child (cid bigint NOT NULL, "
               "pid bigint REFERENCES parent (pid))")
    cl.execute("SELECT create_reference_table('parent')")
    cl.execute("SELECT create_distributed_table('child','cid',4)")
    cl.execute("INSERT INTO parent VALUES (1, 10)")
    cl.execute("ALTER TABLE child DROP COLUMN pid")
    assert cl.catalog.table("child").foreign_keys == []
    # the parent DELETE no longer probes a dropped column
    cl.execute("DELETE FROM parent WHERE pid = 1")


def test_drop_referenced_column_refused(tmp_path):
    """Dropping a parent column a child FK references is refused (the
    CASCADE that PostgreSQL would require is unsupported)."""
    from citus_tpu.errors import AnalysisError
    cl = ct.Cluster(str(tmp_path / "dref"))
    cl.execute("CREATE TABLE parent (pid bigint NOT NULL, v bigint)")
    cl.execute("CREATE TABLE child (cid bigint NOT NULL, "
               "pid bigint REFERENCES parent (pid))")
    cl.execute("SELECT create_distributed_table('parent','pid',4)")
    cl.execute("SELECT create_distributed_table('child','pid',4)")
    with pytest.raises(AnalysisError):
        cl.execute("ALTER TABLE parent DROP COLUMN pid")
    # constraint still intact and enforced
    from citus_tpu.integrity import ForeignKeyViolation
    with pytest.raises(ForeignKeyViolation):
        cl.execute("INSERT INTO child VALUES (1, 99)")


def test_value_preserving_parent_update_allowed(tmp_path):
    """UPDATE parent SET pk = <same value> succeeds even with matching
    child rows (PostgreSQL NO ACTION re-checks the post-image)."""
    cl = ct.Cluster(str(tmp_path / "vpu"))
    cl.execute("CREATE TABLE parent (pid bigint NOT NULL, v bigint)")
    cl.execute("CREATE TABLE child (cid bigint NOT NULL, "
               "pid bigint REFERENCES parent (pid))")
    cl.execute("SELECT create_distributed_table('parent','pid',4)")
    cl.execute("SELECT create_distributed_table('child','pid',4)")
    cl.execute("INSERT INTO parent VALUES (1, 10)")
    cl.execute("INSERT INTO child VALUES (100, 1)")
    cl.execute("UPDATE parent SET pid = 1 WHERE pid = 1")  # no-op rewrite
    # a genuinely key-changing update still raises
    from citus_tpu.integrity import ForeignKeyViolation
    with pytest.raises(ForeignKeyViolation):
        cl.execute("UPDATE parent SET pid = 2 WHERE pid = 1")


def test_nullif_not_strict(tmp_path):
    cl = ct.Cluster(str(tmp_path / "nifd"))
    assert cl.execute("SELECT nullif(5, NULL)").rows[0][0] == 5
    assert cl.execute("SELECT nullif(5, 5)").rows[0][0] is None
    assert cl.execute("SELECT nullif(5, 4)").rows[0][0] == 5
    assert cl.execute("SELECT nullif(NULL, 5)").rows[0][0] is None


def test_generate_series_numeric_and_integer(tmp_path):
    """PostgreSQL supports numeric generate_series(1.1, 4.0, 1.3);
    round 4 implements it instead of rejecting (round-3 ADVICE)."""
    from decimal import Decimal
    from citus_tpu.errors import AnalysisError
    cl = ct.Cluster(str(tmp_path / "gsr"))
    with pytest.raises(AnalysisError):
        cl.execute("SELECT * FROM generate_series('a', 'b')")
    assert [r[0] for r in
            cl.execute("SELECT * FROM generate_series(1, 3)").rows] == [1, 2, 3]
    assert [r[0] for r in cl.execute(
        "SELECT * FROM generate_series(1.1, 4.0, 1.3)").rows] == \
        [Decimal("1.1"), Decimal("2.4"), Decimal("3.7")]
    assert [r[0] for r in cl.execute(
        "SELECT * FROM generate_series(1.5, 3)").rows] == \
        [Decimal("1.5"), Decimal("2.5")]
    # any numeric argument makes the whole series numeric (PG typing)
    assert [r[0] for r in cl.execute(
        "SELECT * FROM generate_series(2.0, 4.0)").rows] == \
        [Decimal("2.0"), Decimal("3.0"), Decimal("4.0")]
    with pytest.raises(AnalysisError):
        cl.execute("SELECT * FROM generate_series(true, false)")


def test_default_session_is_thread_local(tmp_path):
    """Round-3 ADVICE (medium): BEGIN on the session-less API must not
    pull other threads' statements into its transaction block."""
    import threading
    cl = ct.Cluster(str(tmp_path / "tls"))
    cl.execute("CREATE TABLE t (k bigint)")
    cl.execute("BEGIN")
    cl.execute("INSERT INTO t VALUES (1)")  # staged in THIS thread's txn

    results = {}

    def other_thread():
        # autocommit: must not join (or see) the open transaction
        cl.execute("INSERT INTO t VALUES (2)")
        results["count"] = cl.execute("SELECT count(*) FROM t").rows[0][0]

    th = threading.Thread(target=other_thread)
    th.start()
    th.join()
    assert results["count"] == 1  # sees only its own committed row
    cl.execute("ROLLBACK")
    # the staged row is gone; the other thread's autocommit row persists
    assert cl.execute("SELECT count(*) FROM t").rows == [(1,)]


def test_float_round_half_to_even(tmp_path):
    """PostgreSQL round(double precision) ties to even: round(2.5)=2."""
    cl = ct.Cluster(str(tmp_path / "rte"))
    cl.execute("CREATE TABLE fr (k bigint NOT NULL, x double precision)")
    cl.execute("SELECT create_distributed_table('fr','k',2)")
    cl.execute("INSERT INTO fr VALUES (1, 2.5), (2, 3.5), (3, -2.5)")
    rows = dict(cl.execute(
        "SELECT k, round(x) FROM fr ORDER BY k").rows)
    assert rows[1] == 2.0 and rows[2] == 4.0 and rows[3] == -2.0
    # numeric literals keep half-away-from-zero (PostgreSQL numeric)
    assert cl.execute("SELECT round(2.5)").rows[0][0] == 3
