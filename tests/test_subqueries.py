"""Recursive planning: scalar subqueries + IN (SELECT ...) vs sqlite."""

import sqlite3

import pytest

import citus_tpu as ct
from citus_tpu.errors import AnalysisError


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, s text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.execute("CREATE TABLE u (x bigint, y bigint)")
    rows = [(i, i % 20, ["a", "b", "c"][i % 3]) for i in range(500)]
    urows = [(i, i * 3) for i in range(10)]
    cl.copy_from("t", rows=rows)
    cl.copy_from("u", rows=urows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, v INTEGER, s TEXT)")
    sq.execute("CREATE TABLE u (x INTEGER, y INTEGER)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)", rows)
    sq.executemany("INSERT INTO u VALUES (?,?)", urows)
    return cl, sq


def check(db, sql):
    cl, sq = db
    ours = sorted(cl.execute(sql).rows, key=repr)
    theirs = sorted(sq.execute(sql).fetchall(), key=repr)
    assert ours == theirs


QUERIES = [
    "SELECT count(*) FROM t WHERE v > (SELECT count(*) FROM u)",
    "SELECT count(*) FROM t WHERE k IN (SELECT x FROM u)",
    "SELECT count(*) FROM t WHERE k NOT IN (SELECT x FROM u WHERE y > 12)",
    "SELECT count(*) FROM t WHERE v = (SELECT min(y) FROM u WHERE x = 1)",
    "SELECT v, count(*) FROM t WHERE v >= (SELECT max(x) FROM u) GROUP BY v ORDER BY v",
    "SELECT count(*) FROM t WHERE k IN (SELECT x FROM u) AND v < 10",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_subqueries_vs_sqlite(db, sql):
    check(db, sql)


def test_scalar_subquery_empty_is_null(db):
    cl, sq = db
    sql = "SELECT count(*) FROM t WHERE v > (SELECT y FROM u WHERE x = 9999)"
    check(db, sql)  # NULL comparison -> no rows


def test_scalar_subquery_multirow_errors(db):
    cl, _ = db
    with pytest.raises(AnalysisError):
        cl.execute("SELECT count(*) FROM t WHERE v > (SELECT y FROM u)")


def test_delete_with_subquery(db):
    cl, sq = db
    cl.execute("DELETE FROM t WHERE k IN (SELECT x FROM u)")
    sq.execute("DELETE FROM t WHERE k IN (SELECT x FROM u)")
    check(db, "SELECT count(*) FROM t")


def test_parameterized_queries(db):
    cl, sq = db
    ours = cl.execute("SELECT count(*) FROM t WHERE v > $1 AND s = $2",
                      params=(10, "a")).rows
    theirs = sq.execute("SELECT count(*) FROM t WHERE v > ? AND s = ?",
                        (10, "a")).fetchall()
    assert ours == list(theirs)
    # router param
    assert cl.execute("SELECT count(*) FROM t WHERE k = $1", params=(7,)).rows == [(1,)]
    # param in DML
    cl.execute("DELETE FROM t WHERE v = $1", params=(3,))
    sq.execute("DELETE FROM t WHERE v = ?", (3,))
    check(db, "SELECT count(*) FROM t")
    with pytest.raises(AnalysisError):
        cl.execute("SELECT count(*) FROM t WHERE v > $2", params=(1,))
