"""Recursive planning: scalar subqueries + IN (SELECT ...) vs sqlite."""

import sqlite3

import pytest

import citus_tpu as ct
from citus_tpu.errors import AnalysisError


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, s text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.execute("CREATE TABLE u (x bigint, y bigint)")
    rows = [(i, i % 20, ["a", "b", "c"][i % 3]) for i in range(500)]
    urows = [(i, i * 3) for i in range(10)]
    cl.copy_from("t", rows=rows)
    cl.copy_from("u", rows=urows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, v INTEGER, s TEXT)")
    sq.execute("CREATE TABLE u (x INTEGER, y INTEGER)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)", rows)
    sq.executemany("INSERT INTO u VALUES (?,?)", urows)
    return cl, sq


def check(db, sql):
    cl, sq = db
    ours = sorted(cl.execute(sql).rows, key=repr)
    theirs = sorted(sq.execute(sql).fetchall(), key=repr)
    assert ours == theirs


QUERIES = [
    "SELECT count(*) FROM t WHERE v > (SELECT count(*) FROM u)",
    "SELECT count(*) FROM t WHERE k IN (SELECT x FROM u)",
    "SELECT count(*) FROM t WHERE k NOT IN (SELECT x FROM u WHERE y > 12)",
    "SELECT count(*) FROM t WHERE v = (SELECT min(y) FROM u WHERE x = 1)",
    "SELECT v, count(*) FROM t WHERE v >= (SELECT max(x) FROM u) GROUP BY v ORDER BY v",
    "SELECT count(*) FROM t WHERE k IN (SELECT x FROM u) AND v < 10",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_subqueries_vs_sqlite(db, sql):
    check(db, sql)


def test_scalar_subquery_empty_is_null(db):
    cl, sq = db
    sql = "SELECT count(*) FROM t WHERE v > (SELECT y FROM u WHERE x = 9999)"
    check(db, sql)  # NULL comparison -> no rows


def test_scalar_subquery_multirow_errors(db):
    cl, _ = db
    with pytest.raises(AnalysisError):
        cl.execute("SELECT count(*) FROM t WHERE v > (SELECT y FROM u)")


def test_delete_with_subquery(db):
    cl, sq = db
    cl.execute("DELETE FROM t WHERE k IN (SELECT x FROM u)")
    sq.execute("DELETE FROM t WHERE k IN (SELECT x FROM u)")
    check(db, "SELECT count(*) FROM t")


def test_parameterized_queries(db):
    cl, sq = db
    ours = cl.execute("SELECT count(*) FROM t WHERE v > $1 AND s = $2",
                      params=(10, "a")).rows
    theirs = sq.execute("SELECT count(*) FROM t WHERE v > ? AND s = ?",
                        (10, "a")).fetchall()
    assert ours == list(theirs)
    # router param
    assert cl.execute("SELECT count(*) FROM t WHERE k = $1", params=(7,)).rows == [(1,)]
    # param in DML
    cl.execute("DELETE FROM t WHERE v = $1", params=(3,))
    sq.execute("DELETE FROM t WHERE v = ?", (3,))
    check(db, "SELECT count(*) FROM t")
    with pytest.raises(AnalysisError):
        cl.execute("SELECT count(*) FROM t WHERE v > $2", params=(1,))


def test_min_max_over_text(tmp_path):
    """min()/max() over text: lexicographic rank partials stay
    device-combinable (int64 min/max), finalize maps back to words."""
    import citus_tpu as ct
    cl = ct.Cluster(str(tmp_path / "mmtext"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, s text)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", rows=[(1, 0, "banana"), (2, 0, "apple"), (3, 1, "cherry"),
                            (4, 1, "date"), (5, 0, None), (6, 2, None)])
    assert cl.execute("SELECT min(s), max(s) FROM t").rows == [("apple", "date")]
    assert cl.execute("SELECT g, min(s), max(s) FROM t GROUP BY g ORDER BY g").rows \
        == [(0, "apple", "banana"), (1, "cherry", "date"), (2, None, None)]
    assert cl.execute("SELECT min(upper(s)) FROM t").rows == [("APPLE",)]
    assert cl.execute("SELECT max(s) FROM t WHERE k > 100").rows == [(None,)]
    cl.close()


def test_correlated_exists_semi_join(tmp_path):
    """Equality-correlated [NOT] EXISTS decorrelates to semi/anti-join
    (NULL outer keys preserved under NOT EXISTS, unlike NOT IN)."""
    import sqlite3

    import citus_tpu as ct
    cl = ct.Cluster(str(tmp_path / "corr"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("CREATE TABLE u (k bigint NOT NULL, w bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.execute("SELECT create_distributed_table('u', 'k', 4)")
    trows = [(i, i % 7 if i % 11 else None) for i in range(200)]
    urows = [(i, i % 5) for i in range(80)]
    cl.copy_from("t", rows=trows)
    cl.copy_from("u", rows=urows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, v INTEGER)")
    sq.execute("CREATE TABLE u (k INTEGER, w INTEGER)")
    sq.executemany("INSERT INTO t VALUES (?,?)", trows)
    sq.executemany("INSERT INTO u VALUES (?,?)", urows)
    for sql in [
        "SELECT count(*) FROM t WHERE EXISTS (SELECT 1 FROM u WHERE u.k = t.k)",
        "SELECT count(*) FROM t WHERE EXISTS (SELECT 1 FROM u WHERE u.k = t.k AND u.w > 2)",
        "SELECT count(*) FROM t WHERE NOT EXISTS (SELECT 1 FROM u WHERE u.k = t.k)",
        "SELECT count(*) FROM t WHERE NOT EXISTS (SELECT 1 FROM u WHERE u.w = t.v)",
        "SELECT count(*) FROM t WHERE EXISTS (SELECT 1 FROM u WHERE t.v = u.w AND u.k < 40)",
        "SELECT v, count(*) FROM t WHERE EXISTS (SELECT 1 FROM u WHERE u.k = t.k) "
        "GROUP BY v ORDER BY v NULLS LAST",
    ]:
        ours = [tuple(r) for r in cl.execute(sql).rows]
        theirs = [tuple(r) for r in sq.execute(sql).fetchall()]
        assert ours == theirs, (sql, ours, theirs)
    cl.close()


def test_correlated_scalar_subqueries(tmp_path):
    """Equality-correlated scalar aggregate subqueries decorrelate to a
    LEFT JOIN on a grouped derived table (count coalesces to 0)."""
    import sqlite3

    import citus_tpu as ct
    cl = ct.Cluster(str(tmp_path / "cscal"))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, g bigint, v bigint)")
    cl.execute("CREATE TABLE u (k bigint NOT NULL, w bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.execute("SELECT create_distributed_table('u', 'k', 4)")
    trows = [(i, i % 6, (i * 3) % 50) for i in range(150)]
    urows = [(i % 40, i) for i in range(120)]
    cl.copy_from("t", rows=trows)
    cl.copy_from("u", rows=urows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, g INTEGER, v INTEGER)")
    sq.execute("CREATE TABLE u (k INTEGER, w INTEGER)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)", trows)
    sq.executemany("INSERT INTO u VALUES (?,?)", urows)
    for sql in [
        "SELECT t.k, (SELECT max(w) FROM u WHERE u.k = t.k) FROM t ORDER BY t.k LIMIT 50",
        "SELECT t.k, (SELECT count(*) FROM u WHERE u.k = t.k) FROM t ORDER BY t.k LIMIT 50",
        "SELECT count(*) FROM t WHERE t.v > (SELECT avg(w) FROM u WHERE u.k = t.g)",
        "SELECT t.k, (SELECT sum(w) FROM u WHERE u.k = t.k AND u.w > 30) FROM t "
        "ORDER BY t.k LIMIT 40",
    ]:
        ours = [tuple(r) for r in cl.execute(sql).rows]
        theirs = [tuple(r) for r in sq.execute(sql).fetchall()]
        assert ours == theirs, (sql, ours[:6], theirs[:6])
    cl.close()


# ---- round-2 gap #6: correlation beyond single equality ---------------

@pytest.fixture()
def cdb(tmp_path):
    cl = ct.Cluster(str(tmp_path / "cdb"), n_nodes=2)
    cl.execute("CREATE TABLE o (ok bigint NOT NULL, oa bigint, ob bigint, ov bigint)")
    cl.execute("CREATE TABLE i (ik bigint NOT NULL, ia bigint, ib bigint, iv bigint)")
    cl.execute("SELECT create_distributed_table('o', 'ok', 4)")
    cl.execute("SELECT create_distributed_table('i', 'ik', 4)")
    orows = [(n, n % 7, n % 5, n % 11) for n in range(300)]
    irows = [(n, n % 9, n % 5, n % 13) for n in range(120)]
    cl.copy_from("o", rows=orows)
    cl.copy_from("i", rows=irows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE o (ok INTEGER, oa INTEGER, ob INTEGER, ov INTEGER)")
    sq.execute("CREATE TABLE i (ik INTEGER, ia INTEGER, ib INTEGER, iv INTEGER)")
    sq.executemany("INSERT INTO o VALUES (?,?,?,?)", orows)
    sq.executemany("INSERT INTO i VALUES (?,?,?,?)", irows)
    return cl, sq


CORRELATED_QUERIES = [
    # multi-key EXISTS / NOT EXISTS
    "SELECT count(*) FROM o WHERE EXISTS (SELECT 1 FROM i WHERE "
    "i.ia = o.oa AND i.ib = o.ob)",
    "SELECT count(*) FROM o WHERE NOT EXISTS (SELECT 1 FROM i WHERE "
    "i.ia = o.oa AND i.ib = o.ob AND i.iv > 5)",
    # multi-key EXISTS with inner-only predicates + other conjuncts
    "SELECT oa, count(*) FROM o WHERE EXISTS (SELECT 1 FROM i WHERE "
    "i.ia = o.oa AND i.ib = o.ob AND i.iv < 9) AND o.ov > 2 "
    "GROUP BY oa ORDER BY oa",
    # correlated IN (single extra key -> 2-key EXISTS)
    "SELECT count(*) FROM o WHERE o.ov IN (SELECT i.iv FROM i WHERE "
    "i.ia = o.oa)",
    # correlated IN composed with other predicates
    "SELECT count(*) FROM o WHERE o.ov IN (SELECT i.iv FROM i WHERE "
    "i.ib = o.ob AND i.ik < 60) AND o.oa < 5",
    # multi-key correlated scalar aggregate
    "SELECT ok, (SELECT sum(i.iv) FROM i WHERE i.ia = o.oa AND "
    "i.ib = o.ob) FROM o ORDER BY ok LIMIT 40",
    "SELECT ok, (SELECT count(*) FROM i WHERE i.ia = o.oa AND "
    "i.ib = o.ob) FROM o ORDER BY ok LIMIT 40",
]


@pytest.mark.parametrize("sql", CORRELATED_QUERIES)
def test_correlated_vs_sqlite(cdb, sql):
    cl, sq = cdb
    ours = sorted(cl.execute(sql).rows, key=repr)
    theirs = sorted(sq.execute(sql).fetchall(), key=repr)
    assert ours == theirs


def test_noagg_correlated_scalar(tmp_path):
    """Non-aggregate correlated scalar: unique inner keys work; a
    duplicated key raises PostgreSQL's multi-row error."""
    cl = ct.Cluster(str(tmp_path / "na"), n_nodes=2)
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("CREATE TABLE lk (lk_k bigint NOT NULL, lk_v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", rows=[(i, i % 4) for i in range(50)])
    cl.copy_from("lk", rows=[(i, i * 100) for i in range(4)])
    r = cl.execute("SELECT k, (SELECT lk.lk_v FROM lk WHERE lk.lk_k = t.v) "
                   "FROM t ORDER BY k LIMIT 5")
    assert r.rows == [(0, 0), (1, 100), (2, 200), (3, 300), (4, 0)]
    # missing key -> NULL
    cl.execute("INSERT INTO t VALUES (100, 99)")
    r2 = cl.execute("SELECT (SELECT lk.lk_v FROM lk WHERE lk.lk_k = t.v) "
                    "FROM t WHERE k = 100")
    assert r2.rows == [(None,)]
    # duplicate inner key -> runtime error, as in PostgreSQL
    cl.execute("INSERT INTO lk VALUES (2, 999)")
    with pytest.raises(AnalysisError, match="more than one row"):
        cl.execute("SELECT k, (SELECT lk.lk_v FROM lk WHERE lk.lk_k = t.v) "
                   "FROM t ORDER BY k")
    cl.close()


def test_exists_under_or_still_works(cdb):
    """EXISTS not in a top-level conjunct keeps the expression path."""
    cl, sq = cdb
    sql = ("SELECT count(*) FROM o WHERE o.oa = 6 OR EXISTS "
           "(SELECT 1 FROM i WHERE i.ia = o.ob)")
    assert cl.execute(sql).rows == list(sq.execute(sql).fetchall())


def test_correlated_in_with_aggregate_item(cdb):
    """IN over a correlated AGGREGATE subquery: one value per outer
    row, not a set — must not desugar to a multi-key semi join."""
    cl, sq = cdb
    sql = ("SELECT count(*) FROM o WHERE o.ov IN "
           "(SELECT max(i.iv) FROM i WHERE i.ia = o.oa)")
    assert cl.execute(sql).rows == list(sq.execute(sql).fetchall())


def test_exists_over_ungrouped_aggregate_is_true(cdb):
    """EXISTS (SELECT count(*) ...) is always true: an ungrouped
    aggregate returns exactly one row."""
    cl, sq = cdb
    sql = ("SELECT count(*) FROM o WHERE EXISTS "
           "(SELECT count(*) FROM i WHERE i.ia = o.oa)")
    assert cl.execute(sql).rows == list(sq.execute(sql).fetchall())
    sql2 = ("SELECT count(*) FROM o WHERE NOT EXISTS "
            "(SELECT sum(i.iv) FROM i WHERE i.ia = o.oa)")
    assert cl.execute(sql2).rows == [(0,)]


def test_distinct_scalar_subquery_not_decorrelated(tmp_path):
    """SELECT DISTINCT dedups before the one-row rule; duplicates with a
    single distinct value must not raise."""
    cl = ct.Cluster(str(tmp_path / "ds"), n_nodes=2)
    cl.execute("CREATE TABLE t2 (k bigint NOT NULL, v bigint)")
    cl.execute("CREATE TABLE lk (lk_k bigint, lk_v bigint)")
    cl.execute("SELECT create_distributed_table('t2', 'k', 2)")
    cl.copy_from("t2", rows=[(1, 0), (2, 1)])
    cl.copy_from("lk", rows=[(0, 5), (0, 5), (1, 7)])
    r = cl.execute("SELECT k, (SELECT DISTINCT lk.lk_v FROM lk "
                   "WHERE lk.lk_k = t2.v) FROM t2 ORDER BY k")
    assert r.rows == [(1, 5), (2, 7)]
    cl.close()
