"""Range-partitioned (distributed) tables + time-partition helpers.

Reference: PostgreSQL PARTITION BY RANGE distributed per-partition, and
create_time_partitions / drop_old_time_partitions
(src/backend/distributed/utils/multi_partitioning_utils.c)."""

import datetime

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.errors import AnalysisError, CatalogError, UnsupportedFeatureError


@pytest.fixture()
def db(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"))
    cl.execute("""CREATE TABLE events (
        tenant bigint NOT NULL, ts date, amount bigint)
        PARTITION BY RANGE (ts)""")
    cl.execute("CREATE TABLE events_2024h1 PARTITION OF events "
               "FOR VALUES FROM ('2024-01-01') TO ('2024-07-01')")
    cl.execute("CREATE TABLE events_2024h2 PARTITION OF events "
               "FOR VALUES FROM ('2024-07-01') TO ('2025-01-01')")
    cl.execute("SELECT create_distributed_table('events', 'tenant', 4)")
    rows = []
    d0 = datetime.date(2024, 1, 1)
    for i in range(2000):
        rows.append((i % 10, (d0 + datetime.timedelta(days=i % 360)).isoformat(), i))
    cl.copy_from("events", rows=rows)
    return cl


def test_metadata_shape(db):
    t = db.catalog.table("events")
    assert t.is_partitioned and t.partition_by["column"] == "ts"
    parts = db.catalog.partitions_of("events")
    assert [p.name for p in parts] == ["events_2024h1", "events_2024h2"]
    for p in parts:
        assert p.is_distributed and p.shard_count == 4
    # siblings colocate
    assert parts[0].colocation_id == parts[1].colocation_id


def test_ingest_routes_by_range(db):
    h1 = db.execute("SELECT count(*) FROM events_2024h1").rows[0][0]
    h2 = db.execute("SELECT count(*) FROM events_2024h2").rows[0][0]
    assert h1 + h2 == 2000 and h1 > 0 and h2 > 0
    # rows landed in the right partition
    assert db.execute(
        "SELECT count(*) FROM events_2024h1 WHERE ts >= '2024-07-01'"
    ).rows == [(0,)]


def test_parent_scan_unions_partitions(db):
    assert db.execute("SELECT count(*) FROM events").rows == [(2000,)]
    a = db.execute("SELECT sum(amount) FROM events").rows[0][0]
    b = (db.execute("SELECT sum(amount) FROM events_2024h1").rows[0][0]
         + db.execute("SELECT sum(amount) FROM events_2024h2").rows[0][0])
    assert a == b == sum(range(2000))
    # group-by through the parent
    r = db.execute("SELECT tenant, count(*) FROM events GROUP BY tenant "
                   "ORDER BY tenant")
    assert len(r.rows) == 10 and sum(c for _, c in r.rows) == 2000


def test_partition_pruning_single_partition(db):
    r = db.execute("EXPLAIN SELECT count(*) FROM events "
                   "WHERE ts >= date '2024-02-01' AND ts < date '2024-03-01'")
    text = "\n".join(row[0] for row in r.rows)
    assert "partitions: 1/2" in text
    assert "Chunk Pruning" in text  # stacked: partition + chunk level
    got = db.execute("SELECT count(*) FROM events "
                     "WHERE ts >= date '2024-02-01' AND ts < date '2024-03-01'").rows
    d0 = datetime.date(2024, 1, 1)
    expect = sum(1 for i in range(2000)
                 if datetime.date(2024, 2, 1) <= d0 + datetime.timedelta(days=i % 360)
                 < datetime.date(2024, 3, 1))
    assert got == [(expect,)]


def test_row_outside_partitions_rejected(db):
    with pytest.raises(AnalysisError, match="no partition"):
        db.copy_from("events", rows=[(1, "2030-01-01", 5)])
    with pytest.raises(AnalysisError):
        db.copy_from("events", rows=[(1, None, 5)])


def test_overlapping_partition_rejected(db):
    with pytest.raises(CatalogError, match="overlap"):
        db.execute("CREATE TABLE events_bad PARTITION OF events "
                   "FOR VALUES FROM ('2024-06-01') TO ('2024-08-01')")


def test_dml_through_parent(db):
    r = db.execute("UPDATE events SET amount = 0 WHERE amount < 100")
    assert r.explain["updated"] == 100
    assert db.execute("SELECT sum(amount) FROM events").rows == \
        [(sum(range(100, 2000)),)]
    r = db.execute("DELETE FROM events WHERE ts < date '2024-07-01'")
    assert r.explain["deleted"] > 0
    assert db.execute("SELECT count(*) FROM events_2024h1").rows == [(0,)]
    with pytest.raises(UnsupportedFeatureError, match="row movement"):
        db.execute("UPDATE events SET ts = '2024-08-01' WHERE amount = 150")


def test_drop_parent_cascades(db):
    db.execute("DROP TABLE events")
    assert not db.catalog.has_table("events")
    assert not db.catalog.has_table("events_2024h1")
    assert not db.catalog.has_table("events_2024h2")


def test_truncate_parent(db):
    db.execute("TRUNCATE events")
    assert db.execute("SELECT count(*) FROM events").rows == [(0,)]


def test_joins_through_parent(db):
    db.execute("CREATE TABLE tenants (tenant bigint, name text)")
    db.copy_from("tenants", rows=[(i, f"t{i}") for i in range(10)])
    r = db.execute(
        "SELECT t.name, count(*) FROM events e JOIN tenants t "
        "ON e.tenant = t.tenant GROUP BY t.name ORDER BY t.name")
    assert len(r.rows) == 10 and sum(c for _, c in r.rows) == 2000


def test_create_time_partitions_and_retention(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db2"))
    cl.execute("CREATE TABLE metrics (k bigint, ts timestamp, v bigint) "
               "PARTITION BY RANGE (ts)")
    r = cl.execute("SELECT create_time_partitions('metrics', '1 hour', "
                   "'2024-01-01 06:00', '2024-01-01 00:00')")
    assert r.rows == [(True,)]
    parts = cl.catalog.partitions_of("metrics")
    assert len(parts) == 6
    assert parts[0].name == "metrics_p2024010100"
    # idempotent: nothing new to create
    r2 = cl.execute("SELECT create_time_partitions('metrics', '1 hour', "
                    "'2024-01-01 06:00', '2024-01-01 00:00')")
    assert r2.rows == [(False,)]
    # extend from the last bound without start_from
    cl.execute("SELECT create_time_partitions('metrics', '1 hour', "
               "'2024-01-01 08:00')")
    assert len(cl.catalog.partitions_of("metrics")) == 8
    cl.copy_from("metrics", rows=[(1, "2024-01-01 03:30:00", 7),
                                  (2, "2024-01-01 07:15:00", 9)])
    assert cl.execute("SELECT count(*) FROM metrics_p2024010103").rows == [(1,)]
    # retention drop
    r3 = cl.execute("SELECT drop_old_time_partitions('metrics', "
                    "'2024-01-01 06:00')")
    assert r3.rows == [(6,)]
    assert len(cl.catalog.partitions_of("metrics")) == 2
    assert cl.execute("SELECT count(*) FROM metrics").rows == [(1,)]
    # time_partitions view
    tp = cl.execute("SELECT time_partitions()").rows
    assert len(tp) == 2 and all(r[0] == "metrics" for r in tp)


def test_daily_time_partitions_on_date_column(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db3"))
    cl.execute("CREATE TABLE logs (k bigint, d date, msg text) "
               "PARTITION BY RANGE (d)")
    cl.execute("SELECT create_time_partitions('logs', '1 day', "
               "'2024-03-05', '2024-03-01')")
    parts = cl.catalog.partitions_of("logs")
    assert [p.name for p in parts] == [
        "logs_p20240301", "logs_p20240302", "logs_p20240303",
        "logs_p20240304"]


def test_insert_select_through_parent(db):
    db.execute("CREATE TABLE staging (tenant bigint, ts date, amount bigint)")
    db.copy_from("staging", rows=[(1, "2024-03-03", 100000),
                                  (2, "2024-09-09", 200000)])
    r = db.execute("INSERT INTO events SELECT * FROM staging")
    assert r.explain["inserted"] == 2
    assert db.execute(
        "SELECT count(*) FROM events WHERE amount >= 100000").rows == [(2,)]
    # partitioned SOURCE expands too
    db.execute("CREATE TABLE flat (tenant bigint, ts date, amount bigint)")
    db.execute("INSERT INTO flat SELECT * FROM events")
    assert db.execute("SELECT count(*) FROM flat").rows == [(2002,)]


def test_parameterized_select_on_parent(db):
    r = db.execute("SELECT count(*) FROM events WHERE amount < $1",
                   params=[100])
    assert r.rows == [(100,)]


def test_parent_pk_enforced_in_partitions(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db4"))
    cl.execute("CREATE TABLE seq_events (ts date PRIMARY KEY, v bigint) "
               "PARTITION BY RANGE (ts)")
    cl.execute("CREATE TABLE seq_a PARTITION OF seq_events "
               "FOR VALUES FROM ('2024-01-01') TO ('2024-02-01')")
    from citus_tpu.integrity import UniqueViolation
    cl.copy_from("seq_events", rows=[("2024-01-05", 1)])
    with pytest.raises(UniqueViolation):
        cl.copy_from("seq_events", rows=[("2024-01-05", 2)])
    # unique key NOT including the partition column is refused (PG rule)
    with pytest.raises(UnsupportedFeatureError, match="partition column"):
        cl.execute("CREATE TABLE bad (k bigint PRIMARY KEY, ts date) "
                   "PARTITION BY RANGE (ts)")


def test_decimal_partition_column_routing(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db5"))
    cl.execute("CREATE TABLE priced (k bigint, amount decimal(10,2)) "
               "PARTITION BY RANGE (amount)")
    cl.execute("CREATE TABLE priced_lo PARTITION OF priced "
               "FOR VALUES FROM (0) TO (100)")
    cl.execute("CREATE TABLE priced_hi PARTITION OF priced "
               "FOR VALUES FROM (100) TO (1000)")
    # float ndarray fast path must scale like encode_columns
    cl.copy_from("priced", columns={"k": np.arange(4),
                                    "amount": np.array([50.0, 99.99, 100.0, 500.5])})
    assert cl.execute("SELECT count(*) FROM priced_lo").rows == [(2,)]
    assert cl.execute("SELECT count(*) FROM priced_hi").rows == [(2,)]
    # and the object path agrees
    cl.copy_from("priced", rows=[(9, 42.42)])
    assert cl.execute("SELECT count(*) FROM priced_lo").rows == [(3,)]


def test_alter_parent_cascades_add_column(db):
    db.execute("ALTER TABLE events ADD COLUMN note text")
    assert db.catalog.table("events_2024h1").schema.has("note")
    db.copy_from("events", rows=[(1, "2024-05-05", 1, "hello")])
    r = db.execute("SELECT note FROM events WHERE note = 'hello'")
    assert r.rows == [("hello",)]
    with pytest.raises(CatalogError, match="partition column"):
        db.execute("ALTER TABLE events DROP COLUMN ts")