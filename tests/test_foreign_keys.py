"""Foreign-key constraints: declaration matrix + runtime enforcement.

Reference: commands/foreign_constraint.c
(ErrorIfUnsupportedForeignConstraintExists) for the distribution rules;
PostgreSQL RI triggers for enforcement semantics (here set-based on the
coordinator: one parent probe per ingest batch, pre-image driven
RESTRICT / CASCADE / SET NULL on the referenced side).
"""

import pytest

import citus_tpu as ct
from citus_tpu.errors import AnalysisError, UnsupportedFeatureError
from citus_tpu.integrity import ForeignKeyViolation


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"))
    c.execute("CREATE TABLE customers (cid bigint NOT NULL, name text)")
    c.execute("CREATE TABLE orders (oid bigint NOT NULL, "
              "cid bigint REFERENCES customers (cid), amt bigint)")
    c.execute("SELECT create_distributed_table('customers','cid',4)")
    c.execute("SELECT create_distributed_table('orders','cid',4)")
    c.execute("INSERT INTO customers VALUES (1,'a'), (2,'b'), (3,'c')")
    c.execute("INSERT INTO orders VALUES (10,1,100), (11,2,200)")
    return c


# --------------------------------------------------------- declaration


def test_distribution_must_cover_fk_key(tmp_path):
    c = ct.Cluster(str(tmp_path / "d"))
    c.execute("CREATE TABLE p (k bigint NOT NULL)")
    c.execute("CREATE TABLE f (i bigint NOT NULL, k bigint REFERENCES p (k))")
    c.execute("SELECT create_distributed_table('p','k',4)")
    with pytest.raises(AnalysisError):
        # the FK doesn't include f's distribution column
        c.execute("SELECT create_distributed_table('f','i',4)")
    c.execute("SELECT create_distributed_table('f','k',4)")


def test_colocation_required(tmp_path):
    c = ct.Cluster(str(tmp_path / "d"))
    c.execute("CREATE TABLE p (k bigint NOT NULL)")
    c.execute("CREATE TABLE f (k bigint NOT NULL REFERENCES p (k))")
    c.execute("SELECT create_distributed_table('p','k',4)")
    with pytest.raises(AnalysisError):
        # different shard count -> different colocation group
        c.execute("SELECT create_distributed_table('f','k',8)")


def test_reference_to_distributed_rejected(tmp_path):
    c = ct.Cluster(str(tmp_path / "d"))
    c.execute("CREATE TABLE p (k bigint NOT NULL)")
    c.execute("CREATE TABLE f (k bigint NOT NULL REFERENCES p (k))")
    c.execute("SELECT create_distributed_table('p','k',4)")
    with pytest.raises(AnalysisError):
        c.execute("SELECT create_reference_table('f')")


def test_unknown_parent_and_columns(tmp_path):
    c = ct.Cluster(str(tmp_path / "d"))
    from citus_tpu.errors import CatalogError
    with pytest.raises(CatalogError):
        c.execute("CREATE TABLE f (k bigint REFERENCES nope (k))")
    c.execute("CREATE TABLE p (k bigint NOT NULL)")
    with pytest.raises(AnalysisError):
        c.execute("CREATE TABLE f (k bigint REFERENCES p (missing))")
    with pytest.raises(AnalysisError):
        # type mismatch text vs bigint
        c.execute("CREATE TABLE f (k text REFERENCES p (k))")


def test_anything_may_reference_reference_table(tmp_path):
    c = ct.Cluster(str(tmp_path / "d"))
    c.execute("CREATE TABLE dims (d bigint NOT NULL, label text)")
    c.execute("SELECT create_reference_table('dims')")
    c.execute("CREATE TABLE facts (i bigint NOT NULL, d bigint "
              "REFERENCES dims (d))")
    # FK on a non-distribution column is fine against a reference table
    c.execute("SELECT create_distributed_table('facts','i',4)")
    c.execute("INSERT INTO dims VALUES (1,'x')")
    c.execute("INSERT INTO facts VALUES (1, 1)")
    with pytest.raises(ForeignKeyViolation):
        c.execute("INSERT INTO facts VALUES (2, 9)")


# --------------------------------------------------------- enforcement


def test_insert_violation_and_null(cl):
    with pytest.raises(ForeignKeyViolation):
        cl.execute("INSERT INTO orders VALUES (12, 99, 1)")
    cl.execute("INSERT INTO orders VALUES (13, NULL, 1)")  # MATCH SIMPLE
    assert cl.execute("SELECT count(*) FROM orders").rows == [(3,)]


def test_copy_from_batch_violation(cl):
    with pytest.raises(ForeignKeyViolation):
        cl.copy_from("orders", rows=[(20, 1, 5), (21, 42, 5)])
    # the failed batch must not be partially applied
    assert cl.execute("SELECT count(*) FROM orders").rows == [(2,)]


def test_delete_restrict_and_allowed(cl):
    with pytest.raises(ForeignKeyViolation):
        cl.execute("DELETE FROM customers WHERE cid = 1")
    cl.execute("DELETE FROM customers WHERE cid = 3")  # no children
    assert cl.execute("SELECT count(*) FROM customers").rows == [(2,)]


def test_delete_cascade_recursive(tmp_path):
    c = ct.Cluster(str(tmp_path / "d"))
    c.execute("CREATE TABLE a (k bigint NOT NULL)")
    c.execute("CREATE TABLE b (k bigint NOT NULL "
              "REFERENCES a (k) ON DELETE CASCADE)")
    c.execute("CREATE TABLE cc (k bigint NOT NULL "
              "REFERENCES b (k) ON DELETE CASCADE)")
    c.execute("SELECT create_distributed_table('a','k',2)")
    c.execute("SELECT create_distributed_table('b','k',2)")
    c.execute("SELECT create_distributed_table('cc','k',2)")
    c.execute("INSERT INTO a VALUES (1), (2)")
    c.execute("INSERT INTO b VALUES (1), (2)")
    c.execute("INSERT INTO cc VALUES (1), (2)")
    c.execute("DELETE FROM a WHERE k = 1")
    assert c.execute("SELECT count(*) FROM b").rows == [(1,)]
    assert c.execute("SELECT count(*) FROM cc").rows == [(1,)]


def test_delete_set_null(tmp_path):
    c = ct.Cluster(str(tmp_path / "d"))
    c.execute("CREATE TABLE r (k bigint NOT NULL)")
    c.execute("SELECT create_reference_table('r')")
    c.execute("CREATE TABLE s (i bigint NOT NULL, k bigint "
              "REFERENCES r (k) ON DELETE SET NULL)")
    c.execute("SELECT create_distributed_table('s','i',2)")
    c.execute("INSERT INTO r VALUES (1), (2)")
    c.execute("INSERT INTO s VALUES (1, 1), (2, 2)")
    c.execute("DELETE FROM r WHERE k = 1")
    assert c.execute("SELECT i, k FROM s ORDER BY i").rows \
        == [(1, None), (2, 2)]


def test_parent_key_update_restricted(cl):
    with pytest.raises(ForeignKeyViolation):
        cl.execute("UPDATE customers SET cid = 77 WHERE cid = 1")
    # updating a non-referenced column is free
    cl.execute("UPDATE customers SET name = 'z' WHERE cid = 1")


def test_child_fk_update_checked(cl):
    with pytest.raises(ForeignKeyViolation):
        cl.execute("UPDATE orders SET cid = 42 WHERE oid = 10")
    cl.execute("UPDATE orders SET cid = 3 WHERE oid = 10")
    assert cl.execute("SELECT cid FROM orders WHERE oid = 10").rows == [(3,)]
    with pytest.raises(UnsupportedFeatureError):
        cl.execute("UPDATE orders SET cid = cid + 1 WHERE oid = 10")


def test_truncate_and_drop_blocked(cl):
    with pytest.raises(AnalysisError):
        cl.execute("TRUNCATE customers")
    with pytest.raises(AnalysisError):
        cl.execute("DROP TABLE customers")
    # dropping the child first unblocks the parent
    cl.execute("DROP TABLE orders")
    cl.execute("DROP TABLE customers")


def test_upsert_respects_fk(cl):
    with pytest.raises(ForeignKeyViolation):
        cl.execute("INSERT INTO orders VALUES (30, 77, 1) "
                   "ON CONFLICT (oid, cid) DO NOTHING")


def test_insert_select_respects_fk(cl):
    cl.execute("CREATE TABLE src (oid bigint NOT NULL, cid bigint, "
               "amt bigint)")
    cl.execute("SELECT create_distributed_table('src','cid',4)")
    cl.execute("INSERT INTO src VALUES (50, 1, 7), (51, 42, 7)")
    with pytest.raises(ForeignKeyViolation):
        cl.execute("INSERT INTO orders SELECT oid, cid, amt FROM src")
    assert cl.execute("SELECT count(*) FROM orders").rows == [(2,)]
    cl.execute("DELETE FROM src WHERE cid = 42")
    cl.execute("INSERT INTO orders SELECT oid, cid, amt FROM src")
    assert cl.execute("SELECT count(*) FROM orders").rows == [(3,)]


def test_merge_fails_closed_on_fk_tables(cl):
    cl.execute("CREATE TABLE stage (oid bigint NOT NULL, cid bigint, "
               "amt bigint)")
    cl.execute("SELECT create_distributed_table('stage','cid',4)")
    with pytest.raises(UnsupportedFeatureError):
        cl.execute("MERGE INTO orders o USING stage s ON o.oid = s.oid "
                   "WHEN NOT MATCHED THEN INSERT VALUES (s.oid, s.cid, "
                   "s.amt)")


def test_cross_kind_numeric_fk(tmp_path):
    """Child double referencing a decimal parent compares in the
    parent's scaled-int space."""
    c = ct.Cluster(str(tmp_path / "d"))
    c.execute("CREATE TABLE p (k decimal(8,2) NOT NULL)")
    c.execute("CREATE TABLE f (i bigint NOT NULL, k double "
              "REFERENCES p (k))")
    c.execute("INSERT INTO p VALUES (5.00)")
    c.execute("INSERT INTO f VALUES (1, 5.0)")  # exists -> ok
    with pytest.raises(ForeignKeyViolation):
        c.execute("INSERT INTO f VALUES (2, 6.0)")


def test_if_not_exists_does_not_clobber_fks(cl):
    cl.execute("CREATE TABLE IF NOT EXISTS orders (zzz bigint "
               "REFERENCES customers (cid))")
    t = cl.catalog.table("orders")
    assert t.foreign_keys and t.foreign_keys[0]["columns"] == ["cid"]
    # parent-side protection still works
    with pytest.raises(ForeignKeyViolation):
        cl.execute("DELETE FROM customers WHERE cid = 1")


def test_rename_keeps_fk_edges(cl):
    cl.execute("ALTER TABLE customers RENAME TO clients")
    with pytest.raises(ForeignKeyViolation):
        cl.execute("DELETE FROM clients WHERE cid = 1")
    with pytest.raises(ForeignKeyViolation):
        cl.execute("INSERT INTO orders VALUES (60, 99, 1)")
    cl.execute("INSERT INTO orders VALUES (61, 3, 1)")  # cid=3 exists


def test_fk_survives_catalog_reload(cl, tmp_path):
    # a second coordinator sharing the data dir sees the constraint
    import os
    c2 = ct.Cluster(os.path.join(str(tmp_path), "db"))
    with pytest.raises(ForeignKeyViolation):
        c2.execute("INSERT INTO orders VALUES (31, 88, 1)")
