"""Multi-statement interactive transactions: BEGIN/COMMIT/ROLLBACK,
savepoints, two-phase locking, and mid-commit crash recovery.

Reference: transaction/transaction_management.c:319
(CoordinatedTransactionCallback — pre-commit PREPARE on all write
connections), the subxact/savepoint callback at :176, and
transaction_recovery.c (RecoverTwoPhaseCommits).
"""

import os
import subprocess
import sys
import threading
import time

import pytest

import citus_tpu as ct
from citus_tpu.errors import TransactionError, UnsupportedFeatureError
from citus_tpu.transaction.session import InFailedTransaction


@pytest.fixture()
def cl(tmp_path):
    c = ct.Cluster(str(tmp_path / "db"))
    c.execute("CREATE TABLE accounts (aid bigint NOT NULL, balance bigint)")
    c.execute("CREATE TABLE audit (eid bigint NOT NULL, note text)")
    c.execute("SELECT create_distributed_table('accounts','aid',4)")
    c.execute("SELECT create_distributed_table('audit','eid',4)")
    c.execute("INSERT INTO accounts VALUES (1, 100), (2, 200)")
    return c


# ------------------------------------------------------------ basics


def test_read_your_writes_and_isolation(cl):
    s1, s2 = cl.session(), cl.session()
    s1.execute("BEGIN")
    s1.execute("INSERT INTO accounts VALUES (3, 300)")
    s1.execute("UPDATE accounts SET balance = 150 WHERE aid = 1")
    # s1 sees its own staged writes across statements
    assert sorted(s1.execute(
        "SELECT aid, balance FROM accounts ORDER BY aid").rows) == \
        [(1, 150), (2, 200), (3, 300)]
    # s2 sees none of it until COMMIT
    assert sorted(s2.execute(
        "SELECT aid, balance FROM accounts ORDER BY aid").rows) == \
        [(1, 100), (2, 200)]
    s1.execute("COMMIT")
    assert sorted(s2.execute(
        "SELECT aid, balance FROM accounts ORDER BY aid").rows) == \
        [(1, 150), (2, 200), (3, 300)]


def test_atomic_multi_table_commit(cl):
    s1, s2 = cl.session(), cl.session()
    s1.execute("BEGIN")
    s1.execute("UPDATE accounts SET balance = 50 WHERE aid = 1")
    s1.execute("INSERT INTO audit VALUES (1, 'debit')")
    assert s2.execute("SELECT count(*) FROM audit").rows == [(0,)]
    s1.execute("COMMIT")
    # both effects landed atomically
    assert s2.execute(
        "SELECT balance FROM accounts WHERE aid = 1").rows == [(50,)]
    assert s2.execute("SELECT note FROM audit").rows == [("debit",)]


def test_rollback_restores_preimage(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("DELETE FROM accounts WHERE aid = 2")
    s.execute("UPDATE accounts SET balance = 0 WHERE aid = 1")
    s.execute("INSERT INTO accounts VALUES (9, 900)")
    assert sorted(s.execute("SELECT aid FROM accounts").rows) == [(1,), (9,)]
    s.execute("ROLLBACK")
    assert sorted(cl.execute(
        "SELECT aid, balance FROM accounts ORDER BY aid").rows) == \
        [(1, 100), (2, 200)]


def test_delete_of_rows_inserted_in_same_txn(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO accounts VALUES (5, 500), (6, 600)")
    s.execute("DELETE FROM accounts WHERE aid = 5")
    assert sorted(s.execute("SELECT aid FROM accounts ORDER BY aid").rows) \
        == [(1,), (2,), (6,)]
    s.execute("COMMIT")
    assert sorted(cl.execute("SELECT aid FROM accounts ORDER BY aid").rows) \
        == [(1,), (2,), (6,)]


def test_two_deletes_same_stripe_accumulate(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("DELETE FROM accounts WHERE aid = 1")
    s.execute("DELETE FROM accounts WHERE aid = 2")
    assert s.execute("SELECT count(*) FROM accounts").rows == [(0,)]
    s.execute("COMMIT")
    assert cl.execute("SELECT count(*) FROM accounts").rows == [(0,)]


def test_aggregate_sees_staged_writes(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO accounts VALUES (3, 300)")
    assert s.execute("SELECT sum(balance) FROM accounts").rows == [(600,)]
    s.execute("ROLLBACK")
    assert cl.execute("SELECT sum(balance) FROM accounts").rows == [(300,)]


# ------------------------------------------------------------ statements


def test_begin_twice_warns_commit_without_txn_warns(cl):
    s = cl.session()
    s.execute("BEGIN")
    r = s.execute("BEGIN")
    assert "already a transaction" in r.explain.get("warning", "")
    s.execute("ROLLBACK")
    r = s.execute("COMMIT")
    assert "no transaction" in r.explain.get("warning", "")


def test_error_aborts_block_until_rollback(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO accounts VALUES (4, 400)")
    with pytest.raises(Exception):
        s.execute("SELECT no_such_column FROM accounts")
    with pytest.raises(InFailedTransaction):
        s.execute("SELECT 1")
    # COMMIT of an aborted transaction rolls back
    r = s.execute("COMMIT")
    assert r.explain.get("transaction") == "rollback"
    assert sorted(cl.execute("SELECT aid FROM accounts").rows) == [(1,), (2,)]


def test_unstageable_ddl_refused_in_transaction(cl):
    """Most DDL now stages transactionally (round 4); statements with
    in-place physical effects (directory renames, VACUUM) stay refused."""
    s = cl.session()
    s.execute("BEGIN")
    with pytest.raises(UnsupportedFeatureError):
        s.execute("ALTER TABLE accounts RENAME TO accounts2")
    s.execute("ROLLBACK")
    s.execute("BEGIN")
    with pytest.raises(UnsupportedFeatureError):
        s.execute("VACUUM accounts")
    s.execute("ROLLBACK")


def test_spellings(cl):
    s = cl.session()
    s.execute("START TRANSACTION")
    s.execute("INSERT INTO accounts VALUES (7, 700)")
    s.execute("END")  # = COMMIT
    assert (7,) in cl.execute("SELECT aid FROM accounts").rows
    s.execute("BEGIN WORK")
    s.execute("INSERT INTO accounts VALUES (8, 800)")
    s.execute("ABORT")  # = ROLLBACK
    assert (8,) not in cl.execute("SELECT aid FROM accounts").rows


# ------------------------------------------------------------ savepoints


def test_savepoint_rollback_to_and_release(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO accounts VALUES (10, 1)")
    s.execute("SAVEPOINT a")
    s.execute("INSERT INTO accounts VALUES (11, 1)")
    s.execute("SAVEPOINT b")
    s.execute("DELETE FROM accounts WHERE aid = 1")
    s.execute("ROLLBACK TO SAVEPOINT b")   # undoes the delete
    assert (1,) in s.execute("SELECT aid FROM accounts").rows
    s.execute("ROLLBACK TO a")             # undoes insert of 11, b is gone
    s.execute("RELEASE SAVEPOINT a")
    s.execute("COMMIT")
    rows = sorted(cl.execute("SELECT aid FROM accounts").rows)
    assert (10,) in rows and (11,) not in rows and (1,) in rows


def test_unknown_savepoint_aborts_block(cl):
    """PostgreSQL: an error inside a transaction block — including a
    bad ROLLBACK TO — puts it in the aborted state (25P02)."""
    s = cl.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO accounts VALUES (14, 1)")
    with pytest.raises(TransactionError):
        s.execute("ROLLBACK TO nosuch")
    with pytest.raises(InFailedTransaction):
        s.execute("SELECT 1")
    s.execute("ROLLBACK")
    assert (14,) not in cl.execute("SELECT aid FROM accounts").rows


def test_ddl_refusal_aborts_block(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO accounts VALUES (15, 1)")
    with pytest.raises(UnsupportedFeatureError):
        s.execute("ALTER TABLE accounts RENAME TO accounts2")
    # the refusal aborted the block: COMMIT rolls back
    r = s.execute("COMMIT")
    assert r.explain.get("transaction") == "rollback"
    assert (15,) not in cl.execute("SELECT aid FROM accounts").rows


def test_copy_from_joins_default_session_txn(cl):
    cl.execute("BEGIN")
    cl.copy_from("accounts", rows=[(20, 2000)])
    assert (20,) in cl.execute("SELECT aid FROM accounts").rows
    cl.execute("ROLLBACK")
    assert (20,) not in cl.execute("SELECT aid FROM accounts").rows


def test_join_sees_staged_rows_in_empty_table(cl):
    """Joins must see staged inserts into a previously-empty table
    (the empty-shard skip consults the overlay)."""
    s = cl.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO audit VALUES (1, 'x'), (2, 'y')")
    rows = s.execute(
        "SELECT a.aid, b.note FROM accounts a JOIN audit b "
        "ON a.aid = b.eid ORDER BY a.aid").rows
    assert rows == [(1, "x"), (2, "y")]
    s.execute("ROLLBACK")


def test_savepoint_clears_aborted_state(cl):
    """PostgreSQL: ROLLBACK TO a savepoint set before the failure
    resumes the transaction."""
    s = cl.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO accounts VALUES (12, 1)")
    s.execute("SAVEPOINT sp")
    with pytest.raises(Exception):
        s.execute("SELECT broken FROM accounts")
    with pytest.raises(InFailedTransaction):
        s.execute("SELECT 1")
    s.execute("ROLLBACK TO sp")
    s.execute("INSERT INTO accounts VALUES (13, 1)")
    s.execute("COMMIT")
    rows = sorted(cl.execute("SELECT aid FROM accounts").rows)
    assert (12,) in rows and (13,) in rows


def test_savepoint_outside_txn_errors(cl):
    with pytest.raises(TransactionError):
        cl.session().execute("SAVEPOINT sp")


# ------------------------------------------------------------ locking


def test_conflicting_write_blocks_until_commit(cl):
    """Two-phase locking: a concurrent session's conflicting UPDATE
    waits for the open transaction's COMMIT (the reference holds shard
    write locks to transaction end)."""
    s1 = cl.session()
    s1.execute("BEGIN")
    s1.execute("UPDATE accounts SET balance = 111 WHERE aid = 1")

    done = threading.Event()
    errors = []

    def blocked_writer():
        try:
            cl.session().execute(
                "UPDATE accounts SET balance = 222 WHERE aid = 1")
        except Exception as e:  # pragma: no cover
            errors.append(e)
        finally:
            done.set()

    th = threading.Thread(target=blocked_writer, daemon=True)
    th.start()
    assert not done.wait(0.5), "writer should block on the open txn's lock"
    s1.execute("COMMIT")
    assert done.wait(10), "writer should proceed after COMMIT"
    th.join()
    assert not errors
    # the blocked writer ran after the commit: last write wins
    assert cl.execute(
        "SELECT balance FROM accounts WHERE aid = 1").rows == [(222,)]


def test_deadlock_between_sessions_detected(tmp_path):
    """Opposite-order lock acquisition across two open transactions:
    the in-process wait-graph detector cancels one (youngest-victim
    policy, distributed_deadlock_detection.c:105).  The two tables use
    different shard counts so they land in different colocation groups
    (= different lock resources)."""
    from citus_tpu.transaction import DeadlockDetected

    c = ct.Cluster(str(tmp_path / "dl"))
    c.execute("CREATE TABLE t1 (k bigint NOT NULL)")
    c.execute("CREATE TABLE t2 (k bigint NOT NULL)")
    c.execute("SELECT create_distributed_table('t1','k',4)")
    c.execute("SELECT create_distributed_table('t2','k',8)")

    b = threading.Barrier(2, timeout=10)
    outcomes = {}

    def run(name, first, second):
        s = c.session()
        try:
            s.execute("BEGIN")
            s.execute(f"DELETE FROM {first} WHERE k = -1")  # EXCLUSIVE
            b.wait()
            s.execute(f"DELETE FROM {second} WHERE k = -2")
            s.execute("COMMIT")
            outcomes[name] = "committed"
        except DeadlockDetected:
            outcomes[name] = "deadlock"
            s.execute("ROLLBACK")
        except Exception as e:  # pragma: no cover
            outcomes[name] = f"error:{type(e).__name__}"

    t1 = threading.Thread(target=run, args=("a", "t1", "t2"), daemon=True)
    t2 = threading.Thread(target=run, args=("b", "t2", "t1"), daemon=True)
    t1.start(), t2.start()
    t1.join(60), t2.join(60)
    assert sorted(outcomes.values()) == ["committed", "deadlock"], outcomes


# ------------------------------------------------------------ CDC


def test_cdc_events_deferred_to_commit(tmp_path):
    from citus_tpu.config import Settings
    st = Settings()
    st.enable_change_data_capture = True
    c = ct.Cluster(str(tmp_path / "cdcdb"), settings=st)
    c.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    c.execute("SELECT create_distributed_table('t','k',2)")
    s = c.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO t VALUES (1, 10)")
    s.execute("UPDATE t SET v = 11 WHERE k = 1")
    assert list(c.cdc.events("t")) == []  # nothing until commit
    s.execute("COMMIT")
    ops = [e["op"] for e in c.cdc.events("t")]
    assert ops == ["insert", "update"]
    # rolled-back events never surface
    s.execute("BEGIN")
    s.execute("INSERT INTO t VALUES (2, 20)")
    s.execute("ROLLBACK")
    assert [e["op"] for e in c.cdc.events("t")] == ["insert", "update"]


# ------------------------------------------------------------ recovery


_KILL_SCRIPT = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import citus_tpu as ct
from citus_tpu.storage import writer as W

data_dir, mode = sys.argv[1], sys.argv[2]
cl = ct.Cluster(data_dir, n_nodes=2)
s = cl.session()
s.execute("BEGIN")
s.execute("INSERT INTO accounts VALUES (50, 5000)")
s.execute("UPDATE accounts SET balance = 101 WHERE aid = 1")

if mode == "after_committed":
    # die after the COMMITTED record, before any staged state flips
    orig = W.commit_staged
    def boom(directory, xid):
        os._exit(9)
    W.commit_staged = boom
    from citus_tpu.storage import deletes as D
    D.commit_staged_deletes = boom
    try:
        s.execute("COMMIT")
    except SystemExit:
        raise
elif mode == "before_committed":
    # die after PREPARED, before COMMITTED
    from citus_tpu.transaction.manager import TransactionLog, TxState
    orig_log = cl.txlog.log
    def log(xid, state, payload=None):
        orig_log(xid, state, payload)
        if state == TxState.PREPARED:
            os._exit(9)
    cl.txlog.log = log
    s.execute("COMMIT")
"""


def _run_kill(cl, tmp_path, mode):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    p = subprocess.run([sys.executable, "-c", _KILL_SCRIPT,
                        cl.catalog.data_dir, mode],
                       env=env, capture_output=True, text=True, timeout=120)
    assert p.returncode == 9, (p.returncode, p.stderr[-2000:])


def test_recovery_rolls_forward_after_committed_record(cl, tmp_path):
    """Killed between the COMMITTED record and the flip: recovery rolls
    the whole interactive transaction forward."""
    _run_kill(cl, tmp_path, "after_committed")
    from citus_tpu.transaction.recovery import recover_transactions
    st = recover_transactions(cl.catalog, cl.txlog)
    assert st["rolled_forward"] >= 1
    cl._reload_catalog()
    rows = dict(cl.execute(
        "SELECT aid, balance FROM accounts ORDER BY aid").rows)
    assert rows[50] == 5000 and rows[1] == 101


def test_recovery_rolls_back_prepared_without_committed(cl, tmp_path):
    """Killed between PREPARED and COMMITTED: recovery rolls back and
    the pre-image survives."""
    _run_kill(cl, tmp_path, "before_committed")
    from citus_tpu.transaction.recovery import recover_transactions
    st = recover_transactions(cl.catalog, cl.txlog)
    assert st["rolled_back"] >= 1
    cl._reload_catalog()
    rows = dict(cl.execute(
        "SELECT aid, balance FROM accounts ORDER BY aid").rows)
    assert 50 not in rows and rows[1] == 100


def test_abandoned_session_rolls_back_on_close(tmp_path):
    c = ct.Cluster(str(tmp_path / "ab"))
    c.execute("CREATE TABLE t (k bigint NOT NULL)")
    c.execute("SELECT create_distributed_table('t','k',2)")
    with c.session() as s:
        s.execute("BEGIN")
        s.execute("INSERT INTO t VALUES (1)")
    # context exit closed the session -> rollback
    assert c.execute("SELECT count(*) FROM t").rows == [(0,)]


def test_upsert_inside_transaction(cl):
    s = cl.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO accounts VALUES (1, 999) "
              "ON CONFLICT (aid) DO UPDATE SET balance = 999")
    assert s.execute(
        "SELECT balance FROM accounts WHERE aid = 1").rows == [(999,)]
    s.execute("ROLLBACK")
    assert cl.execute(
        "SELECT balance FROM accounts WHERE aid = 1").rows == [(100,)]


def test_rollback_to_savepoint_releases_later_locks(tmp_path):
    """PostgreSQL parity (round-3 weak #6): locks acquired after a
    savepoint are released by ROLLBACK TO, so another session can write
    the table without waiting for the transaction to end."""
    from citus_tpu.config import ExecutorSettings, Settings
    st = Settings(executor=ExecutorSettings(lock_timeout_s=1.0))
    cl = ct.Cluster(str(tmp_path / "db"), settings=st)
    cl.execute("CREATE TABLE a (x bigint)")
    cl.execute("CREATE TABLE b (x bigint)")
    cl.copy_from("a", rows=[(1,)])
    cl.copy_from("b", rows=[(1,)])
    s1, s2 = cl.session(), cl.session()
    s1.execute("BEGIN")
    s1.execute("UPDATE a SET x = 2")          # lock on a: held at savepoint
    s1.execute("SAVEPOINT sp")
    s1.execute("UPDATE b SET x = 2")          # lock on b: post-savepoint
    s1.execute("ROLLBACK TO SAVEPOINT sp")
    # b's lock is gone: s2 can write b immediately ...
    s2.execute("UPDATE b SET x = 3")
    # ... while a's lock (pre-savepoint) is still held
    with pytest.raises(Exception):
        s2.execute("UPDATE a SET x = 3")
    s1.execute("COMMIT")
    assert cl.execute("SELECT x FROM a").rows == [(2,)]
    assert cl.execute("SELECT x FROM b").rows == [(3,)]


def test_rollback_to_reacquires_lock_dropped_by_failed_upgrade(tmp_path):
    """A failed post-savepoint SHARED->EXCLUSIVE upgrade (contended by
    ANOTHER PROCESS at the flock layer) drops the lock outright;
    ROLLBACK TO must re-acquire it so the restored pre-savepoint staged
    writes stay protected (2PL)."""
    from citus_tpu.config import ExecutorSettings, Settings
    from citus_tpu.transaction.write_locks import group_resource, lockfile_path
    st = Settings(executor=ExecutorSettings(lock_timeout_s=1.0))
    cl = ct.Cluster(str(tmp_path / "db"), settings=st)
    cl.execute("CREATE TABLE a (x bigint)")
    cl.copy_from("a", rows=[(1,)])
    s1 = cl.session()
    s1.execute("BEGIN")
    s1.execute("INSERT INTO a VALUES (2)")     # SHARED group lock
    s1.execute("SAVEPOINT sp")
    res = group_resource(cl.catalog.table("a"))
    lockfile = lockfile_path(cl.catalog.data_dir, res)
    hold = subprocess.Popen(  # foreign process holds SHARED on the flock
        [sys.executable, "-c",
         "import fcntl, os, sys, time; "
         "fd = os.open(sys.argv[1], os.O_CREAT | os.O_RDWR); "
         "fcntl.flock(fd, fcntl.LOCK_SH); print('held', flush=True); "
         "time.sleep(30)", lockfile],
        stdout=subprocess.PIPE, text=True)
    assert hold.stdout.readline().strip() == "held"
    try:
        with pytest.raises(TransactionError, match="upgrade"):
            s1.execute("UPDATE a SET x = 9")   # flock upgrade fails, drop
        assert res not in s1.txn.locks         # the lock is really gone
    finally:
        hold.terminate()
        hold.wait()
    s1.execute("ROLLBACK TO SAVEPOINT sp")     # must re-acquire SHARED
    assert res in s1.txn.locks and s1.txn.locks[res].mode == "shared"
    s1.execute("COMMIT")
    assert sorted(cl.execute("SELECT x FROM a").rows) == [(1,), (2,)]
