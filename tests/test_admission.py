"""Shared task-pool admission control.

Reference: citus.max_shared_pool_size backed by shared-memory counters
(connection/shared_connection_stats.c) — bounds the node-wide worker
connections; optional acquisitions fail fast, required ones wait."""

import threading
import time

import pytest

import citus_tpu as ct
from citus_tpu.errors import ExecutionError
from citus_tpu.executor.admission import GLOBAL_POOL, SharedTaskPool


def test_required_waits_and_bounds_concurrency():
    pool = SharedTaskPool()
    peak = []

    def work(i):
        with pool.slot(2, timeout=10):
            peak.append(pool.in_use)
            time.sleep(0.02)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert pool.high_water <= 2
    assert pool.granted == 8
    assert pool.waits > 0
    assert pool.in_use == 0


def test_optional_denied_fast():
    pool = SharedTaskPool()
    assert pool.acquire(1) is True
    t0 = time.monotonic()
    assert pool.acquire(1, optional=True) is False
    assert time.monotonic() - t0 < 0.1  # never waited
    assert pool.stats()["denied_optional"] == 1
    pool.release()


def test_required_times_out():
    pool = SharedTaskPool()
    pool.acquire(1)
    with pytest.raises(ExecutionError, match="max_shared_pool_size"):
        pool.acquire(1, timeout=0.1)
    pool.release()


def test_unlimited_by_default():
    pool = SharedTaskPool()
    for _ in range(64):
        assert pool.acquire(0) is True
    assert pool.high_water == 64


def test_fifo_ticket_order():
    """Regression: a freed slot goes to the LONGEST-waiting required
    acquirer, not whichever thread the OS wakes first."""
    pool = SharedTaskPool()
    pool.acquire(1)
    order = []
    started = []
    threads = []

    def waiter(i):
        started.append(i)
        pool.acquire(1, timeout=10)
        order.append(i)
        time.sleep(0.01)
        pool.release()

    for i in range(4):
        t = threading.Thread(target=waiter, args=(i,))
        threads.append(t)
        t.start()
        # arrival order is the ticket order: wait until i is queued
        deadline = time.monotonic() + 5
        while len(pool._waiters) < i + 1 and time.monotonic() < deadline:
            time.sleep(0.001)
    pool.release()
    for t in threads:
        t.join()
    assert order == [0, 1, 2, 3]
    # waits counts waiters, not grants: the seed acquire never waited
    assert pool.waits == 4
    assert pool.granted == 5


def test_optional_never_barges_waiters():
    """Regression: with a required waiter queued, an optional acquire
    is denied even at the instant a slot frees — the freed slot belongs
    to the queue head."""
    pool = SharedTaskPool()
    pool.acquire(1)
    got = []
    t = threading.Thread(target=lambda: got.append(
        pool.acquire(1, timeout=10)))
    t.start()
    deadline = time.monotonic() + 5
    while not pool._waiters and time.monotonic() < deadline:
        time.sleep(0.001)
    pool.release()  # head ticket now owns the slot, maybe not yet awake
    assert pool.acquire(1, optional=True) is False
    t.join()
    assert got == [True]
    pool.release()


def test_timeout_counter_in_stats():
    pool = SharedTaskPool()
    pool.acquire(1)
    with pytest.raises(ExecutionError, match="max_shared_pool_size"):
        pool.acquire(1, timeout=0.05)
    assert pool.stats()["timeouts"] == 1
    pool.release()


def test_queries_bounded_end_to_end(tmp_path):
    """Concurrent queries through the SQL surface respect the cap and
    the citus_stat_pool view reports it."""
    import dataclasses
    from citus_tpu.config import ExecutorSettings, Settings
    st = Settings(executor=ExecutorSettings(max_shared_pool_size=2))
    cl = ct.Cluster(str(tmp_path / "db"), settings=st)
    cl.execute("CREATE TABLE t (k bigint, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 8)")
    cl.copy_from("t", rows=[(i, i) for i in range(20000)])
    results = []

    def q():
        results.append(cl.execute("SELECT sum(v) FROM t").rows[0][0])

    threads = [threading.Thread(target=q) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == [sum(range(20000))] * 6
    view = cl.execute("SELECT citus_stat_pool()")
    row = dict(zip(view.columns, view.rows[0]))
    assert row["pool_size"] == 2
    assert row["in_use"] == 0
    assert row["granted"] >= 6