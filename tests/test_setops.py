"""Set operations, EXISTS, derived tables — VERDICT round-2 item #5.

Reference: UNION/INTERSECT/EXCEPT and EXISTS sublinks route through
recursive planning (recursive_planning.c:223,1303); derived tables
materialize as intermediate results.  Everything oracle-diffed against
sqlite3 over identical rows."""

import sqlite3

import numpy as np
import pytest

import citus_tpu as ct


@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    cl = ct.Cluster(str(tmp_path_factory.mktemp("db")))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint, s text)")
    cl.execute("CREATE TABLE u (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.execute("SELECT create_distributed_table('u', 'k', 4)")
    rng = np.random.default_rng(5)
    trows = [(i, int(rng.integers(0, 12)) if rng.random() > 0.05 else None,
              f"g{i % 3}") for i in range(400)]
    urows = [(i, int(rng.integers(0, 8)) if rng.random() > 0.05 else None)
             for i in range(250)]
    cl.copy_from("t", rows=trows)
    cl.copy_from("u", rows=urows)
    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE t (k INTEGER, v INTEGER, s TEXT)")
    sq.execute("CREATE TABLE u (k INTEGER, v INTEGER)")
    sq.executemany("INSERT INTO t VALUES (?,?,?)", trows)
    sq.executemany("INSERT INTO u VALUES (?,?)", urows)
    yield cl, sq
    cl.close()


SETOP_QUERIES = [
    "SELECT v FROM t UNION SELECT v FROM u ORDER BY v NULLS LAST",
    "SELECT v FROM t UNION ALL SELECT v FROM u ORDER BY v NULLS LAST LIMIT 40",
    "SELECT v FROM t INTERSECT SELECT v FROM u ORDER BY v NULLS LAST",
    "SELECT v FROM t EXCEPT SELECT v FROM u ORDER BY v NULLS LAST",
    "SELECT v, count(*) FROM t GROUP BY v UNION SELECT v, count(*) FROM u "
    "GROUP BY v ORDER BY 1 NULLS LAST, 2",
    "SELECT v FROM t WHERE v < 3 UNION SELECT v FROM t WHERE v > 9 "
    "INTERSECT SELECT v FROM u ORDER BY v",
    "SELECT k FROM t WHERE exists (SELECT 1 FROM u WHERE u.v = 7) "
    "ORDER BY k LIMIT 5",
    "SELECT count(*) FROM t WHERE not exists (SELECT 1 FROM u WHERE u.v = 99)",
    "SELECT count(*) FROM (SELECT v FROM t UNION ALL SELECT v FROM u) z",
    "SELECT g, n FROM (SELECT s AS g, count(*) AS n FROM t GROUP BY s) z "
    "WHERE n > 50 ORDER BY g",
    "SELECT count(*) FROM t JOIN (SELECT k FROM u WHERE v < 4) z ON t.k = z.k",
    "SELECT z.v, count(*) FROM (SELECT v FROM t WHERE v IS NOT NULL) z "
    "GROUP BY z.v ORDER BY z.v",
]


@pytest.mark.parametrize("sql", SETOP_QUERIES)
def test_vs_sqlite(loaded, sql):
    cl, sq = loaded
    ours = [tuple(r) for r in cl.execute(sql).rows]
    theirs = [tuple(r) for r in sq.execute(sql).fetchall()]
    if "ORDER BY" not in sql:
        ours, theirs = sorted(ours, key=repr), sorted(theirs, key=repr)
    assert ours == theirs, (sql, ours[:8], theirs[:8])


def test_bag_semantics_all_variants(loaded):
    """sqlite lacks EXCEPT/INTERSECT ALL; check bag semantics against a
    Counter-based oracle."""
    from collections import Counter
    cl, sq = loaded
    tv = [r[0] for r in sq.execute("SELECT v FROM t").fetchall()]
    uv = [r[0] for r in sq.execute("SELECT v FROM u").fetchall()]
    tc, uc = Counter(tv), Counter(uv)
    got = Counter(r[0] for r in cl.execute(
        "SELECT v FROM t EXCEPT ALL SELECT v FROM u").rows)
    exp = Counter({k: n - uc.get(k, 0) for k, n in tc.items()
                   if n - uc.get(k, 0) > 0})
    assert got == exp
    got = Counter(r[0] for r in cl.execute(
        "SELECT v FROM t INTERSECT ALL SELECT v FROM u").rows)
    exp = Counter({k: min(n, uc[k]) for k, n in tc.items()
                   if k in uc and min(n, uc[k]) > 0})
    assert got == exp


def test_setop_column_count_mismatch(loaded):
    cl, _ = loaded
    from citus_tpu.errors import AnalysisError
    with pytest.raises(AnalysisError):
        cl.execute("SELECT v FROM t UNION SELECT k, v FROM u")


def test_union_in_cte_and_insert(loaded, tmp_path):
    cl, sq = loaded
    r = cl.execute("WITH allv AS (SELECT v FROM t UNION SELECT v FROM u) "
                   "SELECT count(*) FROM allv")
    exp = sq.execute("SELECT count(*) FROM (SELECT v FROM t UNION "
                     "SELECT v FROM u)").fetchall()
    assert [tuple(x) for x in r.rows] == [tuple(x) for x in exp]
    # INSERT .. SELECT with a set operation body takes the pull rung
    cl.execute("CREATE TABLE vs (v bigint)")
    ins = cl.execute("INSERT INTO vs SELECT v FROM t UNION SELECT v FROM u")
    assert ins.explain["strategy"] == "insert_select:pull"
    assert cl.execute("SELECT count(*) FROM vs").rows == [tuple(x) for x in exp]
    cl.execute("DROP TABLE vs")


def test_derived_alias_required(loaded):
    cl, _ = loaded
    from citus_tpu.errors import SqlSyntaxError
    with pytest.raises(SqlSyntaxError):
        cl.execute("SELECT * FROM (SELECT v FROM t)")


def test_parenthesized_setop_operand(loaded):
    cl, sq = loaded
    sql = ("SELECT v FROM (SELECT v FROM t WHERE v < 5 UNION ALL "
           "SELECT v FROM u WHERE v < 5) z ORDER BY v NULLS LAST LIMIT 20")
    ours = [tuple(r) for r in cl.execute(sql).rows]
    theirs = [tuple(r) for r in sq.execute(sql).fetchall()]
    assert ours == theirs
