"""Multi-coordinator control plane over RPC — VERDICT round-2 item #9.

Reference: metadata sync + node activation over libpq connections
(metadata/metadata_sync.c:229, worker_transaction.c).  Here: a TCP
JSON-RPC skeleton (net/rpc.py) carrying catalog invalidation pushes and
in-flight transaction (2PC vote) exchange between coordinator
processes, with the shared data directory as the degenerate bulk
transport for the catalog document itself."""

import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import citus_tpu as ct


def wait_until(fn, timeout=5.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if fn():
            return True
        time.sleep(0.02)
    return False


def test_rpc_roundtrip_and_events(tmp_path):
    from citus_tpu.net.rpc import RpcClient, RpcServer
    srv = RpcServer().start()
    srv.register("echo", lambda p: {"got": p["x"] * 2})
    cli = RpcClient(srv.host, srv.port)
    assert cli.call("echo", {"x": 21})["got"] == 42
    events = []
    cli.subscribe(events.append)
    time.sleep(0.05)
    srv.broadcast({"event": "hello"})
    assert wait_until(lambda: events and events[0]["event"] == "hello")
    cli.close()
    srv.stop()


def test_catalog_invalidation_over_rpc(tmp_path):
    """Two in-process coordinators: invalidations travel by RPC push
    (the mtime poller branch is bypassed entirely)."""
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2, serve_port=0)
    b = ct.Cluster(str(tmp_path / "db"), n_nodes=2,
                   coordinator=("127.0.0.1", a.control_port))
    try:
        a.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
        a.execute("SELECT create_distributed_table('t', 'k', 4)")
        a.copy_from("t", columns={"k": np.arange(100), "v": np.ones(100, np.int64)})
        # b learns of a's DDL through the push channel
        assert wait_until(lambda: b._catalog_dirty)
        assert b.execute("SELECT count(*) FROM t").rows == [(100,)]
        # writes through b reach a synchronously: b's commit pushes the
        # catalog document to the authority, which applies it in-line
        b.execute("CREATE TABLE u (x bigint)")
        b.execute("INSERT INTO u VALUES (7)")
        assert a.catalog.has_table("u")
        assert a.execute("SELECT x FROM u").rows == [(7,)]
    finally:
        b.close()
        a.close()


def test_peer_inflight_protects_recovery(tmp_path):
    """2PC-vote exchange: the authority spares xids a peer reports
    in-flight, even without the same-host flock probe."""
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2, serve_port=0)
    b = ct.Cluster(str(tmp_path / "db"), n_nodes=2,
                   coordinator=("127.0.0.1", a.control_port))
    try:
        xid = b.txlog.begin()  # b holds an in-flight transaction
        b._control.report_inflight()
        assert xid in a._control.peer_inflight_xids()
        assert xid in b._control.peer_inflight_xids()
        b.txlog.release(xid)
    finally:
        b.close()
        a.close()


def test_second_process_coordinator(tmp_path):
    """A real second coordinator process syncs metadata over RPC."""
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2, serve_port=0)
    try:
        a.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
        a.execute("SELECT create_distributed_table('t', 'k', 4)")
        a.copy_from("t", columns={"k": np.arange(50), "v": np.ones(50, np.int64)})
        script = textwrap.dedent(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import citus_tpu as ct
            b = ct.Cluster({str(tmp_path / 'db')!r}, n_nodes=2,
                           coordinator=("127.0.0.1", {a.control_port}))
            assert b.execute("SELECT count(*) FROM t").rows == [(50,)]
            b.execute("CREATE TABLE w (x bigint)")
            b.execute("INSERT INTO w VALUES (11), (22)")
            b.close()
            print("PEER OK")
        """)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "PEER OK" in r.stdout
        # the peer's DDL+write arrived as a pushed catalog document,
        # applied synchronously by the authority
        assert a.catalog.has_table("w")
        assert a.execute("SELECT sum(x) FROM w").rows == [(33,)]
    finally:
        a.close()


def test_mx_still_works_without_rpc(tmp_path):
    """No control plane configured: the mtime-poll fallback still syncs
    (degenerate transport only)."""
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    b = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    try:
        a.execute("CREATE TABLE t (k bigint)")
        a.execute("INSERT INTO t VALUES (5)")
        assert b.execute("SELECT count(*) FROM t").rows == [(1,)]
    finally:
        b.close()
        a.close()


def test_fallback_to_polling_when_authority_dies(tmp_path):
    """Losing the push channel degrades to mtime polling — peers keep
    seeing each other's commits through the shared catalog."""
    a = ct.Cluster(str(tmp_path / "db"), n_nodes=2, serve_port=0)
    b = ct.Cluster(str(tmp_path / "db"), n_nodes=2,
                   coordinator=("127.0.0.1", a.control_port))
    try:
        a.execute("CREATE TABLE t (k bigint)")
        a.execute("INSERT INTO t VALUES (1)")
        assert wait_until(lambda: b._catalog_dirty)
        assert b.execute("SELECT count(*) FROM t").rows == [(1,)]
        # kill the authority's server: b's push channel dies
        a._control.server.stop()
        assert wait_until(lambda: not b._control.connected)
        # a's further commits still reach b via the mtime fallback
        a.execute("INSERT INTO t VALUES (2)")
        assert b.execute("SELECT count(*) FROM t").rows == [(2,)]
    finally:
        b.close()
        a.close()
