"""Continuous aggregation end-to-end: citus_create_rollup backfill,
CDC-driven incremental refresh with lag convergence, planner routing of
dashboard queries to the rollup (EXPLAIN-visible), the t-digest
percentile backend, and the exactly-once restart regression at fault
point ``rollup_refresh``."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import Settings
from citus_tpu.testing.faults import FAULTS

DASH_Q = ("SELECT tid, count(*), sum(v), approx_count_distinct(kind), "
          "approx_percentile(0.5) WITHIN GROUP (ORDER BY v) "
          "FROM ev GROUP BY tid ORDER BY tid")


@pytest.fixture(autouse=True)
def _disarm():
    yield
    FAULTS.disarm()


def make_cluster(tmp_path, rows=300, tenants=5):
    cl = ct.Cluster(
        str(tmp_path / "db"), n_nodes=1,
        settings=Settings(enable_change_data_capture=True,
                          start_maintenance_daemon=False))
    cl.execute("CREATE TABLE ev (tid bigint NOT NULL, kind text, "
               "v double, code bigint)")
    cl.execute("SELECT create_distributed_table('ev', 'tid', 4)")
    ingest(cl, rows, tenants=tenants)
    return cl


def ingest(cl, rows, *, tenants=5, seed=0):
    rng = np.random.default_rng(seed)
    cl.copy_from("ev", columns={
        "tid": rng.integers(0, tenants, rows).astype(np.int64),
        "kind": np.array([f"k{int(x)}" for x in
                          rng.integers(0, 40, rows)], object),
        "v": rng.uniform(1.0, 100.0, rows),
        "code": rng.integers(0, 8, rows).astype(np.int64),
    })


def oracle(cl):
    """Raw-scan GROUP BY truth: {tid: (count, sum, distinct kinds)}."""
    res = cl.execute("SELECT tid, count(*), sum(v), count(DISTINCT kind) "
                     "FROM ev GROUP BY tid")
    return {r[0]: (r[1], float(r[2]), r[3]) for r in res.rows}


def create_rollup(cl, aggs="count(*), sum(v), approx_count_distinct(kind), "
                           "approx_percentile(v), approx_top_k(code)"):
    cl.execute(f"SELECT citus_create_rollup('ev_r', 'ev', 'tid', '{aggs}')")


# -------------------------------------------------- create + backfill

def test_create_rollup_backfills_and_matches_oracle(tmp_path):
    cl = make_cluster(tmp_path)
    try:
        create_rollup(cl)
        truth = oracle(cl)
        rows = cl.execute("SELECT tid, n_rows, sum_v, acd_kind FROM ev_r").rows
        assert {r[0] for r in rows} == set(truth)
        from citus_tpu.rollup.sketches import decode_sketch, finalize_sketch
        for tid, n, s, acd in rows:
            assert n == truth[tid][0]
            assert s == pytest.approx(truth[tid][1])
            # the stored hll word finalizes within the documented ±9%
            # 1-sigma bound (3 sigma allowance) of the exact distinct
            est, ok = finalize_sketch("hll", decode_sketch(acd)[1])
            assert ok
            exact = truth[tid][2]
            assert abs(est - exact) <= max(3, 0.27 * exact), (tid, est)
        # the rollup is colocated with its source
        src = cl.catalog.table("ev")
        rt = cl.catalog.table("ev_r")
        assert rt.is_distributed and rt.dist_column == "tid"
        assert len(rt.shards) == len(src.shards)
        # view starts converged: backfill watermark == CDC head
        name, source, table, backend, wm, head, pending = \
            cl.execute("SELECT citus_rollups()").rows[0]
        assert (name, source, table, backend) == ("ev_r", "ev", "ev_r",
                                                  "ddsk")
        assert wm == head and pending == 0
    finally:
        cl.close()


def test_create_rollup_validation_errors(tmp_path):
    from citus_tpu.errors import AnalysisError
    cl = make_cluster(tmp_path, rows=20)
    try:
        for bad in [
            "SELECT citus_create_rollup('r1', 'ev', 'kind', 'count(*)')",
            "SELECT citus_create_rollup('r1', 'ev', 'tid', 'avg(v)')",
            "SELECT citus_create_rollup('r1', 'ev', 'tid', "
            "'approx_top_k(kind)')",
            "SELECT citus_create_rollup('r1', 'ev', 'tid, nope', "
            "'count(*)')",
        ]:
            with pytest.raises(AnalysisError):
                cl.execute(bad)
        # a source without CDC has no delta stream to refresh from
        cl.execute("CREATE TABLE quiet (a bigint)")
        cl.execute("SELECT create_distributed_table('quiet', 'a', 2)")
        cl.cdc.enabled = False
        try:
            with pytest.raises(AnalysisError):
                cl.execute("SELECT citus_create_rollup('r2', 'quiet', "
                           "'a', 'count(*)')")
        finally:
            cl.cdc.enabled = True
    finally:
        cl.close()


def test_sketch_merge_demands_sketch_column(tmp_path):
    from citus_tpu.errors import AnalysisError
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=1)
    try:
        cl.execute("CREATE TABLE kv (k bigint, n bigint)")
        cl.execute("SELECT create_distributed_table('kv', 'k', 2)")
        with pytest.raises(AnalysisError):
            cl.execute("INSERT INTO kv VALUES (1, 2) ON CONFLICT (k) "
                       "DO UPDATE SET n = sketch_merge(n, excluded.n)")
    finally:
        cl.close()


# --------------------------------------------- incremental refresh

def test_refresh_converges_to_cdc_head(tmp_path):
    cl = make_cluster(tmp_path, rows=200)
    try:
        create_rollup(cl)
        ingest(cl, 150, seed=1)
        ingest(cl, 150, seed=2)
        _, _, _, _, wm, head, pending = \
            cl.execute("SELECT citus_rollups()").rows[0]
        assert pending > 0 and head > wm  # lag is visible before refresh
        folded = cl.execute("SELECT citus_refresh_rollups()").rows[0][0]
        assert folded == 300
        _, _, _, _, wm, head, pending = \
            cl.execute("SELECT citus_rollups()").rows[0]
        assert wm == head and pending == 0  # lag converged
        truth = oracle(cl)
        for tid, n, s in cl.execute(
                "SELECT tid, n_rows, sum_v FROM ev_r").rows:
            assert n == truth[tid][0]
            assert s == pytest.approx(truth[tid][1])
    finally:
        cl.close()


def test_refresh_respects_batch_limit(tmp_path):
    cl = make_cluster(tmp_path, rows=50)
    try:
        create_rollup(cl, aggs="count(*)")
        cl.execute("SET citus.rollup_max_batch_rows = 40")
        for seed in (1, 2, 3):
            ingest(cl, 60, seed=seed)
        # each refresh_once folds <= ~one batch; run_once drains all
        first = cl.rollup_manager.refresh_once("ev_r")
        assert first is not None and first <= 60
        cl.rollup_manager.run_once()
        truth = oracle(cl)
        for tid, n in cl.execute("SELECT tid, n_rows FROM ev_r").rows:
            assert n == truth[tid][0]
    finally:
        cl.close()


def test_background_refresh_loop_follows_guc(tmp_path):
    cl = make_cluster(tmp_path, rows=100)
    try:
        create_rollup(cl, aggs="count(*), sum(v)")
        assert cl.rollup_manager._thread is None  # interval 0 = off
        cl.execute("SET citus.rollup_refresh_interval_ms = 20")
        assert cl.rollup_manager._thread is not None
        ingest(cl, 120, seed=3)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if cl.execute("SELECT citus_rollups()").rows[0][6] == 0:
                break
            time.sleep(0.05)
        else:
            pytest.fail("background refresh never converged")
        truth = oracle(cl)
        for tid, n, s in cl.execute(
                "SELECT tid, n_rows, sum_v FROM ev_r").rows:
            assert n == truth[tid][0]
            assert s == pytest.approx(truth[tid][1])
        cl.execute("SET citus.rollup_refresh_interval_ms = 0")
        assert cl.rollup_manager._thread is None
    finally:
        cl.close()


def test_updates_and_deletes_are_counted_not_folded(tmp_path):
    cl = make_cluster(tmp_path, rows=100)
    try:
        create_rollup(cl, aggs="count(*)")
        from citus_tpu.executor.executor import GLOBAL_COUNTERS
        before = GLOBAL_COUNTERS.snapshot().get("rollup_skipped_changes", 0)
        cl.execute("DELETE FROM ev WHERE tid = 0")
        cl.execute("UPDATE ev SET v = v + 1 WHERE tid = 1")
        cl.rollup_manager.run_once()
        after = GLOBAL_COUNTERS.snapshot().get("rollup_skipped_changes", 0)
        assert after > before
        # the watermark still advances past the skipped changes
        assert cl.execute("SELECT citus_rollups()").rows[0][6] == 0
    finally:
        cl.close()


# ------------------------------------------------------ routing

def test_dashboard_query_routes_to_rollup(tmp_path):
    cl = make_cluster(tmp_path, rows=400)
    try:
        create_rollup(cl)
        r_roll = cl.execute(DASH_Q)
        assert r_roll.explain.get("strategy") == "rollup"
        assert r_roll.explain.get("rollup") == "ev_r"
        cl.execute("SET citus.enable_rollup_routing = off")
        r_raw = cl.execute(DASH_Q)
        assert (r_raw.explain or {}).get("strategy") != "rollup"
        cl.execute("SET citus.enable_rollup_routing = on")
        assert [r[0] for r in r_roll.rows] == [r[0] for r in r_raw.rows]
        for roll, raw in zip(r_roll.rows, r_raw.rows):
            assert roll[1] == raw[1]                      # count exact
            assert roll[2] == pytest.approx(raw[2])       # sum exact
            # both arms run the same sketch algorithms over the same
            # rows, so approx answers agree exactly too
            assert roll[3] == raw[3]
            assert roll[4] == pytest.approx(raw[4])
    finally:
        cl.close()


def test_where_on_group_cols_and_scalar_shape_route(tmp_path):
    cl = make_cluster(tmp_path, rows=200)
    try:
        create_rollup(cl)
        q = ("SELECT count(*), sum(v), approx_top_k(code, 3) FROM ev "
             "WHERE tid IN (1, 2)")
        r = cl.execute(q)
        assert r.explain.get("strategy") == "rollup"
        n, s = cl.execute("SELECT count(*), sum(v) FROM ev "
                          "WHERE tid IN (1, 2)  -- raw arm\n").rows[0]
        assert (r.rows[0][0], r.rows[0][1]) == (n, pytest.approx(s))
        top = json.loads(r.rows[0][2])
        assert 1 <= len(top) <= 3 and top[0]["count"] >= top[-1]["count"]
    finally:
        cl.close()


def test_non_matching_queries_fall_through(tmp_path):
    cl = make_cluster(tmp_path, rows=100)
    try:
        create_rollup(cl, aggs="count(*), sum(v)")
        for q in [
            "SELECT kind, count(*) FROM ev GROUP BY kind",   # not a group col
            "SELECT tid, max(v) FROM ev GROUP BY tid",       # agg not stored
            "SELECT tid, count(*) FROM ev WHERE v > 5 GROUP BY tid",
            "SELECT tid, count(DISTINCT kind) FROM ev GROUP BY tid",
        ]:
            r = cl.execute(q)
            assert (r.explain or {}).get("strategy") != "rollup", q
    finally:
        cl.close()


def test_explain_shows_rollup_scan(tmp_path):
    cl = make_cluster(tmp_path, rows=50)
    try:
        create_rollup(cl)
        lines = [r[0] for r in cl.execute("EXPLAIN " + DASH_Q).rows]
        assert lines[0].startswith("Rollup Scan on ev_r")
        assert any("Finalize From Stored Sketches" in l for l in lines)
        cl.execute("SET citus.enable_rollup_routing = off")
        lines = [r[0] for r in cl.execute("EXPLAIN " + DASH_Q).rows]
        assert not lines[0].startswith("Rollup Scan"), lines[0]
    finally:
        cl.close()


def test_drop_rollup_restores_raw_plan(tmp_path):
    cl = make_cluster(tmp_path, rows=50)
    try:
        create_rollup(cl, aggs="count(*)")
        q = "SELECT tid, count(*) FROM ev GROUP BY tid"
        assert cl.execute(q).explain.get("strategy") == "rollup"
        cl.execute("SELECT citus_drop_rollup('ev_r')")
        assert not cl.catalog.rollups
        assert not cl.catalog.has_table("ev_r")
        assert (cl.execute(q).explain or {}).get("strategy") != "rollup"
    finally:
        cl.close()


# -------------------------------------------------- t-digest backend

def test_tdigest_percentile_backend(tmp_path):
    cl = make_cluster(tmp_path, rows=400, tenants=2)
    try:
        cl.execute("SET citus.percentile_backend = tdigest")
        assert cl.execute("SHOW citus.percentile_backend").rows[0][0] \
            == "tdigest"
        create_rollup(cl, aggs="count(*), approx_percentile(v)")
        assert cl.execute("SELECT citus_rollups()").rows[0][3] == "tdg"
        word = cl.execute(
            "SELECT apct_v FROM ev_r WHERE tid = 0").rows[0][0]
        assert word.startswith("tdg:")
        # incremental refresh merges t-digests like any other sketch
        ingest(cl, 300, tenants=2, seed=5)
        cl.rollup_manager.run_once()
        est = cl.execute(
            "SELECT approx_percentile(0.5) WITHIN GROUP (ORDER BY v) "
            "FROM ev WHERE tid = 0").rows[0]
        truth = sorted(r[0] for r in cl.execute(
            "SELECT v FROM ev WHERE tid = 0").rows)
        exact = truth[len(truth) // 2]
        # ~2% rank error over uniform[1,100] values: stay within ±10
        assert abs(float(est[0]) - exact) < 10.0, (est, exact)
        with pytest.raises(Exception):
            cl.execute("SET citus.percentile_backend = nope")
    finally:
        cl.close()


# ---------------------------------------- exactly-once kill/restart

_CHILD = r"""
import os, sys
import citus_tpu as ct
from citus_tpu.config import Settings
from citus_tpu.testing.faults import FAULTS
db = sys.argv[1]
FAULTS.arm("rollup_refresh", kill=True)
cl = ct.Cluster(db, settings=Settings(enable_change_data_capture=True,
                                      start_maintenance_daemon=False))
cl.execute("INSERT INTO ev VALUES (1, 'kx', 5.0, 3), (2, 'ky', 6.0, 4), "
           "(1, 'kz', 7.0, 3)")
try:
    cl.rollup_manager.run_once()
except BaseException:
    pass
os._exit(7)  # fault never fired: the parent fails on this exit code
"""


def test_refresh_kill_between_apply_and_watermark_is_exactly_once(tmp_path):
    """Kill the refresh between the delta upsert and the watermark
    commit: recovery must roll BOTH back, and the next refresh replays
    the batch exactly once — no double counting, no gap — landing on
    the raw-scan oracle."""
    cl = make_cluster(tmp_path, rows=120)
    create_rollup(cl, aggs="count(*), sum(v), approx_count_distinct(kind)")
    wm_before = cl.rollup_manager.watermark("ev_r")
    base = {r[0]: (r[1], r[2]) for r in cl.execute(
        "SELECT tid, n_rows, sum_v FROM ev_r").rows}
    cl.close()

    db = str(tmp_path / "db")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", _CHILD, db], env=env,
                       timeout=180, capture_output=True)
    assert r.returncode == 1, (r.returncode, r.stderr[-2000:])

    cl2 = ct.Cluster(db, settings=Settings(enable_change_data_capture=True,
                                           start_maintenance_daemon=False))
    try:
        # the torn transaction rolled back whole: watermark unmoved AND
        # no delta rows leaked into the rollup
        assert cl2.rollup_manager.watermark("ev_r") == wm_before
        after_crash = {r[0]: (r[1], r[2]) for r in cl2.execute(
            "SELECT tid, n_rows, sum_v FROM ev_r").rows}
        assert after_crash == base
        # replay folds the batch exactly once
        cl2.rollup_manager.run_once()
        truth = oracle(cl2)
        got = {r[0]: (r[1], float(r[2])) for r in cl2.execute(
            "SELECT tid, n_rows, sum_v FROM ev_r").rows}
        assert set(got) == set(truth)
        for tid in truth:
            assert got[tid][0] == truth[tid][0]
            assert got[tid][1] == pytest.approx(truth[tid][1])
        # a second refresh is a no-op (idempotent at the head)
        assert cl2.rollup_manager.run_once() == 0
        assert cl2.execute("SELECT citus_rollups()").rows[0][6] == 0
    finally:
        cl2.close()


# ------------------------------------------------ A/B speed (slow)

@pytest.mark.slow
def test_rollup_serves_dashboard_faster_than_raw_scan(tmp_path):
    """Acceptance A/B: the rollup arm answers the dashboard query well
    inside each sketch's error bound of the raw-scan oracle while
    running >=10x faster on a wide source table."""
    cl = make_cluster(tmp_path, rows=50_000, tenants=8)
    try:
        create_rollup(cl)

        def timed(n=5):
            best = float("inf")
            for _ in range(n):
                t0 = time.monotonic()
                res = cl.execute(DASH_Q)
                best = min(best, time.monotonic() - t0)
            return best, res

        cl.execute("SET citus.enable_rollup_routing = off")
        raw_t, raw = timed()
        cl.execute("SET citus.enable_rollup_routing = on")
        roll_t, roll = timed()
        assert roll.explain.get("strategy") == "rollup"
        for a, b in zip(roll.rows, raw.rows):
            assert a[0] == b[0] and a[1] == b[1]
            assert a[2] == pytest.approx(b[2])
            assert abs(a[3] - b[3]) <= max(3, 0.27 * b[3])
            assert a[4] == pytest.approx(b[4], rel=0.06)
        assert roll_t * 10 <= raw_t, (roll_t, raw_t)

        # refresh lag converges after a concurrent ingest burst
        stop = threading.Event()

        def pound():
            s = 100
            while not stop.is_set():
                ingest(cl, 500, tenants=8, seed=s)
                s += 1

        th = threading.Thread(target=pound)
        th.start()
        time.sleep(1.0)
        stop.set()
        th.join()
        cl.execute("SELECT citus_refresh_rollups()")
        assert cl.execute("SELECT citus_rollups()").rows[0][6] == 0
        truth = oracle(cl)
        for tid, n in cl.execute("SELECT tid, n_rows FROM ev_r").rows:
            assert n == truth[tid][0]
    finally:
        cl.close()
