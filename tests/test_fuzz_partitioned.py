"""Differential fuzz: a range-partitioned distributed table must answer
every query exactly like a flat table holding the same rows.

Reference analog: the query-generator harness diffing distributed vs
local execution (src/test/regress/citus_tests/query_generator/) —
here the two sides are the partition-expansion path (parent -> pruned
partitions / UNION ALL) and the ordinary single-table path."""

import random

import numpy as np
import pytest

import citus_tpu as ct

N = 4000


@pytest.fixture(scope="module")
def db(tmp_path_factory):
    cl = ct.Cluster(str(tmp_path_factory.mktemp("pfz")))
    cl.execute("CREATE TABLE flat (k bigint NOT NULL, d date, v bigint, s text)")
    cl.execute("SELECT create_distributed_table('flat', 'k', 4)")
    cl.execute("CREATE TABLE part (k bigint NOT NULL, d date, v bigint, s text) "
               "PARTITION BY RANGE (d)")
    for q, (lo, hi) in enumerate([("2024-01-01", "2024-04-01"),
                                  ("2024-04-01", "2024-07-01"),
                                  ("2024-07-01", "2024-10-01"),
                                  ("2024-10-01", "2025-01-01")]):
        cl.execute(f"CREATE TABLE part_q{q} PARTITION OF part "
                   f"FOR VALUES FROM ('{lo}') TO ('{hi}')")
    cl.execute("SELECT create_distributed_table('part', 'k', 4)")
    rng = np.random.default_rng(42)
    import datetime
    d0 = datetime.date(2024, 1, 1)
    rows = []
    for i in range(N):
        rows.append((
            int(rng.integers(0, 500)),
            (d0 + datetime.timedelta(days=int(rng.integers(0, 366)))).isoformat(),
            int(rng.integers(-100, 100)) if rng.random() > 0.05 else None,
            ["x", "y", "z"][int(rng.integers(0, 3))],
        ))
    cl.copy_from("flat", rows=rows)
    cl.copy_from("part", rows=rows)
    return cl


PREDICATES = [
    "",
    " WHERE d >= date '2024-03-15' AND d < date '2024-05-20'",
    " WHERE d < date '2024-02-01'",
    " WHERE d >= date '2024-11-11'",
    " WHERE v > 0",
    " WHERE v > 0 AND d >= date '2024-06-01'",
    " WHERE s = 'y'",
    " WHERE k = 77",
    " WHERE k = 77 AND d < date '2024-07-01'",
    " WHERE d >= date '2024-01-01' AND d < date '2025-01-01'",
]

SHAPES = [
    "SELECT count(*), sum(v), min(v), max(v) FROM {t}{p}",
    "SELECT s, count(*), sum(v) FROM {t}{p} GROUP BY s ORDER BY s",
    "SELECT count(DISTINCT k) FROM {t}{p}",
    "SELECT avg(v) FROM {t}{p}",
]


@pytest.mark.parametrize("shape", SHAPES)
def test_partitioned_equals_flat(db, shape):
    for p in PREDICATES:
        got = sorted(db.execute(shape.format(t="part", p=p)).rows, key=repr)
        want = sorted(db.execute(shape.format(t="flat", p=p)).rows, key=repr)
        assert got == want, (shape, p)


def test_partitioned_joins_equal_flat(db):
    db.execute("CREATE TABLE dims (k bigint, name text)")
    db.copy_from("dims", rows=[(i, f"n{i % 7}") for i in range(500)])
    got = sorted(db.execute(
        "SELECT dm.name, count(*) FROM part e JOIN dims dm ON e.k = dm.k "
        "WHERE e.d >= date '2024-05-01' GROUP BY dm.name ORDER BY dm.name").rows)
    want = sorted(db.execute(
        "SELECT dm.name, count(*) FROM flat e JOIN dims dm ON e.k = dm.k "
        "WHERE e.d >= date '2024-05-01' GROUP BY dm.name ORDER BY dm.name").rows)
    assert got == want


def test_partitioned_dml_equals_flat(db):
    for t in ("part", "flat"):
        db.execute(f"UPDATE {t} SET v = 0 WHERE v < -50")
        db.execute(f"DELETE FROM {t} WHERE s = 'z' AND d < date '2024-03-01'")
    got = db.execute("SELECT count(*), sum(v) FROM part").rows
    want = db.execute("SELECT count(*), sum(v) FROM flat").rows
    assert got == want


def test_vacuum_parent_fans_out(db):
    db.execute("DELETE FROM part WHERE v = 7")
    r = db.execute("VACUUM part")
    assert r.explain.get("placements_rewritten", 0) > 0
    # still query-consistent after the rewrite
    got = db.execute("SELECT count(*), sum(v) FROM part").rows
    want = db.execute("SELECT count(*), sum(v) FROM flat WHERE v != 7 "
                      "OR v IS NULL").rows
    assert got == want


def test_legacy_catalog_document_loads(tmp_path):
    """Forward compatibility: a round-3-shaped document (no indexes,
    partition keys, or breadth sections) loads with defaults — the
    upgrade-test analog (src/test/regress/citus_tests/upgrade/)."""
    import json
    import os
    cl = ct.Cluster(str(tmp_path / "old"))
    cl.execute("CREATE TABLE t (k bigint, v bigint)")
    cl.copy_from("t", rows=[(1, 2)])
    doc = cl.catalog.export_document()
    # strip every round-4 section/field, as a round-3 file would look
    for sec in ("extensions", "domains", "collations", "publications",
                "statistics", "domain_columns"):
        doc.pop(sec, None)
    for td in doc["tables"]:
        td.pop("indexes", None)
        td.pop("partition_by", None)
        td.pop("partition_of", None)
    cl.close()
    with open(os.path.join(str(tmp_path / "old"), "catalog.json"), "w") as fh:
        json.dump(doc, fh)
    cl2 = ct.Cluster(str(tmp_path / "old"))
    t = cl2.catalog.table("t")
    assert t.indexes == [] and not t.is_partitioned
    assert cl2.execute("SELECT v FROM t WHERE k = 1").rows == [(2,)]
    cl2.execute("CREATE INDEX t_v ON t (v)")  # new features work on it
    assert cl2.execute("SELECT count(*) FROM t WHERE v = 2").rows == [(1,)]
    cl2.close()