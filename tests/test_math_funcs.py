"""Scalar math function surface vs the sqlite oracle.

Reference: PostgreSQL's float.c / numeric.c math functions, which the
reference pushes down to workers unchanged inside shard queries.  Here
they lower to elementwise xp ops shared by the numpy and jitted device
paths (planner/bound.py BMathFunc); floor/ceil/round/trunc stay exact on
the decimal scaled-int representation.
"""

import sqlite3

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import ExecutorSettings, settings_override

N = 2000


@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    cl = ct.Cluster(str(tmp_path_factory.mktemp("db")))
    cl.execute("""CREATE TABLE m (
        id bigint NOT NULL, n bigint, q decimal(12,3), x double, s text)""")
    cl.execute("SELECT create_distributed_table('m', 'id', 4)")
    rng = np.random.default_rng(7)
    words = ["alpha", "beta", "gamma", "delta", None]
    rows = []
    for i in range(N):
        rows.append((
            i,
            int(rng.integers(-50, 50)) if rng.random() > 0.05 else None,
            round(float(rng.integers(-100000, 100000)) / 1000, 3)
            if rng.random() > 0.1 else None,
            float(np.round(rng.normal(0, 40), 6)),
            words[int(rng.integers(0, 5))],
        ))
    cl.copy_from("m", rows=rows)

    sq = sqlite3.connect(":memory:")
    sq.execute("CREATE TABLE m (id INTEGER, n INTEGER, q REAL, x REAL, s TEXT)")
    sq.executemany("INSERT INTO m VALUES (?,?,?,?,?)", rows)
    _ensure_math_funcs(sq)
    return cl, sq


def _ensure_math_funcs(sq):
    """Older sqlite builds lack SQLITE_ENABLE_MATH_FUNCTIONS; register
    equivalents so the oracle still answers (NULL on NULL input or
    domain error, matching sqlite's native behavior)."""
    try:
        sq.execute("SELECT floor(1.5)")
        return
    except sqlite3.OperationalError:
        pass
    import math

    def _f(fn):
        def g(*a):
            if any(v is None for v in a):
                return None
            try:
                return fn(*a)
            except ValueError:
                return None
        return g

    for name, nargs, fn in [
        ("floor", 1, math.floor), ("ceil", 1, math.ceil),
        ("sqrt", 1, math.sqrt), ("ln", 1, math.log),
        ("exp", 1, math.exp), ("power", 2, math.pow),
        ("mod", 2, math.fmod),
        ("sign", 1, lambda v: (v > 0) - (v < 0)),
    ]:
        sq.create_function(name, nargs, _f(fn))


QUERIES = [
    "SELECT sum(floor(q)), sum(ceil(q)) FROM m",
    "SELECT sum(round(q)), sum(round(q, 1)), sum(round(q, 2)) FROM m",
    "SELECT count(*) FROM m WHERE floor(x) = 3",
    "SELECT sum(sign(q)), sum(sign(n)), sum(sign(x)) FROM m",
    "SELECT sum(mod(n, 7)) FROM m",
    "SELECT avg(sqrt(x)) FROM m WHERE x > 0",
    "SELECT count(*) FROM m WHERE sqrt(x) > 5",
    "SELECT avg(ln(x)) FROM m WHERE x > 1",
    "SELECT avg(exp(x / 100)) FROM m",
    "SELECT avg(power(x, 2)) FROM m",
    # sqlite spells NULL-ignoring greatest/least via coalesce+max/min
    "SELECT sum(max(coalesce(n, 0), 0)), sum(min(coalesce(n, 0), 0)) FROM m",
    "SELECT count(*) FROM m WHERE max(coalesce(q, x), x) > 10",
    "SELECT s, count(*) FROM m WHERE instr(s, 'a') > 0 GROUP BY s ORDER BY s",
    "SELECT floor(q), count(*) FROM m WHERE q BETWEEN -5 AND 5 "
    "GROUP BY floor(q) ORDER BY floor(q)",
    "SELECT sum(abs(round(x, 3))) FROM m",
]


def canon(rows):
    out = []
    for r in rows:
        row = []
        for v in r:
            if isinstance(v, float) or str(type(v).__name__) == "Decimal":
                row.append(round(float(v), 4))
            else:
                row.append(v)
        out.append(tuple(row))
    return out


def _to_ours(sql):
    # sqlite spells strpos() as instr(), greatest/least as scalar max/min
    return (sql.replace("instr(s, 'a')", "strpos(s, 'a')")
            .replace("max(coalesce(n, 0), 0)", "greatest(n, 0)")
            .replace("min(coalesce(n, 0), 0)", "least(n, 0)")
            .replace("max(coalesce(q, x), x)", "greatest(q, x)"))


@pytest.mark.parametrize("sql", QUERIES)
def test_vs_sqlite(loaded, sql):
    cl, sq = loaded
    ours = canon(cl.execute(_to_ours(sql)).rows)
    theirs = canon(sq.execute(sql).fetchall())
    if "ORDER BY" not in sql:
        ours, theirs = sorted(ours, key=repr), sorted(theirs, key=repr)
    flat_o = [v for r in ours for v in r]
    flat_t = [v for r in theirs for v in r]
    assert flat_o == pytest.approx(flat_t, rel=1e-6, abs=1e-4)


@pytest.mark.parametrize("sql", QUERIES)
def test_jax_vs_cpu_identical(loaded, sql):
    cl, _ = loaded
    sql = _to_ours(sql)
    jax_rows = cl.execute(sql).rows
    with settings_override(executor=ExecutorSettings(task_executor_backend="cpu")):
        cpu_rows = cl.execute(sql).rows
    assert jax_rows == cpu_rows


def test_scalar_forms(tmp_cluster):
    cl = tmp_cluster
    cl.execute("CREATE TABLE t1 (a bigint NOT NULL, q decimal(10,2))")
    cl.execute("SELECT create_distributed_table('t1', 'a', 2)")
    cl.copy_from("t1", rows=[(1, 2.25), (2, -2.25), (3, None)])
    r = cl.execute(
        "SELECT a, round(q, 1), floor(q), ceil(q), trunc(q) FROM t1 ORDER BY a").rows
    import decimal
    assert r[0][1:] == (decimal.Decimal("2.3"), decimal.Decimal("2"),
                        decimal.Decimal("3"), decimal.Decimal("2"))
    # round half away from zero, floor toward -inf, trunc toward zero
    assert r[1][1:] == (decimal.Decimal("-2.3"), decimal.Decimal("-3"),
                        decimal.Decimal("-2"), decimal.Decimal("-2"))
    assert r[2][1:] == (None, None, None, None)
    # domain violations produce NULL, not errors
    r = cl.execute("SELECT sqrt(a - 2), ln(a - 2) FROM t1 WHERE a = 1").rows
    assert r == [(None, None)]
    # position() special form and log spellings
    r = cl.execute("SELECT power(2, 10), log(100), log(2, 8), pi() FROM t1 WHERE a = 1").rows
    assert r[0][0] == 1024.0
    assert r[0][1] == pytest.approx(2.0)
    assert r[0][2] == pytest.approx(3.0)
    assert r[0][3] == pytest.approx(3.14159265)
