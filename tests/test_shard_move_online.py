"""Non-blocking elasticity: CDC-catch-up shard moves/splits under live
writes, with fault-injected crash recovery (reference: the 13-step
non-blocking move of shard_transfer.c / NonBlockingShardSplit, SURVEY
§3.6).  Covers the writer-availability contract (zero failed writes
during a background move, blocked-write window << total move time),
kill-mid-move recovery at every phase (copy / catchup / flip), cleaner
crash-adoption via the operation registry, and the two copy-path
regressions: torn deletes-bitmap copies and stale partial stripes."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import citus_tpu as ct
from citus_tpu.config import Settings
from citus_tpu.testing.faults import FAULTS


@pytest.fixture(autouse=True)
def _disarm():
    yield
    FAULTS.disarm()


def make_cluster(tmp_path, nodes=2, rows=4000, cdc=True, daemon=True):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=nodes,
                    settings=Settings(enable_change_data_capture=cdc,
                                      start_maintenance_daemon=daemon))
    cl.execute("CREATE TABLE t (k bigint NOT NULL, v bigint)")
    cl.execute("SELECT create_distributed_table('t', 'k', 4)")
    cl.copy_from("t", columns={"k": np.arange(rows, dtype=np.int64),
                               "v": np.arange(rows, dtype=np.int64) % 97})
    return cl


def _move_args(cl):
    shard = cl.catalog.table("t").shards[0]
    src = shard.placements[0]
    return shard.shard_id, src, 1 - src if src in (0, 1) else 0


# ------------------------------------------------- writer availability

def test_writer_hammer_during_background_move(tmp_path):
    """The headline availability contract: N writer threads hammer the
    table for the whole duration of a background shard move — zero
    failed writes, every row readable after the flip, and the
    blocked-write window (the only stretch writers are excluded) is a
    fraction of the total move time."""
    cl = make_cluster(tmp_path)
    try:
        sid, src, dst = _move_args(cl)
        # slow the bulk copy pass only (times=1) so writers demonstrably
        # overlap the move; catch-up and flip passes run at full speed
        FAULTS.arm("shard_move_copy", delay_s=0.4, times=1)
        jid = cl.background_jobs.create_job("online move")
        cl.background_jobs.add_task(
            jid, "move_shard", {"shard_id": sid, "source": src,
                                "target": dst})
        stop = threading.Event()
        wrote, failures = [], []

        def hammer(base):
            i = 0
            while not stop.is_set():
                k = base + i * 8
                try:
                    cl.execute(f"INSERT INTO t VALUES ({k}, {k % 97})")
                    wrote.append(k)
                except Exception as e:  # any failed write breaks the contract
                    failures.append(e)
                i += 1

        threads = [threading.Thread(target=hammer, args=(100000 + n,))
                   for n in range(4)]
        for th in threads:
            th.start()
        status = cl.background_jobs.wait_for_job(jid)
        stop.set()
        for th in threads:
            th.join()
        assert status == "done"
        assert not failures, failures[:3]
        assert wrote, "hammer never ran"
        cl._plan_cache.clear()
        assert cl.catalog.table("t").shards[0].placements == [dst]
        assert cl.execute("SELECT count(*) FROM t").rows[0][0] == \
            4000 + len(wrote)
        # per-move stats: catch-up ran, and the blocked window is a
        # fraction of the total (the bulk pass alone took >= 400 ms)
        r = cl.execute("SELECT citus_shard_move_stats()")
        d = [dict(zip(r.columns, row)) for row in r.rows
             if row[0] == "move" and row[1] == sid][-1]
        assert d["catchup_rounds"] >= 1
        assert d["total_ms"] >= 400
        assert d["blocked_write_ms"] < 0.5 * d["total_ms"]
        snap = cl.counters.snapshot()
        assert snap.get("shard_move_catchup_rounds", 0) >= 1
        assert snap.get("shard_move_blocked_write_ms", 0) >= 0
    finally:
        cl.close()


def test_concurrent_deletes_during_move(tmp_path):
    """Regression (torn deletes-bitmap copy): DELETEs mutate the
    placement's bitmap file in place while the move's copy passes run;
    the snapshot-under-delete-lock copy must never ship a torn bitmap,
    and no delete may be lost across the flip."""
    cl = make_cluster(tmp_path)
    try:
        sid, src, dst = _move_args(cl)
        FAULTS.arm("shard_move_copy", delay_s=0.1)
        jid = cl.background_jobs.create_job("move under deletes")
        cl.background_jobs.add_task(
            jid, "move_shard", {"shard_id": sid, "source": src,
                                "target": dst})
        stop = threading.Event()
        deleted, failures = [], []

        def deleter(base):
            k = base
            while not stop.is_set() and k < base + 400:
                try:
                    cl.execute(f"DELETE FROM t WHERE k = {k}")
                    deleted.append(k)
                except Exception as e:
                    failures.append(e)
                k += 1

        threads = [threading.Thread(target=deleter, args=(n * 400,))
                   for n in range(2)]
        for th in threads:
            th.start()
        status = cl.background_jobs.wait_for_job(jid)
        stop.set()
        for th in threads:
            th.join()
        assert status == "done"
        assert not failures, failures[:3]
        assert deleted
        cl._plan_cache.clear()
        # every delete that committed is still deleted after the flip
        assert cl.execute("SELECT count(*) FROM t").rows[0][0] == \
            4000 - len(deleted)
        # the shipped bitmap file is valid JSON (not torn mid-write)
        from citus_tpu.storage.deletes import DELETES_FILE
        moved = cl.catalog.shard_dir("t", sid, dst)
        p = os.path.join(moved, DELETES_FILE)
        if os.path.exists(p):
            with open(p) as fh:
                json.load(fh)
    finally:
        cl.close()


def test_split_under_live_writes_and_deletes(tmp_path):
    """Shard split takes the same non-blocking path: writers keep
    writing through the redistribute, catch-up rounds route the new
    stripes, and a DELETE against an already-routed stripe forces the
    dirty restart — the final table is exact either way."""
    cl = make_cluster(tmp_path)
    try:
        t = cl.catalog.table("t")
        shard = t.shards[0]
        mid = (shard.hash_min + shard.hash_max) // 2
        FAULTS.arm("shard_move_copy", delay_s=0.15, match="split:")
        stop = threading.Event()
        wrote, deleted, failures = [], [], []

        def writer():
            i = 0
            while not stop.is_set():
                k = 200000 + i * 2
                try:
                    cl.execute(f"INSERT INTO t VALUES ({k}, 1)")
                    wrote.append(k)
                    if i % 7 == 0:
                        cl.execute(f"DELETE FROM t WHERE k = {i * 3}")
                        deleted.append(i * 3)
                except Exception as e:
                    failures.append(e)
                i += 1

        th = threading.Thread(target=writer)
        th.start()
        r = cl.execute("SELECT citus_split_shard_by_split_points("
                       f"{shard.shard_id}, {mid})")
        stop.set()
        th.join()
        assert not failures, failures[:3]
        assert r.rowcount == 2
        cl._plan_cache.clear()
        assert cl.catalog.table("t").shard_count == 5
        expect = 4000 + len(wrote) - len(set(d for d in deleted if d < 4000))
        assert cl.execute("SELECT count(*) FROM t").rows[0][0] == expect
    finally:
        cl.close()


# --------------------------------------------- kill-mid-move recovery

_CHILD = r"""
import os, sys
import citus_tpu as ct
from citus_tpu.testing.faults import FAULTS
point, db = sys.argv[1], sys.argv[2]
FAULTS.arm(point, kill=True)
from citus_tpu.config import Settings
cl = ct.Cluster(db, settings=Settings(start_maintenance_daemon=False))
sid, src, dst = [int(a) for a in sys.argv[3:6]]
try:
    cl.execute(f"SELECT citus_move_shard_placement({sid}, {src}, {dst})")
except BaseException:
    pass
os._exit(7)  # fault never fired: the parent fails on this exit code
"""


@pytest.mark.parametrize("point", ["shard_move_copy", "shard_move_catchup",
                                   "shard_move_flip"])
def test_kill_mid_move_leaves_source_serving(tmp_path, point):
    """A mover killed at any phase (bulk copy, catch-up round, inside
    the locked flip window before the commit) leaves the cluster
    serving reads AND writes from the source placement, and the next
    cleaner pass adopts the dead operation via the registry and drops
    the orphaned target."""
    cl = make_cluster(tmp_path, rows=2000, daemon=False)
    sid, src, dst = _move_args(cl)
    cl.close()
    db = str(tmp_path / "db")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-c", _CHILD, point, db,
         str(sid), str(src), str(dst)],
        env=env, timeout=120, capture_output=True)
    assert r.returncode == 1, (point, r.returncode, r.stderr[-2000:])

    cl2 = ct.Cluster(db, settings=Settings(start_maintenance_daemon=False))
    try:
        # catalog never flipped: the source still owns the placement
        assert cl2.catalog.table("t").shards[0].placements == [src]
        # reads and writes keep working from the source
        assert cl2.execute("SELECT count(*) FROM t").rows[0][0] == 2000
        cl2.execute("INSERT INTO t VALUES (500000, 1)")
        assert cl2.execute("SELECT count(*) FROM t").rows[0][0] == 2001
        # the next maintenance pass adopts the dead mover's records
        # (registry pid is gone) and drops the orphaned target dir
        from citus_tpu.operations import (
            operations_view, pending_cleanup, try_drop_orphaned_resources,
        )
        tgt = cl2.catalog.shard_dir("t", sid, dst)
        had_target = os.path.isdir(tgt)
        try_drop_orphaned_resources(cl2.catalog)
        assert not os.path.isdir(tgt)
        if point != "shard_move_copy":
            # the kill struck after the bulk copy, so the orphan existed
            assert had_target
        # nothing op-gated left parked; the dead registry row is retired
        assert all(r["policy"] not in ("on_failure", "on_success")
                   for r in pending_cleanup(cl2.catalog))
        assert operations_view(cl2.catalog) == {}
        # a re-run of the same move now succeeds end to end
        cl2.execute(f"SELECT citus_move_shard_placement({sid}, {src}, {dst})")
        cl2._plan_cache.clear()
        assert cl2.catalog.table("t").shards[0].placements == [dst]
        assert cl2.execute("SELECT count(*) FROM t").rows[0][0] == 2001
    finally:
        cl2.close()


# ------------------------------------------------ cleaner crash-adoption

def test_cleaner_adopts_crashed_operation_exactly_once(tmp_path):
    """An operation killed between record_cleanup(ON_FAILURE) and
    complete_operation is adopted by the next pass via the operation
    registry (its pid is dead), and two concurrent cleaners drop the
    orphan exactly once (the cross-process cleanup lock serializes the
    passes)."""
    from citus_tpu.operations.cleaner import (
        ON_FAILURE, ON_SUCCESS, operations_view, pending_cleanup,
        record_cleanup, register_operation, try_drop_orphaned_resources,
    )
    cl = make_cluster(tmp_path, rows=100, daemon=False)
    try:
        # a pid that is certainly dead (the subprocess already exited)
        dead_proc = subprocess.Popen([sys.executable, "-c", "pass"])
        dead_proc.wait()
        dead_pid = dead_proc
        orphan = str(tmp_path / "db" / "data" / "t" / "shard_9999"
                     / "placement_1")
        os.makedirs(orphan)
        with open(os.path.join(orphan, "junk.cts"), "w") as fh:
            fh.write("half-copied")
        register_operation(cl.catalog, 4242, kind="move_shard",
                          pid=dead_pid.pid)
        record_cleanup(cl.catalog, orphan, ON_FAILURE, operation_id=4242)
        results = []
        barrier = threading.Barrier(2)

        def pass_(n):
            barrier.wait()
            results.append(try_drop_orphaned_resources(cl.catalog))

        ts = [threading.Thread(target=pass_, args=(n,)) for n in range(2)]
        for th in ts:
            th.start()
        for th in ts:
            th.join()
        assert sum(results) == 1  # dropped exactly once across both
        assert not os.path.isdir(orphan)
        assert pending_cleanup(cl.catalog) == []
        assert operations_view(cl.catalog) == {}

        # arbitration keeps resources the committed catalog promoted:
        # an ON_FAILURE record for a LIVE placement (the flip landed an
        # instant before the kill) must survive adoption, and an
        # ON_SUCCESS record for a live placement (the flip never
        # landed) must too
        shard = cl.catalog.table("t").shards[0]
        live = cl.catalog.shard_dir("t", shard.shard_id,
                                    shard.placements[0])
        assert os.path.isdir(live)
        register_operation(cl.catalog, 4343, pid=dead_pid.pid)
        record_cleanup(cl.catalog, live, ON_FAILURE, operation_id=4343)
        register_operation(cl.catalog, 4444, pid=dead_pid.pid)
        record_cleanup(cl.catalog, live, ON_SUCCESS, operation_id=4444)
        try_drop_orphaned_resources(cl.catalog)
        assert os.path.isdir(live)  # promoted by the committed catalog
        assert pending_cleanup(cl.catalog) == []
        # a LIVE op's records are never adopted, dead dirs or not
        register_operation(cl.catalog, 4545)  # this process: alive
        record_cleanup(cl.catalog, str(tmp_path / "inflight"), ON_FAILURE,
                       operation_id=4545)
        assert try_drop_orphaned_resources(cl.catalog) == 0
        assert len(pending_cleanup(cl.catalog)) == 1
    finally:
        cl.close()


# -------------------------------------------------- copy-path regressions

def test_stale_partial_stripe_recopied(tmp_path):
    """Regression: a stripe truncated by a killed earlier pass exists at
    the target with the right name but the wrong size — the copy loop
    must re-ship it (size-verified skip), not silently keep it."""
    cl = make_cluster(tmp_path, rows=3000, cdc=False, daemon=False)
    try:
        before = cl.execute("SELECT count(*), sum(v) FROM t").rows
        sid, src, dst = _move_args(cl)
        src_dir = cl.catalog.shard_dir("t", sid, src)
        dst_dir = cl.catalog.shard_dir("t", sid, dst)
        stripes = sorted(n for n in os.listdir(src_dir)
                         if n.endswith(".cts"))
        assert stripes
        os.makedirs(dst_dir)
        with open(os.path.join(src_dir, stripes[0]), "rb") as fh:
            data = fh.read()
        with open(os.path.join(dst_dir, stripes[0]), "wb") as fh:
            fh.write(data[:len(data) // 2])  # the killed pass's leftover
        cl.execute(f"SELECT citus_move_shard_placement({sid}, {src}, {dst})")
        cl._plan_cache.clear()
        assert os.path.getsize(os.path.join(dst_dir, stripes[0])) == len(data)
        assert cl.execute("SELECT count(*), sum(v) FROM t").rows == before
    finally:
        cl.close()


# ------------------------------------------------------- GUCs and stats

def test_shard_move_gucs_roundtrip(tmp_path):
    cl = ct.Cluster(str(tmp_path / "db"), n_nodes=2)
    try:
        for guc, val, shown in [
                ("citus.shard_move_catchup_threshold", "3", "3"),
                ("citus.shard_move_max_catchup_rounds", "7", "7"),
                ("citus.defer_drop_after_shard_move", "off", "off")]:
            cl.execute(f"SET {guc} = {val}")
            assert cl.execute(f"SHOW {guc}").rows[0][0] == shown
        assert cl.settings.sharding.shard_move_catchup_threshold == 3
        assert cl.settings.sharding.shard_move_max_catchup_rounds == 7
        assert cl.settings.sharding.defer_drop_after_shard_move is False
    finally:
        cl.close()


def test_inline_drop_when_defer_disabled(tmp_path):
    """citus.defer_drop_after_shard_move=off drops the source placement
    inside the move instead of waiting for the next cleaner pass."""
    cl = make_cluster(tmp_path, rows=500, cdc=False, daemon=False)
    try:
        cl.execute("SET citus.defer_drop_after_shard_move = off")
        sid, src, dst = _move_args(cl)
        cl.execute(f"SELECT citus_move_shard_placement({sid}, {src}, {dst})")
        cl._plan_cache.clear()
        assert not os.path.isdir(cl.catalog.shard_dir("t", sid, src))
        assert cl.execute("SELECT count(*) FROM t").rows[0][0] == 500
    finally:
        cl.close()


def test_move_stats_view_and_split_row(tmp_path):
    cl = make_cluster(tmp_path, rows=500, cdc=False, daemon=False)
    try:
        sid, src, dst = _move_args(cl)
        cl.execute(f"SELECT citus_move_shard_placement({sid}, {src}, {dst})")
        t = cl.catalog.table("t")
        shard = t.shards[1]
        mid = (shard.hash_min + shard.hash_max) // 2
        cl.execute("SELECT citus_split_shard_by_split_points("
                   f"{shard.shard_id}, {mid})")
        r = cl.execute("SELECT citus_shard_move_stats()")
        assert r.columns == ["op", "shard_id", "source", "target",
                             "bytes_copied", "catchup_rounds",
                             "blocked_write_ms", "total_ms"]
        ops = {row[0] for row in r.rows}
        assert {"move", "split"} <= ops
        for row in r.rows:
            d = dict(zip(r.columns, row))
            assert d["blocked_write_ms"] >= 0
            assert d["total_ms"] >= d["blocked_write_ms"]
            assert d["catchup_rounds"] >= 1
    finally:
        cl.close()
