"""CDC stream hygiene: seek-reads and ack-based rotation.

Reference: a logical replication slot's confirmed_flush position —
resuming a consumer never rescans acknowledged history, and
acknowledged WAL is recycled (cdc/cdc_decoder.c rides real slots)."""

import os

import pytest

import citus_tpu as ct
from citus_tpu.cdc import ChangeDataCapture


@pytest.fixture()
def stream(tmp_path):
    cdc = ChangeDataCapture(str(tmp_path), enabled=True)
    for i in range(2000):
        cdc.emit("t", "insert", lsn=1000 + i,
                 rows=[[i, f"value-{i}"]], columns=["k", "v"])
    return cdc


def test_events_from_lsn_is_o_new_records(stream):
    total = os.path.getsize(stream._path("t"))
    stream.bytes_read = 0
    tail = list(stream.events("t", from_lsn=1000 + 1990))
    assert [r["lsn"] for r in tail] == list(range(2991, 3000))
    # seek-read bound: at most two index strides of history plus the
    # actual tail — constant in stream length, not O(history)
    from citus_tpu.cdc import INDEX_STRIDE_BYTES
    assert stream.bytes_read < 2 * INDEX_STRIDE_BYTES + 4096, \
        f"read {stream.bytes_read} of {total} bytes"
    assert stream.bytes_read < total / 4


def test_full_scan_still_complete(stream):
    assert len(list(stream.events("t"))) == 2000
    assert len(list(stream.events("t", from_lsn=0))) == 2000


def test_last_lsn_is_tail_read(stream):
    total = os.path.getsize(stream._path("t"))
    stream.bytes_read = 0
    assert stream.last_lsn("t") == 2999
    assert stream.bytes_read <= (1 << 16)
    assert stream.bytes_read < total


def test_acknowledge_rotates_and_resumes(stream):
    p = stream._path("t")
    size_before = os.path.getsize(p)
    dropped = stream.acknowledge("t", upto_lsn=1000 + 1499)
    assert dropped == 1500
    assert os.path.getsize(p) < size_before / 2
    assert stream.acknowledged_lsn("t") == 2499
    remaining = list(stream.events("t"))
    assert [r["lsn"] for r in remaining] == list(range(2500, 3000))
    # seek within the rotated stream still works
    assert [r["lsn"] for r in stream.events("t", from_lsn=2990)] == \
        list(range(2991, 3000))
    # appends continue after rotation
    stream.emit("t", "delete", lsn=5000, count=3)
    assert stream.last_lsn("t") == 5000
    assert stream.acknowledge("t", upto_lsn=100) == 0  # nothing older


def test_last_lsn_with_oversized_record(tmp_path):
    cdc = ChangeDataCapture(str(tmp_path), enabled=True)
    big = [[i, "x" * 100] for i in range(2000)]  # ~200KB single record
    cdc.emit("t", "insert", lsn=77, rows=big, columns=["k", "v"])
    assert cdc.last_lsn("t") == 77


def test_ack_position_is_monotonic_without_truncation(tmp_path):
    cdc = ChangeDataCapture(str(tmp_path), enabled=True)
    cdc.emit("u", "insert", lsn=5, count=1)
    assert cdc.acknowledge("u", 5) == 1
    assert cdc.acknowledge("u", 10) == 0  # nothing to drop...
    assert cdc.acknowledged_lsn("u") == 10  # ...but the position advances


def test_partition_parent_writes_are_atomic(tmp_path):
    """A unique violation in the second partition must roll back the
    first partition's rows (PostgreSQL inserts nothing)."""
    from citus_tpu.integrity import UniqueViolation
    cl = ct.Cluster(str(tmp_path / "dbp"))
    cl.execute("CREATE TABLE e (ts date PRIMARY KEY, v bigint) "
               "PARTITION BY RANGE (ts)")
    cl.execute("CREATE TABLE e_a PARTITION OF e "
               "FOR VALUES FROM ('2024-01-01') TO ('2024-02-01')")
    cl.execute("CREATE TABLE e_b PARTITION OF e "
               "FOR VALUES FROM ('2024-02-01') TO ('2024-03-01')")
    cl.copy_from("e", rows=[("2024-02-10", 1)])
    with pytest.raises(UniqueViolation):
        cl.copy_from("e", rows=[("2024-01-05", 2),      # would land in e_a
                                ("2024-02-10", 3)])     # duplicate in e_b
    assert cl.execute("SELECT count(*) FROM e").rows == [(1,)]
    assert cl.execute("SELECT count(*) FROM e_a").rows == [(0,)]


def test_join_with_predicate_on_other_table(tmp_path):
    """The pushed-down arm WHERE must not swallow predicates that
    reference a join partner."""
    cl = ct.Cluster(str(tmp_path / "dbj"))
    cl.execute("CREATE TABLE ev (tenant bigint, ts date, v bigint) "
               "PARTITION BY RANGE (ts)")
    cl.execute("CREATE TABLE ev_a PARTITION OF ev "
               "FOR VALUES FROM ('2024-01-01') TO ('2024-02-01')")
    cl.execute("CREATE TABLE ev_b PARTITION OF ev "
               "FOR VALUES FROM ('2024-02-01') TO ('2024-03-01')")
    cl.copy_from("ev", rows=[(1, "2024-01-10", 5), (2, "2024-02-10", 7)])
    cl.execute("CREATE TABLE tn (tenant bigint, name text)")
    cl.copy_from("tn", rows=[(1, "alpha"), (2, "beta")])
    r = cl.execute("SELECT count(*) FROM ev e JOIN tn t "
                   "ON e.tenant = t.tenant WHERE t.name = 'beta'")
    assert r.rows == [(1,)]


def test_partition_by_validation_is_atomic(tmp_path):
    cl = ct.Cluster(str(tmp_path / "dbv"))
    with pytest.raises(Exception):
        cl.execute("CREATE TABLE bad (k bigint PRIMARY KEY, ts date) "
                   "PARTITION BY RANGE (ts)")
    assert not cl.catalog.has_table("bad")
    # retry with a corrected definition succeeds
    cl.execute("CREATE TABLE bad (k bigint, ts date PRIMARY KEY) "
               "PARTITION BY RANGE (ts)")
    assert cl.catalog.table("bad").is_partitioned


def test_cluster_surface_uses_hygiene(tmp_path):
    from citus_tpu.config import Settings
    cl = ct.Cluster(str(tmp_path / "db"),
                    settings=Settings(enable_change_data_capture=True))
    cl.execute("CREATE TABLE t (k bigint, v bigint)")
    for i in range(50):
        cl.copy_from("t", rows=[(i, i)])
    last = cl.cdc.last_lsn("t")
    assert last > 0
    assert cl.cdc.acknowledge("t", last) == 50
    assert list(cl.cdc.events("t")) == []
    cl.copy_from("t", rows=[(99, 99)])
    assert len(list(cl.cdc.events("t", from_lsn=last))) == 1