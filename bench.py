#!/usr/bin/env python
"""Benchmark: TPC-H Q1 + Q6 + repartition join on columnar lineitem.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
The headline metric stays Q1 rows scanned/sec/chip; "extra" carries Q6
(BASELINE config 1) and the repartition-join rate (config 5, exercising
parallel/shuffle.py's build_repartition_join).

Baseline (BASELINE.md): the reference's columnar scan + GROUP BY SUM runs
75 M rows in 16 s on its microbench box = 4.6875 M rows/s.  vs_baseline
is our warm Q1 rows/s divided by that.  The join compares against the
reference's ~10 M rows/s repartition INSERT..SELECT throughput
(distributed/README.md:1761).

Data persists in .bench_data/ across runs (ingest is skipped when the
table already exists at the right scale).

BENCH_SWEEP=1 additionally measures Q1 at 2x and 4x the configured row
count (the throughput-vs-size curve past the HBM batch cache; the
streaming pipeline should degrade smoothly, not collapse) and reports it
under "sweep".
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

_HERE = os.path.dirname(os.path.abspath(__file__))
LAST_GOOD = os.path.join(_HERE, ".bench_last_good.json")

BASELINE_ROWS_PER_SEC = 75_000_000 / 16.0
# 24M rows ≈ TPC-H SF4 lineitem: large enough to amortize the ~80 ms
# axon-tunnel round trip (6M rows measured 12.3x baseline, 24M 53.9x)
# while the working set still fits the 6 GB HBM batch cache (48M rows
# spills it and collapses to re-streaming through the tunnel).
N_ROWS = int(os.environ.get("BENCH_ROWS", 24_000_000))
SHARDS = 8
# BENCH_PLATFORM=cpu pins JAX to the host backend (the axon PJRT plugin
# otherwise overrides JAX_PLATFORMS); unset = real TPU via the tunnel
PLATFORM = os.environ.get("BENCH_PLATFORM")

Q1 = """SELECT l_returnflag, l_linestatus,
  sum(l_quantity) AS sum_qty,
  sum(l_extendedprice) AS sum_base_price,
  sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
  sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
  avg(l_quantity) AS avg_qty, avg(l_extendedprice) AS avg_price,
  avg(l_discount) AS avg_disc, count(*) AS count_order
FROM lineitem WHERE l_shipdate <= date '1998-12-01' - interval '90' day
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus"""

Q6 = """SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= date '1994-01-01'
  AND l_shipdate < date '1995-01-01'
  AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24"""

# config 5: equi-join on the NON-distribution key of the probe side —
# forces the repartition (all_to_all) path; orders_b is distributed on
# o_custkey, lineitem on l_orderkey
QJOIN = """SELECT count(*), sum(l.l_quantity)
FROM lineitem l JOIN orders_b o ON l.l_orderkey = o.o_orderkey
WHERE o.o_flag = 'H'"""

#: reference repartition INSERT..SELECT throughput (README:1761)
JOIN_BASELINE_ROWS_PER_SEC = 10_000_000.0

#: device-side bytes Q1 processes per row: scanned columns' device
#: dtypes (l_returnflag/l_linestatus/l_shipdate int32; l_quantity/
#: l_extendedprice/l_discount/l_tax int64 scaled decimals) plus one
#: validity byte per column — the numerator of the roofline fraction
Q1_BYTES_PER_ROW = 3 * 4 + 4 * 8 + 7

#: HBM peak bandwidth by device kind (GB/s; public chip specs) — the
#: denominator of the roofline fraction BASELINE.md's north star asks
#: for.  A scan→filter→partial-agg pipeline is bandwidth-bound, so
#: bytes-scanned/s over HBM peak is the scan analog of MFU.
HBM_PEAK_GBPS = {
    "v2": 700.0, "v3": 900.0, "v4": 1228.0,
    "v5e": 819.0, "v5 lite": 819.0, "v5p": 2765.0,
    "v6e": 1640.0, "v6 lite": 1640.0,
}


def _hbm_peak_for(device_kind: str):
    dk = device_kind.lower()
    for key in sorted(HBM_PEAK_GBPS, key=len, reverse=True):
        if key in dk:
            return HBM_PEAK_GBPS[key] * 1e9
    return None


def bench_concurrency(cl, extra: dict) -> None:
    """N parallel clients through the admission pool (VERDICT #9): the
    citus.max_shared_pool_size machinery has to be shown under load.
    Mixed Q1/Q6 stream; reports queries/s and latency percentiles."""
    import threading
    n_clients = int(os.environ.get("BENCH_CLIENTS", "8"))
    per_client = int(os.environ.get("BENCH_QUERIES_PER_CLIENT", "6"))
    lat: list = []
    errs: list = []
    mu = threading.Lock()

    def worker(ci: int) -> None:
        for j in range(per_client):
            q = Q6 if (ci + j) % 2 else Q1
            t0 = time.perf_counter()
            try:
                cl.execute(q)
            except Exception as e:  # recorded, not fatal to the bench
                with mu:
                    errs.append(str(e))
                return
            with mu:
                lat.append(time.perf_counter() - t0)

    cl.execute(Q1)
    cl.execute(Q6)  # both plans warm/compiled before the clock starts
    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat.sort()
    if not lat:
        extra["concurrency_error"] = errs[:1]
        return
    extra["concurrency"] = {
        "clients": n_clients,
        "queries": len(lat),
        "queries_per_sec": round(len(lat) / wall, 2),
        "p50_ms": round(lat[len(lat) // 2] * 1000, 1),
        "p99_ms": round(lat[min(len(lat) - 1,
                                int(len(lat) * 0.99))] * 1000, 1),
        "max_shared_pool_size": cl.settings.executor.max_shared_pool_size,
        "errors": len(errs),
    }


def bench_plan_cache(cl, extra: dict) -> None:
    """Query-family compile amortization (executor/kernel_cache.py +
    planner/auto_param.py): cold compile cost, warm plan-cache hit
    latency, and the kernel hit rate across a Q6 literal family —
    textually distinct SQL that hoists to one structural fingerprint."""
    from citus_tpu.executor.executor import GLOBAL_COUNTERS
    from citus_tpu.executor.kernel_cache import GLOBAL_KERNELS
    GLOBAL_KERNELS.clear()
    cl._plan_cache.invalidate_all()
    c0 = GLOBAL_COUNTERS.snapshot()
    t0 = time.perf_counter()
    cl.execute(Q6)
    cold_s = time.perf_counter() - t0
    c1 = GLOBAL_COUNTERS.snapshot()
    t0 = time.perf_counter()
    cl.execute(Q6)
    warm_s = time.perf_counter() - t0
    c2 = GLOBAL_COUNTERS.snapshot()
    variants = [Q6.replace("< 24", f"< {24 + i}") for i in (1, 2, 3, 4)]
    v0 = GLOBAL_COUNTERS.snapshot()
    t0 = time.perf_counter()
    for q in variants:
        cl.execute(q)
    fam_s = (time.perf_counter() - t0) / len(variants)
    v1 = GLOBAL_COUNTERS.snapshot()
    hits = v1["kernel_cache_hits"] - v0["kernel_cache_hits"]
    misses = v1["kernel_cache_misses"] - v0["kernel_cache_misses"]
    extra["plan_cache"] = {
        "cold_ms": round(cold_s * 1000, 1),
        "cold_compile_ms":
            c1["kernel_compile_ms"] - c0["kernel_compile_ms"],
        "warm_hit_ms": round(warm_s * 1000, 1),
        "warm_plan_cache_hit": bool(
            c2["plan_cache_hits"] - c1["plan_cache_hits"]),
        "literal_variant_avg_ms": round(fam_s * 1000, 1),
        "literal_variant_kernel_hit_rate": round(
            hits / max(1, hits + misses), 3),
        "literal_variant_compile_ms":
            v1["kernel_compile_ms"] - v0["kernel_compile_ms"],
    }


def bench_megabatch(cl, extra: dict) -> None:
    """Same-family query coalescing (executor/megabatch.py): K clients
    hammering ONE router point-lookup family, serial (window=0) vs
    coalesced (window>0) QPS, plus the dispatch occupancy histogram —
    the high-QPS lever ROADMAP open item 1 names."""
    import threading
    from citus_tpu.executor.megabatch import GLOBAL_MEGABATCH
    n_clients = int(os.environ.get("BENCH_MB_CLIENTS", "8"))
    per_client = int(os.environ.get("BENCH_MB_QUERIES", "8"))
    window_ms = float(os.environ.get("BENCH_MB_WINDOW_MS", "5"))
    sql = ("SELECT sum(l_quantity), count(*) FROM lineitem "
           "WHERE l_orderkey = 4242")

    def storm() -> float:
        bar = threading.Barrier(n_clients)

        def run() -> None:
            bar.wait()
            for _ in range(per_client):
                cl.execute(sql)
        ts = [threading.Thread(target=run) for _ in range(n_clients)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return time.perf_counter() - t0

    cl.execute(sql)  # serial plan + kernels warm
    cl.execute(f"SET citus.megabatch_window_ms = {window_ms}")
    cl.execute(f"SET citus.megabatch_max_size = {n_clients}")
    cl.execute(sql)  # batched: kernels warm
    cl.execute("SET citus.megabatch_window_ms = 0")
    serial_wall = storm()
    st0 = GLOBAL_MEGABATCH.stats()
    cl.execute(f"SET citus.megabatch_window_ms = {window_ms}")
    batched_wall = storm()
    st1 = GLOBAL_MEGABATCH.stats()
    cl.execute("SET citus.megabatch_window_ms = 0")
    n = n_clients * per_client
    batches = st1["batches"] - st0["batches"]
    queries = st1["queries"] - st0["queries"]
    hist = {k: st1["occupancy_hist"].get(k, 0)
            - st0["occupancy_hist"].get(k, 0)
            for k in st1["occupancy_hist"]}
    extra["megabatch"] = {
        "clients": n_clients,
        "queries": n,
        "window_ms": window_ms,
        "serial_qps": round(n / serial_wall, 1),
        "batched_qps": round(n / batched_wall, 1),
        "speedup": round(serial_wall / batched_wall, 2),
        "avg_occupancy": round(queries / max(1, batches), 2),
        "occupancy_hist": {k: v for k, v in sorted(hist.items()) if v},
    }


def bench_scan_fuse(cl, extra: dict) -> None:
    """Fused single-dispatch hot loop A/B (ops/scan_agg.py
    build_fused_worker_fn + the executor's donated-accumulator loop):
    uncached Q1 through the fused path vs the staged host worker
    (task_executor_backend = 'cpu') — rows/s, dispatch counts, and
    pipeline stall counters per arm — plus a uuid vs text
    high-cardinality ingest A/B: the uuid lane encoding keeps the
    dictionary flat at zero entries while text grows linearly."""
    import uuid as _uuid
    from citus_tpu.executor.executor import GLOBAL_COUNTERS
    from citus_tpu.executor.device_cache import GLOBAL_CACHE

    def measure():
        GLOBAL_CACHE.clear()
        c0 = GLOBAL_COUNTERS.snapshot()
        t0 = time.perf_counter()
        cl.execute(Q1)
        wall = time.perf_counter() - t0
        c1 = GLOBAL_COUNTERS.snapshot()
        return wall, {k: c1[k] - c0[k] for k in (
            "fused_dispatches", "pipeline_host_stalls",
            "pipeline_device_stalls")}

    cl.execute(Q1)  # fused arm: plan + kernels warm
    fused_wall, fused_c = measure()
    cl.execute("SET citus.task_executor_backend = 'cpu'")
    cl.execute(Q1)  # staged arm warm
    staged_wall, staged_c = measure()
    cl.execute("SET citus.task_executor_backend = 'tpu'")
    fuse = {
        "fused_rows_per_sec": round(N_ROWS / fused_wall, 1),
        "staged_cpu_rows_per_sec": round(N_ROWS / staged_wall, 1),
        "speedup_vs_staged": round(staged_wall / fused_wall, 2),
        "fused_dispatches": fused_c["fused_dispatches"],
        "fused_host_stalls": fused_c["pipeline_host_stalls"],
        "fused_device_stalls": fused_c["pipeline_device_stalls"],
        "staged_fused_dispatches": staged_c["fused_dispatches"],
        "staged_host_stalls": staged_c["pipeline_host_stalls"],
    }
    n = int(os.environ.get("BENCH_FUSE_UUIDS", "300000"))
    words = [str(_uuid.UUID(int=(i * 2654435761) % (1 << 128)))
             for i in range(n)]
    cl.execute("DROP TABLE IF EXISTS fuse_uuid_ab")
    cl.execute("DROP TABLE IF EXISTS fuse_text_ab")
    cl.execute("CREATE TABLE fuse_uuid_ab (k bigint NOT NULL, u uuid)")
    cl.execute("SELECT create_distributed_table('fuse_uuid_ab', 'k', 4)")
    t0 = time.perf_counter()
    cl.copy_from("fuse_uuid_ab", columns={"k": np.arange(n), "u": words})
    uuid_wall = time.perf_counter() - t0
    cl.execute("CREATE TABLE fuse_text_ab (k bigint NOT NULL, u text)")
    cl.execute("SELECT create_distributed_table('fuse_text_ab', 'k', 4)")
    t0 = time.perf_counter()
    cl.copy_from("fuse_text_ab", columns={"k": np.arange(n), "u": words})
    text_wall = time.perf_counter() - t0
    cat = cl.catalog
    cat._ensure_dict("fuse_text_ab", "u")
    fuse["uuid_ingest"] = {
        "distinct_values": n,
        "uuid_rows_per_sec": round(n / uuid_wall, 1),
        "text_rows_per_sec": round(n / text_wall, 1),
        "uuid_dict_entries": len(cat._dicts.get(("fuse_uuid_ab", "u"), ())),
        "text_dict_entries": len(cat._dicts[("fuse_text_ab", "u")]),
    }
    extra["scan_fuse"] = fuse


def bench_hash_agg(cl, extra: dict) -> None:
    """Streaming fused hash aggregation A/B (ops/hash_agg.py
    build_fused_hash_worker + the executor's donated HBM-resident
    table): high-cardinality GROUP BY through the fused device path vs
    the staged host accumulator (task_executor_backend = 'cpu') —
    rows/s plus the dispatch/spill counters — then a 2-host loopback
    push-vs-pull A/B: shipped hash-table partials (TASK_VERSION 3
    "hash" tasks) against the pull path's raw-placement bytes."""
    import shutil
    import tempfile

    import citus_tpu as ct
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    from citus_tpu.executor.executor import GLOBAL_COUNTERS

    # l_orderkey spans ~N_ROWS/4 distinct values: unprovable domain ->
    # the hash_host group mode, the path under test
    sql = ("SELECT l_orderkey, count(*), sum(l_quantity) "
           "FROM lineitem GROUP BY l_orderkey")

    def measure():
        GLOBAL_CACHE.clear()
        c0 = GLOBAL_COUNTERS.snapshot()
        t0 = time.perf_counter()
        cl.execute(sql)
        wall = time.perf_counter() - t0
        c1 = GLOBAL_COUNTERS.snapshot()
        return wall, {k: c1[k] - c0[k] for k in (
            "hash_fused_dispatches", "hash_spill_rows")}

    cl.execute("SET citus.hash_agg_slots = auto")
    cl.execute(sql)  # fused arm: plan + kernels warm
    fused_wall, fused_c = measure()
    cl.execute("SET citus.task_executor_backend = 'cpu'")
    cl.execute(sql)  # staged arm warm
    staged_wall, _ = measure()
    cl.execute("SET citus.task_executor_backend = 'tpu'")
    cl.execute("SET citus.hash_agg_slots = 8192")
    hagg = {
        "fused_rows_per_sec": round(N_ROWS / fused_wall, 1),
        "staged_cpu_rows_per_sec": round(N_ROWS / staged_wall, 1),
        # acceptance bar: >= 2x the staged host accumulator
        "speedup_vs_staged": round(staged_wall / fused_wall, 2),
        "hash_fused_dispatches": fused_c["hash_fused_dispatches"],
        "hash_spill_rows": fused_c["hash_spill_rows"],
    }

    root = tempfile.mkdtemp(prefix="bench_hashagg_", dir=_HERE)
    a = ct.Cluster(os.path.join(root, "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    b = None
    try:
        a.register_node()
        b = ct.Cluster(os.path.join(root, "b"), data_port=0,
                       hosted_nodes=set(), n_nodes=0,
                       coordinator=("127.0.0.1", a.control_port))
        b.register_node()
        a._maybe_reload_catalog(force_sync=True)
        n = int(os.environ.get("BENCH_HASH_AGG_ROWS", "400000"))
        a.execute("CREATE TABLE hb (k bigint NOT NULL, v bigint)")
        a.execute("SELECT create_distributed_table('hb', 'k', 8)")
        # spread ~50k distinct keys over a > direct_gid_limit domain so
        # the planner picks hash_host (the pushable-partials path)
        a.copy_from("hb", columns={"k": (np.arange(n) % 50_000) * 20_000_003,
                                   "v": np.arange(n)})
        q = "SELECT k, count(*), sum(v) FROM hb GROUP BY k"
        runs = {}
        for mode in ("push", "pull"):
            a.execute(f"SET citus.remote_task_execution = {mode}")
            GLOBAL_CACHE.clear()
            a.execute(q)  # plans + kernels warm under this mode
            GLOBAL_CACHE.clear()
            c0 = GLOBAL_COUNTERS.snapshot()
            t0 = time.perf_counter()
            a.execute(q)
            wall = time.perf_counter() - t0
            c1 = GLOBAL_COUNTERS.snapshot()
            runs[mode] = {
                "ms": round(wall * 1000, 2),
                "remote_tasks_pushed":
                    c1["remote_tasks_pushed"] - c0["remote_tasks_pushed"],
                "hash_partials_pushed":
                    c1["hash_partials_pushed"]
                    - c0["hash_partials_pushed"],
                "remote_task_fallbacks":
                    c1["remote_task_fallbacks"]
                    - c0["remote_task_fallbacks"],
                "remote_task_result_bytes":
                    c1["remote_task_result_bytes"]
                    - c0["remote_task_result_bytes"],
            }
        a.execute("SET citus.remote_task_execution = auto")
        hagg["push_vs_pull"] = runs
    finally:
        if b is not None:
            b.close()
        a.close()
        shutil.rmtree(root, ignore_errors=True)
    extra["hash_agg"] = hagg


def bench_trace_overhead(cl, extra: dict) -> None:
    """Tracing cost (observability/): warm Q1 wall time with sampling
    off (the allocation-free no-op recorder) vs sample_rate=1.0 (every
    span recorded).  The acceptance bar is < 3% overhead at rate 0
    relative to this build's own untraced baseline — measured here as
    rate-0 vs rate-0 jitter-adjusted by taking the best of several
    reps, the same protocol the headline metric uses."""
    reps = int(os.environ.get("BENCH_TRACE_REPS", "3"))

    def best_of(sql: str) -> float:
        cl.execute(sql)  # warm
        return min(_t_wall(cl, sql) for _ in range(reps))

    def _t_wall(cl, sql):
        t0 = time.perf_counter()
        cl.execute(sql)
        return time.perf_counter() - t0

    cl.execute("SET citus.trace_sample_rate = 0")
    off_s = best_of(Q1)
    cl.execute("SET citus.trace_sample_rate = 1.0")
    on_s = best_of(Q1)
    cl.execute("SET citus.trace_sample_rate = 0")
    extra["trace_overhead"] = {
        "q1_rate0_ms": round(off_s * 1000, 2),
        "q1_rate1_ms": round(on_s * 1000, 2),
        "sampled_overhead_fraction": round(max(0.0, on_s / off_s - 1.0), 4),
    }


def bench_recorder_overhead(cl, extra: dict) -> None:
    """Flight-recorder cost (observability/flight_recorder.py): warm Q1
    wall time with the sampler off vs ticking at interval=100ms (ring
    append + health checks + one segment line per tick, all off the
    query path).  The acceptance bar is < 3% overhead — the sampler
    runs on its own thread and only takes subsystem snapshot locks."""
    reps = int(os.environ.get("BENCH_RECORDER_REPS", "3"))

    def best_of(sql: str) -> float:
        cl.execute(sql)  # warm
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            cl.execute(sql)
            ts.append(time.perf_counter() - t0)
        return min(ts)

    cl.execute("SET citus.flight_recorder_interval_ms = 0")
    off_s = best_of(Q1)
    cl.execute("SET citus.flight_recorder_interval_ms = 100")
    on_s = best_of(Q1)
    cl.execute("SET citus.flight_recorder_interval_ms = 0")
    extra["recorder_overhead"] = {
        "q1_recorder_off_ms": round(off_s * 1000, 2),
        "q1_recorder_100ms_ms": round(on_s * 1000, 2),
        "recorder_overhead_fraction": round(
            max(0.0, on_s / off_s - 1.0), 4),
    }


def bench_wait_overhead(cl, extra: dict) -> None:
    """Wait-event seam cost (stats.begin_wait/end_wait): warm Q1 wall
    time with the brackets live vs stubbed to no-ops at every
    instrumented call site.  The seam only opens brackets on genuinely
    blocking branches, so a warm local scan should measure within
    noise — the acceptance bar for 'near-free when idle'."""
    import citus_tpu.commands.dml as _dml
    import citus_tpu.executor.executor as _ex
    import citus_tpu.executor.pipeline as _pl
    import citus_tpu.transaction.locks as _lk
    reps = int(os.environ.get("BENCH_WAIT_REPS", "3"))

    def best_of() -> float:
        cl.execute(Q1)  # warm
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            cl.execute(Q1)
            ts.append(time.perf_counter() - t0)
        return min(ts)

    on_s = best_of()
    sites = [(m, m.begin_wait, m.end_wait) for m in (_dml, _ex, _pl, _lk)]
    try:
        for m, _, _ in sites:
            m.begin_wait = lambda event: (event, 0.0)
            m.end_wait = lambda token: 0.0
        off_s = best_of()
    finally:
        for m, bw, ew in sites:
            m.begin_wait, m.end_wait = bw, ew
    extra["wait_event_overhead"] = {
        "q1_instrumented_ms": round(on_s * 1000, 2),
        "q1_stubbed_ms": round(off_s * 1000, 2),
        "overhead_fraction": round(max(0.0, on_s / off_s - 1.0), 4),
    }


_SANITIZE_CHILD = r"""
import json, sys, time
import numpy as np
import citus_tpu as ct
from citus_tpu.config import Settings

cl = ct.Cluster(sys.argv[1],
                settings=Settings(start_maintenance_daemon=False))
cl.execute("CREATE TABLE b (k bigint NOT NULL, v double)")
cl.execute("SELECT create_distributed_table('b', 'k', 8)")
n = int(sys.argv[2])
cl.copy_from("b", columns={"k": np.arange(n, dtype=np.int64) % 97,
                           "v": np.linspace(0.0, 1.0, n)})
q = "SELECT k, count(*), sum(v) FROM b GROUP BY k"
cl.execute(q)  # warm: compile + cache
ts = []
for _ in range(int(sys.argv[3])):
    t0 = time.perf_counter()
    cl.execute(q)
    ts.append(time.perf_counter() - t0)
cl.close()
print(json.dumps({"best_ms": min(ts) * 1000}))
"""


def bench_sanitize_overhead(extra: dict) -> None:
    """Concurrency-sanitizer cost (utils/sanitizer.py): warm Q1-shape
    wall time in a fresh process with CITUS_SANITIZE unset vs =1
    (every package lock wrapped, order graph + begin_wait hook live).
    Also asserts the off-mode zero-cost contract in THIS process:
    threading.Lock is still the raw C factory and the stats-seam guard
    is one False attribute read — off mode must be a passthrough, not
    merely cheap."""
    import subprocess
    import sys as _sys
    import tempfile
    import threading as _th

    from citus_tpu.utils import sanitizer as _san
    assert _th.Lock is _san._real_Lock and not _san._ACTIVE, \
        "sanitizer must be an exact passthrough when CITUS_SANITIZE is unset"
    rows = int(os.environ.get("BENCH_SANITIZE_ROWS", "200000"))
    reps = int(os.environ.get("BENCH_SANITIZE_REPS", "3"))

    def run(sanitize: bool) -> float:
        with tempfile.TemporaryDirectory() as td:
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env.pop("CITUS_SANITIZE", None)
            if sanitize:
                env["CITUS_SANITIZE"] = "1"
            out = subprocess.run(
                [_sys.executable, "-c", _SANITIZE_CHILD,
                 os.path.join(td, "db"), str(rows), str(reps)],
                env=env, capture_output=True, timeout=600, check=True)
            return json.loads(out.stdout)["best_ms"]

    off_ms = run(False)
    on_ms = run(True)
    extra["sanitizer_overhead"] = {
        "q1_sanitize_off_ms": round(off_ms, 2),
        "q1_sanitize_on_ms": round(on_ms, 2),
        "overhead_fraction": round(max(0.0, on_ms / off_ms - 1.0), 4),
        "off_mode_passthrough": True,  # asserted above
    }


def bench_stat_fanout(extra: dict) -> None:
    """citus_cluster_metrics fan-out latency on a 3-node cluster
    (authority + two attached workers, all loopback): the wall cost of
    one merged scrape — probe threads + per-node get_node_stats round
    trips + Prometheus rendering."""
    import shutil
    import tempfile

    import citus_tpu as ct
    reps = int(os.environ.get("BENCH_FANOUT_REPS", "5"))
    root = tempfile.mkdtemp(prefix="bench_fanout_", dir=_HERE)
    a = ct.Cluster(os.path.join(root, "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    workers = []
    try:
        a.register_node()
        for name in ("b", "c"):
            w = ct.Cluster(os.path.join(root, name), data_port=0,
                           hosted_nodes=set(), n_nodes=0,
                           coordinator=("127.0.0.1", a.control_port))
            w.register_node()
            workers.append(w)
        a._maybe_reload_catalog(force_sync=True)
        a.execute("SELECT citus_cluster_metrics()")  # warm
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            r = a.execute("SELECT citus_cluster_metrics()")
            ts.append(time.perf_counter() - t0)
        txt = "\n".join(row[0] for row in r.rows)
        extra["stat_fanout"] = {
            "nodes": 3,
            "cluster_metrics_best_ms": round(min(ts) * 1000, 2),
            "cluster_metrics_avg_ms": round(
                sum(ts) / len(ts) * 1000, 2),
            "series_lines": sum(
                1 for ln in txt.splitlines()
                if ln and not ln.startswith("#")),
        }
    finally:
        for w in workers:
            w.close()
        a.close()
        shutil.rmtree(root, ignore_errors=True)


def bench_wire(extra: dict) -> None:
    """Wire-format A/B (net/data_plane.py CTFR frame vs legacy npz):
    host decode of a ~32 MB task result (micro A/B — the zero-copy
    frombuffer view vs the zip-container copy), then the same remote
    fan-out queries — a distributed agg and a repartition join — on a
    3-host loopback cluster under each citus.wire_format, with the
    remote-RPC wait and per-codec byte counters for both runs."""
    import shutil
    import tempfile

    import citus_tpu as ct
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    from citus_tpu.executor.executor import GLOBAL_COUNTERS
    from citus_tpu.net.data_plane import (
        _npz_bytes, _npz_load, decode_frame, encode_frame,
    )

    arrays = {f"c{i}": np.arange(1_000_000, dtype=np.int64)
              for i in range(4)}
    frame_blob, npz_blob = encode_frame(arrays), _npz_bytes(arrays)

    def best_decode(fn, blob) -> float:
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            fn(blob)
            ts.append(time.perf_counter() - t0)
        return min(ts)

    f_ms = best_decode(decode_frame, frame_blob) * 1000
    z_ms = best_decode(_npz_load, npz_blob) * 1000

    root = tempfile.mkdtemp(prefix="bench_wire_", dir=_HERE)
    a = ct.Cluster(os.path.join(root, "a"), serve_port=0, data_port=0,
                   hosted_nodes=set(), n_nodes=0)
    workers = []
    try:
        a.register_node()
        for name in ("b", "c"):
            w = ct.Cluster(os.path.join(root, name), data_port=0,
                           hosted_nodes=set(), n_nodes=0,
                           coordinator=("127.0.0.1", a.control_port))
            w.register_node()
            workers.append(w)
        a._maybe_reload_catalog(force_sync=True)
        n = int(os.environ.get("BENCH_WIRE_ROWS", "400000"))
        a.execute("CREATE TABLE lw (k bigint NOT NULL, v bigint)")
        a.execute("SELECT create_distributed_table('lw', 'k', 8)")
        a.copy_from("lw", columns={"k": np.arange(n),
                                   "v": np.arange(n) % 97})
        # ow distributed on g, joined on o: forces the repartition path
        a.execute("CREATE TABLE ow (o bigint NOT NULL, g bigint)")
        a.execute("SELECT create_distributed_table('ow', 'g', 8)")
        a.copy_from("ow", columns={"o": np.arange(n // 4),
                                   "g": np.arange(n // 4) % 31})
        agg = "SELECT count(*), sum(v) FROM lw"
        join = "SELECT count(*) FROM lw l JOIN ow o ON l.k = o.o"
        runs = {}
        for fmt in ("frame", "npz"):
            a.execute(f"SET citus.wire_format = {fmt}")
            GLOBAL_CACHE.clear()
            a.execute(agg)
            a.execute(join)  # plans + kernels warm under this format
            GLOBAL_CACHE.clear()
            c0 = GLOBAL_COUNTERS.snapshot()
            t0 = time.perf_counter()
            a.execute(agg)
            agg_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            a.execute(join)
            join_s = time.perf_counter() - t0
            c1 = GLOBAL_COUNTERS.snapshot()
            runs[fmt] = {
                "agg_ms": round(agg_s * 1000, 2),
                "repartition_join_ms": round(join_s * 1000, 2),
                "wait_remote_rpc_ms": round(
                    c1["wait_remote_rpc_ms"] - c0["wait_remote_rpc_ms"],
                    2),
                "wire_frame_bytes":
                    c1["wire_frame_bytes"] - c0["wire_frame_bytes"],
                "wire_npz_bytes":
                    c1["wire_npz_bytes"] - c0["wire_npz_bytes"],
            }
        a.execute("SET citus.wire_format = frame")
        extra["wire"] = {
            "decode_frame_ms": round(f_ms, 3),
            "decode_npz_ms": round(z_ms, 3),
            # the acceptance bar: frame cuts host decode by >= 30 %
            "decode_cut_fraction": round(1.0 - f_ms / max(z_ms, 1e-9), 4),
            "frame": runs["frame"],
            "npz": runs["npz"],
        }
    finally:
        for w in workers:
            w.close()
        a.close()
        shutil.rmtree(root, ignore_errors=True)


def bench_workload(extra: dict) -> None:
    """Closed-loop multi-tenant harness (workload/scheduler.py): mixed
    router + analytic traffic from N client threads in EACH of two
    coordinator OS processes sharing one cluster, admission squeezed
    through a small shared pool so the stride scheduler is the choke
    point.  Reports sustained QPS and per-tenant p50/p99."""
    import shutil
    import subprocess as sp
    import tempfile
    import textwrap
    import threading

    import citus_tpu as ct
    clients = int(os.environ.get("BENCH_WL_CLIENTS", "6"))
    seconds = float(os.environ.get("BENCH_WL_SECONDS", "6"))
    pool = int(os.environ.get("BENCH_WL_POOL", "4"))
    root = tempfile.mkdtemp(prefix="bench_workload_", dir=_HERE)
    d = os.path.join(root, "db")

    # one client thread's closed loop: router lookups on its own tenant
    # key, every 8th query the shared-bucket analytic scan
    driver = textwrap.dedent("""
        def _drive(cl, clients, seconds, out):
            import threading, time

            def loop(ci):
                tenant = str(ci % 4)
                lat = out.setdefault(tenant, [])
                alat = out.setdefault("*", [])
                router = f"SELECT sum(v) FROM wt WHERE k = {ci % 4}"
                analytic = "SELECT count(*), sum(v) FROM wt"
                i = 0
                deadline = time.monotonic() + seconds
                while time.monotonic() < deadline:
                    sql, dst = ((analytic, alat) if i % 8 == 7
                                else (router, lat))
                    t0 = time.perf_counter()
                    try:
                        cl.execute(sql)
                    except Exception:
                        i += 1
                        continue
                    dst.append(time.perf_counter() - t0)
                    i += 1
            ts = [threading.Thread(target=loop, args=(ci,))
                  for ci in range(clients)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
    """)
    child_code = driver + textwrap.dedent(f"""
        import json, sys
        import citus_tpu as ct
        cl = ct.Cluster({d!r}, coordinator=("127.0.0.1", PORT))
        cl.execute("SET citus.max_shared_pool_size = {pool}")
        cl.execute("SELECT sum(v) FROM wt WHERE k = 1")  # warm
        print("READY", flush=True)
        sys.stdin.readline()  # GO
        out = {{}}
        _drive(cl, {clients}, {seconds}, out)
        cl.close()
        print("RESULT " + json.dumps(out), flush=True)
    """)

    a = ct.Cluster(d, serve_port=0)
    child = None
    try:
        a.execute("CREATE TABLE wt (k bigint NOT NULL, v bigint)")
        a.execute("SELECT create_distributed_table('wt', 'k', 8)")
        n = 200_000
        a.copy_from("wt", columns={"k": np.arange(n) % 64,
                                   "v": np.arange(n)})
        a.execute("SET citus.max_shared_pool_size = %d" % pool)
        for t in range(4):
            a.execute(f"SELECT citus_add_tenant_quota('{t}', 1.0)")
        a.execute("SELECT sum(v) FROM wt WHERE k = 1")  # warm
        # the second coordinator always runs the cpu backend: a second
        # OS process cannot share the TPU, and admission behavior (the
        # thing under test) is device-independent
        code = ("import jax\njax.config.update('jax_platforms','cpu')\n"
                + child_code.replace("PORT", str(a.control_port)))
        child = sp.Popen([sys.executable, "-c", code], stdin=sp.PIPE,
                         stdout=sp.PIPE, text=True)
        assert child.stdout.readline().strip() == "READY"
        ns = {}
        exec(compile(driver, "<bench_workload>", "exec"), ns)
        out = {}
        child.stdin.write("GO\n")
        child.stdin.flush()
        t0 = time.perf_counter()
        ns["_drive"](a, clients, seconds, out)
        line = child.stdout.readline()
        wall = time.perf_counter() - t0
        assert line.startswith("RESULT "), line
        for tenant, lats in json.loads(line[len("RESULT "):]).items():
            out.setdefault(tenant, []).extend(lats)
        total = sum(len(v) for v in out.values())
        tenants = {
            t: {"queries": len(v),
                "p50_ms": round(float(np.percentile(v, 50)) * 1000, 2),
                "p99_ms": round(float(np.percentile(v, 99)) * 1000, 2)}
            for t, v in sorted(out.items()) if v
        }
        extra["workload"] = {
            "coordinators": 2,
            "clients_per_coordinator": clients,
            "shared_pool_size": pool,
            "duration_s": seconds,
            "sustained_qps": round(total / wall, 1),
            "tenants": tenants,
        }
    finally:
        if child is not None and child.poll() is None:
            child.kill()
            child.wait()
        a.close()
        shutil.rmtree(root, ignore_errors=True)


def bench_multi_coordinator(extra: dict) -> None:
    """Query-from-any-node scaling (citus_tpu/metadata/): aggregate QPS
    as 1 -> 2 -> 4 coordinator OS processes serve zipfian mixed traffic
    from a ~1M-tenant namespace against one shared cluster.  Every
    coordinator admits from the SAME catalog-persisted quota table (64
    registered heavy hitters in two priority classes, the long tail on
    GUC defaults), so the run also proves zero divergent admission
    decisions: each process reports a fingerprint over the effective
    admission inputs of fixed probe tenants, and all must match.

    Scaling is only meaningful when host cores >= coordinator count;
    the record carries host_cores so a 1-core container's flat curve
    reads as saturation, not a sync-engine bottleneck."""
    import shutil
    import subprocess as sp
    import tempfile
    import textwrap

    import citus_tpu as ct
    clients = int(os.environ.get("BENCH_MC_CLIENTS", "4"))
    seconds = float(os.environ.get("BENCH_MC_SECONDS", "3"))
    tenant_space = int(os.environ.get("BENCH_MC_TENANTS", "1000000"))
    counts = [1, 2, 4]
    root = tempfile.mkdtemp(prefix="bench_multicoord_", dir=_HERE)
    d = os.path.join(root, "db")

    child_code = textwrap.dedent(f"""
        import hashlib, json, sys, threading, time
        import numpy as np
        import citus_tpu as ct
        from citus_tpu.workload import GLOBAL_TENANTS
        seat = int(sys.argv[1])
        cl = ct.Cluster({d!r}, coordinator=("127.0.0.1", PORT))
        cl.metadata_sync.sync_once()
        # admission fingerprint over fixed probe tenants: registered
        # heavy hitters AND defaulted long-tail ids; any divergence in
        # quotas, classes, or GUC fallbacks changes the digest
        wl = cl.settings.workload
        probe = []
        for t in [str(i) for i in range(1, 65)] + ["999983", "717171"]:
            q = GLOBAL_TENANTS.get(t)
            pclass = (q.priority_class if q and q.priority_class
                      else wl.tenant_default_priority_class)
            probe.append((t, q.weight if q else wl.tenant_default_weight,
                          q.max_concurrency if q else 0,
                          q.rate_limit_qps if q else wl.tenant_rate_limit_qps,
                          q.queue_depth if q else wl.tenant_queue_depth,
                          pclass, GLOBAL_TENANTS.class_weight(pclass)))
        fp = hashlib.sha1(json.dumps(probe).encode()).hexdigest()[:16]
        cl.execute("SELECT sum(v) FROM mt WHERE k = 1")  # warm
        print("READY", flush=True)
        sys.stdin.readline()  # GO
        counts = [0] * {clients}

        def loop(ci):
            rng = np.random.default_rng(1000 * seat + ci)
            i = 0
            deadline = time.monotonic() + {seconds}
            while time.monotonic() < deadline:
                # zipfian tenant draw over the ~{tenant_space} namespace
                t = int(min(rng.zipf(1.2), {tenant_space}))
                sql = ("SELECT count(*), sum(v) FROM mt" if i % 8 == 7
                       else f"SELECT sum(v) FROM mt WHERE k = {{t}}")
                try:
                    cl.execute(sql)
                    counts[ci] += 1
                except Exception:
                    pass
                i += 1
        ts = [threading.Thread(target=loop, args=(ci,))
              for ci in range({clients})]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        wall = time.perf_counter() - t0
        cl.close()
        print("RESULT " + json.dumps(
            {{"count": sum(counts), "wall": wall, "fingerprint": fp}}),
            flush=True)
    """)

    a = ct.Cluster(d, serve_port=0)
    procs = []
    try:
        a.execute("CREATE TABLE mt (k bigint NOT NULL, v bigint)")
        a.execute("SELECT create_distributed_table('mt', 'k', 8)")
        n = 200_000
        rng = np.random.default_rng(7)
        keys = np.minimum(rng.zipf(1.2, size=n), tenant_space).astype(np.int64)
        a.copy_from("mt", columns={"k": keys, "v": np.arange(n)})
        # replicated control plane: two priority classes, 64 registered
        # heavy hitters, the other ~1M tenants on GUC defaults
        a.execute("SELECT citus_add_priority_class('gold', 4.0)")
        a.execute("SELECT citus_add_priority_class('basic', 1.0)")
        for t in range(1, 65):
            pc = "gold" if t <= 8 else "basic"
            a.execute(f"SELECT citus_add_tenant_quota('{t}', 2.0, 0, 0.0,"
                      f" 0, '{pc}')")
        qps_by_count = {}
        fingerprints = set()
        code = ("import jax\njax.config.update('jax_platforms','cpu')\n"
                + child_code.replace("PORT", str(a.control_port)))
        for k in counts:
            procs = [sp.Popen([sys.executable, "-c", code, str(seat)],
                              stdin=sp.PIPE, stdout=sp.PIPE, text=True)
                     for seat in range(k)]
            for p in procs:
                assert p.stdout.readline().strip() == "READY"
            for p in procs:
                p.stdin.write("GO\n")
                p.stdin.flush()
            total = 0
            for p in procs:
                line = p.stdout.readline()
                assert line.startswith("RESULT "), line
                r = json.loads(line[len("RESULT "):])
                total += r["count"] / max(r["wall"], 1e-9)
                fingerprints.add(r["fingerprint"])
                p.wait()
            procs = []
            qps_by_count[str(k)] = round(total, 1)
        q1 = qps_by_count["1"]
        extra["multi_coordinator"] = {
            "host_cores": os.cpu_count() or 1,
            "clients_per_coordinator": clients,
            "duration_s": seconds,
            "tenant_namespace": tenant_space,
            "registered_quotas": 64,
            "qps_by_coordinators": qps_by_count,
            "scaling_x2": round(qps_by_count["2"] / max(q1, 1e-9), 2),
            "scaling_x4": round(qps_by_count["4"] / max(q1, 1e-9), 2),
            # one distinct fingerprint across every coordinator = zero
            # divergent admission decisions
            "admission_fingerprints": len(fingerprints),
            "divergent_admission_decisions": len(fingerprints) - 1,
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        a.close()
        shutil.rmtree(root, ignore_errors=True)


def bench_rollup(extra: dict) -> None:
    """Continuous-aggregation A/B (rollup/): a dashboard closed loop
    runs against a wide event table while writer threads keep heavy
    ingest flowing and the background refresh loop folds CDC deltas.
    The A arm re-scans raw events (citus.enable_rollup_routing = off);
    the B arm serves the same query from the rollup table.  Reports
    QPS + p99 per arm, the steady-state refresh lag sampled during the
    run, and how long the lag takes to converge once ingest stops."""
    import shutil
    import tempfile
    import threading

    import citus_tpu as ct
    from citus_tpu.config import Settings

    n = int(os.environ.get("BENCH_ROLLUP_ROWS", "300000"))
    seconds = float(os.environ.get("BENCH_ROLLUP_SECONDS", "5"))
    batch = int(os.environ.get("BENCH_ROLLUP_INGEST_BATCH", "2000"))
    tenants = 16
    root = tempfile.mkdtemp(prefix="bench_rollup_", dir=_HERE)
    dash_q = ("SELECT tid, count(*), sum(v), "
              "approx_count_distinct(kind), "
              "approx_percentile(0.5) WITHIN GROUP (ORDER BY v) "
              "FROM ev GROUP BY tid")

    def make_batch(rng, rows):
        return {
            "tid": rng.integers(0, tenants, rows).astype(np.int64),
            "kind": np.array([f"k{int(x)}" for x in
                              rng.integers(0, 64, rows)], object),
            "v": rng.uniform(1.0, 100.0, rows),
            "code": rng.integers(0, 32, rows).astype(np.int64),
        }

    cl = ct.Cluster(os.path.join(root, "db"),
                    settings=Settings(enable_change_data_capture=True))
    try:
        cl.execute("CREATE TABLE ev (tid bigint NOT NULL, kind text, "
                   "v double, code bigint)")
        cl.execute("SELECT create_distributed_table('ev', 'tid', 8)")
        rng = np.random.default_rng(0)
        done = 0
        while done < n:
            m = min(200_000, n - done)
            cl.copy_from("ev", columns=make_batch(rng, m))
            done += m
        cl.execute("SELECT citus_create_rollup('ev_r', 'ev', 'tid', "
                   "'count(*), sum(v), approx_count_distinct(kind), "
                   "approx_percentile(v), approx_top_k(code)')")
        cl.execute("SET citus.rollup_refresh_interval_ms = 100")

        stop = threading.Event()
        ingested = [0]

        def pound():
            wrng = np.random.default_rng(1)
            while not stop.is_set():
                cl.copy_from("ev", columns=make_batch(wrng, batch))
                ingested[0] += batch

        def arm(route_on):
            cl.execute("SET citus.enable_rollup_routing = "
                       + ("on" if route_on else "off"))
            cl.execute(dash_q)  # warm compile outside the window
            lats, lags = [], []
            deadline = time.monotonic() + seconds
            while time.monotonic() < deadline:
                t0 = time.perf_counter()
                cl.execute(dash_q)
                lats.append(time.perf_counter() - t0)
                if route_on and len(lats) % 10 == 0:
                    lags.append(
                        cl.execute("SELECT citus_rollups()").rows[0][6])
            return {
                "queries": len(lats),
                "qps": round(len(lats) / seconds, 1),
                "p50_ms": round(float(np.percentile(lats, 50)) * 1000, 2),
                "p99_ms": round(float(np.percentile(lats, 99)) * 1000, 2),
            }, lags

        th = threading.Thread(target=pound)
        th.start()
        try:
            raw, _ = arm(route_on=False)
            rolled, lags = arm(route_on=True)
        finally:
            stop.set()
            th.join()
        # lag convergence: once ingest stops the watermark must reach
        # the CDC head and stay there
        t0 = time.monotonic()
        converged = None
        while time.monotonic() - t0 < 60:
            if cl.execute("SELECT citus_rollups()").rows[0][6] == 0:
                converged = round(time.monotonic() - t0, 2)
                break
            time.sleep(0.05)
        cl.execute("SET citus.rollup_refresh_interval_ms = 0")
        extra["rollup"] = {
            "source_rows": n + ingested[0],
            "ingested_during_run": ingested[0],
            "raw_scan": raw,
            "rollup": rolled,
            "speedup_p50": round(raw["p50_ms"] / max(rolled["p50_ms"],
                                                     1e-6), 1),
            "steady_state_lag_changes": {
                "mean": round(float(np.mean(lags)), 1) if lags else 0,
                "max": int(max(lags)) if lags else 0,
            },
            "lag_converged_after_ingest_s": converged,
        }
    finally:
        cl.close()
        shutil.rmtree(root, ignore_errors=True)


def bench_rebalance(extra: dict) -> None:
    """Online rebalancing (operations/shard_transfer.py): N writer
    threads hammer the table for the whole life of a background shard
    move; the contract is zero failed writes with the blocked-write
    window (the locked final catch-up + metadata flip) a tiny fraction
    of the total move time.  Reports sustained write QPS under the
    move, blocked-write ms, and CDC catch-up rounds."""
    import shutil
    import tempfile
    import threading

    import citus_tpu as ct
    from citus_tpu.config import Settings
    from citus_tpu.testing.faults import FAULTS

    writers = int(os.environ.get("BENCH_RB_WRITERS", "4"))
    n = int(os.environ.get("BENCH_RB_ROWS", "200000"))
    root = tempfile.mkdtemp(prefix="bench_rebalance_", dir=_HERE)
    cl = ct.Cluster(os.path.join(root, "db"), n_nodes=2,
                    settings=Settings(enable_change_data_capture=True))
    try:
        cl.execute("CREATE TABLE rb (k bigint NOT NULL, v bigint)")
        cl.execute("SELECT create_distributed_table('rb', 'k', 4)")
        cl.copy_from("rb", columns={"k": np.arange(n, dtype=np.int64),
                                    "v": np.arange(n, dtype=np.int64) % 97})
        shard = cl.catalog.table("rb").shards[0]
        src = shard.placements[0]
        # stretch the bulk pass so the writers demonstrably overlap it
        FAULTS.arm("shard_move_copy", delay_s=0.3, times=1)
        jid = cl.background_jobs.create_job("bench move")
        cl.background_jobs.add_task(
            jid, "move_shard", {"shard_id": shard.shard_id,
                                "source": src, "target": 1 - src})
        stop = threading.Event()
        wrote, failed = [], []

        def hammer(base):
            i = 0
            while not stop.is_set():
                k = base + i * writers
                try:
                    cl.execute(f"INSERT INTO rb VALUES ({k}, {k % 97})")
                    wrote.append(k)
                except Exception:
                    failed.append(k)
                i += 1

        ts = [threading.Thread(target=hammer, args=(10 * n + w,))
              for w in range(writers)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        status = cl.background_jobs.wait_for_job(jid)
        stop.set()
        for t in ts:
            t.join()
        wall = time.perf_counter() - t0
        FAULTS.disarm()
        r = cl.execute("SELECT citus_shard_move_stats()")
        d = [dict(zip(r.columns, row)) for row in r.rows
             if row[0] == "move" and row[1] == shard.shard_id][-1]
        extra["rebalance"] = {
            "move_status": status,
            "writer_threads": writers,
            "writes_total": len(wrote),
            "writes_failed": len(failed),
            "sustained_write_qps": round(len(wrote) / wall, 1),
            "catchup_rounds": d["catchup_rounds"],
            "blocked_write_ms": d["blocked_write_ms"],
            "move_total_ms": d["total_ms"],
            "blocked_fraction": round(
                d["blocked_write_ms"] / max(d["total_ms"], 1), 4),
        }
    finally:
        FAULTS.disarm()
        cl.close()
        shutil.rmtree(root, ignore_errors=True)


def bench_autopilot(extra: dict) -> None:
    """Self-driving rebalancing A/B (services/autopilot.py): the same
    zipfian hot-shard storm under citus.autopilot = off | observe | on.
    Per arm: hot-query p99 before/after the autopilot's decision
    window, actions executed/observed/declined, and failed writes while
    a move ran (the contract: zero).  The observe arm's decision log is
    the dry-run instrument — same decisions as 'on', no moves."""
    import shutil
    import tempfile
    import threading

    import citus_tpu as ct

    n = int(os.environ.get("BENCH_AP_ROWS", "100000"))
    probes = int(os.environ.get("BENCH_AP_PROBES", "150"))
    arms = {}
    for arm in ("off", "observe", "on"):
        root = tempfile.mkdtemp(prefix=f"bench_autopilot_{arm}_", dir=_HERE)
        cl = ct.Cluster(os.path.join(root, "db"), n_nodes=2)
        try:
            cl.execute("CREATE TABLE ap (k bigint NOT NULL, v bigint)")
            cl.execute("SELECT create_distributed_table('ap', 'k', 4)")
            cl.copy_from("ap", columns={
                "k": np.arange(n, dtype=np.int64),
                "v": np.arange(n, dtype=np.int64) % 97})
            cl.execute(f"SET citus.autopilot = {arm}")
            cl.execute("SET citus.autopilot_sustain_ticks = 2")
            cl.execute("SET citus.autopilot_cooldown_s = 3600")
            cl.counters.reset()  # re-zeros the attribution ledger too
            s = cl.session()
            s.execute("PREPARE appt AS SELECT sum(v) FROM ap WHERE k = $1")
            # hot-tenant storm: every probe routes to a shard placed on
            # node 0, so node 0's placements run away in the attribution
            # ledger while node 1 idles — the shape the autopilot fixes
            from citus_tpu.catalog.hashing import hash_int64_scalar
            t = cl.catalog.table("ap")
            keys, k = [], 0
            while len(keys) < 8 and k < n:
                sidx = t.route_hash(hash_int64_scalar(k))
                if t.shards[sidx].placements[0] == 0:
                    keys.append(k)
                k += 1
            keys = (keys * (2 * probes // len(keys) + 1))[:2 * probes]

            def storm(ks):
                lat = []
                for k in ks:
                    t0 = time.perf_counter()
                    s.execute(f"EXECUTE appt ({int(k)})")
                    lat.append(time.perf_counter() - t0)
                lat.sort()
                return round(lat[int(0.99 * (len(lat) - 1))] * 1000, 3)

            before = [tuple(s.placements)
                      for s in cl.catalog.table("ap").shards]
            p99_storm = storm(keys[:probes])
            # decision window: the storm KEEPS RUNNING (the EWMA rates
            # the planner reads are live rates, not history) and a
            # writer hammers ingest while the duty evaluates — and, in
            # the 'on' arm, executes its one move under both
            stop = threading.Event()
            wrote, failed = [], []

            def hammer():
                i = 0
                while not stop.is_set():
                    k = 10 * n + i
                    try:
                        cl.execute(f"INSERT INTO ap VALUES ({k}, {k % 97})")
                        wrote.append(k)
                    except Exception:
                        failed.append(k)
                    i += 1

            s2 = cl.session()
            s2.execute("PREPARE hot AS SELECT sum(v) FROM ap WHERE k = $1")

            def hot_loop():
                i = 0
                while not stop.is_set():
                    s2.execute(f"EXECUTE hot ({int(keys[i % probes])})")
                    i += 1

            threads = [threading.Thread(target=hammer),
                       threading.Thread(target=hot_loop)]
            for th in threads:
                th.start()
            for _ in range(6):
                cl.autopilot.duty()
                time.sleep(0.25)
            stop.set()
            for th in threads:
                th.join()
            p99_after = storm(keys[probes:])
            after = [tuple(s.placements)
                     for s in cl.catalog.table("ap").shards]
            snap = cl.counters.snapshot()
            arms[arm] = {
                "p99_storm_ms": p99_storm,
                "p99_after_ms": p99_after,
                "placements_moved": sum(b != a
                                        for b, a in zip(before, after)),
                "actions_executed": snap["autopilot_actions_executed"],
                "actions_observed": snap["autopilot_actions_observed"],
                "actions_declined": snap["autopilot_actions_declined"],
                "decision_log_rows": len(cl.autopilot.log_rows()),
                "writes_total": len(wrote),
                "writes_failed": len(failed),
            }
        finally:
            cl.close()
            shutil.rmtree(root, ignore_errors=True)
    extra["autopilot"] = arms


def ensure_join_data(cl: "ct.Cluster", n_orders: int) -> None:
    """orders_b: the build side of the repartition join, distributed on
    o_custkey so the l_orderkey = o_orderkey join must reshuffle."""
    if cl.catalog.has_table("orders_b"):
        from citus_tpu.catalog.stats import table_row_count
        if table_row_count(cl.catalog, cl.catalog.table("orders_b")) == n_orders:
            return
        cl.drop_table("orders_b")
    cl.execute("""CREATE TABLE orders_b (
        o_orderkey bigint NOT NULL, o_custkey bigint NOT NULL,
        o_flag text)""")
    cl.execute(f"SELECT create_distributed_table('orders_b', 'o_custkey', {SHARDS})")
    rng = np.random.default_rng(11)
    flags = np.array(["H", "L", "M"])
    chunk = 1_000_000
    for start in range(0, n_orders, chunk):
        n = min(chunk, n_orders - start)
        cl.copy_from("orders_b", columns={
            "o_orderkey": np.arange(start, start + n, dtype=np.int64),
            "o_custkey": rng.integers(0, n_orders // 8 + 1, n),
            "o_flag": flags[rng.integers(0, 3, n)].tolist(),
        })


def ensure_data(cl: "ct.Cluster", n_rows: int = None) -> None:
    n_rows = N_ROWS if n_rows is None else n_rows
    if cl.catalog.has_table("lineitem"):
        from citus_tpu.catalog.stats import table_row_count
        if table_row_count(cl.catalog, cl.catalog.table("lineitem")) == n_rows:
            return
        cl.drop_table("lineitem")
    cl.execute("""CREATE TABLE lineitem (
        l_orderkey bigint NOT NULL, l_quantity decimal(12,2),
        l_extendedprice decimal(12,2), l_discount decimal(12,2),
        l_tax decimal(12,2), l_returnflag text, l_linestatus text,
        l_shipdate date)""")
    cl.execute(f"SELECT create_distributed_table('lineitem', 'l_orderkey', {SHARDS})")
    rng = np.random.default_rng(7)
    chunk = 1_000_000
    rf = np.array(["A", "N", "R"])
    ls = np.array(["F", "O"])
    for start in range(0, n_rows, chunk):
        n = min(chunk, n_rows - start)
        cl.copy_from("lineitem", columns={
            "l_orderkey": rng.integers(0, n_rows // 4, n),
            "l_quantity": (rng.integers(100, 5100, n) / 100.0),
            "l_extendedprice": (rng.integers(90_000, 10_500_000, n) / 100.0),
            "l_discount": (rng.integers(0, 11, n) / 100.0),
            "l_tax": (rng.integers(0, 9, n) / 100.0),
            "l_returnflag": rf[rng.integers(0, 3, n)].tolist(),
            "l_linestatus": ls[rng.integers(0, 2, n)].tolist(),
            "l_shipdate": (rng.integers(0, 2526, n) + 8036).astype(np.int32),
        })


def _emit_last_good_or_die(note: str) -> None:
    """Device unavailable: fall back to the persisted last-good result
    (clearly labeled stale) so the driver always gets a parseable line.
    With no last-good either, re-exec ourselves on the CPU backend and
    emit that measurement honestly labeled platform=cpu — a lower bound,
    never passed off as a TPU number."""
    if os.path.exists(LAST_GOOD):
        with open(LAST_GOOD) as fh:
            rec = json.load(fh)
        rec["stale"] = True
        rec["stale_reason"] = note
        print(json.dumps(rec))
        sys.stdout.flush()
        os._exit(0)
    sys.stderr.write(f"bench: {note} and no last-good result exists; "
                     "measuring on the cpu backend as a labeled lower "
                     "bound\n")
    sys.stderr.flush()
    # the fallback child is a Q1 lower bound only: the join ingest or a
    # size sweep could blow the timeout that the plain run fits in
    env = dict(os.environ, BENCH_PLATFORM="cpu", BENCH_JOIN="0",
               BENCH_SWEEP="0")
    try:
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             capture_output=True, text=True, env=env,
                             timeout=1200)
        line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
        rec = json.loads(line)
        rec["platform"] = "cpu"
        rec["note"] = f"{note}; cpu-backend lower bound, NOT a TPU number"
        print(json.dumps(rec))
        sys.stdout.flush()
        os._exit(0)
    except Exception as e:
        sys.stderr.write(f"bench: cpu fallback failed too: {e}\n")
        sys.stderr.flush()
        os._exit(3)


def _probe_device(timeout_s: float) -> bool:
    """Touch the device from a throwaway subprocess first: the axon TPU
    tunnel can wedge indefinitely during init, and a wedged probe child
    is expendable while a wedged bench process is not."""
    pin = (f"jax.config.update('jax_platforms', {PLATFORM!r}); "
           if PLATFORM else "")
    code = (f"import jax; {pin}d = jax.devices(); "
            "print('DEVICES', len(d), d[0].platform)")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False
    return out.returncode == 0 and "DEVICES" in out.stdout


def _arm_watchdog(seconds: float) -> None:
    """Backup guard: if device init wedges in-process despite the probe,
    emit the last-good result instead of hanging forever."""
    import threading

    def boom():
        sys.stderr.write(
            f"bench: device initialization exceeded {seconds}s "
            "(TPU tunnel wedged?)\n")
        sys.stderr.flush()
        _emit_last_good_or_die("in-process device init watchdog fired")
    t = threading.Timer(seconds, boom)
    t.daemon = True
    t.start()
    # disarm once the device responds
    import jax
    jax.devices()
    t.cancel()


def main() -> None:
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "90"))
    # a pinned platform (cpu smoke/fallback) involves no tunnel: skip the
    # probe — it would also recurse through the fallback re-exec
    if not PLATFORM and not _probe_device(probe_timeout):
        retry_delay = float(os.environ.get("BENCH_RETRY_DELAY_S", "120"))
        sys.stderr.write("bench: device probe timed out; retrying once "
                         f"after {retry_delay}s\n")
        sys.stderr.flush()
        time.sleep(retry_delay)
        if not _probe_device(probe_timeout):
            _emit_last_good_or_die("TPU tunnel wedged (probe timed out twice)")

    import jax
    if PLATFORM:
        jax.config.update("jax_platforms", PLATFORM)
    import citus_tpu as ct
    _arm_watchdog(300.0)
    data_dir = os.path.join(_HERE, ".bench_data")
    cl = ct.Cluster(data_dir)
    ensure_data(cl)

    def timed(sql, warm=1, reps=3):
        for _ in range(warm):
            cl.execute(sql)
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            cl.execute(sql)
            ts.append(time.perf_counter() - t0)
        return min(ts)

    best = timed(Q1)
    rows_per_sec = N_ROWS / best
    q6_rate = N_ROWS / timed(Q6)
    extra = {
        "q6_rows_per_sec": round(q6_rate, 1),
        "q6_vs_baseline": round(q6_rate / BASELINE_ROWS_PER_SEC, 3),
    }
    # roofline (VERDICT weak #4): bytes the warm Q1 scan pushes per
    # second vs the chip's HBM peak — rows/s cannot say how close the
    # engine runs to what the memory system permits
    bytes_per_sec = rows_per_sec * Q1_BYTES_PER_ROW
    peak = _hbm_peak_for(jax.devices()[0].device_kind)
    extra["q1_bytes_scanned_per_sec"] = round(bytes_per_sec, 1)
    extra["device_kind"] = jax.devices()[0].device_kind
    if peak is not None:
        extra["hbm_peak_bytes_per_sec"] = peak
        extra["q1_fraction_of_hbm_peak"] = round(bytes_per_sec / peak, 4)
    if os.environ.get("BENCH_PIPELINE", "1") != "0":
        # host-decode/device-compute overlap on an uncached Q1 scan
        # (executor/pipeline.py): busy fractions near 1.0 on both
        # halves mean the read-ahead queue is hiding decode behind
        # device rounds; a low device fraction = host-bound pipeline
        from citus_tpu.executor.device_cache import GLOBAL_CACHE
        GLOBAL_CACHE.clear()
        t0 = time.perf_counter()
        r = cl.execute(Q1)
        wall = time.perf_counter() - t0
        pl = (r.explain or {}).get("pipeline") or {}
        if pl and wall > 0:
            extra["pipeline"] = {
                "host_decode_ms": pl.get("host_decode_ms", 0),
                "device_ms": pl.get("device_ms", 0),
                "h2d_bytes": pl.get("h2d_bytes", 0),
                "host_stalls": pl.get("host_stalls", 0),
                "device_stalls": pl.get("device_stalls", 0),
                "host_decode_busy_fraction": round(
                    pl.get("host_decode_ms", 0) / (wall * 1000), 4),
                "device_busy_fraction": round(
                    pl.get("device_ms", 0) / (wall * 1000), 4),
                # lower bound on overlapped work: both halves cannot
                # sum past the wall unless they ran concurrently
                "overlap_fraction": round(max(
                    0.0, (pl.get("host_decode_ms", 0)
                          + pl.get("device_ms", 0)) / (wall * 1000) - 1.0),
                    4),
            }
    if os.environ.get("BENCH_CONCURRENCY", "1") != "0":
        bench_concurrency(cl, extra)
    if os.environ.get("BENCH_PLAN_CACHE", "1") != "0":
        bench_plan_cache(cl, extra)
    if os.environ.get("BENCH_MEGABATCH", "1") != "0":
        bench_megabatch(cl, extra)
    if os.environ.get("BENCH_SCAN_FUSE", "1") != "0":
        bench_scan_fuse(cl, extra)
    if os.environ.get("BENCH_HASH_AGG", "1") != "0":
        bench_hash_agg(cl, extra)
    if os.environ.get("BENCH_TRACE", "1") != "0":
        bench_trace_overhead(cl, extra)
    if os.environ.get("BENCH_RECORDER", "1") != "0":
        bench_recorder_overhead(cl, extra)
    if os.environ.get("BENCH_WAIT", "1") != "0":
        bench_wait_overhead(cl, extra)
    if os.environ.get("BENCH_SANITIZE", "0") == "1":
        bench_sanitize_overhead(extra)
    if os.environ.get("BENCH_FANOUT", "1") != "0":
        bench_stat_fanout(extra)
    if os.environ.get("BENCH_WIRE", "1") != "0":
        bench_wire(extra)
    if os.environ.get("BENCH_WORKLOAD", "1") != "0":
        bench_workload(extra)
    if os.environ.get("BENCH_MULTICOORD", "1") != "0":
        bench_multi_coordinator(extra)
    if os.environ.get("BENCH_REBALANCE", "1") != "0":
        bench_rebalance(extra)
    if os.environ.get("BENCH_AUTOPILOT", "1") != "0":
        bench_autopilot(extra)
    if os.environ.get("BENCH_ROLLUP", "1") != "0":
        bench_rollup(extra)
    if os.environ.get("BENCH_JOIN", "1") != "0":
        n_orders = N_ROWS // 4
        ensure_join_data(cl, n_orders)
        join_rate = (N_ROWS + n_orders) / timed(QJOIN, reps=2)
        extra["repartition_join_rows_per_sec"] = round(join_rate, 1)
        extra["join_vs_repartition_baseline"] = round(
            join_rate / JOIN_BASELINE_ROWS_PER_SEC, 3)
    if os.environ.get("BENCH_SWEEP") == "1":
        # throughput-vs-size curve past the HBM batch cache: the
        # streaming pipeline should degrade smoothly, not collapse
        sweep = {str(N_ROWS): round(rows_per_sec, 1)}
        for mult in (2, 4):
            n_sweep = N_ROWS * mult
            ensure_data(cl, n_sweep)
            sweep[str(n_sweep)] = round(n_sweep / timed(Q1), 1)
        ensure_data(cl, N_ROWS)  # restore the standard scale
        extra["sweep_rows_per_sec_by_table_rows"] = sweep
    rec = {
        "metric": "tpch_q1_rows_scanned_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
        "extra": extra,
    }
    # persist last-good only for real-device runs: a CPU smoke run must
    # never become the stale fallback for a TPU bench
    if not PLATFORM:
        persisted = dict(rec, measured_at=time.strftime("%Y-%m-%dT%H:%M:%S"),
                         platform=jax.devices()[0].platform)
        with open(LAST_GOOD + ".tmp", "w") as fh:
            json.dump(persisted, fh)
        os.replace(LAST_GOOD + ".tmp", LAST_GOOD)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
