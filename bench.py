#!/usr/bin/env python
"""Benchmark: TPC-H Q1 rows scanned/sec/chip on columnar lineitem.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): the reference's columnar scan + GROUP BY SUM runs
75 M rows in 16 s on its microbench box = 4.6875 M rows/s.  vs_baseline
is our warm Q1 rows/s divided by that.

Data persists in .bench_data/ across runs (ingest is skipped when the
table already exists at the right scale).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import citus_tpu as ct  # noqa: E402

BASELINE_ROWS_PER_SEC = 75_000_000 / 16.0
N_ROWS = 6_000_000  # ~TPC-H SF1 lineitem
SHARDS = 8

Q1 = """SELECT l_returnflag, l_linestatus,
  sum(l_quantity) AS sum_qty,
  sum(l_extendedprice) AS sum_base_price,
  sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
  sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
  avg(l_quantity) AS avg_qty, avg(l_extendedprice) AS avg_price,
  avg(l_discount) AS avg_disc, count(*) AS count_order
FROM lineitem WHERE l_shipdate <= '1998-09-02'
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus"""


def ensure_data(cl: "ct.Cluster") -> None:
    if cl.catalog.has_table("lineitem"):
        from citus_tpu.catalog.stats import table_row_count
        if table_row_count(cl.catalog, cl.catalog.table("lineitem")) == N_ROWS:
            return
        cl.drop_table("lineitem")
    cl.execute("""CREATE TABLE lineitem (
        l_orderkey bigint NOT NULL, l_quantity decimal(12,2),
        l_extendedprice decimal(12,2), l_discount decimal(12,2),
        l_tax decimal(12,2), l_returnflag text, l_linestatus text,
        l_shipdate date)""")
    cl.execute(f"SELECT create_distributed_table('lineitem', 'l_orderkey', {SHARDS})")
    rng = np.random.default_rng(7)
    chunk = 1_000_000
    rf = np.array(["A", "N", "R"])
    ls = np.array(["F", "O"])
    for start in range(0, N_ROWS, chunk):
        n = min(chunk, N_ROWS - start)
        cl.copy_from("lineitem", columns={
            "l_orderkey": rng.integers(0, N_ROWS // 4, n),
            "l_quantity": (rng.integers(100, 5100, n) / 100.0),
            "l_extendedprice": (rng.integers(90_000, 10_500_000, n) / 100.0),
            "l_discount": (rng.integers(0, 11, n) / 100.0),
            "l_tax": (rng.integers(0, 9, n) / 100.0),
            "l_returnflag": rf[rng.integers(0, 3, n)].tolist(),
            "l_linestatus": ls[rng.integers(0, 2, n)].tolist(),
            "l_shipdate": (rng.integers(0, 2526, n) + 8036).astype(np.int32),
        })


def _arm_watchdog(seconds: float) -> None:
    """The TPU tunnel in this environment can wedge indefinitely during
    device initialization; fail loudly instead of hanging forever."""
    import threading

    def boom():
        sys.stderr.write(
            f"bench: device initialization exceeded {seconds}s "
            "(TPU tunnel wedged?); aborting\n")
        sys.stderr.flush()
        os._exit(3)
    t = threading.Timer(seconds, boom)
    t.daemon = True
    t.start()
    # disarm once the device responds
    import jax
    jax.devices()
    t.cancel()


def main() -> None:
    _arm_watchdog(300.0)
    data_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_data")
    cl = ct.Cluster(data_dir)
    ensure_data(cl)

    cl.execute(Q1)  # warm: compile + populate HBM cache
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        cl.execute(Q1)
        times.append(time.perf_counter() - t0)
    best = min(times)
    rows_per_sec = N_ROWS / best
    print(json.dumps({
        "metric": "tpch_q1_rows_scanned_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
