"""Catalog-persisted tenant control plane: the replicated write door.

Reference: Citus keeps tenant-facing control state in pg_dist_* catalogs
precisely so every MX node plans and admits identically; a GUC-only
quota would fork behavior per coordinator.  This module is the ONE
place allowed to write the catalog's tenant_quotas / priority_classes
sections (cituslint CONF01 confines Catalog.put_tenant_quota,
drop_tenant_quota and put_priority_class here): each write registers an
operation, runs through the 2PC commit_metadata_flip sequence — so
concurrent coordinators arbitrate through the metadata authority, and a
crash mid-write resolves by presumed abort — and then re-hydrates the
process-local registry from the committed document.

The registry (workload/registry.py) stays the hot-path read side;
hydration is what makes admission decisions identical on every
coordinator: same catalog document -> same registry rows -> same
weighted-stride tree -> same admit/shed/queue outcome.
"""

from __future__ import annotations

import uuid

from citus_tpu.workload.registry import GLOBAL_TENANTS

#: tenant names / class names this process mirrored from the catalog.
#: Hydration retires only rows it previously installed, so quotas
#: registered directly against the registry (tests, internal tenants
#: like the rollup refresh worker) survive catalog reloads.
_MIRRORED_TENANTS: set = set()
_MIRRORED_CLASSES: set = set()


def _flip(cl, kind: str, mutate) -> None:
    """One replicated catalog mutation through the PR 10 machinery:
    register the operation, bracket the commit with the decide/decided
    phase markers, retire the registry row.  cat.commit() publishes the
    change to attached coordinators via the on_commit hook."""
    from citus_tpu.operations.cleaner import (complete_operation,
                                              register_operation)
    from citus_tpu.transaction.branches import commit_metadata_flip
    cat = cl.catalog
    op_id = uuid.uuid4().int & ((1 << 62) - 1)
    register_operation(cat, op_id, kind=kind)
    ok = False
    try:
        commit_metadata_flip(cat, op_id, mutate)
        ok = True
    finally:
        complete_operation(cat, op_id, success=ok)


def replicated_set_quota(cl, tenant: str, *, weight: float = 0.0,
                         max_concurrency: int = 0,
                         rate_limit_qps: float = 0.0, queue_depth: int = 0,
                         priority_class: str = "") -> None:
    """citus_add_tenant_quota: a replicated catalog write followed by
    the local registry mirror (remote coordinators mirror when the
    sync engine or invalidation reload delivers the document)."""
    quota = {
        "weight": float(weight),
        "max_concurrency": int(max_concurrency),
        "rate_limit_qps": float(rate_limit_qps),
        "queue_depth": int(queue_depth),
        "priority_class": str(priority_class),
    }
    cat = cl.catalog
    _flip(cl, "tenant_quota",
          lambda: cat.put_tenant_quota(str(tenant), quota))
    hydrate_tenant_registry(cat)


def replicated_remove_quota(cl, tenant: str) -> bool:
    """citus_remove_tenant_quota: tombstoned catalog drop (the merge
    never resurrects it from a concurrent coordinator's document)."""
    cat = cl.catalog
    found: dict = {}

    def mutate():
        found["hit"] = cat.drop_tenant_quota(str(tenant))

    _flip(cl, "tenant_quota_drop", mutate)
    GLOBAL_TENANTS.remove(str(tenant))
    _MIRRORED_TENANTS.discard(str(tenant))
    return bool(found.get("hit"))


def replicated_set_class(cl, name: str, weight: float) -> None:
    """citus_add_priority_class: register/update a class node in the
    scheduler's two-level stride tree, replicated like any quota."""
    cat = cl.catalog
    _flip(cl, "priority_class",
          lambda: cat.put_priority_class(str(name), float(weight)))
    hydrate_tenant_registry(cat)


def hydrate_tenant_registry(cat) -> int:
    """Mirror the catalog's replicated tenant sections into the
    process-local registry.  Every coordinator runs this at open, on
    catalog reload, and after each sync apply; it is idempotent and
    last-write-wins per tenant, so coordinators holding the same
    document always end with identical registries."""
    with cat._lock:
        quotas = {str(t): dict(q) for t, q in cat.tenant_quotas.items()}
        classes = {str(c): dict(v)
                   for c, v in cat.priority_classes.items()}
    for t, q in quotas.items():
        GLOBAL_TENANTS.set_quota(
            t,
            weight=float(q.get("weight", 0.0)),
            max_concurrency=int(q.get("max_concurrency", 0)),
            rate_limit_qps=float(q.get("rate_limit_qps", 0.0)),
            queue_depth=int(q.get("queue_depth", 0)),
            priority_class=str(q.get("priority_class", "")))
    for c, v in classes.items():
        GLOBAL_TENANTS.set_class(c, float(v.get("weight", 1.0)))
    # retire only rows we mirrored earlier whose catalog entry is gone
    for t in _MIRRORED_TENANTS - set(quotas):
        GLOBAL_TENANTS.remove(t)
    for c in _MIRRORED_CLASSES - set(classes):
        GLOBAL_TENANTS.remove_class(c)
    _MIRRORED_TENANTS.clear()
    _MIRRORED_TENANTS.update(quotas)
    _MIRRORED_CLASSES.clear()
    _MIRRORED_CLASSES.update(classes)
    return len(quotas)
